"""Speculative decoding drafters for the serving engine.

Leviathan et al.'s greedy speculative sampling (PAPERS.md): a cheap
drafter proposes ``k`` tokens per active slot, the target model scores
all ``k+1`` window positions in ONE captured verify call
(`models/llama.py _build_verify_step`), and the engine accepts the
longest draft prefix matching the target's argmax plus the one bonus
token the verify already paid for. Greedy verification makes the drafter
pure OPPORTUNITY: a wrong draft costs window slots, never correctness —
the emitted stream is bitwise the non-speculative engine's, whatever the
drafter proposes (tests/test_serving.py asserts it for both backends).

Two backends:

- ``NGramDrafter`` (default, ``PT_SERVE_DRAFTER=ngram``): prompt-lookup /
  n-gram continuation. Zero extra weights, O(1) host work per token: a
  per-request hash index maps every suffix n-gram (n <= max_n) of the
  request's prompt+output stream to its most recent earlier occurrence;
  propose() replays the continuation of the longest suffix match and
  falls back to repeating the last token (exactly right for the run-
  heavy streams greedy decoding produces). This is the zero-cost default
  because its proposals are free relative to one model call.

- ``DraftModelDrafter`` (``PT_SERVE_DRAFTER=model``): a shrunk-config
  target-family model with its own KV caches over the same batch-slot
  layout, driven through the same captured [B, 1] slot step the engine
  uses. Proposing k tokens costs k draft-model calls (batched over every
  active slot), so it pays off when the draft is much smaller than the
  target AND predicts it well (a trained pair); the n-gram backend is
  the right choice for the CPU proxy.

Draft-side cache coherence rides cursor arithmetic like the target's:
``observe()`` advances the draft cursor over positions whose K/V are
known true (catch-up feeds + accepted proposals); rejected positions are
simply re-fed next round. Nothing is ever repaired in place.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Drafter", "NGramDrafter", "DraftModelDrafter", "build_drafter"]


class Drafter:
    """Drafter contract (all host-side; called between decode steps only).

    The engine guarantees: ``on_join`` after a request's prefill (prompt
    and first output token already in ``req``), ``propose`` once per
    speculative decode step with every DECODING slot, ``observe`` with
    the number of tokens the verify accepted, ``on_evict`` when the slot
    is released. Proposals must be exactly ``k`` tokens per slot (the
    verify signature is fixed at [max_batch, k+1])."""

    kind = "none"

    def on_join(self, req) -> None:
        raise NotImplementedError

    def propose(self, active: Dict[int, object], k: int) -> Dict[int, List[int]]:
        """slot -> exactly-k proposed continuation tokens."""
        raise NotImplementedError

    def observe(self, req, n_accepted: int) -> None:
        """``n_accepted`` tokens were emitted for ``req`` this step (its
        ``output_tokens``/``cache_len`` are already advanced)."""
        raise NotImplementedError

    def on_evict(self, req) -> None:
        raise NotImplementedError

    def info(self) -> dict:
        return {"kind": self.kind}


class _NGramIndex:
    """Suffix n-gram -> most recent EARLIER occurrence, O(1) per token.

    ``maps[n][gram] = position just past the gram``; extending by one
    token updates max_n entries. ``prev`` keeps the previous position for
    the gram that is currently the stream suffix, so a suffix that only
    matches itself still finds its last earlier occurrence."""

    __slots__ = ("toks", "maps", "prev", "max_n")

    def __init__(self, toks, max_n: int):
        self.toks: List[int] = []
        self.maps = [None] + [dict() for _ in range(max_n)]
        self.prev = [None] + [dict() for _ in range(max_n)]
        self.max_n = max_n
        self.extend(toks)

    def extend(self, toks) -> None:
        for t in toks:
            self.toks.append(int(t))
            L = len(self.toks)
            for n in range(1, self.max_n + 1):
                if L < n:
                    break
                gram = tuple(self.toks[L - n:L])
                m = self.maps[n]
                old = m.get(gram)
                if old is not None:
                    self.prev[n][gram] = old
                m[gram] = L

    def propose(self, k: int) -> List[int]:
        toks = self.toks
        L = len(toks)
        for n in range(min(self.max_n, L), 0, -1):
            gram = tuple(toks[L - n:L])
            pos = self.maps[n].get(gram)
            if pos == L:                      # the suffix matched itself
                pos = self.prev[n].get(gram)
            if pos is None:
                continue
            cont = toks[pos:pos + k]
            if cont:
                while len(cont) < k:
                    cont.append(cont[-1])
                return cont
        return [toks[-1] if toks else 0] * k


class NGramDrafter(Drafter):
    """Prompt-lookup drafter: propose the continuation of the longest
    recent n-gram match inside the request's own prompt+output stream."""

    kind = "ngram"

    def __init__(self, max_n: int = 4):
        self.max_n = int(max_n)
        self._idx: Dict[int, _NGramIndex] = {}     # rid -> index
        self._lock = threading.Lock()
        # host-side lookups, but a "draft step" all the same: one propose()
        # per engine verify, so draft-vs-verify counts stay comparable
        self.draft_calls = 0

    def on_join(self, req) -> None:
        with self._lock:
            self._idx[req.rid] = _NGramIndex(
                list(req.prompt) + list(req.output_tokens), self.max_n)

    def propose(self, active, k):
        with self._lock:
            self.draft_calls += 1
            out = {}
            for s, r in active.items():
                idx = self._idx.get(r.rid)
                if idx is None:   # defensive: late registration costs O(len)
                    idx = _NGramIndex(
                        list(r.prompt) + list(r.output_tokens), self.max_n)
                    self._idx[r.rid] = idx
                out[s] = idx.propose(k)
            return out

    def observe(self, req, n_accepted: int) -> None:
        with self._lock:
            idx = self._idx.get(req.rid)
            if idx is not None and n_accepted > 0:
                idx.extend(req.output_tokens[-n_accepted:])

    def on_evict(self, req) -> None:
        with self._lock:
            self._idx.pop(req.rid, None)

    def info(self) -> dict:
        return {"kind": self.kind, "max_n": self.max_n,
                "draft_calls": self.draft_calls}


class DraftModelDrafter(Drafter):
    """Shrunk-config draft model over the engine's batch-slot layout.

    The draft keeps its own per-layer KV caches [max_batch, S_max, ...]
    and a per-request cursor ``draft_len`` = number of cache positions
    holding K/V of the TRUE token stream. Each propose() first catches
    the cursor up by feeding the true tokens the target accepted since
    last round (positions the draft mispredicted are simply overwritten),
    then rolls the draft forward k tokens greedily. All feeds are batched
    [B, 1] calls through the draft model's own captured slot step —
    propose() costs ``max(catch_up) + k - 1`` draft calls per engine
    step, amortized over every active slot."""

    kind = "model"

    def __init__(self, draft_model, max_batch: int, max_seq_len: int):
        import jax.numpy as jnp

        self.model = draft_model
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len)
        self._params = [p._value for p in draft_model.parameters()]
        self._caches = [(kc._value, vc._value) for kc, vc in
                        draft_model.init_kv_caches(self.max_batch,
                                                   self.max_seq_len)]
        self._cache_shape = self._caches[0][0].shape[1:]
        self._cache_dtype = self._caches[0][0].dtype
        step = draft_model.__dict__.get("_slot_step")
        if step is None:
            step = draft_model._build_slot_step()
            draft_model.__dict__["_slot_step"] = step
        self._step_fn = step
        self._jnp = jnp
        self._draft_len: Dict[int, int] = {}       # rid -> valid positions
        self._last_k = 0                           # window of the last propose
        self.draft_calls = 0

    # The engine's bucketed batch-1 prefill, replayed on the draft weights.
    # The bucket ladder here is DELIBERATELY independent of the engine's
    # configurable prefill buckets: padding is invariant for the draft
    # (masked positions never enter its cache), and a fixed ladder keeps
    # the drafter usable standalone — it only costs draft-side lowerings,
    # never tokens.
    def on_join(self, req) -> None:
        jnp = self._jnp
        from .engine import _write_slot
        plen = req.prompt.size
        bucket = 8
        while bucket < plen:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len)
        tok = np.zeros((1, bucket), np.int64)
        tok[0, :plen] = req.prompt
        pref = [(jnp.zeros((1,) + self._cache_shape, self._cache_dtype),
                 jnp.zeros((1,) + self._cache_shape, self._cache_dtype))
                for _ in self._caches]
        _, pref_out = self._step_fn(
            self._params, jnp.asarray(tok), pref,
            jnp.zeros((1,), jnp.int32),
            jnp.asarray([plen - 1], jnp.int32))
        self._caches = _write_slot(self._caches, pref_out,
                                   jnp.asarray(req.slot, jnp.int32))
        self._draft_len[req.rid] = plen
        self.draft_calls += 1

    def propose(self, active, k):
        jnp = self._jnp
        b = self.max_batch
        self._last_k = int(k)
        feeds: Dict[int, List[int]] = {}
        for s, r in active.items():
            stream = list(r.prompt) + list(r.output_tokens)
            dl = self._draft_len.get(r.rid, r.cache_len)
            # true tokens not yet in the draft cache, ending at the
            # pending token (stream[cache_len], not yet fed anywhere)
            feeds[s] = [int(t) for t in stream[dl:r.cache_len + 1]]
        rounds = max(len(f) for f in feeds.values()) + k - 1
        drafts: Dict[int, List[int]] = {s: [] for s in feeds}
        last = {s: feeds[s][0] for s in feeds}
        for r_i in range(rounds):
            tok = np.zeros((b, 1), np.int64)
            off = np.zeros((b,), np.int32)
            for s, r in active.items():
                f = feeds[s]
                fed = f[r_i] if r_i < len(f) else last[s]
                tok[s, 0] = fed
                dl = self._draft_len.get(r.rid, r.cache_len)
                off[s] = min(dl + r_i, self.max_seq_len - 1)
            nxt, self._caches = self._step_fn(
                self._params, jnp.asarray(tok), self._caches,
                jnp.asarray(off), np.zeros((b,), np.int32))
            self.draft_calls += 1
            out = np.asarray(nxt)
            for s in feeds:
                if r_i >= len(feeds[s]) - 1 and len(drafts[s]) < k:
                    drafts[s].append(int(out[s]))
                    last[s] = int(out[s])
        return drafts

    def observe(self, req, n_accepted: int) -> None:
        # Positions fed with true tokens + ACCEPTED-AND-FED proposals are
        # valid. propose() feeds proposals 1..k-1 only (the k-th is
        # generated last and never written), so on a full-window accept
        # (n_accepted == k+1) the valid prefix ends at old+k-1, not
        # old+k — without the k-1 cap the cursor would skip one stream
        # position forever and every later draft forward would attend a
        # never-written KV row. cache_len is already advanced, recompute.
        old = req.cache_len - n_accepted
        fed_drafts = min(max(0, n_accepted - 1), max(0, self._last_k - 1))
        self._draft_len[req.rid] = min(old + 1 + fed_drafts, req.cache_len,
                                       self.max_seq_len - 1)

    def on_evict(self, req) -> None:
        self._draft_len.pop(req.rid, None)

    def info(self) -> dict:
        cfg = self.model.config
        return {"kind": self.kind, "draft_calls": self.draft_calls,
                "draft_config": {"hidden": cfg.hidden_size,
                                 "layers": cfg.num_hidden_layers}}


def build_drafter(spec, max_batch: int, max_seq_len: int,
                  draft_model=None) -> Optional[Drafter]:
    """Resolve the engine's drafter knob: a Drafter instance passes
    through; "ngram" (default) needs nothing; "model" needs the
    ``draft_model`` the engine was given."""
    if spec is None or isinstance(spec, Drafter):
        return spec
    name = str(spec).lower()
    if name == "ngram":
        return NGramDrafter()
    if name == "model":
        if draft_model is None:
            raise ValueError(
                "drafter='model' needs a draft_model (a shrunk-config "
                "model of the target family) passed to the engine")
        return DraftModelDrafter(draft_model, max_batch, max_seq_len)
    raise ValueError(f"unknown drafter {spec!r} (ngram | model | a "
                     f"Drafter instance)")
