"""Request lifecycle for the continuous-batching serving engine.

A request moves QUEUED -> PREFILL -> DECODING -> {FINISHED, TIMED_OUT,
CANCELLED}. The scheduler owns every transition and performs them only
BETWEEN decode steps (the continuous-batching contract: a join or eviction
never retraces or perturbs in-flight slots). The per-request TTL rides
`utils.deadline.Deadline`; running out of it raises the typed
`RequestTimeout` from `result()` instead of wedging the caller.
"""
from __future__ import annotations

import enum
import itertools
import threading
import time
from typing import List, Optional

import numpy as np

from ...utils.deadline import Deadline, RequestTimeout

_rid_counter = itertools.count()


class RequestState(enum.Enum):
    QUEUED = 0      # waiting for a batch slot (pages may be reserved)
    PREFILL = 1     # admitted this step; prompt being prefilled
    DECODING = 2    # occupying a slot in the decode batch
    FINISHED = 3    # hit EOS or max_new_tokens; output complete
    TIMED_OUT = 4   # TTL expired (queued or mid-decode); output partial
    CANCELLED = 5   # user cancel; output partial


TERMINAL_STATES = (RequestState.FINISHED, RequestState.TIMED_OUT,
                   RequestState.CANCELLED)


class Request:
    """One generation request: prompt in, tokens out, typed error on TTL.

    Host-side bookkeeping only — all device state (KV cache slot contents)
    belongs to the engine. `token_times` records a perf_counter stamp per
    emitted token so the bench can report p50/p99 per-token latency.
    """

    def __init__(self, prompt_ids, max_new_tokens: int = 16,
                 ttl: Optional[float] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None):
        self.rid = next(_rid_counter)
        self.prompt = np.asarray(prompt_ids, dtype=np.int64).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("Request: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("Request: max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        # per-slot sampling (engine-validated: None means greedy); the
        # Generator is the request's own, seeded deterministically, so a
        # sampled stream is reproducible and independent of its neighbors
        self.temperature = temperature
        self.top_p = top_p
        self.seed = self.rid if seed is None else int(seed)
        self._rng = None
        self.deadline = Deadline(ttl, what=f"serving request {self.rid}")
        self.state = RequestState.QUEUED
        self.output_tokens: List[int] = []
        self.finish_reason: Optional[str] = None  # "eos"|"length"|"ttl"|...
        self.error: Optional[BaseException] = None
        # engine-owned placement (None until admitted)
        self.slot: Optional[int] = None
        self.pages: list = []           # KVPagePool pages reserved for us
        # True while the reservation covers the speculative verify-scratch
        # positions (scheduler.reserve_extra at alloc time); the ladder's
        # shed_reserve_extra() clears it when the scratch pages go back
        self.scratch_reserved = False
        # prefix sharing (engine-owned): refs taken on a committed page
        # chain at submit; shared_len prompt positions whose prefill we
        # skip. Chunked prefill state: prefill_pos = prompt positions
        # already computed into the scratch caches, scratch = the per-
        # request [1, S_pad] KV caches a multi-step prefill accumulates in
        self.shared_pages: list = []
        self.shared_kv: list = []       # per shared page: per-layer (k, v)
        self.shared_len = 0
        self.prefill_pos = 0
        self.scratch = None
        self.cache_len = 0              # valid KV positions in our slot
        self.next_token: Optional[int] = None   # sampled, not yet fed back
        self.submit_time = time.perf_counter()
        self.token_times: List[float] = []
        self._done = threading.Event()

    # ---- scheduler-side transitions ----
    def append_token(self, tok: int) -> bool:
        """Record one emitted token; returns True when the request is
        complete (EOS emitted or max_new_tokens reached)."""
        self.output_tokens.append(int(tok))
        self.token_times.append(time.perf_counter())
        if self.eos_token_id is not None and int(tok) == self.eos_token_id:
            self.finish_reason = "eos"
            return True
        if len(self.output_tokens) >= self.max_new_tokens:
            self.finish_reason = "length"
            return True
        return False

    def finish(self, state: RequestState, error: BaseException = None):
        self.state = state
        if error is not None:
            self.error = error
        if state is RequestState.TIMED_OUT and self.error is None:
            self.error = RequestTimeout(
                f"serving request {self.rid}", self.deadline.timeout,
                detail=f"{len(self.output_tokens)} token(s) generated")
        self._done.set()

    @property
    def is_sampling(self) -> bool:
        return self.temperature is not None

    @property
    def rng(self) -> np.random.Generator:
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        return self._rng

    # ---- caller-side API ----
    @property
    def done(self) -> bool:
        return self.state in TERMINAL_STATES

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self) -> np.ndarray:
        """prompt + generated tokens as one int64 array. Raises the typed
        error (RequestTimeout, ...) if the request did not finish cleanly."""
        if not self.done:
            raise RuntimeError(
                f"request {self.rid} still {self.state.name}; drive "
                f"engine.step() (or engine.run()) to completion first")
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.output_tokens, np.int64)])

    def __repr__(self):
        return (f"Request(rid={self.rid}, state={self.state.name}, "
                f"prompt={self.prompt.size}, out={len(self.output_tokens)}/"
                f"{self.max_new_tokens})")
