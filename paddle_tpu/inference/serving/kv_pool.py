"""Paged KV-cache pool: capacity accounting for the serving engine.

The physical layout stays the static-length dense cache `models/llama.py`
already decodes against — per layer `[B, S_max, H_kv, D]`, one row per
batch slot. What this pool manages is the CAPACITY of that layout: each
slot's S_max positions are divided into fixed-size pages, and a request
must hold enough pages for its whole lifetime (prompt + max_new_tokens)
before it may occupy a slot. That gives vLLM-style capacity-based
admission without a gather kernel: admission is all-or-nothing, so an
admitted request can never stall mid-decode waiting for memory, and the
no-preemption invariant keeps the decode path retrace-free.

Pages are ref-counted (retain/release): the substrate prefix sharing now
spends (`prefix.py`) — a borrower takes refs on a donor's prompt pages
via `share()`, which accepts only pages `commit()`ed by a COMPLETED
prefill (the typed `PageUncommitted` guards the fork-during-prefill
race). A page returns to the free list only when its last holder
releases it — and loses its committed mark there, so a recycled page is
never shareable before its new prefill commits. `info()` exposes the
counters the deadline tests assert on (an expired request's pages must
land back in `free_pages`).
"""
from __future__ import annotations

import threading
from typing import List


class PageUncommitted(RuntimeError):
    """Typed rejection of `share()` on a page whose KV rows are still being
    written (an in-flight bucketed or chunked prefill owns it). Only
    COMMITTED full pages may enter the prefix-sharing radix tree: a fork
    taken mid-prefill would hand the borrower rows the donor has not
    finished computing (the fork-during-prefill race)."""

    def __init__(self, page: "Page"):
        self.page = page
        super().__init__(
            f"page {page.pid} is not committed (an in-flight prefill is "
            f"still writing it) — only committed full pages are shareable")


class PoolExhausted(RuntimeError):
    """Admission failed: not enough free KV pages for the reservation.

    `permanent=True` means the reservation exceeds the pool's TOTAL
    capacity — no amount of waiting admits it (a sizing error, not
    backpressure), and the caller must not retry."""

    def __init__(self, need: int, free: int, total: int,
                 permanent: bool = False):
        self.need, self.free, self.total = need, free, total
        self.permanent = permanent
        tail = ("exceeds total capacity — the request can NEVER be "
                "admitted; resize the pool/engine"
                if permanent else
                "request stays queued until capacity returns")
        super().__init__(
            f"KV page pool exhausted: need {need} page(s), {free} free of "
            f"{total} total — {tail}")


class Page:
    """One fixed-size span of KV positions. Identity is the unit of
    accounting; the engine maps (slot, position) to pages implicitly
    through the dense layout."""

    __slots__ = ("pid", "refs", "committed")

    def __init__(self, pid: int):
        self.pid = pid
        self.refs = 0
        # a page is committed once the prefill that filled its KV rows has
        # completed; only then may share() hand it to another request
        self.committed = False

    def __repr__(self):
        return (f"Page({self.pid}, refs={self.refs}"
                f"{', committed' if self.committed else ''})")


class KVPagePool:
    """Free-list of `total_pages` pages of `page_size` tokens each."""

    def __init__(self, total_pages: int, page_size: int):
        if total_pages < 1 or page_size < 1:
            raise ValueError("KVPagePool: total_pages/page_size must be >= 1")
        self.total_pages = int(total_pages)
        self.page_size = int(page_size)
        self._free: List[Page] = [Page(i) for i in range(total_pages)]
        self._lock = threading.Lock()
        self._allocs = 0
        self._releases = 0
        self._shared = 0
        self._peak_active = 0

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold `n_tokens` KV positions."""
        return -(-max(int(n_tokens), 1) // self.page_size)

    def alloc(self, n: int) -> List[Page]:
        """Take `n` pages off the free list at refcount 1, or raise the
        typed PoolExhausted without taking any (all-or-nothing)."""
        with self._lock:
            if n > len(self._free):
                from ...observability import trace
                trace.event("pool.exhausted", need=n, free=len(self._free),
                            total=self.total_pages)
                raise PoolExhausted(n, len(self._free), self.total_pages)
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                p.refs = 1
            self._allocs += n
            active = self.total_pages - len(self._free)
            self._peak_active = max(self._peak_active, active)
            return pages

    def retain(self, pages: List[Page]):
        """Add a holder to already-allocated pages (prefix sharing)."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"retain of a free page: {p!r}")
                p.refs += 1

    def share(self, pages: List[Page]):
        """retain() restricted to COMMITTED pages — the prefix-sharing
        entry point. Raises the typed `PageUncommitted` (taking no refs)
        when any page is still being written by an in-flight prefill: a
        borrower must never fork onto half-written KV rows, so only pages
        `commit()`ed by a completed prefill are shareable. All-or-nothing,
        like alloc()."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"share of a free page: {p!r}")
                if not p.committed:
                    raise PageUncommitted(p)
            for p in pages:
                p.refs += 1
            self._shared += len(pages)

    def commit(self, pages: List[Page]):
        """Mark pages' KV rows durable (their prefill completed): from here
        on share() accepts them. Idempotent."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"commit of a free page: {p!r}")
                p.committed = True

    def release(self, pages: List[Page]):
        """Drop one holder; pages return to the free list at refcount 0
        (and lose their committed mark — the rows they accounted for are
        no longer anyone's)."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"double release: {p!r}")
                p.refs -= 1
                if p.refs == 0:
                    p.committed = False
                    self._free.append(p)
                    self._releases += 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def info(self) -> dict:
        """cache_info()-style introspection (asserted by the deadline and
        occupancy tests; surfaced in profiler.serving_summary())."""
        with self._lock:
            free = len(self._free)
            return {"total_pages": self.total_pages,
                    "page_size": self.page_size,
                    "free_pages": free,
                    "active_pages": self.total_pages - free,
                    "allocs": self._allocs,
                    "releases": self._releases,
                    "shared": self._shared,
                    "peak_active": self._peak_active}
