"""Paged KV-cache pool: capacity accounting for the serving engine.

The physical layout stays the static-length dense cache `models/llama.py`
already decodes against — per layer `[B, S_max, H_kv, D]`, one row per
batch slot. What this pool manages is the CAPACITY of that layout: each
slot's S_max positions are divided into fixed-size pages, and a request
must hold enough pages for its whole lifetime (prompt + max_new_tokens)
before it may occupy a slot. That gives vLLM-style capacity-based
admission without a gather kernel: admission is all-or-nothing, so an
admitted request can never stall mid-decode waiting for memory, and the
no-preemption invariant keeps the decode path retrace-free.

Pages are ref-counted (retain/release): the substrate for prefix sharing
(two requests pinning one prompt's pages) even though the v1 engine holds
every page at refcount 1. A page returns to the free list only when its
last holder releases it; `info()` exposes the counters the deadline tests
assert on (an expired request's pages must land back in `free_pages`).
"""
from __future__ import annotations

import threading
from typing import List


class PoolExhausted(RuntimeError):
    """Admission failed: not enough free KV pages for the reservation.

    `permanent=True` means the reservation exceeds the pool's TOTAL
    capacity — no amount of waiting admits it (a sizing error, not
    backpressure), and the caller must not retry."""

    def __init__(self, need: int, free: int, total: int,
                 permanent: bool = False):
        self.need, self.free, self.total = need, free, total
        self.permanent = permanent
        tail = ("exceeds total capacity — the request can NEVER be "
                "admitted; resize the pool/engine"
                if permanent else
                "request stays queued until capacity returns")
        super().__init__(
            f"KV page pool exhausted: need {need} page(s), {free} free of "
            f"{total} total — {tail}")


class Page:
    """One fixed-size span of KV positions. Identity is the unit of
    accounting; the engine maps (slot, position) to pages implicitly
    through the dense layout."""

    __slots__ = ("pid", "refs")

    def __init__(self, pid: int):
        self.pid = pid
        self.refs = 0

    def __repr__(self):
        return f"Page({self.pid}, refs={self.refs})"


class KVPagePool:
    """Free-list of `total_pages` pages of `page_size` tokens each."""

    def __init__(self, total_pages: int, page_size: int):
        if total_pages < 1 or page_size < 1:
            raise ValueError("KVPagePool: total_pages/page_size must be >= 1")
        self.total_pages = int(total_pages)
        self.page_size = int(page_size)
        self._free: List[Page] = [Page(i) for i in range(total_pages)]
        self._lock = threading.Lock()
        self._allocs = 0
        self._releases = 0
        self._peak_active = 0

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold `n_tokens` KV positions."""
        return -(-max(int(n_tokens), 1) // self.page_size)

    def alloc(self, n: int) -> List[Page]:
        """Take `n` pages off the free list at refcount 1, or raise the
        typed PoolExhausted without taking any (all-or-nothing)."""
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(n, len(self._free), self.total_pages)
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                p.refs = 1
            self._allocs += n
            active = self.total_pages - len(self._free)
            self._peak_active = max(self._peak_active, active)
            return pages

    def retain(self, pages: List[Page]):
        """Add a holder to already-allocated pages (prefix sharing)."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"retain of a free page: {p!r}")
                p.refs += 1

    def release(self, pages: List[Page]):
        """Drop one holder; pages return to the free list at refcount 0."""
        with self._lock:
            for p in pages:
                if p.refs < 1:
                    raise ValueError(f"double release: {p!r}")
                p.refs -= 1
                if p.refs == 0:
                    self._free.append(p)
                    self._releases += 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def info(self) -> dict:
        """cache_info()-style introspection (asserted by the deadline and
        occupancy tests; surfaced in profiler.serving_summary())."""
        with self._lock:
            free = len(self._free)
            return {"total_pages": self.total_pages,
                    "page_size": self.page_size,
                    "free_pages": free,
                    "active_pages": self.total_pages - free,
                    "allocs": self._allocs,
                    "releases": self._releases,
                    "peak_active": self._peak_active}
