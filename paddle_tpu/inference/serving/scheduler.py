"""Continuous-batching scheduler: join / evict BETWEEN decode steps.

The decode batch has `max_batch` slots. Between any two decode steps the
scheduler (1) evicts slots whose request completed (EOS / length) or ran
out of TTL, (2) expires queued requests past their deadline (typed
RequestTimeout, reserved pages returned to the pool), and (3) admits
queued requests into free slots — strict FIFO, gated on an all-or-nothing
KV-page reservation covering the request's whole lifetime, so an admitted
request never stalls mid-decode and nothing is ever preempted.

Joining is invisible to in-flight slots: every per-slot quantity (position
offset, ragged attention length, cache row) is independent across the
batch dimension, and the decode executable's signature is fixed at
[max_batch, 1] — a join changes the CONTENTS of an inactive slot, never
the avals, so no new lowering and bitwise-identical tokens for everyone
already decoding (tests/test_serving.py proves both).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import List, Tuple

from ...observability import trace
from .kv_pool import KVPagePool, PoolExhausted
from .request import Request, RequestState


class ContinuousBatchingScheduler:
    def __init__(self, pool: KVPagePool, max_batch: int,
                 reserve_extra_tokens: int = 0):
        self.pool = pool
        self.max_batch = int(max_batch)
        # per-request reservation padding: a speculative engine's verify
        # window may write up to spec_k positions past the accepted cursor,
        # so those scratch positions are reserved with the lifetime — the
        # all-or-nothing / no-preemption contract covers them too
        self.reserve_extra = int(reserve_extra_tokens)
        self._queue: deque[Request] = deque()
        self._running: dict[int, Request] = {}   # slot -> request
        self._free_slots = list(range(self.max_batch - 1, -1, -1))
        self._lock = threading.Lock()
        # optional reclaim hook (engine wires the prefix cache's evict):
        # called with the page shortfall when an alloc fails, returns pages
        # freed; a positive return earns exactly one alloc retry, so cached
        # prefixes yield to admission pressure instead of wedging the queue
        self.reclaim = None
        self.counters = {"submitted": 0, "admitted": 0, "finished": 0,
                         "timed_out": 0, "evicted": 0, "rejected": 0}

    def _pages_needed(self, req: Request) -> int:
        """Pages the request must OWN: its whole lifetime minus the shared
        prefix chain it already holds refs on (prefix sharing — the saved
        pages are exactly the prefill it skips)."""
        return self.pool.pages_for(
            req.prompt.size + req.max_new_tokens + self.reserve_extra) \
            - len(req.shared_pages)

    def _alloc(self, need: int):
        """pool.alloc with one reclaim-assisted retry (see `reclaim`)."""
        try:
            return self.pool.alloc(need)
        except PoolExhausted:
            if self.reclaim is None:
                raise
            if self.reclaim(need - self.pool.free_pages) <= 0:
                raise
            return self.pool.alloc(need)

    def _release_all(self, req: Request) -> None:
        """Give back everything the request holds: its own reservation AND
        its refs on the shared prefix chain (the tree's own refs keep the
        cached pages alive; a chain page a peer still decodes against
        never reaches the free list — refcount law)."""
        if req.pages:
            self.pool.release(req.pages)
            req.pages = []
        if req.shared_pages:
            self.pool.release(req.shared_pages)
            req.shared_pages = []

    # ---- intake ----
    def submit(self, req: Request):
        """Enqueue; reserve KV pages eagerly when capacity allows (the
        capacity-based admission control — a reservation made while queued
        is what an expiring queued request gives back).

        Reservations stay FIFO-prefix-ordered: a request reserves only if
        everything AHEAD of it in the queue is already reserved. Otherwise
        a small request behind a blocked head could pin the very pages the
        head is waiting for — with no TTL that wedges the queue forever
        (head can't alloc, reserver behind it can't join past strict FIFO)."""
        need = self._pages_needed(req)
        if need > self.pool.total_pages:
            with self._lock:
                self.counters["rejected"] += 1
            # never-fits: NOT queued — permanent sizing error, don't retry
            raise PoolExhausted(need, self.pool.free_pages,
                                self.pool.total_pages, permanent=True)
        with self._lock:
            self.counters["submitted"] += 1
            if all(r.pages for r in self._queue):
                try:
                    req.pages = self._alloc(need)
                    req.scratch_reserved = self.reserve_extra > 0
                except PoolExhausted:
                    pass  # stays queued unreserved; retried at join passes
            self._queue.append(req)

    # ---- the between-steps pass ----
    def schedule(self) -> Tuple[List[Request], List[Request]]:
        """-> (joined, evicted). Called by the engine before every decode
        step; all state transitions happen here, on the host, while the
        device batch is quiescent."""
        joined, evicted = [], []
        with self._lock:
            # 1. evict completed / expired running slots
            for slot in sorted(self._running):
                req = self._running[slot]
                if req.finish_reason in ("eos", "length"):
                    req.finish(RequestState.FINISHED)
                    self.counters["finished"] += 1
                elif req.deadline.expired:
                    req.finish_reason = "ttl"
                    req.finish(RequestState.TIMED_OUT)
                    self.counters["timed_out"] += 1
                else:
                    continue
                del self._running[slot]
                self._free_slots.append(slot)
                self._release_all(req)
                self.counters["evicted"] += 1
                evicted.append(req)
                trace.event("scheduler.evict", rid=req.rid, slot=slot,
                            reason=req.finish_reason)
            # 2. expire queued requests (typed rejection, pages returned)
            still = deque()
            for req in self._queue:
                if req.deadline.expired:
                    self._release_all(req)
                    req.finish_reason = "ttl"
                    req.finish(RequestState.TIMED_OUT)
                    self.counters["timed_out"] += 1
                    evicted.append(req)
                    trace.event("scheduler.expire_queued", rid=req.rid)
                else:
                    still.append(req)
            self._queue = still
            # 3. join — strict FIFO so a big head request cannot starve
            while self._free_slots and self._queue:
                head = self._queue[0]
                if not head.pages:
                    need = self._pages_needed(head)
                    try:
                        head.pages = self._alloc(need)
                        head.scratch_reserved = self.reserve_extra > 0
                    except PoolExhausted:
                        break
                self._queue.popleft()
                head.slot = self._free_slots.pop()
                head.state = RequestState.PREFILL
                self._running[head.slot] = head
                self.counters["admitted"] += 1
                joined.append(head)
                trace.event("scheduler.join", rid=head.rid, slot=head.slot,
                            pages=len(head.pages))
        return joined, evicted

    # ---- overload control (engine degradation ladder) ----
    def backlog_tokens(self) -> int:
        """Tokens still owed to everything queued or running — the
        numerator of the engine's projected-queue-wait estimate (divided
        by the measured token rate it yields seconds of backlog)."""
        with self._lock:
            queued = sum(r.max_new_tokens for r in self._queue)
            running = sum(max(0, r.max_new_tokens - len(r.output_tokens))
                          for r in self._running.values())
            return queued + running

    def shed_reserve_extra(self) -> int:
        """Degradation-ladder lever: stop reserving the per-request verify
        scratch for future allocations AND give back the whole pages it
        added to every reservation already held (running or queued). A
        request whose scratch went back is marked `scratch_reserved=False`
        so the engine never runs a speculative verify that would write
        past capacity it no longer owns. Returns pages freed."""
        freed = 0
        with self._lock:
            extra, self.reserve_extra = self.reserve_extra, 0
            if not extra:
                return 0
            for req in list(self._running.values()) + list(self._queue):
                if not req.pages or not req.scratch_reserved:
                    continue
                total = int(req.prompt.size) + req.max_new_tokens
                n = min(self.pool.pages_for(total + extra)
                        - self.pool.pages_for(total), len(req.pages))
                if n > 0:
                    # the TAIL of the reservation: prompt-front pages may
                    # be committed into the prefix tree, scratch never is
                    tail, req.pages = req.pages[-n:], req.pages[:-n]
                    self.pool.release(tail)
                    freed += n
                req.scratch_reserved = False
        return freed

    def restore_reserve_extra(self, extra: int) -> None:
        """Exit the ladder level: future reservations cover verify scratch
        again. Requests admitted while shed keep `scratch_reserved=False`
        (their speculative window has no capacity) until they finish."""
        with self._lock:
            self.reserve_extra = int(extra)

    # ---- views ----
    def running(self) -> dict:
        with self._lock:
            return dict(self._running)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active(self) -> int:
        with self._lock:
            return len(self._running)

    @property
    def idle(self) -> bool:
        with self._lock:
            return not self._running and not self._queue

    def info(self) -> dict:
        with self._lock:
            return {**self.counters, "active": len(self._running),
                    "queued": len(self._queue),
                    "free_slots": len(self._free_slots)}
