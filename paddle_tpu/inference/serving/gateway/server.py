"""ServingGateway: the engine behind a socket.

A threaded TCP listener (one accept loop + one handler thread per
connection, the _PyStoreServer shape) in front of ONE ServingEngine, plus
a driver thread that owns the engine's step loop — the engine's documented
single-driver contract holds, handler threads only submit() and wait().

The no-hang law extends to the wire:

- every connection's REQUEST read runs under a per-connection read
  deadline (``PT_GATEWAY_READ_TIMEOUT``, default 30s): an idle or
  trickling peer is closed, never parked forever;
- a request's TTL header becomes the engine's per-request `Deadline`, and
  the resulting typed `RequestTimeout` travels back as a 408 frame — the
  typed error ON the wire, re-raised by the client;
- a TTL-less request's wait is still bounded
  (``PT_GATEWAY_REQUEST_TIMEOUT``, default 300s -> 408);
- ``stop(drain=True)`` is the graceful path: the listener closes first
  (new connects refused), in-flight requests finish under
  ``PT_GATEWAY_DRAIN_TIMEOUT``, THEN the driver stops — a request the
  gateway accepted is never abandoned mid-decode by its own shutdown.

Chaos: ``gateway.accept`` (every accepted connection passes it),
``gateway.read`` (every request read passes it) and ``gateway.admit``
(every GENERATE passes it before engine.submit — the admission edge) are
registered fault sites; the no-hang matrix (tests/test_no_hang.py) arms
each with crash/delay/error/drop and proves the typed-RequestTimeout /
clean-retry bound end to end over a real socket.
"""
from __future__ import annotations

import socket
import threading
import time
import weakref
from typing import Optional

from ....observability import trace
from ....utils.deadline import Deadline, env_timeout
from ....distributed.chaos import faultpoint, register_fault
from ..request import Request
from . import protocol as proto

FP_ACCEPT = register_fault(
    "gateway.accept", "every accepted gateway connection passes here")
FP_READ = register_fault(
    "gateway.read", "every gateway request read passes here")
FP_ADMIT = register_fault(
    "gateway.admit", "every GENERATE passes here before engine.submit — "
    "the gateway-side admission edge (drain check + overload shed)")

_GATEWAYS: "weakref.WeakSet[ServingGateway]" = weakref.WeakSet()


class ServingGateway:
    """Serve one engine over TCP. ``port=0`` binds an ephemeral port
    (read it back from ``self.port``)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 read_timeout: Optional[float] = None, poll: float = 0.001):
        self.engine = engine
        self.read_timeout = (read_timeout if read_timeout is not None
                             else env_timeout("PT_GATEWAY_READ_TIMEOUT",
                                              30.0))
        self._poll = float(poll)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(128)
        self.host, self.port = host, self._sock.getsockname()[1]
        self._stopping = False
        self._draining = False
        self._lock = threading.Lock()
        self._conns: set = set()
        # accepted-but-not-yet-submitted/answered exchanges: drain() must
        # wait these out too — engine idleness alone can't see a handler
        # that read a frame but has not reached submit() yet
        self._inflight = 0
        self.counters = {"connections": 0, "requests": 0, "responses": 0,
                         "errors": 0, "read_timeouts": 0,
                         "protocol_errors": 0, "driver_errors": 0,
                         "metrics_scrapes": 0}
        self._status_counts: dict = {}
        self._driver = threading.Thread(target=self._drive, daemon=True,
                                        name=f"gateway-driver:{self.port}")
        self._driver.start()
        self._accept = threading.Thread(target=self._accept_loop, daemon=True,
                                        name=f"gateway-accept:{self.port}")
        self._accept.start()
        _GATEWAYS.add(self)

    # ------------------------------------------------------------------
    # the engine driver: ONE thread owns step()/run() (engine contract)
    # ------------------------------------------------------------------
    def _drive(self):
        while not self._stopping:
            try:
                if not self.engine.scheduler.idle:
                    self.engine.step()
                else:
                    time.sleep(self._poll)
            except Exception:  # noqa: BLE001 — the driver must survive:
                # an exception escaping step() (a bad lowering, a
                # transient backend failure) would otherwise silently
                # kill the ONLY thread stepping the engine and turn the
                # gateway into a 408 generator with no signal. Count it,
                # back off, keep driving — per-request failures still
                # reach their callers typed through result().
                with self._lock:
                    self.counters["driver_errors"] += 1
                time.sleep(max(self._poll, 0.05))

    # ------------------------------------------------------------------
    # accept + per-connection handlers
    # ------------------------------------------------------------------
    def _accept_loop(self):
        while not self._stopping:
            try:
                fd, _ = self._sock.accept()
            except OSError:
                return  # listener closed: shutdown/drain began
            try:
                # chaos: a fault armed here hits the connection BEFORE any
                # request is parsed — error/drop modes close it (the
                # client's reconnect-and-retry absorbs that, like a dead
                # load-balancer hop), delay stalls it into the client's
                # deadline, crash is the preempted-server case
                faultpoint(FP_ACCEPT)
            except Exception:  # noqa: BLE001 — injected fault: drop the conn
                try:
                    fd.close()
                except OSError:
                    pass
                continue
            with self._lock:
                if self._stopping:
                    fd.close()
                    continue
                self.counters["connections"] += 1
                self._conns.add(fd)
                t = threading.Thread(target=self._handle, args=(fd,),
                                     daemon=True)
            t.start()

    def _count_status(self, status: int):
        with self._lock:
            self._status_counts[status] = \
                self._status_counts.get(status, 0) + 1
            if status != proto.STATUS_OK:
                self.counters["errors"] += 1

    def _handle(self, fd):
        fd.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = bytearray()
        try:
            while not self._stopping:
                try:
                    # per-connection read deadline: the frame read is
                    # bounded chunk-by-chunk, so an idle keep-alive or a
                    # trickling peer is closed at the deadline. The chaos
                    # site sits on the read-to-serve edge — it fires once
                    # per REQUEST read, so an armed mode hits a live
                    # exchange deterministically, never an idle poll
                    dl = Deadline(self.read_timeout,
                                  what=f"gateway read :{self.port}")
                    head, headers, body = proto.read_frame(fd, dl, buf)
                    # an EVENT, not a span around the read: a span would
                    # record every idle keep-alive poll's full read-
                    # deadline wait and churn the bounded ring with idle
                    # records — the event marks only served reads (the
                    # chaos faultpoint below stamps its own record when
                    # armed, so an incident timeline still ends here)
                    trace.event("gateway.read", port=self.port)
                    faultpoint(FP_READ)
                except socket.timeout:
                    with self._lock:
                        self.counters["read_timeouts"] += 1
                    return
                except proto.ProtocolError:
                    with self._lock:
                        self.counters["protocol_errors"] += 1
                    return
                except ConnectionError:
                    return  # peer went away (or an injected drop): close
                except Exception as e:  # noqa: BLE001 — injected error mode:
                    # answer typed so the client re-raises it, keep serving
                    self._count_status(proto.STATUS_INTERNAL)
                    fd.sendall(proto.error_frame(proto.STATUS_INTERNAL, e))
                    continue
                # the read loop armed per-chunk timeouts from the read
                # deadline; the RESPONSE send must not inherit whatever
                # near-zero remainder a slow-but-valid request left behind
                # — but it stays bounded (a peer that stops READING would
                # otherwise park this handler in sendall forever once the
                # kernel buffer fills, pinning _inflight past every drain)
                fd.settimeout(env_timeout("PT_GATEWAY_SEND_TIMEOUT", 30.0))
                if head.startswith("PING"):
                    fd.sendall(proto.response_frame([], None))
                    continue
                if head.startswith("HEALTH"):
                    # answered from bookkeeping alone — never touches the
                    # generate path, so the LB poll works at any pressure.
                    # 200 even while draining: "reachable but not ready"
                    # is exactly what the ready/draining headers encode
                    eng = self.engine
                    self._count_status(proto.STATUS_OK)
                    fd.sendall(proto.health_response_frame(
                        ready=not (self._draining or self._stopping),
                        draining=self._draining or self._stopping,
                        pressure=getattr(eng, "pressure_level", 0),
                        queued=eng.scheduler.queue_depth,
                        active=eng.scheduler.active))
                    continue
                if head.startswith("METRICS"):
                    # drain-aware like GENERATE: a draining gateway answers
                    # the typed 503 (a scraper must never sample a half-
                    # stopped process as healthy), a live one renders the
                    # registry — engine counters included, so a wire scrape
                    # round-trips metrics_snapshot() exactly
                    if self._draining or self._stopping:
                        self._count_status(proto.STATUS_DRAINING)
                        fd.sendall(proto.error_frame(
                            proto.STATUS_DRAINING,
                            proto.GatewayDraining(
                                "gateway is draining for shutdown — "
                                "scrape elsewhere")))
                        continue
                    from ....observability import metrics as _metrics
                    self._count_status(proto.STATUS_OK)
                    with self._lock:
                        self.counters["metrics_scrapes"] += 1
                    fd.sendall(proto.text_response_frame(
                        _metrics.render_prometheus()))
                    continue
                if not head.startswith("GENERATE"):
                    self._count_status(proto.STATUS_BAD_REQUEST)
                    fd.sendall(proto.error_frame(
                        proto.STATUS_BAD_REQUEST,
                        proto.ProtocolError(f"unknown verb {head[:20]!r}")))
                    continue
                with self._lock:
                    self.counters["requests"] += 1
                    self._inflight += 1
                try:
                    # the SEND stays inside the inflight-covered window:
                    # drain() observing inflight == 0 must imply the reply
                    # already left, or stop()'s connection teardown could
                    # strand a finished request's bytes
                    try:
                        reply = self._serve_one(headers, body)
                    except ConnectionError:
                        # an injected drop at the admission edge simulates
                        # the wire dying mid-exchange: close the conn, the
                        # client's reconnect-and-retry absorbs it
                        return
                    except BaseException as e:  # noqa: BLE001 — typed onto the wire
                        status = proto.status_of(e)
                        self._count_status(status)
                        fd.sendall(proto.error_frame(
                            status, e, proto.error_headers(e)))
                        continue
                    self._count_status(proto.STATUS_OK)
                    with self._lock:
                        self.counters["responses"] += 1
                    fd.sendall(reply)
                finally:
                    with self._lock:
                        self._inflight -= 1
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.discard(fd)
            try:
                fd.close()
            except OSError:
                pass

    def _serve_one(self, headers, body) -> bytes:
        # chaos: the admission edge — a fault armed here hits the request
        # AFTER its frame parsed but BEFORE any engine state exists, the
        # exact window an overload shed occupies
        faultpoint(FP_ADMIT)
        if self._draining or self._stopping:
            raise proto.GatewayDraining(
                "gateway is draining for shutdown — resubmit elsewhere")
        prompt = proto.unpack_tokens(body)
        ttl = headers.get("ttl")
        temp = headers.get("temperature")
        top_p = headers.get("top-p")
        seed = headers.get("seed")
        eos = headers.get("eos")
        # the wire-side span of one request: the engine's request id lands
        # on it at submit, so a Chrome-trace timeline links this span to
        # every engine.prefill/decode/verify span that served the rid
        with trace.span("gateway.request", port=self.port) as sp:
            req: Request = self.engine.submit(
                prompt,
                max_new_tokens=int(headers.get("max-new-tokens", 16)),
                ttl=float(ttl) if ttl is not None else None,
                temperature=float(temp) if temp is not None else None,
                top_p=float(top_p) if top_p is not None else None,
                seed=int(seed) if seed is not None else None,
                eos_token_id=int(eos) if eos is not None else None)
            sp.set(rid=req.rid, prompt_len=int(prompt.size))
            # the wait is ALWAYS bounded: the request's own TTL (+grace for
            # the final decode step) when it has one, the gateway request
            # budget otherwise — a wedged driver surfaces as a typed 408,
            # not a parked handler thread
            budget = (float(ttl) + env_timeout("PT_GATEWAY_TTL_GRACE", 10.0)
                      if ttl is not None
                      else env_timeout("PT_GATEWAY_REQUEST_TIMEOUT", 300.0))
            if not req.wait(timeout=budget):
                raise proto.RequestTimeout(
                    f"gateway request {req.rid}", budget,
                    detail="engine did not finish the request within the "
                           "gateway budget")
            tokens = req.result()  # raises the typed error on TTL/cancel
            return proto.response_frame(tokens, req.finish_reason)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting (503 on new GENERATEs, listener closed) and wait
        for every in-flight request to finish. Returns True when the
        engine went idle within the budget."""
        self._draining = True
        try:
            self._sock.close()
        except OSError:
            pass
        budget = (timeout if timeout is not None
                  else env_timeout("PT_GATEWAY_DRAIN_TIMEOUT", 30.0))
        dl = Deadline(budget, what=f"gateway drain :{self.port}")
        while True:
            with self._lock:
                inflight = self._inflight
            # BOTH must clear: a handler that read a frame but has not
            # submitted yet is invisible to engine idleness, and a
            # submitted request is invisible to the in-flight counter
            # once its handler finished — together they cover the window
            if inflight == 0 and self.engine.scheduler.idle:
                return True
            if dl.expired:
                return False
            time.sleep(self._poll or 0.001)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Graceful by default: drain first, then stop the driver and
        close every connection. ``drain=False`` is the hard stop (in-
        flight peers see a reset)."""
        drained = self.drain(timeout) if drain else False
        self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for fd in conns:
            try:
                fd.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                fd.close()
            except OSError:
                pass
        self._driver.join(timeout=5.0)
        return drained

    def __del__(self):
        try:
            if not self._stopping:
                self.stop(drain=False)
        except Exception:  # noqa: BLE001 — interpreter-teardown best effort
            pass

    # ------------------------------------------------------------------
    def info(self) -> dict:
        with self._lock:
            return {"host": self.host, "port": self.port,
                    "draining": self._draining, "stopped": self._stopping,
                    "open_connections": len(self._conns),
                    "read_timeout": self.read_timeout,
                    **self.counters,
                    "status_counts": dict(self._status_counts)}


def gateway_info() -> list:
    """info() of every live gateway (profiler.gateway_summary's source)."""
    return [g.info() for g in list(_GATEWAYS)]
