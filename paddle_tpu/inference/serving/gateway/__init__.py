"""paddle_tpu.inference.serving.gateway — the engine behind a socket.

A deployable serving front-end riding the typed-deadline layer: the PTSG/1
line protocol (`protocol`), the threaded gateway server with graceful
drain and per-connection read deadlines (`server`), and the typed client
(`client`). Requests arrive with TTLs that map straight onto the engine's
per-request `Deadline` — the typed `RequestTimeout` travels the wire as a
408 frame and re-raises client-side. Overload sheds (`EngineOverloaded`)
travel as 429 frames with `retry-after-ms`; the client backs off, trips a
circuit breaker (`CircuitOpen`) on consecutive typed failures, and load
balancers poll the drain-aware HEALTH verb. See README "Serving gateway"
and "Overload control & graceful degradation".
"""
from .client import (CircuitOpen, GatewayClient,  # noqa: F401
                     GatewayConnectionError)
from .protocol import GatewayDraining, ProtocolError  # noqa: F401
from .server import ServingGateway, gateway_info  # noqa: F401

__all__ = ["CircuitOpen", "GatewayClient", "GatewayConnectionError",
           "GatewayDraining", "ProtocolError", "ServingGateway",
           "gateway_info"]
