"""PTSG/1 — the serving gateway's wire protocol.

HTTP/1.1-style line protocol over TCP, idiomatic with the TCPStore server
(`distributed/store.py`): ASCII header lines terminated by ``\\n``, a blank
line, then a fixed-length binary body of little-endian int64 token ids.
One request/response exchange per round; connections are keep-alive until
either side closes.

Request::

    PTSG/1 GENERATE            (or PING / METRICS, no headers/body)
    prompt-len: 12             body token count
    max-new-tokens: 16
    ttl: 2.5                   optional; maps onto the engine's per-request
                               Deadline -> typed RequestTimeout on the wire
    temperature: 0.8           optional sampling knobs
    top-p: 0.9
    seed: 7
    eos: 2
    <blank line>
    <prompt-len * 8 bytes>

Response::

    PTSG/1 200 OK
    tokens: 28                 body token count (prompt + generated)
    finish-reason: length
    <blank line>
    <tokens * 8 bytes>

Errors carry the TYPED class name and message instead of a body::

    PTSG/1 408 RequestTimeout
    error: deadline exceeded: serving request 3 ...
    <blank line>

The client re-raises the matching typed error (`RequestTimeout`,
`PoolExhausted`, `SamplingUnsupported`, ...) so a caller over the socket
sees exactly the exceptions the in-process engine raises. An overload
shed (`EngineOverloaded`) answers 429 with a ``retry-after-ms`` header
carrying the engine's computed backoff advice.

``HEALTH`` answers readiness + overload pressure from bookkeeping alone
(``ready`` / ``draining`` / ``pressure`` / ``queued`` / ``active``
headers, no body) — the load-balancer poll never touches the generate
path, so a saturated engine still answers it instantly.

``METRICS`` answers the process metrics registry as Prometheus text in a
``content-length``-sized UTF-8 body (drain-aware: a draining gateway
answers the typed 503 so a scraper never samples a half-stopped process
as healthy).
"""
from __future__ import annotations

import socket as _socket
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ....utils.deadline import (Deadline, EngineOverloaded, RequestTimeout,
                                recv_exact)

MAGIC = "PTSG/1"
MAX_LINE = 4096          # a header line longer than this is a protocol error
MAX_TOKENS = 1 << 20     # sanity cap on either direction's token payload
MAX_TEXT_BODY = 1 << 26  # content-length (METRICS text) cap — wider than
                         # the token cap so a large registry render never
                         # wedges the scrape behind a mis-labeled
                         # "connection" failure, still bounded vs a
                         # garbage peer

# status codes -> the typed error the client re-raises (the server sends
# type(exc).__name__ beside the code; the CLASS mapping is by code so an
# unknown subclass still surfaces as its base type)
STATUS_OK = 200
STATUS_BAD_REQUEST = 400      # malformed frame / invalid sampling ask
STATUS_TIMEOUT = 408          # typed RequestTimeout (TTL ran out)
STATUS_TOO_LARGE = 413        # sizing error: can never fit the engine
STATUS_EXHAUSTED = 429        # PoolExhausted (permanent=True)
STATUS_INTERNAL = 500         # anything else (incl. injected faults)
STATUS_DRAINING = 503         # gateway is draining: submit rejected


class ProtocolError(ConnectionError):
    """The peer sent bytes that are not a PTSG/1 frame — the stream is
    unparseable from here, so the connection must be closed."""


class GatewayDraining(RuntimeError):
    """Typed submit rejection while the gateway drains for shutdown."""


def pack_tokens(tokens) -> bytes:
    arr = np.asarray(tokens, np.int64).reshape(-1)
    return struct.pack(f"<{arr.size}q", *(int(t) for t in arr))


def unpack_tokens(payload: bytes) -> np.ndarray:
    if len(payload) % 8:
        raise ProtocolError("token payload is not a multiple of 8 bytes")
    return np.frombuffer(payload, "<i8").astype(np.int64)


def read_line(sock, dl: Optional[Deadline], buf: bytearray) -> str:
    """One ``\\n``-terminated ASCII line. `buf` carries bytes read past
    earlier lines (the reader owns one buffer per connection). The
    Deadline bounds the whole read, chunk by chunk, exactly like
    recv_exact — a peer trickling bytes cannot stretch it."""
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line = bytes(buf[:nl])
            del buf[:nl + 1]
            if len(line) > MAX_LINE:
                raise ProtocolError("header line too long")
            return line.decode("ascii", "replace").rstrip("\r")
        if len(buf) > MAX_LINE:
            raise ProtocolError("header line too long")
        if dl is not None:
            if dl.expired:
                raise _socket.timeout("read deadline exhausted")
            sock.settimeout(dl.remaining(floor=0.01))
        chunk = sock.recv(4096)  # staticcheck: ok[unbounded-blocking] — bounded by the Deadline when one is given (client + server request reads both pass one)
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk


def read_body(sock, dl: Optional[Deadline], buf: bytearray,
              nbytes: int) -> bytes:
    """The fixed-length binary body following the blank line."""
    take = min(len(buf), nbytes)
    head = bytes(buf[:take])
    del buf[:take]
    if take == nbytes:
        return head
    return head + recv_exact(sock, nbytes - take, dl,
                             what="peer closed mid-body")


def read_frame(sock, dl: Optional[Deadline],
               buf: bytearray) -> Tuple[str, Dict[str, str], bytes]:
    """-> (verb_or_status_line_tail, headers, body). The first line must
    start with the PTSG/1 magic; `tokens`/`prompt-len` headers size the
    body."""
    first = read_line(sock, dl, buf)
    if not first.startswith(MAGIC + " "):
        raise ProtocolError(f"not a {MAGIC} frame: {first[:60]!r}")
    head = first[len(MAGIC) + 1:]
    headers: Dict[str, str] = {}
    while True:
        line = read_line(sock, dl, buf)
        if not line:
            break
        key, sep, val = line.partition(":")
        if not sep:
            raise ProtocolError(f"malformed header line {line[:60]!r}")
        headers[key.strip().lower()] = val.strip()
    try:
        n = int(headers.get("tokens", headers.get("prompt-len", 0)) or 0)
        # a text body (the METRICS verb) is sized in raw bytes, not tokens
        nbytes = int(headers["content-length"]) \
            if "content-length" in headers else n * 8
    except ValueError as e:
        # a malformed size leaves the (unsized) body unconsumed — the
        # stream is desynced from here, so this MUST be the typed
        # connection-closing error, never an answer-and-continue
        raise ProtocolError(f"malformed token count: {e}") from e
    cap = MAX_TEXT_BODY if "content-length" in headers else MAX_TOKENS * 8
    if n < 0 or n > MAX_TOKENS or nbytes < 0 or nbytes > cap:
        raise ProtocolError(f"body size {nbytes} out of range")
    body = read_body(sock, dl, buf, nbytes) if nbytes else b""
    return head, headers, body


def request_frame(prompt, max_new_tokens: int, ttl: Optional[float],
                  temperature: Optional[float], top_p: Optional[float],
                  seed: Optional[int], eos: Optional[int]) -> bytes:
    arr = np.asarray(prompt, np.int64).reshape(-1)
    lines = [f"{MAGIC} GENERATE", f"prompt-len: {arr.size}",
             f"max-new-tokens: {int(max_new_tokens)}"]
    if ttl is not None:
        lines.append(f"ttl: {float(ttl)!r}")
    if temperature is not None:
        lines.append(f"temperature: {float(temperature)!r}")
    if top_p is not None:
        lines.append(f"top-p: {float(top_p)!r}")
    if seed is not None:
        lines.append(f"seed: {int(seed)}")
    if eos is not None:
        lines.append(f"eos: {int(eos)}")
    return ("\n".join(lines) + "\n\n").encode("ascii") + pack_tokens(arr)


def ping_frame() -> bytes:
    return f"{MAGIC} PING\n\n".encode("ascii")


def metrics_frame() -> bytes:
    """The METRICS verb: scrape the process metrics registry
    (observability/metrics.py Prometheus text) over the wire."""
    return f"{MAGIC} METRICS\n\n".encode("ascii")


def text_response_frame(text: str) -> bytes:
    """A 200 whose body is raw UTF-8 text sized by ``content-length``
    (the METRICS response — token framing stays untouched)."""
    payload = text.encode("utf-8")
    return (f"{MAGIC} {STATUS_OK} OK\ncontent-length: {len(payload)}\n\n"
            ).encode("ascii") + payload


def response_frame(tokens, finish_reason: Optional[str]) -> bytes:
    arr = np.asarray(tokens, np.int64).reshape(-1)
    lines = [f"{MAGIC} {STATUS_OK} OK", f"tokens: {arr.size}"]
    if finish_reason:
        lines.append(f"finish-reason: {finish_reason}")
    return ("\n".join(lines) + "\n\n").encode("ascii") + pack_tokens(arr)


def error_frame(status: int, exc: BaseException,
                extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    name = type(exc).__name__
    msg = str(exc).replace("\n", " ")[:1024]
    lines = [f"{MAGIC} {status} {name}", f"error: {msg}"]
    for key, val in (extra_headers or {}).items():
        lines.append(f"{key}: {val}")
    return ("\n".join(lines) + "\n\n").encode("ascii", "replace")


def error_headers(exc: BaseException) -> Dict[str, str]:
    """Typed-error headers that ride beside the status line: an overload
    shed's 429 carries the engine's computed ``retry-after-ms`` so the
    client's backoff is advised, not guessed."""
    if isinstance(exc, EngineOverloaded):
        return {"retry-after-ms": str(exc.retry_after_ms)}
    return {}


def health_frame() -> bytes:
    """The HEALTH verb: drain-aware readiness + current overload-ladder
    pressure, answered entirely from gateway/engine bookkeeping — a load
    balancer polling it never touches the generate path."""
    return f"{MAGIC} HEALTH\n\n".encode("ascii")


def health_response_frame(ready: bool, draining: bool, pressure: int,
                          queued: int, active: int) -> bytes:
    return (f"{MAGIC} {STATUS_OK} OK\n"
            f"ready: {int(bool(ready))}\n"
            f"draining: {int(bool(draining))}\n"
            f"pressure: {int(pressure)}\n"
            f"queued: {int(queued)}\n"
            f"active: {int(active)}\n\n").encode("ascii")


def status_of(exc: BaseException) -> int:
    """Map an engine-side exception to its wire status."""
    from ..kv_pool import PageUncommitted, PoolExhausted
    from ..engine import SamplingUnsupported
    if isinstance(exc, EngineOverloaded):
        # checked BEFORE RequestTimeout: both are DeadlineExceeded, but an
        # overload shed is retryable-later (429 + retry-after-ms) while a
        # TTL expiry is this request's terminal 408
        return STATUS_EXHAUSTED
    if isinstance(exc, RequestTimeout):
        return STATUS_TIMEOUT
    if isinstance(exc, GatewayDraining):
        return STATUS_DRAINING
    if isinstance(exc, PoolExhausted):
        return STATUS_EXHAUSTED
    if isinstance(exc, SamplingUnsupported):
        return STATUS_BAD_REQUEST
    if isinstance(exc, PageUncommitted):
        # refcount-law violation inside the engine — a server bug, not a
        # client mistake: surfaces as the typed 500
        return STATUS_INTERNAL
    if isinstance(exc, (ValueError, ProtocolError)):
        return STATUS_TOO_LARGE if "max_seq_len" in str(exc) \
            else STATUS_BAD_REQUEST
    return STATUS_INTERNAL
