"""GatewayClient: typed-deadline client for the PTSG/1 serving gateway.

One connection, one in-flight request at a time (a lock serializes —
clone clients for parallel streams, they are cheap). Connects with the
same jittered backoff as the store client, verifies the server with a
PING handshake, and carries every exchange under ONE `Deadline`:

- the response wait is bounded by the request's TTL plus a grace (the
  server enforces the TTL engine-side and answers a typed 408; the client
  budget only fences a wedged/partitioned server) or by an explicit
  ``timeout=`` — never unbounded;
- a timeout mid-exchange poisons the connection (the stream is desynced)
  and raises the typed `RequestTimeout` at once;
- a CONNECTION loss (peer reset, a dropped accept) reconnects and retries
  exactly once — generation is deterministic per request (greedy, or
  seeded sampling), so a replayed GENERATE returns the same tokens;
- error frames re-raise the engine's typed exception class
  (`RequestTimeout`, `PoolExhausted`, `SamplingUnsupported`, ...): the
  socket is invisible in the caller's except clauses.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from ....utils.deadline import Deadline, RequestTimeout, env_timeout
from . import protocol as proto


class GatewayConnectionError(ConnectionError):
    """Terminal client failure: the gateway connection died (or desynced)
    and reconnect-plus-retry did not recover it."""


def _typed_error(status: int, name: str, msg: str,
                 budget: Optional[float]) -> BaseException:
    if status == proto.STATUS_TIMEOUT:
        return RequestTimeout(f"gateway request ({name})", budget,
                              detail=msg)
    if status == proto.STATUS_EXHAUSTED:
        # reconstructed with the SERVER's message; the class attrs exist
        # (None = unknown over the wire) so in-process except clauses that
        # read them keep working, they just can't see the peer's numbers
        from ..kv_pool import PoolExhausted
        e = PoolExhausted.__new__(PoolExhausted)
        RuntimeError.__init__(e, msg)
        e.need = e.free = e.total = None
        e.permanent = True
        return e
    if status == proto.STATUS_DRAINING:
        return proto.GatewayDraining(msg)
    if status == proto.STATUS_BAD_REQUEST and name == "SamplingUnsupported":
        from ..engine import SamplingUnsupported
        e = SamplingUnsupported.__new__(SamplingUnsupported)
        NotImplementedError.__init__(e, msg)
        e.param = e.value = None
        return e
    if status in (proto.STATUS_BAD_REQUEST, proto.STATUS_TOO_LARGE):
        return ValueError(msg)
    if name == "FaultInjected":
        from ....distributed.chaos import FaultInjected
        return FaultInjected("gateway.remote")
    return RuntimeError(f"gateway error {status} {name}: {msg}")


class GatewayClient:
    def __init__(self, host: str, port: int,
                 connect_timeout: Optional[float] = None):
        self.host, self.port = host, int(port)
        self._connect_timeout = (connect_timeout if connect_timeout
                                 is not None else
                                 env_timeout("PT_GATEWAY_CONNECT_TIMEOUT",
                                             10.0))
        # REENTRANT: the mid-exchange reconnect path re-enters _exchange
        # through the ping handshake while the outer exchange holds it
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        self._connect(self._connect_timeout)

    # ------------------------------------------------------------------
    def _connect(self, timeout: float) -> None:
        from ....distributed.store import _backoff_delay
        deadline = time.monotonic() + timeout
        last: Optional[BaseException] = None
        attempt = 0
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=5)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                self._buf.clear()
                self.ping(timeout=5.0)
                return
            except (OSError, ConnectionError) as e:
                last = e
                self._teardown()
            time.sleep(min(_backoff_delay(attempt),
                           max(0.0, deadline - time.monotonic())))
            attempt += 1
        raise GatewayConnectionError(
            f"gateway: cannot connect {self.host}:{self.port}: {last}")

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf.clear()

    @property
    def alive(self) -> bool:
        return self._sock is not None

    def close(self) -> None:
        with self._lock:
            self._teardown()

    # ------------------------------------------------------------------
    def _exchange(self, frame: bytes, dl: Deadline, budget,
                  retry: bool = True):
        """Send one frame, read one frame, typed errors throughout. A
        connection loss reconnects and retries EXACTLY once (idempotent:
        generation is deterministic per request); a deadline expiry is
        typed immediately with the connection poisoned."""
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    # dead at entry (earlier exchange poisoned it):
                    # reconnect before anything is sent
                    self._connect(min(self._connect_timeout,
                                      dl.remaining(floor=0.1) or
                                      self._connect_timeout))
                try:
                    self._sock.settimeout(dl.remaining(floor=0.01))
                    self._sock.sendall(frame)
                    return proto.read_frame(self._sock, dl, self._buf)
                except socket.timeout as e:
                    self._teardown()  # mid-message: stream desynced
                    raise RequestTimeout(
                        f"gateway {self.host}:{self.port}", budget,
                        detail="no response within the budget; connection "
                               "closed to prevent desync") from e
                except (ConnectionError, OSError) as e:
                    self._teardown()
                    if not retry or attempt:
                        raise GatewayConnectionError(
                            f"gateway connection lost: {e}") from e
                    # fall through: reconnect + single retry

    # ------------------------------------------------------------------
    def ping(self, timeout: float = 5.0) -> None:
        dl = Deadline(timeout, what="gateway ping")
        head, _, _ = self._exchange(proto.ping_frame(), dl, timeout,
                                    retry=False)
        if not head.startswith(str(proto.STATUS_OK)):
            raise GatewayConnectionError(f"gateway ping rejected: {head!r}")

    def metrics(self, timeout: float = 10.0) -> str:
        """Scrape the gateway process's metrics registry (the PTSG/1
        METRICS verb): returns the Prometheus text exactly as the server
        rendered it. Raises the typed GatewayDraining on a draining
        gateway (503 on the wire) — a scraper must see the drain, not a
        healthy-looking half-sample."""
        dl = Deadline(timeout, what=f"gateway metrics "
                                    f"{self.host}:{self.port}")
        head, headers, body = self._exchange(proto.metrics_frame(), dl,
                                             timeout)
        parts = head.split(None, 1)
        status = int(parts[0])
        if status != proto.STATUS_OK:
            raise _typed_error(status, parts[1] if len(parts) > 1 else "",
                               headers.get("error", head), timeout)
        return body.decode("utf-8")

    def generate(self, prompt_ids, max_new_tokens: int = 16,
                 ttl: Optional[float] = None,
                 timeout: Optional[float] = None,
                 temperature: Optional[float] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Round-trip one request; returns prompt+generated tokens exactly
        as the in-process `Request.result()` would (bitwise — the gateway
        adds transport, never math). Raises the engine's typed errors."""
        if ttl is not None:
            budget = float(ttl) + env_timeout("PT_GATEWAY_TTL_GRACE", 10.0)
        else:
            budget = env_timeout("PT_GATEWAY_CLIENT_TIMEOUT", 300.0)
        if timeout is not None:
            budget = float(timeout)
        dl = Deadline(budget, what=f"gateway generate "
                                   f"{self.host}:{self.port}")
        frame = proto.request_frame(prompt_ids, max_new_tokens, ttl,
                                    temperature, top_p, seed, eos_token_id)
        # retry-once is sound only when a replay provably regenerates the
        # SAME stream: greedy always, sampled only with an explicit seed
        # (the server defaults an omitted seed to the request id, which
        # differs per submission — and the orphaned original would keep
        # decoding, so an unseeded duplicate is a correctness bug twice)
        retryable = temperature is None or seed is not None
        head, headers, body = self._exchange(frame, dl, budget,
                                             retry=retryable)
        parts = head.split(None, 1)
        status = int(parts[0])
        name = parts[1] if len(parts) > 1 else ""
        if status != proto.STATUS_OK:
            raise _typed_error(status, name,
                               headers.get("error", head), budget)
        return proto.unpack_tokens(body)
