"""GatewayClient: typed-deadline client for the PTSG/1 serving gateway.

One connection, one in-flight request at a time (a lock serializes —
clone clients for parallel streams, they are cheap). Connects with the
same jittered backoff as the store client, verifies the server with a
PING handshake, and carries every exchange under ONE `Deadline`:

- the response wait is bounded by the request's TTL plus a grace (the
  server enforces the TTL engine-side and answers a typed 408; the client
  budget only fences a wedged/partitioned server) or by an explicit
  ``timeout=`` — never unbounded;
- a timeout mid-exchange poisons the connection (the stream is desynced)
  and raises the typed `RequestTimeout` at once;
- a CONNECTION loss (peer reset, a dropped accept) reconnects and retries
  exactly once — generation is deterministic per request (greedy, or
  seeded sampling), so a replayed GENERATE returns the same tokens;
- error frames re-raise the engine's typed exception class
  (`RequestTimeout`, `PoolExhausted`, `SamplingUnsupported`, ...): the
  socket is invisible in the caller's except clauses.

Self-protection (the client half of the overload story):

- a 429 `EngineOverloaded` frame carries the engine's ``retry-after-ms``
  advice; `generate` honors it with jittered bounded backoff (at most
  ``PT_GATEWAY_BREAKER_RETRIES`` re-submissions, never past the
  request's own deadline) instead of hammering a saturated server;
- after ``PT_GATEWAY_BREAKER_THRESHOLD`` CONSECUTIVE typed overloads /
  timeouts the circuit breaker opens: calls fail locally with the typed
  `CircuitOpen` (no wire traffic) for ``PT_GATEWAY_BREAKER_COOLDOWN``
  seconds, then ONE half-open probe is let through — success closes the
  breaker, another typed failure re-opens it for a fresh cooldown;
- `health()` is breaker-exempt (a load balancer must be able to poll a
  tripped backend) and never touches the generate path server-side.
"""
from __future__ import annotations

import random
import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from ....utils.deadline import (Deadline, EngineOverloaded, RequestTimeout,
                                env_int, env_timeout)
from . import protocol as proto


class GatewayConnectionError(ConnectionError):
    """Terminal client failure: the gateway connection died (or desynced)
    and reconnect-plus-retry did not recover it."""


class CircuitOpen(RuntimeError):
    """The client's circuit breaker is open: the last
    ``PT_GATEWAY_BREAKER_THRESHOLD`` exchanges all failed with typed
    overloads/timeouts, so calls fail fast LOCALLY (no wire traffic)
    until the cooldown elapses and a half-open probe succeeds. Carries
    ``retry_after_ms`` — the cooldown remainder — like the server-side
    429 it shields."""

    def __init__(self, host: str, port: int, fails: int,
                 retry_after_ms: int):
        self.retry_after_ms = int(retry_after_ms)
        self.fails = int(fails)
        super().__init__(
            f"gateway {host}:{port} circuit open after {fails} consecutive "
            f"typed overload/timeout failures — retry locally rejected for "
            f"{retry_after_ms}ms (half-open probe follows)")


def _typed_error(status: int, name: str, msg: str,
                 budget: Optional[float],
                 headers: Optional[Dict[str, str]] = None) -> BaseException:
    if status == proto.STATUS_EXHAUSTED and name == "EngineOverloaded":
        # discriminated from PoolExhausted (same 429) by the class name on
        # the status line; the retry-after-ms header rides into the attr
        # the backoff below reads
        try:
            retry_ms = int((headers or {}).get("retry-after-ms", "") or 0)
        except ValueError:
            retry_ms = 0
        return EngineOverloaded("gateway generate", budget, detail=msg,
                                retry_after_ms=retry_ms)
    if status == proto.STATUS_TIMEOUT:
        return RequestTimeout(f"gateway request ({name})", budget,
                              detail=msg)
    if status == proto.STATUS_EXHAUSTED:
        # reconstructed with the SERVER's message; the class attrs exist
        # (None = unknown over the wire) so in-process except clauses that
        # read them keep working, they just can't see the peer's numbers
        from ..kv_pool import PoolExhausted
        e = PoolExhausted.__new__(PoolExhausted)
        RuntimeError.__init__(e, msg)
        e.need = e.free = e.total = None
        e.permanent = True
        return e
    if status == proto.STATUS_DRAINING:
        return proto.GatewayDraining(msg)
    if status == proto.STATUS_BAD_REQUEST and name == "SamplingUnsupported":
        from ..engine import SamplingUnsupported
        e = SamplingUnsupported.__new__(SamplingUnsupported)
        NotImplementedError.__init__(e, msg)
        e.param = e.value = None
        return e
    if status in (proto.STATUS_BAD_REQUEST, proto.STATUS_TOO_LARGE):
        return ValueError(msg)
    if name == "FaultInjected":
        from ....distributed.chaos import FaultInjected
        return FaultInjected("gateway.remote")
    return RuntimeError(f"gateway error {status} {name}: {msg}")


class GatewayClient:
    def __init__(self, host: str, port: int,
                 connect_timeout: Optional[float] = None):
        self.host, self.port = host, int(port)
        self._connect_timeout = (connect_timeout if connect_timeout
                                 is not None else
                                 env_timeout("PT_GATEWAY_CONNECT_TIMEOUT",
                                             10.0))
        # REENTRANT: the mid-exchange reconnect path re-enters _exchange
        # through the ping handshake while the outer exchange holds it
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        # circuit breaker (see module docstring): consecutive typed
        # overload/timeout failures trip it; _breaker_open_until != 0
        # means tripped — before it: fail fast; past it: half-open probe
        self._breaker_threshold = env_int("PT_GATEWAY_BREAKER_THRESHOLD", 5)
        self._breaker_cooldown = env_timeout("PT_GATEWAY_BREAKER_COOLDOWN",
                                             1.0)
        self._breaker_fails = 0
        self._breaker_open_until = 0.0
        self._connect(self._connect_timeout)

    # ------------------------------------------------------------------
    # circuit breaker
    # ------------------------------------------------------------------
    def _breaker_gate(self) -> None:
        """Raise the typed CircuitOpen while tripped and cooling; past the
        cooldown the call proceeds as the ONE half-open probe (a failure
        re-trips for a fresh cooldown, a success closes)."""
        with self._lock:
            remaining = self._breaker_open_until - time.monotonic()
            if remaining > 0:
                raise CircuitOpen(self.host, self.port, self._breaker_fails,
                                  max(1, int(remaining * 1000)))

    def _breaker_record(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self._breaker_fails = 0
                self._breaker_open_until = 0.0
                return
            self._breaker_fails += 1
            half_open_probe_failed = self._breaker_open_until != 0.0
            if half_open_probe_failed \
                    or self._breaker_fails >= self._breaker_threshold:
                self._breaker_open_until = \
                    time.monotonic() + self._breaker_cooldown

    @property
    def breaker_open(self) -> bool:
        with self._lock:
            return time.monotonic() < self._breaker_open_until

    # ------------------------------------------------------------------
    def _connect(self, timeout: float) -> None:
        from ....distributed.store import _backoff_delay
        deadline = time.monotonic() + timeout
        last: Optional[BaseException] = None
        attempt = 0
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=5)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                self._buf.clear()
                self.ping(timeout=5.0)
                return
            except (OSError, ConnectionError) as e:
                last = e
                self._teardown()
            time.sleep(min(_backoff_delay(attempt),
                           max(0.0, deadline - time.monotonic())))
            attempt += 1
        raise GatewayConnectionError(
            f"gateway: cannot connect {self.host}:{self.port}: {last}")

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf.clear()

    @property
    def alive(self) -> bool:
        return self._sock is not None

    def close(self) -> None:
        with self._lock:
            self._teardown()

    # ------------------------------------------------------------------
    def _exchange(self, frame: bytes, dl: Deadline, budget,
                  retry: bool = True):
        """Send one frame, read one frame, typed errors throughout. A
        connection loss reconnects and retries EXACTLY once (idempotent:
        generation is deterministic per request); a deadline expiry is
        typed immediately with the connection poisoned."""
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    # dead at entry (earlier exchange poisoned it):
                    # reconnect before anything is sent
                    self._connect(min(self._connect_timeout,
                                      dl.remaining(floor=0.1) or
                                      self._connect_timeout))
                try:
                    self._sock.settimeout(dl.remaining(floor=0.01))
                    self._sock.sendall(frame)
                    return proto.read_frame(self._sock, dl, self._buf)
                except socket.timeout as e:
                    self._teardown()  # mid-message: stream desynced
                    raise RequestTimeout(
                        f"gateway {self.host}:{self.port}", budget,
                        detail="no response within the budget; connection "
                               "closed to prevent desync") from e
                except (ConnectionError, OSError) as e:
                    self._teardown()
                    if not retry or attempt:
                        raise GatewayConnectionError(
                            f"gateway connection lost: {e}") from e
                    # fall through: reconnect + single retry

    # ------------------------------------------------------------------
    def ping(self, timeout: float = 5.0) -> None:
        dl = Deadline(timeout, what="gateway ping")
        head, _, _ = self._exchange(proto.ping_frame(), dl, timeout,
                                    retry=False)
        if not head.startswith(str(proto.STATUS_OK)):
            raise GatewayConnectionError(f"gateway ping rejected: {head!r}")

    def metrics(self, timeout: float = 10.0) -> str:
        """Scrape the gateway process's metrics registry (the PTSG/1
        METRICS verb): returns the Prometheus text exactly as the server
        rendered it. Raises the typed GatewayDraining on a draining
        gateway (503 on the wire) — a scraper must see the drain, not a
        healthy-looking half-sample."""
        dl = Deadline(timeout, what=f"gateway metrics "
                                    f"{self.host}:{self.port}")
        head, headers, body = self._exchange(proto.metrics_frame(), dl,
                                             timeout)
        parts = head.split(None, 1)
        status = int(parts[0])
        if status != proto.STATUS_OK:
            raise _typed_error(status, parts[1] if len(parts) > 1 else "",
                               headers.get("error", head), timeout)
        return body.decode("utf-8")

    def health(self, timeout: float = 5.0) -> dict:
        """Poll the gateway's drain-aware HEALTH verb: readiness +
        current overload-ladder pressure, answered from bookkeeping alone
        (never touches the generate path). Breaker-exempt by design — a
        load balancer must be able to watch a tripped backend recover."""
        dl = Deadline(timeout, what=f"gateway health "
                                    f"{self.host}:{self.port}")
        head, headers, _ = self._exchange(proto.health_frame(), dl, timeout)
        parts = head.split(None, 1)
        status = int(parts[0])
        if status != proto.STATUS_OK:
            raise _typed_error(status, parts[1] if len(parts) > 1 else "",
                               headers.get("error", head), timeout, headers)

        def _i(key):
            try:
                return int(headers.get(key, "") or 0)
            except ValueError:
                return 0

        return {"ready": headers.get("ready") == "1",
                "draining": headers.get("draining") == "1",
                "pressure": _i("pressure"), "queued": _i("queued"),
                "active": _i("active")}

    def generate(self, prompt_ids, max_new_tokens: int = 16,
                 ttl: Optional[float] = None,
                 timeout: Optional[float] = None,
                 temperature: Optional[float] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 retries: Optional[int] = None) -> np.ndarray:
        """Round-trip one request; returns prompt+generated tokens exactly
        as the in-process `Request.result()` would (bitwise — the gateway
        adds transport, never math). Raises the engine's typed errors.

        A 429 `EngineOverloaded` answer is retried up to ``retries`` times
        (default ``PT_GATEWAY_BREAKER_RETRIES``, 2), each wait the frame's
        ``retry-after-ms`` advice plus up to 25% jitter, never past the
        request's own deadline; consecutive typed overloads/timeouts feed
        the circuit breaker, which fails fast with `CircuitOpen` once
        tripped (``retries=0`` disables the backoff, not the breaker)."""
        if ttl is not None:
            budget = float(ttl) + env_timeout("PT_GATEWAY_TTL_GRACE", 10.0)
        else:
            budget = env_timeout("PT_GATEWAY_CLIENT_TIMEOUT", 300.0)
        if timeout is not None:
            budget = float(timeout)
        dl = Deadline(budget, what=f"gateway generate "
                                   f"{self.host}:{self.port}")
        frame = proto.request_frame(prompt_ids, max_new_tokens, ttl,
                                    temperature, top_p, seed, eos_token_id)
        # retry-once is sound only when a replay provably regenerates the
        # SAME stream: greedy always, sampled only with an explicit seed
        # (the server defaults an omitted seed to the request id, which
        # differs per submission — and the orphaned original would keep
        # decoding, so an unseeded duplicate is a correctness bug twice)
        retryable = temperature is None or seed is not None
        max_retries = env_int("PT_GATEWAY_BREAKER_RETRIES", 2) \
            if retries is None else max(0, int(retries))
        attempt = 0
        while True:
            self._breaker_gate()
            try:
                head, headers, body = self._exchange(frame, dl, budget,
                                                     retry=retryable)
            except RequestTimeout:
                # socket-level expiry (wedged/partitioned server): a typed
                # timeout, so it feeds the breaker like a frame-level 408
                self._breaker_record(ok=False)
                raise
            parts = head.split(None, 1)
            status = int(parts[0])
            name = parts[1] if len(parts) > 1 else ""
            if status == proto.STATUS_OK:
                self._breaker_record(ok=True)
                return proto.unpack_tokens(body)
            err = _typed_error(status, name, headers.get("error", head),
                               budget, headers)
            if isinstance(err, (EngineOverloaded, RequestTimeout)):
                self._breaker_record(ok=False)
            if isinstance(err, EngineOverloaded) and attempt < max_retries:
                # the server's advice, jittered so a shed burst of clients
                # does not resubmit in lockstep; bounded by our own
                # deadline — waiting past it just converts 429 into 408
                wait = max(0.001, err.retry_after_ms / 1000.0) \
                    * (1.0 + 0.25 * random.random())
                remaining = dl.remaining()
                if (remaining is None or wait < remaining) \
                        and not self.breaker_open:
                    attempt += 1
                    time.sleep(wait)
                    continue
            raise err
