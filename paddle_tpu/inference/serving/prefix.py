"""Prefix sharing: a radix-tree index over committed KV pages.

At millions of users the shared-system-prompt case is the common case, and
prefilling the same prompt prefix once per request is the dominant wasted
compute (vLLM's prefix caching / SGLang's RadixAttention). The KVPagePool's
ref-counted pages were built as this substrate in PR 7; this module finally
uses them: after a request's prefill COMMITS, its prompt's full pages enter
a radix tree keyed by page-sized token chunks, each node holding the page
(the tree takes its own ref via `pool.share()` — only committed pages are
accepted, the typed `PageUncommitted` guards the fork-during-prefill race)
plus the page's host-side KV rows per layer.

A new request walks the tree with its prompt: every matched chunk is one
full page of prefill it skips — it takes refs on the shared page chain and
prefills only its O(suffix) tail through the chunked window step
(engine._advance_prefills). Copy-on-write at the fork point: the shared
chain is full pages only, so the partial last page (and everything past the
fork) is the only thing the borrower computes and owns privately — the
match is capped at `plen - 1` so every request prefills at least its final
token (the logits source of its first generated token).

Eviction is refcount-honest: a node is evictable only when it is a LEAF and
its page's refcount is exactly the tree's own ref (nobody is decoding
against it). `evict()` frees least-recently-shared leaves first and is
wired into the scheduler's reclaim hook, so admission pressure trims the
cache instead of wedging the queue. Tokens stay bitwise the unshared path's
(tests/test_serving_gateway.py proves it end to end).
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .kv_pool import KVPagePool, Page


class _Node:
    """One full page of a cached prompt prefix."""

    __slots__ = ("key", "page", "kv", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], page: Page, kv,
                 parent: Optional["_Node"]):
        self.key = key          # the page's token chunk (len == page_size)
        self.page = page        # pool page; the tree holds one ref on it
        self.kv = kv            # per layer: (k, v) numpy [page_size, Hkv, D]
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Radix tree over committed KV pages, shared by one engine's pool."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._nodes = 0
        self._lock = threading.Lock()
        self._clock = itertools.count(1)
        self.counters = {"lookups": 0, "hits": 0, "pages_shared": 0,
                         "pages_inserted": 0, "pages_evicted": 0}

    # ------------------------------------------------------------------
    def _chunks(self, prompt: np.ndarray, limit: int):
        """Page-sized token chunks of `prompt` wholly inside [0, limit)."""
        ps = self.page_size
        for p in range(0, limit - ps + 1, ps):
            yield tuple(int(t) for t in prompt[p:p + ps])

    def _walk(self, prompt: np.ndarray) -> List[_Node]:
        """The matched chain for `prompt` (caller holds the lock): whole
        committed pages only, capped at plen - 1 — the last token is
        always the borrower's to prefill (copy-on-write at the fork)."""
        nodes: List[_Node] = []
        level = self._root
        for key in self._chunks(prompt, int(prompt.size) - 1):
            node = level.get(key)
            if node is None:
                break
            nodes.append(node)
            level = node.children
        return nodes

    def share(self, prompt: np.ndarray):
        """Walk the tree and take one ref per matched page (pool.share —
        committed pages only, typed PageUncommitted otherwise; walk and
        ref-take share one lock hold, so a concurrent eviction can never
        leave the chain dangling). Returns (pages, kv_chain, shared_len);
        the caller owns the refs and must release them with the request's
        lifetime."""
        with self._lock:
            nodes = self._walk(prompt)
            self.counters["lookups"] += 1
            if not nodes:
                return [], [], 0
            self.counters["hits"] += 1
            pages = [n.page for n in nodes]
            self.pool.share(pages)  # all-or-nothing; typed on uncommitted
            tick = next(self._clock)
            for n in nodes:
                n.last_used = tick
            self.counters["pages_shared"] += len(pages)
            return pages, [n.kv for n in nodes], len(nodes) * self.page_size

    def insert(self, prompt: np.ndarray, shared_len: int,
               own_pages: List[Page], kv_of_page) -> int:
        """Commit a prefilled prompt's full pages into the tree. Chunks
        below `shared_len` (a page multiple) are the chain the request
        borrowed — they are already in the tree and stay the donor's.
        Chunk i at or past it is backed by ``own_pages[i - base]`` (the
        request's own pages covering [shared_len, ...) in order) and its
        host KV rows come from ``kv_of_page(i)``. Already-present chunks
        are kept (first writer wins — rows are bitwise-interchangeable by
        the sharing contract); each NEW node takes the tree's own ref via
        pool.share(), so the request releasing its pages later never frees
        a cached page. Returns the number of nodes inserted."""
        ps = self.page_size
        base = int(shared_len) // ps
        added = 0
        with self._lock:
            level = self._root
            parent = None
            for i, key in enumerate(self._chunks(prompt, int(prompt.size))):
                node = level.get(key)
                if node is None:
                    if i < base or i - base >= len(own_pages):
                        break  # borrowed chain evaporated / out of pages:
                        # nothing of ours to pin here — stop extending
                    page = own_pages[i - base]
                    self.pool.share([page])  # tree's ref; typed if uncommitted
                    node = _Node(key, page, kv_of_page(i), parent)
                    level[key] = node
                    self._nodes += 1
                    added += 1
                    self.counters["pages_inserted"] += 1
                node.last_used = next(self._clock)
                parent = node
                level = node.children
        return added

    def evict(self, need: int) -> int:
        """Free up to `need` pages by dropping least-recently-shared LEAF
        nodes whose page is held ONLY by the tree (refcount 1). Returns
        pages actually freed. Never touches a page a live request shares —
        eviction happens only when refcounts release. One tree scan per
        ROUND, evicting every eligible leaf oldest-first; a further round
        runs only when freeing leaves exposed their parents (so the work
        is O(nodes x depth) worst case, not O(nodes x need))."""
        freed = 0
        need = max(0, int(need))
        with self._lock:
            while freed < need:
                leaves = []
                stack = list(self._root.values())
                while stack:
                    n = stack.pop()
                    if n.children:
                        stack.extend(n.children.values())
                    elif n.page.refs == 1:
                        leaves.append(n)
                if not leaves:
                    break
                leaves.sort(key=lambda n: n.last_used)
                for victim in leaves[:need - freed]:
                    level = victim.parent.children \
                        if victim.parent is not None else self._root
                    level.pop(victim.key, None)
                    self._nodes -= 1
                    self.pool.release([victim.page])
                    self.counters["pages_evicted"] += 1
                    freed += 1
        return freed

    def clear(self) -> int:
        """Drop every tree-only page (engine shutdown); returns freed."""
        return self.evict(self._nodes)

    # ------------------------------------------------------------------
    def info(self) -> dict:
        with self._lock:
            held = self._nodes
            c = dict(self.counters)
        return {"nodes": held, "pages_held": held, **c}
