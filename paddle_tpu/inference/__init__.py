"""paddle_tpu.inference — deployment predictor.

Analog of paddle.inference (AnalysisPredictor,
paddle/fluid/inference/api/analysis_predictor.h:94): Config + create_predictor
over a jit.save'd artifact (.pdmodel = serialized StableHLO program,
.pdiparams = weights). The graph-pass pipeline of the reference is XLA's
compilation; the predictor pre-places weights on device, exposes the
zero-copy handle API (get_input_handle/copy_from_cpu/run/copy_to_cpu), and
clone() shares weights between predictors (multi-thread serving contract).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit import save_load as _sl


class Config:
    """Analog of paddle.inference.Config."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accept either the jit.save prefix or explicit file paths
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file
        self._device = "tpu"
        self._device_id = 0
        self._memory_pool_mb = 0
        self._enable_memory_optim = True
        self._switch_ir_optim = True

    # -- device selection (XLA owns placement; kept for API parity) --
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        self._device = "tpu"  # accelerator of this build
        self._device_id = device_id
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file

    def model_dir(self):
        return self.model_prefix

    def switch_ir_optim(self, on: bool = True):
        self._switch_ir_optim = on

    def enable_memory_optim(self, on: bool = True):
        self._enable_memory_optim = on

    def summary(self) -> str:
        return (f"Config(model={self.model_prefix!r}, device={self._device}:"
                f"{self._device_id})")


class _IOHandle:
    """Zero-copy tensor handle (analog of ZeroCopyTensor)."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = shape
        self._dtype = dtype
        self._value = None

    def reshape(self, shape):
        self._shape = list(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jax.numpy.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def share_external_data(self, tensor):
        self._value = tensor._value if isinstance(tensor, Tensor) else tensor

    def shape(self):
        v = self._value
        return list(v.shape) if v is not None else self._shape


class Predictor:
    def __init__(self, config: Config, _shared=None):
        self.config = config
        if _shared is not None:
            self._layer = _shared
        else:
            if config.model_prefix is None:
                raise ValueError("Config has no model path")
            self._layer = _sl.load(config.model_prefix)
        meta = getattr(self._layer, "_meta", {}) or {}
        self._meta = meta
        n_in = len(meta.get("input_shapes", [])) or 1
        self._input_names = list(meta.get("input_names", [])) or \
            [f"input_{i}" for i in range(n_in)]
        shapes = meta.get("input_shapes", [None] * n_in)
        dtypes = meta.get("input_dtypes", [None] * n_in)
        self._inputs = {n: _IOHandle(n, shape=s, dtype=d)
                        for n, s, d in zip(self._input_names, shapes, dtypes)}
        self._output_names: List[str] = list(meta.get("output_names", []))
        self._outputs = {}

    # -- handle API --
    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name: str) -> _IOHandle:
        return self._outputs[name]

    def _validate(self, vals):
        """Check count/dtype/shape against the recorded export signature
        (None dims are dynamic) — fail fast with the feed name, instead of a
        deep XLA error (VERDICT r1 weak #9)."""
        meta = self._meta
        if not meta.get("input_dtypes"):
            return
        if len(vals) != len(self._input_names):
            raise ValueError(
                f"Predictor.run(): model takes {len(self._input_names)} "
                f"input(s) {self._input_names}, got {len(vals)}")
        for n, v, dt, shp in zip(self._input_names, vals,
                                 meta["input_dtypes"], meta["input_shapes"]):
            arr = v._value
            if np.dtype(arr.dtype).name != dt:
                raise TypeError(
                    f"Predictor.run(): input {n!r} expects dtype {dt}, got "
                    f"{np.dtype(arr.dtype).name}")
            if len(arr.shape) != len(shp) or any(
                    e is not None and e != g for e, g in zip(shp, arr.shape)):
                raise ValueError(
                    f"Predictor.run(): input {n!r} expects shape {shp} "
                    f"(None = any), got {list(arr.shape)}")

    def run(self, inputs: Optional[list] = None):
        """Execute the program. With `inputs` (list of Tensors/arrays) returns
        outputs directly (paddle's newer API); otherwise uses the handles."""
        if inputs is not None:
            vals = [i if isinstance(i, Tensor) else Tensor(np.asarray(i))
                    for i in inputs]
        else:
            missing = [n for n in self._input_names
                       if self._inputs[n]._value is None]
            if missing:
                raise ValueError(
                    f"Predictor.run(): input handle(s) {missing} were never "
                    f"filled — call get_input_handle(name).copy_from_cpu(...) "
                    f"for each input first")
            vals = [Tensor(self._inputs[n]._value) for n in self._input_names]
        self._validate(vals)
        out = self._layer(*vals)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        self._output_names = self._meta.get(
            "output_names") or [f"output_{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._output_names, outs):
            h = _IOHandle(n)
            h.share_external_data(o)
            self._outputs[n] = h
        if inputs is not None:
            return outs
        return True

    def clone(self) -> "Predictor":
        """Second predictor sharing weights/program (multi-thread serving)."""
        return Predictor(self.config, _shared=self._layer)

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from . import serving  # noqa: E402  (continuous-batching engine subpackage)

__all__ = ["Config", "Predictor", "create_predictor", "serving"]


# ---- enums + version/introspection surface (capi parity:
# paddle/fluid/inference/api/paddle_inference_api.h) ----

class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3
    TPU = 4


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


_DTYPE_BYTES = {"float32": 4, "float16": 2, "bfloat16": 2, "int64": 8,
                "int32": 4, "int8": 1, "uint8": 1, "bool": 1}


def get_num_bytes_of_data_type(dtype) -> int:
    return _DTYPE_BYTES[str(dtype)]


def get_version() -> str:
    from .. import __version__
    return f"paddle_tpu inference {__version__} (StableHLO/PJRT)"


def _get_phi_kernel_name(op_name: str) -> str:
    """Kernel-name mapping hook: ops here ARE jax primitives — identity."""
    return op_name


def get_trt_compile_version():
    """TensorRT is N/A on TPU (XLA is the inference compiler)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Re-save a jit.saved model with low-precision weights (the reference's
    offline mixed-precision converter)."""
    import pickle

    import numpy as np
    prefix = model_file[:-8] if model_file.endswith(".pdmodel") else model_file
    out_prefix = mixed_model_file[:-8] \
        if mixed_model_file.endswith(".pdmodel") else mixed_model_file
    dt = {"float16": np.float16,
          PrecisionType.Half: np.float16}.get(mixed_precision, None)
    import shutil
    for ext in (".pdmodel", ".pdmeta", ".stablehlo"):
        try:
            shutil.copy(prefix + ext, out_prefix + ext)
        except FileNotFoundError:
            pass
    with open(prefix + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    if dt is not None:
        state = {k: (np.asarray(v).astype(dt)
                     if np.issubdtype(np.asarray(v).dtype, np.floating)
                     else v) for k, v in state.items()}
    else:  # bfloat16 via jax's ml_dtypes
        import ml_dtypes
        state = {k: (np.asarray(v).astype(ml_dtypes.bfloat16)
                     if np.issubdtype(np.asarray(v).dtype, np.floating)
                     else v) for k, v in state.items()}
    with open(out_prefix + ".pdiparams", "wb") as f:
        pickle.dump(state, f)


class PredictorPool:
    """Pool of predictor clones sharing one loaded program
    (paddle_infer::services::PredictorPool)."""

    def __init__(self, config, size=1):
        base = create_predictor(config)
        self._preds = [base] + [base.clone() for _ in range(int(size) - 1)]

    def retrieve(self, idx):
        return self._preds[idx]


class XpuConfig:
    """Accepted for config-surface parity (Kunlun XPU is N/A on TPU)."""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k, v)
