"""Microbenchmark: continuous batching vs sequential per-request generate(),
plus speculative decoding (spec-on vs spec-off) under --spec.

Default mode measures the serving engine (paddle_tpu/inference/serving)
against the baseline it replaces — one `model.generate()` call per
request, back to back — on the SAME mixed-length workload and the SAME
tiny llama config. CPU-runnable ("backend": "cpu-proxy", same convention
as bench.py) so the number stays measurable when the TPU probe reports
tpu-unavailable:

  sequential — for each request: prefill + per-token KV-cache decode at
               batch 1 (each token is one whole-step-captured executable
               call serving ONE sequence).
  continuous — the ServingEngine: same executables, but every decode step
               serves every active slot, with requests joining/leaving
               between steps as they arrive/finish.

--spec mode measures speculative decoding: the SAME engine and the SAME
workload with the n-gram drafter proposing PT_SERVE_BENCH_SPEC_K tokens
per slot against the engine with speculation off. The model ties its
lm head to the embedding (standard weight tying): a random UNTIED tiny
model emits streams with no local structure at all — nothing any drafter
could exploit — while the tied model produces the run/cycle-heavy
streams that stand in for a real LM's locally-predictable spans (the
regime prompt-lookup decoding targets). The acceptance rate is part of
the payload precisely because the speedup is a function of it.

Prints ONE JSON line per mode:
  {"metric": "serving_throughput_speedup_vs_sequential", "value": <x>, ...}
  {"metric": "serving_spec_speedup_vs_nonspec", "value": <x>,
   "acceptance_rate": ..., "tokens_per_verify": ..., ...}
(acceptance floors: 1.5x and 1.25x) and writes a BENCH_SELF_SERVE_<ts>
artifact with the full workload, engine.info() counters (occupancy, pool,
lowerings, speculative funnel), and the latency distribution including
time-to-first-token p50/p99 (submission -> first emitted token, queueing
included — the honest serving number).

The workload keeps the queue deeper than the batch (requests >> slots)
— the serving regime continuous batching exists for; a trickle workload
(queue < batch) degenerates to sequential-with-padding and measures ~1x
on a CPU where tiny-model decode is compute-bound, not dispatch-bound.
The --spec workload decodes longer (48-96 new tokens) because that is
the regime speculation serves: decode-dominated traffic.

--overload mode measures the serving front door under 2x-over-capacity
open-loop load THROUGH the gateway wire: every request is its own client
thread, capacity (slots + queue) covers half the burst, and the rest must
be shed with a typed 429 — fast (shed p99 rides the payload; the slow
battery pins < 50 ms), never a hang, never an untyped error. The two
acceptance floors: accepted requests' tokens stay BITWISE the
closed-loop engine's, and goodput (accepted tokens/s) stays >= 0.8x the
closed-loop engine that was never overloaded — the overload machinery
(admission checks, the degradation ladder) may shed load, not throughput.
Ladder occupancy (fraction of steps at each pressure level) rides the
payload so a ladder that never engages — or never disengages — is
diagnosable from the artifact.

Env: PT_SERVE_BENCH_REQUESTS (default 24), PT_SERVE_BENCH_BATCH (8),
     PT_SERVE_BENCH_REPS (3), PT_SERVE_BENCH_SPEC_K (6).
"""
from __future__ import annotations

import json
import os
import sys
import time

# steady-state dispatch is the subject, not compile thrash: sequential
# generate() lowers one (prefill, decode) pair PER DISTINCT request shape
# (its cache is sized prompt+new), so the mixed workload needs more step-
# capture signatures than the default 16-entry LRU or the sequential leg
# measures retracing instead of serving
os.environ.setdefault("PT_STEP_CAPTURE_SIZE", "128")

import jax

# serving-loop overhead is the subject — always measure on CPU (the env's
# sitecustomize may register a TPU plugin; jax.config wins over env vars)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as P  # noqa: E402
from paddle_tpu.inference.serving import ServingEngine  # noqa: E402
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402

MAX_SEQ = 64        # sized to the workload: 28 prompt + 32 new <= 64
SPEC_MAX_SEQ = 128  # --spec decodes longer: 28 + 96 + k hits 128 (clamped)


def _build(seq=MAX_SEQ, tie=False):
    P.seed(0)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           inter=128, seq=seq)
    model = LlamaForCausalLM(cfg)
    if tie:
        # weight tying (lm_head = embedding^T): gives the random proxy
        # model locally-predictable output structure — see module docstring
        model.lm_head.weight._value = model.llama.embed_tokens.weight._value.T
    return model, cfg


def _workload(n, vocab, seed=0, new_lo=16, new_hi=33, seq=MAX_SEQ, spec_k=0):
    """Mixed-length: prompts 4..28 tokens, new_lo..new_hi-1 new tokens."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(4, 29))
        new = int(rng.randint(new_lo, new_hi))
        new = min(new, seq - plen - spec_k)
        out.append((rng.randint(0, vocab, (plen,)), new))
    return out


def _percentiles(vals_ms):
    arr = np.asarray(vals_ms)
    return (float(np.percentile(arr, 50)), float(np.percentile(arr, 99)))


def _run_sequential(model, work):
    outs = []
    token_times = []
    t0 = time.perf_counter()
    for prompt, new in work:
        tprev = time.perf_counter()
        ids = P.to_tensor(prompt.reshape(1, -1))
        out = model.generate(ids, max_new_tokens=new)
        tend = time.perf_counter()
        outs.append(np.asarray(out.numpy())[0])
        # generate() is opaque per-token; spread the call time uniformly
        # (an upper bound on its p50, fair since its tokens are serial)
        token_times += [(tend - tprev) / new] * new
    wall = time.perf_counter() - t0
    n_tokens = sum(new for _, new in work)
    return outs, n_tokens / wall, token_times


def _run_engine(model, work, batch, max_seq, spec_k=0):
    eng = ServingEngine(model, max_batch=batch, max_seq_len=max_seq,
                        spec_k=spec_k, drafter="ngram" if spec_k else None)
    t0 = time.perf_counter()
    reqs = [eng.submit(prompt, max_new_tokens=new) for prompt, new in work]
    eng.run()
    wall = time.perf_counter() - t0
    outs = [r.result() for r in reqs]
    # per-token inter-arrival latency per request (first token measured
    # from submission — includes queueing, the honest serving number) and
    # time-to-first-token per request
    lat, ttft = [], []
    for r in reqs:
        prev = r.submit_time
        ttft.append(r.token_times[0] - r.submit_time)
        for t in r.token_times:
            lat.append(t - prev)
            prev = t
    n_tokens = sum(len(r.output_tokens) for r in reqs)
    return outs, n_tokens / wall, lat, ttft, eng


def _artifact(payload, detail):
    ts = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BENCH_SELF_SERVE_{ts}.json")
    try:
        with open(path, "w") as f:
            json.dump({**payload, "detail": detail}, f, indent=1)
        print(f"# artifact -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"# artifact write failed: {e}", file=sys.stderr)


def main() -> dict:
    n_requests = int(os.environ.get("PT_SERVE_BENCH_REQUESTS", "24"))
    batch = int(os.environ.get("PT_SERVE_BENCH_BATCH", "8"))
    reps = int(os.environ.get("PT_SERVE_BENCH_REPS", "3"))

    model, cfg = _build()
    work = _workload(n_requests, cfg.vocab_size)

    # warmup: one FULL pass of each path so every lowering both sides use
    # (sequential's per-shape pairs, the engine's prefill buckets and the
    # batched decode) is compiled off the clock — steady-state throughput
    # is the metric, compile latency is whole-step capture's own bench
    _run_sequential(model, work)
    _run_engine(model, work, batch, MAX_SEQ)

    # best-of-reps: single shared core, the best rep is the noise floor
    best_seq = (None, 0.0, None)
    best_cont = None
    for _ in range(reps):
        s = _run_sequential(model, work)
        if s[1] > best_seq[1]:
            best_seq = s
        c = _run_engine(model, work, batch, MAX_SEQ)
        if best_cont is None or c[1] > best_cont[1]:
            best_cont = c
    seq_outs, seq_tps, _ = best_seq
    cont_outs, cont_tps, lat, ttft, eng = best_cont

    # trace-on leg (the observability cost gate): the SAME engine
    # workload with PT_TRACE flipped on — spans per decode step + the
    # scheduler/submit events are the only delta. Best-of-reps like the
    # untraced leg so the ratio compares noise floors, not noise.
    # Documented ceiling: <= 1.25x (slow battery; smoke allows 1.5x).
    from paddle_tpu.observability import trace as obs_trace

    obs_trace.enable(True)
    try:
        traced_tps = -1.0   # the first rep always lands, even at 0 tps
        traced_outs = None
        for _ in range(reps):
            c = _run_engine(model, work, batch, MAX_SEQ)
            if c[1] > traced_tps:
                traced_tps, traced_outs = c[1], c[0]
    finally:
        obs_trace.enable(False)
        obs_trace.trace_clear()
    trace_overhead = cont_tps / traced_tps if traced_tps > 0 else 0.0

    # correctness gate: the engine must emit EXACTLY the oracle's tokens
    # (traced leg included — spans must never perturb the math)
    mismatches = sum(1 for a, b in zip(seq_outs, cont_outs)
                     if a.shape != b.shape or not (a == b).all())
    mismatches += sum(1 for a, b in zip(seq_outs, traced_outs)
                      if a.shape != b.shape or not (a == b).all())

    p50, p99 = _percentiles(np.asarray(lat) * 1e3)
    ttft50, ttft99 = _percentiles(np.asarray(ttft) * 1e3)
    speedup = cont_tps / seq_tps if seq_tps else 0.0
    info = eng.info()

    payload = {
        "metric": "serving_throughput_speedup_vs_sequential",
        "value": round(speedup, 2),
        "unit": "x",
        # acceptance floor: continuous >= 1.5x sequential tokens/s
        "vs_baseline": round(speedup / 1.5, 4),
        "backend": "cpu-proxy",
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "continuous_tokens_per_sec": round(cont_tps, 1),
        "p50_token_ms": round(p50, 2),
        "p99_token_ms": round(p99, 2),
        "ttft_p50_ms": round(ttft50, 2),
        "ttft_p99_ms": round(ttft99, 2),
        "requests": n_requests,
        "max_batch": batch,
        "avg_occupancy": round(info["avg_occupancy"], 3),
        "token_mismatches": mismatches,
        # trace-on / trace-off throughput ratio (documented ceiling 1.25x)
        "trace_overhead": round(trace_overhead, 4),
        "traced_tokens_per_sec": round(traced_tps, 1),
    }
    print(json.dumps(payload), flush=True)

    detail = {
        "workload": [{"prompt_len": int(p.size), "max_new": n}
                     for p, n in work],
        "engine_info": info,
        "latency_ms": {"p50": p50, "p99": p99,
                       "ttft_p50": ttft50, "ttft_p99": ttft99},
    }
    _artifact(payload, detail)
    return payload


def spec_main() -> dict:
    """--spec: speculative (n-gram drafter) vs non-speculative engine on
    one decode-dominated workload over the weight-tied proxy model.

    Default batch is 4 (vs the throughput bench's 8): speculation trades
    per-step fixed cost (dispatch, host loop, token sync) for window
    compute, so its win is largest where steps are overhead-bound — small
    decode batches on this CPU proxy, memory-bound decode on a real TPU.
    At batch 16 the [B, k+1] window's COMPUTE dominates the step and the
    CPU proxy measures ~1x; the knob is exposed so the crossover is
    reproducible."""
    n_requests = int(os.environ.get("PT_SERVE_BENCH_REQUESTS", "24"))
    batch = int(os.environ.get("PT_SERVE_BENCH_BATCH", "4"))
    reps = int(os.environ.get("PT_SERVE_BENCH_REPS", "3"))
    spec_k = int(os.environ.get("PT_SERVE_BENCH_SPEC_K", "6"))

    model, cfg = _build(seq=SPEC_MAX_SEQ, tie=True)
    work = _workload(n_requests, cfg.vocab_size, new_lo=48, new_hi=97,
                     seq=SPEC_MAX_SEQ, spec_k=spec_k)

    _run_engine(model, work, batch, SPEC_MAX_SEQ)                 # warm off
    _run_engine(model, work, batch, SPEC_MAX_SEQ, spec_k=spec_k)  # warm on

    best_off = best_on = None
    for _ in range(reps):
        off = _run_engine(model, work, batch, SPEC_MAX_SEQ)
        if best_off is None or off[1] > best_off[1]:
            best_off = off
        on = _run_engine(model, work, batch, SPEC_MAX_SEQ, spec_k=spec_k)
        if best_on is None or on[1] > best_on[1]:
            best_on = on
    off_outs, off_tps, off_lat, off_ttft, off_eng = best_off
    on_outs, on_tps, on_lat, on_ttft, on_eng = best_on

    # the exactness gate: speculative greedy output must be BITWISE the
    # non-speculative engine's (which PR 7 pinned to sequential generate)
    mismatches = sum(1 for a, b in zip(off_outs, on_outs)
                     if a.shape != b.shape or not (a == b).all())

    p50_on, p99_on = _percentiles(np.asarray(on_lat) * 1e3)
    ttft50_on, ttft99_on = _percentiles(np.asarray(on_ttft) * 1e3)
    ttft50_off, ttft99_off = _percentiles(np.asarray(off_ttft) * 1e3)
    speedup = on_tps / off_tps if off_tps else 0.0
    spec = on_eng.info()["spec"]

    payload = {
        "metric": "serving_spec_speedup_vs_nonspec",
        "value": round(speedup, 2),
        "unit": "x",
        # acceptance floor: speculative >= 1.25x the spec-off engine
        "vs_baseline": round(speedup / 1.25, 4),
        "backend": "cpu-proxy",
        "drafter": "ngram",
        "spec_k": spec_k,
        "acceptance_rate": round(spec["acceptance_rate"], 3),
        "tokens_per_verify": round(spec["tokens_per_verify"], 2),
        "nonspec_tokens_per_sec": round(off_tps, 1),
        "spec_tokens_per_sec": round(on_tps, 1),
        "p50_token_ms": round(p50_on, 2),
        "p99_token_ms": round(p99_on, 2),
        "ttft_p50_ms": round(ttft50_on, 2),
        "ttft_p99_ms": round(ttft99_on, 2),
        "requests": n_requests,
        "max_batch": batch,
        "token_mismatches": mismatches,
    }
    print(json.dumps(payload), flush=True)

    detail = {
        "workload": [{"prompt_len": int(p.size), "max_new": n}
                     for p, n in work],
        "spec_engine_info": on_eng.info(),
        "nonspec_engine_info": off_eng.info(),
        "ttft_ms": {"spec_p50": ttft50_on, "spec_p99": ttft99_on,
                    "nonspec_p50": ttft50_off, "nonspec_p99": ttft99_off},
    }
    _artifact(payload, detail)
    return payload


def shared_main() -> dict:
    """--shared-prefix: N requests over ONE long system prompt (the
    millions-of-users common case) against the prefix-sharing engine vs
    the unshared one, plus a mega-prompt + decode-batch leg proving
    chunked prefill bounds the max inter-decode-step gap.

    Leg 1 emits the prefill-pages-saved ratio (shared pages the borrowers
    skipped / full-prompt pages the unshared engine prefills — accounting,
    so it is deterministic at any scale) and TTFT p50/p99 for both
    engines, with the bitwise token gate across shared/unshared.

    Leg 2 streams one in-flight decode request while a mega-prompt joins:
    with PT_SERVE_PREFILL_CHUNK-style chunking the prompt prefills in
    fixed [1, chunk] windows interleaved with decode steps, so the decode
    stream's max inter-token gap stays under the single-chunk bound
    (measured: 3x the mean chunk time + 2x the mean decode step — one
    engine step is exactly one window plus one decode); the unchunked
    engine eats the whole prefill in one gap. Both gaps ride the payload.

    Env: PT_SERVE_BENCH_REQUESTS (default 8), PT_SERVE_BENCH_PREFIX (48),
         PT_SERVE_BENCH_CHUNK (8)."""
    n_requests = int(os.environ.get("PT_SERVE_BENCH_REQUESTS", "8"))
    prefix_len = int(os.environ.get("PT_SERVE_BENCH_PREFIX", "48"))
    chunk = int(os.environ.get("PT_SERVE_BENCH_CHUNK", "8"))
    page = 16
    new_tokens = 8

    model, cfg = _build(seq=SPEC_MAX_SEQ)
    rng = np.random.RandomState(11)
    common = rng.randint(0, cfg.vocab_size, (prefix_len,))
    work = [np.concatenate([common,
                            rng.randint(0, cfg.vocab_size, (2 + i % 5,))])
            for i in range(n_requests)]

    def run(sharing: bool):
        eng = ServingEngine(model, max_batch=4, max_seq_len=SPEC_MAX_SEQ,
                            page_size=page, prefix_sharing=sharing)
        outs, ttft = [], []
        # arrival order: the first request is the donor (its commit is
        # what makes every later walk hit), the rest stream in behind it
        for p in work:
            r = eng.submit(p, max_new_tokens=new_tokens)
            eng.run()
            outs.append(r.result())
            ttft.append((r.token_times[0] - r.submit_time) * 1e3)
        return outs, ttft, eng

    run(False)  # warm every lowering off the clock
    base_outs, base_ttft, base_eng = run(False)
    run(True)
    shr_outs, shr_ttft, shr_eng = run(True)

    mismatches = sum(1 for a, b in zip(base_outs, shr_outs)
                     if a.shape != b.shape or not (a == b).all())
    info = shr_eng.info()
    prompt_pages = sum(int(p.size) // page for p in work)
    saved = info["prefill_pages_saved"]
    ratio = prompt_pages / max(1, prompt_pages - saved)

    # ---- leg 2: mega-prompt vs the decode batch (own longer-sequence
    # model: the stall the chunking bounds must dwarf a decode step) ----
    gap_model, gap_cfg = _build(seq=512)
    gap_seq = 512

    def gap_leg(use_chunk):
        eng = ServingEngine(gap_model, max_batch=4, max_seq_len=gap_seq,
                            page_size=page,
                            prefill_chunk=chunk if use_chunk else 0)
        ra = eng.submit(work[0][:6], max_new_tokens=48)
        for _ in range(4):
            eng.step()
        # chunk time measured over the mega-prompt's windows ONLY: ra's
        # classic bucketed prefill above is excluded, so the single-chunk
        # bound below cannot be inflated by non-chunk prefill cost
        t_pref0, n_chunks0 = eng._prefill_time, \
            eng._counters["prefill_chunks"]
        mega = rng.randint(0, gap_cfg.vocab_size, (gap_seq - 64,))
        eng.submit(mega, max_new_tokens=4)
        eng.run()
        gaps = np.diff(np.asarray(ra.token_times)) * 1e3
        n_chunks = eng._counters["prefill_chunks"] - n_chunks0
        chunk_ms = (1e3 * (eng._prefill_time - t_pref0) / n_chunks
                    if n_chunks else 0.0)
        return float(gaps.max()), chunk_ms, eng

    gap_leg(True)   # warm the window signature...
    gap_leg(False)  # ...and the mega-prompt's bucket, so BOTH gaps
    # measure prefill stall, not compile latency
    chunked_gap, chunk_ms, ceng = gap_leg(True)
    unchunked_gap, _, _ = gap_leg(False)
    ci = ceng.info()
    decode_ms = (ci["decode_steps"] and
                 1e3 * ceng._decode_time / ci["decode_steps"]) or 0.0
    bound_ms = 3.0 * chunk_ms + 2.0 * decode_ms

    payload = {
        "metric": "serving_shared_prefix_pages_saved",
        "value": round(ratio, 2),
        "unit": "x",
        # acceptance floor: >= 2x prefill-pages-saved at 8 shared requests
        "vs_baseline": round(ratio / 2.0, 4),
        "backend": "cpu-proxy",
        "requests": n_requests,
        "prefix_len": prefix_len,
        "pages_saved": int(saved),
        "prompt_pages": int(prompt_pages),
        "token_mismatches": mismatches,
        "ttft_p50_ms_shared": round(float(np.percentile(shr_ttft, 50)), 2),
        "ttft_p99_ms_shared": round(float(np.percentile(shr_ttft, 99)), 2),
        "ttft_p50_ms_unshared": round(float(np.percentile(base_ttft, 50)),
                                      2),
        "ttft_p99_ms_unshared": round(float(np.percentile(base_ttft, 99)),
                                      2),
        "chunk": chunk,
        "chunked_max_gap_ms": round(chunked_gap, 2),
        "unchunked_max_gap_ms": round(unchunked_gap, 2),
        "single_chunk_bound_ms": round(bound_ms, 2),
        "chunked_gap_ok": bool(chunked_gap <= bound_ms),
    }
    print(json.dumps(payload), flush=True)
    _artifact(payload, {
        "workload": [{"prompt_len": int(p.size)} for p in work],
        "shared_engine_info": info,
        "unshared_engine_info": base_eng.info(),
        "chunked_engine_info": ci,
    })
    return payload


def overload_main() -> dict:
    """--overload: 2x-over-capacity burst through the gateway wire.

    Default batch is 4 with a queue of the same depth: capacity 8, burst
    16 (PT_SERVE_BENCH_REQUESTS caps the burst at an even number). One
    thread per request fires simultaneously with retries=0, so every
    admission decision is measured exactly once — accepted requests wait
    for their tokens, shed ones must get the typed 429 back immediately
    (no model compute sits on the shed path)."""
    import threading

    from paddle_tpu.inference.serving.gateway import (GatewayClient,
                                                      ServingGateway)
    from paddle_tpu.utils.deadline import EngineOverloaded

    offered = int(os.environ.get("PT_SERVE_BENCH_REQUESTS", "24"))
    offered -= offered % 2
    batch = int(os.environ.get("PT_SERVE_BENCH_BATCH", "4"))
    reps = int(os.environ.get("PT_SERVE_BENCH_REPS", "3"))
    max_queue = max(1, offered // 2 - batch)   # slots + queue = burst / 2

    model, cfg = _build()
    work = _workload(offered, cfg.vocab_size, new_lo=8, new_hi=17)

    # closed-loop reference on an engine that is NEVER overloaded: the
    # oracle token streams (greedy decode is deterministic per prompt
    # regardless of batch composition — pinned by the serving suite) and
    # the goodput baseline. The first pass doubles as warmup: the
    # whole-step capture cache is process-global, so the gateway engine
    # below reuses every lowering and the overloaded leg measures
    # serving, not compiling. Best-of-reps on BOTH sides (the ratio
    # compares noise floors, not noise — the bench's convention).
    oracle = None
    ref_tps = 0.0
    for _ in range(reps + 1):           # +1: the warmup pass
        t0 = time.perf_counter()
        ref = ServingEngine(model, max_batch=batch, max_seq_len=MAX_SEQ)
        rr = [ref.submit(p, max_new_tokens=n) for p, n in work]
        ref.run()
        ref_wall = time.perf_counter() - t0
        outs = [r.result() for r in rr]
        if oracle is None:
            oracle = outs
        else:
            for a, b in zip(outs, oracle):
                assert a.shape == b.shape and (a == b).all()
            ref_tps = max(ref_tps, sum(
                o.size - p.size for o, (p, _) in zip(oracle, work))
                / ref_wall)

    def burst():
        eng = ServingEngine(model, max_batch=batch, max_seq_len=MAX_SEQ,
                            max_queue=max_queue)
        gw = ServingGateway(eng)
        clients = [GatewayClient("127.0.0.1", gw.port) for _ in work]
        results = [None] * offered      # (kind, payload, latency_s)
        barrier = threading.Barrier(offered + 1)

        def fire(i):
            prompt, new = work[i]
            barrier.wait()
            t = time.perf_counter()
            try:
                out = clients[i].generate(prompt, max_new_tokens=new,
                                          retries=0, timeout=120.0)
                results[i] = ("ok", out, time.perf_counter() - t)
            except EngineOverloaded as e:
                results[i] = ("shed", e.retry_after_ms,
                              time.perf_counter() - t)
            except BaseException as e:  # noqa: BLE001 — untyped = failure
                results[i] = ("error", type(e).__name__,
                              time.perf_counter() - t)

        threads = [threading.Thread(target=fire, args=(i,), daemon=True)
                   for i in range(offered)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(300.0)
        wall = time.perf_counter() - t0
        info = eng.info()
        for c in clients:
            c.close()
        gw.stop(drain=True, timeout=30.0)
        return results, wall, info

    best = None                         # (goodput, results, info)
    shed_ms = []                        # shed latency pools across reps
    untyped = []
    mismatches = 0
    for _ in range(reps):
        results, wall, info = burst()
        accepted = [(i, r[1]) for i, r in enumerate(results)
                    if r and r[0] == "ok"]
        shed_ms += [r[2] * 1e3 for r in results if r and r[0] == "shed"]
        untyped += [r[1] for r in results if r and r[0] == "error"]
        mismatches += sum(1 for i, out in accepted
                          if out.shape != oracle[i].shape
                          or not (out == oracle[i]).all())
        acc_tokens = sum(out.size - work[i][0].size for i, out in accepted)
        goodput = acc_tokens / wall if wall > 0 else 0.0
        if best is None or goodput > best[0]:
            best = (goodput, results, info)
    goodput, results, info = best
    accepted = [(i, r[1]) for i, r in enumerate(results)
                if r and r[0] == "ok"]
    ratio = goodput / ref_tps if ref_tps else 0.0
    shed_p50, shed_p99 = _percentiles(shed_ms) if shed_ms else (0.0, 0.0)
    steps = [info["pressure"][f"level{i}_steps"] for i in range(4)]
    total_steps = max(1, sum(steps))

    payload = {
        "metric": "serving_overload_goodput_ratio",
        "value": round(ratio, 2),
        "unit": "x",
        # acceptance floor: goodput under 2x overload >= 0.8x closed-loop
        "vs_baseline": round(ratio / 0.8, 4),
        "backend": "cpu-proxy",
        "offered": offered,
        "reps": reps,
        # accepted/shed are the BEST rep's split (they sum to offered);
        # the shed-latency percentiles pool every rep's sheds
        "accepted": len(accepted),
        "shed": sum(1 for r in results if r and r[0] == "shed"),
        "untyped_errors": len(untyped),
        "max_batch": batch,
        "max_queue": max_queue,
        "shed_p50_ms": round(shed_p50, 2),
        "shed_p99_ms": round(shed_p99, 2),
        "accepted_tokens_per_sec": round(goodput, 1),
        "closed_loop_tokens_per_sec": round(ref_tps, 1),
        "token_mismatches": mismatches,
        "ladder_occupancy": {f"level{i}": round(s / total_steps, 3)
                             for i, s in enumerate(steps)},
    }
    print(json.dumps(payload), flush=True)

    _artifact(payload, {
        "workload": [{"prompt_len": int(p.size), "max_new": n}
                     for p, n in work],
        "engine_info": info,
        "untyped": untyped,
        "shed_latency_ms": shed_ms,
    })
    return payload


if __name__ == "__main__":
    if "--overload" in sys.argv[1:]:
        overload_main()
    elif "--shared-prefix" in sys.argv[1:]:
        shared_main()
    elif "--spec" in sys.argv[1:] or os.environ.get(
            "PT_SERVE_BENCH_SPEC", "0") not in ("0", ""):
        spec_main()
    else:
        main()
