"""Microbenchmark: continuous batching vs sequential per-request generate().

Measures the serving engine (paddle_tpu/inference/serving) against the
baseline it replaces — one `model.generate()` call per request, back to
back — on the SAME mixed-length workload and the SAME tiny llama config.
CPU-runnable ("backend": "cpu-proxy", same convention as bench.py) so the
number stays measurable when the TPU probe reports tpu-unavailable:

  sequential — for each request: prefill + per-token KV-cache decode at
               batch 1 (each token is one whole-step-captured executable
               call serving ONE sequence).
  continuous — the ServingEngine: same executables, but every decode step
               serves every active slot, with requests joining/leaving
               between steps as they arrive/finish.

Prints ONE JSON line:
  {"metric": "serving_throughput_speedup_vs_sequential", "value": <x>,
   "unit": "x", "vs_baseline": <value/1.5>, "backend": "cpu-proxy",
   "p50_token_ms": ..., "p99_token_ms": ..., ...}
(acceptance: value >= 1.5) and writes a BENCH_SELF_SERVE_<ts>.json
artifact with the full workload, engine.info() counters (occupancy,
pool, lowering counts), and the latency distribution.

The workload keeps the queue deeper than the batch (requests >> slots)
— the serving regime continuous batching exists for; a trickle workload
(queue < batch) degenerates to sequential-with-padding and measures ~1x
on a CPU where tiny-model decode is compute-bound, not dispatch-bound.

Env: PT_SERVE_BENCH_REQUESTS (default 24), PT_SERVE_BENCH_BATCH (8),
     PT_SERVE_BENCH_REPS (3).
"""
from __future__ import annotations

import json
import os
import sys
import time

# steady-state dispatch is the subject, not compile thrash: sequential
# generate() lowers one (prefill, decode) pair PER DISTINCT request shape
# (its cache is sized prompt+new), so the mixed workload needs more step-
# capture signatures than the default 16-entry LRU or the sequential leg
# measures retracing instead of serving
os.environ.setdefault("PT_STEP_CAPTURE_SIZE", "128")

import jax

# serving-loop overhead is the subject — always measure on CPU (the env's
# sitecustomize may register a TPU plugin; jax.config wins over env vars)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as P  # noqa: E402
from paddle_tpu.inference.serving import ServingEngine  # noqa: E402
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402

MAX_SEQ = 64  # sized to the workload: 28 prompt + 32 new <= 64


def _build():
    P.seed(0)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           inter=128, seq=MAX_SEQ)
    return LlamaForCausalLM(cfg), cfg


def _workload(n, vocab, seed=0):
    """Mixed-length: prompts 4..28 tokens, 16..32 new tokens per request."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(4, 29))
        new = int(rng.randint(16, 33))
        out.append((rng.randint(0, vocab, (plen,)), new))
    return out


def _run_sequential(model, work):
    outs = []
    token_times = []
    t0 = time.perf_counter()
    for prompt, new in work:
        tprev = time.perf_counter()
        ids = P.to_tensor(prompt.reshape(1, -1))
        out = model.generate(ids, max_new_tokens=new)
        tend = time.perf_counter()
        outs.append(np.asarray(out.numpy())[0])
        # generate() is opaque per-token; spread the call time uniformly
        # (an upper bound on its p50, fair since its tokens are serial)
        token_times += [(tend - tprev) / new] * new
    wall = time.perf_counter() - t0
    n_tokens = sum(new for _, new in work)
    return outs, n_tokens / wall, token_times


def _run_continuous(model, work, batch):
    eng = ServingEngine(model, max_batch=batch, max_seq_len=MAX_SEQ)
    t0 = time.perf_counter()
    reqs = [eng.submit(prompt, max_new_tokens=new) for prompt, new in work]
    eng.run()
    wall = time.perf_counter() - t0
    outs = [r.result() for r in reqs]
    # per-token inter-arrival latency per request (first token measured
    # from submission — includes queueing, the honest serving number)
    lat = []
    for r in reqs:
        prev = r.submit_time
        for t in r.token_times:
            lat.append(t - prev)
            prev = t
    n_tokens = sum(len(r.output_tokens) for r in reqs)
    return outs, n_tokens / wall, lat, eng


def main() -> dict:
    n_requests = int(os.environ.get("PT_SERVE_BENCH_REQUESTS", "24"))
    batch = int(os.environ.get("PT_SERVE_BENCH_BATCH", "8"))
    reps = int(os.environ.get("PT_SERVE_BENCH_REPS", "3"))

    model, cfg = _build()
    work = _workload(n_requests, cfg.vocab_size)

    # warmup: one FULL pass of each path so every lowering both sides use
    # (sequential's per-shape pairs, the engine's prefill buckets and the
    # batched decode) is compiled off the clock — steady-state throughput
    # is the metric, compile latency is whole-step capture's own bench
    _run_sequential(model, work)
    _run_continuous(model, work, batch)

    # best-of-reps: single shared core, the best rep is the noise floor
    best_seq = (None, 0.0, None)
    best_cont = (None, 0.0, None, None)
    for _ in range(reps):
        s = _run_sequential(model, work)
        if s[1] > best_seq[1]:
            best_seq = s
        c = _run_continuous(model, work, batch)
        if c[1] > best_cont[1]:
            best_cont = c
    seq_outs, seq_tps, _ = best_seq
    cont_outs, cont_tps, lat, eng = best_cont

    # correctness gate: the engine must emit EXACTLY the oracle's tokens
    mismatches = sum(1 for a, b in zip(seq_outs, cont_outs)
                     if a.shape != b.shape or not (a == b).all())

    lat_ms = np.asarray(sorted(lat)) * 1e3
    p50 = float(np.percentile(lat_ms, 50))
    p99 = float(np.percentile(lat_ms, 99))
    speedup = cont_tps / seq_tps if seq_tps else 0.0
    info = eng.info()

    payload = {
        "metric": "serving_throughput_speedup_vs_sequential",
        "value": round(speedup, 2),
        "unit": "x",
        # acceptance floor: continuous >= 1.5x sequential tokens/s
        "vs_baseline": round(speedup / 1.5, 4),
        "backend": "cpu-proxy",
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "continuous_tokens_per_sec": round(cont_tps, 1),
        "p50_token_ms": round(p50, 2),
        "p99_token_ms": round(p99, 2),
        "requests": n_requests,
        "max_batch": batch,
        "avg_occupancy": round(info["avg_occupancy"], 3),
        "token_mismatches": mismatches,
    }
    print(json.dumps(payload), flush=True)

    detail = {
        "workload": [{"prompt_len": int(p.size), "max_new": n}
                     for p, n in work],
        "engine_info": info,
        "latency_ms": {"p50": p50, "p99": p99,
                       "mean": float(lat_ms.mean()),
                       "max": float(lat_ms.max())},
    }
    ts = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BENCH_SELF_SERVE_{ts}.json")
    try:
        with open(path, "w") as f:
            json.dump({**payload, "detail": detail}, f, indent=1)
        print(f"# artifact -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"# artifact write failed: {e}", file=sys.stderr)
    return payload


if __name__ == "__main__":
    main()
