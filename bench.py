"""Benchmark: LLaMA causal-LM training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md), so vs_baseline is
reported against the driver-tracked north-star proxy: achieved model FLOPs
utilization (MFU) as a fraction of the 40% target on this chip.

Round-4 design (VERDICT r3 item 1):
- default config is a 7B-PROXY: the real LLaMA-7B layer shape
  (h=4096, inter=11008, heads=32, vocab=32000, seq=2048) with as many layers
  as fit one chip's HBM (OOM-adaptive search), fp32 master params + AdamW.
- besides the measured MFU, an EXTRAPOLATED 7B MFU is reported from a
  two-point fit t(L) = a + b*L over two layer counts — labeled as
  extrapolated, with the fit recorded.
- every successful run writes a BENCH_SELF_<ts>.json artifact (full details
  + HLO kernel provenance) so a wedged relay at round-end capture time
  cannot erase the evidence.
- the backend probe spans ~20 minutes (10 attempts, growing backoff); a
  wedged relay makes jax.devices() HANG, so probing runs in a subprocess.

Integrity (VERDICT r1 weak #5 / item 10):
- peak TFLOP/s derived from the attached device kind (not hard-coded),
- FLOP count includes attention (6*N*T + 12*L*B*S^2*H, causal x0.5),
- the metric name carries the config; the JSON carries the real measured
  parameter count and which numbers are measured vs extrapolated,
- the compiled step's HLO is inspected to report whether the Pallas flash
  kernel (tpu_custom_call) or plain XLA attention actually ran.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
_PEAK_BF16_TFLOPS = {
    "TPU v2": 46.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
    "TPU v6e": 918.0,
    "TPU v7": 4614.0,
}

_LLAMA_7B = dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                 num_attention_heads=32)


def _peak_tflops(device) -> tuple[float, str]:
    kind = getattr(device, "device_kind", "") or ""
    for key, val in sorted(_PEAK_BF16_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(key):
            return val, kind
    return 197.0, f"{kind or 'unknown'} (assumed v5e peak)"


def _attention_kernel_provenance(step, batch) -> str:
    """Inspect the HLO of the EXACT benchmarked train step."""
    try:
        txt = step.lower_text(batch)
    except Exception as e:  # noqa: BLE001 — provenance is best-effort
        return f"lowering-failed({type(e).__name__})"
    if "tpu_custom_call" in txt or "mosaic" in txt.lower():
        return "pallas_flash_attention"
    return "xla_dot_attention"


def _probe_once(probe_timeout: int = 75) -> str | None:
    """One subprocess probe of the accelerator backend.

    A wedged remote-compile relay makes jax.devices() HANG rather than
    raise, so the probe runs in a child process under a timeout — the parent
    only initializes jax after a probe succeeds.  Returns None on success,
    else an error string.
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=probe_timeout)
    except subprocess.TimeoutExpired:
        return f"backend init timed out after {probe_timeout}s"
    if r.returncode == 0:
        return None
    last = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["rc!=0"]
    return last[0][-200:]


def _record(history: list, err: str | None):
    history.append({"ts": round(time.time(), 1),
                    "ok": err is None,
                    "detail": None if err is None else err})


def _probe_quick(history: list) -> str | None:
    """3 probes, <5 min total.  None on success, else last error."""
    last = None
    for i, backoff in enumerate((0, 10, 15)):
        if backoff:
            time.sleep(backoff)
        last = _probe_once()
        _record(history, last)
        if last is None:
            return None
        print(f"# quick probe {i + 1}/3: {last}", file=sys.stderr)
    return last


def _probe_patient(history: list, budget_s: float) -> str | None:
    """Probe until the budget is spent.  None on success, else last error."""
    deadline = time.time() + budget_s
    last = "budget exhausted"
    i = 0
    while time.time() < deadline:
        time.sleep(min(60, max(5, deadline - time.time())))
        last = _probe_once()
        _record(history, last)
        i += 1
        if last is None:
            return None
        print(f"# patient probe {i}: {last}", file=sys.stderr)
    return last


def _write_probe_history(history: list):
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_PROBE_HISTORY.json")
    try:
        with open(path, "w") as f:
            json.dump({"probes": history,
                       "n": len(history),
                       "n_ok": sum(1 for h in history if h["ok"])}, f,
                      indent=1)
    except OSError as e:
        print(f"# probe-history write failed: {e}", file=sys.stderr)


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s.upper()[:4000]
            or "Failed to allocate" in s)


def _build_and_time(cfg_kwargs, layers, batch, seq, n_steps=20,
                    warmup=3, fused_loss=False) -> dict:
    """Build the compiled train step for one (layers, batch) point and time
    it.  Raises on OOM (caller adapts)."""
    import jax

    import paddle_tpu as P
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_hybrid_train_step)

    P.seed(0)
    cfg = LlamaConfig(num_hidden_layers=layers,
                      max_position_embeddings=seq, **cfg_kwargs)
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, n_microbatches=1, remat=True,
                                   amp=True, fused_loss=fused_loss)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
    b = {"input_ids": P.to_tensor(ids[:, :-1]),
         "labels": P.to_tensor(ids[:, 1:])}

    last = {}

    def run_blocked(n):
        """Run n steps and force REAL completion by fetching a scalar that
        depends on the last step's parameter updates (block_until_ready on
        relayed buffers can return early in this environment; a 4-byte
        dependent fetch cannot)."""
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step(b)
        last["loss"] = float(loss.numpy())
        leaf = jax.tree_util.tree_leaves(step.state["params"])[0]
        _ = float(leaf[(0,) * leaf.ndim])  # device-side index, tiny transfer
        return time.perf_counter() - t0

    run_blocked(warmup)  # compile + steady state
    dt = min(run_blocked(n_steps), run_blocked(n_steps)) / n_steps

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    kernel = _attention_kernel_provenance(step, b)
    # free the model/optimizer state before the caller builds the next point
    del step, model, opt
    return {"layers": layers, "batch": batch, "seq": seq,
            "step_time_s": dt, "n_params": n_params,
            "loss": last["loss"], "attention_kernel": kernel}


def _flops_per_step(n_params, layers, batch, seq, hidden):
    """6ND matmul FLOPs + causal attention FLOPs (fwd 4*B*S^2*H per layer for
    QK^T+PV, x3 fwd+bwd, x0.5 causal sparsity)."""
    tokens = batch * seq
    matmul = 6.0 * n_params * tokens
    attn = 12.0 * layers * batch * seq * seq * hidden * 0.5
    return matmul + attn


def _emit(payload: dict, detail: dict | None = None):
    print(json.dumps(payload), flush=True)
    if detail is not None:
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"BENCH_SELF_{ts}.json")
        try:
            with open(path, "w") as f:
                json.dump({**payload, "detail": detail}, f, indent=1)
            print(f"# artifact -> {path}", file=sys.stderr)
        except OSError as e:
            print(f"# artifact write failed: {e}", file=sys.stderr)


def _cpu_proxy_fallback(probe_err: str):
    """TPU unreachable after the patient probe phase: measure the tiny
    llama config on CPU so the round still records a real number.

    The metric name and an explicit "backend": "cpu-proxy" label keep it
    from ever being read as chip throughput; vs_baseline stays 0.0 because
    no TPU baseline applies to a CPU measurement."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    cfg_kwargs = dict(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_attention_heads=4)
    try:
        meas = _build_and_time(cfg_kwargs, layers=2, batch=2, seq=64,
                               n_steps=10, warmup=2)
    except Exception as e:  # noqa: BLE001 — proxy is best-effort
        print(json.dumps({
            "metric": "llama_cpu_proxy_train_tokens_per_sec",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "backend": "cpu-proxy", "error": "cpu-proxy-failed",
            "detail": str(e)[:300]}), flush=True)
        return
    tokens_per_sec = meas["batch"] * meas["seq"] / meas["step_time_s"]
    payload = {
        "metric": "llama_cpu_proxy_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "backend": "cpu-proxy",
        "tpu_probe_error": probe_err,
        "n_params_measured": meas["n_params"],
    }
    _emit(payload, {"backend": "cpu-proxy", "measured": meas,
                    "note": "TPU unreachable; tiny-config CPU measurement "
                            "so the perf trajectory records a real number"})
    print(f"# cpu-proxy: {tokens_per_sec:.1f} tokens/s "
          f"(step={meas['step_time_s']*1000:.1f}ms, "
          f"params={meas['n_params']/1e6:.2f}M)", file=sys.stderr)


def main():
    config = os.environ.get("PT_BENCH_CONFIG", "7b_proxy")
    # Fail loud-but-parseable when the chip is unreachable: an explicit
    # error field distinguishes infra failure from a perf regression.
    # VERDICT r4 weak #1 contract: the error JSON is emitted (and flushed)
    # after <5 minutes of failed probes, BEFORE the patient retry phase, so
    # the driver's captured stdout parses no matter when it kills us.  If
    # the chip answers during the patient phase, the real measurement JSON
    # is printed afterwards as the final line, superseding the error line.
    if os.environ.get("PT_BENCH_SKIP_PROBE") != "1":
        history = []
        err = _probe_quick(history)
        if err is not None:
            print(json.dumps({
                "metric": f"llama_{config}_train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "tpu-unavailable",
                "detail": err,
            }), flush=True)
            _write_probe_history(history)
            budget = float(os.environ.get("PT_BENCH_PROBE_BUDGET_S", "1200"))
            err = _probe_patient(history, budget)
            _write_probe_history(history)
            if err is not None:
                # Degrade to a CPU mini-proxy instead of leaving only zeros:
                # the final JSON line supersedes the error line above with a
                # REAL measured number, clearly labeled "backend":
                # "cpu-proxy" so the relay never mistakes it for chip perf
                # but the perf trajectory stops flying blind.
                _cpu_proxy_fallback(err)
                return

    import jax

    if os.environ.get("PT_BENCH_FORCE_CPU") == "1":  # script-logic smoke test
        jax.config.update("jax_platforms", "cpu")

    dev = jax.devices()[0]
    peak, kind = _peak_tflops(dev)

    if config == "382m":
        cfg_kwargs = dict(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4128, num_attention_heads=16)
        candidates = [(10, 16, 1024)]
    elif config == "tiny":  # script-logic smoke config (CPU-safe)
        cfg_kwargs = dict(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_attention_heads=4)
        candidates = [(2, 2, 64)]
    else:  # 7b_proxy: true LLaMA-7B layer geometry, OOM-adaptive depth
        cfg_kwargs = dict(_LLAMA_7B)
        candidates = [(4, 2, 2048), (3, 2, 2048), (2, 2, 2048),
                      (2, 1, 2048), (1, 1, 2048)]

    # 7b_proxy defaults to the fused lm-head+CE Pallas kernel (skips the
    # [B*S, 32k] logits + cotangent buffers); PT_BENCH_FUSED_LOSS=0 reverts
    fused = (config == "7b_proxy"
             and os.environ.get("PT_BENCH_FUSED_LOSS", "1") == "1")
    meas = None
    oom_log = []
    for layers, batch, seq in candidates:
        try:
            meas = _build_and_time(cfg_kwargs, layers, batch, seq,
                                   fused_loss=fused)
            break
        except Exception as e:  # noqa: BLE001
            if _is_oom(e):
                oom_log.append(f"L={layers},B={batch}: OOM")
                print(f"# L={layers},B={batch},S={seq}: OOM, shrinking",
                      file=sys.stderr)
                continue
            raise
    if meas is None:
        print(json.dumps({
            "metric": f"llama_{config}_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": "oom-at-all-candidates", "detail": "; ".join(oom_log)}),
            flush=True)
        return

    h = cfg_kwargs["hidden_size"]
    dt = meas["step_time_s"]
    tokens_per_sec = meas["batch"] * meas["seq"] / dt
    flops = _flops_per_step(meas["n_params"], meas["layers"], meas["batch"],
                            meas["seq"], h)
    achieved = flops / dt / 1e12
    mfu = achieved / peak

    detail = {"device": kind, "peak_bf16_tflops": peak, "config": config,
              "fused_loss": fused,
              "measured": meas, "achieved_tflops": round(achieved, 2),
              "mfu": round(mfu, 4), "oom_log": oom_log}

    extrap = None
    if config == "7b_proxy" and meas["layers"] > 1:
        # two-point fit t(L) = a + b*L -> honest 32-layer extrapolation
        l2 = max(1, meas["layers"] // 2)
        try:
            meas2 = _build_and_time(cfg_kwargs, l2, meas["batch"],
                                    meas["seq"], n_steps=10,
                                    fused_loss=fused)
            b_fit = (dt - meas2["step_time_s"]) / (meas["layers"] - l2)
            a_fit = dt - b_fit * meas["layers"]
            t32 = a_fit + 32 * b_fit
            layer_params = ((meas["n_params"] - meas2["n_params"])
                            / (meas["layers"] - l2))
            n_7b = meas["n_params"] + (32 - meas["layers"]) * layer_params
            f32 = _flops_per_step(n_7b, 32, meas["batch"], meas["seq"], h)
            extrap = {
                "label": "EXTRAPOLATED (not measured): 32-layer LLaMA-7B "
                         "from linear two-point fit t(L)=a+b*L on one chip",
                "fit_points": {f"L{meas['layers']}": dt,
                               f"L{l2}": meas2["step_time_s"]},
                "fit_a_s": a_fit, "fit_b_s_per_layer": b_fit,
                "t32_s": t32, "n_params_7b": int(n_7b),
                "extrapolated_7b_mfu": round(f32 / t32 / 1e12 / peak, 4),
                "extrapolated_7b_tokens_per_sec":
                    round(meas["batch"] * meas["seq"] / t32, 1),
            }
            detail["extrapolated_7b"] = extrap
        except Exception as e:  # noqa: BLE001 — extrapolation is best-effort
            detail["extrapolation_error"] = str(e)[:300]

    payload = {
        "metric": f"llama_7b_proxy_L{meas['layers']}_train_tokens_per_sec_per_chip"
        if config == "7b_proxy"
        else f"llama_{meas['n_params']/1e6:.0f}M_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": round(mfu, 4),
        "n_params_measured": meas["n_params"],
        "attention_kernel": meas["attention_kernel"],
    }
    if extrap is not None:
        payload["extrapolated_7b_mfu"] = extrap["extrapolated_7b_mfu"]
    _emit(payload, detail if config != "tiny" else None)
    print(f"# device={kind} peak={peak}TFLOP/s "
          f"params={meas['n_params']/1e6:.1f}M L={meas['layers']} "
          f"B={meas['batch']} S={meas['seq']} step={dt*1000:.1f}ms "
          f"achieved={achieved:.1f}TFLOP/s mfu={mfu*100:.1f}% "
          f"kernel={meas['attention_kernel']} loss={meas['loss']:.3f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
