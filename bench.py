"""Benchmark: LLaMA causal-LM training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md), so vs_baseline is
reported against the driver-tracked north-star proxy: achieved model FLOPs
utilization (MFU) as a fraction of the 40% target on this chip.

Integrity (VERDICT r1 weak #5 / item 10):
- peak TFLOP/s derived from the attached device kind (not hard-coded),
- FLOP count includes attention (6*N*T + 12*L*B*S^2*H*D_head, causal x0.5),
- the metric name carries the real parameter count,
- the compiled step's HLO is inspected to report whether the Pallas flash
  kernel (tpu_custom_call) or plain XLA attention actually ran.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
_PEAK_BF16_TFLOPS = {
    "TPU v2": 46.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
    "TPU v6e": 918.0,
    "TPU v7": 4614.0,
}


def _peak_tflops(device) -> tuple[float, str]:
    kind = getattr(device, "device_kind", "") or ""
    for key, val in sorted(_PEAK_BF16_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(key):
            return val, kind
    return 197.0, f"{kind or 'unknown'} (assumed v5e peak)"


def _attention_kernel_provenance(step, batch) -> str:
    """Inspect the HLO of the EXACT benchmarked train step."""
    try:
        txt = step.lower_text(batch)
    except Exception as e:  # noqa: BLE001 — provenance is best-effort
        return f"lowering-failed({type(e).__name__})"
    if "tpu_custom_call" in txt or "mosaic" in txt.lower():
        return "pallas_flash_attention"
    return "xla_dot_attention"


def _probe_backend(attempts: int = 3, probe_timeout: int = 90,
                   backoff: int = 30) -> str | None:
    """Verify the accelerator backend can initialize, with bounded
    retry/backoff (VERDICT r2 item 2).

    A wedged remote-compile relay makes jax.devices() HANG rather than
    raise, so the probe runs in a child process under a timeout — the parent
    only initializes jax after a probe succeeds.  Returns None on success,
    else a short error string."""
    import subprocess

    last = "unknown"
    for i in range(attempts):
        if i:
            time.sleep(backoff)
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].device_kind)"],
                capture_output=True, text=True, timeout=probe_timeout)
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {probe_timeout}s"
            print(f"# probe {i + 1}/{attempts}: {last}", file=sys.stderr)
            continue
        if r.returncode == 0:
            return None
        last = (r.stderr or r.stdout).strip().splitlines()[-1:] or ["rc!=0"]
        last = last[0][-200:]
        print(f"# probe {i + 1}/{attempts}: {last}", file=sys.stderr)
    return last


def main():
    # Fail loud-but-parseable when the chip is unreachable: an explicit
    # error field distinguishes infra failure from a perf regression.
    err = _probe_backend()
    if err is not None:
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "tpu-unavailable",
            "detail": err,
        }))
        return

    import jax

    import paddle_tpu as P
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, build_hybrid_train_step

    dev = jax.devices()[0]
    peak, kind = _peak_tflops(dev)

    P.seed(0)
    # sized to use the chip's HBM with fp32 master params + AdamW moments
    # (~382M params -> ~5.4 GB states) while keeping compile time sane
    cfg = LlamaConfig(vocab_size=32000, hidden_size=1536, intermediate_size=4128,
                      num_hidden_layers=10, num_attention_heads=16,
                      max_position_embeddings=1024)
    seq = 1024
    batch = 16

    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, n_microbatches=1, remat=True,
                                   amp=True)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
    b = {"input_ids": P.to_tensor(ids[:, :-1]), "labels": P.to_tensor(ids[:, 1:])}

    kernel = _attention_kernel_provenance(step, b)

    last = {}

    def run_blocked(n):
        """Run n steps and force REAL completion by fetching a scalar that
        depends on the last step's parameter updates (block_until_ready on
        relayed buffers can return early in this environment; a 4-byte
        dependent fetch cannot)."""
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step(b)
        last["loss"] = float(loss.numpy())
        leaf = jax.tree_util.tree_leaves(step.state["params"])[0]
        _ = float(leaf[(0,) * leaf.ndim])  # device-side index, tiny transfer
        return time.perf_counter() - t0

    # warmup (compile + steady state)
    run_blocked(3)

    n_steps = 20
    dt = min(run_blocked(n_steps), run_blocked(n_steps)) / n_steps

    tokens_per_sec = batch * seq / dt

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # 6ND matmul FLOPs + causal attention FLOPs:
    # fwd attention = 4*B*S^2*H*Dh per layer (QK^T and PV), x3 for fwd+bwd,
    # x0.5 causal sparsity
    tokens = batch * seq
    matmul_flops = 6.0 * n_params * tokens
    attn_flops = (12.0 * cfg.num_hidden_layers * batch * seq * seq
                  * cfg.hidden_size * 0.5)
    flops_per_step = matmul_flops + attn_flops
    achieved_tflops = flops_per_step / dt / 1e12
    mfu = achieved_tflops / peak
    vs_baseline = mfu / 0.40  # fraction of the 40%-MFU north-star

    print(json.dumps({
        "metric": f"llama_{n_params/1e6:.0f}M_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))
    # extra context on stderr for humans
    print(f"# device={kind} peak={peak}TFLOP/s params={n_params/1e6:.1f}M "
          f"step={dt*1000:.1f}ms achieved={achieved_tflops:.1f}TFLOP/s "
          f"(matmul {matmul_flops/dt/1e12:.1f} + attn {attn_flops/dt/1e12:.1f}) "
          f"mfu={mfu*100:.1f}% attention_kernel={kernel} "
          f"loss={last['loss']:.3f}", file=sys.stderr)


if __name__ == "__main__":
    main()
