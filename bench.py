"""Benchmark: LLaMA causal-LM training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md), so vs_baseline is
reported against the driver-tracked north-star proxy: achieved model FLOPs
utilization (MFU) fraction of the 40% target on this chip.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as P
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, build_hybrid_train_step

    P.seed(0)
    # a single-chip-sized LLaMA (fits v5e HBM with fp32 master params + Adam)
    cfg = LlamaConfig(vocab_size=32000, hidden_size=1024, intermediate_size=2752,
                      num_hidden_layers=8, num_attention_heads=16,
                      max_position_embeddings=1024)
    seq = 1024
    batch = 16

    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, n_microbatches=1, remat=True,
                                   amp=True)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
    b = {"input_ids": P.to_tensor(ids[:, :-1]), "labels": P.to_tensor(ids[:, 1:])}

    import jax as _jax

    last = {}

    def run_blocked(n):
        """Run n steps and force REAL completion by fetching a scalar that
        depends on the last step's parameter updates (block_until_ready on
        relayed buffers can return early in this environment; a 4-byte
        dependent fetch cannot)."""
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step(b)
        last["loss"] = float(loss.numpy())
        leaf = _jax.tree_util.tree_leaves(step.state["params"])[0]
        _ = float(leaf[(0,) * leaf.ndim])  # device-side index, tiny transfer
        return time.perf_counter() - t0

    # warmup (compile + steady state)
    run_blocked(3)

    n_steps = 30
    dt = min(run_blocked(n_steps), run_blocked(n_steps)) / n_steps

    tokens_per_sec = batch * seq / dt

    # param count & rough train FLOPs (6 * N * tokens, PaLM-style)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_step = 6.0 * n_params * batch * seq
    achieved_tflops = flops_per_step / dt / 1e12
    # v5e peak ~197 TFLOP/s bf16, ~98 fp32; use bf16 peak as the MFU denom
    mfu = achieved_tflops / 197.0
    vs_baseline = mfu / 0.40  # fraction of the 40%-MFU north-star

    print(json.dumps({
        "metric": "llama_1b-ish_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))
    # extra context on stderr for humans
    import sys
    print(f"# params={n_params/1e6:.1f}M step={dt*1000:.1f}ms "
          f"achieved={achieved_tflops:.1f}TFLOP/s mfu={mfu*100:.1f}% "
          f"loss={last['loss']:.3f}", file=sys.stderr)


if __name__ == "__main__":
    main()
