"""Quantized-collectives bench: wire-bytes reduction + loss-curve parity.

The acceptance artifact for the comms subsystem (distributed/comms) on the
dp gradient-sync path of the llama CPU proxy, dp2 virtual mesh:

  wire reduction  — build the TrainStep inside ``comms.quantized("int8")``
                    and read the CommOp accounting: the trainer.grad_sync
                    site's logical bytes (what fp32 sync would move) over
                    its wire bytes (int8 payload + per-block fp32 scales,
                    EQuARX two-shot).  Headline: >= 3.5x at int8.  This is
                    deterministic accounting of the quantized program's
                    actual wire format, not a timing — CPU has no ICI to
                    time honestly.  Proxy caveat (recorded in ROADMAP):
                    grads reach the hook already GSPMD-reduced, so the
                    partitioner's fp32 all-reduce still runs in this
                    program; the ratio compares the QUANTIZED SYNC's wire
                    format against the fp32 sync it is designed to
                    replace.  Retiring the implicit reduction (per-shard
                    grads under shard_map) is the named next layer.
  loss parity     — the SAME proxy trained spec-off twice (bitwise-equal
                    loss curves: the comms hook off-path adds zero
                    equations) and spec-on once (final loss within
                    tolerance of off: the wire round-trip error does not
                    derail optimization).

Prints ONE JSON line:
  {"metric": "comm_wire_reduction_int8", "value": <x>, "unit": "x",
   "vs_baseline": <value/3.5>, "loss_parity": true, "bitwise_off": true,
   ...}
and writes a BENCH_SELF_COMMS_<ts>.json artifact with the per-site
accounting, the capture pass comm report, and both loss curves.

Env: PT_COMM_BENCH_STEPS (default 30), PT_COMM_BENCH_TOL (rel final-loss
tolerance, default 0.05).
"""
from __future__ import annotations

import json
import os
import sys
import time

# dp2 needs 2 virtual CPU devices BEFORE any jax backend query
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + \
        " --xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as P  # noqa: E402
from paddle_tpu.distributed import comms  # noqa: E402
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402
from paddle_tpu.parallel import mesh as mesh_mod  # noqa: E402
from paddle_tpu.parallel.trainer import compile_train_step  # noqa: E402

BATCH, SEQ = 8, 32
ACCEPT_FLOOR = 3.5


def _loss_fn(model, batch):
    return model.compute_loss(batch["input_ids"], batch["labels"])


def _build_batch(cfg):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (BATCH, SEQ + 1))
    return {"input_ids": P.to_tensor(ids[:, :-1]),
            "labels": P.to_tensor(ids[:, 1:])}


def _run(steps: int, quant: bool):
    """Fresh identically-seeded model + TrainStep on a dp2 mesh; returns
    (loss curve, captured pass report or None)."""
    mesh = mesh_mod.init_mesh({"dp": 2}, devices=jax.devices()[:2])
    P.seed(0)
    cfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                           inter=128, seq=SEQ)
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
    step = compile_train_step(model, _loss_fn, opt, mesh=mesh)
    batch = _build_batch(cfg)
    losses = []

    def drive():
        for _ in range(steps):
            losses.append(float(step(batch).numpy()))

    if quant:
        with comms.quantized("int8"):
            drive()
    else:
        drive()
    rep = None
    if step.captured_program is not None:
        rep = step.captured_program.pass_report.as_dict()
    return losses, rep


def main() -> dict:
    steps = int(os.environ.get("PT_COMM_BENCH_STEPS", "30"))
    tol = float(os.environ.get("PT_COMM_BENCH_TOL", "0.05"))

    # --- bitwise-off leg: two identical runs, context off ---
    off_a, _ = _run(steps, quant=False)
    off_b, _ = _run(steps, quant=False)
    bitwise_off = off_a == off_b

    # --- quantized leg (fresh registry so the accounting is this run's) ---
    comms.comm_clear()
    on, pass_report = _run(steps, quant=True)

    # regression (review): the routed PUBLIC global-view collective must
    # work inside the context — the pass-through shard_map needs
    # check_vma=False once the body is the quantized two-shot
    import paddle_tpu.distributed as dist
    with comms.quantized("int8"):
        t = P.to_tensor(np.ones(600, np.float32))
        dist.all_reduce(t)  # replicated over dp2: psum -> ~2.0 everywhere
    assert np.allclose(np.asarray(t._value), 2.0, atol=0.05), \
        np.asarray(t._value)[:4]

    info = comms.comm_info()
    sync_sites = {k: v for k, v in info["sites"].items()
                  if k.startswith("trainer.grad_sync/")}
    logical = sum(s["bytes_logical"] for s in sync_sites.values())
    wire = sum(s["bytes_wire"] for s in sync_sites.values())
    reduction = logical / max(wire, 1)

    rel_gap = abs(on[-1] - off_a[-1]) / max(abs(off_a[-1]), 1e-9)
    parity = rel_gap <= tol and bool(np.isfinite(on[-1]))

    from paddle_tpu import profiler
    print(profiler.comm_summary(), file=sys.stderr)
    print(f"# off final {off_a[-1]:.6f}  on final {on[-1]:.6f}  "
          f"rel gap {rel_gap:.2e}", file=sys.stderr)

    payload = {
        "metric": "comm_wire_reduction_int8",
        "value": round(reduction, 3),
        "unit": "x",
        # acceptance floor: >= 3.5x smaller wire bytes on the dp grad sync
        "vs_baseline": round(reduction / ACCEPT_FLOOR, 4),
        "loss_parity": parity,
        "bitwise_off": bitwise_off,
        "final_loss_off": round(off_a[-1], 6),
        "final_loss_on": round(on[-1], 6),
        "rel_final_gap": round(rel_gap, 6),
        "steps": steps,
        "grad_sync_bytes_logical": logical,
        "grad_sync_bytes_wire": wire,
    }
    print(json.dumps(payload), flush=True)

    ts = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BENCH_SELF_COMMS_{ts}.json")
    detail = {
        "config": {"batch": BATCH, "seq": SEQ, "mesh": "dp2",
                   "block": comms.quant_state().block,
                   "platform": jax.devices()[0].platform},
        "sites": info["sites"],
        "pass_report": pass_report,
        "loss_curve_off": [round(x, 6) for x in off_a],
        "loss_curve_on": [round(x, 6) for x in on],
    }
    try:
        with open(path, "w") as f:
            json.dump({**payload, "detail": detail}, f, indent=1)
        print(f"# artifact -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"# artifact write failed: {e}", file=sys.stderr)
    return payload


if __name__ == "__main__":
    main()
