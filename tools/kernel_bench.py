"""Pallas-vs-XLA kernel A/B gate (VERDICT r3 item 4 / r4 item 1c).

Times each hand-written Pallas kernel against the straightforward jnp/XLA
formulation of the same math, steady-state under jit on the attached device.
The acceptance gate (reference analog: tools/ci_op_benchmark.sh's relative
regression gate) is speedup >= --gate (default 1.2x) for every kernel on
TPU hardware; on CPU the Pallas kernels run in interpret mode, so the run
is recorded as informational (gate not applied).

Usage:
    python tools/kernel_bench.py                     # table + one JSON line
    python tools/kernel_bench.py --save KERNEL_BENCH_<dev>.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_fn(fn, *args, n=20, warmup=3):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / n)
    return best


def _cases(on_tpu: bool):
    """Yields (name, pallas_fn, xla_fn, args, grad) A/B pairs.

    Shapes are bench-scale on TPU, miniature on CPU (interpret mode is
    ~1000x slower; CPU runs only prove the harness).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas.decode_attention import ragged_decode_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    from paddle_tpu.ops.pallas.fused_ce import fused_linear_cross_entropy

    rng = np.random.RandomState(0)

    def arr(*shape, dtype=jnp.bfloat16):
        return jnp.asarray(rng.randn(*shape), dtype=dtype)

    # --- flash attention: [B, S, H, D] causal self-attention fwd+bwd ------
    B, S, H, D = (4, 2048, 16, 128) if on_tpu else (1, 128, 2, 8)
    q, k, v = arr(B, S, H, D), arr(B, S, H, D), arr(B, S, H, D)

    def xla_attn(q, k, v):
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def grad_wrap(f):
        def loss(q, k, v):
            return jnp.sum(f(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    yield ("flash_attention_fwd",
           jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True)),
           jax.jit(xla_attn), (q, k, v))
    yield ("flash_attention_grad",
           grad_wrap(lambda q, k, v: flash_attention(q, k, v, causal=True)),
           grad_wrap(xla_attn), (q, k, v))

    # --- fused lm-head + CE: [N, H] x [H, V] -> scalar loss fwd+bwd -------
    N, Hd, V = (4096, 4096, 32000) if on_tpu else (32, 64, 256)
    h = arr(N, Hd)
    w = arr(Hd, V)
    labels = jnp.asarray(rng.randint(0, V, (N,)), jnp.int32)

    def xla_ce(h, w, labels):
        logits = (h @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
        return lse - gold  # per-row, matching the pallas kernel's output

    yield ("fused_linear_ce_fwd",
           jax.jit(fused_linear_cross_entropy), jax.jit(xla_ce),
           (h, w, labels))
    yield ("fused_linear_ce_grad",
           jax.jit(jax.grad(
               lambda h, w, l: jnp.mean(fused_linear_cross_entropy(h, w, l)),
               argnums=(0, 1))),
           jax.jit(jax.grad(
               lambda h, w, l: jnp.mean(xla_ce(h, w, l)), argnums=(0, 1))),
           (h, w, labels))

    # --- ragged decode attention: [B, 1, H, D] q vs [B, Smax, H, D] cache -
    B2, Smax, H2, D2 = (32, 4096, 16, 128) if on_tpu else (2, 128, 2, 8)
    q1 = arr(B2, 1, H2, D2)
    kc, vc = arr(B2, Smax, H2, D2), arr(B2, Smax, H2, D2)
    lengths = jnp.asarray(
        rng.randint(Smax // 8, Smax, (B2,)), jnp.int32)

    def xla_decode(q1, kc, vc, lengths):
        scale = 1.0 / (q1.shape[-1] ** 0.5)
        s = jnp.einsum("bqhd,bshd->bhqs", q1, kc).astype(jnp.float32) * scale
        mask = (jnp.arange(kc.shape[1])[None, None, None, :]
                < lengths[:, None, None, None])
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(q1.dtype)
        return jnp.einsum("bhqs,bshd->bqhd", p, vc)

    yield ("ragged_decode_attention",
           jax.jit(ragged_decode_attention), jax.jit(xla_decode),
           (q1, kc, vc, lengths))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", default=None)
    ap.add_argument("--gate", type=float, default=1.2,
                    help="required pallas/xla speedup on TPU")
    ap.add_argument("--n", type=int, default=20)
    args = ap.parse_args()

    import jax

    # sitecustomize registers the axon PJRT plugin and overrides
    # jax_platforms; honor a JAX_PLATFORMS=cpu request via jax.config (the
    # env var alone is captured too early — see tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"  # off-TPU the kernels self-select
    # pallas interpret mode (ops/pallas/_common.py:_interpret), so CPU runs
    # prove the harness but are not gated.

    results = []
    for name, pall, xla, fargs in _cases(on_tpu):
        try:
            t_p = _time_fn(pall, *fargs, n=args.n)
            t_x = _time_fn(xla, *fargs, n=args.n)
            speedup = t_x / t_p
            results.append({"kernel": name,
                            "pallas_ms": round(t_p * 1e3, 4),
                            "xla_ms": round(t_x * 1e3, 4),
                            "speedup": round(speedup, 3),
                            "passes_gate": bool(speedup >= args.gate)})
            print(f"# {name}: pallas={t_p*1e3:.3f}ms xla={t_x*1e3:.3f}ms "
                  f"speedup={speedup:.2f}x", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — record, keep measuring
            results.append({"kernel": name, "error": str(e)[:300]})
            print(f"# {name}: FAILED {e}", file=sys.stderr)

    gated = [r for r in results if "speedup" in r]
    payload = {
        "device": getattr(dev, "device_kind", dev.platform),
        "platform": dev.platform,
        "gate": args.gate,
        "gate_applied": on_tpu,
        "all_pass": bool(on_tpu and gated
                         and all(r["passes_gate"] for r in gated)),
        "results": results,
    }
    print(json.dumps(payload), flush=True)
    if args.save:
        with open(args.save, "w") as f:
            json.dump(payload, f, indent=1)
    if on_tpu and not payload["all_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
