"""Pipeline-schedule comparison artifact (VERDICT r2 weak #3 / r3 item 5).

Times one full training step (loss + grads) under three schedules on the
same stage model and mesh:
  - gpipe:        forward scan + AD backward
  - 1f1b fused:   fused-round schedule (steady state = unconditional fwd+bwd
                  per round, no dispatch branch)
  - 1f1b compact: tick-switch schedule (tightest min(S,M) stash)

Run on the CPU mesh the numbers are ratios, not absolutes — single-chip
hardware cannot host a pp>1 mesh, so the wall-time RATIO at compute-bound
stage sizes is the decision artifact (the per-tick dispatch overhead being
measured is platform-independent program structure). The FLOP ratio is the
deterministic check that neither 1F1B variant burns redundant compute
(cost_analysis sums cond branches, so fused's edge conds over-count a
little — wall time is the metric that matters).

Usage: python tools/schedule_bench.py [--pp 4] [--mb 8] [--h 256] [--rows 32]
    -> one JSON line on stdout (also written to SCHEDULE_BENCH.json when
       --save is passed).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"  # hard override: the env may pin a
# (possibly wedged) accelerator platform via JAX_PLATFORMS
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# sitecustomize may have imported jax before this script ran, in which case
# the env var was already captured — pin the platform via config too
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def build(pp=4, M=8, mb=8, h=256):
    """Stage = one matmul+tanh over an (mb, h) microbatch; h is sized so the
    matmul dominates and per-tick dispatch shows up as a ratio, not noise."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.pipeline import spmd_pipeline, spmd_pipeline_1f1b

    dist.init_parallel_env({"pp": pp})
    mesh = mesh_mod.get_mesh()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(pp, h, h).astype(np.float32) * 0.1),
              "b": jnp.asarray(rng.randn(pp, h).astype(np.float32) * 0.1)}
    head = {"wo": jnp.asarray(rng.randn(h, h).astype(np.float32) * 0.1)}
    x = jnp.asarray(rng.randn(M, mb, h).astype(np.float32))
    labels = jnp.asarray(rng.randn(M, mb, h).astype(np.float32))

    def stage_fn(p, v):
        return jnp.tanh(v @ p["w"][0] + p["b"][0])

    def head_loss(hp, y, lab):
        return jnp.mean((y @ hp["wo"] - lab) ** 2)

    def gpipe_step(params, head, x, labels):
        def loss(params, head):
            y = spmd_pipeline(stage_fn, params, x, n_microbatches=M,
                              mesh=mesh, schedule="gpipe")
            per = [head_loss(head, y[m], labels[m]) for m in range(M)]
            return sum(per) / M
        return jax.value_and_grad(loss, argnums=(0, 1))(params, head)

    def f1b_step(variant):
        def step(params, head, x, labels):
            loss, gs, gh, _ = spmd_pipeline_1f1b(
                stage_fn, head_loss, params, head, x, labels,
                n_microbatches=M, mesh=mesh, variant=variant)
            return loss, (gs, gh)
        return step

    raw = dict(gpipe=gpipe_step, f1b_fused=f1b_step("fused"),
               f1b_compact=f1b_step("compact"))
    return {k: jax.jit(v) for k, v in raw.items()}, raw, \
        (params, head, x, labels)


def measure(fn, args, iters=20):
    compiled = fn.lower(*args).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops = float(cost.get("flops", float("nan")))
    out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    loss = float(jax.tree_util.tree_leaves(out)[0])
    return flops, dt, loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--mb", type=int, default=8, help="microbatches M")
    ap.add_argument("--h", type=int, default=256)
    ap.add_argument("--rows", type=int, default=32, help="rows per microbatch")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--save", help="also write JSON to this path")
    args = ap.parse_args()

    fns, raw, fargs = build(pp=args.pp, M=args.mb, mb=args.rows, h=args.h)
    from paddle_tpu.jit.passes import comm_schedule as _cs
    res = {}
    losses = {}
    for name, fn in fns.items():
        f, t, l = measure(fn, fargs, iters=args.iters)
        res[name] = {"flops": f, "step_ms": round(t * 1e3, 2)}
        # comm-volume + overlap-slot columns: the schedule's collective
        # equations as the capture-tier comm pass sees them (GC3-style
        # accounting — count, payload bytes, concurrently-issuable slots)
        try:
            res[name]["comm"] = _cs.analyze(jax.make_jaxpr(raw[name])(*fargs))
        except Exception as e:  # noqa: BLE001 — columns are best-effort
            res[name]["comm"] = {"error": str(e)[:120]}
        losses[name] = l
    for name, l in losses.items():
        assert abs(l - losses["gpipe"]) < 1e-5 * max(1.0, abs(losses["gpipe"])), \
            (name, l, losses["gpipe"])
    out = {
        "config": {"pp": args.pp, "microbatches": args.mb, "h": args.h,
                   "rows_per_microbatch": args.rows,
                   "platform": jax.devices()[0].platform},
        **res,
        "time_ratio_fused_over_gpipe":
            round(res["f1b_fused"]["step_ms"] / res["gpipe"]["step_ms"], 3),
        "time_ratio_compact_over_gpipe":
            round(res["f1b_compact"]["step_ms"] / res["gpipe"]["step_ms"], 3),
        "flops_ratio_compact_over_gpipe":
            round(res["f1b_compact"]["flops"] / res["gpipe"]["flops"], 3),
        "loss_parity": True,
        "stash_microbatches": {
            "gpipe": args.mb + args.pp - 1,
            "1f1b_fused": min(2 * args.pp - 1, args.mb),
            "1f1b_compact": min(args.pp, args.mb)},
    }
    print(json.dumps(out))
    if args.save:
        with open(args.save, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
