"""Pipeline-schedule comparison artifact (VERDICT r2 weak #3 / item 3).

Times one full training step (loss + grads) under gpipe (forward scan + AD
backward) vs the manually-scheduled 1F1B program on the same stage model and
mesh, and reports XLA-analyzed FLOPs for both. Run on the CPU mesh the
numbers are ratios, not absolutes — the FLOP ratio is the deterministic
check that 1F1B no longer burns redundant compute, the time ratio is
corroboration.

Usage: python tools/schedule_bench.py  -> one JSON line on stdout.
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"  # hard override: the env may pin a
# (possibly wedged) accelerator platform via JAX_PLATFORMS
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import jax  # noqa: E402

# sitecustomize may have imported jax before this script ran, in which case
# the env var was already captured — pin the platform via config too
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def build(pp=4, M=6, mb=2, h=64):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.pipeline import spmd_pipeline, spmd_pipeline_1f1b

    dist.init_parallel_env({"pp": pp})
    mesh = mesh_mod.get_mesh()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(pp, h, h).astype(np.float32) * 0.1),
              "b": jnp.asarray(rng.randn(pp, h).astype(np.float32) * 0.1)}
    head = {"wo": jnp.asarray(rng.randn(h, h).astype(np.float32) * 0.1)}
    x = jnp.asarray(rng.randn(M, mb, h).astype(np.float32))
    labels = jnp.asarray(rng.randn(M, mb, h).astype(np.float32))

    def stage_fn(p, v):
        return jnp.tanh(v @ p["w"][0] + p["b"][0])

    def head_loss(hp, y, lab):
        return jnp.mean((y @ hp["wo"] - lab) ** 2)

    def gpipe_step(params, head, x, labels):
        def loss(params, head):
            y = spmd_pipeline(stage_fn, params, x, n_microbatches=M,
                              mesh=mesh, schedule="gpipe")
            per = [head_loss(head, y[m], labels[m]) for m in range(M)]
            return sum(per) / M
        return jax.value_and_grad(loss, argnums=(0, 1))(params, head)

    def f1b_step(params, head, x, labels):
        loss, gs, gh, _ = spmd_pipeline_1f1b(
            stage_fn, head_loss, params, head, x, labels,
            n_microbatches=M, mesh=mesh)
        return loss, (gs, gh)

    return dict(gpipe=jax.jit(gpipe_step), f1b=jax.jit(f1b_step)), \
        (params, head, x, labels)


def measure(fn, args, iters=10):
    compiled = fn.lower(*args).compile()
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, list) else cost
    flops = float(cost.get("flops", float("nan")))
    out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    loss = float(jax.tree_util.tree_leaves(out)[0])
    return flops, dt, loss


def main():
    fns, args = build()
    f_g, t_g, l_g = measure(fns["gpipe"], args)
    f_1, t_1, l_1 = measure(fns["f1b"], args)
    assert abs(l_g - l_1) < 1e-5 * max(1.0, abs(l_g)), (l_g, l_1)
    print(json.dumps({
        "gpipe": {"flops": f_g, "step_ms": round(t_g * 1e3, 2)},
        "1f1b": {"flops": f_1, "step_ms": round(t_1 * 1e3, 2)},
        "flops_ratio_1f1b_over_gpipe": round(f_1 / f_g, 3),
        "time_ratio_1f1b_over_gpipe": round(t_1 / t_g, 3),
        "loss_parity": True,
    }))


if __name__ == "__main__":
    main()
