"""Opportunistic on-chip benchmark capture (VERDICT r3 item 1a).

The TPU relay in this environment wedges for hours at a time; a single
capture attempt at round end has now failed two rounds running.  This
watcher runs in the background for the whole round: every few minutes it
probes the backend in a subprocess (a wedged relay HANGS jax.devices(), so
in-process probing is unsafe), and the first time the chip answers it runs
the full benchmark battery and commits the artifacts:

  1. bench.py (7B-proxy config)      -> BENCH_SELF_<ts>.json
  2. tools/op_benchmark.py --save    -> OPBENCH_<device>.json

On success it commits the artifacts and exits; on a mid-battery relay death
it keeps looping.  Usage: python tools/bench_watcher.py [--interval 300]
"""
from __future__ import annotations

import argparse
import datetime
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg: str):
    ts = datetime.datetime.now().strftime("%H:%M:%S")
    print(f"[{ts}] {msg}", flush=True)


def probe(timeout=90) -> str | None:
    """Returns device kind on success, None when the backend is unreachable."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    kind = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    return kind or None


def run_battery(kind: str) -> bool:
    """Run the full bench battery. True if the headline bench succeeded."""
    env = dict(os.environ, PT_BENCH_SKIP_PROBE="1", PT_BENCH_CONFIG="7b_proxy")
    log(f"chip answered ({kind}) — running bench.py 7b_proxy")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=3600,
                       cwd=REPO)
    log(f"bench.py rc={r.returncode}\nstdout: {r.stdout}\nstderr: {r.stderr[-2000:]}")
    ok = r.returncode == 0 and '"error"' not in r.stdout
    if not ok:
        return False

    kind_slug = kind.replace(" ", "_").replace("/", "_")
    opb = os.path.join(REPO, f"OPBENCH_{kind_slug}.json")
    try:
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "op_benchmark.py"),
             "--save", opb],
            capture_output=True, text=True, timeout=1800, cwd=REPO)
        log(f"op_benchmark rc={r2.returncode}\n{r2.stdout[-2000:]}\n{r2.stderr[-1000:]}")
    except subprocess.TimeoutExpired:
        log("op_benchmark timed out (relay died mid-run?)")
    return True


def commit_artifacts():
    arts = (glob.glob(os.path.join(REPO, "BENCH_SELF_*.json"))
            + glob.glob(os.path.join(REPO, "OPBENCH_*.json")))
    if not arts:
        return
    subprocess.run(["git", "add", "--"] + arts, cwd=REPO, check=False)
    msg = ("Record on-chip benchmark artifacts (7B-proxy MFU + op baseline)"
           "\n\nNo-Verification-Needed: artifact-only data capture")
    # pathspec-limited commit: never sweep up unrelated staged work
    r = subprocess.run(["git", "commit", "-m", msg, "--"] + arts,
                       cwd=REPO, check=False, capture_output=True, text=True)
    log(f"artifact commit rc={r.returncode} {r.stdout.strip()[-200:]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--once", action="store_true",
                    help="single probe+battery attempt, no loop")
    args = ap.parse_args()

    while True:
        kind = probe()
        if kind is None:
            log("backend unreachable")
        else:
            try:
                if run_battery(kind):
                    commit_artifacts()
                    log("capture complete — exiting")
                    return
            except Exception as e:  # noqa: BLE001 — keep the watch alive
                log(f"battery failed: {e}")
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
