"""Opportunistic on-chip benchmark capture (VERDICT r3 item 1a, r4 item 1).

The TPU relay in this environment wedges for hours at a time; a single
capture attempt at round end has failed three rounds running.  This watcher
runs in the background for the whole round: every few minutes it probes the
backend in a subprocess (a wedged relay HANGS jax.devices(), so in-process
probing is unsafe), records every probe in a committed timeline artifact
(BENCH_ATTEMPTS_r<N>.json — r4 weak #3: unavailability must be a recorded
fact, not a claim), and the first time the chip answers it runs the full
battery in one relay window (r4 item 1c):

  1. bench.py (7B-proxy config)        -> BENCH_SELF_<ts>.json
  2. tools/op_benchmark.py --save      -> OPBENCH_<device>.json
  3. tools/kernel_bench.py --save      -> KERNEL_BENCH_<device>.json
  4. tools/schedule_bench.py --save    -> SCHEDULE_BENCH.json (CPU ratios)

On success it commits the artifacts and exits; on a mid-battery relay death
it keeps looping.  Usage: python tools/bench_watcher.py [--interval 300]
"""
from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg: str):
    ts = datetime.datetime.now().strftime("%H:%M:%S")
    print(f"[{ts}] {msg}", flush=True)


def detect_round() -> int:
    """Current round = max committed BENCH_r<N>.json + 1 (driver writes one
    per completed round)."""
    rounds = [int(m.group(1)) for f in glob.glob(
        os.path.join(REPO, "BENCH_r*.json"))
        if (m := re.search(r"BENCH_r0*(\d+)\.json$", f))]
    return (max(rounds) + 1) if rounds else 1


ATTEMPTS_PATH = os.path.join(REPO, f"BENCH_ATTEMPTS_r{detect_round():02d}.json")


def probe(timeout=90) -> str | None:
    """Returns device kind on success, None when the backend is unreachable."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    kind = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    return kind or None


class AttemptLog:
    """Probe-timeline artifact: written on every probe, committed every
    `commit_every` probes and on battery completion."""

    def __init__(self, commit_every: int = 12):
        self.probes: list[dict] = []
        self.commit_every = commit_every
        if os.path.exists(ATTEMPTS_PATH):  # resume within the same round
            try:
                with open(ATTEMPTS_PATH) as f:
                    self.probes = json.load(f).get("probes", [])
            except (OSError, ValueError):
                pass

    def record(self, kind: str | None):
        self.probes.append({
            "ts": datetime.datetime.now().isoformat(timespec="seconds"),
            "ok": kind is not None,
            "device_kind": kind})
        self.write()
        if len(self.probes) % self.commit_every == 0:
            commit([ATTEMPTS_PATH],
                   f"Record TPU probe timeline ({len(self.probes)} probes, "
                   f"{sum(p['ok'] for p in self.probes)} reachable)"
                   "\n\nNo-Verification-Needed: artifact-only data capture")

    def write(self):
        ok = sum(p["ok"] for p in self.probes)
        try:
            with open(ATTEMPTS_PATH, "w") as f:
                json.dump({"n_probes": len(self.probes), "n_ok": ok,
                           "probes": self.probes}, f, indent=1)
        except OSError as e:
            log(f"attempts write failed: {e}")


def commit(paths: list[str], msg: str):
    """Pathspec-limited commit that FAILS LOUDLY (ADVICE r4 #4): rc is
    checked, a failed commit is retried once, and a second failure is
    logged as an error so artifacts are never silently lost (they remain on
    disk either way — the round-end driver sweep commits leftovers)."""
    paths = [p for p in paths if os.path.exists(p)]
    if not paths:
        return
    for attempt in (1, 2):
        subprocess.run(["git", "add", "--"] + paths, cwd=REPO, check=False)
        r = subprocess.run(["git", "commit", "-m", msg, "--"] + paths,
                           cwd=REPO, check=False, capture_output=True,
                           text=True)
        out = (r.stdout + r.stderr).strip()
        if r.returncode == 0:
            log(f"committed {len(paths)} artifact(s): {out.splitlines()[0][:120]}")
            return
        if "nothing to commit" in out or "no changes added" in out:
            return
        log(f"ERROR commit attempt {attempt} rc={r.returncode}: {out[-300:]}")
        time.sleep(2)
    log(f"ERROR artifacts NOT committed (left on disk): {paths}")


def _run(cmd: list[str], timeout: int, env=None) -> subprocess.CompletedProcess:
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO)


def run_battery(kind: str) -> bool:
    """Run the full bench battery in one relay window.  True if the
    headline bench succeeded; auxiliary benches are best-effort."""
    env = dict(os.environ, PT_BENCH_SKIP_PROBE="1", PT_BENCH_CONFIG="7b_proxy")
    log(f"chip answered ({kind}) — running bench.py 7b_proxy")
    r = _run([sys.executable, os.path.join(REPO, "bench.py")], 3600, env)
    log(f"bench.py rc={r.returncode}\nstdout: {r.stdout}\nstderr: {r.stderr[-2000:]}")
    ok = r.returncode == 0 and '"error"' not in r.stdout
    if not ok:
        return False

    kind_slug = kind.replace(" ", "_").replace("/", "_")
    aux = [
        ("op_benchmark",
         [sys.executable, os.path.join(REPO, "tools", "op_benchmark.py"),
          "--save", os.path.join(REPO, f"OPBENCH_{kind_slug}.json")], 1800),
        ("kernel_bench",
         [sys.executable, os.path.join(REPO, "tools", "kernel_bench.py"),
          "--save", os.path.join(REPO, f"KERNEL_BENCH_{kind_slug}.json")],
         1800),
        ("schedule_bench",
         [sys.executable, os.path.join(REPO, "tools", "schedule_bench.py"),
          "--save"], 1800),
    ]
    for name, cmd, tmo in aux:
        try:
            r2 = _run(cmd, tmo)
            log(f"{name} rc={r2.returncode}\n{r2.stdout[-2000:]}\n{r2.stderr[-1000:]}")
        except subprocess.TimeoutExpired:
            log(f"{name} timed out (relay died mid-run?)")
    return True


def commit_artifacts():
    arts = (glob.glob(os.path.join(REPO, "BENCH_SELF_*.json"))
            + glob.glob(os.path.join(REPO, "OPBENCH_*.json"))
            + glob.glob(os.path.join(REPO, "KERNEL_BENCH_*.json"))
            + glob.glob(os.path.join(REPO, "BENCH_ATTEMPTS_r*.json"))
            + [os.path.join(REPO, "SCHEDULE_BENCH.json")])
    commit(arts, "Record on-chip benchmark artifacts "
                 "(7B-proxy MFU + op baseline + kernel A/B)"
                 "\n\nNo-Verification-Needed: artifact-only data capture")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--once", action="store_true",
                    help="single probe+battery attempt, no loop")
    args = ap.parse_args()

    attempts = AttemptLog()
    log(f"watcher up: round artifact {os.path.basename(ATTEMPTS_PATH)}, "
        f"{len(attempts.probes)} prior probes")
    while True:
        kind = probe()
        attempts.record(kind)
        if kind is None:
            log("backend unreachable")
        else:
            try:
                if run_battery(kind):
                    commit_artifacts()
                    log("capture complete — exiting")
                    return
            except Exception as e:  # noqa: BLE001 — keep the watch alive
                log(f"battery failed: {e}")
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
