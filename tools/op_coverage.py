"""Enumerate the ops the model zoo actually executes (VERDICT r3 item 4).

Installs a recorder on the dispatch layer, drives representative eager
forward+backward passes (LLaMA train step, ResNet forward+loss, BERT-style
transformer encoder, detection/vision ops, common optimizer updates), and
writes OP_COVERAGE.json: {op_name: call_count}, ordered by count.

The dtype-sweep battery (tests/test_op_dtype_sweep.py) is required by
tests/test_op_dtype_sweep.py::test_top_ops_covered to cover the top ops of
this enumeration, so coverage claims are data-driven, not hand-curated.

Usage: python tools/op_coverage.py [-o OP_COVERAGE.json]
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def drive():
    import paddle_tpu as P
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops import dispatch

    counts = collections.Counter()
    dispatch.set_coverage_recorder(lambda name: counts.update((name,)))

    try:
        rng = np.random.RandomState(0)
        P.seed(0)

        # --- LLaMA causal-LM eager train step (the north-star workload) ---
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4,
                               inter=64)
        model = LlamaForCausalLM(cfg)
        opt = P.optimizer.AdamW(learning_rate=1e-3,
                                parameters=model.parameters(),
                                grad_clip=P.nn.ClipGradByGlobalNorm(1.0))
        ids = rng.randint(0, cfg.vocab_size, (2, 17))
        logits = model(P.to_tensor(ids[:, :-1]))
        loss = F.cross_entropy(logits, P.to_tensor(ids[:, 1:]),
                               reduction="mean")
        loss.backward()
        opt.step()
        opt.clear_grad()

        # --- ResNet-ish conv net forward + loss + backward ---
        from paddle_tpu.vision.models import resnet18
        net = resnet18(num_classes=10)
        x = P.to_tensor(rng.randn(2, 3, 32, 32).astype(np.float32))
        y = net(x)
        lbl = P.to_tensor(rng.randint(0, 10, (2,)))
        l2 = F.cross_entropy(y, lbl)
        l2.backward()
        sgd = P.optimizer.Momentum(learning_rate=0.1,
                                   parameters=net.parameters())
        sgd.step()

        # --- transformer encoder (BERT-style) ---
        enc = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                         dim_feedforward=64)
        h = P.to_tensor(rng.randn(2, 8, 32).astype(np.float32))
        out = enc(h)
        out.mean().backward()

        # --- RNN family ---
        lstm = nn.LSTM(16, 32)
        seq = P.to_tensor(rng.randn(2, 5, 16).astype(np.float32))
        o, _ = lstm(seq)
        o.sum().backward()
        for act in ("tanh", "relu"):   # rnn_tanh / rnn_relu dispatch names
            srnn = nn.SimpleRNN(16, 24, activation=act)
            so, _ = srnn(seq)
            so.sum().backward()
        cell_x = P.to_tensor(rng.randn(3, 16).astype(np.float32))
        lc_o, _ = nn.LSTMCell(16, 24)(cell_x)
        lc_o.sum().backward()
        gc_o, _ = nn.GRUCell(16, 24)(cell_x)
        gc_o.sum().backward()

        # --- common tensor surface ---
        a = P.to_tensor(rng.randn(4, 4).astype(np.float32))
        a.stop_gradient = False
        b = (a @ a).tanh() * 2 + a.exp().log1p()
        c = P.concat([b, b], axis=0).reshape([4, 8])
        c = P.clip(c, -1.0, 1.0)
        s = c.sum() + c.mean() + c.std() + c.abs().max()
        s.backward()

        # --- normalization / dropout / pooling stack ---
        bn = nn.BatchNorm2D(3)
        gn = nn.GroupNorm(1, 3)
        img = P.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
        v = F.max_pool2d(bn(img), 2)
        v = F.avg_pool2d(gn(img), 2) + v
        v = F.dropout(v, 0.1)
        v.sum().backward()

        # --- losses ---
        p = P.to_tensor(rng.randn(4, 3).astype(np.float32))
        p.stop_gradient = False
        t = P.to_tensor(rng.randn(4, 3).astype(np.float32))
        (F.mse_loss(p, t) + F.l1_loss(p, t)
         + F.smooth_l1_loss(p, t)).backward()
        logit = P.to_tensor(rng.randn(4).astype(np.float32))
        logit.stop_gradient = False
        F.binary_cross_entropy_with_logits(
            logit, P.to_tensor((rng.rand(4) > 0.5).astype(np.float32))
        ).backward()

        # --- BERT-style masked LM head + GPT decode (generate path) ---
        from paddle_tpu.models import BertConfig, BertForSequenceClassification
        bcfg = BertConfig(vocab_size=64, hidden_size=32,
                          num_hidden_layers=1, num_attention_heads=4,
                          intermediate_size=64, max_position_embeddings=32)
        bert = BertForSequenceClassification(bcfg, num_classes=3)
        bids = P.to_tensor(rng.randint(0, 64, (2, 8)))
        bl = F.cross_entropy(bert(bids), P.to_tensor(rng.randint(0, 3, (2,))))
        bl.backward()

        # --- OCR recognition head (CRNN + CTC) ---
        from paddle_tpu.models import CRNN
        crnn = CRNN(num_classes=11, in_channels=1)
        img2 = P.to_tensor(rng.randn(1, 1, 32, 64).astype(np.float32))
        logits2 = crnn(img2)
        lab = P.to_tensor(rng.randint(1, 11, (1, 4)))
        ll = F.ctc_loss(logits2,
                        lab,
                        P.to_tensor(np.asarray([logits2.shape[1]], np.int64)),
                        P.to_tensor(np.asarray([4], np.int64)))
        ll.backward()

        # --- GRU + bidirectional path ---
        gru = nn.GRU(12, 16, direction="bidirect")
        sg = P.to_tensor(rng.randn(2, 5, 12).astype(np.float32))
        og, _ = gru(sg)
        og.mean().backward()

        # --- KV-cache greedy decode (ragged decode path) ---
        from paddle_tpu.models import LlamaConfig as _LC, LlamaForCausalLM as _LM
        dm = _LM(_LC.tiny(vocab=32, hidden=16, layers=1, heads=2, inter=32))
        dm.eval()
        dm.generate(P.to_tensor(rng.randint(0, 32, (1, 3))),
                    max_new_tokens=2, use_cache=True)
    finally:
        dispatch.set_coverage_recorder(None)
    return counts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "OP_COVERAGE.json"))
    args = ap.parse_args()
    counts = drive()
    ordered = dict(sorted(counts.items(), key=lambda kv: -kv[1]))
    with open(args.out, "w") as f:
        json.dump({"n_distinct_ops": len(ordered), "counts": ordered}, f,
                  indent=1)
    print(f"{len(ordered)} distinct ops recorded -> {args.out}")
    for name, n in list(ordered.items())[:30]:
        print(f"  {name:32s} {n}")


if __name__ == "__main__":
    main()
