"""Op-benchmark regression harness (reference: tools/ci_op_benchmark.sh —
the per-op timing CI that gates PRs on relative regressions vs develop).

Usage:
    python tools/op_benchmark.py --save baseline.json          # record
    python tools/op_benchmark.py --compare baseline.json       # gate (exit 1
        on any op slower than --threshold, default 1.15x)
    python tools/op_benchmark.py                               # print table

Each case times the steady-state jitted op on the attached device (the
device-kind is recorded so baselines aren't compared across chips).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases():
    import numpy as np

    import paddle_tpu as P

    rng = np.random.RandomState(0)

    def t(shape, dtype="float32"):
        if dtype.startswith("int"):
            return P.to_tensor(rng.randint(0, 1000, shape).astype(dtype))
        return P.to_tensor(rng.randn(*shape).astype(dtype))

    a1k = t((1024, 1024))
    b1k = t((1024, 1024))
    img = t((8, 64, 56, 56))
    ker = t((64, 64, 3, 3))
    seq = t((8, 512, 512))
    ids = t((8, 512), "int32")
    emb = t((32000, 512))
    q = t((2, 512, 8, 64))
    k = t((2, 512, 8, 64))
    v = t((2, 512, 8, 64))

    return [
        ("matmul_1kx1k", lambda: P.matmul(a1k, b1k)),
        ("add_1kx1k", lambda: a1k + b1k),
        ("softmax_8x512x512", lambda: P.nn.functional.softmax(seq, axis=-1)),
        ("layer_norm_8x512x512",
         lambda: P.nn.functional.layer_norm(seq, [512])),
        ("gelu_1kx1k", lambda: P.nn.functional.gelu(a1k)),
        ("conv2d_8x64x56x56",
         lambda: P.nn.functional.conv2d(img, ker, padding=1)),
        ("embedding_8x512",
         lambda: P.nn.functional.embedding(ids, emb)),
        ("reduce_sum_8x512x512", lambda: seq.sum()),
        ("transpose_8x512x512", lambda: P.transpose(seq, [0, 2, 1])),
        ("sdpa_2x512x8x64",
         lambda: P.nn.functional.scaled_dot_product_attention(
             q, k, v, is_causal=True)),
    ]


def run(n_iters=20, warmup=3):
    import jax

    results = {"device": jax.devices()[0].device_kind, "ops": {}}
    for name, fn in _cases():
        for _ in range(warmup):
            out = fn()
        jax.block_until_ready(out._value)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = fn()
        jax.block_until_ready(out._value)
        dt = (time.perf_counter() - t0) / n_iters
        results["ops"][name] = dt * 1e6  # us
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", help="write results JSON to this path")
    ap.add_argument("--compare", help="baseline JSON to gate against")
    ap.add_argument("--threshold", type=float, default=1.15,
                    help="max allowed slowdown ratio vs baseline")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    res = run(n_iters=args.iters)
    for name, us in res["ops"].items():
        print(f"{name:28s} {us:10.1f} us")

    if args.save:
        with open(args.save, "w") as f:
            json.dump(res, f, indent=1)
        print(f"saved -> {args.save}")
    if args.compare:
        with open(args.compare) as f:
            base = json.load(f)
        if base.get("device") != res["device"]:
            print(f"WARNING: baseline device {base.get('device')!r} != "
                  f"{res['device']!r}; ratios are not meaningful",
                  file=sys.stderr)
        bad = []
        for name, us in res["ops"].items():
            b = base.get("ops", {}).get(name)
            if b is None:
                continue
            ratio = us / b
            mark = " REGRESSION" if ratio > args.threshold else ""
            print(f"{name:28s} {ratio:6.2f}x vs baseline{mark}")
            if ratio > args.threshold:
                bad.append(name)
        if bad:
            print(f"FAILED: {len(bad)} op(s) regressed: {bad}", file=sys.stderr)
            sys.exit(1)
        print("PASS: no op regressed beyond "
              f"{args.threshold:.2f}x")


if __name__ == "__main__":
    main()
