"""Op-benchmark regression harness (reference: tools/ci_op_benchmark.sh —
the per-op timing CI that gates PRs on relative regressions vs develop).

Usage:
    python tools/op_benchmark.py --save baseline.json          # record
    python tools/op_benchmark.py --compare baseline.json       # gate (exit 1
        on any op slower than --threshold, default 1.15x)
    python tools/op_benchmark.py                               # print table

Each case times the steady-state jitted op on the attached device (the
device-kind is recorded so baselines aren't compared across chips).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases():
    import numpy as np

    import paddle_tpu as P

    rng = np.random.RandomState(0)

    def t(shape, dtype="float32"):
        if dtype.startswith("int"):
            return P.to_tensor(rng.randint(0, 1000, shape).astype(dtype))
        return P.to_tensor(rng.randn(*shape).astype(dtype))

    a1k = t((1024, 1024))
    b1k = t((1024, 1024))
    img = t((8, 64, 56, 56))
    ker = t((64, 64, 3, 3))
    seq = t((8, 512, 512))
    ids = t((8, 512), "int32")
    emb = t((32000, 512))
    q = t((2, 512, 8, 64))
    k = t((2, 512, 8, 64))
    v = t((2, 512, 8, 64))

    cases = [
        ("matmul_1kx1k", lambda: P.matmul(a1k, b1k)),
        ("add_1kx1k", lambda: a1k + b1k),
        ("softmax_8x512x512", lambda: P.nn.functional.softmax(seq, axis=-1)),
        ("layer_norm_8x512x512",
         lambda: P.nn.functional.layer_norm(seq, [512])),
        ("gelu_1kx1k", lambda: P.nn.functional.gelu(a1k)),
        ("conv2d_8x64x56x56",
         lambda: P.nn.functional.conv2d(img, ker, padding=1)),
        ("embedding_8x512",
         lambda: P.nn.functional.embedding(ids, emb)),
        ("reduce_sum_8x512x512", lambda: seq.sum()),
        ("transpose_8x512x512", lambda: P.transpose(seq, [0, 2, 1])),
        ("sdpa_2x512x8x64",
         lambda: P.nn.functional.scaled_dot_product_attention(
             q, k, v, is_causal=True)),
    ]
    cases += _pallas_vs_jnp_cases()
    return cases


def _pallas_vs_jnp_cases():
    """Pallas kernel vs jnp-composition pairs (VERDICT r3 items 6/7 gate:
    the committed on-chip baseline must show the kernel delta).  Only added
    on a real TPU backend — in CPU interpret mode the kernels measure the
    interpreter, not the program."""
    import jax

    if jax.devices()[0].platform != "tpu":
        return []
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas.decode_attention import ragged_decode_attention
    from paddle_tpu.ops.pallas.fused_ce import fused_linear_cross_entropy

    rng = np.random.RandomState(0)
    N, H, V = 4096, 4096, 32000
    h = jnp.asarray(rng.randn(N, H).astype(np.float32) * 0.02).astype(jnp.bfloat16)
    w = jnp.asarray(rng.randn(H, V).astype(np.float32) * 0.02).astype(jnp.bfloat16)
    lab = jnp.asarray(rng.randint(0, V, (N,)), jnp.int32)
    g = jnp.ones((N,), jnp.float32) / N

    def jnp_ce(h, w, lab):
        s = (h @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return lse - jnp.take_along_axis(s, lab[:, None], 1)[:, 0]

    flce_grad = jax.grad(
        lambda a, b, c: jnp.sum(fused_linear_cross_entropy(a, b, c) * g),
        argnums=(0, 1))
    jnp_grad = jax.grad(
        lambda a, b, c: jnp.sum(jnp_ce(a, b, c) * g), argnums=(0, 1))

    B, Smax, Hh, Hkv, D = 8, 2048, 32, 32, 128
    qd = jnp.asarray(rng.randn(B, 1, Hh, D).astype(np.float32)).astype(jnp.bfloat16)
    kc = jnp.asarray(rng.randn(B, Smax, Hkv, D).astype(np.float32)).astype(jnp.bfloat16)
    vc = jnp.asarray(rng.randn(B, Smax, Hkv, D).astype(np.float32)).astype(jnp.bfloat16)
    lengths = jnp.full((B,), 1536, jnp.int32)

    def jnp_decode(qv, kv, vv, lens):
        s = jnp.einsum("bqhd,bkhd->bhqk", qv, kv) / np.sqrt(D)
        mask = jnp.arange(Smax)[None, None, None, :] < lens[:, None, None, None]
        p = jax.nn.softmax(jnp.where(mask, s.astype(jnp.float32), -1e30), -1)
        return jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv)

    from paddle_tpu.core.tensor import Tensor

    def wrapjit(fn, *args):
        """jit with the arrays as real ARGUMENTS (closure capture would bake
        them into the HLO as constants).  Blocks on EVERY output leaf before
        handing one to the timer — wrapping only the first leaf would let
        the last iteration's remaining outputs (e.g. dW) run past the
        timer stop under async dispatch."""
        compiled = jax.jit(fn)

        def run():
            out = jax.block_until_ready(compiled(*args))
            return Tensor(jax.tree_util.tree_leaves(out)[0])
        return run

    return [
        ("fused_linear_ce_fwd_4kx32k",
         wrapjit(lambda a, b, c: fused_linear_cross_entropy(a, b, c),
                 h, w, lab)),
        ("jnp_linear_ce_fwd_4kx32k",
         wrapjit(lambda a, b, c: jnp_ce(a, b, c), h, w, lab)),
        ("fused_linear_ce_grad_4kx32k",
         wrapjit(lambda a, b, c: flce_grad(a, b, c), h, w, lab)),
        ("jnp_linear_ce_grad_4kx32k",
         wrapjit(lambda a, b, c: jnp_grad(a, b, c), h, w, lab)),
        ("ragged_decode_attn_8x2048",
         wrapjit(lambda a, b, c, d: ragged_decode_attention(a, b, c, d),
                 qd, kc, vc, lengths)),
        ("jnp_masked_decode_attn_8x2048",
         wrapjit(lambda a, b, c, d: jnp_decode(a, b, c, d),
                 qd, kc, vc, lengths)),
    ]


def run(n_iters=20, warmup=3):
    import jax

    results = {"device": jax.devices()[0].device_kind, "ops": {}}
    for name, fn in _cases():
        for _ in range(warmup):
            out = fn()
        jax.block_until_ready(out._value)
        t0 = time.perf_counter()
        for _ in range(n_iters):
            out = fn()
        jax.block_until_ready(out._value)
        dt = (time.perf_counter() - t0) / n_iters
        results["ops"][name] = dt * 1e6  # us
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", help="write results JSON to this path")
    ap.add_argument("--compare", help="baseline JSON to gate against")
    ap.add_argument("--threshold", type=float, default=1.15,
                    help="max allowed slowdown ratio vs baseline")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    res = run(n_iters=args.iters)
    for name, us in res["ops"].items():
        print(f"{name:28s} {us:10.1f} us")

    if args.save:
        with open(args.save, "w") as f:
            json.dump(res, f, indent=1)
        print(f"saved -> {args.save}")
    if args.compare:
        with open(args.compare) as f:
            base = json.load(f)
        if base.get("device") != res["device"]:
            print(f"WARNING: baseline device {base.get('device')!r} != "
                  f"{res['device']!r}; ratios are not meaningful",
                  file=sys.stderr)
        bad = []
        for name, us in res["ops"].items():
            b = base.get("ops", {}).get(name)
            if b is None:
                continue
            ratio = us / b
            mark = " REGRESSION" if ratio > args.threshold else ""
            print(f"{name:28s} {ratio:6.2f}x vs baseline{mark}")
            if ratio > args.threshold:
                bad.append(name)
        if bad:
            print(f"FAILED: {len(bad)} op(s) regressed: {bad}", file=sys.stderr)
            sys.exit(1)
        print("PASS: no op regressed beyond "
              f"{args.threshold:.2f}x")


if __name__ == "__main__":
    main()
