"""Dev tools (benchmarks, coverage, golden generators)."""
