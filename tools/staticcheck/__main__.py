"""CLI: python -m tools.staticcheck [paths...] [options]

Modes:
  (default)            report every finding (baseline NOT applied), exit 0
  --ci                 apply the baseline ratchet; exit 1 on NEW findings
  --update-baseline    rewrite baseline.json from the current finding set

Both tiers run in one invocation: the AST tier over the scan paths, and
(for a default whole-repo scan of THIS repo) the jaxpr tier — the
canonical captured steps traced and semantically linted (see jaxpr/).
`--no-jaxpr`, a scoped path list, or PT_STATICCHECK_FAST=1 skips the
jaxpr trace (the in-process tier-1 gate uses the env to stay inside its
wall-clock share).

Examples:
  python -m tools.staticcheck                       # full report
  python -m tools.staticcheck --ci                  # the CI gate (2 tiers)
  python -m tools.staticcheck --rules host-sync paddle_tpu/ops
  python -m tools.staticcheck --json > findings.json
"""
from __future__ import annotations

import argparse
import os
import sys

from .baseline import (DEFAULT_BASELINE, load_baseline, new_findings,
                       save_baseline)
from .core import all_checkers, run
from .report import json_report, text_report

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: paddle_tpu tools)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="project root (baseline keys are relative to it)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ci", action="store_true",
                    help="apply the baseline; exit 1 if NEW findings exist")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit JSON instead of text")
    ap.add_argument("--list-rules", action="store_true",
                    help="list registered rule ids and exit")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr tier (canonical-step trace)")
    args = ap.parse_args(argv)

    from . import jaxpr as jaxpr_tier

    if args.list_rules:
        for c in sorted(all_checkers(), key=lambda c: c.rule):
            mod = sys.modules[type(c).__module__]
            doc = (mod.__doc__ or "").strip().splitlines()
            print(f"{c.rule:24s} [{c.severity}] {doc[0] if doc else ''}")
        for r in jaxpr_tier.JAXPR_RULES:
            print(f"{r:24s} [warning] jaxpr tier (tools/staticcheck/jaxpr)")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    findings = run(args.root, paths=args.paths or None, rules=rules)
    # jaxpr tier: whole-repo scans of THIS repo only (the canonical steps
    # are this repo's; a fixture root has its own via PT_STATICCHECK_STEPS)
    want_jaxpr = not args.no_jaxpr and not args.paths \
        and not jaxpr_tier.fast_mode() \
        and (os.environ.get(jaxpr_tier.steps_env()) is not None
             or os.path.realpath(args.root) == os.path.realpath(REPO_ROOT))
    jaxpr_collected = False
    if want_jaxpr and (rules is None
                      or any(r.startswith("jaxpr-") for r in rules)):
        jx = jaxpr_tier.collect_findings(args.root)
        jaxpr_collected = not jaxpr_tier.fast_mode()
        if rules is not None:
            jx = [f for f in jx if f.rule in set(rules)]
            # a rules filter means the jaxpr findings are PARTIAL — the
            # baseline-update path below must still preserve the rest
            jaxpr_collected = False
        findings = findings + jx
    baseline_path = args.baseline or DEFAULT_BASELINE

    if args.update_baseline:
        # scoped invocations merge: entries outside the scanned paths are
        # preserved, so a partial scan can't resurface the rest as "new";
        # likewise a run that SKIPPED the jaxpr tier must not drop its
        # grandfathered jaxpr-* entries
        scanned = None
        if args.paths:
            scanned = [os.path.relpath(p, args.root) if os.path.isabs(p)
                       else p for p in args.paths]
        save_baseline(
            findings, baseline_path, scanned_paths=scanned,
            preserve_rule_prefix=None if jaxpr_collected else "jaxpr-")
        print(f"baseline updated: {len(findings)} finding(s) recorded"
              + (f" under {', '.join(scanned)}" if scanned else "")
              + f" -> {baseline_path}")
        return 0

    if args.ci:
        fresh = new_findings(findings, load_baseline(baseline_path))
        out = json_report(fresh) if args.as_json else text_report(fresh)
        print(out)
        if fresh:
            print(f"\nstaticcheck --ci: {len(fresh)} NEW violation(s) not "
                  f"in the baseline ({len(findings)} total, "
                  f"{len(findings) - len(fresh)} baselined).\n"
                  f"Fix them, add a `# staticcheck: ok[rule]` pragma with a "
                  f"rationale, or (last resort) run --update-baseline.",
                  file=sys.stderr)
            return 1
        print(f"staticcheck --ci: clean "
              f"({len(findings)} baselined finding(s), 0 new).")
        return 0

    print(json_report(findings) if args.as_json else text_report(findings))
    return 0


if __name__ == "__main__":
    sys.exit(main())
