"""Shared AST analyses: traced-function discovery and value-use tainting.

"Traced" means the function object is handed to the tracing machinery —
dispatch.apply / defprim / the distributions' _wrap, or jax.jit/pjit —
so its positional parameters are jax values (possibly Tracers) at runtime.
The analyses here are deliberately heuristic: metadata access
(`.shape`/`.ndim`/`.dtype`) and shape-level builtins (`isinstance`, `len`)
are static under trace and never count as value uses.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

# entry points whose first positional argument becomes a traced callable
TRACE_ENTRY_NAMES = {"apply", "defprim", "_wrap"}
JIT_NAMES = {"jit", "pjit"}

# attributes that are static metadata under trace (reading them off a
# tracer never materializes values on host)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "device", "sharding",
                "aval", "weak_type", "itemsize", "nbytes"}
# builtins whose result over a traced array is static (or that inspect the
# python object, not the array values)
STATIC_CALLS = {"isinstance", "len", "type", "hasattr", "getattr",
                "callable", "id", "repr"}


def call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def attr_root(node: ast.AST) -> str | None:
    """Leftmost name of an attribute chain: `np.linalg.eig` -> 'np'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclasses.dataclass
class TracedFn:
    node: ast.AST          # ast.Lambda | ast.FunctionDef
    params: set[str]       # positional params — traced values at runtime
    entry: str             # 'apply' | 'defprim' | '_wrap' | 'jit' | ...
    entry_node: ast.AST    # the call / decorator that marked it traced


def _positional_params(args: ast.arguments) -> set[str]:
    names = {a.arg for a in args.posonlyargs + args.args}
    if args.vararg is not None:
        names.add(args.vararg.arg)
    # kwonlyargs excluded: apply() passes static config by keyword
    return names


def _functiondefs_by_name(tree: ast.AST) -> dict[str, list[ast.FunctionDef]]:
    out: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def traced_functions(tree: ast.AST) -> Iterator[TracedFn]:
    """Yield every function node in the module that is passed to a trace
    entry point, either inline (lambda / local def referenced by name) or
    via a jit decorator."""
    defs = _functiondefs_by_name(tree)
    seen: set[int] = set()

    def emit(fn_expr: ast.AST, entry: str, entry_node: ast.AST):
        targets: list[ast.AST] = []
        if isinstance(fn_expr, (ast.Lambda, ast.FunctionDef)):
            targets.append(fn_expr)
        elif isinstance(fn_expr, ast.Name):
            targets.extend(defs.get(fn_expr.id, ()))
        for t in targets:
            if id(t) in seen:
                continue
            seen.add(id(t))
            yield TracedFn(node=t, params=_positional_params(t.args),
                           entry=entry, entry_node=entry_node)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in TRACE_ENTRY_NAMES and node.args:
                yield from emit(node.args[0], name, node)
            elif name in JIT_NAMES and node.args:
                yield from emit(node.args[0], "jit", node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dname = (call_name(dec) if isinstance(dec, ast.Call)
                         else dec.attr if isinstance(dec, ast.Attribute)
                         else dec.id if isinstance(dec, ast.Name) else None)
                if dname in JIT_NAMES:
                    yield from emit(node, "jit", dec)


def value_uses(expr: ast.AST, tainted: set[str],
               containers: set[str] = frozenset()) -> list[ast.Name]:
    """Name nodes in `expr` that read a tainted value AS A VALUE.

    Static accesses never count: metadata attributes (`x.shape`, `x.ndim`),
    object-level builtins (`isinstance(x, T)`, `len(x)`), identity checks
    (`x is None`), and container-key membership (`k in params`). Names in
    `containers` (e.g. a traced *args tuple) count only when indexed —
    `if gs:` is a length check, `gs[0] + 1` touches a traced element."""
    out: list[ast.Name] = []

    def visit(n: ast.AST):
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return
        if isinstance(n, ast.Call) and call_name(n) in STATIC_CALLS:
            return
        if isinstance(n, ast.Compare):
            # `x is None` is object identity (static); `k in d` checks keys,
            # not values, so the container side never counts. The left side
            # still counts for ordinary comparisons and as the member of
            # `in` (a traced member against a static container is dynamic).
            if not isinstance(n.ops[0], (ast.Is, ast.IsNot)):
                visit(n.left)
            for op, comp in zip(n.ops, n.comparators):
                if isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)):
                    continue
                visit(comp)
            return
        if isinstance(n, ast.Subscript) and isinstance(n.value, ast.Name) \
                and n.value.id in containers and n.value.id in tainted:
            out.append(n.value)
            visit(n.slice)
            return
        if isinstance(n, ast.Name):
            if n.id in tainted and n.id not in containers:
                out.append(n)
            return
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(expr)
    return out


def _binding_names(target: ast.AST):
    """Names BOUND by an assignment target. `env[n] = x` binds nothing but
    mutates `env` (the container gets tainted, the index `n` does not)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _binding_names(elt)
    elif isinstance(target, ast.Starred):
        yield from _binding_names(target.value)
    elif isinstance(target, (ast.Subscript, ast.Attribute)):
        root = attr_root(target.value)
        if root is not None:
            yield root


def vararg_name(fn: TracedFn) -> set[str]:
    va = fn.node.args.vararg
    return {va.arg} if va is not None else set()


_CONTAINER_LITERALS = (ast.List, ast.ListComp, ast.Tuple, ast.Dict,
                       ast.DictComp, ast.Set, ast.SetComp)


def tainted_names(fn: TracedFn, max_iters: int = 10) -> tuple[set[str], set[str]]:
    """-> (tainted, containers): params plus local names (transitively)
    assigned from tainted values, and the subset that holds *collections* of
    traced values (the *args tuple, a list built from traced elements) —
    their truthiness/length is static, only indexing them is a value use.
    A bounded fixpoint over the function's Assign statements —
    order-insensitive, so re-assignments are over-approximated as tainted."""
    tainted = set(fn.params)
    containers = vararg_name(fn)
    body = fn.node.body if isinstance(fn.node, ast.FunctionDef) else [fn.node.body]
    assigns = [n for stmt in body for n in ast.walk(stmt)
               if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))]
    for _ in range(max_iters):
        grew = False
        for a in assigns:
            value = a.value
            if value is None or not value_uses(value, tainted, containers):
                continue
            is_container = isinstance(value, _CONTAINER_LITERALS)
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            for t in targets:
                for name in _binding_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        grew = True
                    if is_container and isinstance(t, ast.Name) \
                            and name not in containers:
                        containers.add(name)
                        grew = True
        if not grew:
            break
    return tainted, containers
