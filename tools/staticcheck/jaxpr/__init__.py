"""Jaxpr tier: semantic analysis of the repo's captured step programs.

The AST tier (the checkers/ package) can only see Python source; since
the whole-step capture substrate landed, every hot path — TrainStep,
to_static, the serving decode/verify steps — runs through a captured
jaxpr where the real hazards live.  This tier traces the canonical steps
through the repo's own capture machinery (steps.py) and runs the shared
rule engine ``paddle_tpu/jit/passes/lint.py`` over the closed jaxprs,
wrapping each hit into the existing :class:`Finding` model so the
pragma allowlist and the ``baseline.json`` ratchet cover both tiers in
one ``python -m tools.staticcheck --ci`` invocation.

Rules (prefixed ``jaxpr-`` to keep the namespace distinct from the AST
rules; definitions live in jit/passes/lint.py so the in-process
``profiler.lint_summary()`` view and this gate can never drift):

- ``jaxpr-recompile-hazard``       weak_type avals on program inputs,
  signature churn on equivalent re-capture, and capture bailouts of a
  canonical step (a step that silently rides the eager tier re-pays
  dispatch every call — the hazard the capture tier exists to remove)
- ``jaxpr-donation-miss``          donatable-but-not-donated inputs;
  donated inputs matching no output (write_back-before-rebuild class)
- ``jaxpr-unscheduled-collective`` collective eqns with no comm-pass
  tag, and fp32 collectives running beside a quantized wire leg
- ``jaxpr-dead-compute``           dead subgraphs beyond DVE's reach
- ``jaxpr-host-callback``          callback/IO eqns inside a step

Findings anchor at the step-builder's def line, so one
``# staticcheck: ok[jaxpr-...]`` pragma there is the deliberate-site
allowlist, same as the AST tier.

Tracing imports paddle_tpu (CPU backend forced); ``PT_STATICCHECK_FAST=1``
skips the tier entirely — the in-process tier-1 gate uses that to stay
inside its wall-clock share while the standalone CLI gate runs both
tiers.
"""
from __future__ import annotations

import os
from typing import List, Optional

from ..core import Finding, parse_file_cached

# mirrors paddle_tpu/jit/passes/lint.py RULES (asserted in tests); kept
# literal so `--list-rules` never has to import paddle_tpu
RULE_PREFIX = "jaxpr-"
JAXPR_RULES = ("jaxpr-recompile-hazard", "jaxpr-donation-miss",
               "jaxpr-unscheduled-collective", "jaxpr-dead-compute",
               "jaxpr-host-callback")

FAST_ENV = "PT_STATICCHECK_FAST"


def fast_mode() -> bool:
    return os.environ.get(FAST_ENV, "").lower() in ("1", "true", "yes")


def steps_env() -> str:
    """Name of the steps-override env var (steps.py owns the constant but
    importing it must stay lazy — it pulls paddle_tpu on first trace)."""
    return "PT_STATICCHECK_STEPS"


def _mk(rule: str, path: str, line: int, message: str,
        context: str) -> Finding:
    return Finding(rule=rule, severity="warning", path=path, line=line,
                   col=0, message=message, context=context)


def _step_findings(step, root: str) -> List[Finding]:
    from paddle_tpu.jit.passes import lint

    out: List[Finding] = []
    if step.program is None:
        out.append(_mk(
            "jaxpr-recompile-hazard", step.anchor_path, step.anchor_line,
            f"canonical step {step.name!r} failed capture "
            f"({step.error}) — it silently rides the eager tier, "
            f"re-paying python dispatch every call",
            f"{step.name}:capture-bailout"))
        return out
    if step.churn:
        out.append(_mk(
            "jaxpr-recompile-hazard", step.anchor_path, step.anchor_line,
            f"step {step.name!r} re-lowered (or fell back) on a second "
            f"call with equivalent inputs — the cache key churns "
            f"(python scalar, fresh closure, or unhashable static in the "
            f"signature)",
            f"{step.name}:signature-churn"))
    for f in lint.analyze(step.program.closed_jaxpr,
                          donated=step.program.donate,
                          comm_tagged=lint.comm_tagged_of(
                              step.program.pass_report),
                          name=step.name):
        out.append(_mk(
            RULE_PREFIX + f["rule"], step.anchor_path, step.anchor_line,
            f"[{step.name}] {f['message']}",
            f"{step.name}:{f['detail']}"))
    return out


def collect_findings(root: str, steps=None,
                     steps_file: Optional[str] = None) -> List[Finding]:
    """Trace the canonical steps (or ``steps``/``steps_file`` overrides)
    and return ratchet-ready findings, pragma suppression applied at each
    finding's anchor line."""
    if fast_mode():
        return []
    if steps is None:
        from . import steps as steps_mod
        try:
            steps = steps_mod.load_steps(root, steps_file=steps_file)
        except Exception as e:  # noqa: BLE001 — keep the AST tier's results
            return [_mk(
                "jaxpr-recompile-hazard", "tools/staticcheck/jaxpr/steps.py",
                1,
                f"canonical-step tracing failed to even start "
                f"({type(e).__name__}: {str(e)[:160]}) — the jaxpr tier "
                f"is blind; fix the step builders",
                "canonical:load-failure")]
    findings: List[Finding] = []
    for step in steps:
        findings.extend(_step_findings(step, root))
    # pragma allowlist: same semantics as the AST tier, applied at the
    # anchor (step-builder def) line; anchors ride the shared parse cache
    kept: List[Finding] = []
    for f in findings:
        if not f.path.startswith("<"):
            try:
                mod = parse_file_cached(root, os.path.join(root, f.path))
            except Exception:  # noqa: BLE001 — unreadable anchor: keep
                mod = None
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.context))
    return kept
