"""Canonical captured steps the jaxpr tier traces.

The AST tier scans source; this module produces the *programs* the rules
run over: each canonical step is traced through the repo's own capture
machinery (jit/capture.py) exactly the way production code builds it —
TrainStep on the proxy llama, the serving batch-slot decode and
speculative-verify steps, and a to_static program — so the findings are
about what actually lowers, not a synthetic re-trace.

Every step is captured TWICE with equivalent fresh inputs. A second
lowering (or a fallback call) on value-equal avals is the signature-churn
form of the recompile hazard: something non-aval (a fresh closure, a
python scalar, an unhashable static) leaked into the cache key.

``PT_STATICCHECK_STEPS=/path/to/module.py`` swaps the canonical set for a
module exposing ``collect() -> list[StepResult]`` (the known-answer
fixture projects use this; ``trace_step`` below is the helper they build
on). Models are deliberately tiny — this is a linter, not a benchmark.
"""
from __future__ import annotations

import dataclasses
import inspect
import os
import runpy
from typing import Callable, List, Optional

STEPS_ENV = "PT_STATICCHECK_STEPS"


@dataclasses.dataclass
class StepResult:
    """One traced canonical step, ready for the rules."""
    name: str
    anchor_path: str          # project-root-relative file to report against
    anchor_line: int          # pragma line: `# staticcheck: ok[rule]` here
    program: object = None    # GraftProgram (None when capture failed)
    churn: bool = False       # re-lowered / fell back on equivalent inputs
    error: str = ""           # capture-bailout reason when program is None


def _anchor(obj, root: str):
    try:
        path = os.path.relpath(inspect.getsourcefile(obj), root)
        line = inspect.getsourcelines(obj)[1]
        return path.replace(os.sep, "/"), line
    except Exception:  # noqa: BLE001 — builtins/C callables: best effort
        return "<unknown>", 1


def trace_step(name: str, fn: Callable, make_args: Callable[[], tuple],
               *, root: str, donate="off", passes=None,
               allow_baked_rng: bool = True,
               anchor=None) -> StepResult:
    """Capture ``fn`` twice via capture_step with fresh equivalent args
    from ``make_args()``; returns the StepResult the rules consume."""
    from paddle_tpu.jit import capture

    path, line = _anchor(anchor if anchor is not None else fn, root)
    wrapper = capture.capture_step(fn, donate=donate, passes=passes,
                                   allow_baked_rng=allow_baked_rng)
    try:
        wrapper(*make_args())
        wrapper(*make_args())
    except Exception as e:  # noqa: BLE001 — a crashing step is a bailout
        return StepResult(name, path, line,
                          error=f"{type(e).__name__}: {e}"[:200])
    info = wrapper.cache_info()
    programs = wrapper.programs()
    if not programs:
        return StepResult(name, path, line,
                          error=wrapper.bailout_reason()
                          or "capture produced no program")
    return StepResult(name, path, line, program=programs[0],
                      churn=info["lowerings"] != 1)


# ---------------------------------------------------------------------------
# the canonical set
# ---------------------------------------------------------------------------

def _tiny_llama():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2,
                           inter=64, seq=16)
    return cfg, LlamaForCausalLM(cfg)


def _train_step(root: str) -> StepResult:
    """TrainStep on the proxy llama — the lower_step path (donation via
    donate_argnums, shardings None on the single-device proxy)."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.jit import capture
    from paddle_tpu.parallel import trainer as trainer_mod

    path, line = _anchor(trainer_mod.TrainStep._build, root)
    try:
        P.seed(1234)
        cfg, model = _tiny_llama()
        opt = P.optimizer.AdamW(learning_rate=1e-3,
                                parameters=model.parameters())
        step = trainer_mod.compile_train_step(
            model,
            lambda m, b: m.compute_loss(b["input_ids"], b["labels"]), opt)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, size=(2, 8)).astype("int32")
        batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(ids)}
        step(batch)
        before = capture.capture_info()
        step(batch)  # equivalent avals: must ride the captured executable
        after = capture.capture_info()
    except Exception as e:  # noqa: BLE001 — a build failure is a bailout
        return StepResult("trainstep/llama", path, line,
                          error=f"{type(e).__name__}: {e}"[:200])
    prog = step.captured_program
    if prog is None:
        return StepResult("trainstep/llama", path, line,
                          error=capture.capture_info()["last_bailout"]
                          or "lower_step fell back to plain jit")
    churn = after["fallback_calls"] > before["fallback_calls"] \
        or after["lowerings"] > before["lowerings"]
    return StepResult("trainstep/llama", path, line, program=prog,
                      churn=churn)


def _serving_steps(root: str) -> List[StepResult]:
    """The engine's batch-slot decode step and the speculative verify
    step, captured exactly as inference/serving builds them (KV caches
    donated, per-slot offsets)."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models import llama as llama_mod

    try:
        P.seed(1234)
        cfg, model = _tiny_llama()
        B, W = 2, 3
        params = [p._value for p in model.parameters()]

        def cache_args():
            return [(kc._value, vc._value) for kc, vc in
                    model.init_kv_caches(B, cfg.max_position_embeddings)]

        tok = jnp.asarray(np.zeros((B, 1), np.int32))
        win = jnp.asarray(np.zeros((B, W), np.int32))
        off = jnp.zeros((B,), jnp.int32)
        last = jnp.zeros((B,), jnp.int32)

        out = []
        slot = model._build_slot_step()
        out.append(_wrapped_result(
            "serving/slot_step", slot, root, model._build_slot_step,
            lambda: (params, tok, cache_args(), off, last)))
        verify = model._build_verify_step()
        out.append(_wrapped_result(
            "serving/verify_step", verify, root, model._build_verify_step,
            lambda: (params, win, cache_args(), off)))
        return out
    except Exception as e:  # noqa: BLE001 — a build failure is a bailout
        path, line = _anchor(llama_mod.LlamaForCausalLM, root)
        err = f"{type(e).__name__}: {e}"[:200]
        return [StepResult("serving/slot_step", path, line, error=err),
                StepResult("serving/verify_step", path, line, error=err)]


def _wrapped_result(name: str, wrapper, root: str, anchor,
                    make_args) -> StepResult:
    """Drive an already-built CapturedStep twice (the model step builders
    pick their own donate config) and package the result."""
    path, line = _anchor(anchor, root)
    try:
        wrapper(*make_args())
        wrapper(*make_args())
    except Exception as e:  # noqa: BLE001
        return StepResult(name, path, line,
                          error=f"{type(e).__name__}: {e}"[:200])
    info = getattr(wrapper, "cache_info", lambda: {})()
    programs = getattr(wrapper, "programs", lambda: [])()
    if not programs:
        reason = getattr(wrapper, "bailout_reason", lambda: "")()
        return StepResult(name, path, line,
                          error=reason or "capture produced no program "
                                "(step fell back to the eager tier)")
    return StepResult(name, path, line, program=programs[0],
                      churn=info.get("lowerings", 1) != 1)


def _deepfm_step(root: str) -> StepResult:
    """The recommendation workload: DeepFM training through the sharded
    embedding tables (distributed/embedding) — on a dp2 mesh when this
    host has >= 2 devices (the exchange path: unique -> id all_to_all ->
    gather -> wire return must be fully comm-pass tagged), dense dp1
    otherwise. The lint gate is the 'zero new naked collectives' half of
    the subsystem's acceptance."""
    import jax
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import capture
    from paddle_tpu.models import deepfm as deepfm_mod
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel import trainer as trainer_mod

    path, line = _anchor(deepfm_mod.DeepFM, root)
    prev_mesh = mesh_mod.get_mesh()
    try:
        mesh = None
        if len(jax.devices()) >= 2:
            mesh = mesh_mod.init_mesh({"dp": 2}, devices=jax.devices()[:2])
        else:
            mesh_mod.set_mesh(None)
        P.seed(1234)
        model = deepfm_mod.DeepFM(
            sparse_feature_number=32, sparse_feature_dim=4,
            dense_feature_dim=4, sparse_field_num=4, layer_sizes=(16,))
        opt = P.optimizer.SGD(learning_rate=0.05,
                              parameters=model.parameters())
        step = trainer_mod.compile_train_step(
            model,
            lambda m, b: nn.functional.binary_cross_entropy_with_logits(
                m(b["sparse"], b["dense"]), b["y"]),
            opt, mesh=mesh)
        rng = np.random.RandomState(0)
        raw = {"sparse": rng.randint(0, 32, (8, 4)),
               "dense": rng.randn(8, 4).astype(np.float32),
               "y": (rng.rand(8, 1) > 0.5).astype(np.float32)}

        def batch():
            return {k: P.to_tensor(v.copy()) for k, v in raw.items()}

        step(batch())
        before = capture.capture_info()
        step(batch())  # equivalent avals: must ride the captured executable
        after = capture.capture_info()
    except Exception as e:  # noqa: BLE001 — a build failure is a bailout
        return StepResult("trainstep/deepfm-sharded-embedding", path, line,
                          error=f"{type(e).__name__}: {e}"[:200])
    finally:
        mesh_mod.set_mesh(prev_mesh)
    prog = step.captured_program
    if prog is None:
        return StepResult("trainstep/deepfm-sharded-embedding", path, line,
                          error=capture.capture_info()["last_bailout"]
                          or "lower_step fell back to plain jit")
    churn = after["fallback_calls"] > before["fallback_calls"] \
        or after["lowerings"] > before["lowerings"]
    return StepResult("trainstep/deepfm-sharded-embedding", path, line,
                      program=prog, churn=churn)


def _supervised_steps(root: str) -> List[StepResult]:
    """The elastic supervisor's TrainStep swap leg
    (distributed/supervisor.swap_train_step): capture the step at the
    PRE-swap mesh shape, drive the single-controller reshard the
    supervisor runs at every resume, and re-capture at the POST-swap
    shape — both programs must lint clean, or a scale event would trade a
    healthy step for a hazardous one mid-run. dp2 -> dp1 when this host
    has >= 2 devices, dp1 -> dp1 (still a full drop + re-lower) otherwise."""
    import jax
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.distributed import supervisor as sv_mod
    from paddle_tpu.jit import capture
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel import trainer as trainer_mod

    path, line = _anchor(sv_mod.swap_train_step, root)
    prev_mesh = mesh_mod.get_mesh()
    names = ("supervisor/trainstep-pre-swap",
             "supervisor/trainstep-post-swap")
    try:
        n_pre = 2 if len(jax.devices()) >= 2 else 1
        P.seed(1234)
        mesh_pre = mesh_mod.init_mesh({"dp": n_pre},
                                      devices=jax.devices()[:n_pre])
        model = P.nn.Linear(8, 4)
        opt = P.optimizer.SGD(learning_rate=0.1,
                              parameters=model.parameters())

        def loss_fn(m, b):
            x, y = b
            return P.nn.functional.mse_loss(m(P.to_tensor(x)),
                                            P.to_tensor(y))

        step = trainer_mod.compile_train_step(model, loss_fn, opt,
                                              mesh=mesh_pre)
        rng = np.random.RandomState(0)
        batch = (rng.randn(8, 8).astype(np.float32),
                 rng.randn(8, 4).astype(np.float32))

        results = []
        for name in names:
            if name == names[1]:
                # build the post-swap mesh HERE, not before the loop:
                # init_mesh installs the global mesh, and the pre-swap
                # capture must run with the dp{n_pre} mesh current
                sv_mod.swap_train_step(step, mesh_mod.init_mesh(
                    {"dp": 1}, devices=jax.devices()[:1]))
            step(batch)
            before = capture.capture_info()
            step(batch)  # equivalent avals: must ride the captured step
            after = capture.capture_info()
            prog = step.captured_program
            if prog is None:
                results.append(StepResult(
                    name, path, line,
                    error=capture.capture_info()["last_bailout"]
                    or "lower_step fell back to plain jit"))
                continue
            churn = after["fallback_calls"] > before["fallback_calls"] \
                or after["lowerings"] > before["lowerings"]
            results.append(StepResult(name, path, line, program=prog,
                                      churn=churn))
        return results
    except Exception as e:  # noqa: BLE001 — a build failure is a bailout
        err = f"{type(e).__name__}: {e}"[:200]
        return [StepResult(n, path, line, error=err) for n in names]
    finally:
        mesh_mod.set_mesh(prev_mesh)


def _to_static_step(root: str) -> StepResult:
    """A to_static-compiled layer — the jit.api lower_step path."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import api as jit_api

    path, line = _anchor(jit_api.StaticFunction._build, root)
    try:
        P.seed(1234)
        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 4))
        static = P.to_static(model)
        x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        static(P.to_tensor(x))
        static(P.to_tensor(x.copy()))
        sf = model._static_function
    except Exception as e:  # noqa: BLE001 — a build failure is a bailout
        return StepResult("to_static/mlp", path, line,
                          error=f"{type(e).__name__}: {e}"[:200])
    progs = [e[0].captured_program for e in sf.concrete_programs
             if getattr(e[0], "captured_program", None) is not None]
    if not progs:
        return StepResult("to_static/mlp", path, line,
                          error="to_static compile did not capture "
                                "(lower_step fell back to plain jit)")
    return StepResult("to_static/mlp", path, line, program=progs[0],
                      churn=len(sf.concrete_programs) != 1)


def _force_cpu():
    """A linter must never grab the accelerator; env alone is not enough
    because a sitecustomize may re-register a TPU plugin and override
    jax_platforms (see tests/conftest.py), so force it at config level."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized: keep it
        pass


def canonical_steps(root: str) -> List[StepResult]:
    """Trace the repo's canonical steps on the CPU backend."""
    _force_cpu()
    results = [_train_step(root)]
    results += _serving_steps(root)
    results.append(_to_static_step(root))
    results.append(_deepfm_step(root))
    results += _supervised_steps(root)
    return results


def load_steps(root: str,
               steps_file: Optional[str] = None) -> List[StepResult]:
    """The canonical set, or the module named by PT_STATICCHECK_STEPS /
    ``steps_file`` (must expose ``collect(root) -> list[StepResult]``)."""
    target = steps_file or os.environ.get(STEPS_ENV)
    if target:
        _force_cpu()
        mod = runpy.run_path(target)
        return list(mod["collect"](root))
    return canonical_steps(root)
