"""Ratcheting baseline: pre-existing violations are recorded, only NEW ones
fail CI. Keys are (rule, path, context) with an occurrence count, so line
drift from unrelated edits doesn't resurface old findings, while adding a
second violation identical in text to a baselined one still fails."""
from __future__ import annotations

import collections
import json
import os

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _counts(findings: list[Finding]) -> dict[str, int]:
    c: collections.Counter[str] = collections.Counter()
    for f in findings:
        c[f.key] += 1
    return dict(sorted(c.items()))


def save_baseline(findings: list[Finding], path: str = DEFAULT_BASELINE,
                  scanned_paths=None, preserve_rule_prefix=None) -> None:
    """Rewrite the baseline from `findings`. With `scanned_paths` (a partial
    scan), only entries whose file lives under one of those paths are
    replaced; everything else is preserved — a scoped `--update-baseline
    some/dir` must not silently drop the grandfathered findings the scan
    never visited. With `preserve_rule_prefix`, existing entries whose
    rule starts with it survive the rewrite — used when a whole tier
    (the jaxpr trace) was skipped, so the update cannot silently drop its
    grandfathered keys."""
    counts = _counts(findings)
    if preserve_rule_prefix:
        kept = {k: v for k, v in load_baseline(path).items()
                if k.split("::", 1)[0].startswith(preserve_rule_prefix)
                and k not in counts}
        counts = dict(sorted({**kept, **counts}.items()))
    if scanned_paths:
        prefixes = tuple(p.strip("/").rstrip("/") for p in scanned_paths)

        def scanned(key: str) -> bool:
            kpath = key.split("::", 2)[1]
            return any(kpath == p or kpath.startswith(p + "/")
                       for p in prefixes)

        kept = {k: v for k, v in load_baseline(path).items()
                if not scanned(k)}
        counts = dict(sorted({**kept, **counts}.items()))
    payload = {
        "version": BASELINE_VERSION,
        "total": sum(counts.values()),
        "counts": counts,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def load_baseline(path: str = DEFAULT_BASELINE) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{payload.get('version')!r}")
    return dict(payload.get("counts", {}))


def new_findings(findings: list[Finding],
                 baseline: dict[str, int]) -> list[Finding]:
    """Findings beyond the baselined count for their key, in input order.
    The first `n` occurrences of a key baselined with count `n` are grandfathered;
    occurrences past that are new."""
    remaining = dict(baseline)
    fresh = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            fresh.append(f)
    return fresh
