"""chaos-site-coverage: every fault site must be in the no-hang matrix.

A ``register_fault("site", ...)`` declaration is a claim: this blocking
window can fail, and the no-hang guarantee covers it. The claim is only
proven by the fault matrix (tests/test_no_hang.py ``MATRIX``), which arms
each site with crash/delay/error/drop and asserts the typed-or-absorbed
outcome end to end. A site registered in code but absent from the matrix
is an UNPROVEN no-hang claim — exactly the gap this rule closes: the
matrix's own runtime assertion (``MATRIX keys == chaos.fault_sites()``)
only fires when the matrix test RUNS, while this rule fails ``--ci`` the
moment the uncovered site lands.

Flags ``register_fault("<literal>", ...)`` calls (and their import-alias
spellings) under ``paddle_tpu/`` whose site string never appears as the
site element of a ``MATRIX`` key in ``tests/test_no_hang.py``. Trees
without a matrix file (fixture projects that don't exercise this rule)
are skipped. Zero entries are baselined; a new site must land together
with its matrix rows.
"""
from __future__ import annotations

import ast
import os

from ..astutil import call_name
from ..core import Checker, Module, Project, parse_file_cached, register

MATRIX_PATH = os.path.join("tests", "test_no_hang.py")
_REGISTER_NAMES = {"register_fault", "_register_fault"}


def _matrix_sites(root: str) -> set[str] | None:
    """Site elements of the MATRIX keys, or None when the tree has no
    matrix file / no MATRIX dict (nothing to cross-check)."""
    path = os.path.join(root, MATRIX_PATH)
    if not os.path.exists(path):
        return None
    try:
        tree = parse_file_cached(root, path).tree
    except (SyntaxError, OSError):
        return None
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "MATRIX"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        sites: set[str] = set()
        for key in node.value.keys:
            if isinstance(key, ast.Tuple) and key.elts \
                    and isinstance(key.elts[0], ast.Constant) \
                    and isinstance(key.elts[0].value, str):
                sites.add(key.elts[0].value)
        return sites
    return None


@register
class ChaosSiteCoverageChecker(Checker):
    rule = "chaos-site-coverage"
    severity = "warning"

    def __init__(self):
        # site -> first (module, node) registration seen
        self._sites: dict[str, tuple[Module, ast.AST]] = {}

    def check_module(self, mod: Module):
        if not mod.path.startswith("paddle_tpu/"):
            return ()
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in _REGISTER_NAMES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            self._sites.setdefault(node.args[0].value, (mod, node))
        return ()

    def finalize(self, project: Project):
        covered = _matrix_sites(project.root)
        if covered is None:
            return
        for site in sorted(set(self._sites) - covered):
            mod, node = self._sites[site]
            yield mod.finding(
                self.rule, self.severity, node,
                f"fault site {site!r} is registered here but absent from "
                f"the no-hang matrix ({MATRIX_PATH} MATRIX) — an unproven "
                f"no-hang claim; add its crash/delay/error/drop rows (the "
                f"matrix asserts the typed-or-absorbed outcome end to end)",
                context=site)
