"""mutable-global: module-level state written outside installer functions.

The dual eager/static recorder (dispatch._static_recorder and friends) is
module-global by design, but every write to module state must go through a
named installer (`set_*`, `reset_*`, ...) so the thread-safety story stays
auditable. Flags (a) `global X; X = ...` rebinding and (b) mutation of
module-level containers (`CACHE[k] = v`, `REGISTRY.append(...)`) from
functions whose names don't look like installers.
"""
from __future__ import annotations

import ast
import re

from ..core import Checker, Module, register

# installer-shaped function names: writes from these are the sanctioned
# path. `__enter__`/`__exit__` are the scoped-guard idiom (push/pop of a
# context) — as auditable as a set_* pair. `export` covers `_export`-style
# module registrars that build `__all__` at import time.
_INSTALLER_RE = re.compile(
    r"^_?(set|install|reset|clear|enable|disable|init|seed|register|"
    r"unregister|switch|use|load|toggle|push|pop|configure|update|export"
    r")|^__(enter|exit)__$")
_MUTATOR_METHODS = {"append", "extend", "insert", "add", "update", "clear",
                    "setdefault", "pop", "popitem", "remove", "discard"}


def _module_level_mutables(tree: ast.Module) -> set[str]:
    """Names bound at module top level to a mutable literal or constructor."""
    out: set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
            isinstance(value, ast.Call) and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "list", "set", "defaultdict",
                                  "OrderedDict", "Counter", "deque"))
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _enclosing_function(node: ast.AST) -> ast.FunctionDef | None:
    cur = getattr(node, "_sc_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_sc_parent", None)
    return None


def _is_local(fn: ast.FunctionDef, name: str) -> bool:
    """Is `name` rebound locally in fn (param or plain assignment), i.e. the
    writes we see target a shadowing local, not the module global?"""
    args = fn.args
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        params.add(args.vararg.arg)
    if args.kwarg:
        params.add(args.kwarg.arg)
    if name in params:
        return True
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(n, (ast.For, ast.comprehension)):
            t = n.target
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name) and leaf.id == name:
                    return True
    return False


@register
class MutableGlobalChecker(Checker):
    rule = "mutable-global"
    severity = "warning"

    def check_module(self, mod: Module):
        mutables = _module_level_mutables(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Global):
                fn = _enclosing_function(node)
                if fn is None or _INSTALLER_RE.match(fn.name):
                    continue
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`global {', '.join(node.names)}` rebound in "
                    f"{fn.name}() — route module-state writes through a "
                    f"set_*/reset_* installer so the dual eager/static "
                    f"recorder stays auditable")
            elif isinstance(node, (ast.Subscript, ast.Attribute)) \
                    and isinstance(getattr(node, "_sc_parent", None),
                                   (ast.Assign, ast.AugAssign)) \
                    and node._sc_parent.value is not node \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in mutables:
                fn = _enclosing_function(node)
                if fn is None or _INSTALLER_RE.match(fn.name) \
                        or _is_local(fn, node.value.id):
                    continue
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"module-level container `{node.value.id}` mutated in "
                    f"{fn.name}() — move the write into a set_*/register_* "
                    f"installer")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATOR_METHODS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in mutables:
                fn = _enclosing_function(node)
                if fn is None or _INSTALLER_RE.match(fn.name) \
                        or _is_local(fn, node.func.value.id):
                    continue
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"module-level container `{node.func.value.id}` mutated "
                    f"via .{node.func.attr}() in {fn.name}() — move the "
                    f"write into a set_*/register_* installer")
