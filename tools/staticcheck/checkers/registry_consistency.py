"""registry-consistency: op_name strings vs the tolerance/coverage registries.

Every op dispatched through apply()/defprim() is supposed to be governed:
either it has a (per-dtype) tolerance entry in tests/op_tolerances.py or it
shows up in the OP_COVERAGE.json enumeration the dtype-sweep battery is
pinned to. Cross-checked both directions:

- an op dispatched in code with neither entry is UNGOVERNED (new ops must
  register; pre-existing ones are baselined — the ratchet stops the set
  from growing);
- a registry name that no dispatch site produces is STALE (a renamed or
  deleted op whose tolerance/coverage entry now governs nothing).

Op names are extracted statically: `op_name="..."` literals, defprim's
positional name, the jax-callable's own name when op_name is omitted
(apply(jnp.tril, ...) -> "tril"), factory indirection — a function whose
body calls apply(..., op_name=<param>) propagates string constants from
its call sites (`abs = _unop("abs", jnp.abs)`) — and instance-attribute
indirection: `apply(..., op_name=self.mode.lower())` where `__init__` binds
`self.mode = <param>` resolves through the string constants subclasses
pass to `super().__init__(...)` (and direct instantiations), lowercased
when the site calls `.lower()` — the rnn.py LSTM/GRU dispatch shape.
An implied name is only believed when it isn't shadowed by a local
binding in the enclosing function (`apply(primal, ...)` where `primal`
is a parameter is a helper, not an op).

Governance resolution follows three routes (PR 11 burn-down — each has a
known-answer fixture):

1. the literal registries: FWD/GRAD_OVERRIDES keys, SKIPS keys,
   OP_COVERAGE.json counts;
2. family-sweep registrations: module-level `for _op in _FAMILY:
   SKIPS.setdefault((_op, ...), ...)` loops over constant name
   collections (the linalg/fft/selection recorded-skip idiom) — these
   govern the ORPHAN direction only; a blanket family record is not a
   per-op claim, so it never makes a name "stale";
3. battery governance: an op whose name is public API (module-level
   `__all__` export — including the loop-built `__all__.append` form —
   or a public module-level def/alias assignment) AND is exercised by
   name somewhere under tests/ (attribute/name reference or a cases-table
   string key) is governed by that battery. Ops reachable only through
   private indirection, or exercised by no battery, stay orphans.
   NAMESPACED families (PR 15): an op name of the form
   ``<module-tail>_<public-name>`` (`sparse_sin` dispatched by the public
   `paddle_tpu.sparse.sin`, including public methods of a public
   module-level class like `sparse.nn.relu`) is governed when a battery
   reaches that exact module — `import paddle_tpu.sparse as S; S.sin(...)`
   resolves to BOTH `sin` and `sparse_sin`, and the public surface gains
   the module-qualified spelling symmetrically. A same-named public op in
   an unrelated module never governs the namespaced one: the qualified
   name only exists on the refs side through an import of that module.
"""
from __future__ import annotations

import ast
import json
import os

from ..astutil import call_name
from ..core import Checker, Finding, Module, Project, register

TOLERANCES_PATH = os.path.join("tests", "op_tolerances.py")
COVERAGE_PATH = "OP_COVERAGE.json"
_ENTRY_NAMES = {"apply", "defprim", "_wrap"}


def _op_name_of_call(node: ast.Call) -> tuple[str | None, bool]:
    """-> (static op name of one apply()/defprim()/_wrap() call or None,
    implied?) — implied means the name came from the callable argument,
    not an explicit op_name=/defprim literal."""
    for kw in node.keywords:
        if kw.arg == "op_name":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value, False
            return None, False  # dynamic op_name — handled by factory pass
    if call_name(node) == "defprim" and len(node.args) > 1 \
            and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        return node.args[1].value, False
    if node.args:
        a0 = node.args[0]
        implied = a0.id if isinstance(a0, ast.Name) else \
            a0.attr if isinstance(a0, ast.Attribute) else None
        # local helper names (`apply(f, ...)`, `apply(_impl, ...)`) are not
        # op names — only believe an implied name that looks like one
        if implied and len(implied) > 2 and not implied.startswith("_"):
            return implied, True
    return None, False


def _locally_bound(node: ast.AST, name: str) -> bool:
    """Is `name` a parameter / local binding of the function enclosing
    `node`? An implied op name that is really a local variable
    (`apply(primals, ...)` in a vjp helper) would otherwise surface as a
    phantom ungoverned op."""
    cur = getattr(node, "_sc_parent", None)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        cur = getattr(cur, "_sc_parent", None)
    if cur is None:
        return False
    args = cur.args
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    for va in (args.vararg, args.kwarg):
        if va is not None:
            params.add(va.arg)
    if name in params:
        return True
    for n in ast.walk(cur):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name) and leaf.id == name:
                        return True
        elif isinstance(n, (ast.For, ast.comprehension)):
            for leaf in ast.walk(n.target):
                if isinstance(leaf, ast.Name) and leaf.id == name:
                    return True
    return False


def _factory_params(tree: ast.AST) -> dict[str, str]:
    """Functions whose body dispatches with op_name=<param>: name -> param."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs}
        for inner in ast.walk(node):
            if not (isinstance(inner, ast.Call)
                    and call_name(inner) in _ENTRY_NAMES):
                continue
            for kw in inner.keywords:
                if kw.arg == "op_name" and isinstance(kw.value, ast.Name) \
                        and kw.value.id in params:
                    out[node.name] = kw.value.id
    return out


def _factory_arg_index(tree: ast.AST, fname: str, param: str) -> int | None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fname:
            names = [a.arg for a in node.args.posonlyargs + node.args.args]
            if param in names:
                return names.index(param)
    return None


def _self_attr_op_name(node: ast.Call):
    """-> (attr, lower?) for op_name=self.X / op_name=self.X.lower()."""
    for kw in node.keywords:
        if kw.arg != "op_name":
            continue
        v = kw.value
        lower = False
        if isinstance(v, ast.Call) and not v.args and not v.keywords \
                and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "lower":
            v = v.func.value
            lower = True
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self":
            return v.attr, lower
    return None


def _class_init(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            return node
    return None


def _init_param_of_attr(init, attr: str):
    """Index (0-based, after self) of the __init__ param bound to
    `self.<attr>`, or None."""
    params = [a.arg for a in init.args.posonlyargs + init.args.args][1:]
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr == attr \
                    and isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in params:
                return params.index(node.value.id), node.value.id
    return None


def _string_consts(expr, scope=None, depth=0) -> list[str]:
    """String constants an expression can evaluate to: a literal, a
    constant-armed conditional (`"A" if cond else "B"`), or a local name
    bound to either within `scope` (the enclosing function)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        return (_string_consts(expr.body, scope, depth)
                + _string_consts(expr.orelse, scope, depth))
    if isinstance(expr, ast.Name) and scope is not None and depth < 2:
        out = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == expr.id:
                out += _string_consts(node.value, scope, depth + 1)
        return out
    return []


def _const_args(call: ast.Call, idx: int, pname: str, scope=None) -> list[str]:
    for kw in call.keywords:
        if kw.arg == pname:
            return _string_consts(kw.value, scope)
    if idx < len(call.args):
        return _string_consts(call.args[idx], scope)
    return []


def _load_tolerance_names(root: str) -> set[str] | None:
    """Keys of FWD_OVERRIDES/GRAD_OVERRIDES/SKIPS, parsed without import."""
    path = os.path.join(root, TOLERANCES_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    names: set[str] = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Name)):
            continue
        target = node.targets[0].id
        try:
            value = ast.literal_eval(node.value)
        except ValueError:
            continue
        if target in ("FWD_OVERRIDES", "GRAD_OVERRIDES"):
            names |= set(value)
        elif target == "SKIPS":
            names |= {k[0] for k in value}
    return names


def _load_coverage_names(root: str) -> set[str] | None:
    path = os.path.join(root, COVERAGE_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return set(json.load(f).get("counts", {}))


_REGISTRY_DICTS = {"SKIPS", "FWD_OVERRIDES", "GRAD_OVERRIDES"}


def _const_str_seq(node: ast.AST, seqs: dict) -> list[str] | None:
    """A constant sequence of strings: a literal tuple/list, or a Name
    bound at module level to one (collected into `seqs`)."""
    if isinstance(node, ast.Name):
        return seqs.get(node.id)
    try:
        v = ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None
    if isinstance(v, (tuple, list)) and v \
            and all(isinstance(x, str) for x in v):
        return list(v)
    return None


def _family_skip_entries(root: str) -> set[tuple]:
    """Registry keys registered by module-level family-sweep loops
    (`for _op in _LINALG_OPS: SKIPS.setdefault((_op, check, dt), reason)`)
    — the alias-collection registration the literal parser above can't
    follow. Keys expand the cross product of every loop-bound element;
    unresolvable elements become the ``"*"`` wildcard. Shared with the
    dtype-rule-coverage checker so a loop-skipped family never counts as
    an uncovered hole."""
    path = os.path.join(root, TOLERANCES_PATH)
    if not os.path.exists(path):
        return set()
    from ..core import parse_file_cached
    tree = parse_file_cached(root, path).tree
    seqs: dict[str, list[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            vals = _const_str_seq(node.value, {})
            if vals:
                seqs[node.targets[0].id] = vals
    entries: set[tuple] = set()

    def elt_values(e: ast.AST, bindings: dict) -> list[str]:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            return [e.value]
        if isinstance(e, ast.Name) and e.id in bindings:
            return bindings[e.id]
        return ["*"]

    def key_entries(key: ast.AST, bindings: dict) -> list[tuple]:
        elts = key.elts if isinstance(key, ast.Tuple) else [key]
        out: list[tuple] = [()]
        for e in elts:
            out = [t + (v,) for t in out for v in elt_values(e, bindings)]
        # a key whose op element is unresolved governs nothing
        return [t for t in out if t and t[0] != "*"]

    def walk(stmts, bindings: dict):
        for stmt in stmts:
            if isinstance(stmt, ast.For):
                vals = _const_str_seq(stmt.iter, seqs)
                inner = dict(bindings)
                if vals is not None and isinstance(stmt.target, ast.Name):
                    inner[stmt.target.id] = vals
                walk(stmt.body, inner)
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("setdefault", "update") \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in _REGISTRY_DICTS \
                        and node.args:
                    entries.update(key_entries(node.args[0], bindings))
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Subscript) \
                        and isinstance(node.targets[0].value, ast.Name) \
                        and node.targets[0].value.id in _REGISTRY_DICTS:
                    entries.update(
                        key_entries(node.targets[0].slice, bindings))

    walk(tree.body, {})
    return entries


def _load_family_skip_names(root: str) -> set[str]:
    """Op names of the family-sweep registrations. Orphan-direction
    governance only: a blanket family record never makes a name
    'stale'."""
    return {e[0] for e in _family_skip_entries(root)}


_TEST_SCAN_EXCLUDE = {"__pycache__", "fixtures", "staticcheck_proj"}


def _module_tails(path: str) -> tuple[str, ...]:
    """Module-tail prefixes of one source path below the package root:
    ``paddle_tpu/sparse/__init__.py`` -> ``("sparse",)``. The public-
    surface side of the namespaced-family route — symmetrical with the
    alias prefixes _module_pkg_aliases derives on the refs side."""
    parts = path[:-3].split("/") if path.endswith(".py") else path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return _tail_prefixes(parts[1:])


def _public_surface(project: Project) -> set[str]:
    """Names the scanned package exports: module-level `__all__` entries
    (literal assigns, `+=`, `.extend(...)`, `.append(...)` — including
    appends loop-bound over constant name collections) plus public
    module-level defs and alias assignments (`acos = _unop("acos", ...)`).
    Each def/alias also contributes its module-qualified spelling
    (`sparse_sin` for `paddle_tpu.sparse.sin`), and a public module-level
    class contributes qualified spellings of its public methods
    (`sparse_relu` for `paddle_tpu.sparse.nn.relu`) — the namespaced-op
    families whose op names prefix the public name with the module tail."""
    out: set[str] = set()
    for mod in project.modules:
        if not mod.path.startswith("paddle_tpu"):
            continue
        tails = _module_tails(mod.path)
        seqs: dict[str, list[str]] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                vals = _const_str_seq(node.value, {})
                if vals:
                    seqs[node.targets[0].id] = vals

        def _public(name: str) -> None:
            out.add(name)
            for pref in tails:
                out.add(f"{pref}_{name}")

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not node.name.startswith("_"):
                _public(node.name)
            elif isinstance(node, ast.ClassDef) \
                    and not node.name.startswith("_"):
                # qualified spellings ONLY: a public method name is not a
                # bare public-op surface (that would let any `log_prob`
                # reference govern every class's log_prob), but its
                # module-qualified form is unambiguous
                for sub in node.body:
                    if isinstance(sub,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)) \
                            and not sub.name.startswith("_"):
                        for pref in tails:
                            out.add(f"{pref}_{sub.name}")
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.Call, ast.Name, ast.Attribute)):
                # alias registrations only (`acos = _unop("acos", ...)`,
                # re-binds of callables) — a constant assignment like
                # `PAGE_SIZE = 16` is config, not public-op surface
                for t in node.targets:
                    if isinstance(t, ast.Name) and not t.id.startswith("_") \
                            and t.id != "__all__":
                        _public(t.id)
        for node in ast.walk(mod.tree):
            lit = None
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets):
                lit = node.value
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == "__all__":
                lit = node.value
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "extend") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "__all__" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    out.add(a0.value)
                    continue
                if isinstance(a0, ast.Name):
                    # `for name in _NAMES: __all__.append(name)` — resolve
                    # through the nearest enclosing for over a const seq
                    cur = getattr(node, "_sc_parent", None)
                    while cur is not None:
                        if isinstance(cur, ast.For) \
                                and isinstance(cur.target, ast.Name) \
                                and cur.target.id == a0.id:
                            vals = _const_str_seq(cur.iter, seqs)
                            if vals:
                                out.update(vals)
                            break
                        cur = getattr(cur, "_sc_parent", None)
                    continue
                lit = a0
            if lit is not None:
                try:
                    v = ast.literal_eval(lit)
                except (ValueError, TypeError, SyntaxError):
                    continue
                if isinstance(v, (tuple, list)):
                    out.update(x for x in v if isinstance(x, str))
    return out


_PKG = "paddle_tpu"


def _tail_prefixes(parts: list[str]) -> tuple[str, ...]:
    """Underscore-joined suffixes of a module path below the package root:
    ``["nn", "functional"]`` -> ``("nn_functional", "functional")`` — the
    spellings a namespaced op name may be qualified with."""
    return tuple("_".join(parts[i:]) for i in range(len(parts)))


def _module_pkg_aliases(
        tree: ast.Module) -> tuple[set[str], set[str], dict]:
    """-> (attr_bases, bare_names, prefixes) the module binds to the
    package: `import paddle_tpu as P` / `import paddle_tpu.nn.functional
    as F` give attribute bases; `from paddle_tpu.x import name [as n]`
    gives bare names (the imported name is itself a package reference).
    `prefixes` maps each attribute base to the module-tail spellings its
    attributes may be namespaced under (`import paddle_tpu.sparse as S`
    -> S: ("sparse",), so `S.sin` also references `sparse_sin`)."""
    bases: set[str] = set()
    bare: set[str] = set()
    prefixes: dict[str, tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _PKG or a.name.startswith(_PKG + "."):
                    alias = a.asname or a.name.split(".", 1)[0]
                    bases.add(alias)
                    # namespaced prefixes ONLY for an asname that names
                    # the submodule: a bare `import paddle_tpu.sparse`
                    # binds the ROOT package, and attaching the sparse
                    # prefix to it would let any `paddle_tpu.X` falsely
                    # govern `sparse_X`
                    if a.asname is not None:
                        tails = _tail_prefixes(a.name.split(".")[1:])
                        if tails:
                            prefixes[alias] = tails
        elif isinstance(node, ast.ImportFrom) and node.module \
                and (node.module == _PKG
                     or node.module.startswith(_PKG + ".")):
            for a in node.names:
                bare.add(a.asname or a.name)
                bare.add(a.name)
    return bases, bare, prefixes


def _battery_references(root: str) -> set[str]:
    """Names the test batteries under <root>/tests/ reference THROUGH the
    package: attributes whose base resolves to a paddle_tpu import alias
    (`P.acos`, `F.relu`, OpTest cases passing `P.acos` uncalled), names
    imported from the package (`from paddle_tpu.models import ...`), and
    string keys of dict-literal cases tables (`"acos": Case(...)`).
    Incidental identifiers — loop variables, builtins, np./jnp. usage —
    never count (an op must be exercised via the package to be battery
    governed). The registry file itself (op_tolerances.py) is excluded —
    references there ARE the registry, already loaded above."""
    from ..astutil import attr_root
    from ..core import parse_file_cached
    refs: set[str] = set()
    tests_dir = os.path.join(root, "tests")
    if not os.path.isdir(tests_dir):
        return refs
    for dirpath, dirs, files in os.walk(tests_dir):
        dirs[:] = sorted(d for d in dirs if d not in _TEST_SCAN_EXCLUDE)
        for fn in sorted(files):
            if not fn.endswith(".py") or fn == "op_tolerances.py":
                continue
            try:
                mod = parse_file_cached(root, os.path.join(dirpath, fn))
            except (SyntaxError, OSError):
                continue
            bases, bare, prefixes = _module_pkg_aliases(mod.tree)
            refs |= bare

            def pkg_ref_in(node) -> bool:
                for n in ast.walk(node):
                    if isinstance(n, ast.Attribute) \
                            and attr_root(n) in bases:
                        return True
                    if isinstance(n, ast.Name) and n.id in bare:
                        return True
                return False

            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Attribute):
                    base = attr_root(node)
                    if base in bases:
                        refs.add(node.attr)
                        # module-qualified spelling: S.sin through
                        # `import paddle_tpu.sparse as S` also exercises
                        # the namespaced op name `sparse_sin`
                        for pref in prefixes.get(base, ()):
                            refs.add(f"{pref}_{node.attr}")
                elif isinstance(node, ast.Dict):
                    # cases-table keys count only when the table's VALUES
                    # reach the package (`"acos": Case(P.acos, ...)`) —
                    # a config dict like {"dropout": 0.1} governs nothing
                    if not any(v is not None and pkg_ref_in(v)
                               for v in node.values):
                        continue
                    for k in node.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str) \
                                and k.value.isidentifier():
                            refs.add(k.value)
    return refs


@register
class RegistryConsistencyChecker(Checker):
    rule = "registry-consistency"
    severity = "warning"

    def __init__(self):
        # op name -> first (module, node) dispatch site seen
        self._sites: dict[str, tuple[Module, ast.AST]] = {}
        # pending factory indirection, resolved in finalize
        self._factories: dict[str, tuple[Module, str]] = {}
        self._calls: list[tuple[Module, ast.Call]] = []
        # instance-attribute indirection (op_name=self.X[.lower()]):
        # class name -> (module, ClassDef); pending sites to resolve
        self._classes: dict[str, tuple[Module, ast.ClassDef]] = {}
        self._attr_sites: list[tuple[Module, ast.Call, str, str, bool]] = []

    def check_module(self, mod: Module):
        if not mod.path.startswith("paddle_tpu"):
            return ()
        for fname, param in _factory_params(mod.tree).items():
            self._factories[fname] = (mod, param)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            self._classes.setdefault(cls.name, (mod, cls))
            for node in ast.walk(cls):
                if isinstance(node, ast.Call) \
                        and call_name(node) in _ENTRY_NAMES:
                    dyn = _self_attr_op_name(node)
                    if dyn is not None:
                        self._attr_sites.append(
                            (mod, node, cls.name, dyn[0], dyn[1]))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                self._calls.append((mod, node))
                if call_name(node) in _ENTRY_NAMES:
                    name, implied = _op_name_of_call(node)
                    if name and not (implied
                                     and _locally_bound(node, name)):
                        self._sites.setdefault(name, (mod, node))
        return ()

    def _resolve_self_attr_sites(self):
        """op_name=self.X[.lower()]: resolve through the string constants
        flowing into the binding __init__ parameter — from subclasses'
        `super().__init__(...)` / `Base.__init__(self, ...)` calls and from
        direct instantiations."""
        for mod, node, cls_name, attr, lower in self._attr_sites:
            entry = self._classes.get(cls_name)
            if entry is None:
                continue
            init = _class_init(entry[1])
            if init is None:
                continue
            bound = _init_param_of_attr(init, attr)
            if bound is None:
                continue
            idx, pname = bound
            values: list[str] = []
            # subclass super().__init__ / Base.__init__ forwarding
            for _, sub_cls in self._classes.values():
                bases = {b.id for b in sub_cls.bases
                         if isinstance(b, ast.Name)}
                if cls_name not in bases:
                    continue
                sub_init = _class_init(sub_cls)
                if sub_init is None:
                    continue
                for call in ast.walk(sub_init):
                    if not isinstance(call, ast.Call):
                        continue
                    f = call.func
                    is_super = (isinstance(f, ast.Attribute)
                                and f.attr == "__init__"
                                and isinstance(f.value, ast.Call)
                                and isinstance(f.value.func, ast.Name)
                                and f.value.func.id == "super")
                    is_direct = (isinstance(f, ast.Attribute)
                                 and f.attr == "__init__"
                                 and isinstance(f.value, ast.Name)
                                 and f.value.id == cls_name)
                    if not (is_super or is_direct):
                        continue
                    off = 1 if is_direct else 0  # explicit self argument
                    values += _const_args(call, idx + off, pname,
                                          scope=sub_init)
            # direct instantiations of the class itself
            for call_mod, call in self._calls:
                if isinstance(call.func, ast.Name) \
                        and call.func.id == cls_name:
                    values += _const_args(call, idx, pname)
            for v in values:
                self._sites.setdefault(v.lower() if lower else v, (mod, node))

    def _resolve_factory_sites(self):
        for fname, (fmod, param) in self._factories.items():
            idx = _factory_arg_index(fmod.tree, fname, param)
            for mod, node in self._calls:
                if call_name(node) != fname:
                    continue
                value = None
                for kw in node.keywords:
                    if kw.arg == param:
                        value = kw.value
                if value is None and idx is not None and idx < len(node.args):
                    value = node.args[idx]
                if isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    self._sites.setdefault(value.value, (mod, node))

    def finalize(self, project: Project):
        tol = _load_tolerance_names(project.root)
        cov = _load_coverage_names(project.root)
        if tol is None and cov is None:
            return  # no registries in this tree — nothing to cross-check
        self._resolve_factory_sites()
        self._resolve_self_attr_sites()
        registry = (tol or set()) | (cov or set())
        # orphan-direction governance beyond the literal registries:
        # family-sweep skip loops + battery-exercised public ops
        family = _load_family_skip_names(project.root)
        battery = _public_surface(project) & _battery_references(project.root)
        governed = registry | family | battery
        for name in sorted(set(self._sites) - governed):
            mod, node = self._sites[name]
            yield mod.finding(
                self.rule, self.severity, node,
                f"op {name!r} is dispatched here but has no tolerance "
                f"entry in {TOLERANCES_PATH}, no {COVERAGE_PATH} record, "
                f"no family-sweep skip, and no test battery references it "
                f"by name — ungoverned ops can silently regress",
                context=name)
        for name in sorted(registry - set(self._sites)):
            where = []
            if tol and name in tol:
                where.append(TOLERANCES_PATH)
            if cov and name in cov:
                where.append(COVERAGE_PATH)
            yield Finding(
                rule=self.rule, severity="error", path=where[0], line=1,
                col=0, context=name,
                message=f"registry entry {name!r} ({' + '.join(where)}) "
                        f"matches no dispatch site in paddle_tpu/ — stale "
                        f"after a rename/delete, or the extractor can't "
                        f"see the site (add an explicit op_name=)")
