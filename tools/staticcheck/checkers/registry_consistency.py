"""registry-consistency: op_name strings vs the tolerance/coverage registries.

Every op dispatched through apply()/defprim() is supposed to be governed:
either it has a (per-dtype) tolerance entry in tests/op_tolerances.py or it
shows up in the OP_COVERAGE.json enumeration the dtype-sweep battery is
pinned to. Cross-checked both directions:

- an op dispatched in code with neither entry is UNGOVERNED (new ops must
  register; pre-existing ones are baselined — the ratchet stops the set
  from growing);
- a registry name that no dispatch site produces is STALE (a renamed or
  deleted op whose tolerance/coverage entry now governs nothing).

Op names are extracted statically: `op_name="..."` literals, defprim's
positional name, the jax-callable's own name when op_name is omitted
(apply(jnp.tril, ...) -> "tril"), factory indirection — a function whose
body calls apply(..., op_name=<param>) propagates string constants from
its call sites (`abs = _unop("abs", jnp.abs)`) — and instance-attribute
indirection: `apply(..., op_name=self.mode.lower())` where `__init__` binds
`self.mode = <param>` resolves through the string constants subclasses
pass to `super().__init__(...)` (and direct instantiations), lowercased
when the site calls `.lower()` — the rnn.py LSTM/GRU dispatch shape.
"""
from __future__ import annotations

import ast
import json
import os

from ..astutil import call_name
from ..core import Checker, Finding, Module, Project, register

TOLERANCES_PATH = os.path.join("tests", "op_tolerances.py")
COVERAGE_PATH = "OP_COVERAGE.json"
_ENTRY_NAMES = {"apply", "defprim", "_wrap"}


def _op_name_of_call(node: ast.Call) -> str | None:
    """Static op name of one apply()/defprim()/_wrap() call, or None."""
    for kw in node.keywords:
        if kw.arg == "op_name":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
            return None  # op_name is dynamic — handled by factory pass
    if call_name(node) == "defprim" and len(node.args) > 1 \
            and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        return node.args[1].value
    if node.args:
        a0 = node.args[0]
        implied = a0.id if isinstance(a0, ast.Name) else \
            a0.attr if isinstance(a0, ast.Attribute) else None
        # local helper names (`apply(f, ...)`, `apply(_impl, ...)`) are not
        # op names — only believe an implied name that looks like one
        if implied and len(implied) > 2 and not implied.startswith("_"):
            return implied
    return None


def _factory_params(tree: ast.AST) -> dict[str, str]:
    """Functions whose body dispatches with op_name=<param>: name -> param."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs}
        for inner in ast.walk(node):
            if not (isinstance(inner, ast.Call)
                    and call_name(inner) in _ENTRY_NAMES):
                continue
            for kw in inner.keywords:
                if kw.arg == "op_name" and isinstance(kw.value, ast.Name) \
                        and kw.value.id in params:
                    out[node.name] = kw.value.id
    return out


def _factory_arg_index(tree: ast.AST, fname: str, param: str) -> int | None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fname:
            names = [a.arg for a in node.args.posonlyargs + node.args.args]
            if param in names:
                return names.index(param)
    return None


def _self_attr_op_name(node: ast.Call):
    """-> (attr, lower?) for op_name=self.X / op_name=self.X.lower()."""
    for kw in node.keywords:
        if kw.arg != "op_name":
            continue
        v = kw.value
        lower = False
        if isinstance(v, ast.Call) and not v.args and not v.keywords \
                and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "lower":
            v = v.func.value
            lower = True
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self":
            return v.attr, lower
    return None


def _class_init(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            return node
    return None


def _init_param_of_attr(init, attr: str):
    """Index (0-based, after self) of the __init__ param bound to
    `self.<attr>`, or None."""
    params = [a.arg for a in init.args.posonlyargs + init.args.args][1:]
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr == attr \
                    and isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in params:
                return params.index(node.value.id), node.value.id
    return None


def _string_consts(expr, scope=None, depth=0) -> list[str]:
    """String constants an expression can evaluate to: a literal, a
    constant-armed conditional (`"A" if cond else "B"`), or a local name
    bound to either within `scope` (the enclosing function)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        return (_string_consts(expr.body, scope, depth)
                + _string_consts(expr.orelse, scope, depth))
    if isinstance(expr, ast.Name) and scope is not None and depth < 2:
        out = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == expr.id:
                out += _string_consts(node.value, scope, depth + 1)
        return out
    return []


def _const_args(call: ast.Call, idx: int, pname: str, scope=None) -> list[str]:
    for kw in call.keywords:
        if kw.arg == pname:
            return _string_consts(kw.value, scope)
    if idx < len(call.args):
        return _string_consts(call.args[idx], scope)
    return []


def _load_tolerance_names(root: str) -> set[str] | None:
    """Keys of FWD_OVERRIDES/GRAD_OVERRIDES/SKIPS, parsed without import."""
    path = os.path.join(root, TOLERANCES_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    names: set[str] = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Name)):
            continue
        target = node.targets[0].id
        try:
            value = ast.literal_eval(node.value)
        except ValueError:
            continue
        if target in ("FWD_OVERRIDES", "GRAD_OVERRIDES"):
            names |= set(value)
        elif target == "SKIPS":
            names |= {k[0] for k in value}
    return names


def _load_coverage_names(root: str) -> set[str] | None:
    path = os.path.join(root, COVERAGE_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return set(json.load(f).get("counts", {}))


@register
class RegistryConsistencyChecker(Checker):
    rule = "registry-consistency"
    severity = "warning"

    def __init__(self):
        # op name -> first (module, node) dispatch site seen
        self._sites: dict[str, tuple[Module, ast.AST]] = {}
        # pending factory indirection, resolved in finalize
        self._factories: dict[str, tuple[Module, str]] = {}
        self._calls: list[tuple[Module, ast.Call]] = []
        # instance-attribute indirection (op_name=self.X[.lower()]):
        # class name -> (module, ClassDef); pending sites to resolve
        self._classes: dict[str, tuple[Module, ast.ClassDef]] = {}
        self._attr_sites: list[tuple[Module, ast.Call, str, str, bool]] = []

    def check_module(self, mod: Module):
        if not mod.path.startswith("paddle_tpu"):
            return ()
        for fname, param in _factory_params(mod.tree).items():
            self._factories[fname] = (mod, param)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            self._classes.setdefault(cls.name, (mod, cls))
            for node in ast.walk(cls):
                if isinstance(node, ast.Call) \
                        and call_name(node) in _ENTRY_NAMES:
                    dyn = _self_attr_op_name(node)
                    if dyn is not None:
                        self._attr_sites.append(
                            (mod, node, cls.name, dyn[0], dyn[1]))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                self._calls.append((mod, node))
                if call_name(node) in _ENTRY_NAMES:
                    name = _op_name_of_call(node)
                    if name:
                        self._sites.setdefault(name, (mod, node))
        return ()

    def _resolve_self_attr_sites(self):
        """op_name=self.X[.lower()]: resolve through the string constants
        flowing into the binding __init__ parameter — from subclasses'
        `super().__init__(...)` / `Base.__init__(self, ...)` calls and from
        direct instantiations."""
        for mod, node, cls_name, attr, lower in self._attr_sites:
            entry = self._classes.get(cls_name)
            if entry is None:
                continue
            init = _class_init(entry[1])
            if init is None:
                continue
            bound = _init_param_of_attr(init, attr)
            if bound is None:
                continue
            idx, pname = bound
            values: list[str] = []
            # subclass super().__init__ / Base.__init__ forwarding
            for _, sub_cls in self._classes.values():
                bases = {b.id for b in sub_cls.bases
                         if isinstance(b, ast.Name)}
                if cls_name not in bases:
                    continue
                sub_init = _class_init(sub_cls)
                if sub_init is None:
                    continue
                for call in ast.walk(sub_init):
                    if not isinstance(call, ast.Call):
                        continue
                    f = call.func
                    is_super = (isinstance(f, ast.Attribute)
                                and f.attr == "__init__"
                                and isinstance(f.value, ast.Call)
                                and isinstance(f.value.func, ast.Name)
                                and f.value.func.id == "super")
                    is_direct = (isinstance(f, ast.Attribute)
                                 and f.attr == "__init__"
                                 and isinstance(f.value, ast.Name)
                                 and f.value.id == cls_name)
                    if not (is_super or is_direct):
                        continue
                    off = 1 if is_direct else 0  # explicit self argument
                    values += _const_args(call, idx + off, pname,
                                          scope=sub_init)
            # direct instantiations of the class itself
            for call_mod, call in self._calls:
                if isinstance(call.func, ast.Name) \
                        and call.func.id == cls_name:
                    values += _const_args(call, idx, pname)
            for v in values:
                self._sites.setdefault(v.lower() if lower else v, (mod, node))

    def _resolve_factory_sites(self):
        for fname, (fmod, param) in self._factories.items():
            idx = _factory_arg_index(fmod.tree, fname, param)
            for mod, node in self._calls:
                if call_name(node) != fname:
                    continue
                value = None
                for kw in node.keywords:
                    if kw.arg == param:
                        value = kw.value
                if value is None and idx is not None and idx < len(node.args):
                    value = node.args[idx]
                if isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    self._sites.setdefault(value.value, (mod, node))

    def finalize(self, project: Project):
        tol = _load_tolerance_names(project.root)
        cov = _load_coverage_names(project.root)
        if tol is None and cov is None:
            return  # no registries in this tree — nothing to cross-check
        self._resolve_factory_sites()
        self._resolve_self_attr_sites()
        registry = (tol or set()) | (cov or set())
        for name in sorted(set(self._sites) - registry):
            mod, node = self._sites[name]
            yield mod.finding(
                self.rule, self.severity, node,
                f"op {name!r} is dispatched here but has no tolerance "
                f"entry in {TOLERANCES_PATH} and no {COVERAGE_PATH} record "
                f"— ungoverned ops can silently regress",
                context=name)
        for name in sorted(registry - set(self._sites)):
            where = []
            if tol and name in tol:
                where.append(TOLERANCES_PATH)
            if cov and name in cov:
                where.append(COVERAGE_PATH)
            yield Finding(
                rule=self.rule, severity="error", path=where[0], line=1,
                col=0, context=name,
                message=f"registry entry {name!r} ({' + '.join(where)}) "
                        f"matches no dispatch site in paddle_tpu/ — stale "
                        f"after a rename/delete, or the extractor can't "
                        f"see the site (add an explicit op_name=)")
