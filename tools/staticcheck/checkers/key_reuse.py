"""key-reuse: the same jax.random key consumed twice.

JAX PRNG keys are single-use values: every draw must come off a FRESH key
(`key, sub = jax.random.split(key)`); feeding the same key to two
`jax.random.*` calls — or using a key again after splitting it — yields
correlated "random" numbers that silently wreck initialization and
dropout independence. The deferred ROADMAP rule, now implemented.

Heuristic, per scope (function body or module top level), in source order:
- a name becomes a KEY when it is assigned from a producer call
  (`jax.random.key/PRNGKey/split/fold_in/clone`, `next_key()`) or is a
  parameter with a key-like name (`key`, `rng`, `*_key`);
- passing a key as the first positional argument (or `key=` keyword) of a
  `jax.random.*` call CONSUMES it — including `split`/`fold_in` (using the
  parent key after splitting it is the classic form of this bug);
- rebinding the name un-consumes it;
- two consuming uses in SIBLING branches of the same `if` are mutually
  exclusive and never flagged; a use whose branch path is a prefix of the
  other's (same straight line, or one nested under the other) is.

Uses inside loop bodies appear once to this linear scan, so a key consumed
once per iteration without rebinding is not caught — fold_in with the loop
index (the repo idiom) is the fix for those sites anyway.
"""
from __future__ import annotations

import ast

from ..astutil import call_name
from ..core import Checker, Module, register

_PRODUCER_NAMES = {"key", "PRNGKey", "split", "fold_in", "clone", "next_key",
                   "wrap_key_data"}
_KEYISH_PARAMS = ("key", "rng")


def _is_random_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute):
        # bare next_key() / key_override-style helpers
        return isinstance(f, ast.Name) and f.id in ("next_key",)
    # must be the jax.random NAMESPACE, not just anything rooted at `jax`:
    # jax.device_put(key) / jax.vmap(f)(key) do not consume the key
    chain = []
    cur = f
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    root = cur.id if isinstance(cur, ast.Name) else None
    if root == "jax":
        return len(chain) >= 2 and chain[-1] == "random"
    # `import jax.random as X` aliases: X.split / X.normal
    return root in ("random", "jrandom", "jr") and len(chain) == 1


def _is_producer(node: ast.Call) -> bool:
    return _is_random_call(node) and call_name(node) in _PRODUCER_NAMES


def _consumed_key_arg(node: ast.Call):
    """The ast.Name this jax.random call consumes as its key, if any."""
    if not _is_random_call(node) or call_name(node) in ("key", "PRNGKey"):
        return None  # seed-int producers consume no key
    if node.args and isinstance(node.args[0], ast.Name):
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value
    return None


def _branch_path(node: ast.AST, scope: ast.AST) -> tuple:
    """(id(if_node), arm) pairs from `scope` down to `node` — two uses
    conflict only when one path is a prefix of the other (mutually
    exclusive if/else arms are not both taken)."""
    path = []
    cur = node
    while cur is not None and cur is not scope:
        parent = getattr(cur, "_sc_parent", None)
        if isinstance(parent, (ast.If, ast.Try)):
            for arm in ("body", "orelse", "handlers", "finalbody"):
                block = getattr(parent, arm, None)
                if isinstance(block, list) and cur in block:
                    path.append((id(parent), arm))
                    break
        cur = parent
    return tuple(reversed(path))


_TERMINATORS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _arm_terminates(owner: ast.AST, arm: str) -> bool:
    """Does this if/try arm end in return/raise/continue/break? If so, code
    AFTER the statement is mutually exclusive with the arm's interior."""
    block = getattr(owner, arm, None)
    if not isinstance(block, list) or not block:
        return False
    last = block[-1]
    return isinstance(last, _TERMINATORS)


def _conflicting(prev_path: tuple, new_path: tuple,
                 owners: dict[int, ast.AST]) -> bool:
    """prev (earlier in source) and new conflict unless control flow makes
    them mutually exclusive: sibling arms of one if, or prev inside an arm
    that terminates before new's straight-line position."""
    common = 0
    while common < len(prev_path) and common < len(new_path) \
            and prev_path[common] == new_path[common]:
        common += 1
    if common < len(prev_path) and common < len(new_path):
        return False  # diverge into sibling arms: never both taken
    if common == len(prev_path):
        return True   # prev dominates new (same line of flow, or new nested)
    # prev is deeper: reaching new means prev's arm exited or wasn't taken
    owner_id, arm = prev_path[common]
    return not _arm_terminates(owners.get(owner_id), arm)


def _assigned_names(stmt: ast.AST):
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    out = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.append(n)
    return out


@register
class KeyReuseChecker(Checker):
    rule = "key-reuse"
    severity = "warning"

    def check_module(self, mod: Module):
        scopes = [mod.tree]
        scopes += [n for n in ast.walk(mod.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_scope(mod, scope)

    def _check_scope(self, mod: Module, scope: ast.AST):
        own_fns = {id(n) for n in ast.walk(scope)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n is not scope} if not isinstance(scope, ast.Module) \
            else {id(n) for n in ast.walk(scope)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        def nodes_of(kind):
            for n in ast.walk(scope):
                if not isinstance(n, kind) or n is scope:
                    continue
                # stay in THIS scope: skip anything inside a nested function
                cur = getattr(n, "_sc_parent", None)
                nested = False
                while cur is not None and cur is not scope:
                    if id(cur) in own_fns:
                        nested = True
                        break
                    cur = getattr(cur, "_sc_parent", None)
                if not nested:
                    yield n

        owners = {id(n): n for n in ast.walk(scope)
                  if isinstance(n, (ast.If, ast.Try))}

        keys: set[str] = set()
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in scope.args.posonlyargs + scope.args.args \
                    + scope.args.kwonlyargs:
                if a.arg in _KEYISH_PARAMS or a.arg.endswith("_key"):
                    keys.add(a.arg)

        # events in source order: (line, col, kind, payload)
        events = []
        for call in nodes_of(ast.Call):
            name_node = _consumed_key_arg(call)
            if name_node is not None:
                events.append((name_node.lineno, name_node.col_offset,
                               "use", (name_node, call)))
        for stmt in nodes_of((ast.Assign, ast.AugAssign, ast.AnnAssign,
                              ast.For, ast.AsyncFor)):
            value = getattr(stmt, "value", None) or getattr(stmt, "iter", None)
            produced = any(_is_producer(c) for c in ast.walk(value)
                           if isinstance(c, ast.Call)) if value is not None \
                else False
            for n in _assigned_names(stmt):
                # bindings land AFTER the value's uses on the same line
                events.append((n.lineno, n.col_offset + 10_000, "bind",
                               (n.id, produced)))
        events.sort(key=lambda e: (e[0], e[1]))

        spent: dict[str, ast.AST] = {}
        for _, _, kind, payload in events:
            if kind == "bind":
                name, produced = payload
                spent.pop(name, None)
                if produced:
                    keys.add(name)
            else:
                name_node, call = payload
                name = name_node.id
                if name not in keys:
                    continue
                prev = spent.get(name)
                if prev is not None and _conflicting(
                        _branch_path(prev, scope),
                        _branch_path(name_node, scope), owners):
                    yield mod.finding(
                        self.rule, self.severity, call,
                        f"key {name!r} already consumed at line "
                        f"{prev.lineno} — split a fresh subkey "
                        f"(`{name}, sub = jax.random.split({name})`) instead "
                        f"of drawing twice from the same key")
                else:
                    spent[name] = name_node
