"""dead-export: `__all__` names that don't resolve at module scope.

A name exported in a literal `__all__` but never bound at module level is
an ImportError waiting for `from mod import *` (and breaks the namespace
parity test's notion of the public surface). Modules that build `__all__`
dynamically (append in a loop, `globals()[...]` registration — e.g.
ops/breadth.py) are skipped: the binding set isn't statically resolvable.
"""
from __future__ import annotations

import ast

from ..core import Checker, Module, register


def _literal_strs(node: ast.AST) -> list[str] | None:
    """Strings of a literal list/tuple (or concatenation of them)."""
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _literal_strs(node.left)
        right = _literal_strs(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def _collect_exports(tree: ast.Module):
    """-> (exports with nodes, dynamic?) — dynamic means some write to
    __all__ couldn't be resolved to literal strings."""
    exports: list[tuple[str, ast.AST]] = []
    dynamic = False
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name) and t.id == "__all__"
                       for t in targets):
                continue
            strs = _literal_strs(node.value)
            if strs is None:
                dynamic = True
            else:
                exports.extend((s, node) for s in strs)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "__all__":
            if node.func.attr == "extend" and node.args:
                strs = _literal_strs(node.args[0])
                if strs is None:
                    dynamic = True
                else:
                    exports.extend((s, node) for s in strs)
            else:
                dynamic = True  # .append in a helper/loop etc.
    return exports, dynamic


def _bound_names(body: list[ast.stmt]) -> set[str]:
    """Names bound at module scope — recursing into If/Try/For/While/With
    blocks but not into function/class bodies."""
    names: set[str] = set()
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(stmt.name)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                if alias.name == "*":
                    names.add("*")
                else:
                    names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        elif isinstance(stmt, (ast.If, ast.Try, ast.For, ast.While,
                               ast.With)):
            for attr in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, attr, [])
                for item in sub:
                    if isinstance(item, ast.ExceptHandler):
                        names |= _bound_names(item.body)
                    elif isinstance(item, ast.stmt):
                        names |= _bound_names([item])
            if isinstance(stmt, ast.For):
                for leaf in ast.walk(stmt.target):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
            if isinstance(stmt, ast.With):
                for it in stmt.items:
                    if it.optional_vars is not None:
                        for leaf in ast.walk(it.optional_vars):
                            if isinstance(leaf, ast.Name):
                                names.add(leaf.id)
    return names


@register
class DeadExportChecker(Checker):
    rule = "dead-export"
    severity = "error"

    def check_module(self, mod: Module):
        exports, dynamic = _collect_exports(mod.tree)
        if dynamic or not exports:
            return
        bound = _bound_names(mod.tree.body)
        if "*" in bound:
            return  # star import: binding set not statically resolvable
        for name, node in exports:
            if name not in bound:
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`__all__` exports {name!r} but no module-level "
                    f"binding with that name exists — "
                    f"`from {mod.path.replace('/', '.')[:-3]} import *` "
                    f"would raise AttributeError",
                    context=name)
