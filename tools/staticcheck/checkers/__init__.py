"""Checker registry — importing this package registers every checker."""
from . import (  # noqa: F401
    chaos_site_coverage,
    closure_capture,
    dead_export,
    dtype_rule_coverage,
    host_sync,
    key_reuse,
    mutable_global,
    naked_collective,
    numpy_on_tracer,
    registry_consistency,
    tracer_branch,
    typed_error_wire_coverage,
    unbounded_blocking,
)
