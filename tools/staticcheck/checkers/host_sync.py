"""host-sync: device→host round-trips on hot paths.

`.item()` / `.tolist()` / `np.asarray(tensor)` / `float(tensor)` block on
the device and break under jit (ConcretizationTypeError on a Tracer). On
the op/nn/model hot paths every one of these is either a genuine bug or a
deliberate eager-only design decision — the latter get a
`# staticcheck: ok[host-sync]` pragma with the rationale, everything else
fails the ratchet.
"""
from __future__ import annotations

import ast

from ..astutil import STATIC_ATTRS, attr_root, call_name
from ..core import Checker, Module, register

HOT_PATH_PREFIXES = (
    "paddle_tpu/ops/",
    "paddle_tpu/nn/functional/",
    "paddle_tpu/models/",
)
_SYNC_METHODS = {"item", "tolist"}
_NUMPY_ROOTS = {"np", "numpy", "_np"}
_UNWRAP_CALLS = {"_u", "_unwrap", "_v", "_concrete"}


def _mentions_tensor_value(node: ast.AST) -> bool:
    """Does the expression reach into a Tensor's payload — `x._value` or an
    unwrap helper call? Metadata reads (`_u(x).dtype`) don't count, and
    `.item()` chains are excluded: the inner call is already flagged on its
    own, one finding per sync."""
    found = False

    def visit(n: ast.AST):
        nonlocal found
        if found:
            return
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return
        if isinstance(n, ast.Attribute) and n.attr == "_value":
            found = True
            return
        if isinstance(n, ast.Call) and call_name(n) in _UNWRAP_CALLS:
            found = True
            return
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return found


@register
class HostSyncChecker(Checker):
    rule = "host-sync"
    severity = "warning"

    def check_module(self, mod: Module):
        if not mod.path.startswith(HOT_PATH_PREFIXES):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS \
                    and not node.args:
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`.{f.attr}()` forces a device->host sync and breaks "
                    f"under jit — keep the value on device, or pragma with "
                    f"the eager-only rationale")
            elif isinstance(f, ast.Attribute) \
                    and f.attr in ("asarray", "array") \
                    and attr_root(f) in _NUMPY_ROOTS \
                    and any(_mentions_tensor_value(a) for a in node.args):
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`{ast.unparse(f)}` over a tensor payload materializes "
                    f"it on host — use jnp, or pragma if the op is "
                    f"inherently eager (dynamic output shape)")
            elif isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
                    and len(node.args) == 1 \
                    and _mentions_tensor_value(node.args[0]):
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`{f.id}()` over a tensor payload is a hidden host "
                    f"sync — keep it as a 0-d array, or pragma with the "
                    f"rationale")
