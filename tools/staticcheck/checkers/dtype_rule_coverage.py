"""dtype-rule-coverage: tolerance overrides must cover the swept dtypes.

The dtype-sweep battery (tests/test_op_dtype_sweep.py) exercises every op
at the low-precision dtypes (bfloat16/float16) with tolerances from
tests/op_tolerances.py. An op gets an FWD_OVERRIDES / GRAD_OVERRIDES entry
when the author decided the DEFAULT tolerance is wrong for it — but an
entry that names only ONE of the swept dtypes leaves the other silently
riding the default, which is exactly the judgement the entry said not to
trust. This rule flags every (op, leg, dtype) hole: an override entry that
has no tolerance pair for a dtype the sweep exercises and no recorded SKIP
for that (op, leg, dtype) in the SKIPS table.

Pre-existing holes are baselined (the ratchet stops the set growing); a
NEW op must record every swept dtype — a pair or a reasoned skip.

Skips are read from the literal SKIPS dict AND from the family-level
loop registrations (`for _op in _LINALG_OPS: SKIPS.setdefault(...)`) via
the resolver shared with the registry-consistency pass — a loop-skipped
family never counts as an uncovered hole.
"""
from __future__ import annotations

import ast
import os

from ..core import Checker, Finding, Project, register

TOLERANCES_PATH = os.path.join("tests", "op_tolerances.py")
SWEEP_PATH = os.path.join("tests", "test_op_dtype_sweep.py")

# the swept low-precision dtypes when no sweep module is present to parse
DEFAULT_LOWP = ("bfloat16", "float16")
_TABLES = {"FWD_OVERRIDES": "fwd", "GRAD_OVERRIDES": "grad"}


def _parse_assignments(path: str) -> dict[str, ast.AST] | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _sweep_dtypes(root: str) -> dict[str, tuple[str, ...]]:
    """Low-precision dtypes each leg exercises, parsed from the sweep
    module's DTYPES_FWD / DTYPES_LOWP_GRAD lists (defaults when absent)."""
    assigns = _parse_assignments(os.path.join(root, SWEEP_PATH))
    out = {"fwd": DEFAULT_LOWP, "grad": DEFAULT_LOWP}
    if assigns is None:
        return out
    for var, leg in (("DTYPES_FWD", "fwd"), ("DTYPES_LOWP_GRAD", "grad")):
        node = assigns.get(var)
        if node is None:
            continue
        try:
            vals = ast.literal_eval(node)
        except ValueError:
            continue
        lowp = tuple(d for d in vals if d in DEFAULT_LOWP)
        if lowp:
            out[leg] = lowp
    return out


def _dict_entries(node: ast.AST):
    """-> [(op, lineno, {dtype, ...})] for a {op: {dtype: (...)}} literal."""
    if not isinstance(node, ast.Dict):
        return []
    out = []
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        dtypes = set()
        if isinstance(v, ast.Dict):
            for dk in v.keys:
                if isinstance(dk, ast.Constant) and isinstance(dk.value, str):
                    dtypes.add(dk.value)
        out.append((k.value, k.lineno, dtypes))
    return out


def _literal_skips(node: ast.AST) -> set[tuple[str, str, str]]:
    try:
        value = ast.literal_eval(node)
    except (ValueError, TypeError):
        return set()
    return {k for k in value if isinstance(k, tuple) and len(k) == 3}


@register
class DtypeRuleCoverageChecker(Checker):
    rule = "dtype-rule-coverage"
    severity = "warning"

    def finalize(self, project: Project):
        assigns = _parse_assignments(
            os.path.join(project.root, TOLERANCES_PATH))
        if assigns is None:
            return  # no tolerance registry in this tree
        swept = _sweep_dtypes(project.root)
        skips = _literal_skips(assigns.get("SKIPS", ast.Dict([], [])))
        from .registry_consistency import _family_skip_entries
        skips |= {e for e in _family_skip_entries(project.root)
                  if len(e) == 3}
        path = TOLERANCES_PATH.replace(os.sep, "/")
        for table, leg in _TABLES.items():
            for op, line, dtypes in _dict_entries(assigns.get(table)):
                for dt in swept[leg]:
                    if dt in dtypes or (op, leg, dt) in skips \
                            or (op, leg, "*") in skips:
                        continue
                    yield Finding(
                        rule=self.rule, severity=self.severity, path=path,
                        line=line, col=0, context=f"{op}:{leg}:{dt}",
                        message=f"{table} entry for {op!r} covers "
                                f"{sorted(dtypes)} but not {dt!r}, which "
                                f"the dtype sweep exercises — that leg "
                                f"silently rides the default tolerance; "
                                f"add a ({dt}) pair or a recorded SKIP")
