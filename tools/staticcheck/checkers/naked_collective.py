"""naked-collective: direct lax collectives outside the comms subsystem.

Every framework collective is supposed to route through
``paddle_tpu/distributed/comms/`` so it gets a CommOp record (owner,
logical-vs-wire bytes, deadline, overlap slot) and — when the quantized
context is on — the EQuARX wire format.  A direct ``jax.lax.psum`` /
``all_gather`` / ``ppermute`` / ``all_to_all`` call anywhere else is
invisible to ``profiler.comm_summary()``, never quantizes, and carries no
deadline: exactly the scattered-collectives state the comms subsystem
replaced.

Flagged call shapes (attribute calls only — ``from jax.lax import psum``
is not an idiom this tree uses):

  - ``jax.lax.psum(...)`` / ``lax.psum(...)`` and the rest of the
    collective family (psum/pmean/pmax/pmin/psum_scatter/all_gather/
    ppermute/all_to_all/reduce_scatter);

outside ``paddle_tpu/distributed/comms/`` (the one module allowed to
touch the wire).  Deliberate direct sites — the shard_map-internal
pipeline/ring-attention schedules whose collectives ARE the schedule, and
the comms layer's own exact fallbacks — carry
``# staticcheck: ok[naked-collective]`` with a rationale; anything new
fails the ratchet.
"""
from __future__ import annotations

import ast

from ..core import Checker, Module, register

COLLECTIVE_NAMES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "ppermute", "all_to_all", "reduce_scatter", "pshuffle",
})

ALLOWED_PREFIX = "paddle_tpu/distributed/comms/"


def _is_lax_attr(func: ast.AST) -> bool:
    """True for `lax.<name>` / `jax.lax.<name>` / `*.lax.<name>` chains."""
    if not isinstance(func, ast.Attribute):
        return False
    base = func.value
    if isinstance(base, ast.Name):
        return base.id == "lax"
    if isinstance(base, ast.Attribute):
        return base.attr == "lax"
    return False


@register
class NakedCollectiveChecker(Checker):
    rule = "naked-collective"
    severity = "warning"

    def check_module(self, mod: Module):
        if not mod.path.startswith("paddle_tpu/") \
                or mod.path.startswith(ALLOWED_PREFIX):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            name = node.func.attr
            if name in COLLECTIVE_NAMES and _is_lax_attr(node.func):
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"direct jax.lax.{name}() outside distributed/comms/ — "
                    f"unaccounted, unquantizable, deadline-less wire "
                    f"traffic; route through comms.wire_all_reduce/"
                    f"wire_all_gather (or pragma a deliberate "
                    f"schedule-internal site with its rationale)")
