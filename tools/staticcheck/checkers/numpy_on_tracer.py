"""numpy-on-tracer: host numpy applied to traced values.

`np.*` functions inside a function handed to the tracing machinery either
raise TracerArrayConversionError under jit or silently execute on host in
eager mode, splitting the program into unfusible pieces. Only calls that
feed a traced parameter (or a value derived from one) are flagged — index
construction with numpy over static shapes (`np.triu_indices(n)`) is fine
and common.
"""
from __future__ import annotations

import ast

from ..astutil import (attr_root, tainted_names, traced_functions,
                       value_uses)
from ..core import Checker, Module, register

_NUMPY_ROOTS = {"np", "numpy", "_np"}


@register
class NumpyOnTracerChecker(Checker):
    rule = "numpy-on-tracer"
    severity = "error"

    def check_module(self, mod: Module):
        for fn in traced_functions(mod.tree):
            tainted, containers = tainted_names(fn)
            body = fn.node.body if isinstance(fn.node, ast.FunctionDef) \
                else [fn.node.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if not isinstance(node.func, ast.Attribute):
                        continue
                    if attr_root(node.func) not in _NUMPY_ROOTS:
                        continue
                    args = list(node.args) + [kw.value for kw in node.keywords]
                    uses = [u for a in args
                            for u in value_uses(a, tainted, containers)]
                    if not uses:
                        continue
                    names = ", ".join(sorted({u.id for u in uses}))
                    yield mod.finding(
                        self.rule, self.severity, node,
                        f"numpy call `{ast.unparse(node.func)}` fed traced "
                        f"value(s) {names} inside a function passed to "
                        f"{fn.entry}() — use the jnp equivalent so the op "
                        f"stays traceable/fusible")
