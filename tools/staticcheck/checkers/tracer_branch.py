"""tracer-branch: Python control flow on traced values.

Inside a function handed to dispatch.apply / defprim / _wrap / jax.jit, a
Python `if`/`while`/`assert` (or ternary) whose condition reads a traced
parameter AS A VALUE raises ConcretizationTypeError under trace — or worse,
silently bakes one branch into the compiled program when the op is first
run eagerly. Metadata conditions (`v.ndim == 2`, `isinstance(v, ...)`) are
static under trace and are not flagged.
"""
from __future__ import annotations

import ast

from ..astutil import tainted_names, traced_functions, value_uses
from ..core import Checker, Module, register

_STMTS = (ast.If, ast.While, ast.Assert, ast.IfExp)
_WORDS = {ast.If: "if", ast.While: "while", ast.Assert: "assert",
          ast.IfExp: "ternary"}


@register
class TracerBranchChecker(Checker):
    rule = "tracer-branch"
    severity = "error"

    def check_module(self, mod: Module):
        for fn in traced_functions(mod.tree):
            tainted, containers = tainted_names(fn)
            body = fn.node.body if isinstance(fn.node, ast.FunctionDef) \
                else [fn.node.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, _STMTS):
                        continue
                    uses = value_uses(node.test, tainted, containers)
                    if not uses:
                        continue
                    names = ", ".join(sorted({u.id for u in uses}))
                    yield mod.finding(
                        self.rule, self.severity, node,
                        f"Python `{_WORDS[type(node)]}` on traced value(s) "
                        f"{names} inside a function passed to "
                        f"{fn.entry}() — use jnp.where/lax.cond, or hoist "
                        f"the branch out of the traced function")
