"""typed-error-wire-coverage: serving-side typed errors must map to a
PTSG/1 status.

The gateway handler serializes whatever the engine raises through
``status_of`` in ``gateway/protocol.py``; an exception class with no
``isinstance`` branch there falls through to the generic 500, so the
client loses the TYPE — retry policy, breaker accounting, and the typed
re-raise all degrade to "internal error". The contract this rule closes:
a typed exception raised (or constructed as a request's terminal error)
anywhere on the serving path must be covered by ``status_of`` — by its
own class or an ancestor — the moment it lands, not when a client first
trips over an unmapped 500 in production.

Scope: modules under ``inference/serving/`` except ``gateway/client.py``
(client-side errors never traverse the server handler). Exception
classes are collected from the serving tree plus ``utils/deadline.py``
(the shared deadline hierarchy serving raises from); a class counts as
an exception when its base chain reaches a builtin exception. Trees
without a ``gateway/protocol.py`` defining ``status_of`` (fixture
projects that don't exercise this rule) are skipped. Zero entries are
baselined; a new typed serving error must land together with its wire
mapping (and its client-side reconstruction if it should stay typed end
to end).
"""
from __future__ import annotations

import ast

from ..astutil import call_name
from ..core import Checker, Module, Project, register

PROTOCOL_TAIL = "inference/serving/gateway/protocol.py"
SERVING_DIR = "inference/serving/"
CLIENT_TAIL = "gateway/client.py"
DEADLINE_TAIL = "utils/deadline.py"

_BUILTIN_EXC = {
    "BaseException", "Exception", "ArithmeticError", "AssertionError",
    "AttributeError", "BufferError", "ConnectionError", "EOFError",
    "ImportError", "IndexError", "InterruptedError", "KeyError",
    "LookupError", "MemoryError", "NotImplementedError", "OSError",
    "OverflowError", "PermissionError", "RuntimeError", "StopIteration",
    "TimeoutError", "TypeError", "ValueError",
}


def _tail_name(node: ast.AST) -> str:
    """`Name` / dotted-`Attribute` -> the last component, else ''."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


@register
class TypedErrorWireCoverageChecker(Checker):
    rule = "typed-error-wire-coverage"
    severity = "warning"

    def __init__(self):
        # class name -> base-class names (last components)
        self._bases: dict[str, set[str]] = {}
        # (module, node, class name) per raise/construction site
        self._sites: list[tuple[Module, ast.AST, str]] = []

    def _collect_classes(self, mod: Module):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                names = {_tail_name(b) for b in node.bases} - {""}
                self._bases.setdefault(node.name, set()).update(names)

    def check_module(self, mod: Module):
        if mod.path.endswith(DEADLINE_TAIL):
            self._collect_classes(mod)
            return ()
        if SERVING_DIR not in mod.path:
            return ()
        self._collect_classes(mod)
        if mod.path.endswith(CLIENT_TAIL):
            return ()
        for node in ast.walk(mod.tree):
            # every construction is a site, not just `raise X(...)`: the
            # server also ships errors it never raises (error_frame(...,
            # GatewayDraining(...))) and requests carry terminal errors
            # by assignment (req.error = RequestTimeout(...))
            if isinstance(node, ast.Call):
                name = _tail_name(node.func)
            elif isinstance(node, ast.Raise) and node.exc is not None \
                    and not isinstance(node.exc, ast.Call):
                name = _tail_name(node.exc)   # `raise Name` re-raise form
            else:
                continue
            if name:
                self._sites.append((mod, node, name))
        return ()

    def _reaches(self, name: str, targets: set[str]) -> bool:
        seen, frontier = set(), {name}
        while frontier:
            n = frontier.pop()
            if n in targets:
                return True
            seen.add(n)
            frontier.update(self._bases.get(n, set()) - seen)
        return False

    def finalize(self, project: Project):
        protocol = next((m for m in project.modules
                         if m.path.endswith(PROTOCOL_TAIL)), None)
        if protocol is None:
            return
        covered: set[str] = set()
        for fn in ast.walk(protocol.tree):
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "status_of"):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and call_name(node) == "isinstance"
                        and len(node.args) == 2):
                    continue
                spec = node.args[1]
                elts = spec.elts if isinstance(spec, ast.Tuple) else [spec]
                covered.update(_tail_name(e) for e in elts)
        covered.discard("")
        if not covered:
            return
        reported: set[tuple[str, str]] = set()   # one per (path, class)
        for mod, node, name in self._sites:
            if name not in self._bases \
                    or not self._reaches(name, _BUILTIN_EXC) \
                    or self._reaches(name, covered) \
                    or (mod.path, name) in reported:
                continue
            reported.add((mod.path, name))
            yield mod.finding(
                self.rule, self.severity, node,
                f"typed exception {name!r} travels the serving path but "
                f"has no PTSG/1 status mapping in {PROTOCOL_TAIL} "
                f"status_of — the gateway would ship it as the generic "
                f"500 and clients lose the type; add an isinstance "
                f"branch (and a client-side reconstruction if it must "
                f"stay typed end to end)",
                context=name)
