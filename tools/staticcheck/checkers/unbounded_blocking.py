"""unbounded-blocking: blocking waits with no deadline in paddle_tpu/.

The no-hang guarantee (ISSUE 5) says every blocking primitive must carry a
bound: a partitioned store, a hung peer, or a SIGKILLed worker then raises
a typed `DeadlineExceeded` into the elastic restart path instead of
wedging the job silently. This rule flags the call shapes that wait
forever by construction:

  - `q.get()` with no arguments and no `timeout=` — a blocking queue pop
    (`d.get(key)` always has a positional argument and is never flagged);
  - `x.wait(...)` / `x.wait_for(...)` with neither a `timeout=` keyword
    nor a positional argument that is plausibly a bound (a numeric
    literal, or a name like `timeout`/`deadline`/`interval`/`budget` —
    `Event.wait(0.5)` and `stop.wait(self.interval)` pass,
    `store.wait("key")` and `cond.wait()` fail);
  - `sock.recv(...)`-family reads — a socket deadline is invisible
    statically, so every raw read must either run under a managed
    `Deadline` or state why it may park forever, via the pragma;
  - argless `t.join()` — joining a thread/process with no timeout parks
    forever on a worker that never exits (a writer wedged on dead
    storage, a heartbeat thread spinning reconnects). `t.join(5.0)` /
    `t.join(timeout=...)` pass; `os.path.join(a, b)` and `sep.join(xs)`
    always carry arguments and are never flagged.

Deliberately unbounded sites (server-side handler threads released by
stop(), device DMA waits) get `# staticcheck: ok[unbounded-blocking]`
with the rationale; everything else fails the ratchet.
"""
from __future__ import annotations

import ast

from ..core import Checker, Module, register

_WAIT_METHODS = {"wait", "wait_for"}
_RECV_METHODS = {"recv", "recv_into", "recvfrom", "recvmsg"}
# positional-argument names that plausibly carry a time bound
_BOUND_HINTS = ("timeout", "deadline", "interval", "budget", "secs",
                "seconds", "remaining")


def _has_timeout_kwarg(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


def _plausible_bound(arg: ast.AST) -> bool:
    """Is this positional argument plausibly a time bound?"""
    if isinstance(arg, ast.Constant):
        return isinstance(arg.value, (int, float)) \
            and not isinstance(arg.value, bool)
    name = None
    if isinstance(arg, ast.Name):
        name = arg.id
    elif isinstance(arg, ast.Attribute):
        name = arg.attr
    elif isinstance(arg, ast.Call):
        f = arg.func
        name = f.attr if isinstance(f, ast.Attribute) \
            else f.id if isinstance(f, ast.Name) else None
    if name is None:
        return False
    low = name.lower()
    return any(h in low for h in _BOUND_HINTS)


@register
class UnboundedBlockingChecker(Checker):
    rule = "unbounded-blocking"
    severity = "warning"

    def check_module(self, mod: Module):
        if not mod.path.startswith("paddle_tpu/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            if attr == "get":
                if not node.args and not node.keywords:
                    yield mod.finding(
                        self.rule, self.severity, node,
                        "`.get()` with no timeout blocks forever if the "
                        "producer dies — pass `timeout=` and handle Empty, "
                        "or pragma with why this queue is always fed")
            elif attr in _WAIT_METHODS:
                if _has_timeout_kwarg(node):
                    continue
                if any(_plausible_bound(a) for a in node.args):
                    continue
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"`.{attr}()` without a bound waits forever on a peer "
                    f"that never delivers — pass `timeout=` (typed "
                    f"DeadlineExceeded beats a silent hang), or pragma "
                    f"with why this wait is released by construction")
            elif attr == "join":
                # only the ARGLESS form is a blocking join hazard:
                # str.join/os.path.join always take the iterable/components
                if not node.args and not _has_timeout_kwarg(node):
                    yield mod.finding(
                        self.rule, self.severity, node,
                        "`.join()` with no bound waits forever on a "
                        "worker that never exits — pass `timeout=` and "
                        "handle the still-alive case with a typed "
                        "DeadlineExceeded (utils.deadline.join_bounded); "
                        "for timeout-less join APIs (queue.Queue.join, "
                        "multiprocessing.Pool.join) restructure to a "
                        "bounded wait, or pragma with why the worker "
                        "always terminates")
            elif attr in _RECV_METHODS:
                yield mod.finding(
                    self.rule, self.severity, node,
                    f"raw `.{attr}()` — a socket deadline is invisible "
                    f"statically; run the read under utils.deadline."
                    f"Deadline (re-arming settimeout per chunk) or pragma "
                    f"with why this read may park forever")
