"""closure-capture: tensor payloads closed over by op lambdas.

A function handed to `dispatch.apply`/`defprim` executes with its
POSITIONAL params as the traced inputs. Any array payload it instead pulls
from the enclosing scope rides along as a baked constant: it bypasses the
autograd tape (no gradient flows to it), AMP casting, AND the compiled-op
cache key (ops/_op_cache.py refuses to key on array-bearing closures, so
the op silently stays uncached). masked_fill had exactly this bug; the fix
is always to pass the payload through `apply()` as a positional argument.
The long-deferred ROADMAP rule, now implemented.

Two triggers, per traced function (entry apply/defprim/_wrap — jit-ed
train steps legitimately close over parameter pytrees and are exempt):
- direct: the body reads `X._value` / `X.numpy()` for a free variable X —
  an unwrapped Tensor payload crossing the closure boundary;
- indirect: a free variable X is used as a value and an enclosing
  function assigns X from array-producing code (`jnp.*`/`jax.*` calls,
  `_unwrap`/`_u`/`to_tensor`/`asarray`, or a `._value` unwrap).

Free config captures (ints, axis tuples, flags) are the sanctioned idiom
and never match either trigger. Module-level constants are exempt: they
cannot go stale under a compiled executable and carry no per-call grad.
"""
from __future__ import annotations

import ast

from ..astutil import STATIC_ATTRS, attr_root, call_name, traced_functions
from ..core import Checker, Module, register

_OP_ENTRIES = {"apply", "defprim", "_wrap"}
_UNWRAP_CALLS = {"_unwrap", "_u", "to_tensor", "asarray", "array"}
_ARRAY_ROOTS = {"jnp", "jax"}
# jnp/jax calls that return shape/dtype metadata, not arrays
_NONARRAY_CALLS = {"broadcast_shapes", "result_type", "promote_types",
                   "issubdtype", "ndim", "shape", "size", "eval_shape"}


def _is_payload_read(n: ast.Attribute) -> bool:
    """`X._value` (payload crossing the closure) or `X.numpy()` (host copy
    of it). Metadata chained off the payload (`X._value.shape`) and module
    paths (`jax.numpy.flip`) are static and do not count."""
    parent = getattr(n, "_sc_parent", None)
    if n.attr == "_value":
        return not (isinstance(parent, ast.Attribute)
                    and parent.attr in STATIC_ATTRS)
    if n.attr == "numpy":
        return isinstance(parent, ast.Call) and parent.func is n
    return False


def _bound_names(fn_node: ast.AST) -> set[str]:
    """Every name bound within the traced function (params of it and of any
    nested function, assignment/loop/comprehension/with targets)."""
    out: set[str] = set()
    nodes = [fn_node]
    for n in ast.walk(fn_node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            nodes.append(n)
    for n in nodes:
        a = n.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs):
            out.add(p.arg)
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)) and n is not fn_node:
            out.add(n.name)
    return out


def _body_nodes(fn_node: ast.AST):
    body = fn_node.body if isinstance(fn_node, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)) \
        else [fn_node.body]
    for stmt in body:
        yield from ast.walk(stmt)


def _is_array_expr(expr: ast.AST) -> bool:
    """Does this assignment RHS produce an array payload? (`x._value`,
    `_unwrap(x)`, `jnp.tril(...)`, `t.numpy()`, ...).

    Evidence is judged on the EXPRESSION HEAD (through tuple/comprehension/
    conditional structure), not on arbitrary sub-expressions — a dict of
    lambdas that mention `jax.lax` builds a function table, not an array.
    A payload `._value` read anywhere in the RHS counts, except under a
    metadata attribute (`t._value.shape` is a static shape)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr == "_value" \
                and not (isinstance(getattr(n, "_sc_parent", None),
                                    ast.Attribute)
                         and n._sc_parent.attr in STATIC_ATTRS):
            return True
    heads = [expr]
    while heads:
        e = heads.pop()
        if isinstance(e, (ast.Tuple, ast.List)):
            heads.extend(e.elts)
        elif isinstance(e, ast.IfExp):
            heads.extend((e.body, e.orelse))
        elif isinstance(e, (ast.ListComp, ast.GeneratorExp)):
            heads.append(e.elt)
        elif isinstance(e, ast.Call):
            name = call_name(e)
            if name in _NONARRAY_CALLS:
                continue
            if name in _UNWRAP_CALLS or name == "numpy":
                return True
            if isinstance(e.func, ast.Attribute) \
                    and attr_root(e.func) in _ARRAY_ROOTS:
                return True
    return False


def _enclosing_functions(node: ast.AST):
    cur = getattr(node, "_sc_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield cur
        cur = getattr(cur, "_sc_parent", None)


def _array_evidenced_names(traced_node: ast.AST) -> set[str]:
    """Names assigned from array-producing expressions in any enclosing
    function of the traced fn (module-level constants intentionally
    excluded — see module docstring)."""
    out: set[str] = set()
    for fn in _enclosing_functions(traced_node):
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and _is_array_expr(n.value):
                for t in n.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            out.add(leaf.id)
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and _is_array_expr(n.value):
                if isinstance(n.target, ast.Name):
                    out.add(n.target.id)
    return out


@register
class ClosureCaptureChecker(Checker):
    rule = "closure-capture"
    severity = "warning"

    def check_module(self, mod: Module):
        for traced in traced_functions(mod.tree):
            if traced.entry not in _OP_ENTRIES:
                continue
            bound = _bound_names(traced.node)
            evidenced = None  # computed lazily: most fns have no candidates
            seen: set[str] = set()
            for n in _body_nodes(traced.node):
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id not in bound \
                        and n.value.id not in seen \
                        and _is_payload_read(n):
                    seen.add(n.value.id)
                    yield mod.finding(
                        self.rule, self.severity, n,
                        f"op function captures tensor payload "
                        f"`{n.value.id}.{n.attr}` from the enclosing scope — "
                        f"pass it through apply() as a positional arg "
                        f"(closures bypass the tape, AMP, and the "
                        f"compiled-op cache key)")
                elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id not in bound and n.id not in seen:
                    if evidenced is None:
                        evidenced = _array_evidenced_names(traced.node)
                    if n.id in evidenced:
                        seen.add(n.id)
                        yield mod.finding(
                            self.rule, self.severity, n,
                            f"op function closes over `{n.id}`, an array "
                            f"built in the enclosing function — pass it "
                            f"through apply() as a positional arg (closures "
                            f"bypass the tape, AMP, and the compiled-op "
                            f"cache key)")
