"""Analysis core: Finding model, module loading, pragma handling, runner.

A Checker subclass registers itself with @register, visits one parsed
module at a time via check_module(), and may emit cross-file findings in
finalize() once every module has been seen (the registry-consistency pass
needs the whole project before it can report orphans in either direction).
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
import threading
from typing import Iterable, Iterator

SEVERITIES = ("error", "warning", "info")

# `# staticcheck: ok` suppresses every rule on that line;
# `# staticcheck: ok[rule-a,rule-b]` suppresses just those rules.
_PRAGMA_RE = re.compile(r"#\s*staticcheck:\s*ok(?:\[([a-z0-9_,\s-]+)\])?")

DEFAULT_SCAN_PATHS = ("paddle_tpu", "tools")
EXCLUDE_DIR_NAMES = {"__pycache__", ".git", "fixtures"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str      # project-root-relative, posix separators
    line: int
    col: int
    message: str
    context: str = ""  # stable baseline key component (defaults to source line)

    @property
    def key(self) -> str:
        """Baseline identity. Uses the source-line text (or a checker-chosen
        stable token) instead of the line number so unrelated edits above a
        baselined violation don't resurface it as 'new'."""
        return f"{self.rule}::{self.path}::{self.context}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file plus the bits checkers keep re-deriving."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.abspath = path
        self.path = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)
        # parent links let checkers look outward from a node
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._sc_parent = node  # type: ignore[attr-defined]
        self._pragmas = self._parse_pragmas()

    def _parse_pragmas(self) -> dict[int, set[str] | None]:
        out: dict[int, set[str] | None] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rules = m.group(1)
            if rules is None:
                out[i] = None  # all rules
            else:
                out[i] = {r.strip() for r in rules.split(",") if r.strip()}
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        if line not in self._pragmas:
            return False
        rules = self._pragmas[line]
        return rules is None or rule in rules

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, severity: str, node: ast.AST, message: str,
                context: str | None = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, severity=severity, path=self.path,
                       line=line, col=col, message=message,
                       context=context if context is not None
                       else self.line_text(line))


class _ModuleCache:
    """Parsed-Module cache keyed by (root, path, mtime, size), shared by
    every checker and every run() in one process — the in-process tier-1
    gate scans the repo and the fixture projects several times, and
    re-parsing ~400 files each time dominated its wall clock. State lives
    on this instance under a lock (the utils/memo audited-container
    idiom); a stale file (new mtime/size) reparses transparently."""

    def __init__(self, maxsize: int = 4096):
        self._d: dict = {}
        self._lock = threading.Lock()
        self._maxsize = maxsize

    def get_or_parse(self, root: str, path: str) -> Module:
        try:
            st = os.stat(path)
            key = (root, os.path.abspath(path), st.st_mtime_ns, st.st_size)
        except OSError:
            return Module(root, path)
        with self._lock:
            mod = self._d.get(key)
        if mod is not None:
            return mod
        mod = Module(root, path)
        with self._lock:
            if len(self._d) >= self._maxsize:
                self._d.clear()  # full flush: keys are cheap to rebuild
            self._d[key] = mod
        return mod


_MODULE_CACHE = _ModuleCache()


def parse_file_cached(root: str, path: str) -> Module:
    """Cached Module for any file (checkers use this for registries and
    test batteries that live outside the scan paths)."""
    return _MODULE_CACHE.get_or_parse(root, path)


class Project:
    """The set of modules under analysis plus the project root (so cross-file
    checkers can reach registries that live outside the scan paths)."""

    def __init__(self, root: str, modules: list[Module]):
        self.root = root
        self.modules = modules


class Checker:
    rule = ""           # rule id, kebab-case
    severity = "warning"

    def check_module(self, mod: Module) -> Iterable[Finding]:
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()


_CHECKERS: list[type[Checker]] = []


def register(cls: type[Checker]) -> type[Checker]:
    _CHECKERS.append(cls)
    return cls


def all_checkers() -> list[Checker]:
    from . import checkers  # noqa: F401  — importing populates the registry
    return [cls() for cls in _CHECKERS]


def iter_py_files(root: str, paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(absp):
            yield absp
            continue
        for dirpath, dirs, files in os.walk(absp):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIR_NAMES)
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def load_project(root: str, paths: Iterable[str] | None = None) -> Project:
    paths = tuple(paths) if paths else DEFAULT_SCAN_PATHS
    modules = []
    for f in iter_py_files(root, paths):
        try:
            modules.append(_MODULE_CACHE.get_or_parse(root, f))
        except SyntaxError as e:
            raise SyntaxError(f"staticcheck cannot parse {f}: {e}") from e
    return Project(root, modules)


def run(root: str, paths: Iterable[str] | None = None,
        rules: Iterable[str] | None = None) -> list[Finding]:
    """Run every registered checker over the project; returns findings with
    pragma suppressions already applied, sorted by (path, line, rule)."""
    project = load_project(root, paths)
    checkers = all_checkers()
    if rules is not None:
        wanted = set(rules)
        checkers = [c for c in checkers if c.rule in wanted]
    findings: list[Finding] = []
    by_path = {m.path: m for m in project.modules}
    for checker in checkers:
        for mod in project.modules:
            findings.extend(checker.check_module(mod))
        findings.extend(checker.finalize(project))
    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.col, f.message))
    return kept
