"""Text and JSON reporters for staticcheck findings."""
from __future__ import annotations

import collections
import json

from .core import Finding


def text_report(findings: list[Finding], verbose_summary: bool = True) -> str:
    lines = [f.format() for f in findings]
    if verbose_summary:
        by_rule = collections.Counter(f.rule for f in findings)
        if findings:
            lines.append("")
        lines.append(f"{len(findings)} finding(s)"
                     + ("" if not by_rule else " — "
                        + ", ".join(f"{r}: {n}"
                                    for r, n in sorted(by_rule.items()))))
    return "\n".join(lines)


def json_report(findings: list[Finding]) -> str:
    return json.dumps(
        {"total": len(findings),
         "findings": [f.to_json() for f in findings]},
        indent=1)
