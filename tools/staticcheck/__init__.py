"""graftcheck — AST-based JAX-hazard static analysis for the op/nn surface.

The framework's premise is that every op IS a jax function that must trace
cleanly under `jax.jit`/pjit (see dispatch.apply). Nothing about that is
enforced by the runtime until a user hits it under trace, so this package
walks the source with compiler-style passes and reports the classic JAX
hazards statically:

- ``tracer-branch``      Python `if`/`while`/`assert` on traced values
- ``numpy-on-tracer``    `np.*` calls fed traced values inside op lambdas
- ``host-sync``          `.item()`/`np.asarray`/`float()` on hot paths
- ``registry-consistency`` op_name strings vs tolerance/coverage registries
- ``mutable-global``     module globals written outside `set_*` installers
- ``dead-export``        `__all__` names that don't resolve

...plus the later rules (key-reuse, closure-capture, unbounded-blocking,
dtype-rule-coverage, naked-collective) and the **jaxpr tier** (jaxpr/):
the canonical captured steps traced through jit/capture.py and
semantically linted (jaxpr-recompile-hazard, jaxpr-donation-miss,
jaxpr-unscheduled-collective, jaxpr-dead-compute, jaxpr-host-callback) —
both tiers share the Finding model, pragma allowlist, and baseline.

Run `python -m tools.staticcheck --help` for the CLI; the checked-in
`baseline.json` makes the CI gate a ratchet (only NEW violations fail).
"""
from .core import (  # noqa: F401
    Checker, Finding, Module, Project, all_checkers, register, run)
from .baseline import load_baseline, new_findings, save_baseline  # noqa: F401

__all__ = [
    "Checker", "Finding", "Module", "Project", "all_checkers", "register",
    "run", "load_baseline", "new_findings", "save_baseline",
]
