"""Generate tests/golden/vision_zoo_stats.json (VERDICT r4 item 6).

For every constructor in the vision zoo: fixed seed, fixed input, record
output-activation statistics (mean / std / absmax of the logits and the
param count).  The committed JSON is the golden baseline the behavior test
replays — converting "one forward pass ran" into "the output is still
byte-for-byte the same computation" (reference analog:
test/legacy_test/test_vision_models.py asserts outputs per model).

Usage: python tools/gen_zoo_golden.py  (writes the JSON; commit it)
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "golden", "vision_zoo_stats.json")

# models needing larger minimum spatial input
BIG_INPUT = {"inception_v3": 96, "googlenet": 64}


def zoo_names():
    from paddle_tpu.vision import models as M
    out = []
    for n in sorted(getattr(M, "__all__", dir(M))):
        fn = getattr(M, n, None)
        if callable(fn) and not isinstance(fn, type) \
                and n[0].islower() and n not in ("lenet",):
            out.append(n)
    return out


def stats_for(name):
    import paddle_tpu as P
    from paddle_tpu.vision import models as M

    P.seed(0)
    model = getattr(M, name)()
    model.eval()
    n_params = int(sum(int(np.prod(p.shape)) for p in model.parameters()))
    side = BIG_INPUT.get(name, 32)
    x = np.random.RandomState(0).randn(1, 3, side, side).astype(np.float32)
    out = model(P.to_tensor(x))
    if isinstance(out, (tuple, list)):
        out = out[0]
    o = np.asarray(out.numpy(), np.float64)
    return {"n_params": n_params, "input_side": side,
            "mean": float(o.mean()), "std": float(o.std()),
            "absmax": float(np.abs(o).max()), "shape": list(o.shape)}


def main():
    golden = {}
    for n in zoo_names():
        try:
            golden[n] = stats_for(n)
            print(f"{n}: {golden[n]}", flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep going
            print(f"{n}: FAILED {e}", flush=True)
            golden[n] = {"error": str(e)[:200]}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(golden, f, indent=1, sort_keys=True)
    print(f"wrote {OUT} ({len(golden)} models)")


if __name__ == "__main__":
    main()
