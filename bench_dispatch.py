"""Microbenchmark: eager small-op dispatch throughput, CPU-runnable.

Measures the compiled-op cache (paddle_tpu/ops/_op_cache.py) against the
uncached path (`PT_OP_CACHE=0` equivalent) on a same-shape eager loop —
the dispatch-layer perf trajectory that stays measurable even when the TPU
backend probe reports `tpu-unavailable` (BENCH_r05).

Prints ONE JSON line:
  {"metric": "eager_dispatch_cached_speedup", "value": <geomean x>,
   "unit": "x", "vs_baseline": <value/3.0>, ...per-workload ops/sec...}
and writes a BENCH_SELF_DISPATCH_<ts>.json artifact with full detail
(per-workload iters/sec both ways + dispatch.cache_info() counters).

Workloads (batch 64, same shapes every iteration):
  softmax_fwd   — no-grad composite op (exp/max/sub/div chain)
  gelu_fwd      — no-grad, longer elementwise chain (tanh approximation)
  linear_train  — linear + mse fwd AND backward: the vjp-retrace-per-call
                  path the cache eliminates

Env: PT_DISPATCH_BENCH_ITERS (default 300), PT_DISPATCH_BENCH_WARMUP (20).
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax

# dispatch overhead is the subject — always measure on CPU (the env's
# sitecustomize may register a TPU plugin; jax.config wins over env vars)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as P  # noqa: E402
from paddle_tpu.ops import dispatch  # noqa: E402


def _workloads():
    rng = np.random.RandomState(0)
    x = P.to_tensor(rng.randn(64, 256).astype(np.float32))
    w = P.to_tensor(rng.randn(256, 64).astype(np.float32),
                    stop_gradient=False)
    b = P.to_tensor(np.zeros(64, np.float32), stop_gradient=False)
    tgt = P.to_tensor(rng.randn(64, 64).astype(np.float32))

    def softmax_fwd():
        return P.nn.functional.softmax(x, axis=-1)

    def gelu_fwd():
        return P.nn.functional.gelu(x, approximate=True)

    def linear_train():
        out = P.nn.functional.linear(x, w, b)
        loss = P.nn.functional.mse_loss(out, tgt)
        loss.backward()
        w.clear_grad()
        b.clear_grad()
        return loss

    return [("softmax_fwd", softmax_fwd), ("gelu_fwd", gelu_fwd),
            ("linear_train", linear_train)]


def _time_loop(fn, iters: int, warmup: int) -> float:
    """-> iterations/second, result-blocked at the end of each timed run."""
    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out._value)
    best = float("inf")
    for _ in range(2):  # two timed reps, keep the best (noise floor)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        jax.block_until_ready(out._value)
        best = min(best, time.perf_counter() - t0)
    return iters / best


def main() -> dict:
    iters = int(os.environ.get("PT_DISPATCH_BENCH_ITERS", "300"))
    warmup = int(os.environ.get("PT_DISPATCH_BENCH_WARMUP", "20"))

    detail = {"iters": iters, "warmup": warmup, "workloads": {}}
    speedups = []
    for name, fn in _workloads():
        per = {}
        for label, enabled in (("cached", True), ("uncached", False)):
            dispatch.cache_clear()
            dispatch.set_op_cache_enabled(enabled)
            per[f"{label}_iters_per_sec"] = round(_time_loop(fn, iters,
                                                             warmup), 1)
            if enabled:  # snapshot BEFORE the uncached leg clears counters
                per["cache_info"] = dispatch.cache_info()
        dispatch.set_op_cache_enabled(True)
        per["speedup"] = round(per["cached_iters_per_sec"]
                               / per["uncached_iters_per_sec"], 2)
        speedups.append(per["speedup"])
        detail["workloads"][name] = per
        print(f"# {name}: cached={per['cached_iters_per_sec']}/s "
              f"uncached={per['uncached_iters_per_sec']}/s "
              f"-> {per['speedup']}x", file=sys.stderr)

    geomean = float(np.exp(np.mean(np.log(speedups))))
    payload = {
        "metric": "eager_dispatch_cached_speedup",
        "value": round(geomean, 2),
        "unit": "x",
        # north-star proxy: the ISSUE-4 acceptance floor is 3x on a
        # same-shape CPU loop
        "vs_baseline": round(geomean / 3.0, 4),
        **{f"{k}_speedup": v["speedup"]
           for k, v in detail["workloads"].items()},
    }
    print(json.dumps(payload), flush=True)

    ts = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BENCH_SELF_DISPATCH_{ts}.json")
    try:
        with open(path, "w") as f:
            json.dump({**payload, "detail": detail}, f, indent=1)
        print(f"# artifact -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"# artifact write failed: {e}", file=sys.stderr)
    return payload


if __name__ == "__main__":
    main()
