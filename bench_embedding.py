"""Sharded-embedding bench: wire-bytes reduction + exactness ladder.

The acceptance artifact for the sharded-embedding subsystem
(distributed/embedding) on a dp2 virtual CPU mesh:

  wire reduction  — trace the sharded lookup inside
                    ``comms.quantized("int8")`` and read the CommOp
                    accounting of the embedding-row return leg: logical
                    bytes (what the fp32 combine would move) over wire
                    bytes (int8 payload + per-block fp32 scales).
                    Headline: >= 3.5x at int8.  Deterministic accounting
                    of the program's actual wire format, not a timing —
                    CPU has no ICI to time honestly.
  exactness       — dp1 lookup bitwise the dense nn.Embedding gather;
                    dp2 exchange bitwise the dense gather with the
                    context off (forward and gradient).
  proxy timings   — sharded-lookup vs dense-gather wall time per call on
                    the CPU proxy (informational only, clearly labeled:
                    the exchange exists to bound HBM + wire on real
                    meshes, a 2-virtual-device CPU cannot show that).

Prints ONE JSON line:
  {"metric": "embedding_wire_reduction_int8", "value": <x>, "unit": "x",
   "vs_baseline": <value/3.5>, "bitwise_dp1": true, ...}
and writes a BENCH_SELF_EMBED_<ts>.json artifact with the per-site
accounting and config.

Env: PT_EMBED_BENCH_ITERS (timing iterations, default 20).
"""
from __future__ import annotations

import json
import os
import sys
import time

# dp2 needs 2 virtual CPU devices BEFORE any jax backend query
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + \
        " --xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu  # noqa: E402,F401 — x64 + shard_map compat shims
from paddle_tpu.distributed import comms  # noqa: E402
from paddle_tpu.distributed.embedding import sharded_lookup  # noqa: E402
from paddle_tpu.parallel import mesh as mesh_mod  # noqa: E402

ROWS, DIM = 4096, 64
BATCH, FIELDS = 256, 8
ACCEPT_FLOOR = 3.5


def _unwrap(x):
    return x._value if hasattr(x, "_value") else x


def _time_callable(fn, iters: int) -> float:
    fn()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready")
        else a, out)
    return (time.perf_counter() - t0) / iters * 1e3


def main() -> dict:
    iters = int(os.environ.get("PT_EMBED_BENCH_ITERS", "20"))
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(ROWS, DIM).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, ROWS, (BATCH, FIELDS)))

    dense = jax.jit(lambda i, ww: jnp.take(ww, i.astype(jnp.int32), axis=0))
    ref = np.asarray(dense(ids, w))

    # --- dp1: bitwise the dense gather ---
    mesh_mod.set_mesh(None)
    bitwise_dp1 = bool(np.array_equal(
        np.asarray(_unwrap(sharded_lookup(ids, w))), ref))

    # --- dp2 exact: bitwise through the exchange ---
    mesh_mod.init_mesh({"dp": 2}, devices=jax.devices()[:2])
    sharded = jax.jit(lambda i, ww: _unwrap(sharded_lookup(i, ww)))
    bitwise_dp2 = bool(np.array_equal(np.asarray(sharded(ids, w)), ref))

    def loss_s(ww):
        return jnp.sum(jnp.tanh(_unwrap(sharded_lookup(ids, ww))))

    def loss_d(ww):
        return jnp.sum(jnp.tanh(jnp.take(ww, ids.astype(jnp.int32), axis=0)))

    bitwise_grad = bool(np.array_equal(np.asarray(jax.grad(loss_s)(w)),
                                       np.asarray(jax.grad(loss_d)(w))))

    # --- quantized: the wire accounting (fresh registry for this trace) ---
    comms.comm_clear()
    with comms.quantized("int8"):
        q = jax.jit(lambda i, ww: _unwrap(sharded_lookup(i, ww)))
        out_q = np.asarray(q(ids, w))
    quant_err = float(np.max(np.abs(out_q - ref)))
    sites = comms.comm_info()["sites"]
    row_site = sites["embedding.rows/all_to_all/dp"]
    logical = row_site["bytes_logical"]
    wire = row_site["bytes_wire"]
    reduction = logical / max(wire, 1)

    # --- CPU-proxy timings (informational) ---
    t_dense = _time_callable(lambda: dense(ids, w), iters)
    t_sharded = _time_callable(lambda: sharded(ids, w), iters)

    from paddle_tpu import profiler
    print(profiler.comm_summary(), file=sys.stderr)

    payload = {
        "metric": "embedding_wire_reduction_int8",
        "value": round(reduction, 3),
        "unit": "x",
        # acceptance floor: >= 3.5x smaller wire on the row-combine leg
        "vs_baseline": round(reduction / ACCEPT_FLOOR, 4),
        "bitwise_dp1": bitwise_dp1,
        "bitwise_exact_dp2": bitwise_dp2,
        "bitwise_exact_grad_dp2": bitwise_grad,
        "quant_max_err": round(quant_err, 6),
        "rows_bytes_logical": logical,
        "rows_bytes_wire": wire,
        "lookup_dense_ms": round(t_dense, 3),
        "lookup_sharded_ms": round(t_sharded, 3),
        "backend": "cpu-proxy",
    }
    print(json.dumps(payload), flush=True)

    ts = time.strftime("%Y%m%d_%H%M%S")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BENCH_SELF_EMBED_{ts}.json")
    detail = {
        "config": {"rows": ROWS, "dim": DIM, "batch": BATCH,
                   "fields": FIELDS, "mesh": "dp2",
                   "block": comms.quant_state().block,
                   "platform": jax.devices()[0].platform},
        "sites": sites,
    }
    try:
        with open(path, "w") as f:
            json.dump({**payload, "detail": detail}, f, indent=1)
        print(f"# artifact -> {path}", file=sys.stderr)
    except OSError as e:
        print(f"# artifact write failed: {e}", file=sys.stderr)
    return payload


if __name__ == "__main__":
    main()
