"""Dtype-swept OpTest battery (VERDICT r3 item 4; reference protocol:
test/legacy_test/eager_op_test.py:379 check_output_with_place over
fp32/fp64/fp16/bf16 + test/white_list/op_accuracy_white_list.py governance).

Three legs per op case:
  1. forward sweep: op(dtype) vs op(float64) for float64/float32/bf16/fp16,
     tolerances from tests/op_tolerances.py (per-op overrides recorded there);
  2. float64 finite-difference gradient check: autograd vs central
     differences — the formula-correctness leg, now across ~90 differentiable
     ops instead of a few dozen;
  3. low-precision gradient sweep: autograd(bf16/fp16) vs autograd(float64)
     — bf16 is the TPU-native training dtype (this leg is what r3 lacked).

test_top_ops_covered pins the battery to OP_COVERAGE.json (the dispatch-
instrumented enumeration of what the model zoo executes): every enumerated
op must have a sweep case or a recorded NOT_SWEPT reason.

The whole module runs with jax x64 enabled (module fixture) so the float64
reference is real, then restores the session default.
"""
from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest
from ml_dtypes import bfloat16

import paddle_tpu as P
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from op_tolerances import fwd_tol, grad_tol, skip_reason

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DTYPES_FWD = ["float64", "float32", "bfloat16", "float16"]
DTYPES_LOWP_GRAD = ["bfloat16", "float16"]
_NP_DT = {"float64": np.float64, "float32": np.float32,
          "bfloat16": bfloat16, "float16": np.float16}


@pytest.fixture(scope="module", autouse=True)
def _x64():
    # paddle_tpu itself enables x64 at import (reference float64 parity);
    # restore whatever the session had, don't force it off
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


class Case:
    def __init__(self, op, gen, wrt=(0,), kwargs=None, out_index=0):
        self.op = op
        self.gen = gen            # gen(rng) -> list of np arrays (f64 base)
        self.wrt = tuple(wrt)     # () = forward-only
        self.kwargs = kwargs or {}
        self.out_index = out_index


def _r(seed):
    return np.random.RandomState(seed)


def _cast(arrays, dtype):
    dt = _NP_DT[dtype]
    return [a.astype(dt) if a.dtype.kind == "f" else a for a in arrays]


def _run(case, arrays):
    ts = [Tensor(jax.numpy.asarray(a)) for a in arrays]
    out = case.op(*ts, **case.kwargs)
    if isinstance(out, (tuple, list)):
        out = out[case.out_index]
    return out


def _fwd(case, arrays):
    out = _run(case, arrays)
    return np.asarray(out.numpy()).astype(np.float64)


def _autograd(case, arrays):
    ts = [Tensor(jax.numpy.asarray(a)) for a in arrays]
    for i in case.wrt:
        ts[i].stop_gradient = False
    out = case.op(*ts, **case.kwargs)
    if isinstance(out, (tuple, list)):
        out = out[case.out_index]
    out.sum().backward()
    return [np.asarray(ts[i].grad.numpy()).astype(np.float64)
            for i in case.wrt]


# ---------------------------------------------------------------------------
# Case registry. Names match the dispatch op names where one exists.
# ---------------------------------------------------------------------------

def _pair(seed, shape=(3, 4)):
    r = _r(seed)
    return lambda rng=None: [r.randn(*shape).copy(), r.randn(*shape).copy()]


CASES = {
    # --- elementwise binary ---
    "add": Case(P.add, lambda: [_r(0).randn(3, 4), _r(1).randn(3, 4)],
                wrt=(0, 1)),
    "subtract": Case(P.subtract,
                     lambda: [_r(2).randn(3, 4), _r(3).randn(3, 4)],
                     wrt=(0, 1)),
    "multiply": Case(P.multiply,
                     lambda: [_r(4).randn(3, 4), _r(5).randn(3, 4)],
                     wrt=(0, 1)),
    "divide": Case(P.divide,
                   lambda: [_r(6).randn(3, 4), _r(7).rand(3, 4) + 0.5],
                   wrt=(0, 1)),
    "pow": Case(P.pow, lambda: [_r(8).rand(3, 4) + 0.5,
                                _r(9).rand(3, 4) * 2], wrt=(0, 1)),
    "maximum": Case(P.maximum,
                    lambda: [_r(10).randn(3, 4), _r(11).randn(3, 4)],
                    wrt=(0, 1)),
    "minimum": Case(P.minimum,
                    lambda: [_r(12).randn(3, 4), _r(13).randn(3, 4)],
                    wrt=(0, 1)),
    "atan2": Case(P.atan2,
                  lambda: [_r(14).randn(3, 4), _r(15).rand(3, 4) + 0.5],
                  wrt=(0, 1)),
    "lerp": Case(lambda x, y: P.lerp(x, y, 0.3),
                 lambda: [_r(16).randn(3, 4), _r(17).randn(3, 4)],
                 wrt=(0, 1)),
    # --- elementwise unary ---
    "exp": Case(P.exp, lambda: [_r(20).randn(3, 4)]),
    "expm1": Case(P.expm1, lambda: [_r(21).randn(3, 4)]),
    "log": Case(P.log, lambda: [_r(22).rand(3, 4) + 0.5]),
    "log1p": Case(P.log1p, lambda: [_r(23).rand(3, 4)]),
    "log2": Case(P.log2, lambda: [_r(24).rand(3, 4) + 0.5]),
    "log10": Case(P.log10, lambda: [_r(25).rand(3, 4) + 0.5]),
    "sqrt": Case(P.sqrt, lambda: [_r(26).rand(3, 4) + 0.2]),
    "rsqrt": Case(P.rsqrt, lambda: [_r(27).rand(3, 4) + 0.2]),
    "abs": Case(P.abs, lambda: [_r(28).randn(3, 4) + 0.1]),
    "floor": Case(P.floor, lambda: [_r(29).randn(3, 4) * 3]),
    "ceil": Case(P.ceil, lambda: [_r(30).randn(3, 4) * 3]),
    "round": Case(P.round, lambda: [_r(31).randn(3, 4) * 3]),
    "sign": Case(P.sign, lambda: [_r(32).randn(3, 4)]),
    "trunc": Case(P.trunc, lambda: [_r(33).randn(3, 4) * 3], wrt=()),
    "sin": Case(P.sin, lambda: [_r(34).randn(3, 4)]),
    "cos": Case(P.cos, lambda: [_r(35).randn(3, 4)]),
    "tan": Case(P.tan, lambda: [_r(36).rand(3, 4) - 0.5]),
    "asin": Case(P.asin, lambda: [_r(37).rand(3, 4) * 1.6 - 0.8]),
    "acos": Case(P.acos, lambda: [_r(38).rand(3, 4) * 1.6 - 0.8]),
    "atan": Case(P.atan, lambda: [_r(39).randn(3, 4)]),
    "sinh": Case(P.sinh, lambda: [_r(40).randn(3, 4)]),
    "cosh": Case(P.cosh, lambda: [_r(41).randn(3, 4)]),
    "tanh": Case(P.tanh, lambda: [_r(42).randn(3, 4)]),
    "erf": Case(P.erf, lambda: [_r(43).randn(3, 4)]),
    "reciprocal": Case(P.reciprocal, lambda: [_r(44).rand(3, 4) + 0.5]),
    "square": Case(P.square, lambda: [_r(45).randn(3, 4)]),
    "sigmoid": Case(P.sigmoid, lambda: [_r(46).randn(3, 4)]),
    "clip": Case(lambda x: P.clip(x, -0.6, 0.6),
                 lambda: [_r(47).randn(3, 4)]),
    # --- reductions ---
    "sum": Case(lambda x: P.sum(x, axis=1), lambda: [_r(50).randn(3, 4)]),
    "mean": Case(lambda x: P.mean(x, axis=0), lambda: [_r(51).randn(3, 4)]),
    "max": Case(lambda x: P.max(x, axis=1), lambda: [_r(52).randn(3, 4)]),
    "min": Case(lambda x: P.min(x, axis=1), lambda: [_r(53).randn(3, 4)]),
    "prod": Case(lambda x: P.prod(x, axis=1),
                 lambda: [_r(54).rand(3, 4) + 0.5]),
    "std": Case(P.std, lambda: [_r(55).randn(3, 4)]),
    "var": Case(P.var, lambda: [_r(56).randn(3, 4)]),
    "logsumexp": Case(P.logsumexp, lambda: [_r(57).randn(3, 4)]),
    "cumsum": Case(lambda x: P.cumsum(x, axis=1),
                   lambda: [_r(58).randn(3, 4)]),
    "cumprod": Case(lambda x: P.cumprod(x, dim=1),
                    lambda: [_r(59).rand(3, 4) + 0.5]),
    "norm": Case(P.norm, lambda: [_r(60).randn(3, 4)]),
    # --- search / sort ---
    "argmax": Case(lambda x: P.argmax(x, axis=1),
                   lambda: [_r(61).randn(3, 4)], wrt=()),
    "argmin": Case(lambda x: P.argmin(x, axis=1),
                   lambda: [_r(62).randn(3, 4)], wrt=()),
    "sort": Case(lambda x: P.sort(x, axis=1), lambda: [_r(63).randn(3, 4)]),
    "argsort": Case(lambda x: P.argsort(x, axis=1),
                    lambda: [_r(64).randn(3, 4)], wrt=()),
    "topk": Case(lambda x: P.topk(x, 2, axis=1),
                 lambda: [_r(65).randn(3, 4)], out_index=0),
    "where": Case(lambda c, x, y: P.where(c, x, y),
                  lambda: [_r(66).rand(3, 4) > 0.5, _r(67).randn(3, 4),
                           _r(68).randn(3, 4)], wrt=(1, 2)),
    # --- linalg-ish ---
    "matmul": Case(P.matmul, lambda: [_r(70).randn(3, 5), _r(71).randn(5, 4)],
                   wrt=(0, 1)),
    "bmm": Case(P.bmm, lambda: [_r(72).randn(2, 3, 4), _r(73).randn(2, 4, 3)],
                wrt=(0, 1)),
    "dot": Case(P.dot, lambda: [_r(74).randn(6), _r(75).randn(6)],
                wrt=(0, 1)),
    "mv": Case(P.mv, lambda: [_r(76).randn(3, 4), _r(77).randn(4)],
               wrt=(0, 1)),
    "outer": Case(P.outer, lambda: [_r(78).randn(3), _r(79).randn(4)],
                  wrt=(0, 1)),
    "einsum": Case(lambda a, b: P.einsum("ij,jk->ik", a, b),
                   lambda: [_r(80).randn(3, 5), _r(81).randn(5, 4)],
                   wrt=(0, 1)),
    "trace": Case(P.trace, lambda: [_r(82).randn(4, 4)]),
    "diag": Case(P.diag, lambda: [_r(83).randn(4, 4)]),
    "tril": Case(P.tril, lambda: [_r(84).randn(4, 4)]),
    "triu": Case(P.triu, lambda: [_r(85).randn(4, 4)]),
    "kron": Case(P.kron, lambda: [_r(86).randn(2, 2), _r(87).randn(2, 3)],
                 wrt=(0, 1)),
    "cross": Case(lambda a, b: P.cross(a, b, axis=1),
                  lambda: [_r(88).randn(2, 3), _r(89).randn(2, 3)],
                  wrt=(0, 1)),
    # --- manip ---
    "reshape": Case(lambda x: P.reshape(x, [4, 3]),
                    lambda: [_r(90).randn(3, 4)]),
    "transpose": Case(lambda x: P.transpose(x, [1, 0]),
                      lambda: [_r(91).randn(3, 4)]),
    "concat": Case(lambda a, b: P.concat([a, b], axis=0),
                   lambda: [_r(92).randn(2, 4), _r(93).randn(3, 4)],
                   wrt=(0, 1)),
    "split": Case(lambda x: P.split(x, 2, axis=1),
                  lambda: [_r(94).randn(3, 4)], out_index=0),
    "stack": Case(lambda a, b: P.stack([a, b], axis=0),
                  lambda: [_r(95).randn(3, 4), _r(96).randn(3, 4)],
                  wrt=(0, 1)),
    "squeeze": Case(lambda x: P.squeeze(x, axis=1),
                    lambda: [_r(97).randn(3, 1, 4)]),
    "unsqueeze": Case(lambda x: P.unsqueeze(x, axis=1),
                      lambda: [_r(98).randn(3, 4)]),
    "flip": Case(lambda x: P.flip(x, axis=[1]),
                 lambda: [_r(99).randn(3, 4)]),
    "roll": Case(lambda x: P.roll(x, 2, axis=1),
                 lambda: [_r(100).randn(3, 4)]),
    "tile": Case(lambda x: P.tile(x, [2, 1]), lambda: [_r(101).randn(3, 4)]),
    "expand": Case(lambda x: P.expand(x, [3, 3, 4]),
                   lambda: [_r(102).randn(1, 3, 4)]),
    "flatten": Case(lambda x: P.flatten(x, start_axis=1),
                    lambda: [_r(103).randn(2, 3, 4)]),
    "gather": Case(lambda x, i: P.gather(x, i, axis=0),
                   lambda: [_r(104).randn(5, 4),
                            np.asarray([0, 2, 4], np.int64)], wrt=(0,)),
    "index_select": Case(lambda x, i: P.index_select(x, i, axis=1),
                         lambda: [_r(105).randn(3, 5),
                                  np.asarray([1, 3], np.int64)], wrt=(0,)),
    "one_hot": Case(lambda i: F.one_hot(i, 5),
                    lambda: [np.asarray([0, 3, 4], np.int64)], wrt=()),
    "pad": Case(lambda x: F.pad(x, [1, 1], value=0.0),
                lambda: [_r(106).randn(3, 4)]),
    # --- activations ---
    "relu": Case(F.relu, lambda: [_r(110).randn(3, 4) + 0.05]),
    # inputs kept >=0.3 away from the 0 and 6 kinks: bf16 rounding must not
    # move any element across a gradient discontinuity
    "relu6": Case(F.relu6, lambda: [np.where(
        np.abs(_r(111).randn(3, 4) * 2) < 0.3,
        np.sign(_r(111).randn(3, 4)) * 0.5,
        _r(111).randn(3, 4) * 2)]),
    "gelu": Case(F.gelu, lambda: [_r(112).randn(3, 4)]),
    "silu": Case(F.silu, lambda: [_r(113).randn(3, 4)]),
    "softplus": Case(F.softplus, lambda: [_r(114).randn(3, 4)]),
    "softsign": Case(F.softsign, lambda: [_r(115).randn(3, 4)]),
    "hardswish": Case(F.hardswish, lambda: [_r(116).randn(3, 4) * 3 + 0.1]),
    "hardsigmoid": Case(F.hardsigmoid,
                        lambda: [_r(117).randn(3, 4) * 3 + 0.1]),
    "leaky_relu": Case(F.leaky_relu, lambda: [_r(118).randn(3, 4) + 0.05]),
    "elu": Case(F.elu, lambda: [_r(119).randn(3, 4)]),
    "selu": Case(F.selu, lambda: [_r(120).randn(3, 4)]),
    "mish": Case(F.mish, lambda: [_r(121).randn(3, 4)]),
    "tanhshrink": Case(F.tanhshrink, lambda: [_r(122).randn(3, 4)]),
    "hardshrink": Case(F.hardshrink, lambda: [_r(123).randn(3, 4) * 2]),
    "softshrink": Case(F.softshrink, lambda: [_r(124).randn(3, 4) * 2]),
    "prelu": Case(F.prelu, lambda: [_r(125).randn(3, 4),
                                    np.asarray([0.25])], wrt=(0, 1)),
    "glu": Case(lambda x: F.glu(x, axis=-1), lambda: [_r(126).randn(3, 6)]),
    "softmax": Case(lambda x: F.softmax(x, axis=-1),
                    lambda: [_r(127).randn(3, 4)]),
    "log_softmax": Case(lambda x: F.log_softmax(x, axis=-1),
                        lambda: [_r(128).randn(3, 4)]),
    # --- nn building blocks ---
    "linear": Case(F.linear, lambda: [_r(130).randn(3, 5),
                                      _r(131).randn(5, 4) * 0.5,
                                      _r(132).randn(4) * 0.1],
                   wrt=(0, 1, 2)),
    "embedding": Case(lambda i, w: F.embedding(i, w),
                      lambda: [np.asarray([[0, 2], [3, 1]], np.int64),
                               _r(133).randn(5, 4)], wrt=(1,)),
    "conv2d": Case(lambda x, w: F.conv2d(x, w, padding=1),
                   lambda: [_r(134).randn(1, 2, 5, 5),
                            _r(135).randn(3, 2, 3, 3) * 0.3], wrt=(0, 1)),
    "conv2d_transpose": Case(lambda x, w: F.conv2d_transpose(x, w),
                             lambda: [_r(136).randn(1, 2, 4, 4),
                                      _r(137).randn(2, 3, 3, 3) * 0.3],
                             wrt=(0, 1)),
    "max_pool2d": Case(lambda x: F.max_pool2d(x, 2),
                       lambda: [_r(138).randn(1, 2, 4, 4)]),
    "avg_pool2d": Case(lambda x: F.avg_pool2d(x, 2),
                       lambda: [_r(139).randn(1, 2, 4, 4)]),
    "adaptive_avg_pool2d": Case(lambda x: F.adaptive_avg_pool2d(x, 2),
                                lambda: [_r(140).randn(1, 2, 6, 6)]),
    "interpolate": Case(lambda x: F.interpolate(x, scale_factor=2,
                                                mode="bilinear"),
                        lambda: [_r(141).randn(1, 2, 3, 3)]),
    "pixel_shuffle": Case(lambda x: F.pixel_shuffle(x, 2),
                          lambda: [_r(142).randn(1, 4, 3, 3)]),
    "layer_norm": Case(lambda x, w, b: F.layer_norm(x, [4], weight=w,
                                                    bias=b),
                       lambda: [_r(143).randn(3, 4),
                                _r(144).rand(4) + 0.5,
                                _r(145).randn(4) * 0.1], wrt=(0, 1, 2)),
    "group_norm": Case(lambda x: F.group_norm(x, 2),
                       lambda: [_r(146).randn(2, 4, 3, 3)]),
    "instance_norm": Case(F.instance_norm,
                          lambda: [_r(147).randn(2, 3, 4, 4)]),
    "batch_norm": Case(
        lambda x, m, v, w, b: F.batch_norm(x, m, v, weight=w, bias=b,
                                           training=False),
        lambda: [_r(148).randn(2, 3, 4, 4), _r(149).randn(3) * 0.1,
                 _r(150).rand(3) + 0.5, _r(151).rand(3) + 0.5,
                 _r(152).randn(3) * 0.1], wrt=(0, 3, 4)),
    "normalize": Case(lambda x: F.normalize(x, axis=1),
                      lambda: [_r(153).randn(3, 4)]),
    "cosine_similarity": Case(lambda a, b: F.cosine_similarity(a, b, axis=1),
                              lambda: [_r(154).randn(3, 4),
                                       _r(155).randn(3, 4)], wrt=(0, 1)),
    "sdpa": Case(lambda q, k, v: F.scaled_dot_product_attention(
        q, k, v, is_causal=True),
        lambda: [_r(156).randn(1, 4, 2, 8) * 0.5,
                 _r(157).randn(1, 4, 2, 8) * 0.5,
                 _r(158).randn(1, 4, 2, 8) * 0.5], wrt=(0, 1, 2)),
    "rms_norm": Case(
        lambda x, w: __import__(
            "paddle_tpu.incubate.nn.functional", fromlist=["x"]
        ).fused_rms_norm(x, w),
        lambda: [_r(159).randn(3, 8), _r(160).rand(8) + 0.5], wrt=(0, 1)),
    # --- losses ---
    "cross_entropy": Case(
        lambda x, lab: F.cross_entropy(x, lab, reduction="mean"),
        lambda: [_r(161).randn(4, 5), np.asarray([0, 2, 4, 1], np.int64)],
        wrt=(0,)),
    "nll_loss": Case(
        lambda x, lab: F.nll_loss(x, lab),
        lambda: [np.log(_r(162).rand(4, 5) + 0.1),
                 np.asarray([0, 2, 4, 1], np.int64)], wrt=(0,)),
    "mse_loss": Case(F.mse_loss, lambda: [_r(163).randn(3, 4),
                                          _r(164).randn(3, 4)], wrt=(0,)),
    "l1_loss": Case(F.l1_loss, lambda: [_r(165).randn(3, 4),
                                        _r(166).randn(3, 4)], wrt=(0,)),
    "smooth_l1_loss": Case(F.smooth_l1_loss,
                           lambda: [_r(167).randn(3, 4),
                                    _r(168).randn(3, 4)], wrt=(0,)),
    "binary_cross_entropy": Case(
        F.binary_cross_entropy,
        lambda: [_r(169).rand(6) * 0.8 + 0.1,
                 (_r(170).rand(6) > 0.5).astype(np.float64)], wrt=(0,)),
    # dispatch records this op as 'bce_with_logits'
    "bce_with_logits": Case(
        F.binary_cross_entropy_with_logits,
        lambda: [_r(171).randn(6),
                 (_r(172).rand(6) > 0.5).astype(np.float64)], wrt=(0,)),
    "kl_div": Case(
        lambda x, t: F.kl_div(x, t, reduction="mean"),
        lambda: [np.log(_r(173).rand(4, 5) + 0.1),
                 _r(174).rand(4, 5) + 0.1], wrt=(0,)),
    # cast to the WIDEST float: casting down to fp32 would make the fp64
    # finite-difference leg measure fp32 rounding, not the gradient
    "cast": Case(lambda x: P.cast(x, "float64"),
                 lambda: [_r(175).randn(3, 4)]),
    "ctc_loss": Case(
        lambda lg, lab, ilen, llen: F.ctc_loss(lg, lab, ilen, llen),
        lambda: [_r(176).randn(1, 6, 5),
                 np.asarray([[1, 2, 3]], np.int64),
                 np.asarray([6], np.int64),
                 np.asarray([3], np.int64)], wrt=(0,)),
}

# ---------------------------------------------------------------------------
# Round-5 extension (VERDICT r4 item 4): the long tail beyond the model-zoo
# floor — linalg decompositions (with their grads), fft, indexing/scatter,
# stats/quantiles, special functions, and the loss family.  Decomposition
# outputs with sign/phase gauge freedom are compared through invariant
# functionals (|R| for qr, singular/eigen-values, reconstructions) so the
# dtype sweep never fails on a legitimate sign flip.
# ---------------------------------------------------------------------------

def _pd(seed, n=4):
    """Well-conditioned symmetric positive-definite matrix."""
    a = _r(seed).randn(n, n)
    return a @ a.T + n * np.eye(n)


def _sym(seed, n=4):
    a = _r(seed).randn(n, n)
    return (a + a.T) / 2 + np.diag(np.arange(n) * 2.0)  # separated eigvals


L = P.linalg
FFT = P.fft

CASES.update({
    # --- linalg decompositions / solvers ---
    "cholesky": Case(L.cholesky, lambda: [_pd(200)], wrt=(0,)),
    "qr": Case(lambda a: P.abs(L.qr(a)[1]), lambda: [_r(201).randn(4, 3)],
               wrt=(0,)),
    "svd": Case(lambda a: L.svd(a)[1], lambda: [_r(202).randn(4, 3)],
                wrt=(0,)),
    "svd_reconstruct": Case(
        lambda a: (lambda u, s, vh: u @ P.diag(s) @ vh)(*L.svd(a, full_matrices=False)),
        lambda: [_r(203).randn(4, 3)], wrt=()),
    "eigh": Case(lambda a: L.eigh(a)[0], lambda: [_sym(204)], wrt=(0,)),
    "eigvalsh": Case(L.eigvalsh, lambda: [_sym(205)], wrt=(0,)),
    "eigvals": Case(lambda a: P.sort(P.abs(L.eigvals(a))),
                    lambda: [_sym(206)], wrt=()),
    "lu": Case(lambda a: L.lu(a)[0], lambda: [_pd(207)], wrt=()),
    "solve": Case(L.solve, lambda: [_pd(208), _r(209).randn(4, 2)],
                  wrt=(0, 1)),
    "triangular_solve": Case(
        lambda a, b: L.triangular_solve(a, b, upper=False),
        lambda: [np.tril(_r(210).randn(4, 4)) + 4 * np.eye(4),
                 _r(211).randn(4, 2)], wrt=(0, 1)),
    "cholesky_solve": Case(
        lambda b, l: P.cholesky_solve(b, l, upper=False)
        if hasattr(P, "cholesky_solve") else L.cholesky_solve(b, l),
        lambda: [_r(212).randn(4, 2), np.linalg.cholesky(_pd(213))],
        wrt=(0,)),
    "lstsq": Case(lambda a, b: L.lstsq(a, b)[0],
                  lambda: [_r(214).randn(5, 3), _r(215).randn(5, 2)],
                  wrt=()),
    "inv": Case(L.inv, lambda: [_pd(216)], wrt=(0,)),
    "pinv": Case(L.pinv, lambda: [_r(217).randn(4, 3)], wrt=(0,)),
    "det": Case(L.det, lambda: [_pd(218)], wrt=(0,)),
    "slogdet": Case(lambda a: L.slogdet(a)[1], lambda: [_pd(219)],
                    wrt=(0,)),
    "matrix_power": Case(lambda a: L.matrix_power(a, 3),
                         lambda: [_r(220).randn(4, 4) * 0.5], wrt=(0,)),
    "matrix_rank": Case(lambda a: P.cast(L.matrix_rank(a), "float64"),
                        lambda: [_pd(221)], wrt=()),
    "cond_linalg": Case(L.cond, lambda: [_pd(222)], wrt=()),
    "multi_dot": Case(lambda a, b, c: L.multi_dot([a, b, c]),
                      lambda: [_r(223).randn(3, 4), _r(224).randn(4, 2),
                               _r(225).randn(2, 5)], wrt=(0, 1, 2)),
    "corrcoef": Case(L.corrcoef, lambda: [_r(226).randn(3, 8)], wrt=()),
    "cov": Case(L.cov, lambda: [_r(227).randn(3, 8)], wrt=(0,)),
    "householder_product": Case(
        L.householder_product,
        lambda: [_r(228).randn(4, 3), _r(229).randn(3)], wrt=(0, 1)),
    "addmm": Case(P.addmm, lambda: [_r(230).randn(3, 5), _r(231).randn(3, 4),
                                    _r(232).randn(4, 5)], wrt=(0, 1, 2)),
    "inner": Case(P.inner, lambda: [_r(233).randn(3, 4), _r(234).randn(5, 4)],
                  wrt=(0, 1)),
    "tensordot": Case(lambda a, b: P.tensordot(a, b, axes=2),
                      lambda: [_r(235).randn(3, 4, 5), _r(236).randn(4, 5)],
                      wrt=(0, 1)),
    "vander": Case(lambda x: P.vander(x, 4), lambda: [_r(237).randn(5)],
                   wrt=(0,)),
    # --- fft (complex kernels are c64/c128-only: low-precision legs are
    #     recorded skips; |.| makes outputs real and the FD loss scalar) ---
    "fft": Case(lambda x: P.abs(FFT.fft(x)), lambda: [_r(240).randn(8)],
                wrt=(0,)),
    "ifft": Case(lambda a, b: P.abs(FFT.ifft(P.complex(a, b))),
                 lambda: [_r(241).randn(8), _r(242).randn(8)], wrt=(0, 1)),
    "rfft": Case(lambda x: P.abs(FFT.rfft(x)), lambda: [_r(243).randn(8)],
                 wrt=(0,)),
    "irfft": Case(lambda a, b: FFT.irfft(P.complex(a, b), 8),
                  lambda: [_r(244).randn(5), _r(245).randn(5)], wrt=(0, 1)),
    "fft2": Case(lambda x: P.abs(FFT.fft2(x)), lambda: [_r(246).randn(4, 4)],
                 wrt=(0,)),
    "ifft2": Case(lambda a, b: P.abs(FFT.ifft2(P.complex(a, b))),
                  lambda: [_r(247).randn(4, 4), _r(248).randn(4, 4)],
                  wrt=(0, 1)),
    "rfft2": Case(lambda x: P.abs(FFT.rfft2(x)),
                  lambda: [_r(249).randn(4, 4)], wrt=(0,)),
    "irfft2": Case(lambda a, b: FFT.irfft2(P.complex(a, b), s=(4, 4)),
                   lambda: [_r(250).randn(4, 3), _r(251).randn(4, 3)],
                   wrt=(0, 1)),
    "hfft": Case(lambda a, b: FFT.hfft(P.complex(a, b), 8),
                 lambda: [_r(252).randn(5), _r(253).randn(5)], wrt=(0, 1)),
    "ihfft": Case(lambda x: P.abs(FFT.ihfft(x)), lambda: [_r(254).randn(8)],
                  wrt=(0,)),
    "fftshift": Case(FFT.fftshift, lambda: [_r(255).randn(8)], wrt=(0,)),
    "ifftshift": Case(FFT.ifftshift, lambda: [_r(256).randn(8)], wrt=(0,)),
    # --- indexing / scatter ---
    "gather_nd": Case(
        P.gather_nd,
        lambda: [_r(260).randn(4, 5),
                 np.asarray([[0, 1], [3, 4], [2, 2]], np.int64)], wrt=(0,)),
    "scatter": Case(
        lambda x, idx, upd: P.scatter(x, idx, upd, overwrite=False),
        lambda: [_r(261).randn(5, 3), np.asarray([0, 2, 4], np.int64),
                 _r(262).randn(3, 3)], wrt=(0, 2)),
    "scatter_nd": Case(
        lambda idx, upd: P.scatter_nd(idx, upd, [6]),
        lambda: [np.asarray([[1], [3], [5]], np.int64),
                 _r(263).randn(3)], wrt=(1,)),
    "scatter_nd_add": Case(
        P.scatter_nd_add,
        lambda: [_r(264).randn(6), np.asarray([[1], [3], [1]], np.int64),
                 _r(265).randn(3)], wrt=(0, 2)),
    "put_along_axis": Case(
        lambda x, i, v: P.put_along_axis(x, i, v, axis=1),
        lambda: [_r(266).randn(3, 5),
                 _r(267).randint(0, 5, (3, 2)).astype(np.int64),
                 _r(268).randn(3, 2)], wrt=(0, 2)),
    "take_along_axis": Case(
        lambda x, i: P.take_along_axis(x, i, axis=1),
        lambda: [_r(269).randn(3, 5),
                 _r(270).randint(0, 5, (3, 2)).astype(np.int64)], wrt=(0,)),
    "index_sample": Case(
        P.index_sample,
        lambda: [_r(271).randn(3, 5),
                 _r(272).randint(0, 5, (3, 2)).astype(np.int64)], wrt=(0,)),
    "index_add": Case(
        lambda x, i, v: P.index_add(x, i, 0, v),
        lambda: [_r(273).randn(5, 3), np.asarray([0, 2], np.int64),
                 _r(274).randn(2, 3)], wrt=(0, 2)),
    "index_put": Case(
        lambda x, i, v: P.index_put(x, [i], v),
        lambda: [_r(275).randn(5, 3), np.asarray([1, 3], np.int64),
                 _r(276).randn(2, 3)], wrt=(0, 2)),
    "index_fill": Case(
        lambda x, i: P.index_fill(x, i, 0, 0.5),
        lambda: [_r(277).randn(5, 3), np.asarray([1, 3], np.int64)],
        wrt=(0,)),
    "masked_fill": Case(
        lambda x, m: P.masked_fill(x, m, 0.5),
        lambda: [_r(278).randn(4, 4), _r(279).rand(4, 4) > 0.5], wrt=(0,)),
    "masked_select": Case(
        P.masked_select,
        lambda: [_r(280).randn(4, 4), _r(281).rand(4, 4) > 0.5], wrt=(0,)),
    "diagonal": Case(P.diagonal, lambda: [_r(282).randn(4, 4)], wrt=(0,)),
    "diagflat": Case(P.diagflat, lambda: [_r(283).randn(4)], wrt=(0,)),
    "rot90": Case(P.rot90, lambda: [_r(284).randn(3, 4)], wrt=(0,)),
    "unbind": Case(lambda x: P.unbind(x)[1], lambda: [_r(285).randn(3, 4)],
                   wrt=(0,)),
    "chunk": Case(lambda x: P.chunk(x, 2, axis=1)[0],
                  lambda: [_r(286).randn(3, 4)], wrt=(0,)),
    "repeat_interleave": Case(
        lambda x: P.repeat_interleave(x, 2, axis=0),
        lambda: [_r(287).randn(3, 4)], wrt=(0,)),
    "diff": Case(P.diff, lambda: [_r(288).randn(3, 5)], wrt=(0,)),
    # --- stats / order ---
    "amax": Case(lambda x: P.amax(x, axis=1), lambda: [_r(290).randn(3, 5)],
                 wrt=(0,)),
    "amin": Case(lambda x: P.amin(x, axis=1), lambda: [_r(291).randn(3, 5)],
                 wrt=(0,)),
    "nansum": Case(
        P.nansum,
        lambda: [np.where(_r(292).rand(3, 5) > 0.8, np.nan,
                          _r(293).randn(3, 5))], wrt=()),
    "nanmean": Case(
        P.nanmean,
        lambda: [np.where(_r(294).rand(3, 5) > 0.8, np.nan,
                          _r(295).randn(3, 5))], wrt=()),
    "median": Case(lambda x: P.median(x, axis=1),
                   lambda: [_r(296).randn(3, 5)], wrt=(0,)),
    "nanmedian": Case(lambda x: P.nanmedian(x, axis=1),
                      lambda: [_r(297).randn(3, 5)], wrt=()),
    "quantile": Case(lambda x: P.quantile(x, 0.3, axis=1),
                     lambda: [_r(298).randn(3, 5)], wrt=(0,)),
    "kthvalue": Case(lambda x: P.kthvalue(x, 2, axis=1)[0],
                     lambda: [_r(299).randn(3, 5)], wrt=(0,)),
    "mode": Case(lambda x: P.mode(x, axis=1)[0],
                 lambda: [_r(300).randn(3, 5)], wrt=()),
    "cummax": Case(lambda x: P.cummax(x, axis=1)[0],
                   lambda: [_r(301).randn(3, 5)], wrt=(0,)),
    "cummin": Case(lambda x: P.cummin(x, axis=1)[0],
                   lambda: [_r(302).randn(3, 5)], wrt=(0,)),
    "logcumsumexp": Case(lambda x: P.logcumsumexp(x, axis=1),
                         lambda: [_r(303).randn(3, 5)], wrt=(0,)),
    "searchsorted": Case(
        lambda s, v: P.cast(P.searchsorted(s, v), "float64"),
        lambda: [np.sort(_r(304).randn(8)), _r(305).randn(5)], wrt=()),
    "bucketize": Case(
        lambda x, s: P.cast(P.bucketize(x, s), "float64"),
        lambda: [_r(306).randn(5), np.sort(_r(307).randn(6))], wrt=()),
    # --- elementwise binary extras ---
    "fmax": Case(P.fmax, lambda: [_r(310).randn(3, 4), _r(311).randn(3, 4)],
                 wrt=(0, 1)),
    "fmin": Case(P.fmin, lambda: [_r(312).randn(3, 4), _r(313).randn(3, 4)],
                 wrt=(0, 1)),
    "copysign": Case(P.copysign,
                     lambda: [_r(314).randn(3, 4),
                              _r(315).randn(3, 4)], wrt=(0,)),
    "hypot": Case(P.hypot, lambda: [_r(316).randn(3, 4) + 2.0,
                                    _r(317).randn(3, 4) + 2.0], wrt=(0, 1)),
    "heaviside": Case(P.heaviside,
                      lambda: [_r(318).randn(3, 4), _r(319).rand(3, 4)],
                      wrt=()),
    "remainder": Case(P.remainder,
                      lambda: [_r(320).randn(3, 4) * 3,
                               _r(321).rand(3, 4) + 1.0], wrt=(0,)),
    "mod_floor": Case(P.floor_mod,
                      lambda: [_r(322).randn(3, 4) * 3,
                               _r(323).rand(3, 4) + 1.0], wrt=()),
    "ldexp": Case(P.ldexp,
                  lambda: [_r(324).randn(3, 4),
                           _r(325).randint(-3, 4, (3, 4)).astype(np.int64)],
                  wrt=(0,)),
    "logaddexp": Case(P.logaddexp,
                      lambda: [_r(326).randn(3, 4), _r(327).randn(3, 4)],
                      wrt=(0, 1)),
    "nextafter": Case(P.nextafter,
                      lambda: [_r(328).randn(3, 4), _r(329).randn(3, 4)],
                      wrt=()),
    # --- special functions ---
    "logit": Case(lambda x: P.logit(x, eps=1e-6),
                  lambda: [_r(330).rand(3, 4) * 0.8 + 0.1], wrt=(0,)),
    "erfinv": Case(P.erfinv, lambda: [_r(331).rand(3, 4) * 1.6 - 0.8],
                   wrt=(0,)),
    "lgamma": Case(P.lgamma, lambda: [_r(332).rand(3, 4) * 3 + 0.5],
                   wrt=(0,)),
    "digamma": Case(P.digamma, lambda: [_r(333).rand(3, 4) * 3 + 0.5],
                    wrt=(0,)),
    "polygamma": Case(lambda x: P.polygamma(x, 1),
                      lambda: [_r(334).rand(3, 4) * 3 + 0.5], wrt=(0,)),
    "i0": Case(P.i0, lambda: [_r(335).randn(3, 4)], wrt=(0,)),
    "i0e": Case(P.i0e, lambda: [_r(336).randn(3, 4)], wrt=(0,)),
    "i1": Case(P.i1, lambda: [_r(337).randn(3, 4)], wrt=(0,)),
    "i1e": Case(P.i1e, lambda: [_r(338).randn(3, 4)], wrt=(0,)),
    "stanh": Case(P.stanh, lambda: [_r(339).randn(3, 4)], wrt=(0,)),
    "nan_to_num": Case(
        P.nan_to_num,
        lambda: [np.where(_r(340).rand(3, 4) > 0.8, np.nan,
                          _r(341).randn(3, 4))], wrt=()),
    # fractional parts pinned to [0.1, 0.9]: a value NEAR an integer would
    # cross the trunc boundary under bf16 rounding and flip frac by ~1
    "frac": Case(P.frac,
                 lambda: [_r(342).randint(-3, 4, (3, 4)).astype(np.float64)
                          + _r(343).rand(3, 4) * 0.8 + 0.1], wrt=(0,)),
    "deg2rad": Case(P.deg2rad, lambda: [_r(345).randn(3, 4) * 90],
                    wrt=(0,)),
    "rad2deg": Case(P.rad2deg, lambda: [_r(344).randn(3, 4)], wrt=(0,)),
    # --- losses ---
    "margin_ranking_loss": Case(
        F.margin_ranking_loss,
        lambda: [_r(350).randn(6), _r(351).randn(6),
                 np.sign(_r(352).randn(6))], wrt=(0, 1)),
    "hinge_embedding_loss": Case(
        F.hinge_embedding_loss,
        lambda: [_r(353).randn(6), np.sign(_r(354).randn(6))], wrt=(0,)),
    "cosine_embedding_loss": Case(
        F.cosine_embedding_loss,
        lambda: [_r(355).randn(4, 6), _r(356).randn(4, 6),
                 np.sign(_r(357).randn(4))], wrt=(0, 1)),
    "triplet_margin_loss": Case(
        F.triplet_margin_loss,
        lambda: [_r(358).randn(4, 6), _r(359).randn(4, 6),
                 _r(360).randn(4, 6)], wrt=(0, 1, 2)),
    "multi_label_soft_margin_loss": Case(
        F.multi_label_soft_margin_loss,
        lambda: [_r(361).randn(4, 5),
                 (_r(362).rand(4, 5) > 0.5).astype(np.float64)], wrt=(0,)),
    "multi_margin_loss": Case(
        F.multi_margin_loss,
        lambda: [_r(363).randn(4, 5),
                 _r(364).randint(0, 5, (4,)).astype(np.int64)], wrt=(0,)),
    "poisson_nll_loss": Case(
        F.poisson_nll_loss,
        lambda: [_r(365).randn(4, 5), _r(366).rand(4, 5) * 3], wrt=(0,)),
    "gaussian_nll_loss": Case(
        F.gaussian_nll_loss,
        lambda: [_r(367).randn(4, 5), _r(368).randn(4, 5),
                 _r(369).rand(4, 5) + 0.5], wrt=(0, 2)),
    "huber_loss": Case(
        lambda x, y: F.smooth_l1_loss(x, y, delta=1.0)
        if not hasattr(F, "huber_loss") else F.huber_loss(x, y),
        lambda: [_r(370).randn(4, 5), _r(371).randn(4, 5)], wrt=(0,)),
    "soft_margin_loss": Case(
        F.soft_margin_loss,
        lambda: [_r(372).randn(6), np.sign(_r(373).randn(6))], wrt=(0,)),
    "square_error_cost": Case(
        F.square_error_cost,
        lambda: [_r(374).randn(4, 5), _r(375).randn(4, 5)], wrt=(0,)),
    "log_loss": Case(
        F.log_loss,
        lambda: [_r(376).rand(6, 1) * 0.8 + 0.1,
                 (_r(377).rand(6, 1) > 0.5).astype(np.float64)], wrt=(0,)),
    "sigmoid_focal_loss": Case(
        lambda x, lab: F.sigmoid_focal_loss(x, lab, reduction="mean"),
        lambda: [_r(378).randn(6, 1),
                 (_r(379).rand(6, 1) > 0.5).astype(np.float64)], wrt=(0,)),
    "dice_loss": Case(
        lambda x, lab: F.dice_loss(x, lab),
        lambda: [_softmax_rows(_r(380).rand(4, 3) + 0.1),
                 _r(381).randint(0, 3, (4, 1)).astype(np.int64)], wrt=(0,)),
    "npair_loss": Case(
        F.npair_loss,
        lambda: [_r(382).randn(4, 6), _r(383).randn(4, 6),
                 _r(384).randint(0, 3, (4,)).astype(np.int64)], wrt=(0, 1)),
    # --- nn functional extras ---
    "celu": Case(F.celu, lambda: [_r(390).randn(3, 4)], wrt=(0,)),
    "thresholded_relu": Case(F.thresholded_relu,
                             lambda: [_r(391).randn(3, 4)], wrt=(0,)),
    "hardtanh": Case(F.hardtanh, lambda: [_r(392).randn(3, 4) * 2],
                     wrt=(0,)),
    "log_sigmoid": Case(F.log_sigmoid, lambda: [_r(393).randn(3, 4)],
                        wrt=(0,)),
    "local_response_norm": Case(
        lambda x: F.local_response_norm(x, 3),
        lambda: [_r(394).randn(1, 4, 5, 5)], wrt=(0,)),
    "channel_shuffle": Case(
        lambda x: F.channel_shuffle(x, 2),
        lambda: [_r(395).randn(1, 4, 3, 3)], wrt=(0,)),
    "pixel_unshuffle": Case(
        lambda x: F.pixel_unshuffle(x, 2),
        lambda: [_r(396).randn(1, 2, 4, 4)], wrt=(0,)),
    "unfold": Case(lambda x: F.unfold(x, 2),
                   lambda: [_r(397).randn(1, 2, 4, 4)], wrt=(0,)),
    "fold": Case(lambda x: F.fold(x, [4, 4], 2),
                 lambda: [_r(398).randn(1, 8, 9)], wrt=(0,)),
    "grid_sample": Case(
        F.grid_sample,
        lambda: [_r(399).randn(1, 2, 4, 4),
                 (_r(400).rand(1, 3, 3, 2) * 1.6 - 0.8)], wrt=(0, 1)),
    "affine_grid": Case(
        lambda t: F.affine_grid(t, [1, 2, 4, 4]),
        lambda: [_r(401).randn(1, 2, 3) * 0.5], wrt=(0,)),
    "pairwise_distance": Case(
        F.pairwise_distance,
        lambda: [_r(402).randn(4, 6), _r(403).randn(4, 6)], wrt=(0, 1)),
})


def _softmax_rows(a):
    e = np.exp(a - a.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


# Enumerated-but-not-swept ops: every entry must say where the op IS tested.
NOT_SWEPT = {
    "shard_constraint": "sharding annotation, identity numerics "
                        "(tests/test_distributed.py exercises placement)",
    "dropout": "stochastic; eval-mode identity + mask statistics tested in "
               "tests/test_nn.py",
    "rope": "fused rotary embedding parity tested in "
            "tests/test_incubate_fused.py",
    "lstm": "composite recurrent layer; parity in tests/test_nn.py",
    "rnn_tanh": "composite recurrent layer; parity in tests/test_nn.py",
    "rnn_relu": "composite recurrent layer; parity in tests/test_nn.py",
    "lstm_cell": "composite recurrent cell; parity in tests/test_nn.py",
    "gru_cell": "composite recurrent cell; parity in tests/test_nn.py",
    "clone": "identity copy; covered by tensor-op suite",
    "getitem": "indexing dispatch; semantics covered by the tensor-op and "
               "manip suites (tests/test_tensor_ops.py)",
    "gru": "composite recurrent layer; parity in tests/test_nn.py",
    "kv_cache_upd": "dynamic_update_slice cache write; decode-vs-oracle "
                    "parity in tests/test_pallas_fused_kernels.py",
    "decode_mask": "constant mask construction for the prefill path; "
                   "decode parity tests cover it",
    "ragged_decode_attention": "Pallas decode kernel; reference parity in "
                               "tests/test_pallas_fused_kernels.py",
    "bert_pad_mask": "constant attention-mask construction; BERT forward "
                     "covered in tests/test_model_zoo.py",
}


def _ids():
    return sorted(CASES)


@pytest.mark.parametrize("dtype", DTYPES_FWD)
@pytest.mark.parametrize("name", _ids())
def test_forward_dtype(name, dtype):
    if skip_reason(name, "fwd", dtype):
        pytest.skip(skip_reason(name, "fwd", dtype))
    case = CASES[name]
    base = [np.asarray(a) for a in case.gen()]
    base = [a.astype(np.float64) if a.dtype.kind == "f" else a for a in base]
    ref = _fwd(case, base)
    got = _fwd(case, _cast(base, dtype))
    rtol, atol = fwd_tol(name, dtype)
    np.testing.assert_allclose(
        got, ref, rtol=rtol, atol=atol,
        err_msg=f"{name} forward at {dtype} vs float64")


@pytest.mark.parametrize("name", [n for n in _ids() if CASES[n].wrt])
def test_grad_fd_float64(name):
    """Autograd vs central finite differences, genuinely in float64."""
    if skip_reason(name, "grad", "float64"):
        pytest.skip(skip_reason(name, "grad", "float64"))
    case = CASES[name]
    base = [np.asarray(a) for a in case.gen()]
    base = [a.astype(np.float64) if a.dtype.kind == "f" else a for a in base]
    auto = _autograd(case, base)
    eps = 1e-5
    rtol, atol = grad_tol(name, "float64")
    for k, i in enumerate(case.wrt):
        num = np.zeros_like(base[i], np.float64)
        flat = base[i].reshape(-1)
        numf = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            up = float(_fwd(case, base).sum())
            flat[j] = orig - eps
            dn = float(_fwd(case, base).sum())
            flat[j] = orig
            numf[j] = (up - dn) / (2 * eps)
        np.testing.assert_allclose(
            auto[k], num, rtol=max(rtol, 1e-5), atol=max(atol, 1e-7),
            err_msg=f"{name}: autograd vs finite differences (input {i})")


@pytest.mark.parametrize("dtype", DTYPES_LOWP_GRAD)
@pytest.mark.parametrize("name", [n for n in _ids() if CASES[n].wrt])
def test_grad_low_precision(name, dtype):
    """Autograd at bf16/fp16 vs autograd at float64 — the TPU training-dtype
    gradient leg."""
    if skip_reason(name, "grad", dtype):
        pytest.skip(skip_reason(name, "grad", dtype))
    case = CASES[name]
    base = [np.asarray(a) for a in case.gen()]
    base = [a.astype(np.float64) if a.dtype.kind == "f" else a for a in base]
    ref = _autograd(case, base)
    got = _autograd(case, _cast(base, dtype))
    rtol, atol = grad_tol(name, dtype)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            g.astype(np.float64), r, rtol=rtol, atol=atol,
            err_msg=f"{name} grad at {dtype} vs float64")


def test_top_ops_covered():
    """Every op the model zoo executes (OP_COVERAGE.json, regenerated by
    tools/op_coverage.py) is either dtype-swept here or has a recorded
    NOT_SWEPT pointer to where it is tested."""
    path = os.path.join(REPO, "OP_COVERAGE.json")
    with open(path) as f:
        cov = json.load(f)["counts"]
    missing = [op for op in cov
               if op not in CASES and op not in NOT_SWEPT]
    assert not missing, (
        f"model-zoo ops with no dtype-sweep case and no recorded "
        f"exemption: {missing}")


def test_battery_size():
    """The battery must stay at 250-op scale (VERDICT r3 item 4 set the
    top-100 floor; r4 item 4 raised it to the long tail)."""
    assert len(CASES) >= 250, len(CASES)
