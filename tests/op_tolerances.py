"""Per-op, per-dtype tolerance governance for the dtype-swept OpTest battery
(analog of the reference's test/white_list/op_accuracy_white_list.py +
op_threshold_white_list.py: tolerance relaxations are RECORDED, not ad hoc).

Layout:
- DEFAULT_FWD / DEFAULT_GRAD: (rtol, atol) per dtype, applied unless an op
  has an override below.
- FWD_OVERRIDES / GRAD_OVERRIDES: {op_name: {dtype: (rtol, atol)}} — every
  entry must carry a comment saying WHY the default is insufficient.
- SKIPS: {(op_name, check, dtype): reason} — checks that cannot run for a
  recorded reason (unsupported dtype, non-differentiable output, ...).

The low-precision checks compare against the SAME op computed in float64
(the reference compares fp16 kernels against their fp32 siblings the same
way); the float64 forward itself is pinned by the numpy-reference suites
(test_op_suite.py) and by the finite-difference grad leg here.
"""

DEFAULT_FWD = {
    "float64": (1e-12, 1e-12),   # vs itself (sanity that x64 is really on)
    "float32": (1e-5, 1e-6),
    "bfloat16": (5e-2, 1e-2),    # 8-bit mantissa: ~0.8% per op
    "float16": (5e-3, 1e-3),     # 11-bit mantissa: ~0.05% per op
}

DEFAULT_GRAD = {
    "float64": (1e-7, 1e-9),     # autograd vs central finite differences
    "float32": (1e-4, 1e-5),     # autograd(fp32) vs autograd(fp64)
    "bfloat16": (1.5e-1, 5e-2),  # grads accumulate two bf16 roundings
    "float16": (2e-2, 5e-3),
}

FWD_OVERRIDES = {
    # exp amplifies input rounding by |x| (relative error e^dx-1 ~ dx*|x|);
    # fp16 legs follow the same argument at the 11-bit mantissa (~8x
    # tighter than bf16, looser than the elementwise default)
    "exp": {"bfloat16": (1e-1, 1e-2), "float16": (1e-2, 2e-3)},
    "expm1": {"bfloat16": (1e-1, 1e-2), "float16": (1e-2, 2e-3)},
    # reductions over n elements accumulate n roundings
    "sum": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    # fp16 legs: same reduction-accumulation argument at fp16's 11-bit
    # mantissa (~8x tighter than bf16, looser than the elementwise default)
    "logsumexp": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "matmul": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "linear": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "conv2d": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "einsum": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "norm": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "std": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "var": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    # softmax family: exp + normalization; absolute scale is <= 1 so atol
    # rules (fp16 legs: the same exp/normalization rounding, ~8x tighter)
    "softmax": {"bfloat16": (1e-1, 2e-2), "float16": (1e-2, 2e-3)},
    "log_softmax": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "cross_entropy": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    "sdpa": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    # normalizations divide by a reduced statistic
    "layer_norm": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "rms_norm": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "batch_norm": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "group_norm": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "instance_norm": {"bfloat16": (1.5e-1, 5e-2), "float16": (2e-2, 5e-3)},
    # tan near pi/2 and pow amplify relative error (fp16 ~8x tighter)
    "tan": {"bfloat16": (2e-1, 5e-2), "float16": (2e-2, 5e-3)},
    "pow": {"bfloat16": (1e-1, 2e-2), "float16": (1e-2, 2e-3)},
    # products chain per-factor roundings (fp16 ~8x tighter than bf16)
    "cumprod": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 5e-3)},
    "prod": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 5e-3)},
    "kron": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
    # addmm = beta*C + alpha*(A@B): matmul-class accumulation
    "addmm": {"bfloat16": (1e-1, 5e-2), "float16": (1e-2, 2e-3)},
}

GRAD_OVERRIDES = {
    # grad of matmul is another matmul: same accumulation as forward
    # (fp16 legs follow conv2d's bf16->fp16 scaling: ~5x tighter rtol)
    "matmul": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "linear": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "conv2d": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "einsum": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "sdpa": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "layer_norm": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "rms_norm": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "group_norm": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "instance_norm": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "batch_norm": {"bfloat16": (2.5e-1, 1e-1), "float16": (5e-2, 1e-2)},
    # softmax-family grads chain the forward's exp rounding (fp16 ~5x
    # tighter than bf16, looser than the elementwise default)
    "softmax": {"bfloat16": (2e-1, 5e-2), "float16": (5e-2, 1e-2)},
    "log_softmax": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "cross_entropy": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "logsumexp": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    # fp16 legs below follow the conv2d bf16->fp16 precedent: ~5x tighter
    # rtol at the 11-bit mantissa, same amplification argument as bf16
    "tan": {"bfloat16": (3e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "pow": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    # d/dx = 1/(2 sqrt x): blows up near 0
    "sqrt": {"bfloat16": (2e-1, 5e-2), "float16": (5e-2, 1e-2)},
    "rsqrt": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    # erf: the missing bf16 leg IS the default — recorded explicitly so the
    # entry covers every swept dtype (dtype-rule-coverage)
    "erf": {"bfloat16": (1.5e-1, 5e-2), "float16": (5e-2, 1e-2)},
    "gelu": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "silu": {"bfloat16": (2e-1, 5e-2), "float16": (5e-2, 1e-2)},
    "mish": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    # f' = tanh(x)^2: tiny near 0 (fp16 keeps a wider margin like bf16)
    "tanhshrink": {"bfloat16": (5e-1, 5e-2), "float16": (1e-1, 1e-2)},
    "cumprod": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "prod": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "std": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "var": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "norm": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
    "interpolate": {"bfloat16": (2e-1, 1e-1), "float16": (5e-2, 1e-2)},
}

# (op, check, dtype) -> reason.  check in {"fwd", "grad"}; dtype "*" = all.
SKIPS = {
    ("argmax", "grad", "*"): "integer output: not differentiable",
    ("argmin", "grad", "*"): "integer output: not differentiable",
    ("argsort", "grad", "*"): "integer output: not differentiable",
    ("one_hot", "grad", "*"): "indicator output: not differentiable",
    ("sign", "grad", "*"): "derivative is 0 a.e.; FD check is vacuous",
    ("floor", "grad", "*"): "derivative is 0 a.e.; FD check is vacuous",
    ("ceil", "grad", "*"): "derivative is 0 a.e.; FD check is vacuous",
    ("round", "grad", "*"): "derivative is 0 a.e.; FD check is vacuous",
    ("embedding", "fwd", "float16"):
        "weight gather: exact at any dtype, fp16 leg adds nothing",
    ("max_pool2d", "grad", "bfloat16"):
        "argmax ties flip under bf16 rounding: grad routes to another "
        "(valid) input element, elementwise compare is ill-posed",
    ("max", "grad", "bfloat16"):
        "argmax ties flip under bf16 rounding (same as max_pool2d)",
    ("min", "grad", "bfloat16"): "argmin ties flip under bf16 rounding",
    ("topk", "grad", "bfloat16"): "selection ties flip under bf16 rounding",
    ("max_pool2d", "grad", "float16"):
        "argmax ties flip under fp16 rounding (same as bf16)",
    ("ctc_loss", "fwd", "float16"):
        "alpha-recursion logsumexp exceeds fp16's exponent range "
        "(bf16, with fp32's exponent width, is the low-precision leg)",
    ("ctc_loss", "grad", "float16"): "same exponent-range limit as forward",
    ("max", "grad", "float16"): "argmax ties flip under fp16 rounding",
    ("min", "grad", "float16"): "argmin ties flip under fp16 rounding",
    ("topk", "grad", "float16"): "selection ties flip under fp16 rounding",
}


# --- family-level recorded skips (r5 long-tail extension) -------------------
# XLA's decomposition/fft kernels are f32/f64 (c64/c128) only; there IS no
# bf16/fp16 kernel to test (the reference's own OpTest skips these the same
# way via its no-fp16/bf16 white lists).
_LINALG_OPS = (
    "cholesky", "qr", "svd", "svd_reconstruct", "eigh", "eigvalsh",
    "eigvals", "lu", "solve", "triangular_solve", "cholesky_solve", "lstsq",
    "inv", "pinv", "det", "slogdet", "matrix_power", "matrix_rank",
    "cond_linalg", "multi_dot", "householder_product", "corrcoef", "cov",
)
_FFT_OPS = ("fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "rfft2",
            "irfft2", "hfft", "ihfft")
for _op in _LINALG_OPS:
    for _dt in ("bfloat16", "float16"):
        SKIPS.setdefault((_op, "fwd", _dt),
                         "XLA linalg decompositions are f32/f64-only")
        SKIPS.setdefault((_op, "grad", _dt),
                         "XLA linalg decompositions are f32/f64-only")
for _op in _FFT_OPS:
    for _dt in ("bfloat16", "float16"):
        SKIPS.setdefault((_op, "fwd", _dt),
                         "XLA fft kernels are complex64/128-only")
        SKIPS.setdefault((_op, "grad", _dt),
                         "XLA fft kernels are complex64/128-only")
for _dt in ("bfloat16", "float16"):
    for _chk in ("fwd", "grad"):
        SKIPS.setdefault(("grid_sample", _chk, _dt),
                         "low-precision sample coordinates round to "
                         "different source pixels: outputs are valid but "
                         "not comparable elementwise")
# selection/tie semantics under low-precision rounding (same rationale as
# the existing max/min/topk entries)
for _op in ("amax", "amin", "fmax", "fmin", "median", "kthvalue", "cummax",
            "cummin", "quantile"):
    for _dt in ("bfloat16", "float16"):
        SKIPS.setdefault((_op, "grad", _dt),
                         "selection ties flip under low-precision rounding")


def fwd_tol(op, dtype):
    return FWD_OVERRIDES.get(op, {}).get(dtype, DEFAULT_FWD[dtype])


def grad_tol(op, dtype):
    return GRAD_OVERRIDES.get(op, {}).get(dtype, DEFAULT_GRAD[dtype])


def skip_reason(op, check, dtype):
    return (SKIPS.get((op, check, dtype))
            or SKIPS.get((op, check, "*")))
