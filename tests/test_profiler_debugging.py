"""Profiler + numeric-debugging tests (SURVEY.md §5: tracing/profiling and
nan/inf scanning parity)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu import profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 export_chrome_tracing, make_scheduler)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=2, skip_first=1)
    states = [sched(i) for i in range(10)]
    S = ProfilerState
    assert states == [S.CLOSED,               # skip_first
                      S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,
                      S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,
                      S.CLOSED]               # repeat exhausted


def test_profiler_records_ops_and_spans(tmp_path):
    model = nn.Linear(8, 8)
    x = P.randn([4, 8])
    prof = Profiler()
    prof.start()
    with RecordEvent("my_span"):
        y = model(x)
        y.sum().backward()
    for _ in range(3):
        prof.step()
    prof.stop()
    events = prof.events()
    names = {e["name"] for e in events}
    assert "my_span" in names
    assert any(n for n in names if n != "my_span"), names  # op events recorded
    # export + summary
    out = tmp_path / "trace.json"
    prof.export(str(out))
    data = json.load(open(out))
    assert data["traceEvents"]
    s = prof.summary()
    assert "Calls" in s and "my_span" in s


def test_profiler_scheduler_windows(tmp_path):
    collected = []
    prof = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1),
                    on_trace_ready=lambda p: collected.append(len(p.events())))
    prof.start()  # step 0: CLOSED
    x = P.randn([4, 4])
    for i in range(4):
        (x * 2.0).sum()
        prof.step()
    prof.stop()
    assert collected, "on_trace_ready never fired"


def test_export_chrome_tracing_handler(tmp_path):
    prof = Profiler(on_trace_ready=export_chrome_tracing(str(tmp_path)))
    prof.start()
    (P.randn([4, 4]) + 1.0).sum()
    prof.stop()
    files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert files


def test_benchmark_timer():
    from paddle_tpu.profiler.timer import Benchmark
    b = Benchmark()
    b.begin()
    import time
    for _ in range(3):
        time.sleep(0.01)
        b.step(num_samples=32)
    b.end()
    assert b.step_cost.count == 2  # first step() only sets t0
    assert b.ips() > 0
    assert "ips" in b.step_info()


def test_nan_inf_checker():
    from paddle_tpu.amp import debugging
    x = P.to_tensor(np.array([1.0, 0.0], np.float32))
    debugging.enable_tensor_checker()
    try:
        with pytest.raises(FloatingPointError):
            _ = x / P.to_tensor(np.array([0.0, 0.0], np.float32))
    finally:
        debugging.disable_tensor_checker()
    # disabled again: no raise
    _ = x / P.to_tensor(np.array([0.0, 0.0], np.float32))


def test_check_numerics():
    from paddle_tpu.amp import debugging
    t = P.to_tensor(np.array([1.0, np.nan, np.inf, 0.0], np.float32))
    with pytest.raises(FloatingPointError):
        debugging.check_numerics(t, "op", "t")
    n_nan, n_inf, n_zero = debugging.check_numerics(
        t, "op", "t", debug_mode=debugging.DebugMode.CHECK_NAN_INF)
    assert int(n_nan) == 1 and int(n_inf) == 1 and int(n_zero) == 1


def test_collect_operator_stats():
    from paddle_tpu.amp import debugging
    x = P.randn([4, 4])
    with debugging.collect_operator_stats() as st:
        _ = x + x
        _ = x * x
        _ = x * x
    assert sum(st.stats.values()) >= 3
