"""Model-family tests: BERT/ERNIE (GLUE path), GPT, DeepFM, OCR det+rec —
the BASELINE workload configs beyond LLaMA/ResNet."""
import numpy as np

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.models import (CRNN, BertConfig, BertForPretraining,
                               BertForSequenceClassification, DBNet, DeepFM,
                               GPTConfig, GPTForCausalLM, bert_pretraining_loss,
                               ctc_rec_loss, db_loss)


def _ids(rng, b, s, vocab):
    return P.to_tensor(rng.randint(0, vocab, (b, s)))


def test_bert_sequence_classification_trains():
    P.seed(0)
    rng = np.random.RandomState(0)
    cfg = BertConfig.tiny()
    model = BertForSequenceClassification(cfg, num_classes=2)
    opt = P.optimizer.AdamW(learning_rate=5e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    ids = _ids(rng, 8, 16, cfg.vocab_size)
    # learnable rule: label = parity of first token
    labels = P.to_tensor((rng.randint(0, 2, (8,))).astype(np.int64))
    first = last = None
    for _ in range(30):
        logits = model(ids)
        loss = loss_fn(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = first if first is not None else v
        last = v
    assert last < first * 0.5, (first, last)


def test_bert_pretraining_heads():
    P.seed(0)
    rng = np.random.RandomState(0)
    cfg = BertConfig.tiny()
    model = BertForPretraining(cfg)
    ids = _ids(rng, 4, 12, cfg.vocab_size)
    mlm_logits, nsp_logits = model(ids)
    assert mlm_logits.shape == [4, 12, cfg.vocab_size]
    assert nsp_logits.shape == [4, 2]
    masked = np.full((4, 12), -100, np.int64)
    masked[:, 3] = rng.randint(0, cfg.vocab_size, 4)
    loss = bert_pretraining_loss(mlm_logits, nsp_logits,
                                 P.to_tensor(masked),
                                 P.to_tensor(rng.randint(0, 2, (4,))))
    loss.backward()
    assert model.bert.embeddings.word_embeddings.weight.grad is not None
    assert np.isfinite(float(loss.numpy()))


def test_gpt_lm_trains_and_generates():
    P.seed(0)
    rng = np.random.RandomState(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=5e-3, parameters=model.parameters())
    # repeatable sequence task
    ids = P.to_tensor(np.tile(np.arange(16) % 8, (4, 1)))
    first = last = None
    for _ in range(40):
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = first if first is not None else v
        last = v
    assert last < first * 0.5, (first, last)
    model.eval()
    out = model.generate(P.to_tensor(np.arange(8)[None, :] % 8),
                         max_new_tokens=4)
    assert out.shape == [1, 12]
    # after training on the cyclic pattern, continuation should follow it
    sampled = model.generate(P.to_tensor(np.arange(8)[None, :] % 8),
                             max_new_tokens=4, temperature=1.0, top_k=2)
    assert sampled.shape == [1, 12]


def test_deepfm_trains_on_synthetic_ctr():
    P.seed(0)
    rng = np.random.RandomState(0)
    model = DeepFM(sparse_feature_number=100, sparse_feature_dim=8,
                   dense_feature_dim=4, sparse_field_num=6,
                   layer_sizes=(32, 16))
    opt = P.optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
    B = 64
    sparse = rng.randint(0, 100, (B, 6))
    dense = rng.randn(B, 4).astype(np.float32)
    y = ((sparse[:, 0] % 2) ^ (dense[:, 0] > 0)).astype(np.float32)[:, None]
    sp_t, de_t, y_t = P.to_tensor(sparse), P.to_tensor(dense), P.to_tensor(y)
    first = last = None
    for _ in range(60):
        logits = model(sp_t, de_t)
        loss = nn.functional.binary_cross_entropy_with_logits(logits, y_t)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = first if first is not None else v
        last = v
    assert last < first * 0.7, (first, last)
    probs = model.predict(sp_t, de_t)
    assert probs.shape == [B, 1]
    assert 0.0 <= float(probs.numpy().min()) and float(probs.numpy().max()) <= 1.0


def test_dbnet_det_forward_and_loss():
    P.seed(0)
    model = DBNet(in_channels=3, base=8)
    x = P.randn([2, 3, 64, 64])
    out = model(x)
    assert out["maps"].shape == out["binary"].shape
    assert out["maps"].shape[0] == 2 and out["maps"].shape[1] == 1
    gt = P.to_tensor(np.random.RandomState(0).rand(
        *out["maps"].shape).astype(np.float32))
    loss = db_loss(out, gt, gt)
    loss.backward()
    assert np.isfinite(float(loss.numpy()))


def test_crnn_rec_ctc_trains():
    P.seed(0)
    rng = np.random.RandomState(0)
    model = CRNN(in_channels=1, num_classes=12, hidden=32, base=8)
    opt = P.optimizer.AdamW(learning_rate=5e-3, parameters=model.parameters())
    x = P.to_tensor(rng.randn(2, 1, 32, 64).astype(np.float32))
    labels = P.to_tensor(rng.randint(1, 12, (2, 4)))
    label_lens = P.to_tensor(np.array([4, 3], np.int32))
    first = last = None
    for _ in range(15):
        logits = model(x)  # [B, 16, 12]
        assert logits.shape[1] == 16
        loss = ctc_rec_loss(logits, labels, label_lens)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(loss.numpy())
        first = first if first is not None else v
        last = v
    assert last < first, (first, last)


def test_gpt_state_dict_keys_canonical():
    """Regression: tied output head must not shadow the embedding weight."""
    from paddle_tpu.models import GPTModel
    cfg = GPTConfig.tiny()
    lm = GPTForCausalLM(cfg)
    keys = set(lm.state_dict().keys())
    assert "gpt.word_embeddings.weight" in keys
    assert "_tied" not in keys
    # checkpoint interchanges with the bare GPTModel
    base = GPTModel(cfg)
    base_keys = {"gpt." + k for k in base.state_dict().keys()}
    assert base_keys <= keys


def test_dbnet_non_multiple_of_32_input():
    """Regression: FPN upsample must handle sizes where strides don't divide."""
    model = DBNet(in_channels=3, base=8)
    out = model(P.randn([1, 3, 72, 72]))
    assert out["maps"].shape[0] == 1


def test_yolov3_trains_and_predicts():
    """Detection family (PaddleDetection yolov3 slot): the fused
    yolo_loss must decrease under training on a fixed synthetic batch, and
    predict() must run decode+NMS end to end."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.models import YOLOv3

    P.seed(0)
    rng = np.random.RandomState(0)
    model = YOLOv3(num_classes=4, width=4)
    opt = P.optimizer.Adam(learning_rate=2e-3,
                           parameters=model.parameters())
    x = P.to_tensor(rng.rand(2, 3, 64, 64).astype("f"))
    gt_box = P.to_tensor(rng.rand(2, 3, 4).astype("f") * 0.4 + 0.3)
    gt_label = P.to_tensor(rng.randint(0, 4, (2, 3)))
    losses = []
    for _ in range(8):
        loss = model.loss(model(x), gt_box, gt_label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses
    model.eval()
    dets = model.predict(x, P.to_tensor(np.array([[64, 64], [64, 64]])),
                         conf_thresh=0.0, top_k=5)
    assert len(dets) == 2
    for per_img in dets:
        for cls_id, score, x1, y1, x2, y2 in per_img[:3]:
            assert 0 <= cls_id < 4 and np.isfinite(score)
