"""Jaxpr-tier known-answer fixture steps (never collected by pytest).

One deliberately hazardous step per jaxpr rule, traced through the SAME
capture machinery the canonical steps use (tools/staticcheck/jaxpr/steps
``trace_step``), so the known-answer tests prove the whole pipeline —
capture -> pass pipeline -> lint rules -> Finding mapping -> ratchet.

``collect(root)`` is the PT_STATICCHECK_STEPS hook: pointing the CLI at
this file swaps the canonical steps for these, which is how the tests
demonstrate that `python -m tools.staticcheck --ci` exits nonzero on a
NEW jaxpr-tier finding.

`quantized_writeback_step` is the PR-10 regression net: the MULTICHIP
write_back-before-rebuild donation bug donated an fp32 buffer whose
value was rebuilt at a different dtype/shape, so nothing aliased the
donation and the later host write_back read a deleted array — at the
jaxpr level that is a donated input matching no output, exactly what
``jaxpr-donation-miss`` reports.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np


def _arr(shape, dtype=np.float32, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(dtype))


# ---- host-callback ---------------------------------------------------------

def callback_step(x):
    jax.debug.print("step sum={s}", s=jnp.sum(x))
    return x + 1.0


def pragma_callback_step(x):  # staticcheck: ok[jaxpr-host-callback] — fixture: deliberate allowlisted site
    jax.debug.print("allowlisted sum={s}", s=jnp.sum(x))
    return x + 1.0


# ---- dead-compute (inside a scan body; DVE now sweeps sub-jaxprs, so the
# fixture captures with the dve pass TRIMMED — the rule's job is exactly
# what remains when the pipeline didn't/couldn't clean it) -------------------

def dead_in_scan_step(x):
    def body(c, t):
        junk = jnp.exp(t) * jnp.sin(t)  # noqa: F841 — dead by design
        return c + t, t
    total, _ys = jax.lax.scan(body, jnp.zeros((), x.dtype), x)
    return total


# ---- recompile-hazard ------------------------------------------------------

def weak_scalar_step(x, s):
    # `s` arrives as a weak-typed scalar (a python float leaked in)
    return x * s


def _static_n_step(x, n):
    return x + float(n)


_churn_counter = [0]


def churn_args():
    # a varying python-int static: every call is a fresh signature
    _churn_counter[0] += 1
    return (_arr((8, 8)), _churn_counter[0])


# ---- unscheduled-collective ------------------------------------------------

def naked_collective_step(x):
    # psum under pmap; traced with the comm pass EXCLUDED from the
    # pipeline, so the collective has no CommOp tag
    return jax.pmap(lambda v: jax.lax.psum(v, "i"),
                    axis_name="i")(x[None])[0]


def fp32_beside_quantized_step(x):
    # the EQuARX replace-not-shadow violation: an int8 wire leg and a
    # float32 psum on the SAME axis — the fp32 collective the quantized
    # path was supposed to retire still runs beside it
    def body(v):
        wire = jax.lax.psum((v * 127.0).astype(jnp.int8), "i")
        full = jax.lax.psum(v, "i")
        return full + wire.astype(jnp.float32) / 127.0
    return jax.pmap(body, axis_name="i")(x[None])[0]


# ---- donation-miss ---------------------------------------------------------

def quantized_writeback_step(w):
    # donated fp32 param rebuilt as int8 blocks: no output matches the
    # donated aval (the PR-10 write_back-before-rebuild shape)
    scale = jnp.max(jnp.abs(w)) / 127.0
    return (w / scale).astype(jnp.int8), scale


def partial_donation_step(a, b):
    # donate=(0,) only: `b` is equally donatable (an unclaimed matching
    # output exists) — the step silently holds two copies of it
    return a * 2.0, b * 2.0


# ---- control ---------------------------------------------------------------

def clean_step(x):
    return jnp.tanh(x) * 2.0


def collect(root):
    """PT_STATICCHECK_STEPS entry point -> list[StepResult]."""
    from tools.staticcheck.jaxpr.steps import trace_step

    t = functools.partial(trace_step, root=root)
    mk = lambda *shapes: (lambda: tuple(_arr(s) for s in shapes))  # noqa: E731
    return [
        t("fixture/callback", callback_step, mk((8, 8))),
        t("fixture/pragma_callback", pragma_callback_step, mk((8, 8))),
        t("fixture/dead_in_scan", dead_in_scan_step, mk((16,)),
          passes=("fusion", "cse", "comm")),
        t("fixture/weak_scalar", weak_scalar_step,
          lambda: (_arr((8, 8)), jnp.asarray(3.0))),
        t("fixture/signature_churn", _static_n_step, churn_args),
        t("fixture/naked_collective", naked_collective_step, mk((4, 4)),
          passes=("fusion", "cse", "dve")),
        t("fixture/fp32_beside_quantized", fp32_beside_quantized_step,
          mk((4, 4))),
        t("fixture/quantized_writeback", quantized_writeback_step,
          mk((64, 64)), donate=(0,)),
        t("fixture/partial_donation", partial_donation_step,
          mk((32, 32), (32, 32)), donate=(0,)),
        t("fixture/clean", clean_step, mk((8, 8))),
    ]
