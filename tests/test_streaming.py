"""Streaming sharded ingestion (io/streaming.py).

Three layers:

1. determinism + resumability known answers — the per-epoch order is a
   pure function of (seed, epoch, rank), the cursor is exact-resume
   state, and a mid-epoch restore replays nothing and loses nothing;
2. liveness — a SIGKILLed fetch worker surfaces as the typed
   DataLoaderWorkerError, a stalled fetch as DataLoaderTimeout, and
   recover() continues exactly-once from the cursor (the io.stream_fetch
   site also rides the tests/test_no_hang.py matrix);
3. durability chaos — a writer child is SIGKILLed at every
   cursor-checkpoint crash site (the new stream.cursor_* sites plus the
   checkpoint manager's own commit-path sites); restoring from the
   surviving committed generation resumes with ZERO duplicate and ZERO
   lost samples relative to that generation's cursor.
"""
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed import chaos
from paddle_tpu.distributed.ckpt_manager import CheckpointManager
from paddle_tpu.io import (ShardedSampleStream, StreamLoader,
                           restore_stream_checkpoint, save_stream_checkpoint)
from paddle_tpu.io.dataloader import DataLoaderWorkerError
from paddle_tpu.utils.deadline import DataLoaderTimeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WRITER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist_workers", "stream_chaos_writer.py")


def _shards(n_shards=4, per=5):
    return [[np.asarray([10.0 * s + i], np.float32) for i in range(per)]
            for s in range(n_shards)]


def _values(batches):
    out = []
    for b in batches:
        arr = b._value if hasattr(b, "_value") else b
        out.extend(np.asarray(arr)[:, 0].tolist())
    return out


def _epoch_values(stream, epoch):
    return [float(stream.sample_at(i, epoch=epoch)[0])
            for i in range(stream.epoch_len(epoch))]


@pytest.fixture
def arm(monkeypatch):
    def _arm(site, mode, hits="1", skip="0"):
        monkeypatch.setenv("PT_FAULTPOINT", site)
        monkeypatch.setenv("PT_FAULTPOINT_MODE", mode)
        monkeypatch.setenv("PT_FAULTPOINT_HITS", hits)
        monkeypatch.setenv("PT_FAULTPOINT_SKIP", skip)
        chaos.reset_hits()
    yield _arm
    chaos.reset_hits()


# ---------------- determinism + resumability ----------------

def test_deterministic_sharded_order():
    a = ShardedSampleStream(_shards(), seed=1)
    b = ShardedSampleStream(_shards(), seed=1)
    assert _epoch_values(a, 0) == _epoch_values(b, 0)
    # epochs reshuffle (seed-derived), same multiset
    e0, e1 = _epoch_values(a, 0), _epoch_values(a, 1)
    assert e0 != e1 and sorted(e0) == sorted(e1)
    # a different seed is a different order
    c = ShardedSampleStream(_shards(), seed=2)
    assert _epoch_values(c, 0) != e0


def test_rank_striping_partitions_the_shard_set():
    world = [ShardedSampleStream(_shards(5, 3), world_size=2, rank=r, seed=4)
             for r in range(2)]
    vals = [set(_epoch_values(s, 0)) for s in world]
    assert vals[0].isdisjoint(vals[1])
    assert len(vals[0] | vals[1]) == 15
    with pytest.raises(ValueError, match="rank"):
        ShardedSampleStream(_shards(), world_size=2, rank=2)


def test_loader_delivers_epoch_exactly_once_and_rolls():
    st = ShardedSampleStream(_shards(), seed=3)
    got = _values(StreamLoader(st, batch_size=4))
    assert got == _epoch_values(st, 0)
    assert st.exhausted() and st.pos == 20
    # next iteration rolls the epoch
    got1 = _values(StreamLoader(st, batch_size=4))
    assert st.epoch == 1 and got1 == _epoch_values(st, 1)


def test_partial_final_batch_counts_exactly():
    st = ShardedSampleStream(_shards(3, 3), seed=0)  # 9 samples
    batches = list(StreamLoader(st, batch_size=4, to_tensors=False))
    assert [len(b) for b in batches] == [4, 4, 1]
    assert st.pos == 9


def test_mid_epoch_cursor_resume_no_dup_no_loss():
    st = ShardedSampleStream(_shards(), seed=3)
    st.roll_epoch()   # epoch 1: a shuffled mid-stream case
    it = iter(StreamLoader(st, batch_size=4))
    pre = _values([next(it), next(it)])
    cursor = st.state_dict()
    it.close()        # the consumer dies mid-epoch

    fresh = ShardedSampleStream(_shards(), seed=3)
    fresh.load_state_dict(cursor)
    post = _values(StreamLoader(fresh, batch_size=4))
    assert pre + post == _epoch_values(fresh, 1)


def test_cursor_refuses_incompatible_stream():
    st = ShardedSampleStream(_shards(), seed=3)
    cur = st.state_dict()
    other = ShardedSampleStream(_shards(), seed=4)
    with pytest.raises(ValueError, match="seed"):
        other.load_state_dict(cur)
    # a cursor written by another RANK repositions inside the wrong
    # stripe — silent duplicate/lost coverage, so it must refuse typed
    peer = ShardedSampleStream(_shards(), world_size=2, rank=1, seed=3)
    with pytest.raises(ValueError, match="rank"):
        peer.load_state_dict(
            ShardedSampleStream(_shards(), world_size=2, rank=0,
                                seed=3).state_dict())
    with pytest.raises(ValueError, match="not a stream cursor"):
        st.load_state_dict({"pos": 3})


def test_tuple_samples_advance_cursor_by_batch_size():
    """Supervised (x, y) pairs collate into a 2-tuple of stacked arrays;
    the cursor must advance by the delivered SAMPLE count, not the
    container arity (the exactly-once accounting regression)."""
    shards = [[(np.asarray([10.0 * s + i], np.float32),
                np.asarray([float(i % 2)], np.float32))
               for i in range(5)] for s in range(2)]
    st = ShardedSampleStream(shards, seed=1)
    xs = []
    for bx, _by in StreamLoader(st, batch_size=4, to_tensors=False):
        xs.extend(np.asarray(bx)[:, 0].tolist())
    assert st.pos == 10 and st.exhausted()
    assert xs == [float(st.sample_at(i, epoch=0)[0][0]) for i in range(10)]


def test_custom_collate_fn_cursor_stays_exact():
    """A collate_fn may reshape the batch arbitrarily (here: identity,
    whose 'leading dim' is the SAMPLE's own shape) — the cursor must
    advance by the worker's true packed count regardless."""
    st = ShardedSampleStream(_shards(3, 4), seed=2)  # 12 samples
    n = 0
    for batch in StreamLoader(st, batch_size=4, collate_fn=lambda b: b,
                              to_tensors=False):
        n += len(batch)
    assert n == 12 and st.pos == 12 and st.exhausted()


def test_cursor_refuses_changed_shard_set():
    """Object-storage drift: a shard landing (or growing) between save
    and restore re-permutes the epoch — the cursor must refuse typed."""
    st = ShardedSampleStream(_shards(4), seed=3)
    cur = st.state_dict()
    grown = ShardedSampleStream(_shards(5), seed=3)
    with pytest.raises(ValueError, match="shard_lens"):
        grown.load_state_dict(cur)
    fatter = ShardedSampleStream(_shards(4, per=6), seed=3)
    with pytest.raises(ValueError, match="shard_lens"):
        fatter.load_state_dict(cur)


# ---------------- liveness (the PR 4 law) ----------------

def test_worker_sigkill_typed_then_recover_exactly_once(arm):
    st = ShardedSampleStream(_shards(), seed=3)
    loader = StreamLoader(st, batch_size=4, timeout=5.0)
    arm("io.stream_fetch", "crash", skip="2")
    seen = []
    with pytest.raises(DataLoaderWorkerError) as ei:
        for b in loader:
            seen.extend(_values([b]))
    assert ei.value.exitcode == -signal.SIGKILL
    # the kill races the queue's feeder thread: 0..2 of the clean batches
    # may be lost in the pipe — they were never DELIVERED, so the cursor
    # never moved for them and recovery re-fetches them (the law below)
    assert len(seen) <= 8 and len(seen) % 4 == 0
    chaos.reset_hits()
    os_env_clear = ("PT_FAULTPOINT", "PT_FAULTPOINT_MODE",
                    "PT_FAULTPOINT_HITS", "PT_FAULTPOINT_SKIP")
    for k in os_env_clear:
        os.environ.pop(k, None)
    loader.recover()
    for b in loader:
        seen.extend(_values([b]))
    assert seen == _epoch_values(st, 0)   # zero duplicate, zero lost


def test_stalled_fetch_raises_typed_timeout(arm):
    st = ShardedSampleStream(_shards(), seed=3)
    arm("io.stream_fetch", "delay:30", hits="inf")
    with pytest.raises(DataLoaderTimeout):
        list(StreamLoader(st, batch_size=4, timeout=0.7))


def test_poisoned_shard_raises_typed_runtime_error(arm):
    st = ShardedSampleStream(_shards(), seed=3)
    arm("io.stream_fetch", "error")
    with pytest.raises(RuntimeError, match="stream fetch worker failed"):
        list(StreamLoader(st, batch_size=4, timeout=5.0))


# ---------------- cursor durability on CheckpointManager ----------------

def test_cursor_rides_checkpoint_generations(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"))
    st = ShardedSampleStream(_shards(), seed=3)
    it = iter(StreamLoader(st, batch_size=4))
    consumed = _values([next(it), next(it)])
    state = {"w": np.ones((2, 2), np.float32)}
    save_stream_checkpoint(mgr, state, 1, st, user_data={"note": "mid"})
    it.close()

    fresh_state = {"w": np.zeros((2, 2), np.float32)}
    fresh = ShardedSampleStream(_shards(), seed=3)
    step = restore_stream_checkpoint(mgr, fresh_state, fresh)
    assert step == 1
    np.testing.assert_array_equal(fresh_state["w"], state["w"])
    assert fresh.state_dict() == st.state_dict()
    rest = _values(StreamLoader(fresh, batch_size=4))
    assert consumed + rest == _epoch_values(fresh, 0)
    # a generation without a cursor is a typed refusal, not a guess
    mgr.save({"w": state["w"]}, 2)
    with pytest.raises(KeyError, match="stream_cursor"):
        restore_stream_checkpoint(mgr, dict(fresh_state),
                                  ShardedSampleStream(_shards(), seed=3))


# ---------------- the kill matrix at the cursor-checkpoint sites ----------------

# expected surviving generation per kill site: the COMMIT rename inside
# save_stream_checkpoint's manager.save is the durability point, exactly
# as in the ckpt chaos matrix — the stream.cursor_* brackets land before
# (staged) and after (committed) the whole protocol
EXPECTED_SURVIVOR = {
    "stream.cursor_staged": 1,
    "stream.cursor_committed": 2,
    "ckpt.manifest_written": 1,
    "ckpt.commit_written": 2,
}


def test_matrix_covers_every_stream_crash_site():
    assert set(chaos.registered_sites("stream.")) <= set(EXPECTED_SURVIVOR)


# ---------------- writer-kill ACROSS a dp-shrink (supervisor swap) ----------------

# the supervisor's gather-commit runs save_stream_checkpoint, so the
# stream.cursor_* crash sites fire inside a LIVE supervised fleet; killing
# the committer there forces a dp2 -> dp1 shrink whose rollback generation
# depends on where the kill landed (staged: the generation never committed)
SHRINK_SURVIVOR = {
    # committer dies on its 3rd save (init gen 0, gen 1, gen 2):
    "stream.cursor_staged": 1,     # gen 2 never committed -> roll to 1
    "stream.cursor_committed": 2,  # gen 2 committed before the kill
}


@pytest.mark.parametrize("site", sorted(SHRINK_SURVIVOR))
def test_writer_kill_across_dp_shrink_exactly_once(tmp_path, site):
    """SIGKILL the COMMITTER inside save_stream_checkpoint during a real
    dp2 supervised run: the survivor detects the lapse, rolls the fleet
    onto the surviving committed generation (params AND cursor from the
    same commit point) and finishes on dp1 — the full run replayed from
    the recorded event boundaries matches the survivor bitwise, i.e. the
    global sample prefix was delivered exactly once across the shrink."""
    import threading

    from paddle_tpu.distributed import supervisor as sv
    from paddle_tpu.distributed.launch.elastic import ElasticManager
    from paddle_tpu.distributed.store import create_master_store

    member_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "dist_workers")
    sys.path.insert(0, member_dir)
    try:
        from supervisor_member import (BATCH as SBATCH, PARAMS,
                                       build_stream as sup_stream,
                                       shard_state, step_fn)
        import tests.test_supervisor as ts
    finally:
        sys.path.pop(0)

    sv.reset_events()
    chaos.reset_hits()
    n_steps = 5
    store = create_master_store()
    proc = None
    el = sup = None
    try:
        # child 'a' is the LOWEST id -> the committer -> the writer we kill
        env = dict(os.environ,
                   PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""),
                   JAX_PLATFORMS="cpu", PT_TEST_BUDGET="20.0",
                   PT_CRASHPOINT=site, PT_CRASHPOINT_HITS="3")
        for k in ("PT_FAULTPOINT", "PT_FAULTPOINT_MODE"):
            env.pop(k, None)
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(member_dir, "supervisor_member.py"),
             str(store.port), "a", str(tmp_path), str(n_steps), "2"],
            cwd=str(tmp_path), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

        el = ElasticManager(store, node_id="b", np_range=(1, 2),
                            heartbeat_interval=0.1, timeout=0.6)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep_last_k=16)
        sup = sv.Supervisor(store=store, elastic=el, ckpt=mgr,
                            params=PARAMS, state={}, stream=sup_stream(),
                            batch_size=SBATCH, ckpt_every=1, budget=20.0,
                            watch_budget=20.0, churn_probe=1.0)
        outcome = {}

        def run():
            try:
                members = sup.bind(2, timeout=30.0)
                sup.state = shard_state(members, "b")
                outcome["state"] = sup.run(step_fn, n_steps)
            except BaseException as e:  # noqa: BLE001
                outcome["error"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(120.0)
        assert not t.is_alive(), f"{site}: survivor hung"
        assert "error" not in outcome, (site, outcome.get("error"))

        out, err = proc.communicate(timeout=60)
        assert proc.returncode == -signal.SIGKILL, (
            f"{site}: committer should die by SIGKILL, got "
            f"rc={proc.returncode}\n{out}\n{err[-2000:]}")

        want_gen = SHRINK_SURVIVOR[site]
        evs = [e for e in sup.events]
        assert evs, "no scale event recorded"
        assert evs[0]["generation"] == want_gen, (site, evs[0])
        assert evs[0]["how"] == "full-restore"
        # the rollback cursor sits exactly at the committed generation's
        # global-sample boundary: gen N == N dp2 steps == N * 2 ranks *
        # BATCH samples
        assert evs[0]["cursor_pos"] == want_gen * 2 * SBATCH, evs[0]
        # one bitwise equality proves exactly-once + zero committed loss
        full, members = ts._replay(evs, n_steps, ["a", "b"], mgr=mgr)
        assert members == ["b"]
        want = ts._owner_shards(full, members, "b")
        for k in want:
            assert np.array_equal(outcome["state"][k], want[k]), (site, k)
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        if sup is not None:
            sup.close()
        if el is not None:
            el.stop()
        store.stop()


def test_writer_kill_matrix_resumes_no_dup_no_loss(tmp_path):
    """SIGKILL the writer at each cursor-checkpoint site; restore from
    the surviving committed generation and finish the epoch: committed
    prefix + resumed remainder must equal the deterministic epoch order
    exactly — zero duplicates, zero losses."""
    env_base = dict(os.environ,
                    PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""),
                    JAX_PLATFORMS="cpu", PT_CRASHPOINT_HITS="2")
    for k in ("PT_FAULTPOINT", "PT_FAULTPOINT_MODE"):
        env_base.pop(k, None)
    children = {}
    for site in sorted(EXPECTED_SURVIVOR):
        out_dir = tmp_path / site.replace(".", "_")
        out_dir.mkdir()
        env = dict(env_base, PT_CRASHPOINT=site)
        children[site] = (out_dir, subprocess.Popen(
            [sys.executable, WRITER, str(out_dir)], cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True))

    from tests.dist_workers.stream_chaos_writer import BATCH, build_stream
    for site, (out_dir, proc) in children.items():
        _, err = proc.communicate(timeout=240)
        assert proc.returncode == -signal.SIGKILL, (
            f"{site}: writer should die by SIGKILL at the armed site, got "
            f"rc={proc.returncode}\n{err[-2000:]}")
        assert not (out_dir / "survived").exists(), site

        want_gen = EXPECTED_SURVIVOR[site]
        mgr = CheckpointManager(str(out_dir / "ckpt"))
        assert mgr.latest() == want_gen, (
            f"{site}: latest() -> {mgr.latest()}, want {want_gen}")

        stream = build_stream()
        state = {"w": np.zeros((4, 4), np.float32)}
        got = restore_stream_checkpoint(mgr, state, stream)
        assert got == want_gen
        np.testing.assert_array_equal(
            state["w"], np.full((4, 4), float(want_gen), np.float32),
            err_msg=f"{site}: torn state restored")
        # the committed cursor sits exactly at the generation's batch edge
        assert stream.pos == want_gen * 2 * BATCH, (site, stream.pos)
        resumed = _values(StreamLoader(stream, batch_size=BATCH))
        full = _epoch_values(stream, 0)
        committed_prefix = full[:want_gen * 2 * BATCH]
        assert committed_prefix + resumed == full, (
            f"{site}: duplicate or lost samples on resume")
