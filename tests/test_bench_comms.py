"""Guard for the quantized-collectives bench (bench_comms.py).

The wire-reduction number is deterministic accounting (program wire
format, not timing), so the >=3.5x acceptance floor is asserted even in
the tier-1 smoke run; the loss-parity tolerance is asserted at the full
step count only under slow (more steps = the real accumulation regime).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(steps: int):
    env = dict(os.environ, PT_COMM_BENCH_STEPS=str(steps))
    env.pop("XLA_FLAGS", None)  # the bench pins its own 2-device cpu
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench_comms.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout  # exactly ONE JSON line on stdout
    return json.loads(lines[0]), r.stderr


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_comms_smoke_json_contract():
    payload, stderr = _run_bench(steps=6)
    assert payload["metric"] == "comm_wire_reduction_int8"
    assert payload["unit"] == "x"
    # deterministic accounting: the floor holds at any step count
    assert payload["value"] >= 3.5, payload
    assert payload["vs_baseline"] >= 1.0, payload
    # the off path is bitwise repeatable (the comms hook adds nothing)
    assert payload["bitwise_off"] is True, payload
    assert payload["grad_sync_bytes_wire"] > 0
    assert payload["grad_sync_bytes_logical"] > \
        payload["grad_sync_bytes_wire"]
    # the summary table made it to stderr next to the artifact pointer
    assert "trainer.grad_sync" in stderr
    assert "artifact ->" in stderr
    art = stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        detail = json.load(f)["detail"]
    assert "trainer.grad_sync/all_reduce/dp" in detail["sites"]
    assert len(detail["loss_curve_off"]) == 6
    # the captured step's comm pass saw the quantized wire legs
    assert detail["pass_report"] is None or \
        detail["pass_report"]["comm_tagged"] >= 2
    os.unlink(art)  # tiny-step artifacts are not trajectory evidence


@pytest.mark.slow
def test_bench_comms_meets_acceptance_floor():
    payload, _ = _run_bench(steps=30)
    assert payload["value"] >= 3.5, payload
    assert payload["loss_parity"] is True, payload
    assert payload["bitwise_off"] is True, payload
