"""Elastic membership (VERDICT r1 missing #7): heartbeat leases over the
TCPStore, scale up/down detection, deterministic re-ranking, and the
controller's roster-based restart decisions."""
import time

import pytest

from paddle_tpu.distributed.launch.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.store import create_master_store


@pytest.fixture()
def store():
    s = create_master_store(port=0, world_size=1)
    yield s
    s.stop()


def _mk(store, nid, np_range=(1, 4), timeout=1.0):
    return ElasticManager(store, node_id=nid, np_range=np_range,
                          heartbeat_interval=0.1, timeout=timeout)


def test_membership_and_rerank(store):
    a = _mk(store, "nodeA")
    b = _mk(store, "nodeB")
    try:
        assert a.wait_for_np(2, timeout=5)
        assert a.alive_members() == ["nodeA", "nodeB"]
        assert a.rank_of() == 0
        assert b.rank_of() == 1
    finally:
        a.stop()
        b.stop()


def test_scale_down_detected_on_stale_heartbeat(store):
    a = _mk(store, "nodeA", timeout=0.6)
    b = _mk(store, "nodeB", timeout=0.6)
    try:
        assert a.wait_for_np(2, timeout=5)
        a.commit_roster()
        assert a.watch_once() == ElasticStatus.COMPLETED
        # node B dies (heartbeat stops advancing)
        b.stop()
        time.sleep(1.2)
        assert a.watch_once() == ElasticStatus.RESTART
        roster = a.commit_roster()
        assert roster == ["nodeA"]
        assert a.rank_of(roster) == 0
    finally:
        a.stop()
        b.stop()


def test_graceful_leave_is_immediate(store):
    a = _mk(store, "nodeA")
    b = _mk(store, "nodeB")
    try:
        assert a.wait_for_np(2, timeout=5)
        a.commit_roster()
        b.leave()  # marks hb 'gone' — no timeout wait needed
        assert a.watch_once() == ElasticStatus.RESTART
        assert a.commit_roster() == ["nodeA"]
    finally:
        a.stop()
        b.stop()


def test_scale_up_detected(store):
    a = _mk(store, "nodeA", np_range=(1, 4))
    try:
        assert a.wait_for_np(1, timeout=5)
        a.commit_roster()
        assert a.watch_once() == ElasticStatus.COMPLETED
        c = _mk(store, "nodeC", np_range=(1, 4))
        try:
            assert a.wait_for_np(2, timeout=5)
            assert a.watch_once() == ElasticStatus.RESTART
            roster = a.commit_roster()
            assert roster == ["nodeA", "nodeC"]
            assert a.rank_of(roster) == 0 and c.rank_of(roster) == 1
        finally:
            c.stop()
    finally:
        a.stop()


def test_hold_below_np_min(store):
    a = _mk(store, "nodeA", np_range=(2, 4), timeout=0.6)
    b = _mk(store, "nodeB", np_range=(2, 4), timeout=0.6)
    try:
        assert a.wait_for_np(2, timeout=5)
        a.commit_roster()
        b.leave()
        # below np_min=2: HOLD (RESTART only applies at/above the minimum)
        status = a.watch_once()
        assert status == ElasticStatus.HOLD
    finally:
        a.stop()
        b.stop()


def test_require_np_timeout_is_typed_and_bounded(store):
    """wait_for_np returns False on expiry — a policy decision callers kept
    silently swallowing (the controller built under-strength pods).
    require_np is the can't-ignore form: typed MembershipTimeout naming
    the shortfall, within the budget."""
    from paddle_tpu.utils.deadline import MembershipTimeout

    a = _mk(store, "nodeA")
    try:
        t0 = time.monotonic()
        with pytest.raises(MembershipTimeout, match="only 1 alive"):
            a.require_np(3, timeout=0.6)
        assert time.monotonic() - t0 < 5.0
        # and the satisfied path returns the alive set
        assert a.require_np(1, timeout=5.0) == ["nodeA"]
    finally:
        a.stop()


def test_lease_lapse_eviction_then_rejoin_gets_gap_free_rank(store):
    """A worker whose lease lapsed (suspended process, burst partition) is
    evicted by every observer; when it comes back (fresh manager, same
    node id — the relaunch path) it must rejoin and land a fresh,
    GAP-FREE rank: sorted-position ranks over the alive set, no hole
    where the evicted incarnation used to be."""
    a = _mk(store, "nodeA", timeout=0.5)
    b = _mk(store, "nodeB", timeout=0.5)
    rejoined = None
    try:
        assert a.wait_for_np(2, timeout=5)
        # lapse: stop B's heartbeats WITHOUT revoking (no graceful leave)
        b._stop.set()
        b._hb_thread.join(timeout=5)
        time.sleep(2 * b.interval + b.timeout + 0.5)   # > lease ttl
        assert a.alive_members() == ["nodeA"]
        assert a.rank_of() == 0
        # rejoin under the SAME identity (what a relaunched worker does)
        rejoined = _mk(store, "nodeB", timeout=0.5)
        assert a.wait_for_np(2, timeout=5)
        members = a.alive_members()
        assert members == ["nodeA", "nodeB"], members
        # gap-free: ranks are exactly 0..n-1 over the sorted alive set
        ranks = sorted(m.rank_of(members) for m in (a, rejoined))
        assert ranks == [0, 1], ranks
    finally:
        a.stop()
        b.stop()
        if rejoined is not None:
            rejoined.stop()


def test_nnodes_range_parses():
    from paddle_tpu.distributed.launch.context import Context
    ctx = Context.from_args(["--nnodes", "2:4", "--master", "127.0.0.1:45001",
                             "dummy.py"])
    assert ctx.nnodes == 2 and ctx.np_max == 4 and ctx.elastic
    ctx2 = Context.from_args(["dummy.py"])
    assert not ctx2.elastic
