"""Multi-process HYBRID-parallel verification (VERDICT r3 item 3; reference
pattern: test/collective/fleet/test_parallel_dygraph_pipeline_parallel.py:25
— launch a real multi-device job running the hybrid payload).

Two REAL processes x 4 virtual CPU devices each = an 8-device dp2 x pp2 x mp2
mesh spanning processes (dp is the cross-process axis).  The workers:
  * rendezvous via the launcher's TCPStore + jax.distributed,
  * build the SAME tiny LLaMA and run the compiled hybrid train step
    (1F1B pipeline + TP + dp-sharded ZeRO states) for 3 steps,
  * save a sharded checkpoint (each process writes its addressable shards),
  * reload it into a fresh model/optimizer and run 1 more step (resume leg).

The test then asserts loss parity per step against the SAME payload run
single-process on the conftest's 8-device mesh, and that the resumed step-4
loss matches a 4-step single-process run.
"""
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_STEPS = 3

PAYLOAD = r'''
import numpy as np


def run_payload(n_steps, ckpt_dir=None, resume=False, skip_batches=0):
    """Build the hybrid model/step deterministically and run n_steps.
    Returns list of per-step losses.  With resume=True, first load the
    sharded checkpoint from ckpt_dir into the fresh state, then run.
    skip_batches advances the data stream so a resumed run continues the
    uninterrupted batch sequence."""
    import jax
    import paddle_tpu as P
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer,
    )
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_hybrid_train_step)
    from paddle_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.get_mesh()
    P.seed(0)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=4, heads=4, inter=64)
    cfg.sequence_parallel = True
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-2,
                            parameters=model.parameters())
    opt = DygraphShardingOptimizer(opt)
    step = build_hybrid_train_step(model, opt, mesh=mesh, n_microbatches=4)

    if resume:
        import paddle_tpu.distributed.checkpoint as dck
        state = {"params": step.state["params"], "opt": step.state["opt"]}
        dck.load_state_dict(state, ckpt_dir)
        step.state["params"] = state["params"]
        step.state["opt"] = state["opt"]

    rng = np.random.RandomState(0)
    for _ in range(skip_batches):
        rng.randint(0, cfg.vocab_size, (8, 17))
    losses = []
    for i in range(n_steps):
        ids = rng.randint(0, cfg.vocab_size, (8, 17))
        batch = {"input_ids": P.to_tensor(ids[:, :-1]),
                 "labels": P.to_tensor(ids[:, 1:])}
        loss = step(batch)
        losses.append(float(np.asarray(
            loss._value.addressable_shards[0].data)))
    if ckpt_dir is not None and not resume:
        import paddle_tpu.distributed.checkpoint as dck
        dck.save_state_dict({"params": step.state["params"],
                             "opt": step.state["opt"]}, ckpt_dir)
        dck.wait()
    return losses
'''

WORKER = PAYLOAD + r'''
import json, os, sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import mesh as mesh_mod

out_dir = sys.argv[1]
n_steps = int(sys.argv[2])
rank = int(os.environ["PADDLE_TRAINER_ID"])

dist.init_parallel_env({"dp": 2, "pp": 2, "mp": 2})
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
mesh = mesh_mod.get_mesh()
# dp must be the cross-process axis: each process contributes 4 devices
assert mesh.devices.shape == (2, 2, 2)

ckpt = os.path.join(out_dir, "ckpt")
losses = run_payload(n_steps, ckpt_dir=ckpt)
resumed = run_payload(1, ckpt_dir=ckpt, resume=True, skip_batches=n_steps)

with open(os.path.join(out_dir, f"res{rank}.json"), "w") as f:
    json.dump({"rank": rank, "losses": losses, "resumed": resumed}, f)
'''


def _single_process_reference(tmp_path, n_steps):
    """Same payload on this process's own 8-device mesh (conftest platform),
    in a subprocess so mesh/global state can't leak into other tests."""
    script = tmp_path / "ref.py"
    script.write_text(PAYLOAD + r'''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu.distributed as dist

out, n_steps = sys.argv[1], int(sys.argv[2])
dist.init_parallel_env({"dp": 2, "pp": 2, "mp": 2})
losses = run_payload(n_steps)
with open(out, "w") as f:
    json.dump(losses, f)
''')
    out = tmp_path / "ref.json"
    env = dict(os.environ,
               PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, str(script), str(out), str(n_steps)],
                       cwd=REPO, env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, f"reference run failed: {r.stderr[-3000:]}"
    with open(out) as f:
        return json.load(f)


def test_two_process_hybrid_parallel(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ,
               PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)  # workers set their own 4-device flag
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", f"--log_dir={tmp_path}/log",
         str(script), str(tmp_path), str(N_STEPS)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    logs = ""
    logdir = tmp_path / "log"
    if logdir.exists():
        for p in sorted(logdir.iterdir()):
            logs += f"\n--- {p.name} ---\n" + p.read_text()[-3000:]
    assert r.returncode == 0, f"launch failed: {r.stderr[-2000:]}\n{logs}"

    results = {}
    for rank in (0, 1):
        path = tmp_path / f"res{rank}.json"
        assert path.exists(), f"rank {rank} produced no result\n{logs}"
        with open(path) as f:
            results[rank] = json.load(f)

    # both processes observe the identical global loss sequence
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["resumed"], results[1]["resumed"],
                               rtol=1e-6)

    # loss parity with the single-process 8-device run of the same payload
    ref = _single_process_reference(tmp_path, N_STEPS + 1)
    np.testing.assert_allclose(results[0]["losses"], ref[:N_STEPS],
                               rtol=1e-4, atol=1e-5)

    # checkpoint resume: the resumed step forwards the restored params on
    # the (N_STEPS+1)-th batch, so its loss must equal the uninterrupted
    # run's step N_STEPS+1 loss (loss is computed before the update, so it
    # depends only on the restored parameters and the batch)
    np.testing.assert_allclose(results[0]["resumed"], [ref[N_STEPS]],
                               rtol=1e-4, atol=1e-5)
