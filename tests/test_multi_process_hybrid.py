"""Multi-process HYBRID-parallel verification (VERDICT r3 item 3 / r4 items
3+5; reference pattern: test/collective/fleet/
test_parallel_dygraph_pipeline_parallel.py:25).

Two REAL processes x 4 virtual CPU devices each = an 8-device dp2 x pp2 x
mp2 mesh spanning processes (dp is the cross-process axis), driven from the
declarative registry (dist_registry.py, the testslist.csv analog).  Both the
1F1B and the VPP (interleaved virtual stage) schedules get:
  * per-step loss parity across the two ranks,
  * loss parity vs the SAME payload single-process on an 8-device mesh,
  * a sharded-checkpoint save -> fresh-model resume leg whose step-(N+1)
    loss equals the uninterrupted single-process run's.
"""
import numpy as np
import pytest

from dist_registry import run_dist

N_STEPS = 3


@pytest.mark.parametrize("schedule", ["1f1b", "vpp"])
def test_two_process_hybrid_parallel(tmp_path, schedule):
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    _, results, logs = run_dist("hybrid_2proc", mp_dir,
                                args=(N_STEPS, schedule))
    for rank in (0, 1):
        assert rank in results, f"rank {rank} produced no result\n{logs}"

    # both processes observe the identical global loss sequence
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["resumed"], results[1]["resumed"],
                               rtol=1e-6)

    # loss parity with the single-process 8-device run of the same payload
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    _, ref_results, ref_logs = run_dist("hybrid_ref", ref_dir,
                                        args=(N_STEPS + 1, schedule))
    assert 0 in ref_results, f"reference run produced no result\n{ref_logs}"
    ref = ref_results[0]["losses"]
    np.testing.assert_allclose(results[0]["losses"], ref[:N_STEPS],
                               rtol=1e-4, atol=1e-5)

    # checkpoint resume: the resumed step forwards the restored params on
    # the (N_STEPS+1)-th batch, so its loss must equal the uninterrupted
    # run's step N_STEPS+1 loss (loss is computed before the update, so it
    # depends only on the restored parameters and the batch)
    np.testing.assert_allclose(results[0]["resumed"], [ref[N_STEPS]],
                               rtol=1e-4, atol=1e-5)
