"""Sharded embedding tables (distributed/embedding/).

The exactness ladder under test:

1. dp1 / no mesh: ShardedEmbedding is BITWISE the dense nn.Embedding
   reference — same initializer draws, same jnp.take gather;
2. dp2 proxy (virtual CPU devices): the unique -> id all_to_all ->
   gather -> wire-return exchange is bitwise the dense gather with the
   quantized context off (forward AND gradients), and within the
   blockwise wire error bound with it on;
3. the whole DeepFM train step captures over the exchange, lowers once,
   lints clean, and its dp2 loss curve is bitwise the dp1 curve;
4. the wire legs are routed through distributed/comms (CommOp records,
   compression accounting) — no naked collectives;
5. a row-sharded table spec plans through plan_reshard and a scale event
   (grow/shrink) rides the PR 8 redistribute executor bitwise.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.distributed import comms
from paddle_tpu.distributed.embedding import (ShardedEmbedding, hash_bucket,
                                              sharded_lookup,
                                              table_param_spec)
from paddle_tpu.models import DeepFM
from paddle_tpu.nn.layer.common import Embedding
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.trainer import compile_train_step


@pytest.fixture(autouse=True)
def _clean_mesh_and_comms():
    prev = mesh_mod.get_mesh()
    comms.comm_clear()
    yield
    mesh_mod.set_mesh(prev)
    comms.comm_clear()


def _unwrap(x):
    return x._value if hasattr(x, "_value") else x


def _dp_mesh(n):
    return mesh_mod.init_mesh({"dp": n}, devices=jax.devices()[:n])


# ---------------- hash bucketing ----------------

def test_hash_bucket_identity_and_hashed():
    ids = jnp.asarray([0, 1, 31, 63])
    # identity-mod: in-range ids keep their row (the dp1-bitwise contract)
    np.testing.assert_array_equal(np.asarray(hash_bucket(ids, 64, False)),
                                  [0, 1, 31, 63])
    b = np.asarray(hash_bucket(ids, 64, True))
    assert b.dtype == np.int32 and np.all((0 <= b) & (b < 64))
    # deterministic, and it actually mixes (not the identity)
    b2 = np.asarray(hash_bucket(ids, 64, True))
    np.testing.assert_array_equal(b, b2)
    assert not np.array_equal(b, np.asarray(ids))


def test_hash_bucket_spreads_arbitrary_id_space():
    # 100k-scale raw ids land roughly uniformly over the buckets
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 10**8, 4096))
    counts = np.bincount(np.asarray(hash_bucket(ids, 16, True)), minlength=16)
    assert counts.min() > 0.5 * 4096 / 16, counts


# ---------------- dp1: bitwise the dense reference ----------------

def test_dp1_bitwise_dense_reference():
    mesh_mod.set_mesh(None)
    P.seed(11)
    sharded = ShardedEmbedding(32, 8)
    P.seed(11)
    dense = Embedding(32, 8)
    np.testing.assert_array_equal(np.asarray(sharded.weight._value),
                                  np.asarray(dense.weight._value))
    ids = P.to_tensor(np.random.RandomState(0).randint(0, 32, (6, 4)))
    np.testing.assert_array_equal(np.asarray(_unwrap(sharded(ids))),
                                  np.asarray(_unwrap(dense(ids))))


def test_indivisible_table_degrades_to_dense_bitwise():
    # 33 rows on dp2: the exchange path refuses (rows % n != 0) and the
    # dense gather serves — correctness never depends on the fast path
    _dp_mesh(2)
    w = jnp.asarray(np.random.RandomState(1).randn(33, 4).astype(np.float32))
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 33, (8, 3)))
    out = _unwrap(sharded_lookup(ids, w))
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.take(w, ids.astype(jnp.int32),
                                             axis=0)))


# ---------------- dp2: the exchange path ----------------

def _rand_table(rows=32, dim=8, seed=7):
    return jnp.asarray(np.random.RandomState(seed).randn(rows, dim)
                       .astype(np.float32))


def test_dp2_lookup_bitwise_and_sites_recorded():
    _dp_mesh(2)
    w = _rand_table()
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 32, (8, 4)))
    out = np.asarray(_unwrap(sharded_lookup(ids, w)))
    ref = np.asarray(jnp.take(w, ids.astype(jnp.int32), axis=0))
    np.testing.assert_array_equal(out, ref)
    sites = comms.comm_info()["sites"]
    assert "embedding.ids/all_to_all/dp" in sites
    assert "embedding.rows/all_to_all/dp" in sites
    # exact regime: wire == logical (nothing flattered)
    rows = sites["embedding.rows/all_to_all/dp"]
    assert rows["bytes_wire"] == rows["bytes_logical"] > 0


def test_dp2_grad_bitwise_dense_reference():
    _dp_mesh(2)
    w = _rand_table()
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 32, (8, 4)))
    scale = jnp.arange(8.0)

    def loss_sharded(ww):
        return jnp.sum(jnp.tanh(_unwrap(sharded_lookup(ids, ww))) * scale)

    def loss_dense(ww):
        return jnp.sum(jnp.tanh(jnp.take(ww, ids.astype(jnp.int32), axis=0))
                       * scale)

    gs = np.asarray(jax.grad(loss_sharded)(w))
    gd = np.asarray(jax.grad(loss_dense)(w))
    # duplicates included: the dedup'd push pre-accumulates per unique id,
    # and the result still lands bitwise on this proxy
    np.testing.assert_array_equal(gs, gd)


def test_dp2_quantized_lookup_and_grad_within_wire_error_bound():
    _dp_mesh(2)
    w = _rand_table()
    ids = jnp.asarray(np.random.RandomState(5).randint(0, 32, (8, 4)))
    ref = np.asarray(jnp.take(w, ids.astype(jnp.int32), axis=0))

    # ONE value_and_grad trace serves both halves (grad-of-shard_map
    # compiles dominate this file's wall clock)
    def run(ww):
        out = _unwrap(sharded_lookup(ids, ww))
        return jnp.sum(jnp.tanh(out)), out

    (_, out_d), gd = jax.value_and_grad(run, has_aux=True)(w)
    comms.comm_clear()
    with comms.quantized("int8"):
        (_, out_q), gq = jax.value_and_grad(run, has_aux=True)(w)
    out_q, gq, gd = np.asarray(out_q), np.asarray(gq), np.asarray(gd)
    np.testing.assert_array_equal(np.asarray(out_d), ref)  # off: bitwise
    # blockwise int8: |err| <= block absmax / 254 <= global absmax / 254
    bound = np.abs(np.asarray(w)).max() / 254 + 1e-6
    assert np.max(np.abs(out_q - ref)) <= bound
    # straight-through gradient on the wire: finite and close, not bitwise
    assert np.all(np.isfinite(gq))
    assert np.max(np.abs(gq - gd)) <= 0.1 * (np.abs(gd).max() + 1.0)
    sites = comms.comm_info()["sites"]
    rows = sites["embedding.rows/all_to_all/dp"]
    assert rows["quantized"] == "int8"
    assert rows["bytes_wire"] < rows["bytes_logical"]
    # id legs stay exact int32; the sparse grad push crossed the wire
    assert sites["embedding.ids/all_to_all/dp"]["quantized"] is None
    assert "embedding.rows.grad/all_to_all/dp" in sites


def test_capacity_overflow_drops_to_zero_embedding():
    _dp_mesh(2)
    w = _rand_table(rows=4, dim=2)
    # per rank: two distinct ids, both owned by shard 0 -> capacity 1
    # keeps the smaller unique (ids sort first), drops the other to the
    # documented zero embedding (the MoE capacity-factor semantics)
    ids = jnp.asarray([[0, 1], [0, 1]])
    out = np.asarray(_unwrap(sharded_lookup(ids, w, capacity=1)))
    ref = np.asarray(jnp.take(w, ids.astype(jnp.int32), axis=0))
    np.testing.assert_array_equal(out[:, 0], ref[:, 0])        # kept
    np.testing.assert_array_equal(out[:, 1], np.zeros((2, 2)))  # dropped


# ---------------- DeepFM end-to-end through the captured step ----------------

def _tiny_deepfm(seed=0):
    P.seed(seed)
    model = DeepFM(sparse_feature_number=64, sparse_feature_dim=8,
                   dense_feature_dim=4, sparse_field_num=6,
                   layer_sizes=(16,))
    opt = P.optimizer.SGD(learning_rate=0.05,
                          parameters=model.parameters())
    return model, opt


def _ctr_loss(m, b):
    return nn.functional.binary_cross_entropy_with_logits(
        m(b["sparse"], b["dense"]), b["y"])


def _ctr_batch(B=32, seed=0):
    rng = np.random.RandomState(seed)
    return {"sparse": P.to_tensor(rng.randint(0, 64, (B, 6))),
            "dense": P.to_tensor(rng.randn(B, 4).astype(np.float32)),
            "y": P.to_tensor((rng.rand(B, 1) > 0.5).astype(np.float32))}


def _drive(mesh_n, steps=3, quant=False):
    if mesh_n > 1:
        mesh = _dp_mesh(mesh_n)
    else:
        mesh_mod.set_mesh(None)
        mesh = None
    model, opt = _tiny_deepfm()
    step = compile_train_step(model, _ctr_loss, opt, mesh=mesh)

    def run():
        return [float(step(_ctr_batch()).numpy()) for _ in range(steps)]

    if quant:
        with comms.quantized("int8"):
            losses = run()
    else:
        losses = run()
    return losses, step


def test_deepfm_captured_step_dp2_bitwise_dp1_and_quantized_parity():
    # ONE dp2 exact run is the anchor for both halves (train-step builds
    # dominate this file's wall clock — don't build it twice)
    l2, step2 = _drive(2)
    l1, _ = _drive(1)
    assert l2 == l1, (l2, l1)
    assert l2[-1] < l2[0]
    # one lowering, exchange collectives tagged by the comm pass
    assert step2.captured_program is not None
    rep = step2.captured_program.pass_report
    assert rep.comm_tagged >= 4, rep.as_dict()   # >=2 tables x 2 wire legs
    # the captured step lints clean — the same program the staticcheck
    # jaxpr tier gates (zero unscheduled collectives, no dead compute)
    from paddle_tpu.jit.passes import lint
    rec = lint.lint_records().get("pure_step")
    assert rec is not None and rec["findings"] == [], rec

    # quantized regime: finite, loss-parity vs the exact curve, and both
    # the embedding combine and the grad sync ride the int8 wire
    comms.comm_clear()
    lq, _ = _drive(2, quant=True)
    assert np.isfinite(lq[-1])
    assert abs(lq[-1] - l2[-1]) / max(abs(l2[-1]), 1e-9) < 0.1, (lq, l2)
    sites = comms.comm_info()["sites"]
    assert sites["embedding.rows/all_to_all/dp"]["quantized"] == "int8"
    assert sites["trainer.grad_sync/all_reduce/dp"]["quantized"] == "int8"


# ---------------- scale events ride the PR 8 executor ----------------

def test_row_sharded_table_reshard_grow_and_shrink():
    from paddle_tpu.distributed import reshard as rs

    rows, dim = 16, 4
    full = np.arange(rows * dim, dtype=np.float32).reshape(rows, dim)

    # grow: 2 owners -> 4 owners, rows stay sharded on the same axis
    src = rs.MeshSpec.from_members(["a", "b"], shape={"mp": 2})
    dst = rs.MeshSpec.from_members(["a", "b", "c", "d"], shape={"mp": 4})
    spec = table_param_spec(rows, dim, src_axis="mp", dst_axis="mp")
    plan = rs.plan_reshard(src, dst, {"table": spec})
    assert plan.recoverable_from_peers
    assert plan.bytes_moved > 0
    states = {"a": {"table": full[:8].copy()}, "b": {"table": full[8:].copy()}}
    out, _ = rs.redistribute(src, dst, {"table": spec}, states)
    for i, o in enumerate(["a", "b", "c", "d"]):
        np.testing.assert_array_equal(out[o]["table"], full[i * 4:(i + 1) * 4])

    # shrink back 4 -> 2 with one owner dead: survivors supply the bricks
    back = rs.plan_reshard(dst, src, {"table": spec},
                           available={"a", "b", "c"})
    # owner 'd' held rows 12..16, which nobody else holds
    assert not back.recoverable_from_peers
    lost_rows = {p.index[0] for p in back.lost}
    assert lost_rows == {(12, 16)}


def test_table_reshard_replicate_to_sharded():
    """An embedding table trained replicated (dp1 job) scale-events onto a
    row-sharded mesh: src spec None, dst spec mp — the planner reuses the
    local copy where possible and ships only the missing rows."""
    from paddle_tpu.distributed import reshard as rs

    rows, dim = 8, 2
    full = (np.arange(rows * dim, dtype=np.float32) + 1).reshape(rows, dim)
    src = rs.MeshSpec.from_members(["a"], shape={"mp": 1})
    dst = rs.MeshSpec.from_members(["a", "b"], shape={"mp": 2})
    spec = table_param_spec(rows, dim, src_axis=None, dst_axis="mp")
    out, plan = rs.redistribute(src, dst, {"table": spec},
                                {"a": {"table": full.copy()}})
    np.testing.assert_array_equal(out["a"]["table"], full[:4])
    np.testing.assert_array_equal(out["b"]["table"], full[4:])
    # the owner that already held everything reused its bytes locally
    assert plan.bytes_local > 0
