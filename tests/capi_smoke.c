/* C serving smoke test: load a jit.saved StableHLO model through the
 * PDT_* C ABI (libpaddle_tpu_capi.so) and run named-IO inference — the
 * capability the reference ships as capi_exp (pd_inference_api).
 * Usage: capi_smoke <model_prefix> <n_features>
 * Prints "OUT <v0> <v1> ..." for the first batch row on success. */
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>

typedef void* (*fp_void)(void);
typedef void (*fp_cfg_set)(void*, const char*);
typedef void* (*fp_pred_create)(void*);
typedef size_t (*fp_num)(void*);
typedef const char* (*fp_name)(void*, size_t);
typedef void* (*fp_handle)(void*, const char*);
typedef int (*fp_reshape)(void*, const int*, int);
typedef int (*fp_copy_from)(void*, const float*, size_t);
typedef int (*fp_run)(void*);
typedef int (*fp_get_shape)(void*, int*, int, int*);
typedef int (*fp_copy_to)(void*, float*, size_t);
typedef int (*fp_init)(const char*);
typedef const char* (*fp_err)(void);

#define LOAD(sym, type)                                    \
  type sym = (type)dlsym(lib, #sym);                       \
  if (!sym) {                                              \
    fprintf(stderr, "missing symbol %s\n", #sym);          \
    return 2;                                              \
  }

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_prefix> <n_features>\n", argv[0]);
    return 2;
  }
  const char* model = argv[1];
  int nfeat = atoi(argv[2]);

  void* lib = dlopen("libpaddle_tpu_capi.so", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 2;
  }
  LOAD(PDT_Init, fp_init);
  LOAD(PDT_GetLastError, fp_err);
  LOAD(PDT_ConfigCreate, fp_void);
  LOAD(PDT_ConfigSetModel, fp_cfg_set);
  LOAD(PDT_PredictorCreate, fp_pred_create);
  LOAD(PDT_PredictorGetInputNum, fp_num);
  LOAD(PDT_PredictorGetInputName, fp_name);
  LOAD(PDT_PredictorGetOutputNum, fp_num);
  LOAD(PDT_PredictorGetOutputName, fp_name);
  LOAD(PDT_PredictorGetInputHandle, fp_handle);
  LOAD(PDT_PredictorGetOutputHandle, fp_handle);
  LOAD(PDT_TensorReshape, fp_reshape);
  LOAD(PDT_TensorCopyFromCpuFloat, fp_copy_from);
  LOAD(PDT_PredictorRun, fp_run);
  LOAD(PDT_TensorGetShape, fp_get_shape);
  LOAD(PDT_TensorCopyToCpuFloat, fp_copy_to);

  if (PDT_Init(getenv("PDT_PLATFORM") ? getenv("PDT_PLATFORM") : "") != 0) {
    fprintf(stderr, "init: %s\n", PDT_GetLastError());
    return 1;
  }
  void* cfg = PDT_ConfigCreate();
  PDT_ConfigSetModel(cfg, model);
  void* pred = PDT_PredictorCreate(cfg);
  if (!pred) {
    fprintf(stderr, "create: %s\n", PDT_GetLastError());
    return 1;
  }
  size_t nin = PDT_PredictorGetInputNum(pred);
  size_t nout = PDT_PredictorGetOutputNum(pred);
  if (nin < 1 || nout < 1) {
    fprintf(stderr, "io counts: %zu in %zu out\n", nin, nout);
    return 1;
  }
  const char* in_name = PDT_PredictorGetInputName(pred, 0);
  const char* out_name = PDT_PredictorGetOutputName(pred, 0);
  printf("IO %s -> %s\n", in_name, out_name);

  void* in = PDT_PredictorGetInputHandle(pred, in_name);
  int batch = 2;
  int dims[2];
  dims[0] = batch;
  dims[1] = nfeat;
  if (PDT_TensorReshape(in, dims, 2) != 0) {
    fprintf(stderr, "reshape: %s\n", PDT_GetLastError());
    return 1;
  }
  float* data = (float*)malloc(sizeof(float) * batch * nfeat);
  for (int i = 0; i < batch * nfeat; ++i) data[i] = 0.01f * i;
  if (PDT_TensorCopyFromCpuFloat(in, data, (size_t)(batch * nfeat)) != 0) {
    fprintf(stderr, "copy_from: %s\n", PDT_GetLastError());
    return 1;
  }
  if (PDT_PredictorRun(pred) != 0) {
    fprintf(stderr, "run: %s\n", PDT_GetLastError());
    return 1;
  }
  void* out = PDT_PredictorGetOutputHandle(pred, out_name);
  int oshape[8], ondims = 0;
  if (PDT_TensorGetShape(out, oshape, 8, &ondims) != 0) {
    fprintf(stderr, "get_shape: %s\n", PDT_GetLastError());
    return 1;
  }
  size_t total = 1;
  for (int i = 0; i < ondims; ++i) total *= (size_t)oshape[i];
  float* result = (float*)malloc(sizeof(float) * total);
  if (PDT_TensorCopyToCpuFloat(out, result, total) != 0) {
    fprintf(stderr, "copy_to: %s\n", PDT_GetLastError());
    return 1;
  }
  size_t per_row = total / (size_t)batch;
  printf("OUT");
  for (size_t i = 0; i < per_row && i < 8; ++i) printf(" %.6f", result[i]);
  printf("\n");
  free(result);
  free(data);
  return 0;
}
