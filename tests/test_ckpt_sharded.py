"""Crash-consistent SHARDED generation commits (distributed/ckpt_manager).

The owner-sharded layout is two-phase: every owner stages its bricks as
`shard-<owner>.npz` + CRC sidecar + a per-owner receipt, then ONE
committer collects every receipt, cross-checks them against the staged
sidecars, and writes metadata + the unified manifest + the atomic COMMIT
marker. The laws under test:

  * a partial stage (shards and receipts but no marker) NEVER becomes
    latest() — readers keep resolving the previous committed generation;
  * a receipt that disagrees with the staged bytes is a typed
    CheckpointCorruptionError at commit time, not a torn restore later;
  * GC reaps dead partial stages of BOTH layouts once a newer commit
    lands;
  * a generation written shard-by-shard restores bit-identically to the
    same state written through the gather layout — one read side;
  * N owners staging concurrently beat one gatherer writing the same
    bytes (the whole point of sharding the write path).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import CheckpointCorruptionError
from paddle_tpu.distributed.ckpt_manager import (COMMIT, CheckpointManager,
                                                 MANIFEST, SHARDED_LAYOUT)
from paddle_tpu.utils.deadline import CheckpointTimeout


def _state_for(step, rows=8):
    return {"w": np.full((rows, 4), float(step), np.float32),
            "b": (np.arange(rows, dtype=np.float32) + 1) * step}


def _meta_for(state):
    return {n: {"shape": list(v.shape), "dtype": str(v.dtype),
                "spec": ["dp"] + [None] * (v.ndim - 1)}
            for n, v in state.items()}


def _stripe(state, i, n):
    """Owner i's dp-stripe of every param, slice-keyed the way the
    reader assembles (`name|lo:hi,...` over every dim)."""
    out = {}
    for name, v in state.items():
        rows = v.shape[0] // n
        lo, hi = i * rows, (i + 1) * rows
        idx = ",".join([f"{lo}:{hi}"] + [f"0:{d}" for d in v.shape[1:]])
        out[f"{name}|{idx}"] = v[lo:hi].copy()
    return out


def _sharded_save(root, step, state, owners, budget=30.0):
    """Every owner stages its stripe from its own thread (its own manager,
    like separate processes over a shared filesystem); the lowest id
    collects receipts and commits. Returns per-owner wall seconds."""
    meta = _meta_for(state)
    walls, errs = {}, {}

    def run(i, owner):
        try:
            mgr = CheckpointManager(root)
            t0 = time.monotonic()
            mgr.save_sharded(step, owner, owners,
                             _stripe(state, i, len(owners)), meta,
                             budget=budget)
            walls[owner] = time.monotonic() - t0
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs[owner] = e

    threads = [threading.Thread(target=run, args=(i, o))
               for i, o in enumerate(owners)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    if errs:
        raise next(iter(errs.values()))
    return walls


def test_partial_stage_is_never_latest(tmp_path):
    """Shards + receipts but no COMMIT marker: the generation does not
    exist for readers — latest() and restore() keep the previous one."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    mgr.save(_state_for(1), 1)

    # both owners stage step-2 fully (receipts included); nobody commits
    st2 = _state_for(2)
    for i, owner in enumerate(("a", "b")):
        mgr.stage_shards(2, owner, _stripe(st2, i, 2))
    assert os.path.exists(os.path.join(mgr.gen_dir(2), "receipt-a.json"))
    assert not os.path.exists(os.path.join(mgr.gen_dir(2), COMMIT))

    fresh = CheckpointManager(root)
    assert fresh.latest() == 1
    state = {"w": np.zeros((8, 4), np.float32),
             "b": np.zeros(8, np.float32)}
    assert fresh.restore(state) == 1
    np.testing.assert_array_equal(state["w"], _state_for(1)["w"])


def test_receipt_shard_mismatch_rejected_typed(tmp_path):
    """A receipt whose CRC disagrees with the staged sidecar (a torn or
    replayed stage) must fail the COMMIT with the typed
    CheckpointCorruptionError — and leave the generation uncommitted."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    st = _state_for(3)
    mgr.stage_shards(3, "a", _stripe(st, 0, 2))
    mgr.stage_shards(3, "b", _stripe(st, 1, 2))

    # doctor b's receipt so it vouches for different bytes
    rpath = os.path.join(mgr.gen_dir(3), "receipt-b.json")
    rec = json.load(open(rpath))
    rec["files"]["shard-b.npz"]["crc32"] = "deadbeef"
    with open(rpath, "w") as f:
        json.dump(rec, f)

    with pytest.raises(CheckpointCorruptionError):
        mgr.commit_sharded(3, ["a", "b"], _meta_for(st), budget=5.0)
    assert not os.path.exists(os.path.join(mgr.gen_dir(3), COMMIT))
    assert CheckpointManager(root).latest() is None


def test_receipt_owner_mismatch_rejected_typed(tmp_path):
    """A receipt filed under one owner's name but claiming another (a
    mis-routed or replayed receipt) is typed corruption, not a commit."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    st = _state_for(4)
    mgr.stage_shards(4, "a", _stripe(st, 0, 2))
    mgr.stage_shards(4, "b", _stripe(st, 1, 2))
    rpath = os.path.join(mgr.gen_dir(4), "receipt-b.json")
    rec = json.load(open(rpath))
    rec["owner"] = "z"
    with open(rpath, "w") as f:
        json.dump(rec, f)
    with pytest.raises(CheckpointCorruptionError):
        mgr.commit_sharded(4, ["a", "b"], _meta_for(st), budget=5.0)


def test_under_covered_commit_rejected_typed(tmp_path):
    """Receipts that together cover only part of a parameter's volume
    must refuse to commit: an under-covered generation would only fail
    at restore time, long after the writers are gone."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    st = _state_for(5)
    # owner a stages only ITS stripe but claims to be the whole commit
    mgr.stage_shards(5, "a", _stripe(st, 0, 2))
    with pytest.raises(CheckpointCorruptionError, match="under-covered"):
        mgr.commit_sharded(5, ["a"], _meta_for(st), budget=5.0)


def test_commit_abort_raises_typed_timeout(tmp_path):
    """The committer's receipt wait honors its abort callback (an owner
    died, the roster changed): typed CheckpointTimeout naming the missing
    receipts, without burning the whole budget."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    st = _state_for(6)
    mgr.stage_shards(6, "a", _stripe(st, 0, 2))
    with pytest.raises(CheckpointTimeout, match="missing"):
        mgr.commit_sharded(6, ["a", "b"], _meta_for(st), budget=30.0,
                           abort=lambda: True)


def test_gc_reaps_partial_stages_of_both_layouts(tmp_path):
    """Dead partial attempts — a gather-layout stage without a marker AND
    a sharded stage without a marker — are reaped by the next successful
    commit's GC; committed generations obey keep_last_k."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root, keep_last_k=2)
    _sharded_save(root, 1, _state_for(1), ["a", "b"])

    # dead gather-layout attempt at step 2
    os.makedirs(mgr.gen_dir(2), exist_ok=True)
    with open(os.path.join(mgr.gen_dir(2), "shard-0.npz"), "wb") as f:
        f.write(b"half a shard from a dead gatherer")
    # dead sharded attempt at step 3: staged + receipt, no marker
    mgr.stage_shards(3, "a", _stripe(_state_for(3), 0, 2))

    _sharded_save(root, 4, _state_for(4), ["a", "b"])
    assert mgr.all_steps() == [1, 4]
    assert not os.path.exists(mgr.gen_dir(2))
    assert not os.path.exists(mgr.gen_dir(3))


def test_sharded_restores_bitwise_like_gather(tmp_path):
    """One read side for both layouts: the same state committed through
    the gather path and through per-owner shard files restores
    bit-identically, and the sharded manifest is typed with its layout."""
    st = _state_for(7)
    groot, sroot = str(tmp_path / "g"), str(tmp_path / "s")
    CheckpointManager(groot).save(st, 7)
    _sharded_save(sroot, 7, st, ["a", "b", "c", "d"])

    man = CheckpointManager(sroot).manifest(7)
    assert man["layout"] == SHARDED_LAYOUT
    out = {}
    for root in (groot, sroot):
        state = {"w": np.zeros((8, 4), np.float32),
                 "b": np.zeros(8, np.float32)}
        assert CheckpointManager(root).restore(state) == 7
        out[root] = state
    for name in st:
        np.testing.assert_array_equal(out[groot][name], out[sroot][name])
        np.testing.assert_array_equal(out[sroot][name], st[name])


def test_sharded_commit_beats_gather_commit(tmp_path):
    """The acceptance bench. A gather commit is a reshard onto ONE owner
    — every non-committer ships its stripe over the store transport
    before a single writer serializes the whole state. The sharded
    commit's point is that those bytes never cross the wire: each owner
    writes its bricks to the shared checkpoint filesystem directly.
    Both sides run the real primitives (StoreTransport over a TCPStore
    for the gather's byte movement, save_sharded for the bricks)."""
    from paddle_tpu.distributed import reshard as rs
    from paddle_tpu.distributed import store as store_mod

    rows, owners = 4096, ["a", "b", "c", "d"]   # ~16 MB of float32
    st = {"w": np.random.RandomState(0)
          .standard_normal((rows, 1024)).astype(np.float32)}
    stripes = {o: _stripe(st, i, len(owners))
               for i, o in enumerate(owners)}

    # -- gather commit: 3 stripes over the wire, then one writer --------
    groot = str(tmp_path / "g")
    ts = store_mod.TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    try:
        tr = rs.StoreTransport(ts, prefix="bench")
        from paddle_tpu.utils.deadline import Deadline

        def ship(owner):
            for key, arr in stripes[owner].items():
                tr.put(f"{owner}/{key}", arr.tobytes())

        t0 = time.monotonic()
        senders = [threading.Thread(target=ship, args=(o,))
                   for o in owners[1:]]
        for t in senders:
            t.start()
        full = {k: v.copy() for k, v in stripes[owners[0]].items()}
        dl = Deadline(60.0, what="bench gather")
        for o in owners[1:]:
            for key, arr in stripes[o].items():
                got = np.frombuffer(tr.get(f"{o}/{key}", dl),
                                    dtype=arr.dtype).reshape(arr.shape)
                full[key] = got.copy()
        assembled = {"w": np.concatenate(
            [full[k] for k in sorted(full, key=lambda k: int(
                k.split("|")[1].split(":")[0]))])}
        CheckpointManager(groot).save(assembled, 1)
        gather_wall = time.monotonic() - t0
        for t in senders:
            t.join(timeout=30.0)
    finally:
        ts.stop()

    # -- sharded commit: every owner writes its own bricks --------------
    sroot = str(tmp_path / "s")
    t0 = time.monotonic()
    _sharded_save(sroot, 1, st, owners)
    sharded_wall = time.monotonic() - t0

    state = {"w": np.zeros((rows, 1024), np.float32)}
    assert CheckpointManager(sroot).restore(state) == 1
    np.testing.assert_array_equal(state["w"], st["w"])
    np.testing.assert_array_equal(assembled["w"], st["w"])
    assert sharded_wall < gather_wall, (
        f"sharded commit ({sharded_wall:.3f}s) did not beat the gather "
        f"commit ({gather_wall:.3f}s) on {rows * 1024 * 4} bytes")


def test_commit_drops_files_no_receipt_vouches_for(tmp_path):
    """Leftover shard files from a dead EARLIER attempt of the same step
    (an owner that is not part of this commit) must not ride into the
    manifest: the generation is exactly what the receipts vouch for."""
    root = str(tmp_path / "c")
    mgr = CheckpointManager(root)
    st = _state_for(8)
    # a dead previous attempt by owner z, receipt and all
    mgr.stage_shards(8, "z", _stripe(st, 0, 2))
    _sharded_save(root, 8, st, ["a", "b"])
    man = CheckpointManager(root).manifest(8)
    assert "shard-z.npz" not in man["files"]
    assert not os.path.exists(os.path.join(mgr.gen_dir(8), "shard-z.npz"))
    state = {"w": np.zeros((8, 4), np.float32),
             "b": np.zeros(8, np.float32)}
    assert CheckpointManager(root).restore(state) == 8
    np.testing.assert_array_equal(state["w"], st["w"])
