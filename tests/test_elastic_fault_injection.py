"""Fault injection for elastic training (VERDICT r3 item 9; reference:
fleet/elastic/manager.py ETCD-lease liveness + whole-job restart).

Two legs, both driven from the declarative registry (dist_registry.py):
1. store-side TTL lease semantics: a member SIGKILLed mid-run is declared
   dead by the STORE's clock — in particular, a FRESH observer that never
   saw the victim's heartbeats agrees immediately after expiry (the
   observer-side sequence scheme could not do this).
2. end-to-end: a 2-rank training job; rank 1 SIGKILLs itself mid-step; the
   launch controller detects the death, relaunches the pod (next
   generation), and the workers RESUME from the sharded checkpoint —
   the final loss equals an uninterrupted run's.
"""
import subprocess
import time

import numpy as np

from dist_registry import run_dist, start_dist
from paddle_tpu.distributed.launch.elastic import ElasticManager
from paddle_tpu.distributed.store import create_master_store


def test_lease_survives_fresh_observer_after_kill(tmp_path):
    """SIGKILL a member process; the store's lease clock declares it dead,
    and an observer created AFTER the death (no heartbeat history) sees the
    correct alive set as soon as the TTL lapses."""
    master = create_master_store(port=0, world_size=1)
    try:
        victim = start_dist("elastic_member", tmp_path,
                            args=(master.port, "victim"),
                            stdout=subprocess.PIPE)
        assert victim.stdout.readline().strip() == "joined"

        alive_mgr = ElasticManager(master, node_id="survivor",
                                   np_range=(1, 4),
                                   heartbeat_interval=0.1, timeout=0.5)
        try:
            assert alive_mgr.wait_for_np(2, timeout=5)
            assert "victim" in alive_mgr.alive_members()

            victim.kill()            # SIGKILL: no graceful leave
            victim.wait(timeout=10)
            time.sleep(1.5)          # > ttl = 2*hb + timeout = 0.7s

            # a FRESH observer — never saw any heartbeat from the victim
            fresh = ElasticManager(master, node_id="late-observer",
                                   np_range=(1, 4),
                                   heartbeat_interval=0.1, timeout=0.5)
            try:
                members = fresh.alive_members()
                assert "victim" not in members, members
                assert {"survivor", "late-observer"} <= set(members)
            finally:
                fresh.stop()
        finally:
            alive_mgr.stop()
    finally:
        master.stop()


def test_kill_rank_relaunch_resume(tmp_path):
    n_steps = 4
    r, _, logs = run_dist("elastic_train_killrank", tmp_path,
                          args=(n_steps,))
    # the pod restarted (the controller's relaunch message) ...
    assert "restarting all local ranks" in r.stderr + logs, logs

    from dist_registry import REGISTRY, collect_results
    results = collect_results(REGISTRY["elastic_train_killrank"], tmp_path,
                              prefix="done")
    for rank in (0, 1):
        assert rank in results, f"rank {rank} never completed\n{logs}"
    # ... and the second generation RESUMED, not restarted from scratch
    assert results[0]["resumed_from"] >= 1, results
    assert results[0]["resumed_from"] == results[1]["resumed_from"]

    # loss continuity: uninterrupted single-process run over the same data
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as P
    P.seed(0)
    model = P.nn.Linear(8, 4)
    opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    ref = []
    for _ in range(n_steps):
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 4).astype(np.float32)
        loss = P.nn.functional.mse_loss(model(P.to_tensor(x)),
                                        P.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ref.append(float(loss.numpy()))
    resumed_losses = results[0]["losses"]
    np.testing.assert_allclose(
        resumed_losses, ref[results[0]["resumed_from"]:], rtol=1e-5,
        err_msg="post-resume losses diverge from the uninterrupted run")
