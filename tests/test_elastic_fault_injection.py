"""Fault injection for elastic training (VERDICT r3 item 9; reference:
fleet/elastic/manager.py ETCD-lease liveness + whole-job restart).

Two legs:
1. store-side TTL lease semantics: a member SIGKILLed mid-run is declared
   dead by the STORE's clock — in particular, a FRESH observer that never
   saw the victim's heartbeats agrees immediately after expiry (the
   observer-side sequence scheme could not do this).
2. end-to-end: a 2-rank training job; rank 1 SIGKILLs itself mid-step; the
   launch controller detects the death, relaunches the pod (next
   generation), and the workers RESUME from the sharded checkpoint —
   the final loss equals an uninterrupted run's.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.launch.elastic import ElasticManager
from paddle_tpu.distributed.store import TCPStore, create_master_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


MEMBER = r'''
import os, sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.launch.elastic import ElasticManager

store = TCPStore("127.0.0.1", int(sys.argv[1]), is_master=False)
m = ElasticManager(store, node_id=sys.argv[2], np_range=(1, 4),
                   heartbeat_interval=0.1, timeout=0.5)
print("joined", flush=True)
time.sleep(120)   # heartbeat until killed
'''


def test_lease_survives_fresh_observer_after_kill(tmp_path):
    """SIGKILL a member process; the store's lease clock declares it dead,
    and an observer created AFTER the death (no heartbeat history) sees the
    correct alive set as soon as the TTL lapses."""
    master = create_master_store(port=0, world_size=1)
    try:
        script = tmp_path / "member.py"
        script.write_text(MEMBER.format(repo=REPO))
        victim = subprocess.Popen(
            [sys.executable, str(script), str(master.port), "victim"],
            stdout=subprocess.PIPE, text=True)
        assert victim.stdout.readline().strip() == "joined"

        alive_mgr = ElasticManager(master, node_id="survivor",
                                   np_range=(1, 4),
                                   heartbeat_interval=0.1, timeout=0.5)
        try:
            assert alive_mgr.wait_for_np(2, timeout=5)
            assert "victim" in alive_mgr.alive_members()

            victim.kill()            # SIGKILL: no graceful leave
            victim.wait(timeout=10)
            time.sleep(1.5)          # > ttl = 2*hb + timeout = 0.7s

            # a FRESH observer — never saw any heartbeat from the victim
            fresh = ElasticManager(master, node_id="late-observer",
                                   np_range=(1, 4),
                                   heartbeat_interval=0.1, timeout=0.5)
            try:
                members = fresh.alive_members()
                assert "victim" not in members, members
                assert {"survivor", "late-observer"} <= set(members)
            finally:
                fresh.stop()
        finally:
            alive_mgr.stop()
    finally:
        master.stop()


WORKER = r'''
import json, os, signal, sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as P
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.checkpoint as dck

out_dir = sys.argv[1]
n_steps = int(sys.argv[2])
rank = int(os.environ["PADDLE_TRAINER_ID"])
ckpt = os.path.join(out_dir, "ckpt")
kill_marker = os.path.join(out_dir, "killed.marker")

dist.init_parallel_env({"dp": 2})

P.seed(0)
model = P.nn.Linear(8, 4)
opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())

start = 0
meta = os.path.join(ckpt, "step.json")
if os.path.exists(meta):
    with open(meta) as f:
        start = json.load(f)["step"]
    state = {"params": {n: p._value for n, p in model.named_parameters()}}
    dck.load_state_dict(state, ckpt)
    for n, p in model.named_parameters():
        p._set_value(state["params"][n])

rng = np.random.RandomState(0)
losses = []
for step in range(n_steps):
    x = rng.randn(4, 8).astype(np.float32)   # deterministic data stream
    y = rng.randn(4, 4).astype(np.float32)
    if step < start:
        continue                             # replay RNG, skip done steps
    loss = P.nn.functional.mse_loss(model(P.to_tensor(x)), P.to_tensor(y))
    loss.backward(); opt.step(); opt.clear_grad()
    losses.append(float(loss.numpy()))

    dck.save_state_dict(
        {"params": {n: p._value for n, p in model.named_parameters()}}, ckpt)
    dck.wait()
    dist.barrier()
    if rank == 0:
        with open(meta, "w") as f:
            json.dump({"step": step + 1}, f)
    dist.barrier()

    # FAULT: rank 1 dies hard mid-run, once
    if rank == 1 and step == 1 and not os.path.exists(kill_marker):
        open(kill_marker, "w").write("x")
        os.kill(os.getpid(), signal.SIGKILL)

with open(os.path.join(out_dir, f"done{rank}.json"), "w") as f:
    json.dump({"rank": rank, "resumed_from": start, "losses": losses}, f)
'''


def test_kill_rank_relaunch_resume(tmp_path):
    n_steps = 4
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ,
               PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", "--max_restart=3",
         f"--log_dir={tmp_path}/log", str(script), str(tmp_path),
         str(n_steps)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    logs = ""
    logdir = tmp_path / "log"
    if logdir.exists():
        for p in sorted(logdir.iterdir()):
            logs += f"\n--- {p.name} ---\n" + p.read_text()[-2000:]
    assert r.returncode == 0, f"launch failed: {r.stderr[-2000:]}\n{logs}"
    # the pod restarted (the controller's relaunch message) ...
    assert "restarting all local ranks" in r.stderr + logs, logs

    results = {}
    for rank in (0, 1):
        path = tmp_path / f"done{rank}.json"
        assert path.exists(), f"rank {rank} never completed\n{logs}"
        with open(path) as f:
            results[rank] = json.load(f)
    # ... and the second generation RESUMED, not restarted from scratch
    assert results[0]["resumed_from"] >= 1, results
    assert results[0]["resumed_from"] == results[1]["resumed_from"]

    # loss continuity: uninterrupted single-process run over the same data
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as P
    P.seed(0)
    model = P.nn.Linear(8, 4)
    opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    ref = []
    for _ in range(n_steps):
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 4).astype(np.float32)
        loss = P.nn.functional.mse_loss(model(P.to_tensor(x)),
                                        P.to_tensor(y))
        loss.backward(); opt.step(); opt.clear_grad()
        ref.append(float(loss.numpy()))
    resumed_losses = results[0]["losses"]
    np.testing.assert_allclose(
        resumed_losses, ref[results[0]["resumed_from"]:], rtol=1e-5,
        err_msg="post-resume losses diverge from the uninterrupted run")
