"""Eager-mode ZeRO stages really shard device buffers (VERDICT r1 item 6).

The reference's memory win (group_sharded_optimizer_stage2.py:53,
group_sharded_stage3.py:59) is measured here directly: after wrapping, the
max per-device buffer bytes must shrink ~n× for the sharded pytrees, and
training must still converge with loss parity vs the unwrapped run.
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.parallel import mesh as mesh_mod


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def _mlp(seed=0, d=64):
    P.seed(seed)
    return nn.Sequential(nn.Linear(d, 4 * d), nn.GELU(), nn.Linear(4 * d, d))


def _per_device_bytes(arr):
    by_dev = {}
    for s in arr.addressable_shards:
        by_dev[s.device] = by_dev.get(s.device, 0) + s.data.nbytes
    return max(by_dev.values())


def _train(model, opt, steps=5, d=64, seed=3):
    rng = np.random.RandomState(seed)
    x = P.to_tensor(rng.randn(16, d).astype("float32"))
    y = P.to_tensor(rng.randn(16, d).astype("float32"))
    losses = []
    for _ in range(steps):
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


def test_stage3_params_actually_sharded():
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        GroupShardedStage3)
    d = 64
    model_ref = _mlp(seed=1, d=d)
    opt_ref = P.optimizer.SGD(learning_rate=0.05, parameters=model_ref.parameters())
    ref_losses = _train(model_ref, opt_ref, d=d)

    mesh_mod.init_mesh({"sharding": 8})
    model = _mlp(seed=1, d=d)
    full_bytes = {id(p): p._value.nbytes for p in model.parameters()}
    opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
    wrapped = GroupShardedStage3(model, opt)

    # weight matrices hold 1/8 of their bytes per device after wrapping
    for p in model.parameters():
        if p.ndim == 2:
            assert _per_device_bytes(p._value) * 8 <= full_bytes[id(p)] + 1, p.shape

    losses = _train(wrapped, opt, d=d)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    # params STAY sharded across update steps
    for p in model.parameters():
        if p.ndim == 2:
            assert _per_device_bytes(p._value) * 8 <= full_bytes[id(p)] + 1


def test_stage1_opt_states_sharded():
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer)
    mesh_mod.init_mesh({"sharding": 8})
    model = _mlp(seed=2)
    opt = DygraphShardingOptimizer(
        P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters()))
    losses = _train(model, opt)
    assert losses[-1] < losses[0]
    inner = opt.inner_opt
    checked = 0
    for state in inner._states.values():
        for v in state.values():
            if hasattr(v, "ndim") and v.ndim == 2:
                assert _per_device_bytes(v) * 8 <= v.nbytes + 1
                checked += 1
    assert checked > 0


def test_stage3_non_divisible_dims_stay_replicated():
    """A (63, 63) weight is not divisible by 8: wrap must not crash, the
    param just stays replicated (reference pads; we keep it whole)."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        GroupShardedStage3)
    mesh_mod.init_mesh({"sharding": 8})
    P.seed(5)
    model = nn.Sequential(nn.Linear(63, 63), nn.GELU(), nn.Linear(63, 63))
    opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
    wrapped = GroupShardedStage3(model, opt)
    losses = _train(wrapped, opt, d=63)
    assert losses[-1] < losses[0]


def test_stage1_states_keep_tp_spec_on_hybrid_mesh():
    """On a {'sharding': 4, 'mp': 2} mesh, an mp-sharded weight's opt state
    must stay mp-sharded after the eager stage-1 reshard (review finding:
    base_spec was dropped, replicating states across mp)."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer)
    mesh_mod.init_mesh({"sharding": 4, "mp": 2})
    P.seed(6)
    model = _mlp(seed=6)
    # hand-annotate a TP spec on the first weight (column-parallel style)
    w = list(model.parameters())[0]
    w._sharding = (None, "mp")
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = mesh_mod.get_mesh()
    w._value = jax.device_put(w._value, NamedSharding(mesh, PartitionSpec(None, "mp")))
    opt = DygraphShardingOptimizer(
        P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters()))
    _train(model, opt)
    st = opt.inner_opt._states[id(w)]
    m_spec = str(next(v for v in st.values()
                      if hasattr(v, "ndim") and v.ndim == 2).sharding.spec)
    assert "mp" in m_spec, m_spec
    # and per-device bytes shrink by BOTH axes (mp × sharding = 8)
    v = next(v for v in st.values() if hasattr(v, "ndim") and v.ndim == 2)
    assert _per_device_bytes(v) * 8 <= v.nbytes + 1


def test_fleet_path_stage2_shards_eagerly():
    """strategy.sharding stage 2 through fleet.distributed_optimizer (the
    primary API path) must shard opt states in eager mode."""
    from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
    from paddle_tpu.distributed.fleet.hybrid_optimizer import HybridParallelOptimizer
    mesh_mod.init_mesh({"sharding": 8})
    model = _mlp(seed=7)
    s = DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": 2, "degree": 8}
    opt = HybridParallelOptimizer(
        P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters()),
        hcg=None, strategy=s)
    losses = _train(model, opt)
    assert losses[-1] < losses[0]
    inner = opt.inner_opt
    checked = 0
    for state in inner._states.values():
        for v in state.values():
            if hasattr(v, "ndim") and v.ndim == 2:
                assert _per_device_bytes(v) * 8 <= v.nbytes + 1
                checked += 1
    assert checked > 0
    # params full at rest
    for p in model.parameters():
        if p.ndim == 2:
            assert _per_device_bytes(p._value) == p._value.nbytes


def test_stage2_grads_and_states_sharded_params_full():
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        group_sharded_parallel)
    d = 64
    model_ref = _mlp(seed=4, d=d)
    opt_ref = P.optimizer.AdamW(learning_rate=1e-3,
                                parameters=model_ref.parameters())
    ref_losses = _train(model_ref, opt_ref, d=d)

    mesh_mod.init_mesh({"sharding": 8})
    model = _mlp(seed=4, d=d)
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    model2, opt2, _ = group_sharded_parallel(model, opt, level="os_g")
    losses = _train(model2, opt2, d=d)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)

    inner = opt2.inner_opt
    checked = 0
    for state in inner._states.values():
        for v in state.values():
            if hasattr(v, "ndim") and v.ndim == 2:
                assert _per_device_bytes(v) * 8 <= v.nbytes + 1
                checked += 1
    assert checked > 0
    # stage-2 params remain FULL per device (replicated at rest)
    for p in model.parameters():
        if p.ndim == 2:
            assert _per_device_bytes(p._value) == p._value.nbytes


def test_hybrid_clip_parity_under_mesh():
    """VERDICT r1 weak #4: global-norm clip at hybrid scope. In the global
    SPMD view the clip over (possibly sharded) eager grads IS the hybrid
    clip — updates must match the single-device run exactly."""
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm
    from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
    from paddle_tpu.distributed.fleet.hybrid_optimizer import HybridParallelOptimizer

    d = 64

    def run(mesh_shape, stage):
        mesh_mod.set_mesh(None)
        model = _mlp(seed=21, d=d)
        if mesh_shape:
            mesh_mod.init_mesh(mesh_shape)
        s = DistributedStrategy()
        if stage:
            s.sharding = True
            s.sharding_configs = {"stage": stage, "degree": 8}
        opt = HybridParallelOptimizer(
            P.optimizer.SGD(learning_rate=0.5,
                            parameters=model.parameters(),
                            grad_clip=ClipGradByGlobalNorm(0.01)),
            hcg=None, strategy=s)
        losses = _train(model, opt, d=d, steps=4)
        return losses

    ref = run(None, 0)
    sharded = run({"sharding": 8}, 2)
    np.testing.assert_allclose(sharded, ref, rtol=1e-4, atol=1e-5)
