"""Crash-point fault-injection matrix for the durable checkpoint layer.

For EVERY crash site registered in the save/commit path, a writer child
commits generation step-1, then is SIGKILLed at the armed site inside the
step-2 save (PT_CRASHPOINT env + PT_CRASHPOINT_HITS=2 — see
dist_workers/ckpt_chaos_writer.py). The reader-side law under test:

    CheckpointManager.latest() + restore() always recover the newest
    COMMITTED generation — step-1 for any kill before the COMMIT marker
    rename, step-2 at-or-after it — and never torn bytes.

Corruption that a kill cannot produce (bit flips on committed data) is
injected directly: checksum verification must reject it with the typed
CheckpointCorruptionError, never silently load it.
"""
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed import chaos
from paddle_tpu.distributed import checkpoint as ckpt  # noqa: F401 — registers ckpt.* sites
from paddle_tpu.distributed.checkpoint import CheckpointCorruptionError
from paddle_tpu.distributed.ckpt_manager import CheckpointManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WRITER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist_workers", "ckpt_chaos_writer.py")

# expected surviving generation per kill site: COMMIT's atomic rename is the
# durability point, so everything upstream of it loses step-2 and everything
# at-or-after it keeps step-2
EXPECTED_LATEST = {
    "ckpt.shard_tmp_written": 1,
    "ckpt.shard_renamed": 1,
    "ckpt.sidecar_written": 1,
    "ckpt.metadata_tmp_written": 1,
    "ckpt.metadata_written": 1,
    "ckpt.generation_staged": 1,
    "ckpt.manifest_written": 1,
    "ckpt.commit_written": 2,
    "ckpt.gc_done": 2,
}


def _state_for(step):
    return {"w": np.full((8, 8), float(step), np.float32),
            "b": (np.arange(6, dtype=np.float32) + 1) * step}


def test_matrix_covers_every_registered_site():
    """Adding a crashpoint() to the save path must widen this matrix: an
    unmapped registered site fails here until EXPECTED_LATEST says which
    generation survives a kill there."""
    assert set(chaos.registered_sites("ckpt.")) == set(EXPECTED_LATEST)


def test_crash_matrix_recovers_last_committed_generation(tmp_path):
    """SIGKILL the writer at every registered ckpt.* site (concurrently);
    a fresh reader must land on the expected committed generation with
    bit-exact content."""
    env_base = dict(os.environ,
                    PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""),
                    PT_CRASHPOINT_HITS="2")
    children = {}
    for site in sorted(EXPECTED_LATEST):
        out_dir = tmp_path / site.replace(".", "_")
        out_dir.mkdir()
        env = dict(env_base, PT_CRASHPOINT=site)
        children[site] = (out_dir, subprocess.Popen(
            [sys.executable, WRITER, str(out_dir)], cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True))

    for site, (out_dir, proc) in children.items():
        _, err = proc.communicate(timeout=240)
        assert proc.returncode == -signal.SIGKILL, (
            f"{site}: writer was supposed to die by SIGKILL at the armed "
            f"site, got rc={proc.returncode}\n{err[-2000:]}")
        assert not (out_dir / "survived").exists(), (
            f"{site}: writer ran past the armed crash site")

        want = EXPECTED_LATEST[site]
        mgr = CheckpointManager(str(out_dir / "ckpt"))
        got = mgr.latest()
        assert got == want, (
            f"{site}: latest() -> {got}, want committed generation {want} "
            f"(dir: {sorted(os.listdir(out_dir / 'ckpt'))})")
        state = {"w": np.zeros((8, 8), np.float32),
                 "b": np.zeros(6, np.float32)}
        assert mgr.restore(state) == want
        expect = _state_for(want)
        np.testing.assert_array_equal(state["w"], expect["w"],
                                      err_msg=f"{site}: torn 'w' restored")
        np.testing.assert_array_equal(state["b"], expect["b"],
                                      err_msg=f"{site}: torn 'b' restored")


def test_corrupted_committed_shard_rejected_not_loaded(tmp_path):
    """Bit-flip a committed generation's shard: restore must raise the typed
    CheckpointCorruptionError (checksum mismatch), and the previous
    generation must still restore cleanly."""
    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=2)
    mgr.save(_state_for(1), 1)
    mgr.save(_state_for(2), 2)

    shard = os.path.join(mgr.gen_dir(2), "shard-0.npz")
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(bytes(data))

    fresh = {"w": np.zeros((8, 8), np.float32), "b": np.zeros(6, np.float32)}
    with pytest.raises(CheckpointCorruptionError):
        mgr.restore(dict(fresh), 2)
    # the intact older generation is still a valid fallback
    state = dict(fresh)
    assert mgr.restore(state, 1) == 1
    np.testing.assert_array_equal(state["w"], _state_for(1)["w"])


def test_flat_checkpoint_corruption_detected(tmp_path):
    """The hardened base layer (save_state_dict/load_state_dict) detects a
    torn shard via its CRC32 sidecar even without the manager."""
    import paddle_tpu.distributed as dist

    d = str(tmp_path / "flat")
    dist.save_state_dict(_state_for(3), d)
    shard = os.path.join(d, "shard-0.npz")
    data = bytearray(open(shard, "rb").read())
    data[10] ^= 0x55
    with open(shard, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(CheckpointCorruptionError):
        dist.load_state_dict(_state_for(3), d)


def test_garbled_sidecar_raises_typed_error(tmp_path):
    """A torn checksum SIDECAR is the same corruption class as a torn shard:
    restore must raise CheckpointCorruptionError (not ValueError) so
    fall-back-to-older-generation handlers keep working."""
    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=2)
    mgr.save(_state_for(1), 1)
    with open(os.path.join(mgr.gen_dir(1), "shard-0.npz.crc32"), "w") as f:
        f.write("not hex garbage\x00")
    with pytest.raises(CheckpointCorruptionError, match="sidecar"):
        mgr.restore({"w": np.zeros((8, 8), np.float32),
                     "b": np.zeros(6, np.float32)}, 1)


def test_lost_sidecars_fall_back_to_manifest_crcs(tmp_path):
    """Tooling that drops *.crc32 sidecars (rsync patterns, object-store
    sync) must not disable verification: restore falls back to the CRCs
    committed in manifest.json and still rejects a bit-flipped shard."""
    import glob

    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=2)
    mgr.save(_state_for(1), 1)
    for sc in glob.glob(os.path.join(mgr.gen_dir(1), "*.crc32")):
        os.remove(sc)
    # intact files still restore fine without sidecars...
    state = {"w": np.zeros((8, 8), np.float32), "b": np.zeros(6, np.float32)}
    assert mgr.restore(state, 1) == 1
    np.testing.assert_array_equal(state["w"], _state_for(1)["w"])
    # ...but a flipped byte is caught by the manifest CRC
    shard = os.path.join(mgr.gen_dir(1), "shard-0.npz")
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 3] ^= 0x0F
    with open(shard, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(CheckpointCorruptionError, match="manifest"):
        mgr.restore(dict(state), 1)


def test_latest_skips_uncommitted_and_unsound_generations(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=3)
    mgr.save(_state_for(1), 1)
    # a dead writer's uncommitted leftovers at a NEWER step
    os.makedirs(mgr.gen_dir(9), exist_ok=True)
    with open(os.path.join(mgr.gen_dir(9), "shard-0.npz"), "wb") as f:
        f.write(b"half a shar")
    assert mgr.latest() == 1
    # a committed generation whose file was truncated after commit is unsound
    mgr.save(_state_for(5), 5)
    shard = os.path.join(mgr.gen_dir(5), "shard-0.npz")
    with open(shard, "wb") as f:
        f.write(b"stub")
    assert mgr.latest() == 1


def test_gc_keeps_last_k_and_reaps_dead_attempts(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=2)
    mgr.save(_state_for(1), 1)
    # fake an uncommitted older attempt, then commit two more generations
    os.makedirs(mgr.gen_dir(2), exist_ok=True)
    with open(os.path.join(mgr.gen_dir(2), "junk"), "w") as f:
        f.write("dead writer droppings")
    mgr.save(_state_for(3), 3)
    mgr.save(_state_for(4), 4)
    assert mgr.all_steps() == [3, 4]
    assert not os.path.exists(mgr.gen_dir(1))   # beyond keep_last_k
    assert not os.path.exists(mgr.gen_dir(2))   # dead uncommitted attempt
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path / "c2"), keep_last_k=0)


def test_manager_async_save_commits_and_reraises_once(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"), keep_last_k=2)
    mgr.save(_state_for(7), 7, async_save=True)
    mgr.wait()
    assert mgr.latest() == 7
    # failure path: a file squatting on the generation dir name makes the
    # background writer die; wait() must re-raise exactly once and clear
    # the pending slot
    with open(mgr.gen_dir(8), "w") as f:
        f.write("not a directory")
    mgr.save(_state_for(8), 8, async_save=True)
    with pytest.raises(RuntimeError, match="async checkpoint generation"):
        mgr.wait()
    mgr.wait()                     # second wait: error already consumed
    assert mgr.latest() == 7       # step-8 never committed
