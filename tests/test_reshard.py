"""Live resharding (distributed/reshard.py): planner + executor + ladder.

Four legs:
1. plan equivalence — for a sweep of (src mesh, spec) -> (dst mesh, spec)
   pairs, the resharded per-owner state is BITWISE equal to a fresh
   full-checkpoint reload sliced to the same destination shards, and the
   planner's wire volume is strictly below the naive full-gather volume on
   the pure shrink/grow cases (the reason to reshard at all);
2. executor liveness — every blocking edge is bounded: a peer that never
   shows up becomes the typed ReshardTimeout within the budget, never a
   hang (complements the site x mode coverage in test_no_hang.py);
3. the fallback ladder — lost bricks come back from the last committed
   generation (partial restore), an unfinishable reshard falls back to a
   full restore, and without a checkpoint the failure is typed;
4. chaos — SIGKILL a real peer process at each reshard.* faultpoint site
   mid-reshard over a real TCPStore: the survivor must end on correct
   state (resharded or restored from the last committed generation)
   within a bounded deadline, and never hang. Quick representative in
   tier-1; the full kill matrix is `slow`.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import chaos
from paddle_tpu.distributed import reshard as rs
from paddle_tpu.distributed.ckpt_manager import CheckpointManager
from paddle_tpu.distributed.store import create_master_store
from paddle_tpu.utils.deadline import ReshardTimeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEMBER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist_workers", "reshard_member.py")


def _full_state(seed=7):
    rng = np.random.RandomState(seed)
    return {
        "linear.weight": rng.randn(12, 8).astype(np.float32),
        "linear.bias": rng.randn(8).astype(np.float32),
        "opt.moment1": rng.randn(12, 8).astype(np.float32),
        "opt.moment2": rng.randn(12, 8).astype(np.float32),
        "loss_scale": np.asarray(32768.0, np.float32),
        "steps": rng.randint(0, 1 << 30, (6,)).astype(np.int64),
    }


def _specs(src_spec_by_name, dst_spec_by_name, full):
    return {
        name: rs.ParamSpec(arr.shape, arr.dtype,
                           src_spec_by_name.get(name, ()),
                           dst_spec_by_name.get(name, ()))
        for name, arr in full.items()
    }


def _shard_states(full, params, mesh, which="src"):
    states = {}
    for o in mesh.owners:
        local = {}
        for name, arr in full.items():
            spec = getattr(params[name], which)
            idx = rs.shard_index(arr.shape, spec, mesh, o)
            local[name] = np.ascontiguousarray(
                arr[tuple(slice(lo, hi) for lo, hi in idx)])
        states[o] = local
    return states


# the parameter sweep: (label, src members/shape, dst members/shape,
# src specs, dst specs, expect_cheaper_than_naive)
SHARD2D = {"linear.weight": ("dp", None), "opt.moment1": ("dp", None),
           "opt.moment2": ("dp", None)}
MP_COLS = {"linear.weight": (None, "mp"), "opt.moment1": (None, "mp"),
           "opt.moment2": (None, "mp")}
GRID = {"linear.weight": ("dp", "mp"), "opt.moment1": ("dp", "mp"),
        "opt.moment2": ("dp", "mp")}
SWEEP = [
    ("shrink_dp3_to_dp2", (["a", "b", "c"], None), (["a", "b"], None),
     SHARD2D, SHARD2D, True),
    ("grow_dp2_to_dp3", (["a", "b"], None), (["a", "b", "c"], None),
     SHARD2D, SHARD2D, True),
    ("shrink_dp4_to_dp1", (["a", "b", "c", "d"], None), (["a"], None),
     SHARD2D, SHARD2D, True),
    ("relayout_rows_to_cols", (["a", "b"], None), (["a", "b"], None),
     SHARD2D, {**MP_COLS,
               "linear.weight": (None, "dp"), "opt.moment1": (None, "dp"),
               "opt.moment2": (None, "dp")}, True),
    ("2d_grid_to_dp", (["a", "b", "c", "d"], {"dp": 2, "mp": 2}),
     (["a", "b"], None), GRID, SHARD2D, True),
    ("replicated_shrink_is_free", (["a", "b", "c"], None), (["a", "b"], None),
     {}, {}, True),
]


@pytest.mark.parametrize("label,src_m,dst_m,src_s,dst_s,cheaper",
                         SWEEP, ids=[c[0] for c in SWEEP])
def test_plan_equivalence_bitwise_vs_checkpoint_reload(
        tmp_path, label, src_m, dst_m, src_s, dst_s, cheaper):
    """Resharded state == fresh full-checkpoint reload, bitwise, for every
    (src mesh, spec) -> (dst mesh, spec) pair; wire volume < naive
    full-gather on the shrink/grow cases."""
    full = _full_state()
    src = rs.MeshSpec.from_members(src_m[0], src_m[1])
    dst = rs.MeshSpec.from_members(dst_m[0], dst_m[1])
    params = _specs(src_s, dst_s, full)
    states = _shard_states(full, params, src, "src")

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)

    out, plan = rs.redistribute(src, dst, params, states, budget=30.0)
    assert plan.recoverable_from_peers
    # the oracle: a fresh FULL reload of the committed generation, sliced
    # to each dst owner's shard — reshard must match it bit for bit
    reloaded = {name: np.zeros_like(arr) for name, arr in full.items()}
    mgr.restore(reloaded, 1)
    for o in dst.owners:
        for name in full:
            idx = plan.dst_index(name, o)
            want = reloaded[name][tuple(slice(lo, hi) for lo, hi in idx)]
            got = out[o][name]
            assert got.dtype == want.dtype, (label, o, name)
            assert got.tobytes() == np.ascontiguousarray(want).tobytes(), \
                f"{label}: {name} @ {o} not bitwise-equal"
    if cheaper:
        assert plan.bytes_moved < plan.naive_bytes, \
            (label, plan.bytes_moved, plan.naive_bytes)


def test_replicated_shrink_moves_zero_bytes():
    """Survivors already hold replicated arrays in full — a pure shrink
    must reuse them locally and move nothing."""
    full = _full_state()
    src = rs.MeshSpec.from_members(["a", "b", "c"])
    dst = rs.MeshSpec.from_members(["a", "b"])
    params = _specs({}, {}, full)
    states = _shard_states(full, params, src)
    out, plan = rs.redistribute(src, dst, params, states, budget=30.0)
    assert plan.bytes_moved == 0
    assert plan.bytes_local == sum(a.nbytes for a in full.values()) * 2
    assert np.array_equal(out["b"]["linear.weight"], full["linear.weight"])


def test_grow_new_member_fetches_only_its_shard():
    """dp2 -> dp3 with row sharding: the only wire traffic is what the new
    member needs; incumbents reuse their overlap locally."""
    full = _full_state()
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a", "b", "c"])
    sharded = {"linear.weight": ("dp", None)}
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    out, plan = rs.redistribute(src, dst, params, states, budget=30.0)
    w = full["linear.weight"]
    # 'a' keeps rows 0:4 of its 0:6 — pure local reuse, zero receives;
    # 'b' tops up rows 4:6 from a; 'c' fetches its rows + the replicated
    # arrays it never held. Nothing beyond those needs the wire.
    assert not plan.recvs_for("a")
    b_topup = sum(s.nbytes for s in plan.recvs_for("b"))
    to_c = sum(s.nbytes for s in plan.recvs_for("c"))
    assert to_c > 0 and plan.bytes_moved == to_c + b_topup
    assert np.array_equal(out["c"]["linear.weight"], w[8:12])
    assert np.array_equal(out["b"]["linear.weight"], w[4:8])


def test_sender_choice_balances_across_replica_holders():
    """A brick held by several survivors is fetched from the least-loaded
    one (deterministic): a grow of a replicated array must not hammer one
    donor for every new joiner."""
    full = {"w": np.arange(4096, dtype=np.float32)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a", "b", "c", "d", "e", "f"])
    params = {"w": rs.ParamSpec((4096,), np.float32, (None,), (None,))}
    plan = rs.plan_reshard(src, dst, params)
    senders = {s.src for s in plan.steps}
    assert senders == {"a", "b"}, senders
    loads = {o: sum(s.nbytes for s in plan.steps if s.src == o)
             for o in senders}
    assert loads["a"] == loads["b"], loads


def test_executor_peer_never_arrives_typed_timeout_bounded():
    """The executor's no-hang law: a missing peer costs at most the budget
    and raises the typed ReshardTimeout."""
    full = _full_state()
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    sharded = {"linear.weight": ("dp", None)}
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    plan = rs.plan_reshard(src, dst, params)
    t0 = time.monotonic()
    with pytest.raises(ReshardTimeout):
        rs.execute(plan, "a", states["a"], rs.LocalTransport(),
                   session="t_missing_peer", budget=0.5)
    assert time.monotonic() - t0 < 5.0


def test_plan_digest_mismatch_aborts_before_transfer():
    """Two owners planning from different membership views must fail typed
    at the plan edge — mismatched bricks never move."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b"])
    params = _specs(sharded, sharded, full)
    plan_a = rs.plan_reshard(src, rs.MeshSpec.from_members(["a"]), params)
    plan_b = rs.plan_reshard(src, rs.MeshSpec.from_members(["a", "b"]),
                             params)
    states = _shard_states(full, params, src)
    transport = rs.LocalTransport()
    errs = {}

    def run(plan, owner):
        try:
            rs.execute(plan, owner, states[owner], transport,
                       session="t_digest", budget=5.0)
        except BaseException as e:  # noqa: BLE001 — type asserted below
            errs[owner] = e

    ts = [threading.Thread(target=run, args=(p, o), daemon=True)
          for p, o in ((plan_a, "a"), (plan_b, "b"))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30.0)
    assert not any(t.is_alive() for t in ts), "digest mismatch hung"
    assert any(isinstance(e, rs.ReshardError)
               and "digest mismatch" in str(e) for e in errs.values()), errs


def test_lost_shard_without_ckpt_is_typed_unrecoverable():
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b", "c"])
    dst = rs.MeshSpec.from_members(["a", "b"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    del states["c"]  # c is dead and took rows 8:12 with it
    with pytest.raises(rs.ShardLost):
        rs.redistribute(src, dst, params, states, available={"a", "b"},
                        budget=5.0)


def test_lost_shard_partial_restores_from_committed_generation(tmp_path):
    """The middle rung: only the DEAD node's bricks come from the
    checkpoint; everything else moves peer-to-peer."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None), "opt.moment1": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b", "c"])
    dst = rs.MeshSpec.from_members(["a", "b"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    del states["c"]
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 3)
    rs.reset_reports()
    out, plan = rs.redistribute(src, dst, params, states,
                                available={"a", "b"}, budget=10.0, ckpt=mgr)
    assert plan.lost, "expected lost bricks for the dead node"
    for o in dst.owners:
        for name in full:
            idx = plan.dst_index(name, o)
            want = full[name][tuple(slice(lo, hi) for lo, hi in idx)]
            assert np.array_equal(out[o][name], want), (o, name)
    hows = {r["owner"]: r["how"] for r in rs.reshard_reports()}
    assert "partial-restore" in hows.values(), hows
    # the ckpt supplied ONLY the lost bytes, not a full reload
    rep = [r for r in rs.reshard_reports() if r["how"] == "partial-restore"]
    assert all(0 < r["bytes_from_ckpt"] < r["naive_bytes"] for r in rep)


def test_full_restore_rung_when_peer_dies_mid_reshard(tmp_path):
    """Bottom rung: the reshard itself cannot complete (peer never
    arrives) -> this owner's dst shards are cut from the last committed
    generation; old state untouched on the way down."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 5)
    before = {k: v.copy() for k, v in states["a"].items()}
    plan = rs.plan_reshard(src, dst, params)
    out, how = rs.reshard_or_restore(plan, "a", states["a"],
                                     rs.LocalTransport(),
                                     session="t_full_restore", ckpt=mgr,
                                     budget=0.5)
    assert how == "full-restore"
    assert np.array_equal(out["linear.weight"], full["linear.weight"])
    # input state was never mutated mid-flight
    for k in before:
        assert np.array_equal(states["a"][k], before[k])


def test_departing_sender_full_restore_is_empty_not_valueerror(tmp_path):
    """Review regression: a pure sender (leaving the mesh) whose reshard
    fails must land on the ladder's typed outcome with an EMPTY state —
    not a ValueError from looking itself up in a mesh it left."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)
    plan = rs.plan_reshard(src, dst, params)
    # 'b' only sends; its commit barrier starves because 'a' never runs
    out, how = rs.reshard_or_restore(plan, "b", states["b"],
                                     rs.LocalTransport(),
                                     session="t_departing", ckpt=mgr,
                                     budget=0.5)
    assert how == "full-restore" and out == {}


def test_stateless_rejoiner_never_gets_local_reuse(tmp_path):
    """Review regression: a node that rejoins under the SAME id after a
    lease lapse sits in both meshes but holds NO usable state. The planner
    must not hand it LocalSteps into its empty dict (untyped KeyError);
    its bricks come by transfer from live holders, by checkpoint when it
    was the only holder, or fail TYPED."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    members = ["a", "b", "c"]
    src = rs.MeshSpec.from_members(members)
    dst = rs.MeshSpec.from_members(members)
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    states["c"] = {}                       # rejoiner: same id, empty disk
    plan = rs.plan_reshard(src, dst, params, available={"a", "b"})
    assert not plan.local_for("c"), plan.local_for("c")
    # c's sharded rows had only c as holder -> lost; replicated arrays
    # still arrive from live holders over the wire
    assert plan.lost_for("c")
    assert any(s.dst == "c" for s in plan.steps)
    # without a checkpoint: typed, not KeyError
    with pytest.raises(rs.ShardLost):
        rs.redistribute(src, dst, params, states, available={"a", "b"},
                        budget=5.0)
    # with one: c partial-restores exactly its lost rows
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)
    out, _ = rs.redistribute(src, dst, params, states,
                             available={"a", "b"}, budget=10.0, ckpt=mgr)
    for o in members:
        idx = plan.dst_index("linear.weight", o)
        want = full["linear.weight"][tuple(slice(lo, hi) for lo, hi in idx)]
        assert np.array_equal(out[o]["linear.weight"], want), o


def test_session_for_unique_per_generation():
    """Transport keys are namespaced by session; the store never forgets a
    payload, so each reshard event must get a fresh id — session_for is
    deterministic across participants and distinct across generations
    and rosters."""
    m1 = rs.MeshSpec.from_members(["a", "b"])
    m2 = rs.MeshSpec.from_members(["a", "b", "c"])
    assert rs.session_for(3, m1) == rs.session_for(3, m1)
    assert rs.session_for(3, m1) != rs.session_for(4, m1)
    assert rs.session_for(3, m1) != rs.session_for(3, m2)


def test_rung_agreement_detects_split_ladder(tmp_path):
    """Review regression: a failure racing the last commit marker can put
    one owner on full-restore while peers keep live resharded state. The
    published rung markers make the split DETECTABLE: rung_agreement
    returns full-restore (divergence or a never-reported owner), and
    "reshard" only when every participant kept live state."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a", "b"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)
    plan = rs.plan_reshard(src, dst, params)

    # split: 'b' never shows up -> 'a' full-restores and publishes it
    t_split = rs.LocalTransport()
    _, how = rs.reshard_or_restore(plan, "a", states["a"], t_split,
                                   session="s_split", ckpt=mgr, budget=0.5)
    assert how == "full-restore"
    assert rs.rung_agreement(plan, t_split, session="s_split",
                             budget=0.5) == "full-restore"

    # healthy: both owners reshard -> agreement says keep live state
    t_ok = rs.LocalTransport()
    outs, errs = {}, {}

    def run(o):
        try:
            outs[o] = rs.reshard_or_restore(plan, o, states[o], t_ok,
                                            session="s_ok", ckpt=mgr,
                                            budget=10.0)
        except BaseException as e:  # noqa: BLE001
            errs[o] = e

    ts = [threading.Thread(target=run, args=(o,), daemon=True)
          for o in plan.participants]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30.0)
    assert not errs, errs
    assert all(h == "reshard" for _, h in outs.values()), outs
    assert rs.rung_agreement(plan, t_ok, session="s_ok",
                             budget=5.0) == "reshard"


def test_plan_digest_spelling_independent():
    """Review regression: 'dp' vs ('dp',) vs trailing-None-dropped specs
    plan identically and must DIGEST identically — a spelling difference
    between two nodes (live PartitionSpec vs checkpoint-metadata list
    form) must never force a spurious plan-mismatch abort."""
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    spellings = [
        {"w": rs.ParamSpec((8, 4), np.float32, ("dp", None), ())},
        {"w": rs.ParamSpec((8, 4), np.float32, (["dp"], None), ())},
        {"w": rs.ParamSpec((8, 4), np.float32, ("dp",), (None, None))},
    ]
    digests = {rs.plan_reshard(src, dst, p).digest() for p in spellings}
    assert len(digests) == 1, digests


def test_store_transport_reshard_end_to_end():
    """The real multi-node path: two owners over one TCPStore, shrink
    dp2 -> dp1, bitwise result, server-side bounded waits underneath."""
    full = _full_state()
    sharded = {"linear.weight": ("dp", None), "opt.moment1": ("dp", None)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    params = _specs(sharded, sharded, full)
    states = _shard_states(full, params, src)
    plan = rs.plan_reshard(src, dst, params)
    store = create_master_store()
    # one client per owner, as on a real fleet: a store client serializes
    # its in-flight rpc, so two owners sharing one client would serialize
    # a blocked server-side wait against the peer's publishing set
    from paddle_tpu.distributed.store import TCPStore
    clients = {"a": store,
               "b": TCPStore("127.0.0.1", store.port, is_master=False)}
    try:
        results, errs = {}, {}

        def run(owner):
            try:
                transport = rs.StoreTransport(clients[owner],
                                              prefix="t_e2e")
                results[owner] = rs.execute(plan, owner, states[owner],
                                            transport, budget=30.0,
                                            session="e2e")
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs[owner] = e

        ts = [threading.Thread(target=run, args=(o,), daemon=True)
              for o in plan.participants]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60.0)
        assert not any(t.is_alive() for t in ts), "store reshard hung"
        assert not errs, errs
        for name in full:
            assert np.array_equal(results["a"][name], full[name]), name
    finally:
        clients["b"].stop()
        store.stop()


def test_read_param_partial_reader(tmp_path):
    """CheckpointManager.read_param assembles ONE array (the ladder's
    partial reader) and still rejects torn bytes."""
    full = _full_state()
    mgr = CheckpointManager(str(tmp_path / "c"))
    mgr.save(dict(full), 4)
    got = mgr.read_param("opt.moment2")
    assert np.array_equal(got, full["opt.moment2"])
    with pytest.raises(KeyError):
        mgr.read_param("nope")
    shard = os.path.join(mgr.gen_dir(4), "shard-0.npz")
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(bytes(data))
    from paddle_tpu.distributed.checkpoint import CheckpointCorruptionError
    with pytest.raises(CheckpointCorruptionError):
        mgr.read_param("opt.moment2")


def test_reshard_summary_reports_bytes_and_ladder():
    rs.reset_reports()
    full = {"w": np.arange(64, dtype=np.float32).reshape(8, 8)}
    src = rs.MeshSpec.from_members(["a", "b"])
    dst = rs.MeshSpec.from_members(["a"])
    params = {"w": rs.ParamSpec((8, 8), np.float32, ("dp", None),
                                ("dp", None))}
    states = _shard_states(full, params, src)
    rs.redistribute(src, dst, params, states, budget=10.0)
    import paddle_tpu.profiler as profiler
    text = profiler.reshard_summary()
    assert "reshard" in text and "Naive" in text
    reports = rs.reshard_reports()
    assert reports and reports[-1]["bytes_moved"] < reports[-1]["naive_bytes"]


# ---------------- trainer integration (single-controller leg) ----------------

def test_trainstep_reshard_preserves_state_and_keeps_training():
    """TrainStep.reshard(new_mesh): params/opt state move placements
    bitwise-unchanged, the step re-lowers under the new mesh, and training
    continues (the in-process dp4 -> dp2 shrink)."""
    import paddle_tpu as P
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.trainer import compile_train_step

    try:
        P.seed(0)
        mesh4 = mesh_mod.init_mesh({"dp": 4})
        model = P.nn.Linear(8, 4)
        opt = P.optimizer.SGD(learning_rate=0.1,
                              parameters=model.parameters())

        def loss_fn(m, batch):
            x, y = batch
            return P.nn.functional.mse_loss(m(P.to_tensor(x)),
                                            P.to_tensor(y))

        step = compile_train_step(model, loss_fn, opt, mesh=mesh4)
        rng = np.random.RandomState(0)
        batch = (rng.randn(8, 8).astype(np.float32),
                 rng.randn(8, 4).astype(np.float32))
        step(batch)
        before = [np.asarray(p._value) for p in step._params]
        health_before = float(step._health["loss_scale"])

        mesh2 = mesh_mod.init_mesh({"dp": 2})
        step.reshard(mesh2)
        after = [np.asarray(p._value) for p in step._params]
        for b, a in zip(before, after):
            assert b.tobytes() == a.tobytes(), "reshard changed param bytes"
        assert float(step._health["loss_scale"]) == health_before
        # training continues on the new mesh (fresh lowering, same math)
        loss = step(batch)
        assert np.isfinite(float(loss.numpy()))
        assert step.mesh is mesh2
    finally:
        mesh_mod.set_mesh(None)


def test_elastic_mesh_shape_rederivation():
    from paddle_tpu.parallel.mesh import elastic_mesh_shape

    assert elastic_mesh_shape({"dp": 4, "mp": 2}, 6) == {"dp": 3, "mp": 2}
    assert elastic_mesh_shape({"dp": 2}, 3) == {"dp": 3}
    with pytest.raises(ValueError):
        elastic_mesh_shape({"dp": 4, "mp": 2}, 5)   # mp=2 can't fit 5
    with pytest.raises(ValueError):
        elastic_mesh_shape({"mp": 2}, 4, elastic_axis="dp")


# ---------------- chaos: SIGKILL a peer at every reshard.* site ----------------

def _chaos_case(tmp_path, site):
    """Parent = survivor 'a' (reshard_or_restore + ckpt fallback) over a
    master store; child = peer 'b' armed to SIGKILL at `site`. The law:
    the survivor ends on correct state (resharded or restored from the
    last committed generation) within a bounded deadline; the child died
    at the armed site; nothing hangs."""
    sys.path.insert(0, os.path.dirname(MEMBER))
    try:
        from reshard_member import FULL_W, FULL_B, build_case
    finally:
        sys.path.pop(0)
    src, dst, params, states = build_case()
    full = {"w": FULL_W, "b": FULL_B}
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)
    plan = rs.plan_reshard(src, dst, params)
    store = create_master_store()
    proc = None
    try:
        env = dict(os.environ,
                   PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""),
                   JAX_PLATFORMS="cpu",
                   PT_FAULTPOINT=site,
                   PT_FAULTPOINT_MODE="crash",
                   PT_FAULTPOINT_HITS="1",
                   PT_FAULTPOINT_SKIP="0",
                   PT_TEST_BUDGET="20.0")
        proc = subprocess.Popen(
            [sys.executable, MEMBER, str(store.port), "b"],
            cwd=str(tmp_path), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

        result = {}

        def survivor():
            result["state"], result["how"] = rs.reshard_or_restore(
                plan, "a", states["a"], rs.StoreTransport(store),
                ckpt=mgr, budget=6.0, session="chaos")

        t0 = time.monotonic()
        t = threading.Thread(target=survivor, daemon=True)
        t.start()
        t.join(60.0)
        elapsed = time.monotonic() - t0
        assert not t.is_alive(), \
            f"{site}: survivor still blocked after 60s — reshard hung"
        assert elapsed < 30.0, f"{site}: unbounded downtime ({elapsed:.1f}s)"
        # survivor landed on correct full state, by reshard or by ladder
        assert result["how"] in ("reshard", "partial-restore",
                                 "full-restore"), result
        assert np.array_equal(result["state"]["w"], FULL_W), site
        assert np.array_equal(result["state"]["b"], FULL_B), site

        out, err = proc.communicate(timeout=60)
        assert proc.returncode == -signal.SIGKILL, (
            f"{site}: peer was supposed to die by SIGKILL at the armed "
            f"site, got rc={proc.returncode}\n{out}\n{err[-2000:]}")
        assert "DONE" not in out, f"{site}: peer ran past the armed site"
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        store.stop()


def test_sites_registered_for_fault_matrix():
    """The reshard.* sites are enumerable via fault_sites(): the site x
    mode matrix (test_no_hang.MATRIX) widens automatically — its coverage
    test fails on any site missing from the matrix."""
    assert {"reshard.plan", "reshard.transfer", "reshard.commit"} <= \
        set(chaos.fault_sites("reshard."))


def test_peer_sigkilled_mid_transfer_survivor_recovers(tmp_path):
    """Quick tier-1 representative: kill the peer at the payload-transfer
    site; the survivor must recover from the committed generation."""
    _chaos_case(tmp_path, "reshard.transfer")


@pytest.mark.slow
@pytest.mark.parametrize("site", ["reshard.plan", "reshard.transfer",
                                  "reshard.commit"])
def test_kill_matrix_every_reshard_site(tmp_path, site):
    """The full kill matrix: a SIGKILL landing at ANY reshard site leaves
    the job completed-on-survivors or recovered-from-commit. Zero hangs."""
    _chaos_case(tmp_path, site)
