"""Elastic training supervisor (distributed/supervisor.py).

Four legs:

1. closed-loop units — a graceful leave shrinks the mesh and the
   surviving state matches the deterministic oracle bitwise; a joiner
   grows the mesh and receives its shards via the planner; epoch fencing
   rejects a worker that missed an epoch; a typed failure under a FULL
   roster propagates instead of being eaten as churn;
2. churn-aware reshard (the PR's fix) — a lease lapsing MID-reshard
   re-plans against survivors within a probe slice instead of burning the
   whole deadline into a generic ReshardTimeout;
3. chaos — a real multi-process supervised dp run with a member
   SIGKILLed at each `supervisor.*` faultpoint site: survivors resume on
   the shrunken mesh within the supervisor deadline, every resumed
   state is bitwise a fresh restore of the SAME committed generation,
   and the stream's global sample prefix replays exactly-once (one
   oracle equality proves both). Quick dp2 -> dp1 representative in
   tier-1; the dp4 -> dp2 site matrix is `slow`;
4. observability — profiler.supervisor_summary() renders the events.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import chaos
from paddle_tpu.distributed import reshard as rs
from paddle_tpu.distributed import supervisor as sv
from paddle_tpu.distributed.ckpt_manager import CheckpointManager
from paddle_tpu.distributed.launch.elastic import ElasticManager
from paddle_tpu.distributed.store import create_master_store
from paddle_tpu.distributed.supervisor import (Supervisor, SupervisedParam,
                                               StaleEpoch)
from paddle_tpu.utils.deadline import Deadline, StoreTimeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEMBER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dist_workers", "supervisor_member.py")

sys.path.insert(0, os.path.dirname(MEMBER))
from supervisor_member import (BATCH, PARAMS, ROWS,  # noqa: E402
                               apply_rank_step, build_stream, full_state,
                               shard_state, step_fn)
sys.path.pop(0)


def _mk_elastic(store, nid, n=4):
    return ElasticManager(store, node_id=nid, np_range=(1, n),
                          heartbeat_interval=0.1, timeout=0.6)


def _mk_sup(store, elastic, mgr, members, nid, **kw):
    kw.setdefault("budget", 20.0)
    kw.setdefault("watch_budget", 20.0)
    kw.setdefault("churn_probe", 1.0)
    state = shard_state(members, nid) if members else {}
    sup = Supervisor(store=store, elastic=elastic, ckpt=mgr, params=PARAMS,
                     state=state, stream=build_stream(),
                     batch_size=BATCH, ckpt_every=1, **kw)
    return sup


# ---------------------------------------------------------------------------
# the deterministic oracle: replay the schedule segment-by-segment from the
# recorded scale events; ONE bitwise equality then proves zero
# committed-progress loss AND exactly-once sample delivery
# ---------------------------------------------------------------------------

def _replay(events, n_steps, initial_members, mgr=None):
    """Returns (full_state, members) after replaying `n_steps` with the
    membership/step/cursor boundaries the events recorded. When `mgr` is
    given, each event's committed generation is restored and asserted
    bitwise against the replayed state at that boundary."""
    full = full_state()
    stream = build_stream()
    members = sorted(initial_members)
    i = 0
    for e in sorted(events, key=lambda ev: ev["epoch"]):
        # run the committed segment up to the event's resume point
        assert e["steps"] >= i or e["how"] == "full-restore", e
        target = int(e["steps"])
        while i < target:
            _sim_step(full, stream, members)
            i += 1
        if e["cursor_pos"] is not None:
            assert stream.pos == e["cursor_pos"], (
                f"epoch {e['epoch']}: resumed cursor {e['cursor_pos']} != "
                f"oracle prefix {stream.pos} — a sample was duplicated or "
                f"lost")
        if mgr is not None and e["generation"] is not None:
            got = {"table": np.zeros((ROWS, 4), np.float32),
                   "w": np.zeros((4,), np.float32)}
            step = mgr.restore(got, int(e["generation"]))
            assert step == int(e["generation"])
            for k in full:
                assert np.array_equal(got[k], full[k]), (
                    f"epoch {e['epoch']}: generation {e['generation']} "
                    f"param {k!r} not bitwise the oracle state")
        members = sorted(e["roster"])
    while i < n_steps:
        _sim_step(full, stream, members)
        i += 1
    return full, members


def _sim_step(full, stream, members):
    n = len(members)
    if stream.pos >= stream.epoch_len():
        stream.roll_epoch()
    take = min(BATCH * n, stream.epoch_len() - stream.pos)
    window = [stream.sample_at(stream.pos + j) for j in range(take)]
    stream.advance(take)
    rows = ROWS // n
    w_new = None
    for r in range(n):
        t, w_new = apply_rank_step(
            full["table"][r * rows:(r + 1) * rows], full["w"],
            window[r::n])
        full["table"][r * rows:(r + 1) * rows] = t
    full["w"] = w_new


def _owner_shards(full, members, nid):
    n = len(members)
    r = sorted(members).index(nid)
    rows = ROWS // n
    return {"table": full["table"][r * rows:(r + 1) * rows],
            "w": full["w"]}


# ---------------------------------------------------------------------------
# closed-loop units (in-process members over one master store)
# ---------------------------------------------------------------------------

def _run_fleet(tmp_path, node_ids, n_steps, fns, joiners=(), budget=20.0):
    """Run one in-process supervised fleet (threads). Returns
    (sups, results, errors)."""
    store = create_master_store()
    els = {nid: _mk_elastic(store, nid) for nid in node_ids}
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep_last_k=16)
    members = sorted(node_ids)
    sups = {nid: _mk_sup(store, els[nid], mgr, members, nid, budget=budget,
                         watch_budget=budget)
            for nid in node_ids}
    results, errors = {}, {}

    def run(nid):
        try:
            sups[nid].bind(len(node_ids), timeout=15.0)
            results[nid] = sups[nid].run(fns[nid], n_steps)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[nid] = e

    threads = {nid: threading.Thread(target=run, args=(nid,), daemon=True)
               for nid in node_ids}
    for t in threads.values():
        t.start()
    for t in threads.values():
        t.join(90.0)
    assert not any(t.is_alive() for t in threads.values()), \
        "supervised fleet hung"
    return sups, results, errors, mgr, store, els


def _stop_fleet(sups, store, els):
    for s in sups.values():
        s.close()
    for e in els.values():
        e.stop()
    store.stop()


def test_graceful_leave_shrinks_and_matches_oracle(tmp_path):
    """dp2 -> dp1: member b leaves after step 3; a detects, commits,
    swaps, resumes, finishes — final state bitwise the deterministic
    oracle, event recorded with the exactly-once cursor."""
    sv.reset_events()

    def fn_a(state, batch, sup):
        return step_fn(state, batch, sup)

    def fn_b(state, batch, sup):
        if sup.steps_done == 2:
            sup.request_stop(leave=True)
        return step_fn(state, batch, sup)

    sups, results, errors, mgr, store, els = _run_fleet(
        tmp_path, ["a", "b"], 6, {"a": fn_a, "b": fn_b})
    try:
        assert not errors, errors
        a = sups["a"]
        assert a.steps_done == 6 and a.roster == ["a"]
        assert len(a.events) == 1
        e = a.events[0]
        assert e["old_size"] == 2 and e["new_size"] == 1
        assert e["generation"] == 3 and e["steps"] == 3
        full, members = _replay(a.events, 6, ["a", "b"], mgr=mgr)
        assert members == ["a"]
        for k in full:
            assert np.array_equal(results["a"][k], full[k]), k
        # the module-level record feeds profiler.supervisor_summary()
        assert any(ev["epoch"] == e["epoch"] for ev in sv.supervisor_events())
    finally:
        _stop_fleet(sups, store, els)


def test_coordinated_drain_typed_event_and_forensics(tmp_path):
    """The coordinated drain is its own TYPED cause end to end: the
    survivor classifies the announced departure as "drain" (no
    failure-detection deadline burned, zero replayed steps — the event
    rides a live rung), the leaver records its own "drained" farewell
    event, and BOTH sides auto-export an incident bundle + trace beside
    the generation directories."""
    sv.reset_events()

    def fn_a(state, batch, sup):
        return step_fn(state, batch, sup)

    def fn_b(state, batch, sup):
        if sup.steps_done == 2:
            sup.request_stop(leave=True)
        return step_fn(state, batch, sup)

    sups, results, errors, mgr, store, els = _run_fleet(
        tmp_path, ["a", "b"], 6, {"a": fn_a, "b": fn_b})
    try:
        assert not errors, errors
        a, b = sups["a"], sups["b"]

        # survivor: exactly one event, typed-distinct from every crash
        assert [e["cause"] for e in a.events] == ["drain"]
        ea = a.events[0]
        # zero replayed steps: the generation the event committed IS the
        # step count at the leave — nothing rolled back, nothing re-run
        assert ea["generation"] == 3 and ea["steps"] == 3
        assert a.steps_done == 6 and a.roster == ["a"]

        # leaver: its own farewell event — it participated in the swap
        # (bricks staged, reshard served) and only then revoked its lease
        eb = b.events[-1]
        assert eb["cause"] == "drain" and eb["how"] == "drained"
        assert eb["state_sha"] is None and eb["roster"] == ["a"]
        assert eb["steps"] == 3

        # per-step sharded commits recorded their accounting
        assert a.commit_stats and all(
            s["owner"] == "a" and s["bytes"] > 0 for s in a.commit_stats)

        # forensics: incident bundle + Chrome-trace export on BOTH sides,
        # beside (never inside) the generation directories
        root = str(tmp_path / "ckpt")
        names = os.listdir(root)
        for nid, ev in (("a", ea), ("b", eb)):
            tag = (f"incident-step{ev['generation']}"
                   f"-epoch{ev['epoch']}-{nid}")
            assert f"{tag}.json" in names, names
            assert f"{tag}.trace.json" in names, names
            with open(os.path.join(root, f"{tag}.json")) as f:
                bundle = json.load(f)
            assert bundle["event"]["cause"] == "drain"
        # the forensics files are invisible to the generation scanner
        assert mgr.latest() == 6

        # zero-dup/zero-lost: the oracle replay equals the survivor
        full, members = _replay(a.events, 6, ["a", "b"], mgr=mgr)
        assert members == ["a"]
        for k in full:
            assert np.array_equal(results["a"][k], full[k]), k

        # the drain-vs-crash split reaches the profiler summary
        import paddle_tpu.profiler as profiler
        text = profiler.supervisor_summary()
        assert "drain" in text and "drained" in text
    finally:
        _stop_fleet(sups, store, els)


def test_rendezvous_key_gc_across_epochs(tmp_path, monkeypatch):
    """Satellite (ISSUE 14): the store must NOT accumulate per-epoch
    rendezvous keys and per-step barrier keys for the life of a run.
    Two scale events (three -> two -> one members, epochs 1 and 2) over
    the inspectable py-fallback store: after the second converged
    rendezvous every epoch-1 {ns}/rdv/* key and the rdvwin record are
    deleted, barrier keys are held to the rolling window, and only the
    CURRENT epoch's rendezvous record remains."""
    from paddle_tpu.distributed import store as store_mod

    class _NoNative:
        @staticmethod
        def get_lib():
            return None

    monkeypatch.setattr(store_mod, "native", _NoNative)
    sv.reset_events()

    def fn_a(state, batch, sup):
        return step_fn(state, batch, sup)

    def fn_b(state, batch, sup):
        if sup.steps_done == 1:
            sup.request_stop(leave=True)
        return step_fn(state, batch, sup)

    def fn_c(state, batch, sup):
        if sup.steps_done == 3:
            sup.request_stop(leave=True)
        return step_fn(state, batch, sup)

    sups, results, errors, mgr, store, els = _run_fleet(
        tmp_path, ["a", "b", "c"], 6,
        {"a": fn_a, "b": fn_b, "c": fn_c})
    try:
        assert not errors, errors
        a = sups["a"]
        assert a.steps_done == 6 and a.roster == ["a"]
        assert a.epoch == 2 and len(a.events) == 2
        kv = store._py_server._kv
        keys = sorted(kv)
        rdv1 = [k for k in keys if k.startswith("sup/rdv/1/")]
        assert rdv1 == [], f"epoch-1 rendezvous keys leaked: {rdv1}"
        assert "sup/rdvwin/1" not in keys
        bar = [k for k in keys if k.startswith("sup/bar/")]
        # rolling + window GC: nothing of the multi-member epochs survives
        # (epoch 2 runs a one-member roster — no barrier at all)
        assert len(bar) == 0, f"barrier keys leaked: {bar}"
        # the CURRENT epoch's record stays (fencing/adoption still needs
        # it); that is a constant, not life-of-run growth
        rdv2 = [k for k in keys if k.startswith("sup/rdv/2/")
                or k == "sup/rdvwin/2"]
        assert len(rdv2) <= 3, rdv2
        # and the run still ends bitwise the oracle — GC changed nothing
        full, members = _replay(a.events, 6, ["a", "b", "c"], mgr=mgr)
        assert members == ["a"]
        for k in full:
            assert np.array_equal(results["a"][k], full[k]), k
    finally:
        _stop_fleet(sups, store, els)


def test_grow_joiner_receives_shards_via_planner(tmp_path):
    """dp1 -> dp2 grow: a runs alone; j joins with joining=True and NO
    state — its shards arrive via the planner; both finish on dp2 with
    the oracle state."""
    store = create_master_store()
    els = {"a": _mk_elastic(store, "a")}
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep_last_k=16)
    sup_a = _mk_sup(store, els["a"], mgr, ["a"], "a")
    results, errors = {}, {}

    def slow_step(state, batch, sup):
        time.sleep(0.4)  # keep a mid-run while the joiner arrives
        return step_fn(state, batch, sup)

    def run_a():
        try:
            sup_a.bind(1, timeout=10.0)
            results["a"] = sup_a.run(slow_step, 6)
        except BaseException as e:  # noqa: BLE001
            errors["a"] = e

    ta = threading.Thread(target=run_a, daemon=True)
    ta.start()
    time.sleep(1.0)  # let a complete a couple of dp1 steps
    els["j"] = _mk_elastic(store, "j")
    sup_j = Supervisor(store=store, elastic=els["j"], ckpt=mgr,
                       params=PARAMS, state={}, stream=build_stream(),
                       batch_size=BATCH, ckpt_every=1, budget=20.0,
                       watch_budget=20.0, churn_probe=1.0, joining=True)

    def run_j():
        try:
            results["j"] = sup_j.run(step_fn, 6)
        except BaseException as e:  # noqa: BLE001
            errors["j"] = e

    tj = threading.Thread(target=run_j, daemon=True)
    tj.start()
    ta.join(60.0)
    tj.join(60.0)
    try:
        assert not ta.is_alive() and not tj.is_alive(), "grow fleet hung"
        assert not errors, errors
        assert sup_a.roster == ["a", "j"] and sup_j.roster == ["a", "j"]
        assert sup_a.events and sup_a.events[0]["new_size"] == 2
        full, members = _replay(sup_a.events, 6, ["a"], mgr=mgr)
        assert members == ["a", "j"]
        for nid in ("a", "j"):
            want = _owner_shards(full, members, nid)
            for k in want:
                assert np.array_equal(results[nid][k], want[k]), (nid, k)
    finally:
        sup_a.close()
        sup_j.close()
        for e in els.values():
            e.stop()
        store.stop()


def test_epoch_fencing_rejects_stale_worker(tmp_path):
    """A worker whose supervision epoch is behind the committed counter
    (it missed events while suspended) gets the typed StaleEpoch from the
    rendezvous — it may not rejoin mid-swap."""
    store = create_master_store()
    el = _mk_elastic(store, "a")
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    sup = _mk_sup(store, el, mgr, ["a"], "a")
    try:
        sup.bind(1, timeout=10.0)
        # the fleet moved two epochs past this worker
        store.add(f"{sup.ns}/epoch", 2)
        with pytest.raises(StaleEpoch, match="may not rejoin mid-swap"):
            sup._rendezvous(Deadline(5.0, what="test"))
    finally:
        sup.close()
        el.stop()
        store.stop()


def test_typed_failure_with_full_roster_propagates(tmp_path):
    """The classifier law: a typed timeout escaping a step while the
    lease roster is INTACT is a real infrastructure failure — it must
    propagate, never be eaten as churn."""
    store = create_master_store()
    el = _mk_elastic(store, "a")
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    sup = _mk_sup(store, el, mgr, ["a"], "a")
    try:
        sup.bind(1, timeout=10.0)

        def bad_step(state, batch, s):
            raise StoreTimeout("a wedged dependency", 1.0)

        with pytest.raises(StoreTimeout, match="wedged"):
            sup.run(bad_step, 2)
        assert sup.events == []
    finally:
        sup.close()
        el.stop()
        store.stop()


def test_stream_must_be_global_order():
    class _FakeStream:
        world_size = 2

    with pytest.raises(ValueError, match="world_size=1"):
        Supervisor(store=None, elastic=type(
            "E", (), {"node_id": "a", "_ttl_ms": 1000})(), ckpt=None,
            stream=_FakeStream())


def test_supervisor_summary_renders():
    import paddle_tpu.profiler as profiler

    sv.reset_events()
    assert "no scale events" in profiler.supervisor_summary()
    sv._register_event({
        "node": "a", "epoch": 3, "cause": "lease-lapse", "how": "reshard",
        "generation": 7, "steps": 7, "roster": ["a", "b"], "old_size": 3,
        "new_size": 2, "bytes_moved": 4096, "detect_latency_s": 0.01,
        "downtime_s": 0.5, "state_sha": "ff", "cursor_pos": 28})
    text = profiler.supervisor_summary()
    assert "lease-lapse" in text and "3->2" in text and "reshard" in text
    sv.reset_events()


def test_attached_train_step_reshards_at_resume(tmp_path):
    """The single-controller leg: with a TrainStep attached, every resume
    calls TrainStep.reshard(train_mesh(n)) FIRST — device state moves
    placement-only (bitwise) and the step re-lowers at the new shape."""
    import paddle_tpu as P
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.trainer import compile_train_step

    store = create_master_store()
    els = {nid: _mk_elastic(store, nid) for nid in ("a", "b")}
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep_last_k=16)
    prev_mesh = mesh_mod.get_mesh()
    try:
        P.seed(0)
        jmesh = mesh_mod.init_mesh({"dp": 2})
        model = P.nn.Linear(8, 4)
        opt = P.optimizer.SGD(learning_rate=0.1,
                              parameters=model.parameters())

        def loss_fn(m, b):
            x, y = b
            return P.nn.functional.mse_loss(m(P.to_tensor(x)),
                                            P.to_tensor(y))

        tstep = compile_train_step(model, loss_fn, opt, mesh=jmesh)
        rng = np.random.RandomState(0)
        dbatch = (rng.randn(8, 8).astype(np.float32),
                  rng.randn(8, 4).astype(np.float32))
        tstep(dbatch)
        before = [np.asarray(p._value).tobytes() for p in tstep._params]

        sups = {}
        for nid in ("a", "b"):
            sups[nid] = Supervisor(
                store=store, elastic=els[nid], ckpt=mgr, params=PARAMS,
                state=shard_state(["a", "b"], nid), stream=build_stream(),
                batch_size=BATCH, ckpt_every=1, budget=20.0,
                watch_budget=20.0, churn_probe=1.0,
                train_step=tstep if nid == "a" else None,
                train_mesh=lambda n: mesh_mod.init_mesh({"dp": n}))
        results, errors = {}, {}

        def fleet_fn(nid):
            def fn(state, batch, sup):
                if nid == "b" and sup.steps_done == 2:
                    sup.request_stop(leave=True)
                return step_fn(state, batch, sup)
            return fn

        threads = {}
        for nid in ("a", "b"):
            def run(nid=nid):
                try:
                    sups[nid].bind(2, timeout=15.0)
                    results[nid] = sups[nid].run(fleet_fn(nid), 5)
                except BaseException as e:  # noqa: BLE001
                    errors[nid] = e
            threads[nid] = threading.Thread(target=run, daemon=True)
            threads[nid].start()
        for t in threads.values():
            t.join(90.0)
        assert not any(t.is_alive() for t in threads.values())
        assert not errors, errors
        assert sups["a"].events, "no scale event recorded"
        # the attached step moved to the dp1 mesh, values bitwise
        assert dict(tstep.mesh.shape) == {"dp": 1}
        after = [np.asarray(p._value).tobytes() for p in tstep._params]
        assert before == after, "TrainStep.reshard changed param bytes"
        # and it still trains at the new shape
        loss = tstep(dbatch)
        assert np.isfinite(float(loss.numpy()))
    finally:
        for s in sups.values():
            s.close()
        for e in els.values():
            e.stop()
        store.stop()
        mesh_mod.set_mesh(prev_mesh)


# ---------------------------------------------------------------------------
# churn-aware reshard (the in-flight lease-lapse fix)
# ---------------------------------------------------------------------------

def test_churn_replan_beats_the_deadline(tmp_path):
    """Three owners plan a relayout; c's lease lapses mid-reshard (it
    never serves its payloads). The OLD ladder burned the whole budget
    into a generic ReshardTimeout; the churn-aware ladder re-plans
    against survivors within ~a probe slice and completes with c's
    bricks from the committed generation."""
    full = full_state()
    src = rs.MeshSpec.from_members(["a", "b", "c"])
    dst = rs.MeshSpec.from_members(["a", "b"])
    params = {n: p.param_spec() for n, p in PARAMS.items()}
    states = {}
    for o in src.owners:
        states[o] = {n: np.ascontiguousarray(
            full[n][tuple(slice(lo, hi) for lo, hi in rs.shard_index(
                p.shape, p.src, src, o))])
            for n, p in params.items()}
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(dict(full), 1)
    transport = rs.LocalTransport()

    t_lapse = time.monotonic() + 0.8

    def alive_fn():
        # the store-side lease truth: c lapses 0.8s into the reshard
        return ["a", "b"] if time.monotonic() > t_lapse \
            else ["a", "b", "c"]

    BUDGET = 30.0
    results, errors = {}, {}

    def run(owner):
        try:
            results[owner] = rs.reshard_or_restore_churn(
                src, dst, params, owner, states[owner], transport,
                session="churn-test", alive_fn=alive_fn, ckpt=mgr,
                budget=BUDGET, probe=1.0)
        except BaseException as e:  # noqa: BLE001
            errors[owner] = e

    t0 = time.monotonic()
    threads = [threading.Thread(target=run, args=(o,), daemon=True)
               for o in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(25.0)
    elapsed = time.monotonic() - t0
    assert not any(t.is_alive() for t in threads), "churn reshard hung"
    assert not errors, errors
    # completed in ~one probe slice + replan, NOT the whole budget
    assert elapsed < BUDGET / 2, f"burned the deadline: {elapsed:.1f}s"
    # b's destination rows include the dead c's shard -> partial restore
    # from the committed generation; a's come entirely from survivors
    assert results["a"][1] == "reshard"
    assert results["b"][1] == "partial-restore"
    for owner in ("a", "b"):
        out, how = results[owner]
        want = {n: full[n][tuple(slice(lo, hi) for lo, hi in
                                 rs.shard_index(p.shape, p.dst, dst, owner))]
                for n, p in params.items()}
        for k in want:
            assert np.array_equal(out[k], want[k]), (owner, k)


# ---------------------------------------------------------------------------
# chaos: SIGKILL a member at every supervisor.* site, mid-run
# ---------------------------------------------------------------------------

def _spawn_member(port, nid, out_dir, n_steps, n_members, extra_env=None):
    env = dict(os.environ,
               PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""),
               JAX_PLATFORMS="cpu",
               PT_TEST_BUDGET="20.0")
    for k in ("PT_FAULTPOINT", "PT_FAULTPOINT_MODE", "PT_CRASHPOINT",
              "PT_SUP_LEAVE_STEP"):
        env.pop(k, None)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, MEMBER, str(port), nid, str(out_dir),
         str(n_steps), str(n_members)],
        cwd=str(out_dir), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _run_parent_member(store, out_dir, n_steps, n_members, budget=20.0):
    """The surviving member 'a', run in-process (like the reshard chaos
    parent). Returns (sup, result_dict_or_error)."""
    el = _mk_elastic(store, "a", n=n_members)
    mgr = CheckpointManager(os.path.join(str(out_dir), "ckpt"),
                            keep_last_k=16)
    sup = _mk_sup(store, el, mgr, None, "a", budget=budget,
                  watch_budget=budget)
    outcome = {}

    def run():
        try:
            members = sup.bind(n_members, timeout=30.0)
            sup.state = shard_state(members, "a")
            outcome["state"] = sup.run(step_fn, n_steps)
        except BaseException as e:  # noqa: BLE001
            outcome["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return sup, el, mgr, t, outcome


def _chaos_case(tmp_path, site, n_members, n_steps=6, leave=None,
                armed=("c",), arm_skip="0"):
    """Parent = survivor 'a' in-process; children = the other members.
    `armed` children SIGKILL at `site` (after `arm_skip` unarmed
    traversals); `leave` maps a child id to its scripted graceful-leave
    step (the event that puts the armed child INSIDE a scale event when
    the site is not supervisor.detect)."""
    sv.reset_events()
    chaos.reset_hits()
    ids = ["a", "b", "c", "d"][:n_members]
    store = create_master_store()
    procs = {}
    sup = el = None
    try:
        for nid in ids[1:]:
            extra = {}
            if nid in armed:
                extra = {"PT_FAULTPOINT": site, "PT_FAULTPOINT_MODE": "crash",
                         "PT_FAULTPOINT_HITS": "1",
                         "PT_FAULTPOINT_SKIP": arm_skip}
            if leave and nid in leave:
                extra["PT_SUP_LEAVE_STEP"] = str(leave[nid])
            procs[nid] = _spawn_member(store.port, nid, tmp_path, n_steps,
                                       n_members, extra)
        sup, el, mgr, t, outcome = _run_parent_member(
            store, tmp_path, n_steps, n_members)
        t0 = time.monotonic()
        t.join(120.0)
        elapsed = time.monotonic() - t0
        assert not t.is_alive(), f"{site}: survivor hung after 120s"
        assert "error" not in outcome, (site, outcome.get("error"))
        assert sup.steps_done == n_steps

        # the armed children died by SIGKILL at the armed site
        for nid in armed:
            out, err = procs[nid].communicate(timeout=60)
            assert procs[nid].returncode == -signal.SIGKILL, (
                f"{site}: {nid} expected SIGKILL, got "
                f"rc={procs[nid].returncode}\n{out}\n{err[-2000:]}")
            assert "DONE" not in out, f"{site}: {nid} ran past the site"
        # scripted leavers AND uninvolved members exit clean
        for nid in ids[1:]:
            if nid in armed:
                continue
            out, err = procs[nid].communicate(timeout=90)
            assert procs[nid].returncode == 0 and "DONE" in out, (
                f"{site}: member {nid} rc={procs[nid].returncode}"
                f"\n{out}\n{err[-2000:]}")

        # every event's resumed state is bitwise a fresh restore of the
        # SAME committed generation, cut to this owner's new shards
        for e in sup.events:
            got = {"table": np.zeros((ROWS, 4), np.float32),
                   "w": np.zeros((4,), np.float32)}
            mgr.restore(got, int(e["generation"]))
            mesh = rs.MeshSpec.from_members(e["roster"])
            shards = {
                n: got[n][tuple(slice(lo, hi) for lo, hi in rs.shard_index(
                    p.param_spec().shape, p.param_spec().dst, mesh, "a"))]
                for n, p in PARAMS.items()}
            assert sv._state_sha(shards) == e["state_sha"], (
                f"{site}: epoch {e['epoch']} resumed state is NOT bitwise "
                f"the fresh restore of generation {e['generation']}")

        # the oracle replay: zero committed-progress loss + exactly-once
        # delivery, one bitwise equality (includes per-event cursor and
        # generation-content checks)
        full, members = _replay(sup.events, n_steps, ids, mgr=mgr)
        assert sorted(sup.roster) == members
        want = _owner_shards(full, members, "a")
        for k in want:
            assert np.array_equal(outcome["state"][k], want[k]), (site, k)
        assert elapsed < 120.0
        return sup
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        try:
            if sup is not None:
                sup.close()
            if el is not None:
                el.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
        store.stop()


def test_sites_registered_for_fault_matrix():
    """The supervisor.* sites are enumerable via fault_sites(): the site
    x mode matrix (test_no_hang.MATRIX) widens automatically."""
    assert {"supervisor.detect", "supervisor.rendezvous",
            "supervisor.swap", "supervisor.resume",
            "supervisor.drain"} <= set(chaos.fault_sites("supervisor."))
    assert {"ckpt.shard_staged", "ckpt.receipts"} <= \
        set(chaos.fault_sites("ckpt."))


def test_member_sigkilled_at_detect_survivor_resumes_dp1(tmp_path):
    """Quick tier-1 representative: dp2 -> dp1. Child b dies by SIGKILL
    at its first supervisor.detect poll; a detects the lapse, commits,
    swaps to dp1 and finishes bitwise the oracle."""
    sup = _chaos_case(tmp_path, "supervisor.detect", n_members=2,
                      armed=("b",))
    assert sup.roster == ["a"]
    assert sup.events and sup.events[-1]["new_size"] == 1


@pytest.mark.slow
@pytest.mark.parametrize("site", ["supervisor.detect",
                                  "supervisor.rendezvous",
                                  "supervisor.swap", "supervisor.resume",
                                  "supervisor.drain",
                                  "ckpt.shard_staged", "ckpt.receipts"])
def test_kill_matrix_dp4_to_dp2_every_supervisor_site(tmp_path, site):
    """The acceptance matrix: a real dp4 run; b leaves gracefully at step
    2 (the scale event), c SIGKILLs at the armed site (for detect: at its
    first poll, before any event; for the ckpt.* sites: inside its very
    first sharded commit — shard staged but receipt never filed, or
    wedged in the marker wait; for drain: announcing its OWN coordinated
    departure at step 3, dying mid-goodbye). Survivors a+d converge on
    dp2 within the supervisor deadline; resumed params bitwise a fresh
    restore of the same committed generation; the stream's global prefix
    replays exactly-once (oracle equality)."""
    leave = {"b": 2}
    skip = "0"
    if site == "supervisor.drain":
        # the armed child only reaches the drain site by draining itself
        leave = {"b": 2, "c": 3}
    if site == "ckpt.shard_staged":
        # skip the INITIAL commit's traversal: dying there loses c's
        # dp-shard with no committed generation to roll back to —
        # genuinely unrecoverable by design. Killed at its step-1 commit
        # instead, the survivors roll back to the initial generation and
        # full-restore (the ladder's bottom rung).
        skip = "1"
    sup = _chaos_case(tmp_path, site, n_members=4, n_steps=6,
                      leave=leave, armed=("c",), arm_skip=skip)
    assert sorted(sup.roster) == ["a", "d"], sup.roster
