"""Pipeline schedule tests: 1F1B + interleaved VPP vs single-device reference
(VERDICT r1 item 2). Mirrors the reference's loss-parity test pattern for
pipeline_parallel.py:387 (1F1B) and :1016 (interleave)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.pipeline import (
    activation_stash_microbatches,
    spmd_pipeline,
    spmd_pipeline_1f1b,
    stack_stage_params,
)


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


H = 8          # hidden
MB = 2         # rows per microbatch


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _slice_stage_fn(params, x):
    """gpipe/1f1b stage bodies receive their [L/pp, ...] slice (here L==pp)."""
    return _stage_fn({k: v[0] for k, v in params.items()}, x)


def _make_params(n_stages, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(n_stages, H, H).astype(np.float32) * 0.5),
        "b": jnp.asarray(rng.randn(n_stages, H).astype(np.float32) * 0.1),
    }


def _sequential(params, x_mb, n_stages):
    out = []
    for m in range(x_mb.shape[0]):
        h = x_mb[m]
        for s in range(n_stages):
            h = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, h)
        out.append(h)
    return jnp.stack(out)


def test_gpipe_matches_sequential():
    dist.init_parallel_env({"pp": 4})
    mesh = mesh_mod.get_mesh()
    M = 8
    params = _make_params(4)
    x = jnp.asarray(np.random.RandomState(1).randn(M, MB, H).astype(np.float32))
    out = spmd_pipeline(_slice_stage_fn, params, x, n_microbatches=M,
                        mesh=mesh, schedule="gpipe")
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x, 4)),
                               rtol=1e-5, atol=1e-6)


def test_vpp_matches_sequential():
    """v=2 chunks per rank over pp=4 -> 8 virtual stages."""
    dist.init_parallel_env({"pp": 4})
    mesh = mesh_mod.get_mesh()
    S, v = 4, 2
    L = S * v
    M = 8   # must divide pp
    flat = _make_params(L)
    # arrange [L, ...] -> [v, S, ...]: element [c, i] = virtual stage c*S+i
    params = {k: a.reshape(v, S, *a.shape[1:]) for k, a in flat.items()}
    x = jnp.asarray(np.random.RandomState(2).randn(M, MB, H).astype(np.float32))
    out = spmd_pipeline(_stage_fn, params, x, n_microbatches=M, mesh=mesh,
                        schedule="vpp", n_virtual=v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(flat, x, L)),
                               rtol=1e-5, atol=1e-6)


def test_vpp_grads_match_sequential():
    """AD through the interleaved schedule gives the same parameter grads."""
    dist.init_parallel_env({"pp": 4})
    mesh = mesh_mod.get_mesh()
    S, v, M = 4, 2, 4
    L = S * v
    flat = _make_params(L, seed=5)
    x = jnp.asarray(np.random.RandomState(3).randn(M, MB, H).astype(np.float32))
    tgt = jnp.asarray(np.random.RandomState(4).randn(M, MB, H).astype(np.float32))

    def loss_pipe(p_flat):
        p = {k: a.reshape(v, S, *a.shape[1:]) for k, a in p_flat.items()}
        y = spmd_pipeline(_stage_fn, p, x, n_microbatches=M, mesh=mesh,
                          schedule="vpp", n_virtual=v)
        return jnp.mean((y - tgt) ** 2)

    def loss_seq(p_flat):
        return jnp.mean((_sequential(p_flat, x, L) - tgt) ** 2)

    l1, g1 = jax.value_and_grad(loss_pipe)(flat)
    l2, g2 = jax.value_and_grad(loss_seq)(flat)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in flat:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-4, atol=1e-5)


def _head_loss(head, y, labels):
    return jnp.mean((y @ head["wo"] - labels) ** 2)


@pytest.mark.parametrize("variant", ["fused", "compact"])
def test_1f1b_loss_and_grads_match_sequential(variant):
    """The manually-scheduled 1F1B program must reproduce plain AD exactly —
    in both the fused-round and the tick-switch variants."""
    dist.init_parallel_env({"pp": 4})
    mesh = mesh_mod.get_mesh()
    S, M = 4, 8
    params = _make_params(S, seed=7)
    head = {"wo": jnp.asarray(
        np.random.RandomState(8).randn(H, 3).astype(np.float32) * 0.5)}
    x = jnp.asarray(np.random.RandomState(9).randn(M, MB, H).astype(np.float32))
    labels = jnp.asarray(
        np.random.RandomState(10).randn(M, MB, 3).astype(np.float32))

    loss, g_stage, g_head, dx = spmd_pipeline_1f1b(
        _slice_stage_fn, _head_loss, params, head, x, labels,
        n_microbatches=M, mesh=mesh, variant=variant)

    def ref_loss(params, head, x):
        y = _sequential(params, x, S)
        losses = [_head_loss(head, y[m], labels[m]) for m in range(M)]
        return sum(losses) / M

    ref, ref_grads = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_stage[k]),
                                   np.asarray(ref_grads[0][k]),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_head["wo"]),
                               np.asarray(ref_grads[1]["wo"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_grads[2]),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("variant", ["fused", "compact"])
def test_1f1b_more_microbatches_than_stages(variant):
    """M >> S exercises the steady-state throttle + ring-buffer reuse."""
    dist.init_parallel_env({"pp": 2})
    mesh = mesh_mod.get_mesh()
    S, M = 2, 10
    params = _make_params(S, seed=11)
    head = {"wo": jnp.asarray(
        np.random.RandomState(12).randn(H, 2).astype(np.float32))}
    x = jnp.asarray(np.random.RandomState(13).randn(M, MB, H).astype(np.float32))
    labels = jnp.asarray(
        np.random.RandomState(14).randn(M, MB, 2).astype(np.float32))

    loss, g_stage, g_head, dx = spmd_pipeline_1f1b(
        _slice_stage_fn, _head_loss, params, head, x, labels,
        n_microbatches=M, mesh=mesh, variant=variant)

    def ref_loss(params, head, x):
        y = _sequential(params, x, S)
        return sum(_head_loss(head, y[m], labels[m]) for m in range(M)) / M

    ref, ref_grads = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        params, head, x)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_stage[k]),
                                   np.asarray(ref_grads[0][k]),
                                   rtol=1e-4, atol=1e-5)


def test_1f1b_activation_memory_bound():
    """1F1B stashes min(2S-1, M) (fused) / min(S, M) (compact) microbatch
    inputs; GPipe's AD residuals hold M+S-1 — the schedules' memory
    advantage (pipeline_parallel.py 1F1B rationale)."""
    S, M = 4, 16
    assert activation_stash_microbatches("1f1b", S, M) == 7
    assert activation_stash_microbatches("1f1b_compact", S, M) == 4
    assert activation_stash_microbatches("gpipe", S, M) == 19
    assert (activation_stash_microbatches("1f1b", S, M)
            < activation_stash_microbatches("gpipe", S, M))


def test_1f1b_no_redundant_compute():
    """VERDICT r2 weak #3 regression: every 1F1B tick used to execute BOTH a
    masked forward and a full vjp (~2x gpipe's FLOPs). The switch-based
    compact schedule runs one unit per tick, so the whole-program analyzed
    FLOPs must be clearly BELOW gpipe's fwd+AD-bwd program, not above it.
    (Pinned to 'compact': XLA cost_analysis sums conditional branches, so
    the fused variant's edge conds over-count — its check is the wall-time
    measurement in tools/schedule_bench.py.)"""
    dist.init_parallel_env({"pp": 4})
    mesh = mesh_mod.get_mesh()
    S, M = 4, 8
    params = _make_params(S, seed=21)
    head = {"wo": jnp.asarray(
        np.random.RandomState(22).randn(H, 3).astype(np.float32))}
    x = jnp.asarray(np.random.RandomState(23).randn(M, MB, H).astype(np.float32))
    labels = jnp.asarray(
        np.random.RandomState(24).randn(M, MB, 3).astype(np.float32))

    def f1b(params, head, x, labels):
        return spmd_pipeline_1f1b(_slice_stage_fn, _head_loss, params, head,
                                  x, labels, n_microbatches=M, mesh=mesh,
                                  variant="compact")

    def gpipe(params, head, x, labels):
        def loss(params, head):
            y = spmd_pipeline(_slice_stage_fn, params, x, n_microbatches=M,
                              mesh=mesh, schedule="gpipe")
            return sum(_head_loss(head, y[m], labels[m]) for m in range(M)) / M
        return jax.value_and_grad(loss, argnums=(0, 1))(params, head)

    def flops(fn):
        c = jax.jit(fn).lower(params, head, x, labels).compile().cost_analysis()
        c = c[0] if isinstance(c, list) else c
        return float(c["flops"])

    assert flops(f1b) < 0.8 * flops(gpipe)


def test_schedule_tradeoff_prune_rule():
    """The measured gpipe-vs-1f1b tradeoff steers the auto-tuner: the
    fused-round 1F1B is faster AND smaller-stash than gpipe
    (SCHEDULE_BENCH.json), so gpipe is dominated whenever a pipeline exists
    and 1f1b is pure cost when none does."""
    from paddle_tpu.distributed.auto_tuner.prune import (
        prune_by_schedule_tradeoff)
    tuner = dict(hbm_bytes=0.6e9, num_params=50e6, global_batch_size=32,
                 seq_length=2048, hidden_size=4096)
    base = dict(dp_degree=1, mp_degree=1, pp_degree=4, micro_batches=8)
    # pipeline present: gpipe dominated, 1f1b kept
    assert prune_by_schedule_tradeoff(tuner, dict(base, schedule="gpipe"))
    assert not prune_by_schedule_tradeoff(tuner, dict(base, schedule="1f1b"))
    # no pipeline: 1f1b machinery is pure cost
    flat = dict(base, pp_degree=1)
    assert prune_by_schedule_tradeoff(tuner, dict(flat, schedule="1f1b"))
    assert not prune_by_schedule_tradeoff(tuner, dict(flat, schedule="gpipe"))
