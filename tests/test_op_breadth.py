"""Round-2 op breadth battery (VERDICT r1 item 8) — numpy-reference OpTest
checks (eager + compiled) and numeric-grad spot checks for the new
tensor/linalg/index/signal ops."""
import numpy as np
import pytest

import paddle_tpu as P
from op_test import OpTest

rng = np.random.RandomState(42)


def T(a):
    return P.to_tensor(np.asarray(a))


# ---------- math ----------

def test_logcumsumexp():
    x = rng.randn(3, 5).astype("f")
    OpTest.check_output(lambda t: P.logcumsumexp(t, axis=1), [x],
                        lambda v: np.log(np.cumsum(np.exp(v), axis=1)),
                        rtol=1e-4, atol=1e-5)
    OpTest.check_grad(lambda t: P.logcumsumexp(t, axis=1), [x.astype("d")])


def test_diff_trapezoid():
    x = rng.randn(4, 6).astype("f")
    OpTest.check_output(lambda t: P.diff(t, axis=1), [x],
                        lambda v: np.diff(v, axis=1))
    y = rng.rand(5).astype("f")
    OpTest.check_output(lambda t: P.trapezoid(t), [y], np.trapezoid,
                        rtol=1e-5, atol=1e-6)
    OpTest.check_output(
        lambda t: P.cumulative_trapezoid(t), [y],
        lambda v: np.cumsum((v[1:] + v[:-1]) / 2.0), rtol=1e-5, atol=1e-6)


def test_frexp_ldexp():
    x = np.array([0.5, 8.0, -3.0, 0.0], "f")
    m, e = P.frexp(T(x))
    mr, er = np.frexp(x)
    np.testing.assert_allclose(m.numpy(), mr, rtol=1e-6)
    np.testing.assert_array_equal(e.numpy(), er)
    OpTest.check_output(lambda a, b: P.ldexp(a, b),
                        [np.array([1.5, 2.0], "f"), np.array([2, 3])],
                        lambda a, b: np.ldexp(a, b))


def test_special_fns():
    x = rng.rand(6).astype("f") + 0.5
    from scipy import special as sp
    OpTest.check_output(lambda t: P.gammaln(t), [x], sp.gammaln,
                        rtol=1e-4, atol=1e-5)
    y = rng.rand(6).astype("f") + 0.5
    OpTest.check_output(lambda a, b: P.gammainc(a, b), [x, y], sp.gammainc,
                        rtol=1e-4, atol=1e-5)
    OpTest.check_output(lambda t: P.polygamma(t, 1), [x],
                        lambda v: sp.polygamma(1, v), rtol=1e-3, atol=1e-4)


def test_renorm():
    x = rng.randn(3, 4, 2).astype("f")
    out = P.renorm(T(x), p=2.0, axis=1, max_norm=1.0).numpy()
    for j in range(4):
        n = np.linalg.norm(out[:, j, :])
        assert n <= 1.0 + 1e-4


def test_add_n_rank_shape():
    xs = [rng.randn(2, 3).astype("f") for _ in range(3)]
    out = P.add_n([T(a) for a in xs])
    np.testing.assert_allclose(out.numpy(), sum(xs), rtol=1e-6)
    assert int(P.rank(T(xs[0])).numpy()) == 2
    np.testing.assert_array_equal(P.shape(T(xs[0])).numpy(), [2, 3])
    assert P.is_floating_point(T(xs[0])) and not P.is_integer(T(xs[0]))
    assert not P.is_empty(T(xs[0])).numpy()


def test_inverse_dist_cdist():
    a = rng.randn(3, 3).astype("f") + 3 * np.eye(3, dtype="f")
    OpTest.check_output(lambda t: P.inverse(t), [a], np.linalg.inv,
                        rtol=1e-3, atol=1e-4)
    x, y = rng.randn(4, 3).astype("f"), rng.randn(5, 3).astype("f")
    ref = np.linalg.norm(x[:, None] - y[None], axis=-1)
    OpTest.check_output(lambda u, v: P.cdist(u, v), [x, y], lambda u, v: ref,
                        rtol=1e-4, atol=1e-5)
    OpTest.check_output(lambda u, v: P.dist(u, v, 2.0),
                        [x[:4], y[:4]],
                        lambda u, v: np.linalg.norm((u - v).ravel()),
                        rtol=1e-5, atol=1e-6)


def test_nan_aggregations():
    x = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 7.0]], "f")
    np.testing.assert_allclose(P.nanmedian(T(x)).numpy(), np.nanmedian(x))
    np.testing.assert_allclose(
        P.nanquantile(T(x), 0.5, axis=1).numpy(), np.nanquantile(x, 0.5, axis=1))


def test_search_set_ops():
    seq = np.array([1.0, 3.0, 5.0, 7.0], "f")
    v = np.array([0.5, 3.0, 6.0, 9.0], "f")
    OpTest.check_output(lambda a, b: P.bucketize(b, a), [seq, v],
                        lambda a, b: np.searchsorted(a, b))
    OpTest.check_output(lambda a, b: P.digitize(b, a), [seq, v],
                        lambda a, b: np.digitize(b, a))
    x = np.array([1, 2, 3, 4])
    t = np.array([2, 4, 8])
    np.testing.assert_array_equal(P.isin(T(x), T(t)).numpy(),
                                  np.isin(x, t))


def test_vander_tensordot_multiplex():
    x = np.array([1.0, 2.0, 3.0], "f")
    OpTest.check_output(lambda t: P.vander(t), [x], lambda v: np.vander(v))
    a, b = rng.randn(2, 3, 4).astype("f"), rng.randn(4, 5).astype("f")
    OpTest.check_output(lambda u, v: P.tensordot(u, v, axes=1), [a, b],
                        lambda u, v: np.tensordot(u, v, axes=1),
                        rtol=1e-4, atol=1e-5)
    c1 = np.array([[1.0, 2.0], [3.0, 4.0]], "f")
    c2 = np.array([[10.0, 20.0], [30.0, 40.0]], "f")
    idx = np.array([[1], [0]])
    out = P.multiplex([T(c1), T(c2)], T(idx)).numpy()
    np.testing.assert_allclose(out, [[10.0, 20.0], [3.0, 4.0]])


# ---------- indexing / manipulation ----------

def test_index_add_fill_put():
    x = np.zeros((4, 3), "f")
    idx = np.array([0, 2])
    val = rng.randn(2, 3).astype("f")
    ref = x.copy()
    ref[idx] += val
    OpTest.check_output(lambda a, i, v: P.index_add(a, i, 0, v),
                        [x, idx, val], lambda a, i, v: ref)
    out = P.index_fill(T(x), T(idx), 0, 5.0).numpy()
    assert (out[0] == 5.0).all() and (out[1] == 0.0).all()
    # index_put with accumulate
    y = np.zeros(5, "f")
    out = P.index_put(T(y), [T(np.array([1, 1, 3]))],
                      T(np.array([1.0, 2.0, 3.0], "f")), accumulate=True)
    np.testing.assert_allclose(out.numpy(), [0, 3, 0, 3, 0])
    # grads flow through index_add
    OpTest.check_grad(lambda a, i, v: P.index_add(a, i, 0, v),
                      [x.astype("d"), idx, val.astype("d")], wrt=(0, 2))


def test_masked_scatter():
    x = np.zeros(6, "f")
    m = np.array([1, 0, 1, 1, 0, 0], bool)
    v = np.array([9.0, 8.0, 7.0, 6.0], "f")
    out = P.masked_scatter(T(x), T(m), T(v)).numpy()
    np.testing.assert_allclose(out, [9, 0, 8, 7, 0, 0])


def test_split_family():
    x = np.arange(24).reshape(4, 3, 2)
    outs = P.vsplit(T(x), 2)
    np.testing.assert_array_equal(outs[1].numpy(), x[2:])
    outs = P.hsplit(T(np.arange(8).reshape(2, 4)), 2)
    np.testing.assert_array_equal(outs[0].numpy(), [[0, 1], [4, 5]])
    outs = P.tensor_split(T(np.arange(7)), 3)
    assert [o.shape[0] for o in outs] == [3, 2, 2]


def test_take_unfold_unflatten_view():
    x = np.arange(12).reshape(3, 4)
    np.testing.assert_array_equal(
        P.take(T(x), T(np.array([0, 5, -1])), mode="wrap").numpy(),
        np.take(x, [0, 5, -1], mode="wrap"))
    y = np.arange(8.0, dtype="f")
    out = P.unfold(T(y), 0, 4, 2).numpy()
    np.testing.assert_allclose(out, [[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]])
    out = P.unflatten(T(np.arange(6.0)), 0, [2, 3]).numpy()
    assert out.shape == (2, 3)
    assert P.view(T(np.arange(6)), [3, 2]).shape == [3, 2]
    assert P.view_as(T(np.arange(6)), T(np.zeros((2, 3)))).shape == [2, 3]
    out = P.crop(T(np.arange(16).reshape(4, 4)), shape=[2, 2],
                 offsets=[1, 1]).numpy()
    np.testing.assert_array_equal(out, [[5, 6], [9, 10]])
    assert P.tolist(T(np.arange(3))) == [0, 1, 2]


def test_complex_family():
    x = rng.randn(3, 2).astype("f")
    c = P.as_complex(T(x))
    np.testing.assert_allclose(c.numpy(), x[..., 0] + 1j * x[..., 1],
                               rtol=1e-6)
    back = P.as_real(c).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-6)
    r = np.array([1.0, 2.0], "f")
    t = np.array([0.0, np.pi / 2], "f")
    out = P.polar(T(r), T(t)).numpy()
    np.testing.assert_allclose(out, r * np.exp(1j * t), rtol=1e-5, atol=1e-6)


def test_histogramdd():
    x = rng.rand(50, 2).astype("f")
    h = P.histogramdd(T(x), bins=4)
    ref_h, ref_e = np.histogramdd(x, bins=4)
    np.testing.assert_allclose(h[0].numpy(), ref_h)


# ---------- linalg ----------

def test_lu_unpack_reconstructs():
    a = rng.randn(5, 5).astype("f")
    lu_, piv = P.linalg.lu(T(a))
    Pm, L, U = P.linalg.lu_unpack(lu_, piv)
    rec = Pm.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)


# ---------- signal ----------

def test_stft_istft_roundtrip():
    x = rng.randn(2, 400).astype("f")
    win = np.hanning(128).astype("f")
    S = P.signal.stft(T(x), n_fft=128, hop_length=64, window=T(win))
    assert S.shape == [2, 65, 7]
    y = P.signal.istft(S, n_fft=128, hop_length=64, window=T(win),
                       length=400).numpy()
    np.testing.assert_allclose(y[:, 64:-80], x[:, 64:-80], rtol=1e-3,
                               atol=1e-4)


def test_frame_overlap_add_inverse():
    x = rng.randn(256).astype("f")
    f = P.signal.frame(T(x), 64, 64)  # non-overlapping
    assert f.shape == [64, 4]
    y = P.signal.overlap_add(f, 64).numpy()
    np.testing.assert_allclose(y, x, rtol=1e-6)


def test_stft_differentiable():
    x = rng.randn(200).astype("f")
    t = T(x)
    t.stop_gradient = False
    S = P.signal.stft(t, n_fft=64, hop_length=32)
    loss = P.as_real(S).square().sum() if hasattr(P, "square") else \
        (P.as_real(S) * P.as_real(S)).sum()
    loss.backward()
    assert t.grad is not None and np.isfinite(t.grad.numpy()).all()
