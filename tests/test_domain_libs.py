"""Domain library tests: distribution, sparse, quantization, audio,
geometric, text (viterbi), incubate.asp — the SURVEY.md §2.7 domain-lib row."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn


# ---------------- distribution ----------------

def test_distribution_normal_moments_and_grad():
    from paddle_tpu import distribution as D
    from scipy import stats

    P.seed(0)
    n = D.Normal(1.0, 2.0)
    s = n.sample((4000,))
    assert abs(float(s.numpy().mean()) - 1.0) < 0.2
    assert abs(float(s.numpy().std()) - 2.0) < 0.2
    np.testing.assert_allclose(float(n.log_prob(P.to_tensor(0.3)).numpy()),
                               stats.norm.logpdf(0.3, 1.0, 2.0), rtol=1e-5)
    np.testing.assert_allclose(float(n.entropy().numpy()),
                               stats.norm.entropy(1.0, 2.0), rtol=1e-5)
    np.testing.assert_allclose(float(n.cdf(P.to_tensor(1.5)).numpy()),
                               stats.norm.cdf(1.5, 1.0, 2.0), rtol=1e-5)
    # pathwise gradient through rsample
    mu = P.to_tensor(np.float32(0.5), stop_gradient=False)
    z = D.Normal(mu, 1.0).rsample((16,))
    z.sum().backward()
    np.testing.assert_allclose(float(mu.grad.numpy()), 16.0, rtol=1e-5)


@pytest.mark.parametrize("make,logpdf", [
    (lambda D: (D.Beta(2.0, 3.0), 0.4), lambda s: s.beta.logpdf(0.4, 2, 3)),
    (lambda D: (D.Gamma(2.0, 3.0), 0.7), lambda s: s.gamma.logpdf(0.7, 2, scale=1 / 3)),
    (lambda D: (D.Laplace(0.0, 2.0), 1.0), lambda s: s.laplace.logpdf(1.0, 0, 2)),
    (lambda D: (D.Gumbel(0.0, 1.0), 0.3), lambda s: s.gumbel_r.logpdf(0.3)),
    (lambda D: (D.Cauchy(0.0, 1.0), 0.3), lambda s: s.cauchy.logpdf(0.3)),
    (lambda D: (D.StudentT(5.0, 0.0, 1.0), 0.3), lambda s: s.t.logpdf(0.3, 5)),
    (lambda D: (D.Poisson(3.0), 2.0), lambda s: s.poisson.logpmf(2, 3)),
    (lambda D: (D.Binomial(10, 0.3), 4.0), lambda s: s.binom.logpmf(4, 10, 0.3)),
    (lambda D: (D.Exponential(2.0), 0.5), lambda s: s.expon.logpdf(0.5, scale=0.5)),
    (lambda D: (D.Uniform(0.0, 2.0), 0.5), lambda s: s.uniform.logpdf(0.5, 0, 2)),
])
def test_distribution_log_prob_vs_scipy(make, logpdf):
    from paddle_tpu import distribution as D
    from scipy import stats
    dist, at = make(D)
    np.testing.assert_allclose(float(dist.log_prob(P.to_tensor(at)).numpy()),
                               logpdf(stats), rtol=1e-4)


def test_studentt_rsample_scalar_broadcast():
    """Regression (VERDICT r5 weak #1): StudentT.rsample with SCALAR
    df/loc/scale raised a broadcast error — jax.random.t defaults to
    shape=() and a pre-broadcast df can't shrink back to it. Covers the
    scalar, batched, and scalar+sample-shape corners plus the pathwise
    gradient through loc/scale."""
    from paddle_tpu import distribution as D

    P.seed(7)
    assert D.StudentT(3.0, 0.0, 1.0).rsample().shape == []
    assert D.StudentT(3.0, 0.0, 1.0).rsample((5,)).shape == [5]
    assert D.StudentT([3.0, 4.0], [0.0, 1.0], [1.0, 2.0]).rsample((7,)).shape \
        == [7, 2]
    assert D.StudentT(np.full((2, 3), 5.0), 0.0, 1.0).rsample((4,)).shape \
        == [4, 2, 3]
    # moments at comfortable df: mean -> loc, var -> scale^2 * df/(df-2)
    s = D.StudentT(30.0, 2.0, 1.5).rsample((50000,)).numpy()
    assert abs(s.mean() - 2.0) < 0.05
    assert abs(s.var() - 1.5 ** 2 * 30 / 28) < 0.25
    # reparameterized: gradients flow to loc and scale
    loc = P.to_tensor(np.float32(0.0), stop_gradient=False)
    scale = P.to_tensor(np.float32(1.0), stop_gradient=False)
    z = D.StudentT(4.0, loc, scale).rsample((8,))
    z.sum().backward()
    np.testing.assert_allclose(float(loc.grad.numpy()), 8.0, rtol=1e-5)
    assert scale.grad is not None


def test_distribution_kl_and_transform():
    from paddle_tpu import distribution as D
    from scipy import stats
    kl = D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 2.0))
    np.testing.assert_allclose(float(kl.numpy()),
                               np.log(2) + 2 / 8 - 0.5, rtol=1e-5)
    td = D.TransformedDistribution(D.Normal(0.0, 1.0), [D.ExpTransform()])
    np.testing.assert_allclose(float(td.log_prob(P.to_tensor(1.5)).numpy()),
                               stats.lognorm.logpdf(1.5, 1.0), rtol=1e-5)
    # chain: affine(exp(x)) still invertible
    ch = D.ChainTransform([D.ExpTransform(), D.AffineTransform(1.0, 2.0)])
    x = P.to_tensor(np.float32(0.3))
    y = ch.forward(x)
    np.testing.assert_allclose(float(ch.inverse(y).numpy()), 0.3, rtol=1e-5)


def test_distribution_categorical_dirichlet_mvn():
    from paddle_tpu import distribution as D
    from scipy import stats
    c = D.Categorical(P.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_allclose(float(c.log_prob(P.to_tensor(2)).numpy()),
                               np.log(0.5), rtol=1e-5)
    assert c.sample((100,)).shape == [100]
    d = D.Dirichlet(P.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_allclose(
        float(d.log_prob(P.to_tensor(np.array([0.2, 0.3, 0.5], np.float32))).numpy()),
        stats.dirichlet.logpdf([0.2, 0.3, 0.5], [1, 2, 3]), rtol=1e-5)
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    mvn = D.MultivariateNormal(P.to_tensor(np.zeros(2, np.float32)),
                               covariance_matrix=P.to_tensor(cov))
    np.testing.assert_allclose(
        float(mvn.log_prob(P.to_tensor(np.array([0.3, -0.2], np.float32))).numpy()),
        stats.multivariate_normal.logpdf([0.3, -0.2], np.zeros(2), cov),
        rtol=1e-5)


# ---------------- sparse ----------------

def test_sparse_coo_roundtrip_and_matmul():
    import paddle_tpu.sparse as sp
    dense = np.array([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32)
    indices = np.array([[0, 1, 1], [1, 0, 2]])
    values = np.array([1.0, 2.0, 3.0], np.float32)
    t = sp.sparse_coo_tensor(indices, values, [2, 3])
    np.testing.assert_array_equal(t.to_dense().numpy(), dense)
    assert t.nnz() == 3
    np.testing.assert_array_equal(t.indices().numpy(), indices)

    y = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out = sp.matmul(t, P.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5)

    t2 = sp.to_sparse_coo(P.to_tensor(dense))
    np.testing.assert_array_equal(t2.to_dense().numpy(), dense)
    r = sp.nn.relu(sp.add(t, t))
    np.testing.assert_array_equal(r.to_dense().numpy(), np.maximum(dense * 2, 0))


def test_sparse_csr():
    import paddle_tpu.sparse as sp
    crows = [0, 1, 3]
    cols = [1, 0, 2]
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    t = sp.sparse_csr_tensor(crows, cols, vals, [2, 3])
    dense = np.array([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32)
    np.testing.assert_array_equal(t.to_dense().numpy(), dense)
    assert t.nnz() == 3


# ---------------- quantization ----------------

def test_qat_fake_quant_close_and_trainable():
    from paddle_tpu.quantization import (FakeQuanterWithAbsMaxObserver, QAT,
                                         QuantConfig)

    P.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    q = QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver, weight=None))
    qmodel = q.quantize(model)
    x = P.randn([4, 8])
    y_fp = model(x)
    y_q = qmodel(x)
    assert np.allclose(y_fp.numpy(), y_q.numpy(), atol=0.35), \
        np.abs(y_fp.numpy() - y_q.numpy()).max()
    # gradients flow through STE
    loss = (y_q ** 2).sum()
    loss.backward()
    inner = qmodel[0].inner
    assert inner.weight.grad is not None


def test_ptq_calibrate_freeze():
    from paddle_tpu.quantization import AbsmaxObserver, PTQ, QuantConfig

    model = nn.Sequential(nn.Linear(8, 8))
    p = PTQ(QuantConfig(activation=AbsmaxObserver, weight=None))
    qm = p.quantize(model)
    for _ in range(4):
        qm(P.randn([16, 8]))
    final = p.convert(qm)
    x = P.randn([4, 8])
    out = final(x)
    assert out.shape == [4, 8]
    scale = float(final[0].observer.scale._value[0])
    assert scale > 0.5  # calibrated from randn abs-max


# ---------------- audio ----------------

def test_audio_features_shapes_and_mel():
    from paddle_tpu.audio import features, functional as AF

    sr, n_fft = 16000, 256
    t = np.linspace(0, 1, sr, dtype=np.float32)
    sig = P.to_tensor(np.sin(2 * np.pi * 440 * t)[None, :])
    spec = features.Spectrogram(n_fft=n_fft)(sig)
    assert spec.shape[1] == 1 + n_fft // 2
    # peak bin at 440Hz
    peak = int(np.argmax(spec.numpy()[0].mean(-1)))
    assert abs(peak - round(440 * n_fft / sr)) <= 1
    mel = features.MelSpectrogram(sr=sr, n_fft=n_fft, n_mels=32)(sig)
    assert mel.shape[1] == 32
    mfcc = features.MFCC(sr=sr, n_mfcc=13, n_mels=32, n_fft=n_fft)(sig)
    assert mfcc.shape[1] == 13
    # librosa-style mel conversion sanity
    np.testing.assert_allclose(
        AF.mel_to_hz(AF.hz_to_mel(1000.0)).numpy(), 1000.0, rtol=1e-4)


def test_audio_wav_roundtrip(tmp_path):
    from paddle_tpu.audio import backends
    sr = 8000
    sig = (np.sin(np.linspace(0, 40 * np.pi, sr)) * 0.5).astype(np.float32)
    path = str(tmp_path / "t.wav")
    backends.save(path, P.to_tensor(sig[None, :]), sr)
    loaded, sr2 = backends.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(loaded.numpy()[0], sig, atol=1e-3)
    inf = backends.info(path)
    assert inf.sample_rate == sr and inf.num_frames == sr


# ---------------- geometric ----------------

def test_geometric_segment_and_message_passing():
    import paddle_tpu.geometric as G
    x = P.to_tensor(np.array([[1.0], [2.0], [3.0], [4.0]], np.float32))
    seg = P.to_tensor(np.array([0, 0, 1, 1]))
    np.testing.assert_array_equal(G.segment_sum(x, seg).numpy(), [[3.0], [7.0]])
    np.testing.assert_array_equal(G.segment_mean(x, seg).numpy(), [[1.5], [3.5]])
    np.testing.assert_array_equal(G.segment_max(x, seg).numpy(), [[2.0], [4.0]])
    np.testing.assert_array_equal(G.segment_min(x, seg).numpy(), [[1.0], [3.0]])

    src = P.to_tensor(np.array([0, 1, 2]))
    dst = P.to_tensor(np.array([1, 2, 1]))
    out = G.send_u_recv(x, src, dst, reduce_op="sum")
    np.testing.assert_array_equal(out.numpy(), [[0.0], [4.0], [2.0], [0.0]])
    e = P.to_tensor(np.array([[10.0], [20.0], [30.0]], np.float32))
    out2 = G.send_ue_recv(x, e, src, dst, message_op="add", reduce_op="max")
    np.testing.assert_array_equal(out2.numpy(),
                                  [[0.0], [33.0], [22.0], [0.0]])
    uv = G.send_uv(x, x, src, dst, message_op="mul")
    np.testing.assert_array_equal(uv.numpy(), [[2.0], [6.0], [6.0]])


# ---------------- text / viterbi ----------------

def test_viterbi_decode_matches_bruteforce():
    from paddle_tpu.text import ViterbiDecoder
    rng = np.random.RandomState(0)
    B, T, N = 2, 5, 3
    emis = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
    scores, paths = dec(P.to_tensor(emis))

    # brute force
    import itertools
    for b in range(B):
        best, best_path = -1e9, None
        for path in itertools.product(range(N), repeat=T):
            s = emis[b, 0, path[0]] + sum(
                trans[path[t - 1], path[t]] + emis[b, t, path[t]]
                for t in range(1, T))
            if s > best:
                best, best_path = s, path
        np.testing.assert_allclose(float(scores.numpy()[b]), best, rtol=1e-5)
        assert tuple(paths.numpy()[b]) == best_path


def test_text_dataset_requires_local_file(tmp_path):
    from paddle_tpu.text import UCIHousing
    with pytest.raises(RuntimeError, match="no network egress"):
        UCIHousing()
    f = tmp_path / "housing.data"
    rows = np.random.RandomState(0).randn(10, 14).astype(np.float32)
    np.savetxt(f, rows)
    ds = UCIHousing(data_file=str(f), mode="train")
    x, y = ds[0]
    assert x.shape == (13,) and y.shape == (1,)


# ---------------- asp ----------------

def test_asp_2in4_prune_and_decorate():
    from paddle_tpu.incubate import asp

    P.seed(0)
    model = nn.Linear(16, 8)
    masks = asp.prune_model(model)
    assert masks, "no weights pruned"
    assert asp.check_sparsity(model.weight)
    np.testing.assert_allclose(asp.calculate_density(model.weight), 0.5,
                               atol=0.01)

    opt = asp.decorate(P.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters()))
    x = P.randn([4, 16])
    (model(x) ** 2).sum().backward()
    opt.step()
    # sparsity preserved after the update
    assert asp.check_sparsity(model.weight)
    asp.reset_excluded_layers()


def test_sparse_ops_differentiable():
    """Regression: sparse ops must record on the autograd tape."""
    import paddle_tpu.sparse as sp
    dense = np.array([[0, 1.0], [2.0, 0]], np.float32)
    t = sp.to_sparse_coo(P.to_tensor(dense))
    w = P.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    out = sp.matmul(t, w)
    out.sum().backward()
    assert w.grad is not None
    # d(sum(t @ w))/dw = t^T @ ones: column sums of t
    np.testing.assert_allclose(w.grad.numpy(),
                               np.array([[2.0, 2.0], [1.0, 1.0]]), rtol=1e-5)
    r = sp.nn.relu(sp.multiply(t, t))
    assert r.is_sparse_coo()
    x2 = P.to_tensor(dense, stop_gradient=False)
    out2 = sp.add(sp.to_sparse_coo(P.to_tensor(dense)), x2)
    out2.to_dense().sum().backward()
    assert x2.grad is not None


def test_viterbi_bos_eos_rows():
    """include_bos_eos_tag=True uses last row as start, last col as stop."""
    from paddle_tpu.text import viterbi_decode
    N, T = 3, 4
    rng = np.random.RandomState(1)
    emis = rng.randn(1, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    sc, path = viterbi_decode(P.to_tensor(emis), P.to_tensor(trans),
                              include_bos_eos_tag=True)
    import itertools
    best, best_path = -1e9, None
    for p in itertools.product(range(N), repeat=T):
        s = trans[-1, p[0]] + emis[0, 0, p[0]] + sum(
            trans[p[t - 1], p[t]] + emis[0, t, p[t]] for t in range(1, T))
        s += trans[p[-1], -1]
        if s > best:
            best, best_path = s, p
    np.testing.assert_allclose(float(sc.numpy()[0]), best, rtol=1e-5)
    assert tuple(path.numpy()[0]) == best_path


def test_qat_layer_config_survives_deepcopy():
    """Regression: add_layer_config keyed by identity must survive the
    default non-inplace quantize."""
    from paddle_tpu.quantization import (FakeQuanterWithAbsMaxObserver, QAT,
                                         QuantConfig)
    from paddle_tpu.quantization.qat import QuantedWrapper

    model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    cfg = QuantConfig()
    cfg.add_layer_config(model[0], activation=FakeQuanterWithAbsMaxObserver,
                         weight=None)
    qm = QAT(cfg).quantize(model)  # inplace=False deepcopy
    assert isinstance(qm[0], QuantedWrapper)
    assert not isinstance(qm[1], QuantedWrapper)


def test_audio_8bit_wav(tmp_path):
    from paddle_tpu.audio import backends
    sr = 4000
    sig = (np.sin(np.linspace(0, 20 * np.pi, sr)) * 0.5).astype(np.float32)
    path = str(tmp_path / "t8.wav")
    backends.save(path, P.to_tensor(sig[None, :]), sr, bits_per_sample=8)
    loaded, _ = backends.load(path)
    np.testing.assert_allclose(loaded.numpy()[0], sig, atol=0.02)


def test_segment_ops_under_jit_require_num_segments():
    import jax
    import paddle_tpu.geometric as G

    x = P.to_tensor(np.ones((4, 2), np.float32))
    ids = P.to_tensor(np.array([0, 0, 1, 1]))

    @jax.jit
    def f(v, i):
        return G.segment_sum(P.Tensor(v), P.Tensor(i), num_segments=2)._value

    out = f(x._value, ids._value)
    np.testing.assert_array_equal(np.asarray(out), [[2.0, 2.0], [2.0, 2.0]])

    @jax.jit
    def g(v, i):
        return G.segment_sum(P.Tensor(v), P.Tensor(i))._value

    with pytest.raises(ValueError, match="num_segments"):
        g(x._value, ids._value)
