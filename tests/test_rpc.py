"""paddle.distributed.rpc parity (VERDICT r1 missing #8): in-process agent,
cross-process sync/async calls, worker info, error propagation."""
import multiprocessing as mp
import os
import pickle
import socket
import time

import numpy as np
import pytest

from paddle_tpu.distributed import rpc


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _add(a, b):
    return a + b


def _boom():
    raise ValueError("kaput")


def test_single_worker_rpc_roundtrip():
    rpc.init_rpc("alice", rank=0, world_size=1)
    try:
        assert rpc.rpc_sync("alice", _add, args=(2, 3)) == 5
        fut = rpc.rpc_async("alice", _add, args=(10, 20))
        assert fut.result() == 30
        info = rpc.get_worker_info("alice")
        assert info.name == "alice" and info.rank == 0
        assert rpc.get_current_worker_info().name == "alice"
        assert [w.name for w in rpc.get_all_worker_infos()] == ["alice"]
        with pytest.raises(RuntimeError, match="kaput"):
            rpc.rpc_sync("alice", _boom)
        with pytest.raises(ValueError, match="unknown rpc worker"):
            rpc.rpc_sync("nobody", _add, args=(1, 2))
    finally:
        rpc.shutdown()


def test_unauthenticated_request_rejected():
    """ADVICE r2: the agent must never unpickle an unauthenticated payload
    (pickle deserialization is code execution)."""
    import struct

    hits = []
    rpc.init_rpc("bob", rank=0, world_size=1)
    try:
        info = rpc.get_worker_info("bob")
        payload = pickle.dumps((hits.append, ("pwned",), {}))
        s = socket.create_connection((info.ip, info.port), timeout=5)
        # correct framing, garbage MAC: must be dropped before unpickling
        s.sendall(struct.pack("<Q", len(payload)) + b"\x00" * 32 + payload)
        s.settimeout(2)
        with pytest.raises((socket.timeout, ConnectionError)):
            data = s.recv(1)
            if not data:
                raise ConnectionError("closed without executing")
        s.close()
        assert hits == []
        # the authenticated path still works afterwards
        assert rpc.rpc_sync("bob", _add, args=(1, 2)) == 3
    finally:
        rpc.shutdown()


def _worker(rank, world, port, q):
    try:
        from paddle_tpu.distributed import rpc as r
        name = f"w{rank}"
        r.init_rpc(name, rank=rank, world_size=world,
                   master_endpoint=f"127.0.0.1:{port}")
        try:
            peer = f"w{1 - rank}"
            out = r.rpc_sync(peer, _add, args=(rank * 10, 7))
            q.put((rank, out))
            # numpy payloads cross the wire
            arr = r.rpc_sync(peer, np.arange, args=(4,))
            q.put((rank, arr.tolist()))
        finally:
            r.shutdown()
    except BaseException:  # noqa: BLE001 — surface the traceback to the test
        import traceback
        q.put((rank, "ERROR: " + traceback.format_exc()))
        raise


def test_two_process_rpc():
    port = _free_port()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    ps = [ctx.Process(target=_worker, args=(r, 2, port, q)) for r in range(2)]
    for p in ps:
        p.start()
    results = {}
    for _ in range(4):
        rank, val = q.get(timeout=120)
        assert not (isinstance(val, str) and val.startswith("ERROR")), val
        results.setdefault(rank, []).append(val)
    for p in ps:
        p.join(timeout=120)
        assert p.exitcode == 0
    assert 7 in results[0] and 17 in results[1]
    assert [0, 1, 2, 3] in results[0] and [0, 1, 2, 3] in results[1]


def test_native_transport_in_use():
    """The C++ transport (csrc/runtime.cc RpcServer) must carry RPC when the
    native runtime built — python sockets are only the no-toolchain fallback."""
    from paddle_tpu.distributed.rpc import _NativeRpcServer
    from paddle_tpu.utils import native
    assert native.get_lib() is not None  # toolchain present in CI
    rpc.init_rpc("carol", rank=0, world_size=1)
    try:
        from paddle_tpu.distributed import rpc as rmod
        assert isinstance(rmod._require_state().server, _NativeRpcServer)
        assert rpc.rpc_sync("carol", _add, args=(20, 3)) == 23
        fut = rpc.rpc_async("carol", _add, args=(1, 1))
        assert fut.result() == 2
    finally:
        rpc.shutdown()


def test_python_fallback_interop_with_native_client():
    """Same wire format both ways: a python-transport server must serve the
    native client (and vice versa through the normal path)."""
    from paddle_tpu.distributed import rpc as rmod
    secret = b"s" * 32
    srv = rmod._RpcServer(bind_host="127.0.0.1", secret=secret)
    try:
        import ctypes
        from paddle_tpu.utils import native
        lib = native.get_lib()
        req = pickle.dumps((_add, (2, 5), {}))
        out = ctypes.c_void_p()
        n = lib.pt_rpc_call(b"127.0.0.1", srv.port, secret, len(secret),
                            req, len(req), ctypes.byref(out), 10.0)
        assert n > 0
        status, val = pickle.loads(ctypes.string_at(out, n))
        lib.pt_free(out)
        assert (status, val) == ("ok", 7)
    finally:
        srv.stop()
