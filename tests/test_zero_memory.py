"""Compiled-ZeRO memory proof (VERDICT r4 item 2).

The eager ZeRO stages pay full-model transients per step (documented PERF
NOTE, sharding_optimizer.py); the COMPILED path claims to avoid them by
construction.  These tests make that claim measurable: XLA buffer-assignment
stats (CompiledMemoryStats, per device) of the exact compiled train step
must show

  1. per-device argument bytes tracking  params + opt_state/shard_degree
     (stage 1, dp alias) and  (params + opt_state)/shard_degree  (stage 3,
     explicit 'sharding' axis) at fixed per-device batch, dp in {1, 2, 4};
  2. NO full-size optimizer-state transient: temp bytes do not grow with
     shard degree (a gather-update-scatter implementation would add the
     full unsharded state to temps at dp > 1).

Reference analog: group_sharded_stage3.py:59 claims the same 1/shard-degree
scaling for its GPU param/state sharding; here the compiler's buffer
assignment is the witness, not the wrapper.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as P


def _local_bytes(leaf) -> int:
    """Per-device bytes of one (possibly sharded) jax array."""
    local = leaf.sharding.shard_shape(leaf.shape)
    return int(np.prod(local)) * leaf.dtype.itemsize


def _tree_local_bytes(tree) -> int:
    return sum(_local_bytes(l) for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "sharding"))


def _hybrid_point(dp: int):
    """Build the llama hybrid step on a dp-only mesh with ZeRO stage-1 and
    return (stats, analytic per-device arg estimate, global opt bytes)."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer,
    )
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_hybrid_train_step)
    from paddle_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.init_mesh({"dp": dp}, devices=jax.devices()[:dp])
    P.seed(0)
    cfg = LlamaConfig.tiny(vocab=256, hidden=128, layers=2, heads=4,
                           inter=256)
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-3,
                            parameters=model.parameters())
    opt = DygraphShardingOptimizer(opt)  # stage 1 over the dp alias
    step = build_hybrid_train_step(model, opt, n_microbatches=1, remat=False)

    B = 2 * dp  # fixed per-device batch of 2
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (B, 17))
    batch = {"input_ids": P.to_tensor(ids[:, :-1]),
             "labels": P.to_tensor(ids[:, 1:])}
    stats = step.memory_stats(batch)

    params_local = _tree_local_bytes(step.state["params"])
    opt_local = _tree_local_bytes(step.state["opt"])
    opt_global = sum(l.nbytes for l in
                     jax.tree_util.tree_leaves(step.state["opt"])
                     if hasattr(l, "nbytes"))
    batch_local = sum(v.numpy().nbytes for v in batch.values()) // dp
    expected_args = params_local + opt_local + batch_local
    mesh_mod.clear_mesh() if hasattr(mesh_mod, "clear_mesh") else None
    return stats, expected_args, opt_global, opt_local


@pytest.mark.parametrize("dp", [1, 2, 4])
def test_hybrid_stage1_args_match_buffer_assignment(dp):
    """XLA's per-device argument bytes == params + state/dp + batch (±12%):
    the state REALLY arrives sharded, it is not re-assembled at the jit
    boundary."""
    stats, expected, _, _ = _hybrid_point(dp)
    meas = stats.argument_size_in_bytes
    assert abs(meas - expected) / expected < 0.12, (
        f"dp={dp}: measured arg bytes {meas} vs analytic {expected}")


def test_hybrid_stage1_state_share_scales_inverse_dp():
    """The optimizer-state share of per-device argument bytes scales ~1/dp,
    and temps carry no full-size state transient as dp grows."""
    points = {dp: _hybrid_point(dp) for dp in (1, 2, 4)}
    # per-device state bytes measured from the live sharded pytree
    s1 = points[1][3]
    for dp in (2, 4):
        s = points[dp][3]
        assert abs(s - s1 / dp) / (s1 / dp) < 0.15, (
            f"state bytes at dp={dp}: {s}, want ~{s1 / dp}")
    # buffer-assignment args shrink by at least 60% of the analytic saving
    for dp in (2, 4):
        saved_analytic = s1 - points[dp][3]
        saved_meas = (points[1][0].argument_size_in_bytes
                      - points[dp][0].argument_size_in_bytes)
        assert saved_meas > 0.6 * saved_analytic, (
            f"dp={dp}: args saved {saved_meas} < 60% of analytic "
            f"{saved_analytic}")
    # no full-size state transient: a gather-update-scatter implementation
    # would add the gathered state (s1 - s1/dp bytes) to temps at dp > 1;
    # actual growth must stay well below that (what does grow is collective
    # scratch for the dp grad all-reduce, ~100s of KB here)
    t1 = points[1][0].temp_size_in_bytes
    for dp in (2, 4):
        t = points[dp][0].temp_size_in_bytes
        gathered = s1 - points[dp][3]
        assert t - t1 < 0.5 * gathered, (
            f"dp={dp}: temp bytes grew {t - t1} — at least half a gathered "
            f"full-size state transient ({gathered}B) materialized")


def test_stage3_explicit_sharding_axis_scales_params_and_state():
    """Stage 3 (FSDP) over an EXPLICIT 'sharding' mesh axis (not the dp
    alias): params AND optimizer states arrive 1/n-sharded per device."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        GroupShardedStage3,
    )
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.trainer import compile_train_step

    def build(n):
        mesh_mod.init_mesh({"sharding": n}, devices=jax.devices()[:n])
        P.seed(0)
        model = P.nn.Sequential(
            P.nn.Linear(256, 512), P.nn.ReLU(), P.nn.Linear(512, 256))
        opt = P.optimizer.AdamW(learning_rate=1e-3,
                                parameters=model.parameters())
        model = GroupShardedStage3(model, opt)

        def loss_fn(m, b):
            return P.mean((m(b["x"]) - b["y"]) ** 2)

        step = compile_train_step(model, loss_fn, opt,
                                  batch_spec=("sharding",))
        rng = np.random.RandomState(0)
        B = 4 * n  # fixed per-device batch
        batch = {"x": P.to_tensor(rng.randn(B, 256).astype("f")),
                 "y": P.to_tensor(rng.randn(B, 256).astype("f"))}
        stats = step.memory_stats(batch)
        params_local = sum(_local_bytes(p._value) for p in step._params)
        state_local = _tree_local_bytes(step._opt_state)
        return stats, params_local, state_local

    s1, p1, st1 = build(1)
    s4, p4, st4 = build(4)
    # params and states each shard ~1/4 per device (biases may stay whole)
    assert p4 < 0.30 * p1, f"stage-3 params/device {p4} vs {p1} at n=1"
    assert st4 < 0.30 * st1, f"stage-3 state/device {st4} vs {st1} at n=1"
    # and the compiled argument buffers agree with the pytree accounting
    shrink = (s1.argument_size_in_bytes - s4.argument_size_in_bytes)
    assert shrink > 0.6 * ((p1 - p4) + (st1 - st4)), (
        f"buffer-assignment args shrank {shrink}, want >60% of analytic "
        f"{(p1 - p4) + (st1 - st4)}")
