"""Self-healing compiled train step + in-step dynamic loss scaling.

The laws under test (ISSUE 3 tentpole, leg 2):
- a nan/inf gradient SKIPS that update: the skipped-step counter increments
  and params/opt state stay bit-identical to pre-step;
- steps after the skip match an uninterrupted run exactly (the poisoned
  step has no residue);
- amp.GradScaler's backoff/growth runs INSIDE the jitted step — the scale
  halves on overflow and grows after N good steps without any host sync or
  recompilation — and a scaled run converges like an unscaled one.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.amp import GradScaler
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.trainer import compile_train_step


@pytest.fixture(autouse=True)
def _clean_mesh():
    mesh_mod.set_mesh(None)
    yield
    mesh_mod.set_mesh(None)


def _loss_fn(m, b):
    return P.nn.functional.mse_loss(m(b["x"]), b["y"])


def _make_step(scaler=None, acc=None, seed=3):
    P.seed(seed)
    model = P.nn.Linear(8, 4)
    opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = compile_train_step(model, _loss_fn, opt, accumulate_steps=acc,
                              scaler=scaler)
    return model, step


def _batch(seed, nan=False, batch=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch, 8).astype(np.float32)
    if nan:
        x[0, 0] = np.nan
    y = rng.randn(batch, 4).astype(np.float32)
    return {"x": P.to_tensor(x), "y": P.to_tensor(y)}


def _params(model):
    return {n: np.asarray(p._value) for n, p in model.named_parameters()}


def test_nan_grad_skips_step_params_bit_identical():
    model, step = _make_step()
    step(_batch(0))
    before = _params(model)
    state_before = [
        {k: np.asarray(v) for k, v in st.items()} for st in step._opt_state]

    loss = step(_batch(1, nan=True))
    assert not np.isfinite(float(loss.numpy()))
    assert step.skipped_steps == 1
    after = _params(model)
    for n in before:
        np.testing.assert_array_equal(
            after[n], before[n],
            err_msg=f"param {n} changed on a skipped (nan-grad) step")
    for st_a, st_b in zip(step._opt_state, state_before):
        for k in st_b:
            np.testing.assert_array_equal(np.asarray(st_a[k]), st_b[k])

    # a later clean step applies normally
    step(_batch(2))
    assert step.skipped_steps == 1
    assert any(not np.array_equal(_params(model)[n], before[n])
               for n in before)


def test_post_skip_steps_match_uninterrupted_run():
    model_a, step_a = _make_step(seed=5)
    step_a(_batch(0))
    step_a(_batch(1, nan=True))          # skipped
    step_a(_batch(2))
    step_a(_batch(3))

    model_b, step_b = _make_step(seed=5)
    step_b(_batch(0))
    step_b(_batch(2))
    step_b(_batch(3))

    pa, pb = _params(model_a), _params(model_b)
    for n in pa:
        np.testing.assert_array_equal(
            pa[n], pb[n],
            err_msg=f"poisoned step left residue in {n}")
    assert step_a.skipped_steps == 1 and step_b.skipped_steps == 0


def test_gradscaler_backoff_and_growth_inside_compiled_step():
    scaler = GradScaler(init_loss_scaling=1024.0, incr_ratio=2.0,
                        decr_ratio=0.5, incr_every_n_steps=2,
                        decr_every_n_nan_or_inf=1)
    model, step = _make_step(scaler=scaler)

    step(_batch(0))
    assert step.loss_scale == 1024.0      # 1 good step: no growth yet
    step(_batch(1))
    assert step.loss_scale == 2048.0      # growth after incr_every=2
    jitted = step._jitted

    step(_batch(2, nan=True))             # overflow: backoff + skip
    assert step.loss_scale == 1024.0
    assert step.skipped_steps == 1
    assert step._jitted is jitted         # same compiled program throughout

    # good-step streak restarts after the overflow
    step(_batch(3))
    assert step.loss_scale == 1024.0
    step(_batch(4))
    assert step.loss_scale == 2048.0

    # device-side scale flows back into the scaler object on request
    step.sync_scaler()
    assert scaler._scale == 2048.0


def test_loss_scale_growth_is_capped():
    """With tiny gradients the overflow signal never bounds growth — the
    scale must saturate at MAX_LOSS_SCALE, not double its way to inf
    (inf is unrecoverable: every later step would skip forever)."""
    from paddle_tpu.parallel.trainer import MAX_LOSS_SCALE

    scaler = GradScaler(init_loss_scaling=MAX_LOSS_SCALE / 4,
                        incr_every_n_steps=1)
    model, step = _make_step(scaler=scaler)
    for i in range(5):   # uncapped this would reach MAX*8
        step(_batch(i))
    assert step.loss_scale == MAX_LOSS_SCALE
    assert step.skipped_steps == 0   # scaled grads stayed finite


def test_scaled_run_matches_unscaled_run():
    """Scale/unscale must be value-neutral on finite data — even across a
    growth event — so a scaled run's losses and params track an unscaled
    run's to fp tolerance."""
    scaler = GradScaler(init_loss_scaling=256.0, incr_every_n_steps=2)
    model_s, step_s = _make_step(scaler=scaler, seed=9)
    model_u, step_u = _make_step(seed=9)

    for i in range(5):
        ls = float(step_s(_batch(i)).numpy())
        lu = float(step_u(_batch(i)).numpy())
        np.testing.assert_allclose(ls, lu, rtol=1e-5)
    assert step_s.loss_scale > 256.0      # growth actually happened
    ps, pu = _params(model_s), _params(model_u)
    for n in ps:
        np.testing.assert_allclose(ps[n], pu[n], rtol=1e-5, atol=1e-6)


def test_loss_scale_unaffected_by_global_norm_clip():
    """Regression: the clip branch's grad-rescale factor must not leak into
    the dynamic loss-scale update (a `scale` name collision once collapsed
    the loss scale to the clip ratio on every step)."""
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    scaler = GradScaler(init_loss_scaling=4096.0, incr_every_n_steps=100)
    P.seed(13)
    model = P.nn.Linear(8, 4)
    opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters(),
                          grad_clip=ClipGradByGlobalNorm(0.01))
    step = compile_train_step(model, _loss_fn, opt, scaler=scaler)
    for i in range(3):
        step(_batch(i))
    # no overflow, incr_every not reached: the scale must still be the init
    assert step.loss_scale == 4096.0
    # and a nan step still halves it from there, not from the clip ratio
    step(_batch(9, nan=True))
    assert step.loss_scale == 2048.0 and step.skipped_steps == 1


def test_nan_in_one_microbatch_skips_whole_accumulated_step():
    """Gradient merge: the finite flag is computed over the MERGED grads, so
    one poisoned micro-batch skips the whole accumulated update."""
    model, step = _make_step(acc=2)
    step(_batch(0, batch=8))
    before = _params(model)
    step(_batch(1, nan=True, batch=8))    # nan lands in micro-batch 0
    assert step.skipped_steps == 1
    after = _params(model)
    for n in before:
        np.testing.assert_array_equal(after[n], before[n])
