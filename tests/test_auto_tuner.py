"""Auto-tuner (VERDICT r1 missing #6): grid search with prune rules over
hybrid-parallel configs, history recording, and a real measured tune() over
the compiled LLaMA step on the 8-device CPU mesh."""
import os

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, HistoryRecorder, candidate_space, prune)
from paddle_tpu.parallel import mesh as mesh_mod


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def test_prune_rules():
    tuner_cfg = {"num_devices": 8, "num_attention_heads": 4, "num_layers": 4,
                 "global_batch_size": 8, "vocab_size": 64}
    # wrong product of degrees
    assert prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                             "sharding_degree": 1}, [])
    # mp doesn't divide heads
    assert prune(tuner_cfg, {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
                             "sharding_degree": 1}, [])
    # pp doesn't divide layers
    assert prune(tuner_cfg, {"dp_degree": 1, "mp_degree": 1, "pp_degree": 8,
                             "sharding_degree": 1}, [])
    # valid
    assert not prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2,
                                 "pp_degree": 2, "sharding_degree": 1,
                                 "micro_batches": 2}, [])
    # OOM history prunes smaller micro-batch counts
    hist = [{"cfg": {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                     "sharding_degree": 1, "micro_batches": 4},
             "metric": None, "error": "oom"}]
    assert prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                             "sharding_degree": 1, "micro_batches": 2}, hist)


def test_grid_search_exhausts_and_dedups():
    tuner_cfg = {"num_devices": 4, "num_attention_heads": 4, "num_layers": 4,
                 "global_batch_size": 8,
                 "micro_batches": [1, 2], "use_recompute": [True],
                 "amp": [False]}
    t = AutoTuner(tuner_cfg)
    seen = set()
    while True:
        cfg = t.search_once()
        if cfg is None:
            break
        key = tuple(sorted(cfg.items()))
        assert key not in seen
        seen.add(key)
        t.record(cfg, metric=float(len(seen)))
        degrees = (cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"]
                   * cfg["sharding_degree"])
        assert degrees == 4
    assert len(seen) > 3
    best = t.get_best()
    assert best["metric"] == float(len(seen))


def test_recorder_roundtrip(tmp_path):
    r = HistoryRecorder()
    r.add_cfg({"dp_degree": 2}, metric=10.0)
    r.add_cfg({"dp_degree": 4}, metric=20.0)
    r.add_cfg({"dp_degree": 8}, error="oom")
    assert r.get_best()["cfg"]["dp_degree"] == 4
    p = str(tmp_path / "hist.json")
    r.store_history(p)
    r2 = HistoryRecorder()
    r2.load_history(p)
    assert len(r2.history) == 3
    r.store_history(str(tmp_path / "hist.csv"))
    assert os.path.getsize(str(tmp_path / "hist.csv")) > 0


def test_tune_measures_real_steps():
    """End-to-end: tune the tiny LLaMA step over a small space on the CPU
    mesh and get a best config with a real throughput metric."""
    from paddle_tpu.distributed.auto_tuner import measure_llama_step
    from paddle_tpu.models import LlamaConfig

    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, inter=64)
    tuner_cfg = {
        "num_devices": 8,
        "num_attention_heads": cfg.num_attention_heads,
        "num_layers": cfg.num_hidden_layers,
        "hidden_size": cfg.hidden_size,
        "vocab_size": cfg.vocab_size,
        "global_batch_size": 8,
        "dp_degree": [2, 8],
        "mp_degree": [1, 4],
        "pp_degree": [1],
        "sharding_degree": [1],
        "micro_batches": [1],
        "use_recompute": [False],
        "amp": [False],
    }
    t = AutoTuner(tuner_cfg)
    best = t.tune(measure_llama_step(cfg, global_batch_size=8, seq_len=8,
                                     n_steps=2, warmup=1), max_trials=4)
    assert best is not None and best["metric"] > 0
    tried = [h for h in t.recorder.history if h["metric"] is not None]
    assert len(tried) >= 2


class TestCostModel:
    """Analytic cost model (VERDICT r4 item 7; reference
    auto_parallel/static/cost/ + planner_v2.py plan ranking)."""

    MODEL = dict(num_hidden_layers=4, hidden_size=64,
                 intermediate_size=128, vocab_size=64)

    def test_scaling_properties(self):
        from paddle_tpu.distributed.auto_tuner import estimate

        base = dict(dp_degree=1, mp_degree=1, pp_degree=1,
                    use_recompute=False, micro_batches=1)
        e0 = estimate(self.MODEL, base, 8, 16, "tpu_v4")
        # mp halves per-device flops but adds activation all-reduces
        e_mp = estimate(self.MODEL, {**base, "mp_degree": 2}, 8, 16,
                        "tpu_v4")
        assert e_mp.flops_per_device == pytest.approx(
            e0.flops_per_device / 2)
        assert e_mp.comm_bytes.get("mp_allreduce", 0) > 0
        # remat adds exactly one extra forward: x4/3 flops
        e_r = estimate(self.MODEL, {**base, "use_recompute": True}, 8, 16,
                       "tpu_v4")
        assert e_r.flops_per_device == pytest.approx(
            e0.flops_per_device * 4 / 3)
        # dp ring all-reduce volume: 2(d-1)/d * local param bytes
        e_dp = estimate(self.MODEL, {**base, "dp_degree": 4}, 8, 16,
                        "tpu_v4")
        e_dp2 = estimate(self.MODEL, {**base, "dp_degree": 2}, 8, 16,
                         "tpu_v4")
        assert e_dp.comm_bytes["dp_allreduce"] / \
            e_dp2.comm_bytes["dp_allreduce"] == pytest.approx(1.5)
        # pipeline bubble shrinks with more microbatches and with VPP
        e_pp1 = estimate(self.MODEL, {**base, "pp_degree": 4,
                                      "micro_batches": 4}, 8, 16, "tpu_v4")
        e_pp2 = estimate(self.MODEL, {**base, "pp_degree": 4,
                                      "micro_batches": 8}, 8, 16, "tpu_v4")
        e_vpp = estimate(self.MODEL, {**base, "pp_degree": 4,
                                      "micro_batches": 4, "n_virtual": 2},
                         8, 16, "tpu_v4")
        assert e_pp1.bubble == pytest.approx(3 / 4)
        assert e_pp2.bubble == pytest.approx(3 / 8)
        assert e_vpp.bubble == pytest.approx(3 / 8)
        assert e_pp2.tokens_per_sec > e_pp1.tokens_per_sec

    def test_ranking_matches_measured_order(self):
        """The model's ranking over 3 configs matches real measured
        throughput on the 8-virtual-device CPU platform, along the two
        axes the platform measures faithfully (flops: remat x4/3; dtype:
        emulated-bf16 penalty).  Mesh-shape rankings (dp-vs-mp) are NOT
        validated here: with virtual devices timesharing the same cores,
        per-device compute does not shrink with the mesh, so the platform
        cannot reproduce the parallel-speedup ranking the model predicts
        for real chips (rank_probe evidence: mp8 beats dp8 on CPU purely
        through XLA partition artifacts).

        De-flaked (VERDICT r5 weak #2): only the predicted EXTREMES are
        measured and compared — the model separates them by ~1.5x (one
        extra forward's flops times the bf16-emulation penalty), a gap a
        loaded shared-CPU runner cannot plausibly invert, while adjacent
        pairs sit ~15 percent apart and flipped under load by construction.
        The full 3-config predicted ordering itself is asserted
        analytically (deterministic, measurement-free)."""
        from paddle_tpu.distributed.auto_tuner import (measure_llama_step,
                                                       rank_configs)
        from paddle_tpu.models import LlamaConfig

        cfg = LlamaConfig.tiny(vocab=128, hidden=256, layers=2, heads=4,
                               inter=512)
        base = dict(dp_degree=8, mp_degree=1, pp_degree=1,
                    sharding_degree=1, micro_batches=1, schedule="gpipe")
        cfgs = [dict(base, use_recompute=False, amp=False),
                dict(base, use_recompute=False, amp=True),
                dict(base, use_recompute=True, amp=True)]
        B, S = 32, 128  # compute-dominated scale: flops ordering is real
        ranked = rank_configs(cfg, cfgs, B, S, "cpu_virtual")

        # analytic ordering is deterministic: fewer flops and cheaper dtype
        # can only help, so no-remat/fp32 > no-remat/amp > remat/amp
        predicted = [(e.cfg["use_recompute"], e.cfg["amp"]) for e in ranked]
        assert predicted == [(False, False), (False, True), (True, True)], \
            predicted
        # the extremes must be separated by a margin worth measuring
        assert ranked[0].tokens_per_sec > 1.3 * ranked[-1].tokens_per_sec

        run = measure_llama_step(cfg, global_batch_size=B, seq_len=S,
                                 n_steps=3, warmup=2)
        t_best = run(ranked[0].cfg)
        t_worst = run(ranked[-1].cfg)
        assert t_best > t_worst, (
            f"predicted-best {ranked[0].cfg} measured {t_best:.1f} tok/s, "
            f"predicted-worst {ranked[-1].cfg} measured {t_worst:.1f} tok/s")

    def test_tuner_measures_best_predicted_first_and_prunes(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        tuner_cfg = {
            "num_devices": 8,
            "num_layers": 4, "hidden_size": 64, "vocab_size": 64,
            "num_attention_heads": 4,
            "dp_degree": [1, 2, 4, 8],
            "mp_degree": [1, 2, 4, 8],
            "pp_degree": [1],
            "sharding_degree": [1],
            "micro_batches": [1],
            "use_recompute": [False],
            "amp": [False],
            "cost_prune_ratio": 0.9,
        }
        t = AutoTuner(tuner_cfg, model_desc=self.MODEL,
                      global_batch_size=8, seq_len=16, cluster="tpu_v4")
        order = []

        def fake_run(c):
            order.append(dict(c))
            return t.algo.predicted(c)  # measurement == prediction

        t.tune(fake_run)
        # candidates were measured in predicted-best-first order
        preds = [t.algo.predicted(c) for c in order]
        assert preds == sorted(preds, reverse=True), preds
        # with measurement == prediction and ratio 0.9, the tail of the
        # space is measured-dominated and never run
        assert t.algo.pruned_by_cost, "no config was cost-pruned"
        total_valid = len(order) + len(t.algo.pruned_by_cost)
        assert len(order) < total_valid
