"""Auto-tuner (VERDICT r1 missing #6): grid search with prune rules over
hybrid-parallel configs, history recording, and a real measured tune() over
the compiled LLaMA step on the 8-device CPU mesh."""
import os

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, HistoryRecorder, candidate_space, prune)
from paddle_tpu.parallel import mesh as mesh_mod


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def test_prune_rules():
    tuner_cfg = {"num_devices": 8, "num_attention_heads": 4, "num_layers": 4,
                 "global_batch_size": 8, "vocab_size": 64}
    # wrong product of degrees
    assert prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                             "sharding_degree": 1}, [])
    # mp doesn't divide heads
    assert prune(tuner_cfg, {"dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
                             "sharding_degree": 1}, [])
    # pp doesn't divide layers
    assert prune(tuner_cfg, {"dp_degree": 1, "mp_degree": 1, "pp_degree": 8,
                             "sharding_degree": 1}, [])
    # valid
    assert not prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2,
                                 "pp_degree": 2, "sharding_degree": 1,
                                 "micro_batches": 2}, [])
    # OOM history prunes smaller micro-batch counts
    hist = [{"cfg": {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                     "sharding_degree": 1, "micro_batches": 4},
             "metric": None, "error": "oom"}]
    assert prune(tuner_cfg, {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                             "sharding_degree": 1, "micro_batches": 2}, hist)


def test_grid_search_exhausts_and_dedups():
    tuner_cfg = {"num_devices": 4, "num_attention_heads": 4, "num_layers": 4,
                 "global_batch_size": 8,
                 "micro_batches": [1, 2], "use_recompute": [True],
                 "amp": [False]}
    t = AutoTuner(tuner_cfg)
    seen = set()
    while True:
        cfg = t.search_once()
        if cfg is None:
            break
        key = tuple(sorted(cfg.items()))
        assert key not in seen
        seen.add(key)
        t.record(cfg, metric=float(len(seen)))
        degrees = (cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"]
                   * cfg["sharding_degree"])
        assert degrees == 4
    assert len(seen) > 3
    best = t.get_best()
    assert best["metric"] == float(len(seen))


def test_recorder_roundtrip(tmp_path):
    r = HistoryRecorder()
    r.add_cfg({"dp_degree": 2}, metric=10.0)
    r.add_cfg({"dp_degree": 4}, metric=20.0)
    r.add_cfg({"dp_degree": 8}, error="oom")
    assert r.get_best()["cfg"]["dp_degree"] == 4
    p = str(tmp_path / "hist.json")
    r.store_history(p)
    r2 = HistoryRecorder()
    r2.load_history(p)
    assert len(r2.history) == 3
    r.store_history(str(tmp_path / "hist.csv"))
    assert os.path.getsize(str(tmp_path / "hist.csv")) > 0


def test_tune_measures_real_steps():
    """End-to-end: tune the tiny LLaMA step over a small space on the CPU
    mesh and get a best config with a real throughput metric."""
    from paddle_tpu.distributed.auto_tuner import measure_llama_step
    from paddle_tpu.models import LlamaConfig

    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, inter=64)
    tuner_cfg = {
        "num_devices": 8,
        "num_attention_heads": cfg.num_attention_heads,
        "num_layers": cfg.num_hidden_layers,
        "hidden_size": cfg.hidden_size,
        "vocab_size": cfg.vocab_size,
        "global_batch_size": 8,
        "dp_degree": [2, 8],
        "mp_degree": [1, 4],
        "pp_degree": [1],
        "sharding_degree": [1],
        "micro_batches": [1],
        "use_recompute": [False],
        "amp": [False],
    }
    t = AutoTuner(tuner_cfg)
    best = t.tune(measure_llama_step(cfg, global_batch_size=8, seq_len=8,
                                     n_steps=2, warmup=1), max_trials=4)
    assert best is not None and best["metric"] > 0
    tried = [h for h in t.recorder.history if h["metric"] is not None]
    assert len(tried) >= 2
