"""OP_COVERAGE.json drift gate.

The staticcheck registry-consistency rule and the dtype-sweep battery's
top-op requirement are both pinned to the checked-in OP_COVERAGE.json; if
the enumeration drifts from the file, those gates silently govern a stale
op set. Regenerates the enumeration (tools/op_coverage.py drives real
eager train/infer steps — minutes of work, hence `slow`; tier-1 excludes
it) and asserts exact equality.

On failure: `python tools/op_coverage.py` refreshes the file — commit it
together with whatever changed the op mix.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_op_coverage_json_matches_fresh_enumeration(tmp_path):
    out = str(tmp_path / "fresh.json")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "op_coverage.py"),
         "-o", out],
        cwd=REPO, check=True, timeout=900,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    with open(os.path.join(REPO, "OP_COVERAGE.json")) as f:
        checked_in = json.load(f)
    with open(out) as f:
        fresh = json.load(f)
    assert checked_in == fresh, (
        "OP_COVERAGE.json is stale — rerun `python tools/op_coverage.py` "
        "and commit the result")
