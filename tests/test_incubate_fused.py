"""Fused incubate op surface (VERDICT r1 missing #9).

Each fused op is checked against an unfused composition of the public ops
(the reference's own contract: the fused kernels are numerically the
pseudo-code in fused_transformer.py docstrings), plus grad flow and a
KV-cache decode parity run for FusedMultiTransformer.
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.nn import functional as IF


def _t(a):
    return P.to_tensor(np.asarray(a, dtype=np.float32))


def test_fused_matmul_bias_and_linear():
    rng = np.random.RandomState(0)
    x, w, b = rng.randn(4, 8), rng.randn(8, 16), rng.randn(16)
    out = IF.fused_matmul_bias(_t(x), _t(w), _t(b))
    np.testing.assert_allclose(out.numpy(), x @ w + b, rtol=1e-5, atol=1e-5)
    out2 = IF.fused_linear(_t(x), _t(w), _t(b))
    np.testing.assert_allclose(out2.numpy(), x @ w + b, rtol=1e-5, atol=1e-5)
    out3 = IF.fused_linear_activation(_t(x), _t(w), _t(b), activation="relu")
    np.testing.assert_allclose(out3.numpy(), np.maximum(x @ w + b, 0),
                               rtol=1e-5, atol=1e-5)


def test_fused_norms():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 16).astype(np.float32)
    w = rng.rand(16).astype(np.float32) + 0.5
    b = rng.randn(16).astype(np.float32)
    got = IF.fused_layer_norm(_t(x), _t(w), _t(b), epsilon=1e-5).numpy()
    ref = F.layer_norm(_t(x), [16], _t(w), _t(b), 1e-5).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    got = IF.fused_rms_norm(_t(x), _t(w), epsilon=1e-6).numpy()
    ref = F.rms_norm(_t(x), _t(w), 1e-6).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # residual fusion
    r = rng.randn(2, 6, 16).astype(np.float32)
    got = IF.fused_layer_norm(_t(x), _t(w), _t(b), residual=_t(r)).numpy()
    ref = F.layer_norm(_t(x + r), [16], _t(w), _t(b), 1e-5).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_fused_feedforward_matches_unfused():
    rng = np.random.RandomState(2)
    d, ff = 16, 32
    x = rng.randn(2, 5, d).astype(np.float32)
    w1, b1 = rng.randn(d, ff).astype(np.float32), rng.randn(ff).astype(np.float32)
    w2, b2 = rng.randn(ff, d).astype(np.float32), rng.randn(d).astype(np.float32)
    lw = np.ones(d, np.float32)
    lb = np.zeros(d, np.float32)
    got = IF.fused_feedforward(
        _t(x), _t(w1), _t(w2), _t(b1), _t(b2), ln1_scale=_t(lw), ln1_bias=_t(lb),
        ln2_scale=_t(lw), ln2_bias=_t(lb), dropout1_rate=0.0, dropout2_rate=0.0,
        activation="gelu", pre_layer_norm=True).numpy()
    h = F.layer_norm(_t(x), [d], _t(lw), _t(lb), 1e-5).numpy()
    ref = x + (np.asarray(F.gelu(_t(h @ w1 + b1)).numpy()) @ w2 + b2)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_fused_attention_matches_unfused():
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 6, 4, 8
    E = H * D
    x = rng.randn(B, S, E).astype(np.float32)
    qkv_w = (rng.randn(3, H, D, E) * 0.1).astype(np.float32)
    qkv_b = np.zeros((3, H, D), np.float32)
    lin_w = (rng.randn(E, E) * 0.1).astype(np.float32)
    lin_b = np.zeros(E, np.float32)
    got = IF.fused_multi_head_attention(
        _t(x), _t(qkv_w), _t(lin_w), pre_layer_norm=True,
        pre_ln_scale=_t(np.ones(E, np.float32)),
        pre_ln_bias=_t(np.zeros(E, np.float32)),
        ln_scale=_t(np.ones(E, np.float32)),
        ln_bias=_t(np.zeros(E, np.float32)),
        qkv_bias=_t(qkv_b), linear_bias=_t(lin_b),
        dropout_rate=0.0, attn_dropout_rate=0.0).numpy()

    # unfused reference
    h = F.layer_norm(_t(x), [E], None, None, 1e-5).numpy()
    qkv = np.einsum("bse,thde->tbhsd", h, qkv_w)
    q, k, v = qkv[0], qkv[1], qkv[2]
    logits = np.einsum("bhqd,bhkd->bhqk", q / np.sqrt(D), k)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", probs, v)
    ctx = np.transpose(ctx, (0, 2, 1, 3)).reshape(B, S, E)
    ref = x + ctx @ lin_w
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_fused_attention_grad_flows():
    rng = np.random.RandomState(4)
    B, S, H, D = 2, 4, 2, 4
    E = H * D
    x = P.to_tensor(rng.randn(B, S, E).astype(np.float32))
    qkv_w = P.to_tensor((rng.randn(3, H, D, E) * 0.1).astype(np.float32))
    qkv_w.stop_gradient = False
    lin_w = P.to_tensor((rng.randn(E, E) * 0.1).astype(np.float32))
    lin_w.stop_gradient = False
    out = IF.fused_multi_head_attention(
        x, qkv_w, lin_w, dropout_rate=0.0, attn_dropout_rate=0.0)
    loss = out.sum()
    loss.backward()
    assert qkv_w.grad is not None and np.isfinite(qkv_w.grad.numpy()).all()
    assert lin_w.grad is not None and np.isfinite(lin_w.grad.numpy()).all()


def test_fused_rope_matches_llama_inline():
    from paddle_tpu.models.llama import _rope
    rng = np.random.RandomState(5)
    B, S, H, D = 2, 8, 4, 16
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, H, D).astype(np.float32)
    qo, ko, _ = IF.fused_rotary_position_embedding(
        _t(q), _t(k), use_neox_rotary_style=False)
    qr, kr = _rope(q, k, 10000.0)
    np.testing.assert_allclose(qo.numpy(), np.asarray(qr), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ko.numpy(), np.asarray(kr), rtol=1e-5, atol=1e-5)
    # neox (half-block) style differs from interleaved
    qn, _, _ = IF.fused_rotary_position_embedding(
        _t(q), use_neox_rotary_style=True)
    assert not np.allclose(qn.numpy(), qo.numpy())
    # position_ids path: shifting positions changes the result
    pids = np.tile(np.arange(2, S + 2), (B, 1))
    qp, _, _ = IF.fused_rotary_position_embedding(
        _t(q), position_ids=P.to_tensor(pids), use_neox_rotary_style=False)
    assert not np.allclose(qp.numpy(), qo.numpy())


def test_masked_multihead_attention_decode():
    """Stepped decode with per-example write positions equals full attention
    over the written prefix."""
    rng = np.random.RandomState(6)
    B, H, D, S_max = 2, 2, 4, 8
    cache = np.zeros((2, B, H, S_max, D), np.float32)
    # pre-fill 3 positions with known k/v
    ks = rng.randn(B, H, 3, D).astype(np.float32)
    vs = rng.randn(B, H, 3, D).astype(np.float32)
    cache[0, :, :, :3] = ks
    cache[1, :, :, :3] = vs
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    out, new_cache = IF.masked_multihead_attention(
        _t(x), _t(cache), sequence_lengths=P.to_tensor(np.full(B, 3)))
    q = x.reshape(B, 3, H, D)[:, 0]
    k_new = x.reshape(B, 3, H, D)[:, 1]
    v_new = x.reshape(B, 3, H, D)[:, 2]
    k_all = np.concatenate([ks, k_new[:, :, None]], axis=2)
    v_all = np.concatenate([vs, v_new[:, :, None]], axis=2)
    logits = np.einsum("bhd,bhsd->bhs", q / np.sqrt(D), k_all)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhs,bhsd->bhd", p, v_all).reshape(B, H * D)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    # cache was written at position 3
    np.testing.assert_allclose(new_cache.numpy()[0][:, :, 3], k_new,
                               rtol=1e-6, atol=1e-6)


def test_fused_multi_transformer_cache_decode_parity():
    """Prefill+decode through caches emits the same logits as running the
    full sequence without caches (the FusedMultiTransformer decode contract)."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    P.seed(7)
    E, H, FFN, L = 16, 2, 32, 2
    m = FusedMultiTransformer(E, H, FFN, num_layers=L, dropout_rate=0.0)
    m.eval()
    rng = np.random.RandomState(8)
    x = rng.randn(1, 5, E).astype(np.float32)

    # no-cache full run (causal mask)
    S = x.shape[1]
    causal = np.where(np.tril(np.ones((S, S), bool)), 0.0, -1e30)[None, None]
    full = m(_t(x), attn_mask=P.to_tensor(causal.astype(np.float32))).numpy()

    # prefill 4 tokens, decode the 5th
    caches = m.init_caches(1, 8)
    out_p = m(_t(x[:, :4]), caches=caches, time_step=None)
    out_p, caches = out_p if isinstance(out_p, tuple) else (out_p, caches)
    out_d = m(_t(x[:, 4:5]), caches=caches, time_step=4)
    out_d, _ = out_d if isinstance(out_d, tuple) else (out_d, None)
    np.testing.assert_allclose(out_d.numpy()[:, 0], full[:, 4],
                               rtol=1e-3, atol=1e-4)


def test_cache_path_honors_attn_mask():
    """Padding mask must apply in the cache branch too (review finding): mask
    a prefill position and the decode output must change."""
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    P.seed(11)
    m = FusedMultiTransformer(16, 2, 32, num_layers=1, dropout_rate=0.0)
    m.eval()
    rng = np.random.RandomState(12)
    x = rng.randn(1, 4, 16).astype(np.float32)
    S = 4
    causal = np.where(np.tril(np.ones((S, S), bool)), 0.0, -1e30)[None, None]
    caches = m.init_caches(1, 8)
    out_a, caches_a = m(_t(x), caches=caches,
                        attn_mask=P.to_tensor(causal.astype(np.float32)))
    # same but also mask out position 1 entirely: prefill outputs must differ
    pad = causal.copy()
    pad[..., 1] = -1e30
    caches = m.init_caches(1, 8)
    out_b, _ = m(_t(x), caches=caches,
                 attn_mask=P.to_tensor(pad.astype(np.float32)))
    assert not np.allclose(out_a.numpy()[:, 2:], out_b.numpy()[:, 2:])
    # decode step: masking a cached column must change the decode output
    xn = rng.randn(1, 1, 16).astype(np.float32)
    dm = np.zeros((1, 1, 1, 8), np.float32)
    dm[..., 1] = -1e30
    out_d0, _ = m(_t(xn), caches=caches_a, time_step=4)
    out_d1, _ = m(_t(xn), caches=caches_a, time_step=4,
                  attn_mask=P.to_tensor(dm))
    assert not np.allclose(out_d0.numpy(), out_d1.numpy())


def test_multi_transformer_rotary_is_applied():
    from paddle_tpu.incubate.nn import FusedMultiTransformer
    P.seed(13)
    m = FusedMultiTransformer(16, 2, 32, num_layers=1, dropout_rate=0.0)
    m.eval()
    rng = np.random.RandomState(14)
    x = rng.randn(1, 4, 16).astype(np.float32)
    D = 8  # head_dim
    pos = np.arange(16, dtype=np.float32)
    inv = 1.0 / (10000.0 ** (np.arange(0, D, 2) / D))
    ang = np.outer(pos, inv).astype(np.float32)
    sincos = P.to_tensor(np.stack([np.sin(ang), np.cos(ang)]))
    a = m(_t(x)).numpy()
    b = m(_t(x), rotary_embs=sincos, rotary_emb_dims=1).numpy()
    assert not np.allclose(a, b)


def test_masked_mha_per_example_lengths():
    """Different per-example write positions (ragged batch decode)."""
    rng = np.random.RandomState(15)
    B, H, D, S_max = 2, 2, 4, 8
    cache = np.zeros((2, B, H, S_max, D), np.float32)
    cache[0, 0, :, :2] = rng.randn(H, 2, D)
    cache[1, 0, :, :2] = rng.randn(H, 2, D)
    cache[0, 1, :, :5] = rng.randn(H, 5, D)
    cache[1, 1, :, :5] = rng.randn(H, 5, D)
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    out, nc = IF.masked_multihead_attention(
        _t(x), _t(cache), sequence_lengths=P.to_tensor(np.array([2, 5])))
    k_new = x.reshape(B, 3, H, D)[:, 1]
    np.testing.assert_allclose(nc.numpy()[0][0, :, 2], k_new[0], rtol=1e-6)
    np.testing.assert_allclose(nc.numpy()[0][1, :, 5], k_new[1], rtol=1e-6)


def test_dropout_downscale_in_infer():
    x = _t(np.ones((4, 4)))
    out = F.dropout(x, p=0.5, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(out.numpy(), 0.5 * np.ones((4, 4)), rtol=1e-6)
    out2 = IF.fused_dropout_add(x, x, p=0.5, training=False,
                                mode="downscale_in_infer")
    np.testing.assert_allclose(out2.numpy(), 1.5 * np.ones((4, 4)), rtol=1e-6)


def test_rope_decode_position_beyond_seq():
    """position_ids larger than the current q length must still rotate with
    the true angle (review finding: table was built only up to S)."""
    rng = np.random.RandomState(16)
    q = rng.randn(1, 1, 2, 8).astype(np.float32)
    q7, _, _ = IF.fused_rotary_position_embedding(
        _t(q), position_ids=P.to_tensor(np.array([[7]])),
        use_neox_rotary_style=False)
    # oracle: rotate a length-8 sequence and take row 7
    qfull = np.tile(q, (1, 8, 1, 1))
    qf, _, _ = IF.fused_rotary_position_embedding(
        _t(qfull), use_neox_rotary_style=False)
    np.testing.assert_allclose(q7.numpy()[0, 0], qf.numpy()[0, 7],
                               rtol=1e-5, atol=1e-5)


def test_fused_encoder_layer_trains():
    P.seed(9)
    layer = P.incubate.nn.FusedTransformerEncoderLayer(
        16, 2, 32, dropout_rate=0.0)
    opt = P.optimizer.AdamW(learning_rate=1e-3,
                            parameters=layer.parameters())
    rng = np.random.RandomState(10)
    x = P.to_tensor(rng.randn(2, 6, 16).astype(np.float32))
    y = P.to_tensor(rng.randn(2, 6, 16).astype(np.float32))
    losses = []
    for _ in range(8):
        loss = F.mse_loss(layer(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
