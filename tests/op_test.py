"""OpTest harness — the backbone of the reference's op test strategy
(test/legacy_test/eager_op_test.py:379, SURVEY.md §4.1): each op is checked
against a numpy reference in BOTH eager and compiled (jit-traced) modes, and
gradients are verified numerically (central finite differences) against the
autograd tape.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as P
from paddle_tpu.core.tensor import Tensor


def _to_tensors(arrays):
    return [Tensor(jnp.asarray(a)) for a in arrays]


def _np_of(out):
    if isinstance(out, (tuple, list)):
        return [np.asarray(o.numpy() if isinstance(o, Tensor) else o)
                for o in out]
    return [np.asarray(out.numpy() if isinstance(out, Tensor) else out)]


class OpTest:
    """Mixin-style harness. Subclass in a pytest test class or use the module
    functions directly."""

    rtol = 1e-5
    atol = 1e-6

    @staticmethod
    def run_eager(op: Callable, arrays: Sequence[np.ndarray], **kwargs):
        return _np_of(op(*_to_tensors(arrays), **kwargs))

    @staticmethod
    def run_compiled(op: Callable, arrays: Sequence[np.ndarray], **kwargs):
        """Trace the op through jax.jit — the to_static/compiled mode path."""
        def pure(*vals):
            out = op(*[Tensor(v) for v in vals], **kwargs)
            if isinstance(out, (tuple, list)):
                return tuple(o._value if isinstance(o, Tensor) else o
                             for o in out)
            return out._value if isinstance(out, Tensor) else out
        out = jax.jit(pure)(*[jnp.asarray(a) for a in arrays])
        if isinstance(out, tuple):
            return [np.asarray(o) for o in out]
        return [np.asarray(out)]

    @classmethod
    def check_output(cls, op: Callable, arrays: Sequence[np.ndarray],
                     reference: Callable, rtol=None, atol=None, **kwargs):
        """Run eager AND compiled; compare both against the numpy reference."""
        rtol = rtol if rtol is not None else cls.rtol
        atol = atol if atol is not None else cls.atol
        expect = reference(*arrays)
        if not isinstance(expect, (tuple, list)):
            expect = [expect]
        expect = [np.asarray(e) for e in expect]
        for mode, runner in (("eager", cls.run_eager),
                             ("compiled", cls.run_compiled)):
            got = runner(op, arrays, **kwargs)
            assert len(got) == len(expect), \
                f"{mode}: {len(got)} outputs vs {len(expect)} expected"
            for g, e in zip(got, expect):
                np.testing.assert_allclose(
                    g, e, rtol=rtol, atol=atol,
                    err_msg=f"[{mode}] op output mismatch vs numpy reference")

    @classmethod
    def check_grad(cls, op: Callable, arrays: Sequence[np.ndarray],
                   wrt: Sequence[int] = (0,), eps: float = 1e-3,
                   rtol: float = 5e-2, atol: float = 1e-3,
                   output_index: int | None = None, **kwargs):
        """Numeric-vs-autograd gradient check (the reference's
        check_grad_with_place finite-difference protocol).

        Scalarizes the op as sum(op(...)) and compares d/d inputs[wrt]."""
        arrays = [np.asarray(a, np.float64 if np.asarray(a).dtype.kind == "f"
                             else np.asarray(a).dtype) for a in arrays]

        def scalar(*arrs):
            out = op(*_to_tensors(arrs), **kwargs)
            if isinstance(out, (tuple, list)):
                out = out[output_index if output_index is not None else 0]
            return out

        # autograd gradients
        tensors = _to_tensors(arrays)
        for i in wrt:
            tensors[i].stop_gradient = False
        out = op(*tensors, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[output_index if output_index is not None else 0]
        out.sum().backward()
        auto_grads = [np.asarray(tensors[i].grad.numpy()) for i in wrt]

        # numeric gradients (central differences)
        for k, i in enumerate(wrt):
            base = arrays[i]
            num = np.zeros_like(base, np.float64)
            flat = base.reshape(-1)
            numf = num.reshape(-1)
            for j in range(flat.size):
                orig = flat[j]
                flat[j] = orig + eps
                up = float(scalar(*arrays).sum().numpy())
                flat[j] = orig - eps
                dn = float(scalar(*arrays).sum().numpy())
                flat[j] = orig
                numf[j] = (up - dn) / (2 * eps)
            np.testing.assert_allclose(
                auto_grads[k], num, rtol=rtol, atol=atol,
                err_msg=f"gradient mismatch for input {i} "
                        f"(autograd vs finite differences)")


check_output = OpTest.check_output
check_grad = OpTest.check_grad
