"""Smoke-level guard for the dispatch microbenchmark.

bench_dispatch must stay CPU-runnable and keep its one-JSON-line contract
(it is the perf trajectory when the TPU probe reports tpu-unavailable), so
a tiny-iteration run lives in tier-1. It is slow-exempt by design — a few
seconds — but skips cleanly when the wall-clock budget is tight
(PT_TIGHT_BUDGET=1) since it is a perf artifact, not a correctness gate.
The >=3x acceptance ratio itself is asserted only in the slow battery:
tiny iteration counts on a loaded CI box make ratios noisy.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(iters: int):
    env = dict(os.environ, PT_DISPATCH_BENCH_ITERS=str(iters),
               PT_DISPATCH_BENCH_WARMUP="5")
    r = subprocess.run([sys.executable, os.path.join(REPO,
                                                     "bench_dispatch.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout  # exactly ONE JSON line on stdout
    return json.loads(lines[0]), r.stderr


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_dispatch_smoke_json_contract(tmp_path):
    payload, stderr = _run_bench(iters=40)
    assert payload["metric"] == "eager_dispatch_cached_speedup"
    assert payload["unit"] == "x"
    assert payload["value"] > 0 and payload["vs_baseline"] > 0
    for wl in ("softmax_fwd", "gelu_fwd", "linear_train"):
        assert f"{wl}_speedup" in payload
    assert "artifact ->" in stderr
    # artifact parses and carries the per-workload detail + cache counters
    art = stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        self_json = json.load(f)
    assert set(self_json["detail"]["workloads"]) == {
        "softmax_fwd", "gelu_fwd", "linear_train"}
    for wl, per in self_json["detail"]["workloads"].items():
        ci = per["cache_info"]  # snapshot of the CACHED leg, per workload
        assert ci["hits"] > 0 and ci["per_op"], (wl, ci)
    os.unlink(art)  # tiny-iteration artifacts are not trajectory evidence


@pytest.mark.slow
def test_bench_dispatch_meets_acceptance_floor():
    payload, _ = _run_bench(iters=300)
    assert payload["value"] >= 3.0, payload
