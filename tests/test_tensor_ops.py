"""Tensor + op library unit tests (pattern: numpy-reference checks, SURVEY.md §4.1)."""
import numpy as np
import pytest

import paddle_tpu as P


def test_to_tensor_roundtrip():
    x = P.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert x.dtype == np.float32
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_int_default_dtype():
    x = P.to_tensor([1, 2, 3])
    assert x.dtype == np.int64


def test_creation_ops():
    assert P.zeros([2, 3]).numpy().sum() == 0
    assert P.ones([2, 3]).numpy().sum() == 6
    np.testing.assert_allclose(P.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_allclose(P.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(P.eye(3).numpy(), np.eye(3))
    np.testing.assert_allclose(P.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5),
                               rtol=1e-6)


def test_arith_operators():
    a = P.to_tensor([1.0, 2.0, 3.0])
    b = P.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2.0 - a).numpy(), [1, 0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])


def test_matmul():
    a = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = P.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy())
    np.testing.assert_allclose(P.matmul(a, b, transpose_x=False).numpy(),
                               a.numpy() @ b.numpy())
    np.testing.assert_allclose(
        P.matmul(b, a, transpose_x=True, transpose_y=True).numpy(),
        b.numpy().T @ a.numpy().T)


def test_reductions():
    x = np.random.randn(3, 4, 5).astype(np.float32)
    t = P.to_tensor(x)
    np.testing.assert_allclose(P.sum(t).numpy(), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(P.mean(t, axis=1).numpy(), x.mean(1), rtol=1e-5)
    np.testing.assert_allclose(P.max(t, axis=[0, 2]).numpy(), x.max((0, 2)))
    np.testing.assert_allclose(t.std().numpy(), x.std(ddof=1), rtol=1e-5)
    np.testing.assert_allclose(P.logsumexp(t, axis=-1).numpy(),
                               np.log(np.exp(x).sum(-1)), rtol=1e-4)
    assert P.argmax(t, axis=2).dtype == np.int64


def test_manip():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    t = P.to_tensor(x)
    assert P.reshape(t, [6, 4]).shape == [6, 4]
    assert P.transpose(t, [2, 0, 1]).shape == [4, 2, 3]
    assert P.flatten(t, 1).shape == [2, 12]
    assert P.unsqueeze(t, 0).shape == [1, 2, 3, 4]
    assert P.squeeze(P.unsqueeze(t, 0), 0).shape == [2, 3, 4]
    parts = P.split(t, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = P.split(t, [1, 3], axis=2)
    assert parts[1].shape == [2, 3, 3]
    c = P.concat([t, t], axis=0)
    assert c.shape == [4, 3, 4]
    s = P.stack([t, t], axis=1)
    assert s.shape == [2, 2, 3, 4]


def test_indexing():
    x = np.arange(20, dtype=np.float32).reshape(4, 5)
    t = P.to_tensor(x)
    np.testing.assert_allclose(t[1].numpy(), x[1])
    np.testing.assert_allclose(t[1:3, 2].numpy(), x[1:3, 2])
    np.testing.assert_allclose(t[:, ::2].numpy(), x[:, ::2])
    idx = P.to_tensor([0, 2])
    np.testing.assert_allclose(t[idx].numpy(), x[[0, 2]])
    mask = t > 9.0
    np.testing.assert_allclose(P.masked_select(t, mask).numpy(), x[x > 9])


def test_setitem():
    t = P.zeros([3, 3])
    t[1] = 5.0
    assert t.numpy()[1].sum() == 15.0


def test_gather_scatter():
    x = np.random.randn(5, 3).astype(np.float32)
    t = P.to_tensor(x)
    idx = P.to_tensor([0, 2, 4])
    np.testing.assert_allclose(P.gather(t, idx).numpy(), x[[0, 2, 4]])
    upd = P.ones([3, 3])
    out = P.scatter(t, idx, upd)
    assert out.numpy()[0].sum() == 3.0


def test_topk_sort():
    x = np.random.randn(4, 10).astype(np.float32)
    t = P.to_tensor(x)
    vals, idx = P.topk(t, 3, axis=-1)
    np.testing.assert_allclose(vals.numpy(), np.sort(x, -1)[:, ::-1][:, :3], rtol=1e-6)
    np.testing.assert_allclose(P.sort(t, axis=-1).numpy(), np.sort(x, -1))


def test_where_comparison():
    a = P.to_tensor([1.0, 5.0, 3.0])
    b = P.to_tensor([4.0, 2.0, 3.0])
    np.testing.assert_allclose(P.where(a > b, a, b).numpy(), [4, 5, 3])
    assert bool(P.all(P.to_tensor([True, True])).numpy())
    assert (a == b).numpy().tolist() == [False, False, True]


def test_cast():
    t = P.to_tensor([1.5, 2.5])
    assert P.cast(t, "int32").dtype == np.int32
    assert t.astype("float64").dtype == np.float64


def test_linalg():
    a = np.random.randn(4, 4).astype(np.float32)
    a = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    t = P.to_tensor(a)
    np.testing.assert_allclose(P.linalg.inv(t).numpy(), np.linalg.inv(a), atol=1e-4)
    np.testing.assert_allclose(P.linalg.det(t).numpy(), np.linalg.det(a), rtol=1e-4)
    np.testing.assert_allclose(P.linalg.cholesky(t).numpy(), np.linalg.cholesky(a),
                               atol=1e-4)
    np.testing.assert_allclose(P.linalg.norm(t).numpy(),
                               np.linalg.norm(a), rtol=1e-5)


def test_einsum():
    a = np.random.randn(2, 3).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    out = P.einsum("ij,jk->ik", P.to_tensor(a), P.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_random_shapes_and_determinism():
    P.seed(7)
    a = P.rand([3, 4])
    P.seed(7)
    b = P.rand([3, 4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    assert P.randn([2, 2]).shape == [2, 2]
    r = P.randint(0, 10, [100])
    assert r.numpy().min() >= 0 and r.numpy().max() < 10
    p = P.randperm(16)
    assert sorted(p.numpy().tolist()) == list(range(16))


def test_inplace_ops():
    t = P.ones([3])
    t.add_(P.ones([3]))
    np.testing.assert_allclose(t.numpy(), [2, 2, 2])
    t.zero_()
    assert t.numpy().sum() == 0


def test_cumsum_cumprod():
    x = np.random.rand(3, 4).astype(np.float32)
    t = P.to_tensor(x)
    np.testing.assert_allclose(P.cumsum(t, axis=1).numpy(), np.cumsum(x, 1), rtol=1e-5)
    np.testing.assert_allclose(P.cumprod(t, dim=0).numpy(), np.cumprod(x, 0), rtol=1e-5)


def test_pad():
    x = np.ones((1, 1, 2, 2), np.float32)
    out = P.nn.functional.pad(P.to_tensor(x), [1, 1, 1, 1])
    assert out.shape == [1, 1, 4, 4]
    assert out.numpy().sum() == 4.0


def test_op_errors_carry_enforce_context():
    """Enforce-style diagnostics (paddle/common/enforce.h analog): failed ops
    name themselves and summarize input signatures, chaining the jax error."""
    import pytest

    with pytest.raises((TypeError, ValueError)) as ei:
        P.matmul(P.ones([2, 3]), P.ones([2, 3]))
    msg = str(ei.value)
    assert "matmul" in msg and "float32[2, 3]" in msg
    assert ei.value.__cause__ is not None  # original jax error chained
