"""Recompile-count guard tests for the compiled-op dispatch cache.

The contract under test (ops/_op_cache.py, README "Eager dispatch"):
- a repeated same-shape/dtype eager op compiles EXACTLY once, on both the
  no-grad and the vjp path (retrace counters prove it — the wrapper body
  only executes while jax traces);
- distinct shapes / dtypes / amp regimes get distinct entries;
- the LRU bound evicts; the disable switch restores the uncached path;
- results (fwd + grads) match the uncached path bitwise-comparable ranges
  for a multi-output namedtuple op (eigh);
- Tracer inputs, static mode, and array-bearing closures bypass.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch.cache_clear()
    dispatch.set_op_cache_enabled(True)
    dispatch.set_op_cache_maxsize(512)
    dispatch.set_op_cache_compile_after(2)
    yield
    dispatch.cache_clear()
    dispatch.set_op_cache_enabled(True)
    dispatch.set_op_cache_maxsize(512)
    dispatch.set_op_cache_compile_after(2)


def _op_stats(name):
    return dispatch.cache_info()["per_op"].get(name, {})


def test_same_shape_nograd_compiles_exactly_once():
    x = P.to_tensor(np.random.randn(8, 16).astype(np.float32))
    outs = [P.nn.functional.softmax(x, axis=-1) for _ in range(8)]
    s = _op_stats("softmax")
    assert s["misses"] == 1, s        # first call ran eager, installed entry
    assert s["hits"] == 7, s          # every repeat served compiled
    assert s["retraces"] == 1, s      # ...from exactly ONE trace/compile
    ref = jax.nn.softmax(x._value, axis=-1)
    for o in outs:
        np.testing.assert_allclose(o.numpy(), np.asarray(ref), rtol=1e-6)


def test_vjp_path_compiles_exactly_once_fwd_and_bwd():
    x = P.to_tensor(np.random.randn(4, 8).astype(np.float32))
    w = P.to_tensor(np.random.randn(8, 4).astype(np.float32),
                    stop_gradient=False)
    grads = []
    for _ in range(5):
        (P.matmul(x, w)).sum().backward()
        grads.append(w.grad.numpy().copy())
        w.clear_grad()
    s = _op_stats("matmul")
    assert s["misses"] == 1, s
    assert s["hits"] == 4, s
    assert s["retraces"] == 1, s       # vjp-build wrapper traced once
    assert s["bwd_retraces"] == 1, s   # pullback wrapper traced once
    for g in grads[1:]:
        np.testing.assert_array_equal(g, grads[0])


def test_distinct_shapes_dtypes_amp_get_distinct_entries():
    base = dispatch.cache_info()["size"]
    a = P.to_tensor(np.random.randn(4, 4).astype(np.float32))
    b = P.to_tensor(np.random.randn(2, 4).astype(np.float32))   # new shape
    c = P.to_tensor(np.random.randn(4, 4).astype(np.float64))   # new dtype
    for t in (a, a, b, b, c, c):
        P.tanh(t)
    assert dispatch.cache_info()["size"] == base + 3
    with P.amp.auto_cast(custom_white_list=["tanh"]):            # amp regime
        P.tanh(a)
        P.tanh(a)
    assert dispatch.cache_info()["size"] == base + 4
    s = _op_stats("tanh")
    assert s["misses"] == 4 and s["hits"] == 4, s


def test_static_kwargs_key_by_value():
    x = P.to_tensor(np.random.randn(4, 6).astype(np.float32))
    for axis in (0, 1, 0, 1):
        P.nn.functional.softmax(x, axis=axis)
    s = _op_stats("softmax")
    assert s["misses"] == 2 and s["hits"] == 2, s


def test_lru_eviction_bounds_cache():
    dispatch.set_op_cache_maxsize(3)
    for n in (3, 4, 5, 6, 7):
        t = P.to_tensor(np.random.randn(n).astype(np.float32))
        P.tanh(t)
        P.tanh(t)
    info = dispatch.cache_info()
    assert info["size"] <= 3
    assert info["evictions"] >= 2


def test_disabled_flag_restores_uncached_path():
    dispatch.set_op_cache_enabled(False)
    x = P.to_tensor(np.random.randn(4, 4).astype(np.float32),
                    stop_gradient=False)
    for _ in range(3):
        P.tanh(x).sum().backward()
        x.clear_grad()
    info = dispatch.cache_info()
    assert info["enabled"] is False
    assert info["size"] == 0 and info["hits"] == 0 and info["misses"] == 0


def test_multi_output_namedtuple_fwd_and_grads_match_uncached():
    a = np.random.randn(5, 5)
    sym = (a + a.T).astype(np.float32)

    def run():
        x = P.to_tensor(sym, stop_gradient=False)
        w, v = P.linalg.eigh(x)
        (w.sum() + (v * v).sum()).backward()
        return w.numpy().copy(), v.numpy().copy(), x.grad.numpy().copy()

    run()                      # miss: eager
    w1, v1, g1 = run()         # hit: compiled vjp pair
    s = _op_stats("eigh")
    assert s["misses"] == 1 and s["hits"] == 1 and s["retraces"] == 1, s
    dispatch.set_op_cache_enabled(False)
    w0, v0, g0 = run()         # reference: plain jax.vjp path
    np.testing.assert_allclose(w1, w0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(v1, v0, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-5)


def test_tracer_inputs_bypass():
    x = np.random.randn(4, 4).astype(np.float32)

    def traced(a):
        return P.nn.functional.softmax(Tensor(a), axis=-1)._value

    out = jax.jit(traced)(jnp.asarray(x))
    info = dispatch.cache_info()
    assert info["size"] == 0, info       # nothing keyed on tracers
    assert _op_stats("softmax").get("bypasses", 0) >= 1
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jax.nn.softmax(x, axis=-1)),
                               rtol=1e-6)


def test_static_mode_bypasses():
    P.enable_static()
    try:
        x = P.static.data("cachex", [2, 3], "float32")
        y = P.tanh(x)
        assert dispatch.cache_info()["size"] == 0
    finally:
        P.disable_static()


def test_array_closure_bypasses():
    payload = jnp.ones((3,))
    x = P.to_tensor(np.random.randn(3).astype(np.float32))
    for _ in range(3):
        out = dispatch.apply(lambda v: v + payload, x, op_name="closure_op")
    s = _op_stats("closure_op")
    assert s.get("bypasses", 0) == 3 and s.get("hits", 0) == 0, s
    np.testing.assert_allclose(out.numpy(), x.numpy() + 1.0, rtol=1e-6)


def test_nonarray_output_poisons_entry():
    x = P.to_tensor(np.random.randn(3).astype(np.float32))
    for _ in range(3):
        out = dispatch.apply(lambda v: (v * 2, "tag"), x, op_name="mixed_out")
    assert isinstance(out, tuple) and out[1] == "tag"
    s = _op_stats("mixed_out")
    assert s["hits"] == 0, s  # jit would coerce "tag" — must stay eager


def test_eager_only_op_poisons_and_falls_back():
    # data-dependent output shape: traces fine never — first hit must poison
    x = P.to_tensor(np.array([1.0, 0.0, 2.0, 0.0], np.float32))
    m = P.to_tensor(np.array([True, False, True, False]))
    outs = [P.masked_select(x, m) for _ in range(3)]
    for o in outs:
        np.testing.assert_allclose(o.numpy(), [1.0, 2.0])


def test_nan_check_fires_on_cached_outputs():
    from paddle_tpu.utils import flags
    x = P.to_tensor(np.array([0.0, 1.0], np.float32))
    P.log(x)   # miss (eager) — -inf but flag off
    flags.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            P.log(x)  # served by the compiled executable — scan still runs
    finally:
        flags.set_flags({"FLAGS_check_nan_inf": False})


def test_cache_info_and_profiler_summary_shape():
    x = P.to_tensor(np.random.randn(2, 2).astype(np.float32))
    P.tanh(x)
    P.tanh(x)
    info = dispatch.cache_info()
    assert {"enabled", "size", "maxsize", "hits", "misses", "per_op"} <= \
        set(info)
    assert info["per_op"]["tanh"]["retraces"] == 1
    from paddle_tpu.profiler import op_cache_summary
    txt = op_cache_summary()
    assert "tanh" in txt and "Retrace" in txt


def test_compile_after_threshold_defers_compiles():
    dispatch.set_op_cache_compile_after(4)
    x = P.to_tensor(np.random.randn(3, 3).astype(np.float32))
    for _ in range(6):
        P.tanh(x)
    s = _op_stats("tanh")
    assert s["misses"] == 1 and s["deferred"] == 2, s   # calls 2 and 3
    assert s["hits"] == 3 and s["retraces"] == 1, s     # calls 4..6
