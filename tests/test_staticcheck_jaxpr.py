"""Jaxpr tier of graftcheck (tools/staticcheck/jaxpr + jit/passes/lint).

Mirrors tests/test_staticcheck.py's structure, one layer up the stack:
1. known-answer fixtures (tests/staticcheck_proj/jaxpr_steps.py): one
   deliberately hazardous CAPTURED step per jaxpr rule, traced through the
   real capture machinery — each rule fires exactly where expected, the
   clean step and the pragma'd step stay quiet;
2. ratchet semantics over jaxpr findings (same baseline.json mechanics as
   the AST tier — both tiers share one ratchet);
3. the real gate: the repo's canonical steps (TrainStep on the proxy
   llama, the serving slot/verify steps, a to_static program) must lint
   CLEAN — zero unbaselined jaxpr findings on the shipped tree;
4. the CLI demonstration: `python -m tools.staticcheck --ci` exits
   nonzero on a NEW jaxpr-tier finding.
"""
import os
import runpy
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_STEPS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "staticcheck_proj", "jaxpr_steps.py")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.staticcheck import load_baseline, new_findings, save_baseline  # noqa: E402
from tools.staticcheck.baseline import DEFAULT_BASELINE  # noqa: E402
from tools.staticcheck.jaxpr import (  # noqa: E402
    JAXPR_RULES, collect_findings)


@pytest.fixture(scope="module")
def fixture_findings():
    steps = runpy.run_path(FIXTURE_STEPS)["collect"](REPO)
    return collect_findings(REPO, steps=steps)


@pytest.fixture(scope="module")
def canonical_findings():
    # shared: tracing the canonical steps is this module's expensive call
    return collect_findings(REPO)


# ---------------- rule engine parity ----------------

def test_jaxpr_rule_ids_mirror_lint_rules():
    from paddle_tpu.jit.passes import lint
    assert JAXPR_RULES == tuple("jaxpr-" + r for r in lint.RULES)


# ---------------- known-answer fixtures ----------------

def test_every_jaxpr_rule_fires_on_fixtures(fixture_findings):
    assert {f.rule for f in fixture_findings} == set(JAXPR_RULES), \
        [f.context for f in fixture_findings]


def test_known_answer_contexts(fixture_findings):
    by_ctx = {f.context: f.rule for f in fixture_findings}
    assert by_ctx == {
        "fixture/callback:callbacks=debug_callback": "jaxpr-host-callback",
        "fixture/dead_in_scan:dead=3": "jaxpr-dead-compute",
        "fixture/weak_scalar:weak_type_invars=(1,)":
            "jaxpr-recompile-hazard",
        "fixture/signature_churn:signature-churn": "jaxpr-recompile-hazard",
        "fixture/naked_collective:untagged=1":
            "jaxpr-unscheduled-collective",
        "fixture/fp32_beside_quantized:fp32_beside_quantized_axes=i":
            "jaxpr-unscheduled-collective",
        "fixture/quantized_writeback:donated_unmatched=(0,)":
            "jaxpr-donation-miss",
        "fixture/partial_donation:missed=(1,)": "jaxpr-donation-miss",
    }, by_ctx


def test_findings_anchor_at_fixture_file(fixture_findings):
    assert all(f.path == "tests/staticcheck_proj/jaxpr_steps.py"
               and f.line > 0 for f in fixture_findings), fixture_findings


def test_clean_and_pragma_steps_stay_quiet(fixture_findings):
    ctxs = {f.context for f in fixture_findings}
    assert not any(c.startswith("fixture/clean") for c in ctxs)
    # same violation as fixture/callback, allowlisted at the def line
    assert not any(c.startswith("fixture/pragma_callback") for c in ctxs)


def test_donation_regression_net_for_multichip_writeback(fixture_findings):
    """The PR-10 MULTICHIP write_back-before-rebuild donation bug: a
    donated fp32 param rebuilt at int8 leaves the donation unmatched —
    the jaxpr-donation-miss rule is the regression net that would have
    caught it at lowering time."""
    f = next(f for f in fixture_findings
             if f.context == "fixture/quantized_writeback:"
                             "donated_unmatched=(0,)")
    assert f.rule == "jaxpr-donation-miss"
    assert "deleted" in f.message and "write_back" in f.message


# ---------------- ratchet semantics (shared baseline mechanics) -------------

def test_jaxpr_findings_ride_the_ratchet(fixture_findings, tmp_path):
    bl = str(tmp_path / "bl.json")
    save_baseline(fixture_findings[:-1], bl)
    fresh = new_findings(fixture_findings, load_baseline(bl))
    assert fresh == fixture_findings[-1:]
    save_baseline(fixture_findings, bl)
    assert new_findings(fixture_findings, load_baseline(bl)) == []


def test_fast_mode_skips_the_trace(monkeypatch):
    """PT_STATICCHECK_FAST=1 is the tier-1 timing guard: the jaxpr trace
    is skipped entirely (the AST tier still runs elsewhere)."""
    monkeypatch.setenv("PT_STATICCHECK_FAST", "1")
    assert collect_findings(REPO) == []


# ---------------- in-process capture-tier integration ----------------

def test_lint_records_flow_to_profiler_summary():
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.jit import capture_step
    from paddle_tpu.jit.passes import lint

    @capture_step
    def _linted_fixture_step(x):
        import jax
        jax.debug.print("s={s}", s=x.sum()._value)
        return P.tanh(x)

    _linted_fixture_step(P.to_tensor(np.ones((4, 4), np.float32)))
    rec = lint.lint_records().get("_linted_fixture_step")
    assert rec is not None and rec["rules_hit"] == ["host-callback"], rec
    from paddle_tpu.profiler import lint_summary
    assert "_linted_fixture_step" in lint_summary()
    assert "host-callback" in lint_summary()


# ---------------- the real gate: canonical steps lint clean ----------------

def test_canonical_steps_all_capture(canonical_findings):
    # a canonical step failing capture surfaces as a capture-bailout
    # finding — assert the stronger form for a readable failure
    bails = [f for f in canonical_findings if "capture-bailout" in f.context]
    assert bails == [], [f.message for f in bails]


def test_clean_tree_zero_unbaselined_jaxpr_findings(canonical_findings):
    """The jaxpr-tier half of `python -m tools.staticcheck --ci`: the
    shipped tree's canonical steps must lint clean (nothing to baseline,
    so any finding at all is NEW and fails)."""
    fresh = new_findings(canonical_findings,
                         load_baseline(DEFAULT_BASELINE))
    assert fresh == [], "\n".join(f.format() for f in fresh)
    assert canonical_findings == [], \
        "\n".join(f.format() for f in canonical_findings)


# ---------------- the CLI gate ----------------

def test_cli_ci_exits_nonzero_on_new_jaxpr_finding(tmp_path):
    """`--ci` with the fixture steps swapped in (PT_STATICCHECK_STEPS)
    and an empty baseline: the jaxpr tier alone must fail the gate."""
    bl = str(tmp_path / "bl.json")
    save_baseline([], bl)
    env = dict(os.environ,
               PT_STATICCHECK_STEPS=FIXTURE_STEPS,
               PT_STATICCHECK_FAST="0")
    r = subprocess.run(
        [sys.executable, "-m", "tools.staticcheck", "--ci",
         "--rules", ",".join(JAXPR_RULES), "--baseline", bl],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "NEW violation" in r.stderr
    assert "jaxpr-donation-miss" in r.stdout
