"""auto_parallel tests: ProcessMesh/placements/shard_tensor/reshard/Engine.

Mirrors the reference's test/auto_parallel/ strategy (engine fit/eval/predict,
reshard correctness) on the 8-device virtual CPU mesh (conftest).
"""
import jax
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import ProcessMesh, Replicate, Shard
from paddle_tpu.parallel import mesh as mesh_mod


@pytest.fixture(autouse=True)
def clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def test_process_mesh_basics():
    m = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert m.shape == [2, 4]
    assert m.ndim == 2
    assert m.dim_names == ["x", "y"]
    assert m.process_ids == list(range(8))
    assert m.get_dim_size("y") == 4
    jm = m.jax_mesh()
    assert jm.shape == {"x": 2, "y": 4}
    sub = m[0]
    assert sub.shape == [4]
    assert sub.process_ids == [0, 1, 2, 3]


def test_shard_tensor_and_placements():
    mesh = ProcessMesh([[0, 1], [2, 3], [4, 5], [6, 7]], dim_names=["dp", "mp"])
    x = P.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh, [Shard(0), Shard(1)])
    spec = xs._value.sharding.spec
    assert tuple(spec) == ("dp", "mp")
    np.testing.assert_array_equal(xs.numpy(), x.numpy())
    # recover placements
    pls = dist.auto_parallel.get_placements(xs, mesh)
    assert pls[0] == Shard(0) and pls[1] == Shard(1)

    # reshard to replicated
    xr = dist.reshard(xs, mesh, [Replicate(), Replicate()])
    assert all(s is None for s in tuple(xr._value.sharding.spec)) or \
        len(tuple(xr._value.sharding.spec)) == 0
    np.testing.assert_array_equal(xr.numpy(), x.numpy())


def test_shard_layer_marks_params():
    mesh = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
    layer = nn.Linear(16, 32)

    def shard_fn(name, sub, m):
        for _, p in sub.named_parameters(include_sublayers=False):
            if p.ndim == 2:
                dist.shard_tensor(p, m, [Replicate(), Shard(1)])
            else:
                dist.shard_tensor(p, m, [Replicate(), Shard(0)])

    dist.shard_layer(layer, mesh, shard_fn)
    assert layer.weight._sharding is not None
    assert "mp" in str(layer.weight._value.sharding.spec)


def test_engine_fit_eval_predict():
    mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
    with mesh:
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
        loss = nn.MSELoss()
        opt = P.optimizer.AdamW(learning_rate=0.02, parameters=model.parameters())
        engine = dist.auto_parallel.Engine(model, loss, opt)

        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 128

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                x = rng.randn(8).astype(np.float32)
                return x, np.array([x[:4].sum()], np.float32)

        hist = engine.fit(DS(), batch_size=32, epochs=6, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5, hist["loss"][::8]
        ev = engine.evaluate(DS(), batch_size=32, verbose=0)
        assert ev["loss"] == pytest.approx(hist["loss"][-1], rel=1.0)
        preds = engine.predict(DS(), batch_size=32, verbose=0)
        assert len(preds) == 4 and preds[0].shape == [32, 1]


def test_engine_save_load(tmp_path):
    mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
    with mesh:
        model = nn.Linear(4, 4)
        opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        e = dist.auto_parallel.Engine(model, nn.MSELoss(), opt)
        w0 = model.weight.numpy().copy()
        path = str(tmp_path / "ckpt" / "model")
        e.save(path)
        model.weight.set_value(np.zeros_like(w0))
        e.load(path)
        np.testing.assert_allclose(model.weight.numpy(), w0)


def test_shard_op_constrains_output():
    mesh = ProcessMesh(list(range(8)), dim_names=["x"])
    mesh.install()
    import paddle_tpu.nn.functional as F

    matmul = dist.shard_op(P.matmul, mesh, out_placements=[Shard(0)])
    a = P.randn([8, 16])
    b = P.randn([16, 4])
    out = matmul(a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    assert "x" in str(out._value.sharding.spec)


def test_global_scatter_gather_roundtrip():
    """global_scatter/global_gather over an 'ep' axis inside shard_map."""
    import jax
    from jax.sharding import PartitionSpec
    import jax.numpy as jnp
    from paddle_tpu.distributed.utils import global_scatter, global_gather
    from paddle_tpu.distributed import collective

    mesh = mesh_mod.init_mesh({"ep": 8})
    g = dist.new_group(axis="ep")
    x = np.arange(8 * 8 * 4, dtype=np.float32).reshape(64, 4)

    from jax.experimental.shard_map import shard_map

    def body(v):
        t = P.Tensor(v)
        sent = global_scatter(t, group=g)
        back = global_gather(sent, group=g)
        return back._value, sent._value

    f = shard_map(body, mesh=mesh, in_specs=PartitionSpec("ep"),
                  out_specs=(PartitionSpec("ep"), PartitionSpec("ep")))
    back, sent = f(jnp.asarray(x))
    # scatter then gather restores the original layout
    np.testing.assert_array_equal(np.asarray(back), x)
    # scatter actually moved data: local block 0 of rank r holds rank 0's block r
    assert not np.array_equal(np.asarray(sent), x)


def test_engine_eval_sees_trained_weights():
    """Regression: evaluate/predict jit must read live params, not trace-time
    constants."""
    mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
    with mesh:
        model = nn.Linear(8, 1)
        opt = P.optimizer.AdamW(learning_rate=0.05, parameters=model.parameters())
        engine = dist.auto_parallel.Engine(model, nn.MSELoss(), opt)

        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 64

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                x = rng.randn(8).astype(np.float32)
                return x, np.array([x.sum()], np.float32)

        before = engine.evaluate(DS(), batch_size=32, verbose=0)["loss"]
        p0 = engine.predict(DS(), batch_size=32, verbose=0)[0].numpy().copy()
        engine.fit(DS(), batch_size=32, epochs=20, verbose=0)
        after = engine.evaluate(DS(), batch_size=32, verbose=0)["loss"]
        assert after < before * 0.2, (before, after)
        p1 = engine.predict(DS(), batch_size=32, verbose=0)[0].numpy()
        assert not np.allclose(p0, p1)


def test_shard_op_multi_output_passthrough():
    mesh = ProcessMesh(list(range(8)), dim_names=["x"])
    mesh.install()
    op = dist.shard_op(P.topk, mesh, out_placements=[[Shard(0)]])
    vals, idx = op(P.randn([8, 4]), 2)  # trailing output must survive
    assert vals.shape == [8, 2] and idx.shape == [8, 2]


def test_to_static_dist_model():
    mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
    with mesh:
        model = nn.Linear(8, 1)
        opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
        dm = dist.to_static(model, None, nn.MSELoss(), opt)
        x = P.randn([16, 8])
        y = P.randn([16, 1])
        l0 = float(dm(([x], [y])).numpy())
        for _ in range(30):
            l1 = float(dm(([x], [y])).numpy())
        assert l1 < l0
