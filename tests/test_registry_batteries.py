"""Registry-consistency orphan burn-down battery (ROADMAP standing debt).

Each op exercised here was a baselined `registry-consistency` orphan: a
dispatch site with a stable ``op_name`` that no test battery referenced
THROUGH the package. Per the burn-down rule these are retired by adding
batteries — real known-answer assertions via the public ``P.`` surface —
never by loosening the checker's resolution. The ratchet in
tools/staticcheck/baseline.json is re-cut downward as this file grows.
"""
import numpy as np

import paddle_tpu as P


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


# ---------------- inverse-hyperbolic + pointwise math ----------------

def test_inverse_hyperbolic_known_answers():
    x = P.to_tensor(np.asarray([1.0, 2.0, 10.0], np.float32))
    np.testing.assert_allclose(_np(P.acosh(x)), np.arccosh(_np(x)), rtol=1e-6)
    y = P.to_tensor(np.asarray([-2.0, 0.0, 3.0], np.float32))
    np.testing.assert_allclose(_np(P.asinh(y)), np.arcsinh(_np(y)), rtol=1e-6)
    z = P.to_tensor(np.asarray([-0.5, 0.0, 0.9], np.float32))
    np.testing.assert_allclose(_np(P.atanh(z)), np.arctanh(_np(z)), rtol=1e-6)


def test_neg_negative_positive_cbrt_sinc():
    x = P.to_tensor(np.asarray([-2.0, 0.0, 8.0], np.float32))
    np.testing.assert_array_equal(_np(P.neg(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.negative(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.positive(x)), _np(x))
    np.testing.assert_allclose(_np(P.cbrt(x)), np.cbrt(_np(x)), rtol=1e-6)
    s = P.to_tensor(np.asarray([0.0, 0.5, 1.0], np.float32))
    np.testing.assert_allclose(_np(P.sinc(s)), np.sinc(_np(s)), atol=1e-6)


def test_scale_divide_no_nan_and_increment():
    x = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_allclose(_np(P.scale(x, scale=3.0, bias=1.0)),
                               [4.0, 7.0], rtol=1e-6)
    num = P.to_tensor(np.asarray([6.0, 1.0], np.float32))
    den = P.to_tensor(np.asarray([3.0, 0.0], np.float32))
    np.testing.assert_array_equal(_np(P.divide_no_nan(num, den)), [2.0, 0.0])
    np.testing.assert_array_equal(_np(P.increment(P.to_tensor(
        np.asarray([5.0], np.float32)), value=2.0)), [7.0])


# ---------------- comparisons + predicates ----------------

def test_elementwise_comparisons_known_answers():
    a = P.to_tensor(np.asarray([1, 2, 3], np.int64))
    b = P.to_tensor(np.asarray([2, 2, 2], np.int64))
    np.testing.assert_array_equal(_np(P.equal(a, b)), [False, True, False])
    np.testing.assert_array_equal(_np(P.not_equal(a, b)),
                                  [True, False, True])
    np.testing.assert_array_equal(_np(P.less_than(a, b)),
                                  [True, False, False])
    np.testing.assert_array_equal(_np(P.less_equal(a, b)),
                                  [True, True, False])
    np.testing.assert_array_equal(_np(P.greater_than(a, b)),
                                  [False, False, True])
    np.testing.assert_array_equal(_np(P.greater_equal(a, b)),
                                  [False, True, True])
    assert bool(_np(P.equal_all(a, a))) is True
    assert bool(_np(P.equal_all(a, b))) is False


def test_float_predicates_and_reductions():
    x = P.to_tensor(np.asarray([1.0, np.inf, -np.inf, np.nan], np.float32))
    np.testing.assert_array_equal(_np(P.isfinite(x)),
                                  [True, False, False, False])
    np.testing.assert_array_equal(_np(P.isinf(x)),
                                  [False, True, True, False])
    np.testing.assert_array_equal(_np(P.isnan(x)),
                                  [False, False, False, True])
    np.testing.assert_array_equal(_np(P.isposinf(x)),
                                  [False, True, False, False])
    np.testing.assert_array_equal(_np(P.isneginf(x)),
                                  [False, False, True, False])
    m = P.to_tensor(np.asarray([[True, False], [False, False]]))
    assert bool(_np(P.any(m))) is True
    z = P.to_tensor(np.asarray([0.0, 2.0, 0.0, 3.0], np.float32))
    assert int(_np(P.count_nonzero(z))) == 2
    np.testing.assert_array_equal(_np(P.signbit(P.to_tensor(
        np.asarray([-1.0, 0.0, 2.0], np.float32)))), [True, False, False])


def test_allclose_isclose_contract():
    a = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    b = P.to_tensor(np.asarray([1.0 + 1e-7, 2.0], np.float32))
    assert bool(_np(P.allclose(a, b))) is True
    np.testing.assert_array_equal(
        _np(P.isclose(a, P.to_tensor(np.asarray([1.0, 9.0], np.float32)))),
        [True, False])


# ---------------- integer / bitwise / logical ----------------

def test_bitwise_family_known_answers():
    a = P.to_tensor(np.asarray([0b1100, 0b1010], np.int64))
    b = P.to_tensor(np.asarray([0b1010, 0b0110], np.int64))
    np.testing.assert_array_equal(_np(P.bitwise_and(a, b)), [0b1000, 0b0010])
    np.testing.assert_array_equal(_np(P.bitwise_or(a, b)), [0b1110, 0b1110])
    np.testing.assert_array_equal(_np(P.bitwise_xor(a, b)), [0b0110, 0b1100])
    np.testing.assert_array_equal(_np(P.bitwise_not(a)), [~0b1100, ~0b1010])
    t = P.to_tensor(np.asarray([True, True, False]))
    f = P.to_tensor(np.asarray([True, False, False]))
    np.testing.assert_array_equal(_np(P.logical_xor(t, f)),
                                  [False, True, False])


def test_integer_arithmetic_gcd_lcm_mod_floor_divide():
    a = P.to_tensor(np.asarray([12, 54], np.int64))
    b = P.to_tensor(np.asarray([8, 24], np.int64))
    np.testing.assert_array_equal(_np(P.gcd(a, b)), [4, 6])
    np.testing.assert_array_equal(_np(P.lcm(a, b)), [24, 216])
    np.testing.assert_array_equal(_np(P.floor_divide(a, b)), [1, 2])
    np.testing.assert_array_equal(_np(P.mod(a, b)), [4, 6])


# ---------------- complex views ----------------

def test_complex_real_imag_conj():
    c = P.to_tensor(np.asarray([1 + 2j, 3 - 4j], np.complex64))
    np.testing.assert_array_equal(_np(P.real(c)), [1.0, 3.0])
    np.testing.assert_array_equal(_np(P.imag(c)), [2.0, -4.0])
    np.testing.assert_array_equal(_np(P.conj(c)),
                                  np.conj(_np(c)))
    r = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.isreal(c)), [False, False])
    np.testing.assert_array_equal(_np(P.isreal(r)), [True, True])


# ---------------- shape / assembly breadth ----------------

def test_stacking_family_matches_numpy():
    a = np.arange(4, dtype=np.float32)
    b = a + 10
    ta, tb = P.to_tensor(a), P.to_tensor(b)
    np.testing.assert_array_equal(_np(P.hstack((ta, tb))), np.hstack((a, b)))
    np.testing.assert_array_equal(_np(P.vstack((ta, tb))), np.vstack((a, b)))
    np.testing.assert_array_equal(_np(P.dstack((ta, tb))), np.dstack((a, b)))
    np.testing.assert_array_equal(_np(P.column_stack((ta, tb))),
                                  np.column_stack((a, b)))
    np.testing.assert_array_equal(_np(P.row_stack((ta, tb))),
                                  np.vstack((a, b)))


def test_axis_moves_and_transpose():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    tx = P.to_tensor(x)
    np.testing.assert_array_equal(_np(P.moveaxis(tx, 0, 2)),
                                  np.moveaxis(x, 0, 2))
    np.testing.assert_array_equal(_np(P.swapaxes(tx, 0, 1)),
                                  np.swapaxes(x, 0, 1))
    m = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(_np(P.t(m)), _np(m).T)


def test_diag_embed_block_diag_bincount_unstack():
    v = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.diag_embed(v)),
                                  np.diag(np.asarray([1.0, 2.0])))
    a = P.to_tensor(np.eye(2, dtype=np.float32))
    b = P.to_tensor(np.full((1, 1), 3.0, np.float32))
    bd = _np(P.block_diag([a, b]))
    want = np.zeros((3, 3), np.float32)
    want[:2, :2] = np.eye(2)
    want[2, 2] = 3.0
    np.testing.assert_array_equal(bd, want)
    ids = P.to_tensor(np.asarray([0, 1, 1, 3], np.int64))
    np.testing.assert_array_equal(_np(P.bincount(ids)), [1, 2, 0, 1])
    parts = P.unstack(
        P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)))
    assert len(parts) == 2
    np.testing.assert_array_equal(_np(parts[1]), [3.0, 4.0, 5.0])


# ---------------- PR 13 burn-down: shifts, scatter, assembly breadth ----------------
# (each op below was a baselined registry-consistency orphan; the battery
# retires it through the public P./F. surface with real known answers)

def test_bitwise_shift_family_known_answers():
    a = P.to_tensor(np.asarray([0b0011, 0b0101], np.int64))
    s = P.to_tensor(np.asarray([1, 2], np.int64))
    np.testing.assert_array_equal(_np(P.bitwise_left_shift(a, s)), [6, 20])
    np.testing.assert_array_equal(_np(P.bitwise_right_shift(a, s)), [1, 1])
    np.testing.assert_array_equal(_np(P.left_shift(a, s)), [6, 20])
    np.testing.assert_array_equal(_np(P.right_shift(a, s)), [1, 1])
    np.testing.assert_array_equal(_np(P.bitwise_invert(a)), [~3, ~5])


def test_combinations_cartesian_prod_known_answers():
    np.testing.assert_array_equal(
        _np(P.combinations(P.to_tensor(np.asarray([1, 2, 3], np.int64)),
                           r=2)),
        [[1, 2], [1, 3], [2, 3]])
    np.testing.assert_array_equal(
        _np(P.cartesian_prod([P.to_tensor(np.asarray([1, 2], np.int64)),
                              P.to_tensor(np.asarray([3, 4], np.int64))])),
        [[1, 3], [1, 4], [2, 3], [2, 4]])


def test_scatter_family_known_answers():
    x = np.zeros((3, 3), np.float32)
    np.testing.assert_array_equal(
        _np(P.diagonal_scatter(
            P.to_tensor(x),
            P.to_tensor(np.asarray([1., 2., 3.], np.float32)))),
        np.diag(np.asarray([1., 2., 3.])))
    got = _np(P.select_scatter(
        P.to_tensor(x), P.to_tensor(np.asarray([7., 8., 9.], np.float32)),
        0, 1))
    want = x.copy()
    want[1] = [7., 8., 9.]
    np.testing.assert_array_equal(got, want)
    got = _np(P.slice_scatter(
        P.to_tensor(x), P.to_tensor(np.full((3, 1), 5.0, np.float32)),
        axes=[1], starts=[2], ends=[3], strides=[1]))
    want = x.copy()
    want[:, 2] = 5.0
    np.testing.assert_array_equal(got, want)


def test_pdist_rearrange_reduce_as():
    pts = np.asarray([[0.0, 0.0], [3.0, 4.0], [0.0, 1.0]], np.float32)
    np.testing.assert_allclose(_np(P.pdist(P.to_tensor(pts))),
                               [5.0, 1.0, np.hypot(3.0, 3.0)], rtol=1e-6)
    m = np.arange(6, dtype=np.int64).reshape(2, 3)
    np.testing.assert_array_equal(
        _np(P.rearrange(P.to_tensor(m), "a b -> b a")), m.T)
    np.testing.assert_array_equal(
        _np(P.reduce_as(
            P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)),
            P.to_tensor(np.zeros((3,), np.float32)))),
        [3.0, 5.0, 7.0])


def test_angle_gammaincc_known_answers():
    c = P.to_tensor(np.asarray([1 + 1j, -1 + 0j], np.complex64))
    np.testing.assert_allclose(_np(P.angle(c)), [np.pi / 4, np.pi],
                               rtol=1e-6)
    # gammaincc(1, x) == exp(-x) — the regularized upper incomplete gamma
    got = _np(P.gammaincc(P.to_tensor(np.asarray([1.0, 1.0], np.float32)),
                          P.to_tensor(np.asarray([1.0, 2.0], np.float32))))
    np.testing.assert_allclose(got, np.exp([-1.0, -2.0]), rtol=1e-5)


def test_broadcast_fill_diagonal_assign():
    b1, b2 = P.broadcast_tensors([
        P.to_tensor(np.ones((1, 3), np.float32)),
        P.to_tensor(np.ones((2, 1), np.float32))])
    assert _np(b1).shape == (2, 3) and _np(b2).shape == (2, 3)
    np.testing.assert_array_equal(
        _np(P.fill_diagonal_tensor(
            P.to_tensor(np.zeros((3, 3), np.float32)),
            P.to_tensor(np.asarray([4., 5., 6.], np.float32)))),
        np.diag(np.asarray([4., 5., 6.])))
    np.testing.assert_array_equal(
        _np(P.assign(P.to_tensor(np.asarray([1.5, 2.5], np.float32)))),
        [1.5, 2.5])


def test_shard_index_slice_strided_slice():
    # shard 0 of 12 ids over 2 shards owns [0, 6): in-shard ids keep their
    # local offset, foreign ids map to ignore_value
    ids = P.to_tensor(np.asarray([[1], [6], [11]], np.int64))
    np.testing.assert_array_equal(
        _np(P.shard_index(ids, index_num=12, nshards=2, shard_id=0)),
        [[1], [-1], [-1]])
    m = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_array_equal(
        _np(P.slice(P.to_tensor(m), axes=[0, 1], starts=[1, 0],
                    ends=[3, 2])),
        m[1:3, 0:2])
    np.testing.assert_array_equal(
        _np(P.strided_slice(P.to_tensor(m), axes=[1], starts=[0], ends=[4],
                            strides=[2])),
        m[:, 0:4:2])


def test_linalg_cond_pca_lowrank():
    np.testing.assert_allclose(
        _np(P.linalg.cond(P.to_tensor(np.eye(3, dtype=np.float32)))),
        1.0, rtol=1e-5)
    data = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    U, S, V = P.linalg.pca_lowrank(P.to_tensor(data), q=4)
    rec = _np(U) * _np(S)[None, :] @ _np(V).T
    np.testing.assert_allclose(rec, data - data.mean(0), atol=1e-3)


def test_fftn_family_matches_numpy():
    arr = np.random.RandomState(1).randn(4, 4).astype(np.float32)
    np.testing.assert_allclose(_np(P.fft.fftn(P.to_tensor(arr))),
                               np.fft.fftn(arr), atol=1e-4)
    np.testing.assert_allclose(
        _np(P.fft.ifftn(P.to_tensor(arr.astype(np.complex64)))),
        np.fft.ifftn(arr), atol=1e-4)
    np.testing.assert_allclose(_np(P.fft.rfftn(P.to_tensor(arr))),
                               np.fft.rfftn(arr), atol=1e-4)
    rf = np.fft.rfftn(arr).astype(np.complex64)
    np.testing.assert_allclose(_np(P.fft.irfftn(P.to_tensor(rf))), arr,
                               atol=1e-4)
    # hfftn == fftn over the leading axes + hermitian fft on the last
    arr2 = np.random.RandomState(3).randn(2, 4).astype(np.complex64)
    want = np.fft.hfft(np.fft.fftn(arr2, axes=[0]), axis=-1)
    np.testing.assert_allclose(_np(P.fft.hfftn(P.to_tensor(arr2))), want,
                               atol=1e-3)


def test_pooling_1d_3d_known_answers():
    import paddle_tpu.nn.functional as F

    x1 = np.arange(8, dtype=np.float32).reshape(1, 1, 8)
    np.testing.assert_array_equal(
        _np(F.avg_pool1d(P.to_tensor(x1), kernel_size=2)),
        x1.reshape(1, 1, 4, 2).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_avg_pool1d(P.to_tensor(x1), output_size=2)),
        x1.reshape(1, 1, 2, 4).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool1d(P.to_tensor(x1), output_size=2)),
        x1.reshape(1, 1, 2, 4).max(-1))
    x2 = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool2d(P.to_tensor(x2), output_size=2)),
        x2.reshape(1, 1, 2, 2, 2, 2).max((3, 5)))
    x3 = np.arange(16, dtype=np.float32).reshape(1, 1, 2, 2, 4)
    np.testing.assert_array_equal(
        _np(F.avg_pool3d(P.to_tensor(x3), kernel_size=(1, 1, 2))),
        x3.reshape(1, 1, 2, 2, 2, 2).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool3d(P.to_tensor(x3), output_size=(2, 2, 2))),
        x3.reshape(1, 1, 2, 1, 2, 1, 2, 2).max((3, 5, 7)))
    np.testing.assert_array_equal(
        _np(F.adaptive_avg_pool3d(P.to_tensor(x3), output_size=(2, 2, 2))),
        x3.reshape(1, 1, 2, 1, 2, 1, 2, 2).mean((3, 5, 7)))


def test_conv_1d_3d_known_answers():
    import paddle_tpu.nn.functional as F

    xc = np.arange(6, dtype=np.float32).reshape(1, 1, 6)
    w = np.ones((1, 1, 3), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv1d(P.to_tensor(xc), P.to_tensor(w))),
        np.asarray([[[3., 6., 9., 12.]]]))
    wt = np.ones((1, 1, 2), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv1d_transpose(
            P.to_tensor(np.asarray([[[1., 2., 3.]]], np.float32)),
            P.to_tensor(wt))),
        [[[1., 3., 5., 3.]]])
    x3 = np.ones((1, 1, 2, 2, 2), np.float32)
    w3 = np.ones((1, 1, 2, 2, 2), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv3d(P.to_tensor(x3), P.to_tensor(w3))), [[[[[8.]]]]])
    w3t = np.ones((1, 1, 1, 1, 2), np.float32)
    got = _np(F.conv3d_transpose(P.to_tensor(x3), P.to_tensor(w3t)))
    assert got.shape == (1, 1, 2, 2, 3)
    np.testing.assert_array_equal(got[0, 0, 0, 0], [1., 2., 1.])
