"""Registry-consistency orphan burn-down battery (ROADMAP standing debt).

Each op exercised here was a baselined `registry-consistency` orphan: a
dispatch site with a stable ``op_name`` that no test battery referenced
THROUGH the package. Per the burn-down rule these are retired by adding
batteries — real known-answer assertions via the public ``P.`` surface —
never by loosening the checker's resolution. The ratchet in
tools/staticcheck/baseline.json is re-cut downward as this file grows.
"""
import numpy as np

import paddle_tpu as P
import paddle_tpu.nn.functional as F


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


# ---------------- inverse-hyperbolic + pointwise math ----------------

def test_inverse_hyperbolic_known_answers():
    x = P.to_tensor(np.asarray([1.0, 2.0, 10.0], np.float32))
    np.testing.assert_allclose(_np(P.acosh(x)), np.arccosh(_np(x)), rtol=1e-6)
    y = P.to_tensor(np.asarray([-2.0, 0.0, 3.0], np.float32))
    np.testing.assert_allclose(_np(P.asinh(y)), np.arcsinh(_np(y)), rtol=1e-6)
    z = P.to_tensor(np.asarray([-0.5, 0.0, 0.9], np.float32))
    np.testing.assert_allclose(_np(P.atanh(z)), np.arctanh(_np(z)), rtol=1e-6)


def test_neg_negative_positive_cbrt_sinc():
    x = P.to_tensor(np.asarray([-2.0, 0.0, 8.0], np.float32))
    np.testing.assert_array_equal(_np(P.neg(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.negative(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.positive(x)), _np(x))
    np.testing.assert_allclose(_np(P.cbrt(x)), np.cbrt(_np(x)), rtol=1e-6)
    s = P.to_tensor(np.asarray([0.0, 0.5, 1.0], np.float32))
    np.testing.assert_allclose(_np(P.sinc(s)), np.sinc(_np(s)), atol=1e-6)


def test_scale_divide_no_nan_and_increment():
    x = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_allclose(_np(P.scale(x, scale=3.0, bias=1.0)),
                               [4.0, 7.0], rtol=1e-6)
    num = P.to_tensor(np.asarray([6.0, 1.0], np.float32))
    den = P.to_tensor(np.asarray([3.0, 0.0], np.float32))
    np.testing.assert_array_equal(_np(P.divide_no_nan(num, den)), [2.0, 0.0])
    np.testing.assert_array_equal(_np(P.increment(P.to_tensor(
        np.asarray([5.0], np.float32)), value=2.0)), [7.0])


# ---------------- comparisons + predicates ----------------

def test_elementwise_comparisons_known_answers():
    a = P.to_tensor(np.asarray([1, 2, 3], np.int64))
    b = P.to_tensor(np.asarray([2, 2, 2], np.int64))
    np.testing.assert_array_equal(_np(P.equal(a, b)), [False, True, False])
    np.testing.assert_array_equal(_np(P.not_equal(a, b)),
                                  [True, False, True])
    np.testing.assert_array_equal(_np(P.less_than(a, b)),
                                  [True, False, False])
    np.testing.assert_array_equal(_np(P.less_equal(a, b)),
                                  [True, True, False])
    np.testing.assert_array_equal(_np(P.greater_than(a, b)),
                                  [False, False, True])
    np.testing.assert_array_equal(_np(P.greater_equal(a, b)),
                                  [False, True, True])
    assert bool(_np(P.equal_all(a, a))) is True
    assert bool(_np(P.equal_all(a, b))) is False


def test_float_predicates_and_reductions():
    x = P.to_tensor(np.asarray([1.0, np.inf, -np.inf, np.nan], np.float32))
    np.testing.assert_array_equal(_np(P.isfinite(x)),
                                  [True, False, False, False])
    np.testing.assert_array_equal(_np(P.isinf(x)),
                                  [False, True, True, False])
    np.testing.assert_array_equal(_np(P.isnan(x)),
                                  [False, False, False, True])
    np.testing.assert_array_equal(_np(P.isposinf(x)),
                                  [False, True, False, False])
    np.testing.assert_array_equal(_np(P.isneginf(x)),
                                  [False, False, True, False])
    m = P.to_tensor(np.asarray([[True, False], [False, False]]))
    assert bool(_np(P.any(m))) is True
    z = P.to_tensor(np.asarray([0.0, 2.0, 0.0, 3.0], np.float32))
    assert int(_np(P.count_nonzero(z))) == 2
    np.testing.assert_array_equal(_np(P.signbit(P.to_tensor(
        np.asarray([-1.0, 0.0, 2.0], np.float32)))), [True, False, False])


def test_allclose_isclose_contract():
    a = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    b = P.to_tensor(np.asarray([1.0 + 1e-7, 2.0], np.float32))
    assert bool(_np(P.allclose(a, b))) is True
    np.testing.assert_array_equal(
        _np(P.isclose(a, P.to_tensor(np.asarray([1.0, 9.0], np.float32)))),
        [True, False])


# ---------------- integer / bitwise / logical ----------------

def test_bitwise_family_known_answers():
    a = P.to_tensor(np.asarray([0b1100, 0b1010], np.int64))
    b = P.to_tensor(np.asarray([0b1010, 0b0110], np.int64))
    np.testing.assert_array_equal(_np(P.bitwise_and(a, b)), [0b1000, 0b0010])
    np.testing.assert_array_equal(_np(P.bitwise_or(a, b)), [0b1110, 0b1110])
    np.testing.assert_array_equal(_np(P.bitwise_xor(a, b)), [0b0110, 0b1100])
    np.testing.assert_array_equal(_np(P.bitwise_not(a)), [~0b1100, ~0b1010])
    t = P.to_tensor(np.asarray([True, True, False]))
    f = P.to_tensor(np.asarray([True, False, False]))
    np.testing.assert_array_equal(_np(P.logical_xor(t, f)),
                                  [False, True, False])


def test_integer_arithmetic_gcd_lcm_mod_floor_divide():
    a = P.to_tensor(np.asarray([12, 54], np.int64))
    b = P.to_tensor(np.asarray([8, 24], np.int64))
    np.testing.assert_array_equal(_np(P.gcd(a, b)), [4, 6])
    np.testing.assert_array_equal(_np(P.lcm(a, b)), [24, 216])
    np.testing.assert_array_equal(_np(P.floor_divide(a, b)), [1, 2])
    np.testing.assert_array_equal(_np(P.mod(a, b)), [4, 6])


# ---------------- complex views ----------------

def test_complex_real_imag_conj():
    c = P.to_tensor(np.asarray([1 + 2j, 3 - 4j], np.complex64))
    np.testing.assert_array_equal(_np(P.real(c)), [1.0, 3.0])
    np.testing.assert_array_equal(_np(P.imag(c)), [2.0, -4.0])
    np.testing.assert_array_equal(_np(P.conj(c)),
                                  np.conj(_np(c)))
    r = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.isreal(c)), [False, False])
    np.testing.assert_array_equal(_np(P.isreal(r)), [True, True])


# ---------------- shape / assembly breadth ----------------

def test_stacking_family_matches_numpy():
    a = np.arange(4, dtype=np.float32)
    b = a + 10
    ta, tb = P.to_tensor(a), P.to_tensor(b)
    np.testing.assert_array_equal(_np(P.hstack((ta, tb))), np.hstack((a, b)))
    np.testing.assert_array_equal(_np(P.vstack((ta, tb))), np.vstack((a, b)))
    np.testing.assert_array_equal(_np(P.dstack((ta, tb))), np.dstack((a, b)))
    np.testing.assert_array_equal(_np(P.column_stack((ta, tb))),
                                  np.column_stack((a, b)))
    np.testing.assert_array_equal(_np(P.row_stack((ta, tb))),
                                  np.vstack((a, b)))


def test_axis_moves_and_transpose():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    tx = P.to_tensor(x)
    np.testing.assert_array_equal(_np(P.moveaxis(tx, 0, 2)),
                                  np.moveaxis(x, 0, 2))
    np.testing.assert_array_equal(_np(P.swapaxes(tx, 0, 1)),
                                  np.swapaxes(x, 0, 1))
    m = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(_np(P.t(m)), _np(m).T)


def test_diag_embed_block_diag_bincount_unstack():
    v = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.diag_embed(v)),
                                  np.diag(np.asarray([1.0, 2.0])))
    a = P.to_tensor(np.eye(2, dtype=np.float32))
    b = P.to_tensor(np.full((1, 1), 3.0, np.float32))
    bd = _np(P.block_diag([a, b]))
    want = np.zeros((3, 3), np.float32)
    want[:2, :2] = np.eye(2)
    want[2, 2] = 3.0
    np.testing.assert_array_equal(bd, want)
    ids = P.to_tensor(np.asarray([0, 1, 1, 3], np.int64))
    np.testing.assert_array_equal(_np(P.bincount(ids)), [1, 2, 0, 1])
    parts = P.unstack(
        P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)))
    assert len(parts) == 2
    np.testing.assert_array_equal(_np(parts[1]), [3.0, 4.0, 5.0])


# ---------------- PR 13 burn-down: shifts, scatter, assembly breadth ----------------
# (each op below was a baselined registry-consistency orphan; the battery
# retires it through the public P./F. surface with real known answers)

def test_bitwise_shift_family_known_answers():
    a = P.to_tensor(np.asarray([0b0011, 0b0101], np.int64))
    s = P.to_tensor(np.asarray([1, 2], np.int64))
    np.testing.assert_array_equal(_np(P.bitwise_left_shift(a, s)), [6, 20])
    np.testing.assert_array_equal(_np(P.bitwise_right_shift(a, s)), [1, 1])
    np.testing.assert_array_equal(_np(P.left_shift(a, s)), [6, 20])
    np.testing.assert_array_equal(_np(P.right_shift(a, s)), [1, 1])
    np.testing.assert_array_equal(_np(P.bitwise_invert(a)), [~3, ~5])


def test_combinations_cartesian_prod_known_answers():
    np.testing.assert_array_equal(
        _np(P.combinations(P.to_tensor(np.asarray([1, 2, 3], np.int64)),
                           r=2)),
        [[1, 2], [1, 3], [2, 3]])
    np.testing.assert_array_equal(
        _np(P.cartesian_prod([P.to_tensor(np.asarray([1, 2], np.int64)),
                              P.to_tensor(np.asarray([3, 4], np.int64))])),
        [[1, 3], [1, 4], [2, 3], [2, 4]])


def test_scatter_family_known_answers():
    x = np.zeros((3, 3), np.float32)
    np.testing.assert_array_equal(
        _np(P.diagonal_scatter(
            P.to_tensor(x),
            P.to_tensor(np.asarray([1., 2., 3.], np.float32)))),
        np.diag(np.asarray([1., 2., 3.])))
    got = _np(P.select_scatter(
        P.to_tensor(x), P.to_tensor(np.asarray([7., 8., 9.], np.float32)),
        0, 1))
    want = x.copy()
    want[1] = [7., 8., 9.]
    np.testing.assert_array_equal(got, want)
    got = _np(P.slice_scatter(
        P.to_tensor(x), P.to_tensor(np.full((3, 1), 5.0, np.float32)),
        axes=[1], starts=[2], ends=[3], strides=[1]))
    want = x.copy()
    want[:, 2] = 5.0
    np.testing.assert_array_equal(got, want)


def test_pdist_rearrange_reduce_as():
    pts = np.asarray([[0.0, 0.0], [3.0, 4.0], [0.0, 1.0]], np.float32)
    np.testing.assert_allclose(_np(P.pdist(P.to_tensor(pts))),
                               [5.0, 1.0, np.hypot(3.0, 3.0)], rtol=1e-6)
    m = np.arange(6, dtype=np.int64).reshape(2, 3)
    np.testing.assert_array_equal(
        _np(P.rearrange(P.to_tensor(m), "a b -> b a")), m.T)
    np.testing.assert_array_equal(
        _np(P.reduce_as(
            P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)),
            P.to_tensor(np.zeros((3,), np.float32)))),
        [3.0, 5.0, 7.0])


def test_angle_gammaincc_known_answers():
    c = P.to_tensor(np.asarray([1 + 1j, -1 + 0j], np.complex64))
    np.testing.assert_allclose(_np(P.angle(c)), [np.pi / 4, np.pi],
                               rtol=1e-6)
    # gammaincc(1, x) == exp(-x) — the regularized upper incomplete gamma
    got = _np(P.gammaincc(P.to_tensor(np.asarray([1.0, 1.0], np.float32)),
                          P.to_tensor(np.asarray([1.0, 2.0], np.float32))))
    np.testing.assert_allclose(got, np.exp([-1.0, -2.0]), rtol=1e-5)


def test_broadcast_fill_diagonal_assign():
    b1, b2 = P.broadcast_tensors([
        P.to_tensor(np.ones((1, 3), np.float32)),
        P.to_tensor(np.ones((2, 1), np.float32))])
    assert _np(b1).shape == (2, 3) and _np(b2).shape == (2, 3)
    np.testing.assert_array_equal(
        _np(P.fill_diagonal_tensor(
            P.to_tensor(np.zeros((3, 3), np.float32)),
            P.to_tensor(np.asarray([4., 5., 6.], np.float32)))),
        np.diag(np.asarray([4., 5., 6.])))
    np.testing.assert_array_equal(
        _np(P.assign(P.to_tensor(np.asarray([1.5, 2.5], np.float32)))),
        [1.5, 2.5])


def test_shard_index_slice_strided_slice():
    # shard 0 of 12 ids over 2 shards owns [0, 6): in-shard ids keep their
    # local offset, foreign ids map to ignore_value
    ids = P.to_tensor(np.asarray([[1], [6], [11]], np.int64))
    np.testing.assert_array_equal(
        _np(P.shard_index(ids, index_num=12, nshards=2, shard_id=0)),
        [[1], [-1], [-1]])
    m = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_array_equal(
        _np(P.slice(P.to_tensor(m), axes=[0, 1], starts=[1, 0],
                    ends=[3, 2])),
        m[1:3, 0:2])
    np.testing.assert_array_equal(
        _np(P.strided_slice(P.to_tensor(m), axes=[1], starts=[0], ends=[4],
                            strides=[2])),
        m[:, 0:4:2])


def test_linalg_cond_pca_lowrank():
    np.testing.assert_allclose(
        _np(P.linalg.cond(P.to_tensor(np.eye(3, dtype=np.float32)))),
        1.0, rtol=1e-5)
    data = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    U, S, V = P.linalg.pca_lowrank(P.to_tensor(data), q=4)
    rec = _np(U) * _np(S)[None, :] @ _np(V).T
    np.testing.assert_allclose(rec, data - data.mean(0), atol=1e-3)


def test_fftn_family_matches_numpy():
    arr = np.random.RandomState(1).randn(4, 4).astype(np.float32)
    np.testing.assert_allclose(_np(P.fft.fftn(P.to_tensor(arr))),
                               np.fft.fftn(arr), atol=1e-4)
    np.testing.assert_allclose(
        _np(P.fft.ifftn(P.to_tensor(arr.astype(np.complex64)))),
        np.fft.ifftn(arr), atol=1e-4)
    np.testing.assert_allclose(_np(P.fft.rfftn(P.to_tensor(arr))),
                               np.fft.rfftn(arr), atol=1e-4)
    rf = np.fft.rfftn(arr).astype(np.complex64)
    np.testing.assert_allclose(_np(P.fft.irfftn(P.to_tensor(rf))), arr,
                               atol=1e-4)
    # hfftn == fftn over the leading axes + hermitian fft on the last
    arr2 = np.random.RandomState(3).randn(2, 4).astype(np.complex64)
    want = np.fft.hfft(np.fft.fftn(arr2, axes=[0]), axis=-1)
    np.testing.assert_allclose(_np(P.fft.hfftn(P.to_tensor(arr2))), want,
                               atol=1e-3)


def test_pooling_1d_3d_known_answers():
    import paddle_tpu.nn.functional as F

    x1 = np.arange(8, dtype=np.float32).reshape(1, 1, 8)
    np.testing.assert_array_equal(
        _np(F.avg_pool1d(P.to_tensor(x1), kernel_size=2)),
        x1.reshape(1, 1, 4, 2).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_avg_pool1d(P.to_tensor(x1), output_size=2)),
        x1.reshape(1, 1, 2, 4).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool1d(P.to_tensor(x1), output_size=2)),
        x1.reshape(1, 1, 2, 4).max(-1))
    x2 = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool2d(P.to_tensor(x2), output_size=2)),
        x2.reshape(1, 1, 2, 2, 2, 2).max((3, 5)))
    x3 = np.arange(16, dtype=np.float32).reshape(1, 1, 2, 2, 4)
    np.testing.assert_array_equal(
        _np(F.avg_pool3d(P.to_tensor(x3), kernel_size=(1, 1, 2))),
        x3.reshape(1, 1, 2, 2, 2, 2).mean(-1))
    np.testing.assert_array_equal(
        _np(F.adaptive_max_pool3d(P.to_tensor(x3), output_size=(2, 2, 2))),
        x3.reshape(1, 1, 2, 1, 2, 1, 2, 2).max((3, 5, 7)))
    np.testing.assert_array_equal(
        _np(F.adaptive_avg_pool3d(P.to_tensor(x3), output_size=(2, 2, 2))),
        x3.reshape(1, 1, 2, 1, 2, 1, 2, 2).mean((3, 5, 7)))


def test_conv_1d_3d_known_answers():
    import paddle_tpu.nn.functional as F

    xc = np.arange(6, dtype=np.float32).reshape(1, 1, 6)
    w = np.ones((1, 1, 3), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv1d(P.to_tensor(xc), P.to_tensor(w))),
        np.asarray([[[3., 6., 9., 12.]]]))
    wt = np.ones((1, 1, 2), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv1d_transpose(
            P.to_tensor(np.asarray([[[1., 2., 3.]]], np.float32)),
            P.to_tensor(wt))),
        [[[1., 3., 5., 3.]]])
    x3 = np.ones((1, 1, 2, 2, 2), np.float32)
    w3 = np.ones((1, 1, 2, 2, 2), np.float32)
    np.testing.assert_array_equal(
        _np(F.conv3d(P.to_tensor(x3), P.to_tensor(w3))), [[[[[8.]]]]])
    w3t = np.ones((1, 1, 1, 1, 2), np.float32)
    got = _np(F.conv3d_transpose(P.to_tensor(x3), P.to_tensor(w3t)))
    assert got.shape == (1, 1, 2, 2, 3)
    np.testing.assert_array_equal(got[0, 0, 0, 0], [1., 2., 1.])


# ---------------- PR 14 burn-down: logic, fused transformer ops, vision
# decode, static-compat metrics ----------------
# (each op below was a baselined registry-consistency orphan; the battery
# retires it through the public P./F./incubate surface with real known
# answers — derived from the op's contract, never read off the output)

def test_logical_family_and_clone():
    t = P.to_tensor(np.asarray([True, True, False]))
    f = P.to_tensor(np.asarray([True, False, False]))
    np.testing.assert_array_equal(_np(P.logical_and(t, f)),
                                  [True, False, False])
    np.testing.assert_array_equal(_np(P.logical_or(t, f)),
                                  [True, True, False])
    np.testing.assert_array_equal(_np(P.logical_not(f)),
                                  [False, True, True])
    x = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    c = P.clone(x)
    np.testing.assert_array_equal(_np(c), _np(x))


def test_activation_extras_known_answers():
    # maxout: channels regrouped [groups, C/groups], max over the group
    # axis — ch0/ch2 and ch1/ch3 compete on a 4-channel input
    x = P.to_tensor(np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2))
    np.testing.assert_array_equal(
        _np(F.maxout(x, groups=2)).ravel(), [4., 5., 6., 7.])
    # rrelu out of training: the deterministic mean slope (l+u)/2
    y = P.to_tensor(np.asarray([-4.0, 2.0], np.float32))
    np.testing.assert_array_equal(
        _np(F.rrelu(y, lower=0.25, upper=0.75, training=False)), [-2., 2.])
    # alpha_dropout at p=0 is the identity (SELU-preserving dropout)
    z = P.to_tensor(np.asarray([-1.0, 0.5], np.float32))
    np.testing.assert_array_equal(_np(F.alpha_dropout(z, p=0.0)), _np(z))
    # gumbel_softmax: rows are distributions; hard=True rows are one-hot
    logits = P.to_tensor(np.asarray([[2.0, 1.0, 0.5]], np.float32))
    soft = _np(F.gumbel_softmax(logits, temperature=1.0))
    np.testing.assert_allclose(soft.sum(-1), 1.0, rtol=1e-5)
    hard = _np(F.gumbel_softmax(logits, temperature=1.0, hard=True))
    assert sorted(hard.ravel().tolist())[:2] == [0.0, 0.0]
    assert hard.sum() == 1.0


def test_common_functional_known_answers():
    # bilinear with an all-ones kernel: sum(x1) * sum(x2) per output
    x1 = P.to_tensor(np.asarray([[1.0, 2.0]], np.float32))
    x2 = P.to_tensor(np.asarray([[3.0, 4.0, 5.0]], np.float32))
    w = P.to_tensor(np.ones((2, 2, 3), np.float32))
    np.testing.assert_array_equal(_np(F.bilinear(x1, x2, w)), [[36., 36.]])
    # label_smooth: (1-eps) * onehot + eps / classes
    oh = P.to_tensor(np.asarray([[0.0, 1.0]], np.float32))
    np.testing.assert_allclose(_np(F.label_smooth(oh, epsilon=0.1)),
                               [[0.05, 0.95]], rtol=1e-6)
    # triplet loss, default L2 distance, margin 1:
    # max(d(a,p) - d(a,n) + 1, 0) with d(a,p)=10, d(a,n)=5 -> 6
    a = P.to_tensor(np.zeros((1, 2), np.float32))
    p = P.to_tensor(np.asarray([[6.0, 8.0]], np.float32))
    n = P.to_tensor(np.asarray([[3.0, 4.0]], np.float32))
    np.testing.assert_allclose(
        _np(F.triplet_margin_with_distance_loss(a, p, n)), 6.0, rtol=1e-6)
    np.testing.assert_allclose(
        _np(F.triplet_margin_with_distance_loss(a, n, p)), 0.0, atol=1e-7)


def test_metric_and_static_compat_metrics():
    import paddle_tpu.metric as M
    import paddle_tpu.static.compat as C

    pred = P.to_tensor(np.asarray([[0.1, 0.9], [0.8, 0.2]], np.float32))
    lab = P.to_tensor(np.asarray([[1], [1]], np.int64))
    np.testing.assert_allclose(_np(M.accuracy(pred, lab, k=1)), 0.5)
    # auc: perfectly ranked positives -> 1.0
    scores = P.to_tensor(np.asarray([[0.1, 0.9], [0.8, 0.2]], np.float32))
    labels = P.to_tensor(np.asarray([[1], [0]], np.int64))
    auc_val = C.auc(scores, labels)[0]
    np.testing.assert_allclose(_np(auc_val), 1.0, atol=1e-3)
    # ctr bundle: (sqrerr, abserr, prob, q, pos, total) batch sums
    sq, ab, prob, q, pos, total = C.ctr_metric_bundle(
        P.to_tensor(np.asarray([0.5, 0.0], np.float32)),
        P.to_tensor(np.asarray([1.0, 0.0], np.float32)))
    assert float(_np(sq)) == 0.25 and float(_np(ab)) == 0.5
    assert float(_np(prob)) == 0.5 and float(_np(pos)) == 1.0
    assert float(_np(total)) == 2.0
    # py_func: a host callable embedded via pure_callback
    out_spec = P.to_tensor(np.zeros((2,), np.float32))
    got = C.py_func(lambda v: np.asarray(v) * 2.0,
                    P.to_tensor(np.asarray([1.0, 3.0], np.float32)),
                    out_spec)
    np.testing.assert_array_equal(_np(got), [2.0, 6.0])


def test_incubate_identity_loss_and_quant():
    from paddle_tpu.incubate.ops import identity_loss
    from paddle_tpu.nn.quant import llm_int8_linear
    from paddle_tpu.quantization.quanters import fake_quant_abs_max

    x = P.to_tensor(np.asarray([1.0, 3.0], np.float32))
    np.testing.assert_array_equal(_np(identity_loss(x)), [1.0, 3.0])
    np.testing.assert_allclose(_np(identity_loss(x, "mean")), 2.0)
    np.testing.assert_allclose(_np(identity_loss(x, "sum")), 4.0)
    # fake quant-dequant at 8 bits, scale 1: round(0.5*127)/127
    got = _np(fake_quant_abs_max(
        P.to_tensor(np.asarray([0.5], np.float32)),
        P.to_tensor(np.asarray(1.0, np.float32))))
    np.testing.assert_allclose(got, round(0.5 * 127) / 127, rtol=1e-6)
    # llm.int8: per-output-column dequant w[i,j] * scale[j]
    out = _np(llm_int8_linear(
        P.to_tensor(np.asarray([[1.0, 2.0]], np.float32)),
        P.to_tensor(np.asarray([[1, -2], [3, 4]], np.int8)),
        weight_scale=P.to_tensor(np.asarray([0.5, 0.25], np.float32))))
    np.testing.assert_allclose(out, [[3.5, 1.5]], rtol=1e-6)


def test_fused_transformer_ops_match_references():
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.RandomState(0)
    x = P.to_tensor(rng.randn(2, 4).astype(np.float32))
    y = P.to_tensor(rng.randn(2, 4).astype(np.float32))
    # fused_dropout_add at p=0 is exactly x + y
    np.testing.assert_allclose(_np(IF.fused_dropout_add(x, y, p=0.0)),
                               _np(x) + _np(y), rtol=1e-6)
    # fused_matmul_bias == x @ w + b
    w = P.to_tensor(rng.randn(4, 3).astype(np.float32))
    b = P.to_tensor(rng.randn(3).astype(np.float32))
    np.testing.assert_allclose(_np(IF.fused_matmul_bias(x, w, b)),
                               _np(x) @ _np(w) + _np(b), rtol=1e-5)
    # fused_layer_norm(x, residual=r) == layer_norm(x + r)
    g = P.to_tensor(np.ones((4,), np.float32))
    beta = P.to_tensor(np.zeros((4,), np.float32))
    fused = _np(IF.fused_layer_norm(x, g, beta, residual=y))
    ref = _np(F.layer_norm(P.to_tensor(_np(x) + _np(y)), (4,), g, beta))
    np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-6)
    # fused_rms_norm == v / sqrt(mean(v^2) + eps) * w
    v = _np(x)
    got = _np(IF.fused_rms_norm(x, g, epsilon=1e-6))
    want = v / np.sqrt((v * v).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # fused_feedforward, pre-LN, dropouts off:
    # x + relu(ln(x) @ w1 + b1) @ w2 + b2
    w1 = P.to_tensor(rng.randn(4, 8).astype(np.float32))
    b1 = P.to_tensor(rng.randn(8).astype(np.float32))
    w2 = P.to_tensor(rng.randn(8, 4).astype(np.float32))
    b2 = P.to_tensor(rng.randn(4).astype(np.float32))
    got = _np(IF.fused_feedforward(
        x, w1, w2, linear1_bias=b1, linear2_bias=b2, ln1_scale=g,
        ln1_bias=beta, dropout1_rate=0.0, dropout2_rate=0.0,
        pre_layer_norm=True))
    h = _np(F.layer_norm(x, (4,), g, beta))
    want = v + np.maximum(h @ _np(w1) + _np(b1), 0.0) @ _np(w2) + _np(b2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fused_rope_and_masked_mha_known_answers():
    import paddle_tpu.incubate.nn.functional as IF
    from paddle_tpu.incubate.nn.functional import masked_multihead_attention

    # a single position is position 0: rotation angle 0 == identity
    q = P.to_tensor(np.random.RandomState(1).randn(1, 1, 2, 4)
                    .astype(np.float32))
    rq, rk, rv = IF.fused_rotary_position_embedding(q)
    assert rk is None and rv is None
    np.testing.assert_allclose(_np(rq), _np(q), rtol=1e-6)
    # first decode token (cache empty, write position 0) attends only to
    # itself: the output IS its value head
    B, H, D, S = 1, 1, 2, 4
    x = P.to_tensor(np.asarray([[1., 2., 3., 4., 5., 6.]], np.float32))
    cache = P.to_tensor(np.zeros((2, B, H, S, D), np.float32))
    out, new_cache = masked_multihead_attention(
        x, cache_kv=cache,
        sequence_lengths=P.to_tensor(np.asarray([0], np.int32)))
    np.testing.assert_allclose(_np(out), [[5., 6.]], rtol=1e-6)
    # and the key landed in the cache at position 0
    np.testing.assert_allclose(_np(new_cache)[0, 0, 0, 0], [3., 4.],
                               rtol=1e-6)


def test_vision_decode_ops_known_answers():
    import paddle_tpu.vision.ops as V

    # box_coder decode of zero deltas reproduces the priors exactly
    priors = np.asarray([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.6, 0.9]],
                        np.float32)
    zeros = np.zeros((1, 2, 4), np.float32)
    dec = _np(V.box_coder(P.to_tensor(priors), None, P.to_tensor(zeros),
                          code_type="decode_center_size"))
    np.testing.assert_allclose(dec[0], priors, rtol=1e-6)
    # encode of target == prior is all-zero deltas
    enc = _np(V.box_coder(P.to_tensor(priors), None, P.to_tensor(priors),
                          code_type="encode_center_size"))
    np.testing.assert_allclose(np.diagonal(enc[..., 0]), 0.0, atol=1e-6)
    # prior_box on a 1x1 feature over a 4x4 image, min_size 2: one box
    # centered at (2, 2) with half-extent 1, normalized by the image
    feat = P.to_tensor(np.zeros((1, 1, 1, 1), np.float32))
    img = P.to_tensor(np.zeros((1, 3, 4, 4), np.float32))
    boxes, var = V.prior_box(feat, img, min_sizes=[2])
    np.testing.assert_allclose(_np(boxes).reshape(4),
                               [0.25, 0.25, 0.75, 0.75], rtol=1e-6)
    np.testing.assert_allclose(_np(var).reshape(4), [0.1, 0.1, 0.2, 0.2])
    # yolo_box on a zero head, 1x1 grid, one anchor of exactly one
    # downsample stride: sigmoid(0)=.5 centers the box, exp(0) keeps the
    # anchor extent -> the full image, clipped to [0, size-1]
    head = P.to_tensor(np.zeros((1, 6, 1, 1), np.float32))
    sizes = P.to_tensor(np.asarray([[32, 32]], np.int32))
    bx, score = V.yolo_box(head, sizes, anchors=[32, 32], class_num=1)
    np.testing.assert_allclose(_np(bx).reshape(4), [0., 0., 31., 31.],
                               rtol=1e-6)
    np.testing.assert_allclose(_np(score).reshape(1), 0.25, rtol=1e-6)


# ---------------- sparse namespaced family (paddle_tpu.sparse) ----------------
# These burn the `sparse_*` orphan block: each op is exercised through the
# public module surface (`import paddle_tpu.sparse as Z` — the
# module-qualified battery route) against a dense NumPy reference. The
# value-wise unary family is swept from one cases table whose keys ARE the
# namespaced op names, so the governance claim is explicit per op.

def _coo(dense):
    import paddle_tpu.sparse as sparse
    return sparse.to_sparse_coo(P.to_tensor(np.asarray(dense, np.float32)))


def test_sparse_elementwise_known_answers():
    import paddle_tpu.sparse as sparse
    a = np.array([[0.0, 1.0], [2.0, 0.0]], np.float32)
    b = np.array([[0.0, 3.0], [4.0, 0.0]], np.float32)
    np.testing.assert_allclose(
        _np(sparse.subtract(_coo(a), _coo(b)).to_dense()), a - b, rtol=1e-6)
    full = np.array([[1.0, 3.0], [4.0, 2.0]], np.float32)
    np.testing.assert_allclose(
        _np(sparse.divide(_coo(a), P.to_tensor(full)).to_dense()),
        a / full, rtol=1e-6)
    # masked matmul: dense product sampled at the mask's sparsity pattern
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    y = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)
    mask = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    np.testing.assert_allclose(
        _np(sparse.masked_matmul(P.to_tensor(x), P.to_tensor(y),
                                 _coo(mask)).to_dense()),
        (x @ y) * (mask != 0), rtol=1e-6)
    # addmm: beta*input + alpha*(x @ y) on the input's pattern
    inp = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    out = sparse.addmm(_coo(inp), P.to_tensor(x), P.to_tensor(y),
                       beta=2.0, alpha=1.0)
    np.testing.assert_allclose(_np(out.to_dense()),
                               2.0 * inp + x @ y, rtol=1e-6)


def test_sparse_unary_family_known_answers():
    import paddle_tpu.sparse as sparse
    # values inside every member's domain (atanh/asin need |v| < 1);
    # zeros stay zero for each member, so the dense reference is exact
    dense = np.array([[0.0, 0.5], [-0.25, 0.0]], np.float32)
    cases = {
        "sparse_sin": (sparse.sin, np.sin),
        "sparse_tan": (sparse.tan, np.tan),
        "sparse_asin": (sparse.asin, np.arcsin),
        "sparse_atan": (sparse.atan, np.arctan),
        "sparse_sinh": (sparse.sinh, np.sinh),
        "sparse_tanh": (sparse.tanh, np.tanh),
        "sparse_asinh": (sparse.asinh, np.arcsinh),
        "sparse_atanh": (sparse.atanh, np.arctanh),
        "sparse_square": (sparse.square, np.square),
        "sparse_log1p": (sparse.log1p, np.log1p),
        "sparse_abs": (sparse.abs, np.abs),
        "sparse_neg": (sparse.neg, np.negative),
        "sparse_expm1": (sparse.expm1, np.expm1),
        "sparse_deg2rad": (sparse.deg2rad, np.deg2rad),
        "sparse_rad2deg": (sparse.rad2deg, np.rad2deg),
    }
    for name, (op, ref) in cases.items():
        got = _np(op(_coo(dense)).to_dense())
        np.testing.assert_allclose(got, ref(dense), rtol=1e-5, atol=1e-7,
                                   err_msg=name)
    # sqrt over a non-negative pattern (domain)
    nn = np.array([[0.0, 4.0], [9.0, 0.0]], np.float32)
    np.testing.assert_allclose(_np(sparse.sqrt(_coo(nn)).to_dense()),
                               np.sqrt(nn), rtol=1e-6)


def test_sparse_shape_and_reduction_known_answers():
    import paddle_tpu.sparse as sparse
    dense = np.array([[0.0, 1.0, 0.0], [2.0, 0.0, 3.0]], np.float32)
    t = _coo(dense)
    np.testing.assert_allclose(_np(sparse.pow(t, 2).to_dense()),
                               dense ** 2, rtol=1e-6)
    assert float(_np(sparse.sum(t))) == dense.sum()
    np.testing.assert_array_equal(_np(sparse.isnan(t).to_dense()),
                                  np.isnan(dense))
    np.testing.assert_array_equal(
        _np(sparse.transpose(t, [1, 0]).to_dense()), dense.T)
    np.testing.assert_array_equal(
        _np(sparse.reshape(t, [3, 2]).to_dense()), dense.reshape(3, 2))
    np.testing.assert_array_equal(
        _np(sparse.slice(t, axes=[1], starts=[0], ends=[2]).to_dense()),
        dense[:, :2])
    c = sparse.cast(t, value_dtype="float64")
    np.testing.assert_allclose(_np(c.to_dense()).astype(np.float64),
                               dense.astype(np.float64))
    # nn statics: masked softmax rows renormalize over the stored values
    s = _np(sparse.nn.softmax(t, axis=-1).to_dense())
    row1 = np.exp([1.0]) / np.exp([1.0]).sum()
    row2 = np.exp([2.0, 3.0]) / np.exp([2.0, 3.0]).sum()
    np.testing.assert_allclose(s[0, 1], row1[0], rtol=1e-6)
    np.testing.assert_allclose(s[1, [0, 2]], row2, rtol=1e-6)
