"""Registry-consistency orphan burn-down battery (ROADMAP standing debt).

Each op exercised here was a baselined `registry-consistency` orphan: a
dispatch site with a stable ``op_name`` that no test battery referenced
THROUGH the package. Per the burn-down rule these are retired by adding
batteries — real known-answer assertions via the public ``P.`` surface —
never by loosening the checker's resolution. The ratchet in
tools/staticcheck/baseline.json is re-cut downward as this file grows.
"""
import numpy as np

import paddle_tpu as P


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


# ---------------- inverse-hyperbolic + pointwise math ----------------

def test_inverse_hyperbolic_known_answers():
    x = P.to_tensor(np.asarray([1.0, 2.0, 10.0], np.float32))
    np.testing.assert_allclose(_np(P.acosh(x)), np.arccosh(_np(x)), rtol=1e-6)
    y = P.to_tensor(np.asarray([-2.0, 0.0, 3.0], np.float32))
    np.testing.assert_allclose(_np(P.asinh(y)), np.arcsinh(_np(y)), rtol=1e-6)
    z = P.to_tensor(np.asarray([-0.5, 0.0, 0.9], np.float32))
    np.testing.assert_allclose(_np(P.atanh(z)), np.arctanh(_np(z)), rtol=1e-6)


def test_neg_negative_positive_cbrt_sinc():
    x = P.to_tensor(np.asarray([-2.0, 0.0, 8.0], np.float32))
    np.testing.assert_array_equal(_np(P.neg(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.negative(x)), [2.0, 0.0, -8.0])
    np.testing.assert_array_equal(_np(P.positive(x)), _np(x))
    np.testing.assert_allclose(_np(P.cbrt(x)), np.cbrt(_np(x)), rtol=1e-6)
    s = P.to_tensor(np.asarray([0.0, 0.5, 1.0], np.float32))
    np.testing.assert_allclose(_np(P.sinc(s)), np.sinc(_np(s)), atol=1e-6)


def test_scale_divide_no_nan_and_increment():
    x = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_allclose(_np(P.scale(x, scale=3.0, bias=1.0)),
                               [4.0, 7.0], rtol=1e-6)
    num = P.to_tensor(np.asarray([6.0, 1.0], np.float32))
    den = P.to_tensor(np.asarray([3.0, 0.0], np.float32))
    np.testing.assert_array_equal(_np(P.divide_no_nan(num, den)), [2.0, 0.0])
    np.testing.assert_array_equal(_np(P.increment(P.to_tensor(
        np.asarray([5.0], np.float32)), value=2.0)), [7.0])


# ---------------- comparisons + predicates ----------------

def test_elementwise_comparisons_known_answers():
    a = P.to_tensor(np.asarray([1, 2, 3], np.int64))
    b = P.to_tensor(np.asarray([2, 2, 2], np.int64))
    np.testing.assert_array_equal(_np(P.equal(a, b)), [False, True, False])
    np.testing.assert_array_equal(_np(P.not_equal(a, b)),
                                  [True, False, True])
    np.testing.assert_array_equal(_np(P.less_than(a, b)),
                                  [True, False, False])
    np.testing.assert_array_equal(_np(P.less_equal(a, b)),
                                  [True, True, False])
    np.testing.assert_array_equal(_np(P.greater_than(a, b)),
                                  [False, False, True])
    np.testing.assert_array_equal(_np(P.greater_equal(a, b)),
                                  [False, True, True])
    assert bool(_np(P.equal_all(a, a))) is True
    assert bool(_np(P.equal_all(a, b))) is False


def test_float_predicates_and_reductions():
    x = P.to_tensor(np.asarray([1.0, np.inf, -np.inf, np.nan], np.float32))
    np.testing.assert_array_equal(_np(P.isfinite(x)),
                                  [True, False, False, False])
    np.testing.assert_array_equal(_np(P.isinf(x)),
                                  [False, True, True, False])
    np.testing.assert_array_equal(_np(P.isnan(x)),
                                  [False, False, False, True])
    np.testing.assert_array_equal(_np(P.isposinf(x)),
                                  [False, True, False, False])
    np.testing.assert_array_equal(_np(P.isneginf(x)),
                                  [False, False, True, False])
    m = P.to_tensor(np.asarray([[True, False], [False, False]]))
    assert bool(_np(P.any(m))) is True
    z = P.to_tensor(np.asarray([0.0, 2.0, 0.0, 3.0], np.float32))
    assert int(_np(P.count_nonzero(z))) == 2
    np.testing.assert_array_equal(_np(P.signbit(P.to_tensor(
        np.asarray([-1.0, 0.0, 2.0], np.float32)))), [True, False, False])


def test_allclose_isclose_contract():
    a = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    b = P.to_tensor(np.asarray([1.0 + 1e-7, 2.0], np.float32))
    assert bool(_np(P.allclose(a, b))) is True
    np.testing.assert_array_equal(
        _np(P.isclose(a, P.to_tensor(np.asarray([1.0, 9.0], np.float32)))),
        [True, False])


# ---------------- integer / bitwise / logical ----------------

def test_bitwise_family_known_answers():
    a = P.to_tensor(np.asarray([0b1100, 0b1010], np.int64))
    b = P.to_tensor(np.asarray([0b1010, 0b0110], np.int64))
    np.testing.assert_array_equal(_np(P.bitwise_and(a, b)), [0b1000, 0b0010])
    np.testing.assert_array_equal(_np(P.bitwise_or(a, b)), [0b1110, 0b1110])
    np.testing.assert_array_equal(_np(P.bitwise_xor(a, b)), [0b0110, 0b1100])
    np.testing.assert_array_equal(_np(P.bitwise_not(a)), [~0b1100, ~0b1010])
    t = P.to_tensor(np.asarray([True, True, False]))
    f = P.to_tensor(np.asarray([True, False, False]))
    np.testing.assert_array_equal(_np(P.logical_xor(t, f)),
                                  [False, True, False])


def test_integer_arithmetic_gcd_lcm_mod_floor_divide():
    a = P.to_tensor(np.asarray([12, 54], np.int64))
    b = P.to_tensor(np.asarray([8, 24], np.int64))
    np.testing.assert_array_equal(_np(P.gcd(a, b)), [4, 6])
    np.testing.assert_array_equal(_np(P.lcm(a, b)), [24, 216])
    np.testing.assert_array_equal(_np(P.floor_divide(a, b)), [1, 2])
    np.testing.assert_array_equal(_np(P.mod(a, b)), [4, 6])


# ---------------- complex views ----------------

def test_complex_real_imag_conj():
    c = P.to_tensor(np.asarray([1 + 2j, 3 - 4j], np.complex64))
    np.testing.assert_array_equal(_np(P.real(c)), [1.0, 3.0])
    np.testing.assert_array_equal(_np(P.imag(c)), [2.0, -4.0])
    np.testing.assert_array_equal(_np(P.conj(c)),
                                  np.conj(_np(c)))
    r = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.isreal(c)), [False, False])
    np.testing.assert_array_equal(_np(P.isreal(r)), [True, True])


# ---------------- shape / assembly breadth ----------------

def test_stacking_family_matches_numpy():
    a = np.arange(4, dtype=np.float32)
    b = a + 10
    ta, tb = P.to_tensor(a), P.to_tensor(b)
    np.testing.assert_array_equal(_np(P.hstack((ta, tb))), np.hstack((a, b)))
    np.testing.assert_array_equal(_np(P.vstack((ta, tb))), np.vstack((a, b)))
    np.testing.assert_array_equal(_np(P.dstack((ta, tb))), np.dstack((a, b)))
    np.testing.assert_array_equal(_np(P.column_stack((ta, tb))),
                                  np.column_stack((a, b)))
    np.testing.assert_array_equal(_np(P.row_stack((ta, tb))),
                                  np.vstack((a, b)))


def test_axis_moves_and_transpose():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    tx = P.to_tensor(x)
    np.testing.assert_array_equal(_np(P.moveaxis(tx, 0, 2)),
                                  np.moveaxis(x, 0, 2))
    np.testing.assert_array_equal(_np(P.swapaxes(tx, 0, 1)),
                                  np.swapaxes(x, 0, 1))
    m = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(_np(P.t(m)), _np(m).T)


def test_diag_embed_block_diag_bincount_unstack():
    v = P.to_tensor(np.asarray([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(_np(P.diag_embed(v)),
                                  np.diag(np.asarray([1.0, 2.0])))
    a = P.to_tensor(np.eye(2, dtype=np.float32))
    b = P.to_tensor(np.full((1, 1), 3.0, np.float32))
    bd = _np(P.block_diag([a, b]))
    want = np.zeros((3, 3), np.float32)
    want[:2, :2] = np.eye(2)
    want[2, 2] = 3.0
    np.testing.assert_array_equal(bd, want)
    ids = P.to_tensor(np.asarray([0, 1, 1, 3], np.int64))
    np.testing.assert_array_equal(_np(P.bincount(ids)), [1, 2, 0, 1])
    parts = P.unstack(
        P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3)))
    assert len(parts) == 2
    np.testing.assert_array_equal(_np(parts[1]), [3.0, 4.0, 5.0])
