"""Gradient accumulation / merge (VERDICT r1 item 7).

Reference semantics: fleet/meta_optimizers/gradient_merge_optimizer.py —
accumulate k micro-step grads, apply the averaged grad once. Parity law:
one update from batch B must equal one update from the same B split into k
micro-steps (mean of equal-size means == global mean).
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, build_hybrid_train_step
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.trainer import compile_train_step


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def _data(cfg, batch=8, seq=8, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
    return ids[:, :-1], ids[:, 1:]


def _run_hybrid(cfg, ids, labels, acc, n_steps=3, mesh_shape=None):
    mesh_mod.set_mesh(None)
    P.seed(7)
    model = LlamaForCausalLM(cfg)
    if mesh_shape:
        mesh_mod.init_mesh(mesh_shape)
    opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, accumulate_steps=acc)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    losses = [float(step(batch).numpy()) for _ in range(n_steps)]
    import jax
    leaf = np.asarray(jax.tree_util.tree_leaves(step.state["params"])[0])
    return losses, leaf


def test_hybrid_step_accumulation_parity():
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=8)
    l1, p1 = _run_hybrid(cfg, ids, labels, acc=1)
    l4, p4 = _run_hybrid(cfg, ids, labels, acc=4)
    np.testing.assert_allclose(l4, l1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(p4, p1, rtol=1e-4, atol=1e-5)


def test_hybrid_step_accumulation_under_dp_mesh():
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=16)
    l1, _ = _run_hybrid(cfg, ids, labels, acc=1, mesh_shape={"dp": 4})
    l2, _ = _run_hybrid(cfg, ids, labels, acc=2, mesh_shape={"dp": 4})
    np.testing.assert_allclose(l2, l1, rtol=1e-4, atol=1e-5)


def test_compile_train_step_accumulation_parity():
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=8)

    def run(acc):
        mesh_mod.set_mesh(None)
        P.seed(11)
        model = LlamaForCausalLM(cfg)
        opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        step = compile_train_step(
            model, lambda m, b: m.compute_loss(b["input_ids"], b["labels"]),
            opt, accumulate_steps=acc)
        batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
        return [float(step(batch).numpy()) for _ in range(3)]

    np.testing.assert_allclose(run(4), run(1), rtol=1e-4, atol=1e-5)


def test_strategy_accumulate_steps_is_load_bearing():
    """DistributedStrategy.gradient_merge flows through distributed_optimizer
    into the compiled step (the dead-config finding from VERDICT r1)."""
    from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
    from paddle_tpu.distributed.fleet.hybrid_optimizer import HybridParallelOptimizer

    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    model = LlamaForCausalLM(cfg)
    s = DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs["k_steps"] = 4
    opt = HybridParallelOptimizer(
        P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters()),
        hcg=None, strategy=s)
    assert opt.inner_opt._accumulate_steps == 4

    # and build_hybrid_train_step picks the tag up as its default
    ids, labels = _data(cfg, batch=8)
    step = build_hybrid_train_step(model, opt)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    assert np.isfinite(float(step(batch).numpy()))
