"""ONNX export, predictor IO signatures, and packaging (VERDICT r1 missing
#10 / weak #9)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.jit.api import InputSpec

rng = np.random.RandomState(0)


def _mlp():
    P.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))


def test_onnx_export_mlp_matches(tmp_path):
    mlp = _mlp()
    path = P.onnx.export(mlp, str(tmp_path / "mlp"),
                         input_spec=[InputSpec([None, 16], "float32",
                                               name="x")])
    assert path.endswith(".onnx") and os.path.getsize(path) > 0
    x = rng.randn(4, 16).astype("f")
    ref = mlp(P.to_tensor(x)).numpy()
    got = P.onnx.run_model(path, {"x": x})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # dynamic batch recorded as dim_param; input name honored
    from paddle_tpu.onnx.proto import pb
    m = pb.ModelProto.FromString(open(path, "rb").read())
    assert m.graph.input[0].name == "x"
    assert m.graph.input[0].type.tensor_type.shape.dim[0].dim_param
    assert m.opset_import[0].version == 13


def test_onnx_export_cnn_and_pool(tmp_path):
    P.seed(1)
    cnn = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.MaxPool2D(2, 2), nn.Flatten(),
                        nn.Linear(8 * 4 * 4, 10))
    path = P.onnx.export(cnn, str(tmp_path / "cnn"),
                         input_spec=[InputSpec([1, 3, 8, 8], "float32",
                                               name="img")])
    xi = rng.randn(1, 3, 8, 8).astype("f")
    ref = cnn(P.to_tensor(xi)).numpy()
    got = P.onnx.run_model(path, {"img": xi})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_onnx_export_llama_transformer(tmp_path):
    """Whole-transformer export: attention, rope (sin/cos/iota), RMSNorm,
    softmax, GQA — everything lowers through the jaxpr converters."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    P.seed(2)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, inter=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    path = P.onnx.export(m, str(tmp_path / "llama"),
                         input_spec=[InputSpec([1, 8], "int32", name="ids")])
    ids = rng.randint(0, 64, (1, 8)).astype(np.int32)
    ref = m(P.to_tensor(ids)).numpy()
    got = P.onnx.run_model(path, {"ids": ids})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_onnx_atan2_cbrt_quadrants(tmp_path):
    """ADVICE r2: atan2 must be quadrant-correct (not the principal branch)
    and cbrt must handle negative inputs."""
    class M(nn.Layer):
        def forward(self, y, x):
            from paddle_tpu.ops.dispatch import apply
            import jax.numpy as jnp
            return P.atan2(y, x) + apply(jnp.cbrt, x)

    m = M()
    path = P.onnx.export(m, str(tmp_path / "quad"),
                         input_spec=[InputSpec([5], "float32", name="y"),
                                     InputSpec([5], "float32", name="x")])
    y = np.asarray([1.0, 1.0, -1.0, -1.0, 0.0], np.float32)
    x = np.asarray([1.0, -1.0, 1.0, -1.0, -2.0], np.float32)
    got = P.onnx.run_model(path, {"y": y, "x": x})[0]
    ref = np.arctan2(y, x) + np.cbrt(x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_onnx_dynamic_batch_with_internal_reshape(tmp_path):
    """ADVICE r2: dynamic dims flowing into reshape/broadcast targets were
    baked from the representative trace size; now they are runtime-derived,
    so ONE export serves multiple batch sizes."""
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(6, 8)

        def forward(self, x):
            b = x.shape[0]
            h = self.lin(x.reshape([b * 3, 6]))       # merged dynamic dim
            return h.reshape([b, 3, 8]).sum(axis=1)   # split back

    m = M()
    path = P.onnx.export(m, str(tmp_path / "dyn"),
                         input_spec=[InputSpec([None, 3, 6], "float32",
                                               name="x")])
    for bsz in (2, 5):
        x = rng.randn(bsz, 3, 6).astype("f")
        ref = m(P.to_tensor(x)).numpy()
        got = P.onnx.run_model(path, {"x": x})[0]
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=f"batch={bsz}")


def test_onnx_dynamic_seq_transformer(tmp_path):
    """Dynamic sequence length through a full transformer (causal-mask iotas
    become runtime Ranges, attention reshapes become runtime shapes)."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    P.seed(3)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=1, heads=4, inter=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    path = P.onnx.export(
        m, str(tmp_path / "llama_dyn"),
        input_spec=[InputSpec([1, None], "int32", name="ids")])
    for seq in (4, 9):
        ids = rng.randint(0, 64, (1, seq)).astype(np.int32)
        ref = m(P.to_tensor(ids)).numpy()
        got = P.onnx.run_model(path, {"ids": ids})[0]
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4,
                                   err_msg=f"seq={seq}")


def test_onnx_unsupported_primitive_raises(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            from paddle_tpu.ops.dispatch import apply
            import jax

            def f(v):
                return jax.lax.cumlogsumexp(v) if hasattr(
                    jax.lax, "cumlogsumexp") else jax.lax.associative_scan(
                    jax.numpy.add, v)
            return apply(f, x)

    with pytest.raises(NotImplementedError, match="no converter"):
        P.onnx.export(Weird(), str(tmp_path / "w"),
                      input_spec=[InputSpec([4], "float32")])


def test_jit_save_records_real_io_signatures(tmp_path):
    mlp = _mlp()
    prefix = str(tmp_path / "m")
    P.jit.save(mlp, prefix,
               input_spec=[InputSpec([None, 16], "float32", name="feats")])
    meta = json.load(open(prefix + ".pdmeta"))
    assert meta["input_names"] == ["feats"]
    assert meta["input_dtypes"] == ["float32"]
    assert meta["input_shapes"] == [[None, 16]]
    assert meta["output_names"] == ["output_0"]
    assert meta["output_dtypes"] == ["float32"]
    assert meta["output_shapes"][0][-1] == 8


def test_predictor_uses_and_validates_signatures(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    mlp = _mlp()
    prefix = str(tmp_path / "m")
    P.jit.save(mlp, prefix,
               input_spec=[InputSpec([None, 16], "float32", name="feats")])
    pred = create_predictor(Config(prefix))
    assert pred.get_input_names() == ["feats"]
    h = pred.get_input_handle("feats")
    h.copy_from_cpu(rng.randn(3, 16).astype("f"))
    assert pred.run()
    assert pred.get_output_names() == ["output_0"]
    out = pred.get_output_handle("output_0").copy_to_cpu()
    assert out.shape == (3, 8)
    # dtype mismatch -> loud error naming the feed
    with pytest.raises(TypeError, match="feats"):
        pred.run([rng.randn(3, 16).astype("float64")])
    # rank mismatch
    with pytest.raises(ValueError, match="feats"):
        pred.run([rng.randn(16).astype("f")])
    # fixed-dim mismatch
    with pytest.raises(ValueError, match="feats"):
        pred.run([rng.randn(3, 8).astype("f")])


def test_wheel_builds():
    out = subprocess.run(
        [sys.executable, "setup.py", "bdist_wheel", "-q",
         "--dist-dir", "/tmp/ptpu_dist"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    wheels = [f for f in os.listdir("/tmp/ptpu_dist") if f.endswith(".whl")]
    assert wheels
    import zipfile
    names = zipfile.ZipFile(os.path.join("/tmp/ptpu_dist", wheels[0])).namelist()
    assert any(n.endswith("libpaddle_tpu_rt.so") for n in names)
    assert any(n.endswith("paddle_tpu/__init__.py") for n in names)


def test_export_tp_model_single_device_retrace(tmp_path):
    """A model built UNDER a tensor-parallel mesh (TP layers annotate
    shardings) exports via the automatic single-device re-trace: the mesh is
    cleared for the trace, so no sharding primitives reach the converter,
    and the graph reproduces the eager output (VERDICT r3 weak #8)."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    from paddle_tpu.nn.layer.layers import Layer
    from paddle_tpu.parallel import mesh as mesh_mod

    dist.init_parallel_env({"mp": 2})
    try:
        P.seed(0)

        class TPBlock(Layer):
            def __init__(self):
                super().__init__()
                self.up = ColumnParallelLinear(8, 16, has_bias=False,
                                               gather_output=False)
                self.down = RowParallelLinear(16, 8, has_bias=False,
                                              input_is_parallel=True)

            def forward(self, x):
                return self.down(P.nn.functional.relu(self.up(x)))

        model = TPBlock()
        x = P.to_tensor(np.random.RandomState(0).randn(2, 8)
                        .astype(np.float32))
        eager = model(x).numpy()

        from paddle_tpu.static import InputSpec
        path = P.onnx.export(
            model, str(tmp_path / "tp_model"),
            input_spec=[InputSpec([2, 8], "float32", name="x")])
        # the ambient mesh must survive the export untouched
        assert mesh_mod.get_mesh() is not None
        out = P.onnx.run_model(path, {"x": np.asarray(x.numpy())})[0]
        np.testing.assert_allclose(out, np.asarray(eager), rtol=1e-5,
                                   atol=1e-6)
    finally:
        mesh_mod.set_mesh(None)


class _ScanLayer(nn.Layer):
    """Forward uses lax control flow directly: exercises the Scan / Loop /
    If converters (VERDICT r4 item 9; reference python/paddle/onnx export
    covers paddle's while/cond via its dy2static counterpart)."""

    def __init__(self, kind):
        super().__init__()
        self.kind = kind

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        v = x._value

        if self.kind == "scan":
            def step(carry, row):
                new = jnp.tanh(carry + row)
                return new, new * 2.0
            carry, ys = jax.lax.scan(step, jnp.zeros(v.shape[1:], v.dtype), v)
            out = carry.sum() + ys.sum()
        elif self.kind == "while":
            def cond(s):
                return s[0] < 10.0
            def body(s):
                return (s[0] + 1.0, s[1] * 1.5 + s[0])
            a, b = jax.lax.while_loop(
                cond, body, (jnp.asarray(0.0, v.dtype), v.sum()))
            out = a + b
        elif self.kind == "cond":
            out = jax.lax.cond(v.sum() > 0,
                               lambda u: u.sum() * 2.0,
                               lambda u: u.sum() - 1.0, v)
        elif self.kind == "fori":
            out = jax.lax.fori_loop(
                0, 5, lambda i, s: s * 1.1 + jnp.float32(i), v.sum())
        else:
            raise ValueError(self.kind)
        return P.Tensor(out)


@pytest.mark.parametrize("kind", ["scan", "while", "cond", "fori"])
def test_onnx_control_flow_round_trip(tmp_path, kind):
    m = _ScanLayer(kind)
    path = P.onnx.export(m, str(tmp_path / kind),
                         input_spec=[InputSpec([3, 4], "float32", name="x")])
    x = rng.randn(3, 4).astype("f")
    ref = m(P.to_tensor(x)).numpy()
    got = P.onnx.run_model(path, {"x": x})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # the negative branch of cond must also be exercised
    if kind == "cond":
        xn = -np.abs(x)
        np.testing.assert_allclose(P.onnx.run_model(path, {"x": xn})[0],
                                   m(P.to_tensor(xn)).numpy(),
                                   rtol=1e-5, atol=1e-6)


class _MiscPrims(nn.Layer):
    def forward(self, x):
        import jax
        v = x._value
        vals, idx = jax.lax.top_k(v, 3)
        cs = v.cumsum(axis=-1)
        import jax.numpy as jnp
        sl = jax.lax.dynamic_slice(
            v, (idx[0, 0].astype("int32") * 0, jnp.int32(1)), (2, 3))
        return P.Tensor(vals.sum() + cs.sum() + sl.sum()
                        + idx.astype(v.dtype).sum())


def test_onnx_topk_cumsum_dynamic_slice_round_trip(tmp_path):
    m = _MiscPrims()
    path = P.onnx.export(m, str(tmp_path / "misc"),
                         input_spec=[InputSpec([4, 6], "float32", name="x")])
    x = rng.randn(4, 6).astype("f")
    np.testing.assert_allclose(P.onnx.run_model(path, {"x": x})[0],
                               m(P.to_tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_onnx_rnn_model_round_trip(tmp_path):
    """An actual recurrent MODEL (lax.scan inside nn.GRU) survives export
    and replays numerically in the interpreter."""
    P.seed(7)
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.rnn = nn.GRU(8, 16)
            self.head = nn.Linear(16, 4)

        def forward(self, x):
            out, _ = self.rnn(x)
            return self.head(out[:, -1])

    m = Net()
    m.eval()
    path = P.onnx.export(m, str(tmp_path / "gru"),
                         input_spec=[InputSpec([2, 5, 8], "float32",
                                               name="x")])
    x = rng.randn(2, 5, 8).astype("f")
    ref = m(P.to_tensor(x)).numpy()
    got = P.onnx.run_model(path, {"x": x})[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_onnx_tp_export_warns_replicated(tmp_path):
    """Exporting a model with sharded params warns and records the
    replicated-semantics note in the graph doc_string (VERDICT r4 item 9)."""
    import warnings

    from paddle_tpu.parallel import mesh as mesh_mod

    mesh_mod.init_mesh({"mp": 2})
    try:
        from paddle_tpu.distributed.fleet.meta_parallel.mp_layers import (
            ColumnParallelLinear,
        )
        m = ColumnParallelLinear(8, 8, gather_output=True)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            path = P.onnx.export(
                m, str(tmp_path / "tp"),
                input_spec=[InputSpec([2, 8], "float32", name="x")])
        assert any("REPLICATED" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        from paddle_tpu.onnx.proto import pb
        mp = pb.ModelProto.FromString(open(path, "rb").read())
        assert "REPLICATED" in mp.graph.doc_string
        # and the exported math still replays
        x = rng.randn(2, 8).astype("f")
        np.testing.assert_allclose(P.onnx.run_model(path, {"x": x})[0],
                                   m(P.to_tensor(x)).numpy(),
                                   rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.set_mesh(None)
