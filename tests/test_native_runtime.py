"""Tests for the native C++ runtime core (csrc/runtime.cc via ctypes).

Covers the native-equivalents demanded by SURVEY.md §2.1/§2.5: flags registry,
blocking queue (LoDTensorBlockingQueue analog), TCPStore rendezvous, host
tracer. The TCPStore test exercises a real multi-client rendezvous the way
ProcessGroup bootstrap uses it (collective.py:153 in the reference).
"""
import json
import threading
import time

import pytest

from paddle_tpu.utils import flags, native


def test_native_builds():
    assert native.available(), f"native runtime failed to build: {native.load_error()}"


def test_flags_roundtrip():
    flags.define_flag("FLAGS_test_int", 7)
    assert flags.flag("FLAGS_test_int") == 7
    flags.set_flags({"FLAGS_test_int": 13})
    assert flags.get_flags("FLAGS_test_int") == {"FLAGS_test_int": 13}
    flags.define_flag("FLAGS_test_bool", True)
    flags.set_flags({"FLAGS_test_bool": False})
    assert flags.flag("FLAGS_test_bool") is False
    with pytest.raises(KeyError):
        flags.set_flags({"FLAGS_does_not_exist": 1})


def test_blocking_queue_producer_consumer():
    q = native.BlockingQueue(capacity=4)
    items = [bytes([i]) * (i + 1) for i in range(50)]
    got = []

    def producer():
        for it in items:
            q.push(it)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        try:
            b = q.pop()
        except RuntimeError:  # closed + drained
            break
        got.append(b)
    t.join()
    assert got == items


def test_blocking_queue_timeout_and_capacity():
    q = native.BlockingQueue(capacity=1)
    assert q.push(b"a", timeout=1.0)
    t0 = time.monotonic()
    assert not q.push(b"b", timeout=0.1)  # full -> timeout
    assert time.monotonic() - t0 >= 0.09
    assert q.pop() == b"a"
    assert q.pop(timeout=0.05) is None  # empty -> timeout
    q.close()


def test_tcp_store_rendezvous():
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=3)
    port = master.port
    master.set("nccl_uid", b"\x01\x02\x03deadbeef")

    results = {}

    def rank(i):
        c = TCPStore("127.0.0.1", port, is_master=False)
        results[i] = c.get("nccl_uid")
        c.add("arrived", 1)
        c.wait("go")
        results[f"go{i}"] = c.get("go")
        c.stop()

    threads = [threading.Thread(target=rank, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    # barrier master side: wait until all ranks arrived, then release
    deadline = time.monotonic() + 10
    while int(master.get("arrived") or b"0") < 3:
        assert time.monotonic() < deadline, "ranks never arrived"
        time.sleep(0.01)
    master.set("go", b"now")
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    for i in range(3):
        assert results[i] == b"\x01\x02\x03deadbeef"
        assert results[f"go{i}"] == b"now"
    assert master.add("counter", 5) == 5
    assert master.add("counter", -2) == 3
    assert master.delete_key("counter")
    master.stop()


def test_host_tracer_chrome_dump():
    lib = native.get_lib()
    assert lib is not None
    lib.pt_trace_clear()
    lib.pt_trace_enable(1)
    t0 = lib.pt_now_ns()
    lib.pt_trace_record(b"matmul", b"op", t0, 1500, 1)
    lib.pt_trace_record(b'with"quote', b"py", t0 + 2000, 300, 2)
    lib.pt_trace_enable(0)
    lib.pt_trace_record(b"dropped", b"op", t0, 1, 1)  # disabled -> not recorded
    assert lib.pt_trace_count() == 2

    import ctypes
    out = ctypes.c_void_p()
    n = lib.pt_trace_dump(ctypes.byref(out))
    raw = native._take_bytes(lib, out, n)
    events = json.loads(raw)
    assert len(events) == 2
    assert events[0]["name"] == "matmul"
    assert events[0]["ph"] == "X"
    assert events[0]["dur"] == pytest.approx(1.5)
    assert events[1]["name"] == 'with"quote'
    lib.pt_trace_clear()
    assert lib.pt_trace_count() == 0
