"""LLaMA model + hybrid-parallel compiled train step tests.

Loss-parity across parallelism configs is the core assertion — the same
discipline as the reference's hybrid_parallel_mp_model / pipeline payload tests
(test/collective/fleet/)."""
import jax
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, build_hybrid_train_step
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.trainer import compile_train_step


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def _data(cfg, batch=8, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
    return ids[:, :-1], ids[:, 1:]


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg, batch=2, seq=8)
    logits = model(P.to_tensor(ids))
    assert logits.shape == [2, 8, cfg.vocab_size]
    loss = model.compute_loss(P.to_tensor(ids), P.to_tensor(labels))
    assert np.isfinite(loss.numpy())
    # near log(vocab) at init
    assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 1.0


def test_llama_causality():
    cfg = LlamaConfig.tiny(layers=1)
    model = LlamaForCausalLM(cfg)
    ids, _ = _data(cfg, batch=1, seq=8)
    out1 = model(P.to_tensor(ids)).numpy()
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size  # change last token
    out2 = model(P.to_tensor(ids2)).numpy()
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_llama_eager_training_reduces_loss():
    P.seed(1)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters())
    ids, labels = _data(cfg, batch=4, seq=8)
    x, y = P.to_tensor(ids), P.to_tensor(labels)
    losses = []
    for _ in range(15):
        loss = model.compute_loss(x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses


def test_compiled_step_matches_eager():
    """compile_train_step loss sequence == eager loss sequence (single dev)."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=4, seq=8)

    def run_eager():
        P.seed(9)
        model = LlamaForCausalLM(cfg)
        opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        out = []
        for _ in range(5):
            loss = model.compute_loss(P.to_tensor(ids), P.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            out.append(float(loss.numpy()))
        return out

    def run_compiled():
        P.seed(9)
        model = LlamaForCausalLM(cfg)
        opt = P.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        step = compile_train_step(
            model, lambda m, b: m.compute_loss(b["input_ids"], b["labels"]), opt)
        out = []
        for _ in range(5):
            loss = step({"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)})
            out.append(float(loss.numpy()))
        return out

    e = run_eager()
    c = run_compiled()
    np.testing.assert_allclose(c, e, rtol=1e-4, atol=1e-5)


def test_compile_train_step_with_mesh():
    """generic TrainStep under a dp mesh (regression: in_shardings structure)."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=8, seq=8)
    P.seed(13)
    mesh_mod.init_mesh({"dp": 8})
    model = LlamaForCausalLM(cfg)
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer)
    mesh_mod.set_mesh(mesh_mod.get_mesh())
    step = compile_train_step(
        model, lambda m, b: m.compute_loss(b["input_ids"], b["labels"]), opt)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    l0 = float(step(batch).numpy())
    l1 = float(step(batch).numpy())
    assert np.isfinite(l0) and l1 < l0


def test_zero_sharded_opt_states():
    """ZeRO stage-1 in the hybrid step: Adam moments actually sharded."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=2, inter=64)
    ids, labels = _data(cfg, batch=8, seq=8)
    P.seed(17)
    mesh = mesh_mod.init_mesh({"dp": 2, "sharding": 4})
    model = LlamaForCausalLM(cfg)
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_optimizer import (
        DygraphShardingOptimizer)
    opt = DygraphShardingOptimizer(
        P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters()))
    from paddle_tpu.models import build_hybrid_train_step
    step = build_hybrid_train_step(model, opt, n_microbatches=1)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    l0 = float(step(batch).numpy())
    assert np.isfinite(l0)
    # at least one moment leaf is sharded over the 'sharding' axis
    import jax
    leaves = jax.tree_util.tree_leaves(step.state["opt"])
    specs = [getattr(l.sharding, "spec", None) for l in leaves if hasattr(l, "sharding")]
    assert any(s is not None and "sharding" in str(s) for s in specs), specs


def test_hybrid_step_dp_mp():
    """dp=2 x mp=4 compiled hybrid step: runs + loss matches single-device."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, inter=64)
    ids, labels = _data(cfg, batch=8, seq=8)

    P.seed(21)
    model = LlamaForCausalLM(cfg)
    sd = {k: v.numpy().copy() for k, v in model.state_dict().items()}

    # single-device reference (first loss)
    ref_loss = float(model.compute_loss(P.to_tensor(ids), P.to_tensor(labels)).numpy())

    mesh_mod.init_mesh({"dp": 2, "mp": 4})
    opt = P.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, n_microbatches=1)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    l0 = float(step(batch).numpy())
    np.testing.assert_allclose(l0, ref_loss, rtol=1e-4, atol=1e-5)
    l_prev = l0
    for _ in range(4):
        l = float(step(batch).numpy())
    assert l < l0


def test_hybrid_step_pipeline():
    """pp=2 pipelined step: loss parity with the non-pipelined run."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=4, heads=4, inter=64)
    ids, labels = _data(cfg, batch=8, seq=8)

    P.seed(33)
    model = LlamaForCausalLM(cfg)
    ref_loss = float(model.compute_loss(P.to_tensor(ids), P.to_tensor(labels)).numpy())

    mesh_mod.init_mesh({"dp": 2, "pp": 2, "mp": 2})
    opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
    step = build_hybrid_train_step(model, opt, n_microbatches=4)
    batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
    l0 = float(step(batch).numpy())
    np.testing.assert_allclose(l0, ref_loss, rtol=1e-3, atol=1e-4)
    for _ in range(4):
        l = float(step(batch).numpy())
    assert l < l0
    # write trained params back into the Layer world: eager loss at the synced
    # params must equal the loss the next compiled step reports (it evaluates
    # loss at the pre-update params)
    step.write_back()
    l_after = float(model.compute_loss(P.to_tensor(ids), P.to_tensor(labels)).numpy())
    l_next = float(step(batch).numpy())
    np.testing.assert_allclose(l_after, l_next, rtol=1e-3, atol=1e-4)


def test_llama_generate():
    cfg = LlamaConfig.tiny(vocab=32, hidden=16, layers=1, heads=2, inter=32)
    model = LlamaForCausalLM(cfg)
    ids = P.to_tensor(np.random.randint(0, 32, (1, 4)))
    out = model.generate(ids, max_new_tokens=3)
    assert out.shape == [1, 7]


def test_hybrid_step_1f1b_and_vpp_parity():
    """VERDICT r1 item 2: 1F1B and interleaved-VPP hybrid steps match the
    single-device loss and train (loss decreases)."""
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=8, heads=4, inter=64)
    ids, labels = _data(cfg, batch=8, seq=8)
    batch = None

    P.seed(33)
    ref_model = LlamaForCausalLM(cfg)
    ref_loss = float(ref_model.compute_loss(
        P.to_tensor(ids), P.to_tensor(labels)).numpy())

    for sched, kwargs in [("1f1b", {}), ("vpp", {"n_virtual": 2})]:
        mesh_mod.set_mesh(None)
        P.seed(33)
        model = LlamaForCausalLM(cfg)
        mesh_mod.init_mesh({"dp": 2, "pp": 2, "mp": 2})
        opt = P.optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
        step = build_hybrid_train_step(model, opt, n_microbatches=4,
                                       schedule=sched, **kwargs)
        batch = {"input_ids": P.to_tensor(ids), "labels": P.to_tensor(labels)}
        l0 = float(step(batch).numpy())
        np.testing.assert_allclose(l0, ref_loss, rtol=1e-3, atol=1e-4,
                                   err_msg=sched)
        for _ in range(4):
            l = float(step(batch).numpy())
        assert l < l0, f"{sched}: loss did not decrease ({l0} -> {l})"
    mesh_mod.set_mesh(None)


def test_generate_eos_early_stop_and_deterministic_padding():
    """VERDICT: generation halts at eos_token_id per sequence (the EOS is
    kept), finished rows pad deterministically with pad_token_id, and the
    loop stops early once every row is finished — on BOTH decode paths."""
    P.seed(5)
    cfg = LlamaConfig.tiny(vocab=32, hidden=16, layers=1, heads=2, inter=32,
                           seq=64)
    m = LlamaForCausalLM(cfg)
    ids_np = np.random.RandomState(1).randint(0, 32, (2, 4))
    ids = P.to_tensor(ids_np)
    base = np.asarray(m.generate(ids, max_new_tokens=8).numpy())
    # "EOS" = a row-0 token whose FIRST occurrence is mid-stream (so row 0
    # halts exactly there) and that row 1 never generates (so only row 0
    # finishes early)
    gen0, gen1 = base[0, 4:], base[1, 4:]
    k = next((i for i in range(1, len(gen0) - 1)
              if gen0[i] not in gen0[:i] and gen0[i] not in gen1), None)
    if k is None:  # extremely unlikely at vocab 32 with this seed
        pytest.skip("no unambiguous eos candidate for this seed")
    eos = int(gen0[k])
    for use_cache in (True, False):
        out = np.asarray(m.generate(ids, max_new_tokens=8,
                                    eos_token_id=eos, pad_token_id=31,
                                    use_cache=use_cache).numpy())
        # row 0: tokens up to and including EOS, then deterministic pad
        np.testing.assert_array_equal(out[0, :4 + k + 1], base[0, :4 + k + 1],
                                      err_msg=f"use_cache={use_cache}")
        assert out[0, 4 + k] == eos
        assert (out[0, 4 + k + 1:] == 31).all(), out[0]
        # row 1 never finishes: bitwise the no-EOS run (row independence)
        np.testing.assert_array_equal(out[1], base[1],
                                      err_msg=f"use_cache={use_cache}")
    # all-rows-finished: the loop halts early (output shorter than max)
    single = P.to_tensor(ids_np[0:1])
    out1 = np.asarray(m.generate(single, max_new_tokens=8,
                                 eos_token_id=eos).numpy())
    assert out1.shape == (1, 4 + k + 1), out1.shape
    assert out1[0, -1] == eos


def test_generate_kv_cache_matches_recompute():
    """VERDICT r1 item 5: the compiled KV-cache decode must emit exactly the
    tokens of the full-recompute oracle (incl. grouped-query attention)."""
    P.seed(3)
    cfg = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, inter=64,
                           seq=128)
    m = LlamaForCausalLM(cfg)
    ids = P.to_tensor(np.random.RandomState(0).randint(0, 64, (2, 7)))
    a = m.generate(ids, max_new_tokens=9, use_cache=False)
    b = m.generate(ids, max_new_tokens=9, use_cache=True)
    assert (a.numpy() == b.numpy()).all()

    cfg2 = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128)
    m2 = LlamaForCausalLM(cfg2)
    a2 = m2.generate(ids, max_new_tokens=5, use_cache=False)
    b2 = m2.generate(ids, max_new_tokens=5, use_cache=True)
    assert (a2.numpy() == b2.numpy()).all()
