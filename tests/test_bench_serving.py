"""Smoke-level guard for the continuous-batching serving microbenchmark.

bench_serving must stay CPU-runnable and keep its one-JSON-line contract
(it is the serving-perf trajectory when the TPU probe reports
tpu-unavailable). A tiny-workload run lives in tier-1; the acceptance
ratio itself (continuous >= 1.5x sequential tokens/s) is asserted only in
the slow battery — tiny workloads on a loaded single-core CI box make
ratios noisy, and a trickle workload (queue < batch) legitimately
measures ~1x.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(requests: int, batch: int, reps: int, spec: bool = False,
               spec_k: int = 6):
    env = dict(os.environ, PT_SERVE_BENCH_REQUESTS=str(requests),
               PT_SERVE_BENCH_BATCH=str(batch),
               PT_SERVE_BENCH_REPS=str(reps),
               PT_SERVE_BENCH_SPEC_K=str(spec_k))
    argv = [sys.executable, os.path.join(REPO, "bench_serving.py")]
    if spec:
        argv.append("--spec")
    r = subprocess.run(argv, capture_output=True, text=True, timeout=600,
                       env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout  # exactly ONE JSON line on stdout
    return json.loads(lines[0]), r.stderr


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_serving_smoke_json_contract():
    payload, stderr = _run_bench(requests=6, batch=4, reps=1)
    assert payload["metric"] == "serving_throughput_speedup_vs_sequential"
    assert payload["unit"] == "x"
    assert payload["backend"] == "cpu-proxy"  # never mistaken for chip perf
    assert payload["value"] > 0
    for k in ("sequential_tokens_per_sec", "continuous_tokens_per_sec",
              "p50_token_ms", "p99_token_ms"):
        assert payload[k] > 0, (k, payload)
    assert payload["p99_token_ms"] >= payload["p50_token_ms"]
    # the engine must emit EXACTLY the sequential oracle's tokens — the
    # traced leg included (token_mismatches covers both)
    assert payload["token_mismatches"] == 0, payload
    # the observability cost gate (smoke ceiling; the documented 1.25x
    # ceiling is pinned in the slow battery)
    assert payload["traced_tokens_per_sec"] > 0
    assert 0 < payload["trace_overhead"] <= 1.5, payload
    assert "artifact ->" in stderr
    art = stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        self_json = json.load(f)
    detail = self_json["detail"]
    assert len(detail["workload"]) == 6
    info = detail["engine_info"]
    # really continuous batching: every request admitted+finished, batched
    # decode steps served multiple slots, pool drained back to empty
    assert info["finished"] == 6 and info["timed_out"] == 0
    assert 0 < info["avg_occupancy"] <= 1.0
    assert info["pool"]["active_pages"] == 0
    assert info["step"]["lowerings"] >= 2  # prefill bucket(s) + decode
    assert detail["latency_ms"]["p99"] >= detail["latency_ms"]["p50"]
    os.unlink(art)  # tiny-workload artifacts are not trajectory evidence


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_serving_spec_smoke_json_contract():
    """--spec smoke: JSON contract + the exactness gate (speculative tokens
    bitwise the non-speculative engine's), no floor at smoke scale."""
    payload, stderr = _run_bench(requests=6, batch=2, reps=1, spec=True,
                                 spec_k=4)
    assert payload["metric"] == "serving_spec_speedup_vs_nonspec"
    assert payload["backend"] == "cpu-proxy"
    assert payload["drafter"] == "ngram" and payload["spec_k"] == 4
    assert payload["value"] > 0
    assert 0.0 <= payload["acceptance_rate"] <= 1.0
    # every verify emits at least the bonus token
    assert payload["tokens_per_verify"] >= 1.0
    for k in ("nonspec_tokens_per_sec", "spec_tokens_per_sec",
              "ttft_p50_ms", "ttft_p99_ms"):
        assert payload[k] > 0, (k, payload)
    assert payload["token_mismatches"] == 0, payload
    art = stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        detail = json.load(f)["detail"]
    spec_info = detail["spec_engine_info"]["spec"]
    assert spec_info["verify_steps"] > 0
    # the verify executable lowers exactly once per (max_batch, k+1)
    assert spec_info["verify"]["lowerings"] == 1, spec_info
    os.unlink(art)  # tiny-workload artifacts are not trajectory evidence


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_serving_shared_prefix_smoke():
    """--shared-prefix smoke: JSON contract, the bitwise gate across
    shared/unshared engines, the >= 2x prefill-pages-saved floor (page
    ACCOUNTING — deterministic at any scale, unlike throughput), and the
    chunked-prefill gap bound (each inter-decode-step gap under the
    single-chunk bound, measured with a 3x margin on the same box)."""
    env = dict(os.environ, PT_SERVE_BENCH_REQUESTS="6",
               PT_SERVE_BENCH_PREFIX="48")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serving.py"),
         "--shared-prefix"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    payload = json.loads(lines[0])
    assert payload["metric"] == "serving_shared_prefix_pages_saved"
    assert payload["backend"] == "cpu-proxy"
    # the ISSUE acceptance floor: >= 2x prefill pages saved, tokens
    # bitwise the unshared engine's
    assert payload["value"] >= 2.0, payload
    assert payload["token_mismatches"] == 0, payload
    assert payload["pages_saved"] > 0
    assert payload["ttft_p50_ms_shared"] > 0
    # chunked prefill really bounded the inter-decode-step gap: the
    # DIRECTIONAL claim (chunked gap well under the whole-prefill stall,
    # ~7x here) is what tier-1 asserts — the strict single-chunk bound
    # (chunked_gap_ok) rides the payload but its 3x margin can flake on
    # a loaded CI box, so only the slow acceptance battery pins it
    assert payload["chunked_max_gap_ms"] < payload["unchunked_max_gap_ms"], \
        payload
    assert payload["single_chunk_bound_ms"] > 0
    art = r.stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        detail = json.load(f)["detail"]
    sinfo = detail["shared_engine_info"]
    assert sinfo["shared_prefix_joins"] >= 5   # every follower shared
    assert sinfo["prefix"]["pages_held"] > 0
    cinfo = detail["chunked_engine_info"]
    assert cinfo["chunked_prefills"] >= 1 and cinfo["prefill_chunks"] > 1
    os.unlink(art)  # tiny-workload artifacts are not trajectory evidence


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_serving_overload_smoke_json_contract():
    """--overload smoke: JSON contract + the typed-shed and bitwise gates.
    The goodput (>= 0.8x) and shed-latency (< 50 ms p99) floors are pinned
    only in the slow battery — wire latency on a loaded single-core CI box
    is noise at smoke scale."""
    env = dict(os.environ, PT_SERVE_BENCH_REQUESTS="12",
               PT_SERVE_BENCH_BATCH="2", PT_SERVE_BENCH_REPS="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serving.py"),
         "--overload"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    payload = json.loads(lines[0])
    assert payload["metric"] == "serving_overload_goodput_ratio"
    assert payload["backend"] == "cpu-proxy"
    # 2x-over-capacity really overloaded: work was both served AND shed,
    # and every shed came back as the typed 429 — zero untyped failures
    assert payload["offered"] == 12
    assert payload["accepted"] > 0 and payload["shed"] > 0
    assert payload["accepted"] + payload["shed"] == payload["offered"]
    assert payload["untyped_errors"] == 0, payload
    # accepted tokens bitwise the closed-loop engine's
    assert payload["token_mismatches"] == 0, payload
    assert payload["value"] > 0
    assert payload["shed_p99_ms"] >= payload["shed_p50_ms"] > 0
    # the ladder engaged under the burst and the occupancy is a
    # distribution over the four levels
    occ = payload["ladder_occupancy"]
    assert set(occ) == {"level0", "level1", "level2", "level3"}
    assert abs(sum(occ.values()) - 1.0) < 0.01, occ
    assert sum(occ[k] for k in ("level1", "level2", "level3")) > 0, occ
    art = r.stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        detail = json.load(f)["detail"]
    pressure = detail["engine_info"]["pressure"]
    assert pressure["shed"] == payload["shed"]
    assert len(detail["shed_latency_ms"]) == payload["shed"]
    assert detail["untyped"] == []
    os.unlink(art)  # tiny-workload artifacts are not trajectory evidence


@pytest.mark.slow
def test_bench_serving_overload_meets_floors():
    """Full-scale --overload acceptance: typed 429 under 50 ms p99, tokens
    bitwise, goodput >= 0.8x the closed-loop engine (measured 0.94x)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serving.py"),
         "--overload"],
        capture_output=True, text=True, timeout=600, env=dict(os.environ),
        cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([ln for ln in r.stdout.splitlines()
                          if ln.startswith("{")][0])
    assert payload["value"] >= 0.8, payload
    assert payload["shed"] > 0 and payload["untyped_errors"] == 0, payload
    assert payload["shed_p99_ms"] < 50.0, payload
    assert payload["token_mismatches"] == 0, payload


@pytest.mark.slow
def test_bench_serving_meets_acceptance_floor():
    payload, _ = _run_bench(requests=24, batch=8, reps=3)
    assert payload["value"] >= 1.5, payload
    assert payload["token_mismatches"] == 0, payload
    # the documented observability ceiling on the serving hot path
    assert payload["trace_overhead"] <= 1.25, payload


@pytest.mark.slow
def test_bench_serving_shared_prefix_meets_floors():
    """Full-scale --shared-prefix acceptance: >= 2x pages saved, bitwise,
    and every inter-decode-step gap under the single-chunk bound."""
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serving.py"),
         "--shared-prefix"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads([ln for ln in r.stdout.splitlines()
                          if ln.startswith("{")][0])
    assert payload["value"] >= 2.0, payload
    assert payload["token_mismatches"] == 0, payload
    assert payload["chunked_gap_ok"] is True, payload


@pytest.mark.slow
def test_bench_serving_spec_meets_acceptance_floor():
    """Speculative decoding with the n-gram drafter must clear 1.25x
    tokens/s over the spec-off engine on the decode-dominated CPU-proxy
    workload (measured 1.66x; the acceptance rate rides the payload so a
    drafter regression is diagnosable from the artifact)."""
    payload, _ = _run_bench(requests=24, batch=4, reps=3, spec=True)
    assert payload["value"] >= 1.25, payload
    assert payload["token_mismatches"] == 0, payload
    assert payload["acceptance_rate"] > 0.2, payload
