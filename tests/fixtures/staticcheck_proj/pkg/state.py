"""Fixture: mutable-global violations and the sanctioned installer path."""

_CACHE = {}
_handler = None


def set_handler(fn):
    global _handler  # installer-shaped name: sanctioned, not flagged
    _handler = fn


def sneaky_write(fn):
    global _handler  # mutable-global: rebind outside an installer
    _handler = fn


def memoize(key, value):
    _CACHE[key] = value  # mutable-global: container mutated outside installer


def local_shadow_ok(key, value):
    _CACHE = {}  # local rebind shadows the module global: not flagged
    _CACHE[key] = value
    return _CACHE
