"""Namespaced-family fixture (registry-consistency route 3b): ops whose
names qualify the public name with the module tail (`subpkg_govfoo` for
`paddle_tpu.subpkg.govfoo`). Parse-only, like every fixture module.

- ``subpkg_govfoo``: public module-level def, referenced through
  `import paddle_tpu.subpkg as NS; NS.govfoo` in tests/battery_cases.py
  -> governed;
- ``subpkg_govmethod``: public method of a public module-level class
  (the sparse.nn shape), referenced as `NS.grouped.govmethod`
  -> governed;
- ``subpkg_orphanbar``: dispatched by a public def nothing references
  -> stays an orphan (the known-answer finding).
"""
import jax.numpy as jnp

from ..ops.hazards import apply


def govfoo(x):
    return apply(jnp.tanh, x, op_name="subpkg_govfoo")


class grouped:
    @staticmethod
    def govmethod(x):
        return apply(jnp.cosh, x, op_name="subpkg_govmethod")


def orphanbar(x):
    return apply(jnp.sinh, x, op_name="subpkg_orphanbar")
