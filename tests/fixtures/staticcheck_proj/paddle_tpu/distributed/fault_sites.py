"""chaos-site-coverage known-answer fixture: three fault-site shapes.

- ``fixture.covered`` appears in this tree's tests/test_no_hang.py MATRIX
  -> governed, no finding;
- ``fixture.uncovered`` is registered but in no matrix row -> the one
  finding this fixture owes;
- ``fixture.pragma`` is uncovered but deliberately suppressed with a
  rationale -> no finding (pragma route).
"""


def register_fault(site, description=""):
    return site


FP_COVERED = register_fault(
    "fixture.covered", "a blocking window the matrix proves")
FP_UNCOVERED = register_fault(
    "fixture.uncovered", "a blocking window nobody proves — the finding")
FP_PRAGMA = register_fault(  # staticcheck: ok[chaos-site-coverage] — fixture: deliberately unmatrixed site with a recorded rationale
    "fixture.pragma", "a site whose coverage is deliberately waived")
