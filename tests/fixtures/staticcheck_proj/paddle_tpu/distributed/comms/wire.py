"""Fixture: the comms subsystem itself may touch the wire (allowlisted)."""
import jax


def two_shot(v):
    # inside distributed/comms/: NOT flagged
    return jax.lax.psum(v, "dp")
