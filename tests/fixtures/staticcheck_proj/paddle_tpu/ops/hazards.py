"""Purpose-built staticcheck violations (test fixture — never imported).

Mirrors the real tree's layout (paddle_tpu/ops/) so path-gated rules
(host-sync) and the registry cross-check fire exactly as they do on the
shipped code. Each function below is one known-answer violation asserted
by tests/test_staticcheck.py.
"""
import numpy as np
import jax.numpy as jnp

from .dispatch import apply  # AST-only fixture: import never executes

__all__ = ["branchy", "numpy_feeder", "syncy", "ghost_export"]


def branchy(x):
    def f(v):
        if v > 0:  # tracer-branch: value-dependent Python branch
            return v
        return -v
    return apply(f, x, op_name="covered_op")


def metadata_branch_ok(x):
    def f(v):
        if v.ndim == 2:  # static metadata: must NOT be flagged
            return v
        return v[None]
    return apply(f, x, op_name="covered_op")


def numpy_feeder(x):
    # numpy-on-tracer: np.* fed the traced param
    return apply(lambda v: jnp.asarray(np.cumsum(v)), x,
                 op_name="toleranced_op")


def numpy_static_ok(x):
    def f(v):
        idx = np.arange(int(v.shape[0]))  # static-shape numpy: not flagged
        return v[idx]
    return apply(f, x, op_name="covered_op")


def syncy(x):
    n = int(x._value)  # host-sync: int() over the payload
    return x.item(), n  # host-sync: .item()


def orphan(x):
    # registry-consistency: no tolerance entry, no coverage record
    return apply(jnp.tanh, x, op_name="fixture_orphan_op")


def suppressed(x):
    def f(v):
        if v > 0:  # staticcheck: ok[tracer-branch] — fixture: pragma-suppressed on purpose
            return v
        return -v
    return apply(f, x, op_name="covered_op")


def suppressed_all(x):
    return x.item()  # staticcheck: ok — bare pragma suppresses every rule


def wrong_pragma(x):
    def f(v):
        if v > 0:  # staticcheck: ok[host-sync] — wrong rule id: must still be reported
            return v
        return -v
    return apply(f, x, op_name="covered_op")
