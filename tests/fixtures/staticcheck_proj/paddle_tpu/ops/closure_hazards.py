"""closure-capture known-answer fixture (AST-only — never imported).

Positive captures (payload attribute, hoisted array, host copy), the
sanctioned pass-it-through / static-config idioms (quiet), and a pragma'd
copy — asserted line-by-line by tests/test_staticcheck.py.
"""
import jax.numpy as jnp

from .dispatch import apply


def captured_payload(x, y):
    return apply(lambda v: v + y._value, x, op_name="covered_op")


def captured_hoisted_array(x, mask):
    m = jnp.asarray(mask)
    return apply(lambda v: jnp.where(m, v, 0.0), x, op_name="covered_op")


def captured_host_copy(x, y):
    return apply(lambda v: v * y.numpy(), x, op_name="covered_op")


def passed_through_ok(x, y):
    return apply(lambda v, w: v + w, x, y, op_name="covered_op")


def static_config_ok(x, axis=1):
    return apply(lambda v: jnp.sum(v, axis=axis), x, op_name="covered_op")


def metadata_only_ok(x, y):
    k = y._value.shape
    return apply(lambda v: jnp.reshape(v, k), x, op_name="covered_op")


def suppressed_capture(x, y):
    return apply(lambda v: v * y._value, x, op_name="covered_op")  # staticcheck: ok[closure-capture] — fixture: pragma'd copy of captured_payload
