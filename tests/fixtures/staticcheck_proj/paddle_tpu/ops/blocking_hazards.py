"""unbounded-blocking known-answer fixtures.

Positives: an argless queue get, a store-style wait keyed by a string, a
predicate wait_for with no bound, and a raw socket recv. Negatives: every
bounded variant (timeout kwarg, numeric positional, interval-named bound),
dict-style get with a key, and the pragma'd copy.
"""


def q_get_forever(q):
    return q.get()


def store_wait_forever(store):
    store.wait("roster_ready")


def cond_wait_forever(cond):
    with cond:
        cond.wait_for(lambda: False)


def raw_recv(sock):
    return sock.recv(4096)


def bounded_ok(q, ev, store, popen):
    q.get(timeout=1.0)
    ev.wait(0.5)
    store.wait("key", timeout=2.0)
    popen.wait(timeout=3)


def dict_get_ok(d, env):
    return d.get("key", 0), env.get("PT_FLAG")


def interval_bound_ok(stop, cfg):
    stop.wait(cfg.interval)
    stop.wait(cfg.poll_timeout)


def suppressed_get(q):
    return q.get()  # staticcheck: ok[unbounded-blocking] — fixture: pragma must silence the rule


def thread_join_forever(t):
    t.join()


def thread_join_bounded_ok(t):
    t.join(timeout=5.0)
    t.join(2.0)


def path_join_ok(parts):
    import os
    return os.path.join("a", "b"), ",".join(parts)


def suppressed_join(t):
    t.join()  # staticcheck: ok[unbounded-blocking] — fixture: pragma must silence the join leg
