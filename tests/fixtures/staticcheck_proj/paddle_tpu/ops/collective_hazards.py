"""Fixture: naked-collective positives/negatives (tests/test_staticcheck)."""
import jax
from jax import lax


def naked_psum(v):
    return lax.psum(v, "dp")                      # line 7: FLAGGED


def naked_all_gather(v):
    return jax.lax.all_gather(v, "mp")            # line 11: FLAGGED


def routed_ok(v, comms):
    # routed through the comms subsystem: not a lax attribute call
    return comms.wire_all_reduce(v, "dp", "sum")


def unrelated_attr_ok(engine):
    # `.psum` on something that is not lax stays quiet
    return engine.psum("dp")


def non_collective_lax_ok(v):
    # lax math is not wire traffic
    return lax.tanh(v)


def suppressed(v):
    return lax.ppermute(v, "pp", [(0, 1)])  # staticcheck: ok[naked-collective] — deliberate fixture pragma
