"""registry-consistency resolution fixtures (PR 11 burn-down patterns).

Each function exercises one governance route the extended name resolver
must follow — a working resolver reports NOTHING for any name here:

- ``fixloopskip``  registered into SKIPS by a module-level family-sweep
  loop in tests/op_tolerances.py (the alias-collection registration);
- ``fixbattery``   a public op: exported via the loop-built
  ``__all__.append`` form AND referenced by name in the battery file
  tests/battery_cases.py;
- ``primal``       NOT an op at all: the implied-name extractor must not
  report a phantom op for a local binding handed to apply().
"""
import jax.numpy as jnp

from .dispatch import apply  # AST-only fixture: import never executes

_EXPORTED_OPS = ("fixbattery",)
__all__ = []
for _name in _EXPORTED_OPS:
    __all__.append(_name)


def fixloopskip(x):
    # governed by the family-sweep SKIPS loop in the fixture registry
    return apply(jnp.sinh, x, op_name="fixloopskip")


def fixdtloop(x):
    # literal FWD_OVERRIDES entry whose bfloat16 hole a family-sweep
    # SKIPS loop covers (dtype-rule-coverage must stay quiet)
    return apply(jnp.cosh, x, op_name="fixdtloop")


def fixbattery(x):
    # governed by battery reference: public name + tests/battery_cases.py
    return apply(jnp.tanh, x, op_name="fixbattery")


def dispatch_through_local(primal, x):
    # `primal` is a parameter: the implied-name fallback must not treat
    # it as an op name (no phantom "primal" orphan)
    return apply(primal, x)
