"""Instance-attribute op_name indirection (test fixture — never imported).

The rnn.py dispatch shape: `op_name=self.mode.lower()` where `self.mode`
is bound in __init__ from a parameter, and the concrete strings flow in
from subclasses' `super().__init__(...)` calls (including a
constant-armed conditional). registry-consistency must resolve
"fixlstm" / "fixtanh" / "fixrelu" as dispatch sites — the fixture
registry lists them, so a working resolver yields NO finding here while
a regressed one reports them stale.
"""
from .dispatch import apply  # AST-only fixture: import never executes


class _ModalBase:
    def __init__(self, mode, width):
        self.mode = mode
        self.width = width

    def forward(self, x):
        def f(v):
            return v

        return apply(f, x, op_name=self.mode.lower())


class FixLstm(_ModalBase):
    def __init__(self, width):
        super().__init__("FIXLSTM", width)


class FixSimple(_ModalBase):
    def __init__(self, width, activation="tanh"):
        mode = "FIXTANH" if activation == "tanh" else "FIXRELU"
        super().__init__(mode, width)
