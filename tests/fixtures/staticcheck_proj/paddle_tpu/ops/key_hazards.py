"""key-reuse known-answer fixture (AST-only, never imported).

Each function is one case asserted by tests/test_staticcheck.py: two
positive reuses, the safe split-and-rebind idiom, mutually-exclusive
branches, and a pragma suppression.
"""
import jax


def reuse_same_key(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))       # key-reuse: second draw
    return a + b


def split_then_reuse_original():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    c = jax.random.normal(key, (2,))        # key-reuse: key already split
    return k1, k2, c


def fresh_subkeys_ok():
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (2,))
    key, sub = jax.random.split(key)        # rebound: not a reuse
    b = jax.random.normal(sub, (2,))
    return a + b


def branch_exclusive_ok(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))    # sibling arm: never both taken


def suppressed_reuse(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(key, (4,))  # staticcheck: ok[key-reuse] — fixture: pragma-suppressed on purpose
    return a + b


def non_random_jax_call_ok(key):
    key = jax.device_put(key)               # not jax.random: no consumption
    jax.block_until_ready(key)
    return jax.random.normal(key, (2,))     # first (only) draw: quiet
