"""Fixture serving engine for typed-error-wire-coverage known answers:
one typed raise with a status mapping (quiet), one without (fires), one
subclass covered through its mapped ancestor (quiet), and a builtin
raise that is out of scope."""
from .gateway.protocol import FixtureDraining


class FixtureOverloaded(TimeoutError):
    """Typed shed with NO status_of mapping — the known answer."""


class FixtureFrameTooLong(ValueError):
    """Covered through the mapped ValueError ancestor."""


def admit(queued, cap):
    if queued >= cap:
        raise FixtureOverloaded("queue at cap")


def drain():
    raise FixtureDraining("fixture gateway draining")


def parse_frame(size, limit):
    if size > limit:
        raise FixtureFrameTooLong("frame over limit")
    if size < 0:
        raise ValueError("negative frame size")
