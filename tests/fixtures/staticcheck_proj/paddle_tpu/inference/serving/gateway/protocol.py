"""Fixture wire protocol for typed-error-wire-coverage known answers:
``status_of`` maps FixtureDraining (and the ValueError/FixtureWireError
pair) but NOT FixtureOverloaded — the uncovered raise in the fixture
engine is the one expected finding."""

STATUS_BAD_REQUEST = 400
STATUS_DRAINING = 503
STATUS_INTERNAL = 500


class FixtureWireError(ConnectionError):
    """Malformed fixture frame."""


class FixtureDraining(RuntimeError):
    """Fixture gateway is draining."""


def status_of(exc):
    if isinstance(exc, FixtureDraining):
        return STATUS_DRAINING
    if isinstance(exc, (ValueError, FixtureWireError)):
        return STATUS_BAD_REQUEST
    return STATUS_INTERNAL
