"""Fixture battery: exercises ops by name, the governance route the
registry-consistency checker resolves through public `__all__` exports.
A cases-table string key counts only because the table's values reach
the package (parse-only fixture: the import never executes)."""
import paddle_tpu as P

CASES = {
    "fixbattery": P.run_case,   # key governs; the value ties the table
                                # to the package (a bare-config dict
                                # would govern nothing)
}
