"""Fixture battery: exercises ops by name, the governance route the
registry-consistency checker resolves through public `__all__` exports.
A cases-table string key counts only because the table's values reach
the package (parse-only fixture: the import never executes)."""
import paddle_tpu as P
import paddle_tpu.subpkg as NS

CASES = {
    "fixbattery": P.run_case,   # key governs; the value ties the table
                                # to the package (a bare-config dict
                                # would govern nothing)
}

# namespaced-family route (3b): attribute references through a module
# alias also exercise the module-qualified op names — NS.govfoo reaches
# `subpkg_govfoo`, NS.grouped.govmethod reaches `subpkg_govmethod`
NAMESPACED_CASES = (NS.govfoo, NS.grouped.govmethod)
