"""Fixture tolerance registry for the registry-consistency cross-check."""

FWD_OVERRIDES = {
    "toleranced_op": {"bfloat16": (1e-1, 1e-2)},
    "stale_op": {"float16": (1e-2, 1e-3)},  # no dispatch site: stale
}

GRAD_OVERRIDES = {}

SKIPS = {}
