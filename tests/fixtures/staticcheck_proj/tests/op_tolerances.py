"""Fixture tolerance registry for the registry-consistency cross-check."""

FWD_OVERRIDES = {
    "toleranced_op": {"bfloat16": (1e-1, 1e-2)},
    "stale_op": {"float16": (1e-2, 1e-3)},  # no dispatch site: stale
    # dynamic_names.py sites: op_name=self.mode.lower() resolved through
    # subclass super().__init__ constants — governed, NOT stale
    "fixlstm": {"float16": (1e-2, 1e-3)},
    "fixtanh": {"float16": (1e-2, 1e-3)},
    "fixrelu": {"float16": (1e-2, 1e-3)},
}

GRAD_OVERRIDES = {}

SKIPS = {}
