"""Fixture tolerance registry for the registry-consistency cross-check and
the dtype-rule-coverage completeness rule."""

FWD_OVERRIDES = {
    # partial entry: lacks float16 -> dtype-rule-coverage fires
    "toleranced_op": {"bfloat16": (1e-1, 1e-2)},
    # complete entry (both swept dtypes): quiet; no dispatch site: stale
    "stale_op": {"float16": (1e-2, 1e-3), "bfloat16": (1e-1, 1e-2)},
    # dynamic_names.py sites: op_name=self.mode.lower() resolved through
    # subclass super().__init__ constants — governed, NOT stale
    "fixlstm": {"float16": (1e-2, 1e-3), "bfloat16": (1e-1, 1e-2)},
    # lacks bfloat16 but a recorded SKIP covers the hole: quiet
    "fixtanh": {"float16": (1e-2, 1e-3)},
    # lacks bfloat16 with no skip -> dtype-rule-coverage fires
    "fixrelu": {"float16": (1e-2, 1e-3)},
    # lacks bfloat16 but the family-sweep LOOP below records the skip:
    # quiet (the loop-registered-skip resolution, PR 11)
    "fixdtloop": {"float16": (1e-2, 1e-3)},
}

GRAD_OVERRIDES = {
    # complete grad entry: quiet
    "toleranced_op": {"bfloat16": (2e-1, 1e-1), "float16": (2e-2, 5e-3)},
    # lacks float16 -> dtype-rule-coverage fires (grad leg)
    "fixrelu": {"bfloat16": (2e-1, 1e-1)},
}

SKIPS = {
    ("fixtanh", "fwd", "bfloat16"): "fixture: recorded skip covers the gap",
    ("fixlstm", "grad", "*"): "fixture: wildcard skip (no grad overrides)",
}

# family-sweep registration (the loop-registered form the extended
# resolver follows): governs `fixloopskip` without a literal entry, and
# covers fixdtloop's missing-bfloat16 hole for dtype-rule-coverage
_LOOP_FAMILY = ("fixloopskip", "fixdtloop")
for _op in _LOOP_FAMILY:
    for _dt in ("bfloat16", "float16"):
        for _chk in ("fwd", "grad"):
            SKIPS.setdefault((_op, _chk, _dt), "fixture: family sweep")
