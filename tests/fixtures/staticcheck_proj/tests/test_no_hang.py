"""Fixture no-hang matrix for the chaos-site-coverage known answers: the
covered-site list the checker cross-references (site element of each key)."""

MATRIX = {
    ("fixture.covered", "crash"): ("sigkill", None),
    ("fixture.covered", "delay:1.0"): ("typed", "StoreTimeout"),
}
