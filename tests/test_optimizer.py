"""Optimizer + LR scheduler tests, incl. a convergence run (the reference's
loss-parity test pattern, test/legacy_test/test_dist_base.py style but local)."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import SGD, Adam, AdamW, Momentum, lr as lr_mod


def _quadratic_step(opt_cls, **kw):
    w = P.Parameter(P.to_tensor([5.0])._value)
    opt = opt_cls(parameters=[w], **kw)
    losses = []
    for _ in range(50):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


def test_sgd_converges():
    losses = _quadratic_step(SGD, learning_rate=0.1)
    assert losses[-1] < 1e-3 * losses[0]


def test_momentum_converges():
    losses = _quadratic_step(Momentum, learning_rate=0.05, momentum=0.9)
    assert losses[-1] < 1e-2 * losses[0]


def test_adam_converges():
    losses = _quadratic_step(Adam, learning_rate=0.3)
    assert losses[-1] < 1e-2 * losses[0]


def test_adam_matches_reference_formula():
    # one step of Adam against the hand-computed update
    w0 = 2.0
    g = 2 * w0  # grad of w^2
    w = P.Parameter(P.to_tensor([w0])._value)
    opt = Adam(learning_rate=0.1, parameters=[w], beta1=0.9, beta2=0.999,
               epsilon=1e-8)
    (w * w).sum().backward()
    opt.step()
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    expect = w0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(w.numpy(), [expect], rtol=1e-5)


def test_adamw_decoupled_decay():
    w = P.Parameter(P.ones([1])._value)
    opt = AdamW(learning_rate=0.0, parameters=[w], weight_decay=0.1)
    (w * 1.0).sum().backward()
    opt.step()
    # lr=0 => no update at all (decay is multiplied by lr)
    np.testing.assert_allclose(w.numpy(), [1.0])


def test_weight_decay_l2():
    w = P.Parameter(P.ones([1])._value)
    opt = SGD(learning_rate=0.1, parameters=[w], weight_decay=0.5)
    (w * 0.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 0.5], rtol=1e-6)


def test_optimizer_state_dict_roundtrip():
    w = P.Parameter(P.to_tensor([5.0])._value)
    opt = Adam(learning_rate=0.1, parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    w2 = P.Parameter(P.to_tensor([5.0])._value)
    opt2 = Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(sd)
    assert opt2._global_step == 1


def test_grad_clip_in_optimizer():
    w = P.Parameter(P.to_tensor([1.0])._value)
    opt = SGD(learning_rate=1.0, parameters=[w],
              grad_clip=nn.ClipGradByGlobalNorm(0.1))
    (w * 100.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1], rtol=1e-5)


def test_lr_schedulers():
    s = lr_mod.StepDecay(0.1, step_size=10, gamma=0.5)
    assert abs(s() - 0.1) < 1e-9
    for _ in range(10):
        s.step()
    assert abs(s() - 0.05) < 1e-9

    c = lr_mod.CosineAnnealingDecay(1.0, T_max=100)
    c.step(50)
    assert abs(c() - 0.5) < 1e-6

    w = lr_mod.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    w.step(5)
    assert abs(w() - 0.05) < 1e-9

    pw = lr_mod.PiecewiseDecay([10, 20], [0.1, 0.01, 0.001])
    pw.step(15)
    assert abs(pw() - 0.01) < 1e-9


def test_scheduler_with_optimizer():
    sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
    w = P.Parameter(P.to_tensor([1.0])._value)
    opt = SGD(learning_rate=sched, parameters=[w])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-9


def test_mlp_training_convergence():
    """End-to-end: tiny MLP learns XOR-ish synthetic task."""
    P.seed(0)
    rng = np.random.RandomState(0)
    x_np = rng.randn(256, 2).astype(np.float32)
    y_np = ((x_np[:, 0] * x_np[:, 1]) > 0).astype(np.int64)

    model = nn.Sequential(nn.Linear(2, 32), nn.Tanh(), nn.Linear(32, 2))
    opt = Adam(learning_rate=0.01, parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    x, y = P.to_tensor(x_np), P.to_tensor(y_np)
    first = None
    for i in range(150):
        logits = model(x)
        loss = ce(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    final = float(loss.numpy())
    assert final < 0.35 * first, (first, final)
    acc = (np.argmax(model(x).numpy(), -1) == y_np).mean()
    assert acc > 0.9


# ---- LBFGS (reference: python/paddle/optimizer/lbfgs.py:309) ----

def _rosenbrock_lbfgs(line_search):
    from paddle_tpu.optimizer import LBFGS
    xy = P.Parameter(P.to_tensor([-1.2, 1.0])._value)
    opt = LBFGS(learning_rate=1.0 if line_search else 0.01,
                max_iter=20, history_size=10,
                line_search_fn="strong_wolfe" if line_search else None,
                parameters=[xy])

    def closure():
        opt.clear_grad()
        x, y = xy[0], xy[1]
        loss = (1 - x) ** 2 + 100 * (y - x * x) ** 2
        loss.backward()
        return loss

    for _ in range(15 if line_search else 60):
        opt.step(closure)
    return np.asarray(xy.numpy())


def test_lbfgs_strong_wolfe_solves_rosenbrock():
    sol = _rosenbrock_lbfgs(line_search=True)
    np.testing.assert_allclose(sol, [1.0, 1.0], atol=1e-4)


def test_lbfgs_fixed_step_descends():
    from paddle_tpu.optimizer import LBFGS
    w = P.Parameter(P.to_tensor([5.0, -3.0])._value)
    opt = LBFGS(learning_rate=0.5, max_iter=10, parameters=[w])

    def closure():
        opt.clear_grad()
        loss = (w * w).sum()
        loss.backward()
        return loss

    first = float(opt.step(closure).numpy())
    last = float(opt.step(closure).numpy())
    assert last < 1e-4 * first


def test_lbfgs_matches_reference_quadratic_minimum():
    # Quadratic f(w) = 0.5 w^T A w - b^T w with SPD A: L-BFGS with strong
    # Wolfe must hit the closed-form minimum A^-1 b (numeric OpTest pattern).
    from paddle_tpu.optimizer import LBFGS
    rng = np.random.RandomState(0)
    m = rng.randn(4, 4).astype(np.float32)
    a_np = m @ m.T + 4 * np.eye(4, dtype=np.float32)
    b_np = rng.randn(4).astype(np.float32)
    a, b = P.to_tensor(a_np), P.to_tensor(b_np)
    w = P.Parameter(P.zeros([4])._value)
    opt = LBFGS(learning_rate=1.0, max_iter=30, history_size=10,
                line_search_fn="strong_wolfe", parameters=[w])

    def closure():
        opt.clear_grad()
        loss = 0.5 * (w @ (a @ w)) - (b * w).sum()
        loss.backward()
        return loss

    opt.step(closure)
    np.testing.assert_allclose(w.numpy(), np.linalg.solve(a_np, b_np),
                               atol=5e-4)


def test_lbfgs_state_dict_roundtrip():
    from paddle_tpu.optimizer import LBFGS
    w = P.Parameter(P.to_tensor([3.0])._value)
    opt = LBFGS(learning_rate=1.0, max_iter=3,
                line_search_fn="strong_wolfe", parameters=[w])

    def closure():
        opt.clear_grad()
        loss = (w * w).sum()
        loss.backward()
        return loss

    opt.step(closure)
    sd = opt.state_dict()
    opt2 = LBFGS(learning_rate=1.0, max_iter=3,
                 line_search_fn="strong_wolfe", parameters=[w])
    opt2.set_state_dict(sd)
    assert opt2._hist["n_iter"] == opt._hist["n_iter"]
    assert len(opt2._hist["old_stps"]) == len(opt._hist["old_stps"])


def test_lars_trust_ratio_update():
    # One step of LARS against the hand-computed layer-wise update
    # (incubate/optimizer/lars_momentum.py:30-41 formula).
    from paddle_tpu.optimizer import Lars
    w0 = np.array([3.0, 4.0], np.float32)  # ||w|| = 5
    w = P.Parameter(P.to_tensor(w0)._value)
    lr, mu, coeff, wd = 0.1, 0.9, 0.001, 0.0005
    opt = Lars(learning_rate=lr, momentum=mu, lars_coeff=coeff,
               lars_weight_decay=wd, parameters=[w])
    (w * w).sum().backward()  # grad = 2w, ||g|| = 10
    opt.step()
    g = 2 * w0
    w_n, g_n = np.linalg.norm(w0), np.linalg.norm(g)
    local_lr = lr * coeff * w_n / (g_n + wd * w_n)
    v = local_lr * (g + wd * w0)
    np.testing.assert_allclose(w.numpy(), w0 - v, rtol=1e-5)


def test_lars_converges():
    from paddle_tpu.optimizer import Lars
    losses = _quadratic_step(Lars, learning_rate=1.0, momentum=0.5,
                             lars_coeff=0.1, lars_weight_decay=0.0)
    assert losses[-1] < 1e-2 * losses[0]
