"""vision models/transforms/datasets + metric + hapi Model tests."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import metric as M
from paddle_tpu import vision
from paddle_tpu.vision import transforms as T


def test_resnet18_forward():
    # compiled path: one whole-model XLA compile instead of ~70 per-op
    # compiles (6x faster on this host; same layer code exercised)
    net = P.to_static(vision.resnet18(num_classes=10))
    x = P.to_tensor(np.random.RandomState(0).randn(2, 3, 64, 64).astype("float32"))
    out = net(x)
    assert out.shape == [2, 10]


def test_mobilenet_lenet_forward():
    net = P.to_static(vision.mobilenet_v2(num_classes=7))
    x = P.to_tensor(np.random.RandomState(0).randn(1, 3, 64, 64).astype("float32"))
    assert net(x).shape == [1, 7]
    le = vision.LeNet()  # eager path coverage on the small model
    x = P.to_tensor(np.random.RandomState(0).randn(2, 1, 28, 28).astype("float32"))
    assert le(x).shape == [2, 10]


def test_transforms_pipeline():
    tf = T.Compose([
        T.Resize(40), T.CenterCrop(32), T.RandomHorizontalFlip(0.5),
        T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)])
    img = np.random.RandomState(0).randint(0, 256, (50, 60, 3), np.uint8)
    out = tf(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32
    assert -1.01 <= out.min() and out.max() <= 1.01


def test_fake_data_and_folder(tmp_path):
    ds = vision.FakeData(size=12, image_shape=(3, 16, 16), num_classes=4,
                         transform=T.ToTensor())
    img, label = ds[0]
    assert img.shape == (3, 16, 16) and 0 <= int(label) < 4
    # DatasetFolder over .npy files
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(d / f"{i}.npy",
                    np.zeros((8, 8, 3), np.uint8))
    folder = vision.DatasetFolder(str(tmp_path))
    assert len(folder) == 6
    assert folder.classes == ["cat", "dog"]
    img, label = folder[5]
    assert label == 1


def test_accuracy_metric():
    acc = M.Accuracy(topk=(1, 2))
    pred = P.to_tensor(np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], "float32"))
    label = P.to_tensor(np.array([[1], [2]]))
    correct = acc.compute(pred, label)
    acc.update(correct)
    top1, top2 = acc.accumulate()
    assert abs(top1 - 0.5) < 1e-6
    assert abs(top2 - 0.5) < 1e-6


def test_auc_precision_recall():
    auc = M.Auc()
    preds = np.array([0.1, 0.2, 0.8, 0.9])
    labels = np.array([0, 0, 1, 1])
    auc.update(preds, labels)
    assert auc.accumulate() > 0.9
    p = M.Precision()
    r = M.Recall()
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == 1.0
    assert r.accumulate() == 1.0


def test_hapi_model_fit_evaluate_predict(tmp_path):
    ds = vision.FakeData(size=32, image_shape=(1, 8, 8), num_classes=3,
                         transform=T.ToTensor())

    net = P.nn.Sequential(P.nn.Flatten(), P.nn.Linear(64, 3))
    model = P.Model(net)
    opt = P.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    model.prepare(opt, P.nn.CrossEntropyLoss(), M.Accuracy())
    model.fit(ds, epochs=2, batch_size=8, verbose=0)
    logs = model.evaluate(ds, batch_size=8, verbose=0)
    assert "acc" in logs and "loss" in logs
    preds = model.predict(ds, batch_size=8, stack_outputs=True)
    assert preds[0].shape == (32, 3)
    model.save(str(tmp_path / "ck"))
    model.load(str(tmp_path / "ck"))


def test_summary():
    net = vision.LeNet()
    info = P.summary(net)
    assert info["total_params"] > 0
