"""Distribution-method cases-table battery (registry-consistency orphan
burn-down, ROADMAP standing debt).

Every key in CASES was a baselined `registry-consistency` orphan: a
``paddle_tpu.distribution`` method dispatching under a stable ``op_name``
that no test battery referenced through the package. Per the burn-down
rule these are retired with REAL known-answer assertions via the public
surface — closed-form values where the distribution has them, exact
numeric sums for the discrete entropies, and support/shape laws for the
samplers — never by loosening the checker's resolution. The ratchet in
tools/staticcheck/baseline.json is re-cut downward as this table grows.
"""
import math

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.distribution as D

_G = 0.5772156649015329          # Euler-Mascheroni


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


def _t(a):
    return P.to_tensor(np.asarray(a, np.float32))


def _close(actual, expected, tol=1e-4):
    np.testing.assert_allclose(_np(actual), np.asarray(expected, np.float32),
                               rtol=tol, atol=tol)


def _support(actual, pred, shape=None):
    a = _np(actual)
    assert np.isfinite(a).all(), a
    assert pred(a).all(), a
    if shape is not None:
        assert a.shape == shape, a.shape


def _binomial_entropy(n, p):
    pk = np.asarray([math.comb(n, k) * p**k * (1 - p)**(n - k)
                     for k in range(n + 1)])
    return float(-(pk * np.log(pk)).sum())


def _poisson_entropy(rate):
    pk = np.asarray([rate**k * math.exp(-rate) / math.factorial(k)
                     for k in range(60)])
    pk = pk[pk > 0]
    return float(-(pk * np.log(pk)).sum())


# Each value is a zero-arg case body: building the distribution and
# asserting the known answer IS the case. The string keys are the
# governed op names; the D. references in the values tie the table to
# the package (the battery-governance route the checker resolves).
CASES = {
    # ---- bernoulli (bernoulli_mean also covers ContinuousBernoulli.mean,
    # which dispatches under the same module-qualified name) ----
    "bernoulli_cdf": lambda: _close(
        D.Bernoulli(0.3).cdf(_t([-1.0, 0.5, 2.0])), [0.0, 0.7, 1.0]),
    "bernoulli_mean": lambda: (
        _close(D.Bernoulli(0.3).mean, 0.3),
        _close(D.ContinuousBernoulli(0.3).mean,
               0.3 / (2 * 0.3 - 1) + 1 / (2 * math.atanh(1 - 2 * 0.3)))),
    "bernoulli_variance": lambda: _close(
        D.Bernoulli(0.3).variance, 0.3 * 0.7),
    "bernoulli_entropy": lambda: _close(
        D.Bernoulli(0.3).entropy(),
        -(0.3 * math.log(0.3) + 0.7 * math.log(0.7))),
    "bernoulli_log_prob": lambda: _close(
        D.Bernoulli(0.3).log_prob(_t([1.0, 0.0])),
        [math.log(0.3), math.log(0.7)]),
    "bernoulli_rsample": lambda: _support(
        D.Bernoulli(0.3).rsample((64,)),
        lambda a: (a > 0.0) & (a < 1.0), shape=(64,)),
    "bernoulli_sample": lambda: _support(
        D.Bernoulli(0.3).sample((64,)),
        lambda a: (a == 0.0) | (a == 1.0), shape=(64,)),
    # ---- beta ----
    "beta_entropy": lambda: _close(D.Beta(2.0, 3.0).entropy(), -0.2349066),
    "beta_log_prob": lambda: _close(
        D.Beta(2.0, 3.0).log_prob(_t([0.5])), [math.log(1.5)]),
    "beta_mean": lambda: _close(D.Beta(2.0, 3.0).mean, 0.4),
    "beta_rsample": lambda: _support(
        D.Beta(2.0, 3.0).rsample((32,)), lambda a: (a > 0.0) & (a < 1.0)),
    "beta_variance": lambda: _close(
        D.Beta(2.0, 3.0).variance, 2.0 * 3.0 / (5.0**2 * 6.0)),
    # ---- binomial ----
    "binomial_entropy": lambda: _close(
        D.Binomial(10, 0.5).entropy(), _binomial_entropy(10, 0.5),
        tol=1e-3),
    "binomial_log_prob": lambda: _close(
        D.Binomial(10, 0.5).log_prob(_t([5.0])),
        [math.log(252.0 / 1024.0)]),
    "binomial_mean": lambda: _close(D.Binomial(10, 0.5).mean, 5.0),
    "binomial_sample": lambda: _support(
        D.Binomial(10, 0.5).sample((32,)),
        lambda a: (a >= 0) & (a <= 10) & (a == np.floor(a))),
    "binomial_variance": lambda: _close(
        D.Binomial(10, 0.5).variance, 10 * 0.5 * 0.5),
    # ---- categorical (logits are unnormalized probabilities) ----
    "categorical_entropy": lambda: _close(
        D.Categorical(_t([1.0, 2.0, 1.0])).entropy(),
        -(0.5 * math.log(0.25) + 0.5 * math.log(0.5))),
    "categorical_kl_divergence": lambda: _close(
        D.Categorical(_t([1.0, 2.0, 1.0]))
        .kl_divergence(D.Categorical(_t([1.0, 1.0, 1.0]))),
        0.5 * math.log(0.75) + 0.5 * math.log(1.5)),
    "categorical_log_prob": lambda: _close(
        D.Categorical(_t([1.0, 2.0, 1.0])).log_prob(_t(1.0)),
        math.log(0.5)),
    "categorical_probs": lambda: _close(
        D.Categorical(_t([1.0, 2.0, 1.0])).probs, [0.25, 0.5, 0.25]),
    "categorical_sample": lambda: _support(
        D.Categorical(_t([1.0, 2.0, 1.0])).sample((64,)),
        lambda a: (a >= 0) & (a <= 2) & (a == np.floor(a))),
    # ---- cauchy ----
    "cauchy_cdf": lambda: _close(
        D.Cauchy(0.0, 1.0).cdf(_t([0.0, 1.0])), [0.5, 0.75]),
    "cauchy_entropy": lambda: _close(
        D.Cauchy(0.0, 1.0).entropy(), math.log(4 * math.pi)),
    "cauchy_icdf": lambda: _close(
        D.Cauchy(0.0, 1.0).icdf(_t([0.5, 0.75])), [0.0, 1.0]),
    "cauchy_log_prob": lambda: _close(
        D.Cauchy(0.0, 1.0).log_prob(_t([0.0])), [-math.log(math.pi)]),
    "cauchy_rsample": lambda: _support(
        D.Cauchy(0.0, 1.0).rsample((32,)), np.isfinite),
    # ---- dirichlet (2 components == Beta, so the answers cross-check) --
    "dirichlet_entropy": lambda: _close(
        D.Dirichlet(_t([2.0, 3.0])).entropy(), -0.2349066),
    "dirichlet_log_prob": lambda: _close(
        D.Dirichlet(_t([2.0, 3.0])).log_prob(_t([0.4, 0.6])), 0.5469646),
    "dirichlet_mean": lambda: _close(
        D.Dirichlet(_t([2.0, 3.0])).mean, [0.4, 0.6]),
    "dirichlet_rsample": lambda: _support(
        D.Dirichlet(_t([2.0, 3.0])).rsample((8,)),
        lambda a: (a > 0.0) & (a < 1.0), shape=(8, 2)),
    "dirichlet_variance": lambda: _close(
        D.Dirichlet(_t([2.0, 3.0])).variance, [0.04, 0.04]),
    # ---- distribution base-class surface ----
    "distribution_prob": lambda: _close(
        D.Normal(0.0, 1.0).prob(_t([0.0])),
        [1.0 / math.sqrt(2 * math.pi)]),
    # ---- gamma ----
    "gamma_entropy": lambda: _close(
        D.Gamma(2.0, 3.0).entropy(), 2.0 - math.log(3.0) - 0.4227843),
    "gamma_log_prob": lambda: _close(
        D.Gamma(2.0, 3.0).log_prob(_t([1.0])), [math.log(9.0) - 3.0]),
    "gamma_mean": lambda: _close(D.Gamma(2.0, 3.0).mean, 2.0 / 3.0),
    "gamma_rsample": lambda: _support(
        D.Gamma(2.0, 3.0).rsample((32,)), lambda a: a > 0.0),
    "gamma_variance": lambda: _close(
        D.Gamma(2.0, 3.0).variance, 2.0 / 9.0),
    # ---- geometric (failures before first success, support {0,1,..}) --
    "geometric_cdf": lambda: _close(
        D.Geometric(0.3).cdf(_t([2.0])), [1.0 - 0.7**3]),
    "geometric_entropy": lambda: _close(
        D.Geometric(0.3).entropy(),
        -(0.7 * math.log(0.7) + 0.3 * math.log(0.3)) / 0.3),
    "geometric_log_prob": lambda: _close(
        D.Geometric(0.3).log_prob(_t([2.0])),
        [2 * math.log(0.7) + math.log(0.3)]),
    "geometric_mean": lambda: _close(D.Geometric(0.3).mean, 0.7 / 0.3),
    "geometric_sample": lambda: _support(
        D.Geometric(0.3).sample((64,)),
        lambda a: (a >= 0) & (a == np.floor(a))),
    "geometric_stddev": lambda: _close(
        D.Geometric(0.3).stddev, math.sqrt(0.7) / 0.3),
    "geometric_variance": lambda: _close(
        D.Geometric(0.3).variance, 0.7 / 0.09),
    # ---- gumbel ----
    "gumbel_cdf": lambda: _close(
        D.Gumbel(1.0, 2.0).cdf(_t([1.0])), [math.exp(-1.0)]),
    "gumbel_entropy": lambda: _close(
        D.Gumbel(1.0, 2.0).entropy(), math.log(2.0) + _G + 1.0),
    "gumbel_log_prob": lambda: _close(
        D.Gumbel(1.0, 2.0).log_prob(_t([1.0])), [-math.log(2.0) - 1.0]),
    "gumbel_mean": lambda: _close(D.Gumbel(1.0, 2.0).mean, 1.0 + 2.0 * _G),
    "gumbel_rsample": lambda: _support(
        D.Gumbel(1.0, 2.0).rsample((32,)), np.isfinite),
    "gumbel_stddev": lambda: _close(
        D.Gumbel(1.0, 2.0).stddev, 2.0 * math.pi / math.sqrt(6.0)),
    "gumbel_variance": lambda: _close(
        D.Gumbel(1.0, 2.0).variance, 4.0 * math.pi**2 / 6.0),
    # ---- independent (rank-1 reinterpretation sums the base laws) ----
    "independent_entropy": lambda: _close(
        D.Independent(D.Normal(_t([0.0, 0.0]), _t([1.0, 1.0])), 1)
        .entropy(), math.log(2 * math.pi * math.e)),
    "independent_log_prob": lambda: _close(
        D.Independent(D.Normal(_t([0.0, 0.0]), _t([1.0, 1.0])), 1)
        .log_prob(_t([0.0, 0.0])), -math.log(2 * math.pi)),
    # ---- laplace ----
    "laplace_cdf": lambda: _close(
        D.Laplace(0.0, 1.0).cdf(_t([0.0, 1.0])),
        [0.5, 1.0 - 0.5 * math.exp(-1.0)]),
    "laplace_entropy": lambda: _close(
        D.Laplace(0.0, 1.0).entropy(), 1.0 + math.log(2.0)),
    "laplace_icdf": lambda: _close(
        D.Laplace(0.0, 1.0).icdf(_t([0.5, 1.0 - 0.5 * math.exp(-1.0)])),
        [0.0, 1.0]),
    "laplace_log_prob": lambda: _close(
        D.Laplace(0.0, 1.0).log_prob(_t([0.0])), [-math.log(2.0)]),
    "laplace_rsample": lambda: _support(
        D.Laplace(0.0, 1.0).rsample((32,)), np.isfinite),
    "laplace_stddev": lambda: _close(
        D.Laplace(0.0, 1.0).stddev, math.sqrt(2.0)),
    "laplace_variance": lambda: _close(D.Laplace(0.0, 1.0).variance, 2.0),
    # ---- lognormal (mu=0.5, sigma=0.8) ----
    "lognormal_entropy": lambda: _close(
        D.LogNormal(0.5, 0.8).entropy(),
        0.5 + 0.5 * math.log(2 * math.pi) + math.log(0.8) + 0.5),
    "lognormal_log_prob": lambda: _close(
        D.LogNormal(0.5, 0.8).log_prob(_t([1.0])),
        [-0.25 / (2 * 0.64) - math.log(0.8) - 0.5 * math.log(2 * math.pi)]),
    "lognormal_mean": lambda: _close(
        D.LogNormal(0.5, 0.8).mean, math.exp(0.5 + 0.32)),
    "lognormal_rsample": lambda: _support(
        D.LogNormal(0.5, 0.8).rsample((32,)), lambda a: a > 0.0,
        shape=(32,)),
    "lognormal_variance": lambda: _close(
        D.LogNormal(0.5, 0.8).variance,
        (math.exp(0.64) - 1) * math.exp(2 * 0.5 + 0.64)),
    # ---- multivariate normal (Sigma=[[2,.5],[.5,1]], det=1.75) ----
    "multivariate_normal_entropy": lambda: _close(
        D.MultivariateNormal(
            _t([0.0, 0.0]),
            covariance_matrix=_t([[2.0, 0.5], [0.5, 1.0]])).entropy(),
        0.5 * (2 * (1 + math.log(2 * math.pi)) + math.log(1.75))),
    "multivariate_normal_log_prob": lambda: _close(
        D.MultivariateNormal(
            _t([0.0, 0.0]),
            covariance_matrix=_t([[2.0, 0.5], [0.5, 1.0]]))
        .log_prob(_t([0.0, 0.0])),
        -(math.log(2 * math.pi) + 0.5 * math.log(1.75))),
    "multivariate_normal_rsample": lambda: _support(
        D.MultivariateNormal(
            _t([0.0, 0.0]),
            covariance_matrix=_t([[2.0, 0.5], [0.5, 1.0]])).rsample((8,)),
        np.isfinite, shape=(8, 2)),
    "multivariate_normal_variance": lambda: _close(
        D.MultivariateNormal(
            _t([0.0, 0.0]),
            covariance_matrix=_t([[2.0, 0.5], [0.5, 1.0]])).variance,
        [2.0, 1.0]),
    # ---- normal ----
    "normal_cdf": lambda: _close(
        D.Normal(0.0, 1.0).cdf(_t([0.0, 1.0])), [0.5, 0.8413447]),
    "normal_entropy": lambda: _close(
        D.Normal(0.0, 1.0).entropy(),
        0.5 * math.log(2 * math.pi * math.e)),
    "normal_icdf": lambda: _close(
        D.Normal(0.0, 1.0).icdf(_t([0.5, 0.8413447])), [0.0, 1.0],
        tol=1e-3),
    "normal_log_prob": lambda: _close(
        D.Normal(0.0, 1.0).log_prob(_t([0.0])),
        [-0.5 * math.log(2 * math.pi)]),
    "normal_rsample": lambda: _support(
        D.Normal(0.0, 1.0).rsample((32,)), np.isfinite, shape=(32,)),
    "normal_variance": lambda: _close(D.Normal(0.0, 2.0).variance, 4.0),
    # ---- poisson ----
    "poisson_entropy": lambda: _close(
        D.Poisson(3.0).entropy(), _poisson_entropy(3.0), tol=1e-3),
    "poisson_log_prob": lambda: _close(
        D.Poisson(3.0).log_prob(_t([2.0])),
        [2 * math.log(3.0) - 3.0 - math.log(2.0)]),
    "poisson_sample": lambda: _support(
        D.Poisson(3.0).sample((64,)),
        lambda a: (a >= 0) & (a == np.floor(a))),
    # ---- student t (df=5, loc=1.5, scale=2; entropy via scipy digamma/
    # betaln: (d+1)/2*(psi((d+1)/2)-psi(d/2)) + ln(d)/2 + betaln(d/2,.5)
    # + ln(s) = 2.32064985...) ----
    "student_t_entropy": lambda: _close(
        D.StudentT(5.0, 1.5, 2.0).entropy(), 2.3206498529743413),
    "student_t_log_prob": lambda: _close(
        D.StudentT(5.0, 1.5, 2.0).log_prob(_t([1.5])),
        [math.lgamma(3.0) - math.lgamma(2.5)
         - 0.5 * math.log(5 * math.pi) - math.log(2.0)]),
    "student_t_mean": lambda: _close(D.StudentT(5.0, 1.5, 2.0).mean, 1.5),
    "student_t_rsample": lambda: _support(
        D.StudentT(5.0, 1.5, 2.0).rsample((32,)), np.isfinite,
        shape=(32,)),
    "student_t_variance": lambda: _close(
        D.StudentT(5.0, 1.5, 2.0).variance, 5.0 / 3.0 * 4.0),
    # ---- transformed distribution (exp(Normal) IS LogNormal) ----
    "transformed_distribution_log_prob": lambda: _close(
        D.TransformedDistribution(D.Normal(0.0, 1.0), D.ExpTransform())
        .log_prob(_t([1.0])), [-0.5 * math.log(2 * math.pi)]),
    # ---- uniform ----
    "uniform_cdf": lambda: _close(
        D.Uniform(2.0, 6.0).cdf(_t([3.0, 6.0])), [0.25, 1.0]),
    "uniform_entropy": lambda: _close(
        D.Uniform(2.0, 6.0).entropy(), math.log(4.0)),
    "uniform_icdf": lambda: _close(
        D.Uniform(2.0, 6.0).icdf(_t([0.25, 1.0])), [3.0, 6.0]),
    "uniform_log_prob": lambda: _close(
        D.Uniform(2.0, 6.0).log_prob(_t([3.0])), [-math.log(4.0)]),
    "uniform_mean": lambda: _close(D.Uniform(2.0, 6.0).mean, 4.0),
    "uniform_rsample": lambda: _support(
        D.Uniform(2.0, 6.0).rsample((32,)),
        lambda a: (a >= 2.0) & (a < 6.0), shape=(32,)),
    "uniform_variance": lambda: _close(
        D.Uniform(2.0, 6.0).variance, 16.0 / 12.0),
}


def test_battery_covers_the_burn_down_floor():
    # PR-18 burned >= 34 orphans (table at 61); the PR-20 satellite renamed
    # the remaining distribution ops onto module-qualified public spellings
    # (var -> variance, studentt_* -> student_t_*, mvn_* ->
    # multivariate_normal_*, LogNormal into its own module) and carries 92
    assert len(CASES) == 92, len(CASES)


@pytest.mark.parametrize("name", sorted(CASES))
def test_distribution_method_known_answer(name):
    P.seed(11)
    CASES[name]()
