"""Op battery on the OpTest harness: numpy-reference parity in eager AND
compiled modes + numeric gradient checks (SURVEY.md §4.1 protocol)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import fft as pfft

from op_test import check_grad, check_output

RNG = np.random.RandomState(7)


# ---------------- output parity: math ----------------

@pytest.mark.parametrize("op,ref,arrs", [
    (P.add, np.add, [RNG.randn(3, 4).astype(np.float32),
                     RNG.randn(3, 4).astype(np.float32)]),
    (P.multiply, np.multiply, [RNG.randn(3, 4).astype(np.float32),
                               RNG.randn(3, 4).astype(np.float32)]),
    (P.matmul, np.matmul, [RNG.randn(4, 5).astype(np.float32),
                           RNG.randn(5, 3).astype(np.float32)]),
    (P.exp, np.exp, [RNG.randn(6).astype(np.float32)]),
    (P.log, np.log, [RNG.rand(6).astype(np.float32) + 0.5]),
    (P.sqrt, np.sqrt, [RNG.rand(6).astype(np.float32) + 0.1]),
    (P.tanh, np.tanh, [RNG.randn(6).astype(np.float32)]),
    (P.abs, np.abs, [RNG.randn(6).astype(np.float32)]),
    (P.floor, np.floor, [RNG.randn(6).astype(np.float32) * 3]),
    (P.maximum, np.maximum, [RNG.randn(5).astype(np.float32),
                             RNG.randn(5).astype(np.float32)]),
])
def test_math_ops_match_numpy(op, ref, arrs):
    check_output(op, arrs, ref)


def test_reductions_match_numpy():
    x = RNG.randn(3, 5).astype(np.float32)
    check_output(lambda t: P.sum(t, axis=1), [x], lambda a: a.sum(1))
    check_output(lambda t: P.mean(t, axis=0), [x], lambda a: a.mean(0))
    check_output(lambda t: P.max(t, axis=1), [x], lambda a: a.max(1))
    check_output(lambda t: P.prod(t, axis=1), [x], lambda a: a.prod(1))
    check_output(P.logsumexp, [x],
                 lambda a: np.log(np.exp(a).sum()), rtol=1e-4)


def test_einsum_matches_numpy():
    a = RNG.randn(3, 4).astype(np.float32)
    b = RNG.randn(4, 5).astype(np.float32)
    check_output(lambda x, y: P.einsum("ij,jk->ik", x, y), [a, b],
                 lambda x, y: np.einsum("ij,jk->ik", x, y))
    c = RNG.randn(2, 3, 4).astype(np.float32)
    check_output(lambda x: P.einsum("bij->bji", x), [c],
                 lambda x: np.einsum("bij->bji", x))


def test_sort_search_ops():
    x = RNG.randn(4, 6).astype(np.float32)
    check_output(lambda t: P.sort(t, axis=1), [x], lambda a: np.sort(a, 1))
    check_output(lambda t: P.argsort(t, axis=1), [x],
                 lambda a: np.argsort(a, 1, kind="stable"))
    check_output(lambda t: P.argmax(t, axis=1), [x], lambda a: a.argmax(1))
    vals = np.sort(RNG.randn(8).astype(np.float32))
    q = RNG.randn(5).astype(np.float32)
    check_output(P.searchsorted, [vals, q], np.searchsorted)
    check_output(lambda t: P.topk(t, 3, axis=1), [x],
                 lambda a: (np.sort(a, 1)[:, ::-1][:, :3].copy(),
                            np.argsort(-a, 1, kind="stable")[:, :3]))


def test_manip_ops():
    x = RNG.randn(2, 3, 4).astype(np.float32)
    check_output(lambda t: P.transpose(t, [2, 0, 1]), [x],
                 lambda a: a.transpose(2, 0, 1))
    check_output(lambda t: P.reshape(t, [6, 4]), [x],
                 lambda a: a.reshape(6, 4))
    check_output(lambda t: P.split(t, 3, axis=1), [x],
                 lambda a: tuple(np.split(a, 3, 1)))
    check_output(lambda t: P.flip(t, axis=[1]), [x],
                 lambda a: np.flip(a, 1))
    check_output(lambda t: P.roll(t, 2, axis=2), [x],
                 lambda a: np.roll(a, 2, 2))
    check_output(lambda t: P.tile(t, [1, 2, 1]), [x],
                 lambda a: np.tile(a, (1, 2, 1)))
    pairs = [RNG.randn(2, 3).astype(np.float32) for _ in range(2)]
    check_output(lambda a, b: P.concat([a, b], axis=0), pairs,
                 lambda a, b: np.concatenate([a, b], 0))
    check_output(lambda a, b: P.stack([a, b], axis=1), pairs,
                 lambda a, b: np.stack([a, b], 1))


def test_linalg_ops():
    a = RNG.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    b = RNG.randn(4, 2).astype(np.float32)
    check_output(P.linalg.det, [spd], np.linalg.det, rtol=1e-3)
    check_output(P.linalg.inv, [spd], np.linalg.inv, rtol=1e-3)
    check_output(P.linalg.solve, [spd, b], np.linalg.solve, rtol=1e-3)
    check_output(P.linalg.cholesky, [spd], np.linalg.cholesky, rtol=1e-3)
    # svd: compare singular values (vectors are sign-ambiguous)
    check_output(lambda t: P.linalg.svd(t)[1], [a],
                 lambda m: np.linalg.svd(m)[1], rtol=1e-3)
    check_output(lambda t: P.linalg.eigvalsh(t), [spd],
                 lambda m: np.linalg.eigvalsh(m), rtol=1e-3)


def test_fft_ops():
    x = RNG.randn(8).astype(np.float32)
    check_output(pfft.rfft, [x], np.fft.rfft, rtol=1e-4, atol=1e-4)
    xc = (RNG.randn(8) + 1j * RNG.randn(8)).astype(np.complex64)
    check_output(pfft.fft, [xc], np.fft.fft, rtol=1e-4, atol=1e-4)
    check_output(pfft.ifft, [xc], np.fft.ifft, rtol=1e-4, atol=1e-4)
    x2 = RNG.randn(4, 6).astype(np.float32)
    check_output(pfft.fft2, [x2], np.fft.fft2, rtol=1e-4, atol=1e-4)
    check_output(pfft.fftshift, [x2], np.fft.fftshift)
    np.testing.assert_allclose(pfft.fftfreq(8, 0.5).numpy(),
                               np.fft.fftfreq(8, 0.5))
    # round trip
    rt = pfft.irfft(pfft.rfft(P.to_tensor(x)), n=8)
    np.testing.assert_allclose(rt.numpy(), x, atol=1e-5)


# ---------------- numeric gradient checks ----------------

def test_grad_unary_ops():
    x = RNG.rand(3, 3).astype(np.float64) + 0.5
    check_grad(P.exp, [x])
    check_grad(P.log, [x])
    check_grad(P.sqrt, [x])
    check_grad(P.tanh, [x])
    check_grad(lambda t: P.sum(t * t), [x])


def test_grad_binary_and_matmul():
    a = RNG.randn(3, 4)
    b = RNG.randn(4, 2)
    check_grad(P.matmul, [a, b], wrt=(0, 1))
    c = RNG.randn(3, 4)
    check_grad(P.multiply, [a, c], wrt=(0, 1))
    check_grad(P.divide, [a, np.abs(c) + 1.0], wrt=(0, 1))


def test_grad_reductions_and_softmax():
    x = RNG.randn(4, 5)
    import paddle_tpu.nn.functional as F
    check_grad(lambda t: P.mean(t), [x])
    check_grad(lambda t: P.max(t, axis=1), [x])
    check_grad(lambda t: F.softmax(t, axis=-1), [x])
    check_grad(lambda t: F.log_softmax(t, axis=-1), [x])


def test_grad_einsum_and_linalg():
    a = RNG.randn(3, 4)
    b = RNG.randn(4, 3)
    check_grad(lambda x, y: P.einsum("ij,jk->ik", x, y), [a, b], wrt=(0, 1))
    spd = (a @ a.T + 4 * np.eye(3)).astype(np.float64)
    check_grad(lambda t: P.linalg.det(t), [spd], rtol=8e-2)
    check_grad(lambda t: P.linalg.inv(t), [spd], rtol=8e-2)


def test_grad_fft():
    x = RNG.randn(8)
    check_grad(lambda t: P.abs(pfft.rfft(t)), [x], rtol=8e-2)


def test_fftn_all_axes_default():
    """Regression: fftn with no axes must transform ALL axes (paddle/numpy
    semantics), and axes=None must be accepted."""
    x = RNG.randn(2, 3, 4).astype(np.float32)
    np.testing.assert_allclose(pfft.fftn(P.to_tensor(x)).numpy(),
                               np.fft.fftn(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.fftn(P.to_tensor(x), axes=None).numpy(),
                               np.fft.fftn(x), rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError, match="norm"):
        pfft.fft(P.to_tensor(x[0, 0]), norm="orthonormal")
