"""Unified trace spans, flight recorder, and the metrics wire (ISSUE 15).

The contract under test:
- span()/event() record into a thread-safe bounded ring with parent
  linkage and correlation attrs; disabled tracing is a no-op (the ring
  stays empty — the near-zero-cost law's observable half; the measured
  half is bench_step/bench_serving's trace_overhead gate);
- A GATEWAY-DRIVEN serving run exports a Chrome-trace JSON in which ONE
  request id links the gateway request span to the engine's prefill /
  decode-step / verify-step spans and the scheduler's join/evict events
  (the acceptance timeline);
- a chaos delay at an armed fault site yields a typed deadline error
  whose flight-recorder incident timeline is non-empty and ENDS at the
  faulted site;
- the metrics registry (Counter/Gauge/Histogram + pull collectors)
  renders deterministic Prometheus text; the gateway's PTSG/1 METRICS
  verb round-trips the engine's counters byte-for-byte vs the in-process
  snapshot, and answers the typed 503 while draining;
- every profiler summary renders cleanly in a fresh process whose
  subsystem was never imported (the shared no-data idiom), without
  importing it.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed import chaos
from paddle_tpu.observability import metrics, trace
from paddle_tpu.utils.deadline import DeadlineExceeded, RequestTimeout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(seed=7, vocab=64, hidden=32, layers=2, heads=4, seq=64):
    P.seed(seed)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(vocab=vocab, hidden=hidden, layers=layers,
                           heads=heads, inter=hidden * 2, seq=seq)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def model():
    # ONE model for every engine test in this file: engines over the same
    # weights share step lowerings (the model-stash idiom), so the suite
    # pays the prefill/decode/verify compiles once
    return _model()


def _prompt(n, seed=0, vocab=64):
    return np.random.RandomState(seed).randint(1, vocab, (n,))


@pytest.fixture
def tracing():
    """Enable tracing around one test; restore the disabled default and
    drain ring + incidents so tests stay order-independent."""
    trace.trace_clear()
    trace.clear_incidents()
    trace.enable(True)
    yield
    trace.enable(False)
    trace.trace_clear()
    trace.clear_incidents()


# ---------------------------------------------------------------------------
# the trace ring
# ---------------------------------------------------------------------------

def test_span_nesting_ring_and_export(tracing, tmp_path):
    with trace.span("outer", rid=7) as sp:
        sp.set(late="attr")
        with trace.span("inner", rid=7):
            trace.event("tick", rid=7)
    recs = trace.trace_records()
    assert [r["name"] for r in recs] == ["tick", "inner", "outer"]
    outer = recs[2]
    inner = recs[1]
    assert inner["parent"] == outer["id"]       # nesting -> parent linkage
    assert recs[0]["parent"] == inner["id"]     # events parent too
    assert outer["args"] == {"rid": 7, "late": "attr"}
    assert inner["dur"] >= 0 and recs[0]["dur"] is None
    path = trace.export_trace(str(tmp_path / "t.json"))
    evs = json.load(open(path))["traceEvents"]
    assert [e["name"] for e in evs] == ["tick", "inner", "outer"]
    assert evs[2]["ph"] == "X" and evs[2]["dur"] >= 0
    assert evs[0]["ph"] == "i"
    assert evs[1]["args"]["parent_id"] == evs[2]["args"]["span_id"]


def test_ring_bound_and_dropped_counter(tracing):
    trace.set_ring_size(4)
    try:
        for i in range(10):
            trace.event(f"e{i}")
        recs = trace.trace_records()
        assert len(recs) == 4
        assert [r["name"] for r in recs] == ["e6", "e7", "e8", "e9"]
        assert trace.trace_info()["dropped"] == 6
    finally:
        trace.set_ring_size(4096)


def test_disabled_tracing_is_a_noop():
    trace.enable(False)
    trace.trace_clear()
    with trace.span("x", rid=1) as sp:
        assert sp.set(a=1) is sp    # the null span keeps the API
        trace.event("y")
    assert trace.trace_records() == []
    assert trace.trace_info()["enabled"] is False


def test_trace_summary_renders(tracing):
    import paddle_tpu.profiler as prof
    with trace.span("site.a"):
        trace.event("site.b")
    out = prof.trace_summary()
    assert "site.a" in out and "records=" in out


# ---------------------------------------------------------------------------
# the acceptance timeline: one rid across gateway -> engine -> verify
# ---------------------------------------------------------------------------

def test_gateway_run_exports_rid_linked_chrome_trace(tracing, tmp_path, model):
    """A gateway-driven serving run on a SPECULATIVE engine: the exported
    Chrome trace holds one request id linking gateway.request ->
    engine.submit/prefill -> engine.decode_step -> engine.verify_step ->
    scheduler join/evict — the cross-layer correlation the ISSUE names."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.serving.gateway import (GatewayClient,
                                                      ServingGateway)
    eng = ServingEngine(model, max_batch=4, max_seq_len=64, spec_k=2,
                        drafter="ngram")
    gw = ServingGateway(eng)
    try:
        cli = GatewayClient("127.0.0.1", gw.port)
        out = cli.generate(_prompt(8, seed=3), max_new_tokens=8)
        assert out.size == 16
        cli.close()
    finally:
        gw.stop(drain=True, timeout=10.0)
    path = trace.export_trace(str(tmp_path / "serve.json"))
    evs = json.load(open(path))["traceEvents"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    # the wire-side span carries the engine's request id
    gw_spans = by_name["gateway.request"]
    assert len(gw_spans) == 1 and gw_spans[0]["ph"] == "X"
    rid = gw_spans[0]["args"]["rid"]
    # ... and that SAME id links every engine-side span of the request
    assert any(e["args"].get("rid") == rid
               for e in by_name["engine.submit"])
    assert any(e["args"].get("rid") == rid
               for e in by_name["engine.prefill"])
    assert any(rid in e["args"].get("rids", ())
               for e in by_name["engine.decode_step"])
    assert any(rid in e["args"].get("rids", ())
               for e in by_name["engine.verify_step"])
    assert any(e["args"].get("rid") == rid
               for e in by_name["scheduler.join"])
    assert any(e["args"].get("rid") == rid
               for e in by_name["scheduler.evict"])
    # the verify span nests inside its decode step
    verify = by_name["engine.verify_step"][0]
    decode_ids = {e["args"]["span_id"] for e in by_name["engine.decode_step"]}
    assert verify["args"]["parent_id"] in decode_ids
    # gateway read spans exist on the wire side of the same timeline
    assert by_name["gateway.read"]


def test_engine_trace_off_records_nothing(model):
    """The PT_TRACE=0 default: a full engine run leaves the ring empty
    (no hidden recording on the serving hot path)."""
    from paddle_tpu.inference.serving import ServingEngine
    trace.enable(False)
    trace.trace_clear()
    eng = ServingEngine(model, max_batch=2, max_seq_len=64)
    eng.generate([_prompt(5, seed=1)], max_new_tokens=4)
    assert trace.trace_records() == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_typed_deadline_captures_incident(tracing):
    with trace.span("some.site", step=3):
        pass
    try:
        raise DeadlineExceeded("unit-test wait", 2.5)
    except DeadlineExceeded:
        pass
    inc = trace.last_incident()
    assert inc is not None
    assert inc["error"] == "DeadlineExceeded"
    assert inc["what"] == "unit-test wait" and inc["timeout"] == 2.5
    assert inc["spans"][-1]["name"] == "some.site"


def test_chaos_delay_incident_ends_at_faulted_site(tracing, monkeypatch, model):
    """The postmortem law: a delay chaos case at gateway.read stalls the
    exchange into the client's typed RequestTimeout, and last_incident()
    holds a non-empty timeline ENDING at the faulted site (the chaos
    event records before the stall, inside the read span)."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.serving.gateway import (GatewayClient,
                                                      ServingGateway)
    eng = ServingEngine(model, max_batch=2, max_seq_len=64)
    gw = ServingGateway(eng)
    try:
        cli = GatewayClient("127.0.0.1", gw.port)
        chaos.reset_hits()
        monkeypatch.setenv("PT_FAULTPOINT", "gateway.read")
        monkeypatch.setenv("PT_FAULTPOINT_MODE", "delay:1.5")
        monkeypatch.setenv("PT_FAULTPOINT_HITS", "inf")
        trace.clear_incidents()
        with pytest.raises(RequestTimeout):
            cli.generate(_prompt(4, seed=2), max_new_tokens=4, timeout=0.4)
        inc = trace.last_incident()
        assert inc is not None and inc["error"] == "RequestTimeout"
        assert inc["spans"], "incident carries no timeline"
        last = inc["spans"][-1]
        assert last["name"] == "gateway.read"      # ends at the faulted site
        assert last["cat"] == "chaos.fault"
        assert last["args"]["mode"].startswith("delay")
        cli.close()
    finally:
        monkeypatch.delenv("PT_FAULTPOINT")
        chaos.reset_hits()
        gw.stop(drain=False)


# ---------------------------------------------------------------------------
# metrics registry + the wire
# ---------------------------------------------------------------------------

def test_metric_instruments_and_render():
    c = metrics.Counter("pt_unittest_total", "a test counter")
    c.inc()
    c.inc(4, kind="x")
    g = metrics.Gauge("pt_unittest_gauge", "a gauge")
    g.set(2.5)
    g.inc(0.5)
    h = metrics.Histogram("pt_unittest_seconds", "a histogram",
                          buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(9.0)
    with pytest.raises(ValueError):
        c.inc(-1)
    snap = metrics.metrics_snapshot()
    assert snap["pt_unittest_total"]["values"]["kind=x"] == 4.0
    assert snap["pt_unittest_gauge"]["values"][""] == 3.0
    text = metrics.render_prometheus()
    assert "# TYPE pt_unittest_total counter" in text
    assert 'pt_unittest_total{kind="x"} 4' in text
    assert 'pt_unittest_seconds_bucket{le="0.1"} 1' in text
    assert 'pt_unittest_seconds_bucket{le="+Inf"} 3' in text
    assert "pt_unittest_seconds_count 3" in text
    # deterministic: two renders over unchanged instruments are identical
    assert metrics.render_prometheus() == text


def test_registry_rejects_kind_conflict_and_custom_collector():
    metrics.Counter("pt_unittest_conflict", "first")
    with pytest.raises(ValueError):
        metrics.Gauge("pt_unittest_conflict", "second")
    metrics.register_collector(
        "unittest", lambda: [("pt_unittest_pull", "gauge", "pulled", {}, 7)])
    try:
        assert "pt_unittest_pull 7" in metrics.render_prometheus()
    finally:
        metrics.unregister_collector("unittest")


def test_gateway_metrics_verb_roundtrips_engine_counters(model):
    """The wire scrape equals the in-process snapshot byte-for-byte on
    the engine's counter lines, taken over a quiet engine — the gateway
    adds transport, never resampling."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.serving.gateway import (GatewayClient,
                                                      ServingGateway)
    eng = ServingEngine(model, max_batch=2, max_seq_len=64)
    gw = ServingGateway(eng)
    try:
        cli = GatewayClient("127.0.0.1", gw.port)
        cli.generate(_prompt(5, seed=1), max_new_tokens=6)
        cli.generate(_prompt(9, seed=2), max_new_tokens=4)
        wire = cli.metrics()
        local = metrics.render_prometheus()

        def engine_lines(text):
            return [ln for ln in text.splitlines()
                    if ln.startswith("pt_serving_")]

        assert engine_lines(wire) == engine_lines(local)
        assert any(ln.startswith("pt_serving_tokens_generated")
                   for ln in engine_lines(wire))
        # the scrape itself is visible in the gateway funnel
        assert gw.info()["metrics_scrapes"] == 1
        cli.close()
    finally:
        gw.stop(drain=True, timeout=10.0)


def test_gateway_metrics_scrape_while_draining_is_typed_503(model):
    """Drain-awareness: a scraper hitting a draining gateway gets the
    typed GatewayDraining (503 frame), never a healthy-looking sample."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.inference.serving.gateway import (GatewayClient,
                                                      GatewayDraining,
                                                      ServingGateway)
    eng = ServingEngine(model, max_batch=2, max_seq_len=64)
    gw = ServingGateway(eng)
    cli = None
    try:
        cli = GatewayClient("127.0.0.1", gw.port)
        assert "pt_gateway_requests" in cli.metrics()  # live scrape works
        # park one slow request so drain() has something in flight, then
        # drain in the background and scrape on the EXISTING connection
        req = eng.submit(_prompt(4, seed=5), max_new_tokens=48)
        stopper = threading.Thread(target=gw.stop,
                                   kwargs={"drain": True, "timeout": 15.0},
                                   daemon=True)
        stopper.start()
        deadline = time.monotonic() + 5.0
        while not gw.info()["draining"] and time.monotonic() < deadline:
            time.sleep(0.005)
        assert gw.info()["draining"]
        with pytest.raises(GatewayDraining):
            cli.metrics()
        req.wait(timeout=10.0)
        stopper.join(timeout=15.0)
        assert not stopper.is_alive()
    finally:
        if cli is not None:
            cli.close()
        gw.stop(drain=False)


# ---------------------------------------------------------------------------
# profiler empty-state sweep (fresh process, subsystems never imported)
# ---------------------------------------------------------------------------

SUMMARIES = ("op_cache_summary", "step_capture_summary", "lint_summary",
             "serving_summary", "gateway_summary", "comm_summary",
             "reshard_summary", "supervisor_summary", "trace_summary")

_SWEEP = """
import sys
import paddle_tpu.profiler as prof
for name in {names!r}:
    out = getattr(prof, name)()
    assert isinstance(out, str) and out, name
    print(name, "::", out.splitlines()[0])
# rendering a summary must never import its subsystem
assert "paddle_tpu.inference.serving" not in sys.modules
assert "paddle_tpu.inference.serving.gateway" not in sys.modules
"""


def test_every_summary_renders_in_fresh_process():
    """All nine profiler summaries render in a process that never
    exercised their subsystems — empty-state guards + the one shared
    no-data idiom, and the render itself imports nothing heavy."""
    r = subprocess.run(
        [sys.executable, "-c", _SWEEP.format(names=SUMMARIES)],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = dict(ln.split(" :: ", 1) for ln in r.stdout.splitlines())
    assert set(lines) == set(SUMMARIES)
    # the unloaded subsystems all use the ONE shared idiom
    assert lines["serving_summary"] == "serving: no data (subsystem not loaded)"
    assert lines["gateway_summary"] == "gateway: no data (subsystem not loaded)"
