"""Whole-step capture tier (jit/capture.py + jit/passes/) guard tests.

The contract under test (README "Whole-step capture"):
- a repeated same-signature step lowers EXACTLY once (counters prove it);
  a new aval signature lowers exactly once more;
- every bailout condition (host sync in the step, global-RNG draw,
  unhashable statics) silently falls back to the eager tier, where the
  per-op compiled cache serves individual ops — same values, no error;
- the pass pipeline is value-preserving and actually fires (fusion
  inlines jitted call regions, CSE folds duplicates, DVE drops dead
  values, donation inference marks update-in-place params);
- TrainStep routed through capture is bit-identical to the plain-jit
  path, INCLUDING the in-jit grad-skip/loss-scale semantics;
- the decode-offset threading (models/llama.py) keeps per-token decode
  ops on ONE per-op cache entry across token positions.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import capture, capture_step
from paddle_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _fresh():
    dispatch.cache_clear()
    capture.capture_clear()
    capture.set_step_capture_enabled(True)
    yield
    dispatch.cache_clear()
    capture.capture_clear()
    capture.set_step_capture_enabled(True)


def _mk(shape, sg=True):
    return P.to_tensor(np.random.randn(*shape).astype(np.float32),
                       stop_gradient=sg)


# ---------------------------------------------------------------------------
# recompile-count guards
# ---------------------------------------------------------------------------

def test_exactly_one_lowering_per_signature():
    @capture_step
    def step(x):
        return P.tanh(x) * 2.0

    x = _mk((4, 8))
    outs = [step(x) for _ in range(6)]
    info = step.cache_info()
    assert info["lowerings"] == 1, info
    assert info["hits"] == 5, info
    assert info["bailouts"] == 0 and info["fallback_calls"] == 0, info
    ref = np.tanh(x.numpy()) * 2.0
    for o in outs:
        np.testing.assert_allclose(o.numpy(), ref, rtol=1e-6)


def test_new_aval_signature_lowers_once_more():
    @capture_step
    def step(x):
        return P.exp(x)

    a, b = _mk((4, 4)), _mk((2, 4))        # distinct shapes
    c = P.to_tensor(np.random.randn(4, 4))  # distinct dtype (f64 input)
    for t in (a, a, b, b, c, c):
        step(t)
    info = step.cache_info()
    assert info["lowerings"] == 3, info
    assert info["hits"] == 3, info


def test_full_train_step_capture_parity_with_eager():
    """fwd + tape backward + SGD update, captured vs pure eager."""
    P.seed(11)
    lin1 = P.nn.Linear(8, 16)
    lin2 = P.nn.Linear(16, 2)
    params = list(lin1.parameters()) + list(lin2.parameters())

    def step(param_vals, x, y):
        saved = [p._value for p in params]
        try:
            for p, v in zip(params, param_vals):
                p._value = v._value if isinstance(v, Tensor) else v
            loss = F.mse_loss(lin2(F.relu(lin1(x))), y)
            loss.backward()
            with P.no_grad():
                new = [p - 0.1 * p.grad for p in params]
            return loss, new
        finally:
            for p, v in zip(params, saved):
                p._value = v
                p.grad = None

    cap = capture_step(step)
    x, y = _mk((8, 8)), _mk((8, 2))
    base = [np.asarray(p._value) for p in params]

    def run(fn, n=3):
        vals = [jnp.asarray(a) for a in base]
        for _ in range(n):
            loss, new = fn(vals, x, y)
            vals = [t._value for t in new]
        return float(loss.numpy()), [np.asarray(v) for v in vals]

    l_eager, p_eager = run(step)
    l_cap, p_cap = run(cap)
    assert cap.cache_info()["lowerings"] == 1
    assert cap.cache_info()["hits"] == 2
    assert abs(l_eager - l_cap) < 1e-5
    for a, b in zip(p_eager, p_cap):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# bailouts -> per-op-cache fallback tier
# ---------------------------------------------------------------------------

def test_host_sync_bails_out_and_per_op_cache_serves():
    @capture_step
    def step(x):
        s = x.sum()
        if float(s.numpy()) > -1e30:   # host sync: uncapturable
            return P.tanh(x)
        return x

    x = _mk((4, 4))
    outs = [step(x) for _ in range(4)]
    info = step.cache_info()
    assert info["bailouts"] == 1, info          # capture abandoned once...
    assert info["fallback_calls"] == 4, info    # ...every call ran eager
    assert info["lowerings"] == 0 and info["hits"] == 0, info
    for o in outs:
        np.testing.assert_allclose(o.numpy(), np.tanh(x.numpy()), rtol=1e-6)
    # the fallback tier is the PR-3 per-op cache, and it compiled tanh
    s = dispatch.cache_info()["per_op"]["tanh"]
    assert s["hits"] >= 1 and s["retraces"] == 1, s
    assert capture.capture_info()["last_bailout"], capture.capture_info()


def test_global_rng_draw_bails_out():
    @capture_step
    def step(x):
        return x + P.rand([4, 4])   # global-RNG draw would be baked

    x = _mk((4, 4))
    a, b = step(x), step(x)
    assert step.cache_info()["bailouts"] == 1
    # eager fallback keeps drawing fresh randomness (no baked keys)
    assert not np.allclose(a.numpy(), b.numpy())


def test_to_static_call_inside_captured_step_bails_via_rng_guard():
    """A to_static function draws a per-call host RNG key; replaying a
    captured step would bake it (wrong for random inner fns), so the RNG
    guard conservatively bails and the eager tier serves — value-correct
    either way."""
    lin = P.nn.Linear(4, 4)

    @P.jit.to_static
    def inner(x):
        return F.relu(lin(x))

    @capture_step
    def step(x):
        return inner(x) + 1.0

    x = _mk((2, 4))
    o1, o2 = step(x), step(x)
    np.testing.assert_allclose(o2.numpy(), o1.numpy())
    ref = np.maximum(x.numpy() @ np.asarray(lin.weight._value)
                     + np.asarray(lin.bias._value), 0) + 1.0
    np.testing.assert_allclose(o1.numpy(), ref, rtol=1e-5, atol=1e-6)
    info = step.cache_info()
    assert info["bailouts"] == 1 and info["fallback_calls"] == 2, info


def test_failing_step_raises_same_error_as_eager():
    @capture_step
    def step(x):
        return x @ x   # invalid for non-square inputs

    bad = _mk((2, 4))
    with pytest.raises(TypeError):
        step(bad)


def test_static_mode_and_nested_trace_stay_transparent():
    @capture_step
    def step(x):
        return P.tanh(x)

    P.enable_static()
    try:
        v = P.static.data("capx", [2, 3], "float32")
        out = step(v)
        assert isinstance(out, P.static.Variable)
    finally:
        P.disable_static()
    assert step.cache_info()["lowerings"] == 0

    # under an enclosing jax trace the wrapper inlines (no keying on tracers)
    def traced(a):
        return step(Tensor(a))._value

    x = np.random.randn(2, 3).astype(np.float32)
    out = jax.jit(traced)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.tanh(x), rtol=1e-6)
    assert step.cache_info()["lowerings"] == 0


# ---------------------------------------------------------------------------
# pass pipeline
# ---------------------------------------------------------------------------

def test_passes_fuse_cse_dve_and_preserve_values():
    helper = jax.jit(lambda v: jnp.tanh(v) + 1.0)

    @capture_step
    def step(x):
        a = P.sin(x) * 2.0
        b = P.sin(x) * 2.0                    # CSE target
        dead = P.cos(x) @ P.cos(x)            # DVE target  # noqa: F841
        return dispatch.apply(helper, a + b, op_name="helper")  # fusion

    x = _mk((4, 4))
    out = step(x)
    np.testing.assert_allclose(
        out.numpy(), np.tanh(np.sin(x.numpy()) * 4.0) + 1.0,
        rtol=1e-5, atol=1e-6)
    prog = step.programs()[0]
    rep = prog.pass_report
    assert rep.inlined_calls >= 1, rep.as_dict()
    assert rep.cse_folded >= 1, rep.as_dict()
    assert rep.dve_removed >= 2, rep.as_dict()   # dead matmul + dead cos
    assert rep.eqns_after < rep.eqns_before
    # op-level record reuses the static-world Program representation
    assert prog.op_counts().get("helper") == 1
    assert prog.as_program().ops, prog.describe()


def test_donation_inference_flat_positions():
    from paddle_tpu.jit.passes.donation import infer_donation
    SA = jax.core.ShapedArray
    ins = [SA((64, 64), jnp.float32), SA((32,), jnp.float32),
           SA((), jnp.float32), SA((64, 64), jnp.float32)]
    outs = [SA((), jnp.float32), SA((64, 64), jnp.float32),
            SA((64, 64), jnp.float32)]
    # both big inputs alias the two matching outputs; the scalar and the
    # small vector are never donated
    assert infer_donation(ins, outs) == (0, 3)
    assert infer_donation(ins, outs, reserved=(0,)) == (3,)
    assert infer_donation(ins, outs[:2]) == (0,)   # multiset budget


def test_donate_auto_aliases_param_buffers():
    @capture_step(donate="auto")
    def upd(w, g):
        return w - 0.1 * g

    w = _mk((64, 64))
    g = _mk((64, 64))
    w2 = upd(w, g)
    assert upd.programs()[0].donate == (0,)   # w aliased, g kept
    w3 = upd(w2, g)                           # threading works post-donation
    assert w3.shape == [64, 64]
    with pytest.raises(RuntimeError):
        np.asarray(w._value)                  # the donated buffer is gone


def test_captured_ops_counted_not_bypassed():
    @capture_step
    def step(x):
        return P.tanh(P.exp(x))

    x = _mk((3, 3))
    step(x)
    info = dispatch.cache_info()
    assert info["captured"] >= 2, info       # tanh + exp absorbed by capture
    per = info["per_op"]["tanh"]
    assert per["captured"] >= 1 and per["bypasses"] == 0, per


# ---------------------------------------------------------------------------
# TrainStep integration (grad-skip / loss-scale semantics preserved)
# ---------------------------------------------------------------------------

def _train_run(steps=4, inject_inf_at=2):
    from paddle_tpu.parallel.trainer import compile_train_step
    P.seed(5)
    np.random.seed(5)
    m = P.nn.Sequential(P.nn.Linear(8, 16), P.nn.ReLU(), P.nn.Linear(16, 2))
    opt = P.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    scaler = P.amp.GradScaler(init_loss_scaling=2.0 ** 8)

    def loss_fn(model, batch):
        x, y = batch
        return F.mse_loss(model(x), y)

    step = compile_train_step(m, loss_fn, opt, scaler=scaler)
    rng = np.random.RandomState(3)
    losses = []
    for i in range(steps):
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 2).astype(np.float32)
        if i == inject_inf_at:
            x = x.copy()
            x[0, 0] = np.inf
        losses.append(float(step((P.to_tensor(x), P.to_tensor(y))).numpy()))
    return (losses, step.skipped_steps, step.loss_scale,
            [p.numpy().copy() for p in step._params], step)


def test_trainstep_captured_matches_plain_jit_incl_grad_skip():
    l1, sk1, sc1, p1, step = _train_run()
    assert step.captured_program is not None     # capture tier engaged
    assert step.captured_program.pass_report.inlined_calls >= 1
    capture.set_step_capture_enabled(False)
    l0, sk0, sc0, p0, step0 = _train_run()
    assert step0.captured_program is None        # plain jax.jit path
    assert sk1 == sk0 == 1                       # the inf step was skipped
    assert sc1 == sc0                            # same dynamic loss scale
    for a, b in zip(l1, l0):
        assert (np.isnan(a) and np.isnan(b)) or abs(a - b) < 1e-5
    for a, b in zip(p1, p0):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# decode-offset threading (models/llama.py) + decode-step capture
# ---------------------------------------------------------------------------

def _tiny_llama():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    P.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(vocab=32, hidden=16, layers=1,
                                             heads=2, seq=32))


def test_decode_ops_share_one_cache_entry_across_offsets():
    """The rope/kv/mask ops take the offset as a traced i32 arg now, so
    eager decode at different token positions hits ONE per-op entry."""
    model = _tiny_llama()
    model.eval()
    caches = model.init_kv_caches(1, 8)
    ids = P.to_tensor(np.array([[3]], np.int64))
    with P.no_grad():
        for off in (0, 1, 2, 3):
            model.forward(ids, caches=caches,
                          position_offset=jnp.asarray(off, jnp.int32))
    s = dispatch.cache_info()["per_op"]["rope"]
    assert s["misses"] == 1, s      # one aval signature for every offset
    assert s["hits"] >= 1, s
    assert s["bypasses"] == 0, s    # no closure-capture bypasses left
    s = dispatch.cache_info()["per_op"]["kv_cache_upd"]
    assert s["misses"] == 1 and s["bypasses"] == 0, s


def test_generate_uses_capture_tier_and_matches_no_cache_oracle():
    model = _tiny_llama()
    model.eval()
    ids = P.to_tensor(np.array([[1, 5, 2]], np.int64))
    out_cached = model.generate(ids, max_new_tokens=4, use_cache=True)
    out_oracle = model.generate(ids, max_new_tokens=4, use_cache=False)
    np.testing.assert_array_equal(out_cached.numpy(), out_oracle.numpy())
    info = capture.capture_info()
    # prefill + decode signatures, decode executable re-served per token
    assert info["lowerings"] == 2, info
    assert info["hits"] >= 2, info
    # the step wrapper persists on the model: a second generate() with the
    # same shapes re-serves both executables instead of re-lowering
    out2 = model.generate(ids, max_new_tokens=4, use_cache=True)
    np.testing.assert_array_equal(out2.numpy(), out_cached.numpy())
    assert capture.capture_info()["lowerings"] == 2, capture.capture_info()


def test_trainstep_handles_changed_batch_shape():
    """drop_last=False epochs end with a smaller batch: the capture tier
    must route the new signature to the plain-jit fallback, not crash."""
    from paddle_tpu.parallel.trainer import compile_train_step
    P.seed(2)
    m = P.nn.Sequential(P.nn.Linear(8, 16), P.nn.ReLU(), P.nn.Linear(16, 4))
    opt = P.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    step = compile_train_step(
        m, lambda mm, b: F.mse_loss(mm(b[0]), b[1]), opt)
    full = (_mk((4, 8)), _mk((4, 4)))
    part = (_mk((2, 8)), _mk((2, 4)))
    l1 = float(step(full).numpy())
    l2 = float(step(part).numpy())   # smaller final batch
    l3 = float(step(full).numpy())   # captured executable still serves
    assert all(np.isfinite(v) for v in (l1, l2, l3))
    assert step.captured_program is not None


def test_to_static_routes_through_pass_pipeline():
    lin = P.nn.Linear(4, 4)

    @P.jit.to_static
    def fn(x):
        return F.relu(lin(x)) + F.relu(lin(x))

    x = _mk((2, 4))
    out = fn(x)
    ref = 2 * np.maximum(
        x.numpy() @ np.asarray(lin.weight._value)
        + np.asarray(lin.bias._value), 0.0)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
    assert capture.capture_info()["lowerings"] == 1


def test_profiler_step_capture_summary():
    @capture_step
    def step(x):
        return P.tanh(x)

    x = _mk((2, 2))
    step(x)
    step(x)
    from paddle_tpu.profiler import step_capture_summary
    txt = step_capture_summary()
    assert "lowerings=1" in txt and "hits=1" in txt, txt
