"""Launcher + distributed checkpoint tests.

Launcher tests mirror the reference's TestDistBase pattern (SURVEY.md §4:
multi-process on one host, env-driven ranks); checkpoint tests cover
reshard-on-load across mesh changes (converter/dist_saver parity).
"""
import os

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.parallel import mesh as mesh_mod


@pytest.fixture(autouse=True)
def clean_mesh():
    yield
    mesh_mod.set_mesh(None)


def test_launch_sets_rank_env(tmp_path):
    from dist_registry import run_dist

    _, seen, logs = run_dist("launch_env", tmp_path)
    for i in range(3):
        assert i in seen, f"rank {i} produced no result\n{logs}"
        assert seen[i]["PADDLE_TRAINER_ID"] == str(i)
        assert seen[i]["PADDLE_TRAINERS_NUM"] == "3"
        assert seen[i]["WORLD_SIZE"] == "3"
        assert seen[i]["MASTER_PORT"] == seen[0]["MASTER_PORT"]


def test_launch_restarts_failed_worker(tmp_path):
    from dist_registry import run_dist

    r, _, _ = run_dist("launch_flaky", tmp_path)
    assert "restart 1/2" in r.stderr
    assert (tmp_path / "attempt").read_text() == "2"


def test_launch_gives_up_after_max_restart(tmp_path):
    from dist_registry import run_dist

    r, _, _ = run_dist("launch_exit3", tmp_path)  # registry expects rc=3
    assert "failed permanently" in r.stderr


def test_checkpoint_reshard_on_load(tmp_path):
    """Save under dp=8; load under dp=2 x mp=4 with mp-sharded params."""
    from paddle_tpu.distributed import Shard, Replicate, ProcessMesh

    mesh_mod.init_mesh({"dp": 8})
    model = nn.Linear(16, 32)
    w0 = model.weight.numpy().copy()
    b0 = model.bias.numpy().copy()
    # shard weight over dp for the save
    m1 = ProcessMesh(list(range(8)), dim_names=["dp"])
    dist.shard_tensor(model.weight, m1, [Shard(0)])
    dist.save_state_dict(model.state_dict(), str(tmp_path / "ckpt"))

    # new world: 2x4 mesh, weight sharded over mp on dim 1
    mesh_mod.init_mesh({"dp": 2, "mp": 4})
    model2 = nn.Linear(16, 32)
    m2 = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
    dist.shard_tensor(model2.weight, m2, [Replicate(), Shard(1)])
    sd = model2.state_dict()
    dist.load_state_dict(sd, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(model2.weight.numpy(), w0)
    np.testing.assert_allclose(model2.bias.numpy(), b0)
    # the loaded weight keeps the NEW sharding
    assert "mp" in str(model2.weight._value.sharding.spec)


def test_checkpoint_async_save(tmp_path):
    from paddle_tpu.distributed import checkpoint as ckpt

    mesh_mod.init_mesh({"dp": 8})
    model = nn.Linear(8, 8)
    w0 = model.weight.numpy().copy()
    ckpt.save_state_dict(model.state_dict(), str(tmp_path / "c"), async_save=True)
    ckpt.wait()
    assert (tmp_path / "c" / "metadata.json").exists()
    model2 = nn.Linear(8, 8)
    sd = model2.state_dict()
    ckpt.load_state_dict(sd, str(tmp_path / "c"))
    np.testing.assert_allclose(model2.weight.numpy(), w0)


def test_checkpoint_async_save_failure_reraised_exactly_once(tmp_path,
                                                             monkeypatch):
    """A background-writer exception must surface in wait() — once — and
    leave the module ready for the next save (pending slot + error cleared,
    no metadata.json announcing the failed checkpoint)."""
    from paddle_tpu.distributed import checkpoint as ckpt

    model = nn.Linear(4, 4)

    def boom(*a, **k):
        raise OSError("disk on fire")

    monkeypatch.setattr(ckpt, "_atomic_write", boom)
    ckpt.save_state_dict(model.state_dict(), str(tmp_path / "c"),
                         async_save=True)
    with pytest.raises(RuntimeError, match="async checkpoint save") as ei:
        ckpt.wait()
    assert isinstance(ei.value.__cause__, OSError)
    assert ckpt._writer.idle()
    ckpt.wait()      # second wait: no re-raise, error consumed
    assert not (tmp_path / "c" / "metadata.json").exists()

    # the module recovered: the next async save succeeds end to end
    monkeypatch.undo()
    ckpt.save_state_dict(model.state_dict(), str(tmp_path / "c"),
                         async_save=True)
    ckpt.wait()
    assert (tmp_path / "c" / "metadata.json").exists()


def test_checkpoint_optimizer_state(tmp_path):
    """Nested optimizer state dicts round-trip (list/dict trees)."""
    model = nn.Linear(4, 4)
    opt = P.optimizer.AdamW(learning_rate=0.01, parameters=model.parameters())
    x = P.randn([8, 4])
    loss = (model(x) ** 2).sum()
    loss.backward()
    opt.step()
    sd = opt.state_dict()
    dist.save_state_dict(sd, str(tmp_path / "opt"))

    opt2 = P.optimizer.AdamW(learning_rate=0.01, parameters=model.parameters())
    # populate the same structure but with DIFFERENT values (two extra steps)
    for _ in range(3):
        loss = (model(x) ** 2).sum()
        loss.backward()
        opt2.step()
    sd2 = opt2.state_dict()
    flat_before = {k: np.array(v.numpy() if hasattr(v, "numpy") else v, copy=True)
                   for k, v in _walk_items(sd2)}
    dist.load_state_dict(sd2, str(tmp_path / "opt"))

    flat1 = {k: v for k, v in _walk_items(sd)}
    flat2 = {k: v for k, v in _walk_items(sd2)}
    assert set(flat1) == set(flat2)
    changed = 0
    for k in flat1:
        a = np.asarray(flat2[k].numpy() if hasattr(flat2[k], "numpy") else flat2[k])
        b = np.asarray(flat1[k].numpy() if hasattr(flat1[k], "numpy") else flat1[k])
        np.testing.assert_allclose(a, b, rtol=1e-6)
        if not np.allclose(a, flat_before[k]):
            changed += 1
    assert changed > 0, "load_state_dict restored nothing (vacuous round-trip)"


def _walk_items(tree):
    from paddle_tpu.distributed.checkpoint import _walk
    return list(_walk(tree))


def test_reshard_checkpoint_tool(tmp_path):
    mesh_mod.init_mesh({"dp": 8})
    from paddle_tpu.distributed import ProcessMesh, Shard
    from paddle_tpu.distributed.checkpoint import reshard_checkpoint

    model = nn.Linear(8, 16)
    w0 = model.weight.numpy().copy()
    m = ProcessMesh(list(range(8)), dim_names=["dp"])
    dist.shard_tensor(model.weight, m, [Shard(1)])
    dist.save_state_dict(model.state_dict(), str(tmp_path / "src"))
    reshard_checkpoint(str(tmp_path / "src"), str(tmp_path / "dst"))

    mesh_mod.set_mesh(None)
    model2 = nn.Linear(8, 16)
    sd = model2.state_dict()
    dist.load_state_dict(sd, str(tmp_path / "dst"))
    np.testing.assert_allclose(model2.weight.numpy(), w0)


def test_checkpoint_incomplete_raises(tmp_path):
    """A lost shard file must raise, not silently zero-fill."""
    from paddle_tpu.distributed import ProcessMesh, Shard

    mesh_mod.init_mesh({"dp": 8})
    model = nn.Linear(16, 8)
    m = ProcessMesh(list(range(8)), dim_names=["dp"])
    dist.shard_tensor(model.weight, m, [Shard(0)])
    dist.save_state_dict(model.state_dict(), str(tmp_path / "ckpt"))

    # corrupt: drop one sharded slice from the npz
    shard_file = tmp_path / "ckpt" / "shard-0.npz"
    data = dict(np.load(shard_file).items())
    victim = next(k for k in data if "weight|" in k and not k.endswith("|full"))
    del data[victim]
    np.savez(shard_file, **data)

    model2 = nn.Linear(16, 8)
    sd = model2.state_dict()
    with pytest.raises((RuntimeError, KeyError)):
        dist.load_state_dict(sd, str(tmp_path / "ckpt"))


def test_checkpoint_stale_shards_cleared(tmp_path):
    """Re-saving into the same dir must not leave stale shard files behind."""
    mesh_mod.init_mesh({"dp": 8})
    model = nn.Linear(4, 4)
    dist.save_state_dict(model.state_dict(), str(tmp_path / "c"))
    # simulate an old leftover from a larger world
    np.savez(tmp_path / "c" / "shard-7.npz", **{"weight|full": np.ones((4, 4))})
    w0 = model.weight.numpy().copy()
    dist.save_state_dict(model.state_dict(), str(tmp_path / "c"))
    import glob
    assert sorted(os.path.basename(p) for p in glob.glob(str(tmp_path / "c" / "shard-*.npz"))) == ["shard-0.npz"]
    model2 = nn.Linear(4, 4)
    sd = model2.state_dict()
    dist.load_state_dict(sd, str(tmp_path / "c"))
    np.testing.assert_allclose(model2.weight.numpy(), w0)
