"""Multi-controller (multi-process) distributed verification (VERDICT r2
item 5; reference pattern: test/legacy_test/test_dist_base.py:962 — spawn
real processes, env-driven ranks, assert collective results).

Two REAL processes rendezvous through the launcher's TCPStore +
jax.distributed (payload: tests/dist_workers/controller.py, driven from the
declarative registry) and assert cross-process psum, framework all_reduce,
DataParallel loss parity, and that barrier() actually blocks.
"""
from dist_registry import run_dist


def test_two_process_collectives(tmp_path):
    _, results, logs = run_dist("controller_collectives", tmp_path)
    for rank in (0, 1):
        assert rank in results, f"rank {rank} produced no result\n{logs}"
        res = results[rank]
        assert res["psum"] == 3.0, res            # 1 + 2 across processes
        assert res["all_reduce"] == 2.0, res      # replicated x world=2
        assert abs(res["dp_loss"] - res["ref_loss"]) < 1e-6, res
        assert res["marker_after_barrier"] == 1, res
    assert results[0]["dp_loss"] == results[1]["dp_loss"]
