"""Multi-controller (multi-process) distributed verification (VERDICT r2
item 5; reference pattern: test/legacy_test/test_dist_base.py:962 — spawn
real processes, env-driven ranks, assert collective results).

Two REAL processes are launched via `python -m paddle_tpu.distributed.launch`
with the CPU backend (one XLA device per process). They rendezvous through
the launcher's TCPStore + jax.distributed coordination service, then assert:
  * a cross-process psum over the framework mesh (rank-distinct contributions)
  * the framework-level all_reduce on a replicated global tensor
  * DataParallel loss parity vs the local full-batch reference
  * barrier() actually blocks until the slow rank arrives (store-backed)
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import json, os, sys, time

import numpy as np

# one CPU device per process; never touch a real accelerator backend
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu as P
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.collective import _world_store
from paddle_tpu.parallel import mesh as mesh_mod
from jax.sharding import NamedSharding, PartitionSpec

out_dir = sys.argv[1]
rank = int(os.environ["PADDLE_TRAINER_ID"])

dist.init_parallel_env({"dp": 2})
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2, jax.devices()
mesh = mesh_mod.get_mesh()
res = {"rank": rank}

# 1) cross-process psum with rank-distinct data through the framework mesh
local = np.full((1, 4), float(rank + 1), np.float32)
sharding = NamedSharding(mesh, PartitionSpec("dp", None))
gx = jax.make_array_from_process_local_data(sharding, local, (2, 4))
psummed = jax.jit(jax.shard_map(
    lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
    in_specs=PartitionSpec("dp", None),
    out_specs=PartitionSpec("dp", None)))(gx)
res["psum"] = float(np.asarray(psummed.addressable_shards[0].data)[0, 0])

# 2) framework all_reduce on a replicated global tensor
rep = jax.make_array_from_process_local_data(
    NamedSharding(mesh, PartitionSpec()), np.ones((4,), np.float32), (4,))
t = P.Tensor(rep)
dist.all_reduce(t)
res["all_reduce"] = float(np.asarray(t._value.addressable_shards[0].data)[0])

# 3) DataParallel loss parity: identical weights everywhere (same seed),
#    full batch sharded over the two processes by the wrapper
P.seed(0)
model = P.nn.Linear(8, 4)
dp_model = P.DataParallel(model)
xb = np.random.RandomState(7).randn(4, 8).astype(np.float32)
loss = dp_model(P.to_tensor(xb)).mean()
res["dp_loss"] = float(loss.numpy())
ref = model(P.to_tensor(xb)).mean()   # full batch, no dp sharding
res["ref_loss"] = float(ref.numpy())

# 4) store-backed barrier: the slow rank publishes a marker BEFORE the
#    barrier; the fast rank must see it AFTER the barrier — impossible if
#    barrier() returns without waiting.
st = _world_store()
if rank == 1:
    time.sleep(0.7)
    st.add("marker", 1)
dist.barrier()
res["marker_after_barrier"] = int(st.add("marker", 0))

with open(os.path.join(out_dir, f"res{rank}.json"), "w") as f:
    json.dump(res, f)
'''


def test_two_process_collectives(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ,
               PYTHONPATH=REPO + ":" + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)  # no virtual 8-device mesh in the workers
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", f"--log_dir={tmp_path}/log",
         str(script), str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=150)
    logs = ""
    logdir = tmp_path / "log"
    if logdir.exists():
        for p in sorted(logdir.iterdir()):
            logs += f"\n--- {p.name} ---\n" + p.read_text()[-2000:]
    assert r.returncode == 0, f"launch failed: {r.stderr}\n{logs}"

    results = {}
    for rank in (0, 1):
        path = tmp_path / f"res{rank}.json"
        assert path.exists(), f"rank {rank} produced no result\n{logs}"
        with open(path) as f:
            results[rank] = json.load(f)
    for rank in (0, 1):
        res = results[rank]
        assert res["psum"] == 3.0, res            # 1 + 2 across processes
        assert res["all_reduce"] == 2.0, res      # replicated x world=2
        assert abs(res["dp_loss"] - res["ref_loss"]) < 1e-6, res
        assert res["marker_after_barrier"] == 1, res
    assert results[0]["dp_loss"] == results[1]["dp_loss"]
