"""Smoke-level guard for the whole-step capture microbenchmark.

bench_step must stay CPU-runnable and keep its one-JSON-line contract (it
is the capture-tier perf trajectory when the TPU probe reports
tpu-unavailable). A tiny-iteration run lives in tier-1; the acceptance
ratios themselves (captured >= 2x per-op, within 1.10x of hand-written
jit) are asserted only in the slow battery — tiny iteration counts on a
loaded single-core CI box make ratios noisy.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(iters: int):
    env = dict(os.environ, PT_STEP_BENCH_ITERS=str(iters),
               PT_STEP_BENCH_WARMUP="3")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench_step.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout  # exactly ONE JSON line on stdout
    return json.loads(lines[0]), r.stderr


@pytest.mark.skipif(os.environ.get("PT_TIGHT_BUDGET") == "1",
                    reason="wall-clock budget is tight; perf smoke skipped")
def test_bench_step_smoke_json_contract():
    payload, stderr = _run_bench(iters=5)
    assert payload["metric"] == "step_capture_speedup_vs_perop"
    assert payload["unit"] == "x"
    assert payload["value"] > 0 and payload["captured_vs_handjit"] > 0
    for k in ("per_op_steps_per_sec", "captured_steps_per_sec",
              "hand_jit_steps_per_sec"):
        assert payload[k] > 0
    assert "artifact ->" in stderr
    art = stderr.split("artifact ->", 1)[1].strip().splitlines()[0]
    with open(art) as f:
        self_json = json.load(f)
    tiers = self_json["detail"]["tiers"]
    assert set(tiers) == {"per_op", "captured", "captured_traced",
                          "hand_jit"}
    # the observability cost gate (smoke ceiling; the documented 1.25x
    # floor is pinned in the slow battery — tiny iteration counts on the
    # shared box make ratios noisy)
    assert 0 < payload["trace_overhead"] <= 1.5, payload
    assert tiers["captured_traced"]["iters_per_sec"] > 0
    # the captured tier really captured: one lowering, served hits, and the
    # pass pipeline + donation inference ran on the llama-proxy step
    cap = tiers["captured"]
    assert cap["step_info"]["lowerings"] == 1, cap["step_info"]
    assert cap["step_info"]["hits"] >= 4, cap["step_info"]
    assert cap["step_info"]["bailouts"] == 0, cap["step_info"]
    assert cap["pass_report"] is not None
    assert cap["donated"], cap  # params inferred donatable
    # per-op leg really rode the compiled-op cache
    assert tiers["per_op"]["cache_info"]["hits"] > 0
    # the three tiers agree on the training trajectory
    losses = [tiers[t]["final_loss"] for t in tiers]
    assert max(losses) - min(losses) < 5e-2, losses
    os.unlink(art)  # tiny-iteration artifacts are not trajectory evidence


@pytest.mark.slow
def test_bench_step_meets_acceptance_floor():
    payload, _ = _run_bench(iters=60)
    assert payload["value"] >= 2.0, payload
    assert payload["captured_vs_handjit"] <= 1.10, payload
    # tracing the captured step costs one span per call — the documented
    # observability ceiling
    assert payload["trace_overhead"] <= 1.25, payload
