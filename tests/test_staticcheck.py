"""graftcheck (tools/staticcheck) — framework tests + the tier-1 CI gate.

Three layers:
1. known-answer fixtures (tests/fixtures/staticcheck_proj) asserting every
   rule fires where expected, negatives stay quiet, and pragmas suppress;
2. baseline-ratchet semantics (only NEW findings fail; per-key counts);
3. the real gate: the shipped tree must be clean against the checked-in
   tools/staticcheck/baseline.json — the same check `python -m
   tools.staticcheck --ci` runs, executed in-process as part of tier-1.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "staticcheck_proj")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.staticcheck import (  # noqa: E402
    load_baseline, new_findings, run, save_baseline)
from tools.staticcheck.baseline import DEFAULT_BASELINE  # noqa: E402

FIXTURE_PATHS = ("paddle_tpu", "pkg")


@pytest.fixture(scope="module")
def fixture_findings():
    return run(FIXTURE, paths=FIXTURE_PATHS)


@pytest.fixture(scope="module")
def repo_findings():
    # shared: a full-repo scan is the most expensive call in this module
    return run(REPO)


# ---------------- rule detection on known-answer fixtures ----------------

def test_all_rules_fire_on_fixtures(fixture_findings):
    rules = {f.rule for f in fixture_findings}
    assert rules >= {"tracer-branch", "numpy-on-tracer", "host-sync",
                     "registry-consistency", "mutable-global",
                     "dead-export", "key-reuse", "closure-capture",
                     "unbounded-blocking", "dtype-rule-coverage",
                     "naked-collective", "chaos-site-coverage",
                     "typed-error-wire-coverage"}, rules
    assert len(rules) >= 5  # the acceptance floor, trivially exceeded


def test_findings_carry_location_and_severity(fixture_findings):
    by_rule = {}
    for f in fixture_findings:
        by_rule.setdefault(f.rule, []).append(f)
    tb = by_rule["tracer-branch"]
    assert all(f.path == "paddle_tpu/ops/hazards.py" for f in tb)
    assert all(f.severity == "error" and f.line > 0 for f in tb)
    # branchy() and wrong_pragma() — the pragma'd copy must NOT be here
    assert len(tb) == 2
    hs = by_rule["host-sync"]
    assert {f.line for f in hs} == {46, 47}  # syncy(): int(_value), .item()
    assert all(f.severity == "warning" for f in hs)


def test_registry_cross_check_both_directions(fixture_findings):
    rc = [f for f in fixture_findings if f.rule == "registry-consistency"]
    assert {f.context for f in rc} == {"fixture_orphan_op", "stale_op",
                                       "subpkg_orphanbar"}
    orphan = next(f for f in rc if f.context == "fixture_orphan_op")
    assert orphan.path == "paddle_tpu/ops/hazards.py"  # at the dispatch site
    stale = next(f for f in rc if f.context == "stale_op")
    assert stale.path == "tests/op_tolerances.py"      # at the registry


def test_registry_namespaced_family_governed(fixture_findings):
    """Route 3b known answers (paddle_tpu/subpkg): an op whose name
    qualifies the public name with the module tail is governed by a
    battery reaching that module (`import paddle_tpu.subpkg as NS` +
    `NS.govfoo` -> `subpkg_govfoo`; the public-class-method shape
    `NS.grouped.govmethod` -> `subpkg_govmethod`), while the same
    module's unreferenced public op stays an orphan — the route needs a
    REAL reference through the right module, not a name coincidence."""
    rc = {f.context for f in fixture_findings
          if f.rule == "registry-consistency"}
    assert not rc & {"subpkg_govfoo", "subpkg_govmethod"}, rc
    assert "subpkg_orphanbar" in rc
    # and the fixture module itself trips no other rule
    others = [f for f in fixture_findings
              if f.path.endswith("subpkg/__init__.py")
              and f.rule != "registry-consistency"]
    assert others == [], others


def test_registry_dynamic_self_attr_op_names_resolved(fixture_findings):
    """dynamic_names.py dispatches op_name=self.mode.lower(); the strings
    flow from subclass super().__init__ constants (one a constant-armed
    conditional). All three names are in the fixture registry, so a working
    resolver reports NOTHING for them — neither stale (registry side) nor
    ungoverned (dispatch side)."""
    rc = {f.context for f in fixture_findings
          if f.rule == "registry-consistency"}
    assert not rc & {"fixlstm", "fixtanh", "fixrelu"}, rc


def test_registry_resolution_patterns_governed(fixture_findings):
    """PR 11 orphan burn-down known-answers: names governed through the
    three extended resolution routes produce NO findings in either
    direction — the family-sweep SKIPS loop (`fixloopskip`), battery
    governance through a loop-built `__all__` export plus a
    tests/battery_cases.py reference (`fixbattery`), and implied-name
    local suppression (no phantom `primal` op from
    `apply(primal, x)` where primal is a parameter)."""
    rc = {f.context for f in fixture_findings
          if f.rule == "registry-consistency"}
    assert not rc & {"fixloopskip", "fixdtloop", "fixbattery", "primal"}, rc
    # and the fixture file itself trips no other rule
    others = [f for f in fixture_findings
              if f.path.endswith("resolved_names.py")]
    assert others == [], others


def test_dtype_rule_coverage_known_answers(fixture_findings):
    """op_tolerances.py fixture: the partial override entries fire, one
    finding per (op, leg, missing-dtype) hole; complete entries and holes
    covered by a recorded SKIP (exact or wildcard) stay quiet."""
    dc = [f for f in fixture_findings if f.rule == "dtype-rule-coverage"]
    assert all(f.path == "tests/op_tolerances.py" for f in dc), dc
    assert {f.context for f in dc} == {
        "toleranced_op:fwd:float16",   # partial fwd entry
        "fixrelu:fwd:bfloat16",        # partial, no skip
        "fixrelu:grad:float16",        # partial grad entry
    }, dc
    # stale_op/fixlstm entries are complete; fixtanh's hole has a SKIP
    assert all(f.severity == "warning" and f.line > 1 for f in dc)


def test_static_metadata_and_static_numpy_not_flagged(fixture_findings):
    # metadata_branch_ok (v.ndim branch) and numpy_static_ok (np.arange on a
    # static shape) are hazard-free idioms the heuristics must not flag
    # (line windows are hazards.py's — other fixture files have their own)
    for f in fixture_findings:
        if not f.path.endswith("paddle_tpu/ops/hazards.py"):
            continue
        assert not (24 <= f.line <= 29), f      # metadata_branch_ok body
        assert not (38 <= f.line <= 42), f      # numpy_static_ok body


def test_mutable_global_installer_sanctioned(fixture_findings):
    mg = [f for f in fixture_findings if f.rule == "mutable-global"]
    assert {f.line for f in mg} == {13, 18}  # sneaky_write + memoize
    # set_handler (line 8) and local_shadow_ok (line 22) stay quiet


def test_dead_export_detected(fixture_findings):
    de = [f for f in fixture_findings if f.rule == "dead-export"]
    assert [f.context for f in de] == ["ghost_export"]


def test_key_reuse_known_answers(fixture_findings):
    """key_hazards.py: the two positive reuses fire (same-key double draw,
    draw off an already-split key); the split-and-rebind idiom, mutually
    exclusive branches, and the pragma'd copy stay quiet."""
    kr = [f for f in fixture_findings if f.rule == "key-reuse"]
    assert all(f.path == "paddle_tpu/ops/key_hazards.py" for f in kr), kr
    assert {f.line for f in kr} == {12, 19}, kr
    assert all(f.severity == "warning" for f in kr)
    # and no OTHER rule trips over the key fixture (it is hazard-free
    # apart from the deliberate key reuses)
    others = [f for f in fixture_findings
              if f.path.endswith("key_hazards.py") and f.rule != "key-reuse"]
    assert others == [], others


def test_closure_capture_known_answers(fixture_findings):
    """closure_hazards.py: the three positive captures fire (payload
    attribute, enclosing-scope hoisted array, host `.numpy()` copy); the
    pass-through idiom, static config capture, metadata-only use
    (`y._value.shape`), and the pragma'd copy stay quiet."""
    cc = [f for f in fixture_findings if f.rule == "closure-capture"]
    assert all(f.path == "paddle_tpu/ops/closure_hazards.py" for f in cc), cc
    assert {f.line for f in cc} == {13, 18, 22}, cc
    assert all(f.severity == "warning" for f in cc)
    # and no OTHER rule trips over the closure fixture
    others = [f for f in fixture_findings
              if f.path.endswith("closure_hazards.py")
              and f.rule != "closure-capture"]
    assert others == [], others


def test_naked_collective_known_answers(fixture_findings):
    """collective_hazards.py: the two positives fire (lax.psum,
    jax.lax.all_gather); the comms-routed call, a non-lax `.psum`
    attribute, non-collective lax math, the pragma'd ppermute, and the
    fixture's own distributed/comms/ module (the allowlisted wire layer)
    all stay quiet."""
    nc = [f for f in fixture_findings if f.rule == "naked-collective"]
    assert all(f.path == "paddle_tpu/ops/collective_hazards.py"
               for f in nc), nc
    assert {f.line for f in nc} == {7, 11}, nc
    assert all(f.severity == "warning" for f in nc)
    # no OTHER rule trips over the collective fixture, and nothing at all
    # fires inside the allowlisted comms dir
    others = [f for f in fixture_findings
              if (f.path.endswith("collective_hazards.py")
                  and f.rule != "naked-collective")
              or "distributed/comms/" in f.path]
    assert others == [], others


def test_unbounded_blocking_known_answers(fixture_findings):
    """blocking_hazards.py: the five positives fire (argless q.get(),
    string-keyed store.wait, boundless cond.wait_for, raw sock.recv,
    argless t.join()); every bounded variant (timeout kwarg, numeric
    positional, interval-named bound), dict-style get, str/os.path join
    (always carry arguments), and the pragma'd copies stay quiet."""
    ub = [f for f in fixture_findings if f.rule == "unbounded-blocking"]
    assert all(f.path == "paddle_tpu/ops/blocking_hazards.py" for f in ub), ub
    assert {f.line for f in ub} == {11, 15, 20, 24, 48}, ub
    assert all(f.severity == "warning" for f in ub)
    # and no OTHER rule trips over the blocking fixture
    others = [f for f in fixture_findings
              if f.path.endswith("blocking_hazards.py")
              and f.rule != "unbounded-blocking"]
    assert others == [], others


def test_chaos_site_coverage_known_answers(fixture_findings):
    """fault_sites.py: only the registered-but-unmatrixed site fires; the
    matrix-covered site and the pragma'd deliberate waiver stay quiet, and
    the finding anchors at the registration (context = the site name, so
    the baseline key survives edits above it)."""
    cc = [f for f in fixture_findings if f.rule == "chaos-site-coverage"]
    assert {f.context for f in cc} == {"fixture.uncovered"}, cc
    assert all(f.path == "paddle_tpu/distributed/fault_sites.py"
               for f in cc), cc
    # and no OTHER rule trips over the fault-site fixture
    others = [f for f in fixture_findings
              if f.path.endswith("fault_sites.py")
              and f.rule != "chaos-site-coverage"]
    assert others == [], others


def test_typed_error_wire_coverage_known_answers(fixture_findings):
    """inference/serving fixture: only the typed raise with NO status_of
    mapping fires (FixtureOverloaded), anchored at the raise with the
    class name as the stable baseline key. The mapped class
    (FixtureDraining), the subclass covered through its mapped ValueError
    ancestor (FixtureFrameTooLong), and the builtin raise stay quiet."""
    tw = [f for f in fixture_findings
          if f.rule == "typed-error-wire-coverage"]
    assert {f.context for f in tw} == {"FixtureOverloaded"}, tw
    assert all(f.path == "paddle_tpu/inference/serving/engine.py"
               for f in tw), tw
    assert all(f.severity == "warning" for f in tw)
    # and no OTHER rule trips over the serving fixture modules
    others = [f for f in fixture_findings
              if "inference/serving/" in f.path
              and f.rule != "typed-error-wire-coverage"]
    assert others == [], others


def test_typed_error_wire_coverage_clean_on_repo(repo_findings):
    """Every typed exception the real serving path raises or ships has an
    explicit PTSG/1 mapping (EngineOverloaded -> 429, GatewayDraining ->
    503, RequestTimeout -> 408, ...) — the rule holds at ZERO baselined
    entries: a new typed serving error must land with its wire status."""
    assert [f for f in repo_findings
            if f.rule == "typed-error-wire-coverage"] == []


def test_chaos_site_coverage_clean_on_repo(repo_findings):
    """Every register_fault site in the real tree is in the real no-hang
    MATRIX — the rule holds at ZERO baselined entries (a new fault site
    must land together with its crash/delay/error/drop rows)."""
    assert [f for f in repo_findings
            if f.rule == "chaos-site-coverage"] == []


# ---------------- pragma suppression ----------------

def test_pragma_suppresses_named_rule(fixture_findings):
    # hazards.suppressed() has the identical violation as branchy() plus a
    # `# staticcheck: ok[tracer-branch]` pragma: line 57 must be absent
    assert not any(f.line == 57 for f in fixture_findings
                   if f.path.endswith("hazards.py"))


def test_bare_pragma_suppresses_all_rules(fixture_findings):
    # suppressed_all(): `.item()` + bare `# staticcheck: ok` (line 64)
    assert not any(f.line == 64 for f in fixture_findings
                   if f.path.endswith("hazards.py"))


def test_pragma_for_other_rule_does_not_suppress(fixture_findings):
    # wrong_pragma(): tracer-branch violation pragma'd as ok[host-sync]
    assert any(f.line == 69 and f.rule == "tracer-branch"
               for f in fixture_findings)


# ---------------- baseline ratchet ----------------

def test_baseline_ratchet_only_new_fail(fixture_findings, tmp_path):
    bl = str(tmp_path / "baseline.json")
    grandfathered = fixture_findings[:-2]
    save_baseline(grandfathered, bl)
    fresh = new_findings(fixture_findings, load_baseline(bl))
    assert fresh == fixture_findings[-2:]
    # full baseline -> nothing new
    save_baseline(fixture_findings, bl)
    assert new_findings(fixture_findings, load_baseline(bl)) == []


def test_baseline_counts_duplicate_keys(fixture_findings, tmp_path):
    bl = str(tmp_path / "baseline.json")
    save_baseline(fixture_findings, bl)
    # a second occurrence of an already-baselined key is still NEW
    dup = fixture_findings + [fixture_findings[0]]
    fresh = new_findings(dup, load_baseline(bl))
    assert fresh == [fixture_findings[0]]


def test_scoped_baseline_update_merges(fixture_findings, tmp_path):
    """`--update-baseline <paths>` over a partial scan must not drop
    grandfathered entries outside those paths."""
    bl = str(tmp_path / "baseline.json")
    save_baseline(fixture_findings, bl)  # full: paddle_tpu + pkg + registry
    pkg_only = [f for f in fixture_findings if f.path.startswith("pkg/")]
    other = [f for f in fixture_findings if not f.path.startswith("pkg/")]
    # scoped rewrite of pkg/ with one finding fixed
    save_baseline(pkg_only[:-1], bl, scanned_paths=["pkg"])
    merged = load_baseline(bl)
    assert pkg_only[-1].key not in merged          # fixed entry pruned
    assert all(f.key in merged for f in pkg_only[:-1])
    assert all(f.key in merged for f in other)     # untouched paths survive


def test_baseline_keys_survive_line_drift(fixture_findings):
    # keys use (rule, path, context), never the line number
    assert all(str(f.line) not in f.key.split("::")[0] for f in fixture_findings)
    f0 = fixture_findings[0]
    assert f0.key.startswith(f"{f0.rule}::{f0.path}::")


# ---------------- the CLI ----------------

def test_cli_ci_gate_exit_codes(tmp_path):
    bl = str(tmp_path / "bl.json")
    base_cmd = [sys.executable, "-m", "tools.staticcheck",
                "--root", FIXTURE, *FIXTURE_PATHS, "--baseline", bl]
    # no baseline yet: every finding is new -> nonzero
    r = subprocess.run(base_cmd + ["--ci"], cwd=REPO, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "NEW violation" in r.stderr
    # ratchet the current state, then the gate is clean
    r = subprocess.run(base_cmd + ["--update-baseline"], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(base_cmd + ["--ci"], cwd=REPO, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_cli_json_report(tmp_path):
    import json
    r = subprocess.run(
        [sys.executable, "-m", "tools.staticcheck", "--root", FIXTURE,
         *FIXTURE_PATHS, "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    payload = json.loads(r.stdout)
    assert payload["total"] == len(payload["findings"]) > 0
    assert {"rule", "severity", "path", "line", "message"} <= \
        set(payload["findings"][0])


# ---------------- the real tier-1 gate ----------------

def test_repo_is_clean_against_checked_in_baseline(repo_findings):
    """The in-process equivalent of `python -m tools.staticcheck --ci`:
    the shipped tree must introduce NO findings beyond the checked-in
    baseline. Fix the finding, pragma it with a rationale, or (for
    deliberate debt) regenerate the baseline via --update-baseline."""
    fresh = new_findings(repo_findings, load_baseline(DEFAULT_BASELINE))
    assert fresh == [], (
        f"{len(fresh)} NEW staticcheck violation(s):\n"
        + "\n".join(f.format() for f in fresh[:25]))


def test_checked_in_baseline_not_inflated(repo_findings):
    """The ratchet only ratchets downward if stale entries get pruned:
    at least 90 percent of baselined keys must still correspond to real
    findings (prevents the baseline from accumulating dead grandfather
    entries as violations get fixed)."""
    live = {f.key for f in repo_findings}
    baseline = load_baseline(DEFAULT_BASELINE)
    dead = [k for k in baseline if k not in live]
    assert len(dead) <= max(5, len(baseline) // 10), (
        f"{len(dead)} stale baseline entries, e.g. {dead[:10]} — "
        f"run `python -m tools.staticcheck --update-baseline`")
