"""nn.Layer / layers / losses tests."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_shapes_and_grad():
    layer = nn.Linear(8, 4)
    x = P.randn([2, 8])
    y = layer(x)
    assert y.shape == [2, 4]
    loss = y.sum()
    loss.backward()
    assert layer.weight.grad is not None
    assert layer.weight.grad.shape == [8, 4]
    assert layer.bias.grad.shape == [4]


def test_parameters_registry():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    names = [n for n, _ in m.named_parameters()]
    assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
    assert len(m.parameters()) == 4


def test_state_dict_roundtrip():
    m = nn.Linear(3, 3)
    sd = m.state_dict()
    m2 = nn.Linear(3, 3)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(m2.weight.numpy(), m.weight.numpy())


def test_conv2d():
    conv = nn.Conv2D(3, 8, 3, padding=1)
    x = P.randn([2, 3, 16, 16])
    y = conv(x)
    assert y.shape == [2, 8, 16, 16]
    y.sum().backward()
    assert conv.weight.grad.shape == [8, 3, 3, 3]


def test_conv2d_matches_numpy():
    # 1x1 conv == per-pixel linear
    conv = nn.Conv2D(2, 3, 1, bias_attr=False)
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    y = conv(P.to_tensor(x)).numpy()
    w = conv.weight.numpy()  # [3,2,1,1]
    ref = np.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_conv_transpose():
    deconv = nn.Conv2DTranspose(4, 2, 2, stride=2)
    x = P.randn([1, 4, 8, 8])
    y = deconv(x)
    assert y.shape == [1, 2, 16, 16]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = P.randn([4, 3, 8, 8]) * 3.0 + 1.0
    bn.train()
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
    # running stats updated
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [4, 3, 8, 8]


def test_layernorm():
    ln = nn.LayerNorm(16)
    x = P.randn([2, 5, 16])
    y = ln(x)
    np.testing.assert_allclose(y.numpy().mean(-1), np.zeros((2, 5)), atol=1e-5)
    np.testing.assert_allclose(y.numpy().std(-1), np.ones((2, 5)), atol=1e-2)


def test_rmsnorm():
    rn = nn.RMSNorm(8)
    x = P.randn([3, 8])
    y = rn(x)
    ms = np.sqrt((x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y.numpy(), x.numpy() / ms, rtol=1e-4, atol=1e-5)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = P.ones([1000])
    d.train()
    y = d(x)
    frac_zero = (y.numpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # upscale keeps expectation
    assert abs(y.numpy().mean() - 1.0) < 0.2
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = P.to_tensor([[1, 2, 0]])
    out = emb(ids)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 2], np.zeros(4))


def test_pooling():
    x = P.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y = F.max_pool2d(x, 2, 2)
    np.testing.assert_allclose(y.numpy()[0, 0], [[5, 7], [13, 15]])
    y = F.avg_pool2d(x, 2, 2)
    np.testing.assert_allclose(y.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    y = F.adaptive_avg_pool2d(x, 1)
    np.testing.assert_allclose(y.numpy()[0, 0], [[7.5]])


def test_activations():
    x = P.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 0, 0.5, 2])
    np.testing.assert_allclose(F.sigmoid(x).numpy(),
                               1 / (1 + np.exp(-x.numpy())), rtol=1e-6)
    s = F.softmax(x).numpy()
    np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-6)
    assert F.gelu(x).shape == [5]
    assert F.silu(x).shape == [5]


def test_losses():
    logits = P.randn([4, 10])
    labels = P.to_tensor([1, 2, 3, 4])
    loss = F.cross_entropy(logits, labels)
    assert loss.shape == []
    ref = -np.log(np.exp(logits.numpy())
                  / np.exp(logits.numpy()).sum(-1, keepdims=True))
    ref = np.array([ref[i, labels.numpy()[i]] for i in range(4)]).mean()
    np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-5)

    a, b = P.randn([3, 4]), P.randn([3, 4])
    np.testing.assert_allclose(F.mse_loss(a, b).numpy(),
                               ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-6)
    np.testing.assert_allclose(F.l1_loss(a, b).numpy(),
                               np.abs(a.numpy() - b.numpy()).mean(), rtol=1e-6)


def test_bce_with_logits_stable():
    z = P.to_tensor([100.0, -100.0], stop_gradient=False)
    y = P.to_tensor([1.0, 0.0])
    loss = F.binary_cross_entropy_with_logits(z, y)
    assert np.isfinite(loss.numpy())
    loss.backward()
    assert np.all(np.isfinite(z.grad.numpy()))


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = P.randn([2, 6, 16])
    y = mha(x)
    assert y.shape == [2, 6, 16]
    y.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32,
                                       dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = P.randn([2, 5, 16])
    y = enc(x)
    assert y.shape == [2, 5, 16]


def test_lstm():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = P.randn([3, 7, 4])  # [B, T, I]
    y, (h, c) = lstm(x)
    assert y.shape == [3, 7, 8]
    assert h.shape == [2, 3, 8]
    y.sum().backward()


def test_gru_bidirectional():
    gru = nn.GRU(4, 6, direction="bidirect")
    x = P.randn([2, 5, 4])
    y, h = gru(x)
    assert y.shape == [2, 5, 12]


def test_sdpa_matches_ref():
    q = P.randn([2, 5, 4, 8])
    k = P.randn([2, 5, 4, 8])
    v = P.randn([2, 5, 4, 8])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert out.shape == [2, 5, 4, 8]
    # causality: output at position 0 must not depend on later keys
    v2 = v.numpy().copy()
    v2[:, 1:] = 0.0
    out2 = F.scaled_dot_product_attention(q, k, P.to_tensor(v2), is_causal=True)
    np.testing.assert_allclose(out.numpy()[:, 0], out2.numpy()[:, 0], rtol=1e-5)


def test_clip_grad_by_global_norm():
    p1 = P.Parameter(P.to_tensor([3.0])._value)
    p2 = P.Parameter(P.to_tensor([4.0])._value)
    g1, g2 = P.to_tensor([3.0]), P.to_tensor([4.0])
    clip = nn.ClipGradByGlobalNorm(1.0)
    out = clip([(p1, g1), (p2, g2)])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_hooks():
    m = nn.Linear(2, 2)
    calls = []
    m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(P.randn([1, 2]))
    assert calls == [1]
